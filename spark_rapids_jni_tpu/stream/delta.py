"""Versioned append-only parquet table handle (the streaming ingest unit).

A :class:`DeltaTable` owns an ordered list of parquet blobs for ONE fact
table.  Appends arrive either as new files (:meth:`append_file`) or as an
in-place rewrite of an existing file that strictly extends its row groups
(:meth:`extend_file` — validated against the footer, so a watermark taken
before the rewrite stays a prefix of the new layout).  Every mutation
bumps the epoch.

The position of a reader is a **watermark**: the per-file row-group count
tuple at the time of its last scan.  ``scan(since=watermark)`` decodes
ONLY the row groups appended past the watermark by driving
``parquet/device_scan.scan_table`` with an explicit ``row_groups``
selection — composing with the planner's ``columns`` /
``rowgroup_predicate`` pruning, so a delta scan still drops columns and
statistically-disjoint groups before any page decode.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..analysis import sanitize
from ..column import Table
from ..parquet import decode as D
from ..parquet import device_scan
from ..parquet.footer import extract_footer_bytes
from ..parquet.thrift import parse_struct
from ..utils import metrics

Watermark = tuple[int, ...]     # row-group count per file, in file order


def _file_meta(file_bytes: bytes):
    """(rows-per-row-group, compressed-bytes-per-row-group) from the footer."""
    meta = parse_struct(extract_footer_bytes(file_bytes))
    groups = meta.get(D.FMD.ROW_GROUPS)
    rows, nbytes = [], []
    for rg in (groups.values if groups is not None else []):
        rows.append(int(rg.get(D.RG.NUM_ROWS, 0)))
        total = 0
        for chunk in rg.get(D.RG.COLUMNS).values:
            md = chunk.get(D.CC.META_DATA)
            if md is not None:
                total += int(md.get(D.CMD.TOTAL_COMPRESSED_SIZE, 0) or 0)
        nbytes.append(total)
    return tuple(rows), tuple(nbytes)


class DeltaTable:
    """Append-only fact table: parquet files + epoch + row-group metadata.

    Thread-safe: scans snapshot the file list under the lock and decode
    outside it, so appends never block (or tear) an in-flight refresh.
    """

    def __init__(self, name: str = "fact",
                 files: Optional[Sequence[bytes]] = None):
        self.name = name
        self._lock = sanitize.tracked_rlock("stream.delta")
        self._files: list[bytes] = []
        self._rg_rows: list[tuple[int, ...]] = []
        self._rg_bytes: list[tuple[int, ...]] = []
        self._epoch = 0
        for b in (files or ()):
            self.append_file(b)

    # -- ingest -------------------------------------------------------------

    def append_file(self, file_bytes: bytes) -> int:
        """Append a new parquet file; returns the new epoch."""
        rows, nbytes = _file_meta(file_bytes)
        with self._lock:
            self._files.append(bytes(file_bytes))
            self._rg_rows.append(rows)
            self._rg_bytes.append(nbytes)
            self._epoch += 1
            epoch = self._epoch
        if metrics.recording():
            metrics.count("stream.append.files")
            metrics.count("stream.append.rows", sum(rows))
        return epoch

    def extend_file(self, index: int, file_bytes: bytes) -> int:
        """Replace file ``index`` with a rewrite that extends it: the new
        footer's row-group row counts must keep the old ones as a strict
        prefix (same group boundaries), so existing watermarks remain
        valid.  Returns the new epoch."""
        rows, nbytes = _file_meta(file_bytes)
        with self._lock:
            old = self._rg_rows[index]
            if len(rows) < len(old) or tuple(rows[:len(old)]) != old:
                raise ValueError(
                    f"extend_file({index}): new row-group layout "
                    f"{rows[:len(old)]}... does not keep the existing "
                    f"layout {old} as a prefix")
            appended = sum(rows[len(old):])
            self._files[index] = bytes(file_bytes)
            self._rg_rows[index] = rows
            self._rg_bytes[index] = nbytes
            self._epoch += 1
            epoch = self._epoch
        if metrics.recording():
            metrics.count("stream.append.extended_files")
            metrics.count("stream.append.rows", appended)
        return epoch

    # -- versioning ---------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def watermark(self) -> Watermark:
        """Current per-file row-group counts — pass back as ``since``."""
        with self._lock:
            return tuple(len(r) for r in self._rg_rows)

    def rowgroup_counts(self) -> Watermark:
        return self.watermark()

    def num_files(self) -> int:
        with self._lock:
            return len(self._files)

    def total_rows(self, since: Optional[Watermark] = None) -> int:
        with self._lock:
            rg_rows = list(self._rg_rows)
        total = 0
        for i, rows in enumerate(rg_rows):
            lo = since[i] if since is not None and i < len(since) else 0
            total += sum(rows[lo:])
        return total

    def delta_bytes(self, since: Optional[Watermark] = None) -> int:
        """Compressed bytes of the row groups past ``since`` — the honest
        admission estimate for a refresh (it charges only the new decode
        work, not the whole table)."""
        with self._lock:
            rg_bytes = list(self._rg_bytes)
        total = 0
        for i, nb in enumerate(rg_bytes):
            lo = since[i] if since is not None and i < len(since) else 0
            total += sum(nb[lo:])
        return total

    # -- schema -------------------------------------------------------------

    def schema(self) -> list[str]:
        with self._lock:
            if not self._files:
                raise ValueError(f"DeltaTable {self.name!r} has no files")
            head = self._files[0]
        meta = parse_struct(extract_footer_bytes(head))
        return [leaf.name for leaf in D._leaf_schema_elements(meta)]

    def column_dtype(self, name: str):
        with self._lock:
            if not self._files:
                raise ValueError(f"DeltaTable {self.name!r} has no files")
            head = self._files[0]
        meta = parse_struct(extract_footer_bytes(head))
        for leaf in D._leaf_schema_elements(meta):
            if leaf.name == name:
                return leaf.logical_dtype()
        raise KeyError(f"{self.name}.{name}")

    # -- scan ---------------------------------------------------------------

    def scan(self, columns: Optional[list[str]] = None,
             rowgroup_predicate=None,
             since: Optional[Watermark] = None,
             until: Optional[Watermark] = None) -> Table:
        """Decode rows past ``since`` (None = full scan).  Per file, only
        row groups ``[since[i], count)`` reach the decoder; files fully
        covered by the watermark are skipped outright.  ``until`` bounds
        the scan to a watermark snapshot so concurrent appends landing
        mid-scan are not decoded (they belong to the next epoch).
        Counters: ``stream.delta.rowgroups`` / ``stream.delta.rows`` for
        delta scans, ``stream.scan.rowgroups`` for full scans."""
        with self._lock:
            files = list(self._files)
            rg_rows = list(self._rg_rows)
        if not files:
            raise ValueError(f"DeltaTable {self.name!r} has no files")
        is_delta = since is not None
        with metrics.span("stream.delta_scan" if is_delta else "stream.scan",
                          table=self.name, files=len(files)):
            parts: list[Table] = []
            selected_groups = 0
            for i, b in enumerate(files):
                cnt = len(rg_rows[i])
                if until is not None:
                    cnt = min(cnt, until[i]) if i < len(until) else 0
                lo = since[i] if is_delta and i < len(since) else 0
                if lo >= cnt:
                    continue
                selected_groups += cnt - lo
                parts.append(device_scan.scan_table(
                    b, columns=columns, row_groups=list(range(lo, cnt)),
                    rowgroup_predicate=rowgroup_predicate))
            if not parts:
                # empty delta: zero-row table with the file schema
                out = device_scan.scan_table(files[0], columns=columns,
                                             row_groups=[])
            elif len(parts) == 1:
                out = parts[0]
            else:
                from ..ops.copying import concat_tables
                out = concat_tables(parts)
            if metrics.recording():
                if is_delta:
                    metrics.count("stream.delta.rowgroups", selected_groups)
                    metrics.count("stream.delta.rows", out.num_rows)
                else:
                    metrics.count("stream.scan.rowgroups", selected_groups)
                metrics.annotate(rowgroups=selected_groups,
                                 rows=out.num_rows)
            return out
