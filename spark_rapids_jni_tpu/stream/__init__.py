"""Streaming ingest + incremental query maintenance.

Append-only fact tables version through :class:`DeltaTable` (epoch counter
+ per-file row-group watermark); registered aggregate views refresh in
O(delta) by decoding only appended row groups and merging partial
aggregate states (:mod:`..ops.groupby`) instead of rescanning — see the
README "Streaming & incremental maintenance" section.
"""

from .delta import DeltaTable
from .view import MaterializedView, ViewRegistry

__all__ = ["DeltaTable", "MaterializedView", "ViewRegistry"]
