"""Python side of the embedded-runtime device bridge.

``native/device_bridge.cpp`` forwards host table handles here when the
process hosts a CPython runtime; this module reads the table through
libsrjt's own C accessors, runs the JAX device engine, and imports the
result back through the same C ABI — completing the JNI→device path the
reference gets from ``RowConversionJni.cpp:24-45`` driving CUDA directly.

Every function returns a raw handle as ``int`` (0 = failure); exceptions
never cross the C boundary.
"""

from __future__ import annotations

import ctypes as C

import numpy as np

from . import types as T
from .column import Column, Table
from .rowconv import convert_from_rows, convert_to_rows
from .rowconv.convert import RowBatch

def _load() -> C.CDLL:
    # single shared binding site for the whole libsrjt C ABI
    from . import native
    lib = native.load()
    if lib is None:
        raise OSError("libsrjt.so unavailable")
    return lib


def _np_from_ptr(ptr, n, ctype):
    if not ptr or n == 0:
        return np.zeros(0, dtype=np.ctypeslib.as_ctypes_type(ctype)
                        if not isinstance(ctype, type) else ctype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).copy()


def _table_from_handle(lib, handle: int) -> Table:
    t = C.c_void_p(handle)
    ncols = lib.srjt_table_cols(t)
    n = lib.srjt_table_rows(t)
    cols = []
    for i in range(ncols):
        # srjt_table_column returns a NEW shared handle — freed below once
        # the payloads are copied out, or the column buffers stay pinned
        h = C.c_void_p(lib.srjt_table_column(t, i))
        tid = T.TypeId(lib.srjt_column_type(h))
        scale = lib.srjt_column_scale(h)
        dt = T.DType(tid, scale if tid in (T.TypeId.DECIMAL32,
                                           T.TypeId.DECIMAL64) else 0)
        vptr = lib.srjt_column_valid(h)
        validity = None
        if vptr:
            v = _np_from_ptr(vptr, n, np.uint8).astype(bool)
            validity = None if v.all() else v
        if dt.is_variable_width:
            offs = _np_from_ptr(lib.srjt_column_offsets(h), n + 1, np.int32)
            chars = _np_from_ptr(lib.srjt_column_data(h),
                                 lib.srjt_column_data_size(h), np.uint8)
            import jax.numpy as jnp
            cols.append(Column(dt, jnp.asarray(chars), jnp.asarray(offs),
                               None if validity is None
                               else jnp.asarray(validity)))
        else:
            raw = _np_from_ptr(lib.srjt_column_data(h),
                               lib.srjt_column_data_size(h), np.uint8)
            data = raw.view(dt.storage)
            cols.append(Column.from_numpy(data, dt, validity))
        lib.srjt_column_free(h)
    return Table(cols)


def to_rows_from_handle(table_handle: int) -> int:
    """Host table handle → RowBatches handle via the DEVICE engine."""
    out = None
    lib = None
    try:
        lib = _load()
        table = _table_from_handle(lib, table_handle)
        batches = convert_to_rows(table)
        for b in batches:
            data = np.ascontiguousarray(b.host_bytes())
            offs = np.ascontiguousarray(np.asarray(b.offsets,
                                                   dtype=np.int32))
            nrows = offs.shape[0] - 1
            if out is None:
                out = lib.srjt_rows_import(
                    data.ctypes.data_as(C.c_void_p), data.size,
                    offs.ctypes.data_as(C.c_void_p), nrows)
                if not out:
                    return 0
            else:
                if not lib.srjt_rows_import_append(
                        out, data.ctypes.data_as(C.c_void_p), data.size,
                        offs.ctypes.data_as(C.c_void_p), nrows):
                    lib.srjt_rows_free(out)
                    out = None
                    return 0
        result, out = int(out or 0), None    # ownership passes to caller
        return result
    except Exception:
        if out is not None and lib is not None:
            lib.srjt_rows_free(out)          # don't leak a partial import
        return 0


def from_rows_from_handle(rows_handle: int, type_ids_ptr: int,
                          scales_ptr: int, ncols: int) -> int:
    """RowBatches handle + schema arrays → host table handle via the
    DEVICE engine (batch 0, matching the one-batch contract)."""
    handles: list = []
    lib = None
    try:
        import jax.numpy as jnp
        lib = _load()
        h = C.c_void_p(rows_handle)
        if lib.srjt_rows_num_batches(h) < 1:
            return 0
        tids = np.ctypeslib.as_array(
            (C.c_int32 * ncols).from_address(type_ids_ptr)).copy()
        scales = (np.ctypeslib.as_array(
            (C.c_int32 * ncols).from_address(scales_ptr)).copy()
            if scales_ptr else np.zeros(ncols, np.int32))
        schema = [T.DType(T.TypeId(int(t)),
                          int(s) if T.TypeId(int(t)) in
                          (T.TypeId.DECIMAL32, T.TypeId.DECIMAL64) else 0)
                  for t, s in zip(tids, scales)]
        size = lib.srjt_rows_batch_size(h, 0)
        nrows = lib.srjt_rows_batch_rows(h, 0)
        data = _np_from_ptr(lib.srjt_rows_batch_data(h, 0), size, np.uint8)
        offs = _np_from_ptr(lib.srjt_rows_batch_offsets(h, 0), nrows + 1,
                            np.int32)
        batch = RowBatch(jnp.asarray(data), jnp.asarray(offs))
        table = convert_from_rows(batch, schema)

        keepalive = []
        for col in table.columns:
            valid_ptr = None
            if col.validity is not None:
                v = np.ascontiguousarray(
                    np.asarray(col.validity).astype(np.uint8))
                keepalive.append(v)
                valid_ptr = v.ctypes.data_as(C.c_void_p)
            if col.dtype.is_variable_width:
                chars = np.ascontiguousarray(np.asarray(col.data))
                o = np.ascontiguousarray(np.asarray(col.offsets,
                                                    dtype=np.int32))
                keepalive += [chars, o]
                ch = lib.srjt_column_string(
                    col.num_rows, o.ctypes.data_as(C.c_void_p),
                    chars.ctypes.data_as(C.c_void_p), valid_ptr)
            else:
                raw = np.ascontiguousarray(np.asarray(col.data))
                keepalive.append(raw)
                ch = lib.srjt_column_fixed(
                    int(col.dtype.id), col.dtype.scale, col.num_rows,
                    raw.ctypes.data_as(C.c_void_p), valid_ptr)
            if not ch:
                for hh in handles:
                    lib.srjt_column_free(hh)
                return 0
            handles.append(ch)
        arr = (C.c_void_p * len(handles))(*handles)
        out = lib.srjt_table(arr, len(handles))
        for hh in handles:
            lib.srjt_column_free(hh)
        return int(out or 0)
    except Exception:
        # free any column handles created before the failure (the to-rows
        # path has the same partial-cleanup contract)
        if lib is not None:
            for hh in handles:
                lib.srjt_column_free(hh)
        return 0
