"""Served inference + the online feature store.

A trained model registers as a :class:`ServableModel`: one query function
``tables → Table([prediction f32])`` that runs ``plan → features → jitted
predict`` as a single compiled request.  ``exec/``'s scheduler serves it
through the ordinary pipeline (``QueryScheduler.submit_predict``) so
admission control, request coalescing, capture/replay and device failover
all apply unchanged — the predict qfn carries a ``plan_fingerprint``
derived from the plan's, and the feature pack's only data-dependent sync
rides the ``syncs`` tape.

:class:`FeatureView` wires ``stream/`` view refresh in as an online
feature store: the view registry's refresh listener re-packs the feature
matrix after every delta refresh (incremental or full), so serving reads
features that are exactly the view's current contents — the differential
tests pin online-refresh parity against a from-scratch recompute.
"""

from __future__ import annotations

from typing import Optional

import jax

from .. import types as T
from ..analysis import sanitize
from ..column import Column, Table
from ..utils import flight, metrics
from .features import FeatureBatch, FeatureSpec


class ServableModel:
    """A trained model bound to the plan + FeatureSpec that feeds it."""

    def __init__(self, name: str, plan_qfn, names, spec: FeatureSpec,
                 model, params):
        self.name = name
        self.spec = spec
        self.model = model
        self.params = params
        self._predict = jax.jit(model.predict)

        def qfn(tables):
            t = plan_qfn(tables)
            with metrics.profile_stage("ml.predict", model=name) as rec:
                fb = spec.pack(t, names, with_label=False)
                yhat = self._predict(params, fb.X)
                if rec is not None:
                    rec.out_rows = int(yhat.shape[0])
            return Table([Column(T.float32, yhat)])

        qfn.__name__ = f"predict_{name}"
        tree = getattr(plan_qfn, "plan_tree", None)
        if tree is not None:
            qfn.plan_tree = tree
        fp = getattr(plan_qfn, "plan_fingerprint", None)
        if fp is not None:
            qfn.plan_fingerprint = fp + ":ml.predict"
        self.qfn = qfn

    @classmethod
    def from_plan(cls, name: str, tree, schemas: dict, spec: FeatureSpec,
                  model, params) -> "ServableModel":
        from ..plan import lower
        pqfn = lower.compile_plan(tree, schemas)
        names = list(getattr(pqfn, "plan_output_names", None)
                     or lower.output_names(tree, schemas))
        return cls(name, pqfn, names, spec, model, params)

    def predict_table(self, tables) -> Table:
        """Direct (unscheduled) evaluation — the scheduler-parity oracle."""
        return self.qfn(tables)

    def predict_matrix(self, X):
        """Jitted predict on an already-packed matrix (feature-store path)."""
        return self._predict(self.params, X)


# --- the registry -----------------------------------------------------------

_mu = sanitize.tracked_lock("ml.serve.registry")
_REGISTRY: dict[str, ServableModel] = {}
_probe_installed = False


def register_servable(sv: ServableModel) -> ServableModel:
    global _probe_installed
    with _mu:
        _REGISTRY[sv.name] = sv
        if not _probe_installed:
            flight.register_probe("ml.servables", servables)
            _probe_installed = True
    flight.record("ml.servable.registered", model=sv.name)
    if metrics.recording():
        metrics.count("ml.servable.registered")
    return sv


def get_servable(name: str) -> ServableModel:
    with _mu:
        try:
            return _REGISTRY[name]
        except KeyError:
            raise KeyError(f"no servable {name!r} registered "
                           f"(have {sorted(_REGISTRY)})") from None


def servables() -> list:
    with _mu:
        return sorted(_REGISTRY)


def resolve(model) -> ServableModel:
    return model if isinstance(model, ServableModel) else get_servable(model)


# --- online feature store ---------------------------------------------------


class FeatureView:
    """A stream/ view whose packed feature matrix tracks delta refreshes.

    Registers a refresh listener on the :class:`~stream.view.ViewRegistry`;
    every successful refresh (incremental or full) re-packs the view's
    output through the FeatureSpec, so `current()` always serves features
    consistent with the view's latest refreshed contents.  The listener
    fires OUTSIDE the view's refresh lock (lock-order: view lock strictly
    before the feature-view lock never holds both).
    """

    def __init__(self, registry, plan, spec: FeatureSpec, *,
                 name: Optional[str] = None,
                 with_label: Optional[bool] = None):
        from ..plan import lower
        self.registry = registry
        self.spec = spec
        self.view = registry.register_view(plan, name=name)
        self.names = list(lower.output_names(self.view.tree,
                                             registry.schemas))
        self.with_label = (spec.label is not None if with_label is None
                           else bool(with_label))
        self._mu = sanitize.tracked_lock("ml.serve.feature_view")
        self._batch: Optional[FeatureBatch] = None
        registry.add_refresh_listener(self._on_refresh)

    def _on_refresh(self, view, table) -> None:
        if view is not self.view:
            return
        fb = self.spec.pack(table, self.names, with_label=self.with_label)
        with self._mu:
            self._batch = fb
        if metrics.recording():
            metrics.count("ml.feature_view.repacks")
        flight.record("ml.feature_view.repack", view=view.name,
                      rows=fb.num_rows)

    def refresh(self) -> FeatureBatch:
        """Refresh the underlying view (delta-incremental when maintainable)
        and return the freshly re-packed batch."""
        self.registry.refresh(self.view)     # listener re-packs
        with self._mu:
            return self._batch

    def current(self) -> FeatureBatch:
        """The latest packed batch (refreshing once if never refreshed)."""
        with self._mu:
            fb = self._batch
        return fb if fb is not None else self.refresh()

    def close(self) -> None:
        self.registry.remove_refresh_listener(self._on_refresh)
