"""Epoch/batch iterator over a packed FeatureBatch — zero steady-state syncs.

One jitted program per pipeline shuffles and re-slices the whole epoch on
device: ``fold_in(seed_key, epoch) → permutation → gather → reshape`` to
``[num_batches, batch, k]``.  The epoch index enters as a traced uint32
scalar, so every epoch replays the SAME compiled program — no retrace, no
host sync, and the shuffle is a pure function of (seed, epoch): any replica
reproduces the exact batch sequence from the two integers.

Two shuffle engines (``SRJT_ML_SHUFFLE``):

* ``feistel`` (default) — a 4-round Feistel bijection over ``[0, 2^m)``
  (``2^m`` the next even-bit power of two ≥ n) followed by an on-device
  cumsum compaction to ``[0, n)``.  Pure elementwise u32 mixing + one
  cumsum + one scatter: O(n) work with no sort, which matters because the
  sort inside ``jax.random.permutation`` is single-threaded O(n log n) on
  XLA:CPU and dominates the steady loop long before the gradient math does
  (~16 ms for 40k rows vs <2 ms for the whole fused epoch).
* ``sort`` — ``jax.random.permutation`` (random-bits argsort), kept as the
  cross-check reference; the differential tests pin that both engines
  produce valid permutations.

The steady-state contract (asserted in ``tests/test_ml.py`` via the
``utils.syncs`` counter): after the first warm epoch, an arbitrary number
of epochs dispatches with ZERO host syncs — losses stay on device until
the caller pulls them once at the end.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import knobs, metrics
from .features import FeatureBatch

_FEISTEL_ROUNDS = 4


def _feistel_perm(key, epoch, n: int, m: int):
    """Sort-free permutation of ``[0, n)`` as a device program.

    A balanced Feistel network over ``m``-bit integers (``m`` even,
    ``2^m ≥ n``) is a bijection for any round function; four rounds of a
    murmur-style u32 mix keyed by per-epoch random round keys give a
    well-scrambled permutation of ``[0, 2^m)``.  Values ≥ n compact away
    with a cumsum-indexed scatter, which preserves the permutation
    property over ``[0, n)``.  Everything is elementwise/scan-free of
    host interaction — no sort, no sync.
    """
    h = m // 2
    lo_mask = jnp.uint32((1 << h) - 1)
    rk = jax.random.bits(jax.random.fold_in(key, epoch),
                         (_FEISTEL_ROUNDS,), jnp.uint32)
    idx = jnp.arange(1 << m, dtype=jnp.uint32)
    L, R = idx >> h, idx & lo_mask
    for r in range(_FEISTEL_ROUNDS):
        f = (R ^ rk[r]) * jnp.uint32(0x9E3779B9)
        f = (f ^ (f >> 13)) * jnp.uint32(0x85EBCA6B)
        f = (f ^ (f >> 16)) & lo_mask
        L, R = R, L ^ f
    perm = (L << h) | R
    keep = perm < n
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    return (jnp.zeros(n, jnp.uint32)
            .at[jnp.where(keep, pos, n)]
            .set(perm, mode="drop"))


class BatchPipeline:
    """Deterministic device-side minibatcher over a :class:`FeatureBatch`."""

    def __init__(self, batch: FeatureBatch, *,
                 batch_size: Optional[int] = None,
                 seed: Optional[int] = None,
                 shuffle: Optional[str] = None):
        if batch.y is None:
            raise ValueError("BatchPipeline needs a label vector — pack the "
                             "FeatureSpec with a label (serving paths call "
                             "predict on the matrix directly)")
        self.X, self.y = batch.X, batch.y
        self.n, self.k = int(self.X.shape[0]), int(self.X.shape[1])
        if self.n == 0:
            raise ValueError("cannot batch an empty feature matrix")
        b = batch_size if batch_size is not None else knobs.get("SRJT_ML_BATCH")
        self.batch_size = max(1, min(int(b), self.n))
        self.num_batches = self.n // self.batch_size
        # rows beyond the last full batch are dropped THIS epoch but re-enter
        # the shuffle every epoch, so no row is systematically excluded
        self.rows_per_epoch = self.num_batches * self.batch_size
        self.seed = seed if seed is not None else knobs.get("SRJT_ML_SEED")
        self._key = jax.random.PRNGKey(self.seed)
        self.shuffle = (shuffle if shuffle is not None
                        else knobs.get("SRJT_ML_SHUFFLE"))
        if self.shuffle not in ("feistel", "sort"):
            raise ValueError(f"SRJT_ML_SHUFFLE={self.shuffle!r}: "
                             "want feistel|sort")

        nb, bs, k = self.num_batches, self.batch_size, self.k
        n = self.n
        m = max(2, (n - 1).bit_length())
        m += m & 1                       # balanced halves need an even width
        engine = self.shuffle

        def _shuffle(X, y, key, epoch):
            if engine == "sort":
                perm = jax.random.permutation(
                    jax.random.fold_in(key, epoch), n)
            else:
                perm = _feistel_perm(key, epoch, n, m)
            take = perm[:nb * bs]
            return (X[take].reshape(nb, bs, k), y[take].reshape(nb, bs))

        self._shuffle = jax.jit(_shuffle)

    def epoch_arrays(self, epoch: int):
        """``(Xb [nb, b, k], yb [nb, b])`` for one epoch — pure device work.

        The returned buffers are fresh every call, so the trainer may donate
        them into the jitted step/epoch program (see ``ml/train.py``).
        """
        if metrics.recording():
            metrics.count("ml.pipeline.epochs")
        return self._shuffle(self.X, self.y, self._key, jnp.uint32(epoch))

    def batches(self, epoch: int):
        """Yield ``(xb, yb)`` device slices for one epoch (unfused path)."""
        Xb, yb = self.epoch_arrays(epoch)
        for i in range(self.num_batches):
            yield Xb[i], yb[i]
