"""FeatureSpec: plan/table columns → dense on-device f32 matrix + label.

The JCUDF fixed-width row IS a dense feature matrix (PAPER.md §L1): once
every feature column is lowered to an all-valid FLOAT32 lane, the
``rowconv/`` fixed-width pack interleaves them into the row word stream and
:func:`rowconv.convert.fixed_rows_to_matrix` reinterprets that stream as
``f32 [n, k]`` — a bitcast plus a slice, no gather, no host round-trip.

Lane lowering contract (mirrored bit-for-bit by the numpy oracle in
``tests/test_ml.py``):

* ints / dates / timestamps → ``astype(float32)``
* BOOL8                     → ``(v != 0) → {0.0, 1.0}``
* DECIMAL32/64 scale s      → ``unscaled.astype(f32) * float32(10.0**s)``
* FLOAT64                   → exact bit view (``utils.f64bits``) → f32
* STRING / DictColumn       → ``ops.strings.dictionary_encode`` rank codes
  (categorical ids; dict inputs re-encode through the dictionary only —
  row bytes are never materialized).  Ids rank the column's distinct byte
  strings: for plain strings nulls contribute the zeroed/empty key, for
  dict columns the dictionary's distinct set is the id space — the two
  representations agree exactly on null-free columns (differential-tested)

Nulls resolve through declared imputation policies applied AFTER the lane
cast: ``"zero"``, ``"mean"`` (f64 accumulation on-device), ``("const", v)``,
or ``"error"`` (reject columns that carry a validity mask).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table, force_column
from ..utils import f64bits, knobs, metrics

ImputePolicy = Union[str, tuple]

_CATEGORICAL_IDS = (T.TypeId.STRING,)


def _is_categorical(dt: T.DType) -> bool:
    return dt.id in _CATEGORICAL_IDS


@dataclasses.dataclass(frozen=True)
class Feature:
    """One feature column: a name plus its null-imputation policy.

    ``impute`` is ``"zero"`` | ``"mean"`` | ``("const", v)`` | ``"error"``
    (default; a nullable column without a declared policy is a spec error —
    silent zero-fill has burned every feature store ever built).
    """

    name: str
    impute: ImputePolicy = "error"

    def __post_init__(self):
        p = self.impute
        if isinstance(p, str):
            if p not in ("zero", "mean", "error"):
                raise ValueError(f"feature {self.name!r}: unknown imputation "
                                 f"policy {p!r}")
        elif not (isinstance(p, tuple) and len(p) == 2 and p[0] == "const"):
            raise ValueError(f"feature {self.name!r}: imputation must be "
                             "'zero' | 'mean' | ('const', v) | 'error'")


def _as_feature(f) -> Feature:
    return f if isinstance(f, Feature) else Feature(str(f))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FeatureBatch:
    """Packed on-device features: ``X`` f32 [n, k], optional ``y`` f32 [n]."""

    X: jnp.ndarray
    y: Optional[jnp.ndarray] = None
    feature_names: tuple = ()

    def tree_flatten(self):
        return (self.X, self.y), self.feature_names

    @classmethod
    def tree_unflatten(cls, names, children):
        return cls(children[0], children[1], names)

    @property
    def num_rows(self) -> int:
        return int(self.X.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.X.shape[1])


def _value_lane(col: Column) -> tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Column → (f32 value lane, validity) with zero host materialization."""
    if _is_categorical(col.dtype):
        # rank codes == categorical ids; DictColumn re-encodes through its
        # dictionary (no byte materialization), plain strings pay one
        # distinct-count sync that rides the syncs tape under capture
        from ..ops import strings as S
        codes, _ = S.dictionary_encode(col)
        return codes.data.astype(jnp.float32), codes.validity
    col = force_column(col)
    dt, data = col.dtype, col.data
    if dt.id == T.TypeId.FLOAT32:
        lane = data
    elif dt.id == T.TypeId.FLOAT64:
        # data is the uint32 [n, 2] bit-pair view; exact bitcast on CPU
        lane = f64bits.from_bits(data).astype(jnp.float32)
    elif dt.id == T.TypeId.BOOL8:
        lane = (data != 0).astype(jnp.float32)
    elif dt.id in (T.TypeId.DECIMAL32, T.TypeId.DECIMAL64):
        # np.float32 scale factor: f32 * np.float64 would promote to f64
        # under the package-global x64 mode
        lane = data.astype(jnp.float32) * np.float32(10.0 ** dt.scale)
    elif dt.is_fixed_width and dt.id != T.TypeId.DECIMAL128:
        lane = data.astype(jnp.float32)
    else:
        raise TypeError(f"dtype {dt!r} is not supported as an ML feature")
    return lane, col.validity


def _impute(name: str, lane: jnp.ndarray, valid: Optional[jnp.ndarray],
            policy: ImputePolicy) -> jnp.ndarray:
    if valid is None:
        return lane
    if policy == "error":
        raise ValueError(
            f"feature {name!r} may contain nulls but declares no imputation "
            "policy — set impute='zero'|'mean'|('const', v)")
    if policy == "zero":
        return jnp.where(valid, lane, jnp.float32(0.0))
    if policy == "mean":
        # f64 accumulation on-device: exact whenever the lane values are
        # integers small enough for f64 (the differential tests pin this);
        # for general float lanes the mean is deterministic-on-device only
        s = jnp.sum(jnp.where(valid, lane.astype(jnp.float64), 0.0))
        cnt = jnp.sum(valid.astype(jnp.int64))
        mean = jnp.where(cnt > 0, s / jnp.maximum(cnt, 1), 0.0)
        return jnp.where(valid, lane, mean.astype(jnp.float32))
    return jnp.where(valid, lane, jnp.float32(policy[1]))


def _pack_rowconv(lanes: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """All-valid f32 lanes → f32 [n, k] through the JCUDF row stream."""
    from ..rowconv import convert as RC
    from ..rowconv.layout import compute_row_layout
    tbl = Table([Column(T.float32, l) for l in lanes])
    if tbl.num_rows == 0:
        return jnp.zeros((0, len(lanes)), jnp.float32)
    layout = compute_row_layout(tbl.schema)
    mats = [RC.fixed_rows_to_matrix(b, layout)
            for b in RC.convert_to_rows(tbl)]
    return mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=0)


def _pack_stack(lanes: Sequence[jnp.ndarray]) -> jnp.ndarray:
    return jnp.stack(lanes, axis=1)


@dataclasses.dataclass(frozen=True)
class FeatureSpec:
    """Declarative mapping from named columns to a packed FeatureBatch.

    ``label`` (optional) names the label column; ``label_transform``
    post-processes the label lane: ``None`` keeps the raw value,
    ``("gt", t)`` / ``("ge", t)`` binarize to {0.0, 1.0} f32.
    """

    features: tuple
    label: Optional[Feature] = None
    label_transform: Optional[tuple] = None

    @staticmethod
    def of(features: Sequence, label=None,
           label_transform: Optional[tuple] = None) -> "FeatureSpec":
        lab = None if label is None else _as_feature(label)
        return FeatureSpec(tuple(_as_feature(f) for f in features),
                           lab, label_transform)

    @property
    def feature_names(self) -> tuple:
        return tuple(f.name for f in self.features)

    def _column(self, table: Table, names: Sequence[str], want: str) -> Column:
        try:
            return table.columns[list(names).index(want)]
        except ValueError:
            raise KeyError(f"column {want!r} not in plan output "
                           f"{list(names)}") from None

    def _label_lane(self, table: Table, names: Sequence[str]) -> jnp.ndarray:
        lane, valid = _value_lane(self._column(table, names, self.label.name))
        lane = _impute(self.label.name, lane, valid, self.label.impute)
        if self.label_transform is not None:
            op, t = self.label_transform
            if op == "gt":
                lane = (lane > jnp.float32(t)).astype(jnp.float32)
            elif op == "ge":
                lane = (lane >= jnp.float32(t)).astype(jnp.float32)
            else:
                raise ValueError(f"unknown label transform {op!r}")
        return lane

    def pack(self, table: Table, names: Optional[Sequence[str]] = None, *,
             with_label: bool = True, engine: Optional[str] = None
             ) -> FeatureBatch:
        """Pack ``table`` into a :class:`FeatureBatch` on-device.

        ``names`` gives the table's column names in order (defaults to the
        feature order itself when the table was built column-per-feature).
        """
        if names is None:
            names = self.feature_names + (
                (self.label.name,) if self.label is not None else ())
        engine = engine or knobs.get("SRJT_ML_PACK")
        if engine not in ("rowconv", "stack"):
            raise ValueError(f"SRJT_ML_PACK={engine!r}: want rowconv|stack")
        with metrics.profile_stage("ml.pack", engine=engine) as rec:
            lanes = []
            for f in self.features:
                lane, valid = _value_lane(self._column(table, names, f.name))
                lanes.append(_impute(f.name, lane, valid, f.impute))
            X = (_pack_rowconv if engine == "rowconv" else _pack_stack)(lanes)
            y = (self._label_lane(table, names)
                 if with_label and self.label is not None else None)
            if rec is not None:
                rec.out_rows = int(X.shape[0])
                rec.engine = engine
        if metrics.recording():
            metrics.count("ml.pack.rows", X.shape[0])
            metrics.count("ml.pack.features", X.shape[1])
        return FeatureBatch(X, y, self.feature_names)


def compile_feature_plan(tree, schemas: dict, spec: FeatureSpec, *,
                         with_label: bool = True):
    """Lower a plan tree to ``tables → FeatureBatch`` (one query function).

    The result composes with ``models.compiled.compile_query`` — the pack
    path's only data-dependent sync (string distinct count) rides the
    ``syncs`` tape, so capture/replay works unchanged — and carries
    ``plan_tree`` / ``plan_fingerprint`` so EXPLAIN ANALYZE and the profile
    ledger attribute the ML stages to the plan.
    """
    from ..plan import lower
    pqfn = lower.compile_plan(tree, schemas)
    names = list(getattr(pqfn, "plan_output_names", None)
                 or lower.output_names(tree, schemas))

    def qfn(tables):
        return spec.pack(pqfn(tables), names, with_label=with_label)

    qfn.__name__ = "feature_" + getattr(pqfn, "__name__", "plan")
    qfn.plan_tree = getattr(pqfn, "plan_tree", tree)
    fp = getattr(pqfn, "plan_fingerprint", None)
    if fp is not None:
        qfn.plan_fingerprint = fp + ":ml.features"
    qfn.plan_output_names = names
    qfn.feature_spec = spec
    return qfn
