"""Zero-copy ETL→ML handoff.

The CUDA reference exists to feed Spark ETL output into GPU ML (its
companion demo is mortgage-ETL-into-XGBoost), but it still crosses a
JVM/host boundary.  Here query outputs and model steps live on the same
chips in the same JAX process, so a plan's output lowers straight into
training/inference batches with zero host round-trip:

* :mod:`.features` — ``FeatureSpec`` maps a plan/table's columns to a
  dense on-device f32 feature matrix (+ optional label vector) through the
  ``rowconv/`` fixed-width pack path.  Dict-string codes become categorical
  ids without materializing bytes; nulls resolve through declared
  imputation policies; every cast happens on-device.
* :mod:`.pipeline` — epoch/batch iterator slicing device batches from the
  packed matrix with a deterministic device-side shuffle and zero
  steady-state host syncs.
* :mod:`.train` — jitted train-step harness (linear/logistic regression,
  SGD/Adam) with donated batch buffers, composing with
  ``models/compiled.py`` capture/replay and the ``SRJT_PROFILE`` ledger.
* :mod:`.serve` — trained models register as servables; predict requests
  flow through the ``exec/`` scheduler as ``plan → features → jitted
  predict``, and ``stream/`` view refresh doubles as an online feature
  store.
"""

from .features import (Feature, FeatureBatch, FeatureSpec,  # noqa: F401
                       compile_feature_plan)
from .pipeline import BatchPipeline                          # noqa: F401
from .train import (Trainer, TrainResult, adam,              # noqa: F401
                    linear_regression, logistic_regression, sgd)
from .serve import (FeatureView, ServableModel,              # noqa: F401
                    get_servable, register_servable, servables)
