"""Jitted train-step harness: reference models + optimizers on the packed
feature matrix.

Reference models (linear / logistic regression) and optimizers (SGD with
momentum, Adam) are deliberately hand-rolled pure-f32 pytree math — the
point is the handoff contract, not the model zoo: the whole train step is
`(params, opt_state, xb, yb) → (params, opt_state, loss)` under one
``jax.jit``, and with ``SRJT_ML_EPOCH_FUSE`` (default on) a whole epoch is
ONE dispatch (``lax.scan`` over the batch axis of the pipeline's shuffled
``[nb, b, k]`` tensor).

Donation contract (``SRJT_ML_DONATE``, default ``auto`` = non-CPU only —
XLA:CPU does not implement buffer donation): the epoch's minibatch tensors
are donated into the fused program.  ``BatchPipeline.epoch_arrays`` returns
fresh buffers every call, so donation is always safe there; callers driving
``train_step`` directly must not reuse a donated ``xb``/``yb`` after the
call.  Params/opt-state are NOT donated — the caller may keep the initial
params for A/B runs.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import knobs, metrics, syncs
from .pipeline import BatchPipeline


def _donate_enabled() -> bool:
    v = str(knobs.get("SRJT_ML_DONATE") or "auto").lower()
    if v in ("1", "on", "true", "yes"):
        return True
    if v in ("0", "off", "false", "no"):
        return False
    return jax.default_backend() != "cpu"


# --- reference models -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Model:
    """init(k) → params pytree; loss(params, X, y) → scalar; predict → [n]."""

    name: str
    init: Callable
    loss: Callable
    predict: Callable


def _linear_init(k: int):
    return {"w": jnp.zeros(k, jnp.float32), "b": jnp.float32(0.0)}


def linear_regression() -> Model:
    """Least-squares linear model: loss = mean((Xw + b - y)^2)."""
    def loss(params, X, y):
        r = X @ params["w"] + params["b"] - y
        return jnp.mean(r * r)

    def predict(params, X):
        return X @ params["w"] + params["b"]

    return Model("linreg", _linear_init, loss, predict)


def logistic_regression() -> Model:
    """Binary logistic model, stable BCE-with-logits loss:
    mean(softplus(z) − y·z); predict = sigmoid(z)."""
    def loss(params, X, y):
        z = X @ params["w"] + params["b"]
        return jnp.mean(jax.nn.softplus(z) - y * z)

    def predict(params, X):
        return jax.nn.sigmoid(X @ params["w"] + params["b"])

    return Model("logreg", _linear_init, loss, predict)


# --- reference optimizers ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """init(params) → state; update(grads, state, params) → (params, state)."""

    name: str
    init: Callable
    update: Callable


def sgd(lr: float = 0.1, momentum: float = 0.0) -> Optimizer:
    lr32, mu32 = np.float32(lr), np.float32(momentum)

    def init(params):
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def update(grads, vel, params):
        vel = jax.tree_util.tree_map(lambda v, g: mu32 * v + g, vel, grads)
        params = jax.tree_util.tree_map(lambda p, v: p - lr32 * v,
                                        params, vel)
        return params, vel

    return Optimizer("sgd", init, update)


def adam(lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-8) -> Optimizer:
    lr32, b1_, b2_, eps_ = (np.float32(lr), np.float32(b1), np.float32(b2),
                            np.float32(eps))
    one = np.float32(1.0)

    def init(params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"m": z, "v": z, "t": jnp.float32(0.0)}

    def update(grads, state, params):
        t = state["t"] + one
        m = jax.tree_util.tree_map(
            lambda m, g: b1_ * m + (one - b1_) * g, state["m"], grads)
        v = jax.tree_util.tree_map(
            lambda v, g: b2_ * v + (one - b2_) * (g * g), state["v"], grads)
        c1 = one - b1_ ** t
        c2 = one - b2_ ** t

        def step(p, m, v):
            return p - lr32 * (m / c1) / (jnp.sqrt(v / c2) + eps_)

        params = jax.tree_util.tree_map(step, params, m, v)
        return params, {"m": m, "v": v, "t": t}

    return Optimizer("adam", init, update)


# --- the harness ------------------------------------------------------------


@dataclasses.dataclass
class TrainResult:
    params: dict
    opt_state: dict
    losses: np.ndarray          # per-epoch mean loss, pulled once at the end
    model: Model

    @property
    def final_loss(self) -> float:
        return float(self.losses[-1])


class Trainer:
    """Jitted step/epoch harness for one (model, optimizer) pair."""

    def __init__(self, model: Model, opt: Optimizer, *,
                 fuse: Optional[bool] = None,
                 donate: Optional[bool] = None):
        self.model, self.opt = model, opt
        self.fuse = (knobs.get("SRJT_ML_EPOCH_FUSE") if fuse is None
                     else bool(fuse))
        self.donate = _donate_enabled() if donate is None else bool(donate)
        grad = jax.value_and_grad(model.loss)

        def step(params, ostate, xb, yb):
            loss, g = grad(params, xb, yb)
            params, ostate = opt.update(g, ostate, params)
            return params, ostate, loss

        def epoch(params, ostate, Xb, yb):
            def body(carry, xy):
                p, o, _ = step(carry[0], carry[1], xy[0], xy[1])
                return (p, o), _
            # unroll amortizes the XLA:CPU while-loop per-iteration overhead
            # (~7us/iter unrolled=1 vs ~2us at 8 for a b=32 logreg step)
            (params, ostate), losses = jax.lax.scan(
                body, (params, ostate), (Xb, yb), unroll=8)
            return params, ostate, jnp.mean(losses)

        dn = (2, 3) if self.donate else ()
        self.train_step = jax.jit(step, donate_argnums=dn)
        self.run_epoch = jax.jit(epoch, donate_argnums=dn)

    def init(self, k: int):
        params = self.model.init(k)
        return params, self.opt.init(params)

    def fit(self, pipe: BatchPipeline, epochs: int, *,
            params=None, opt_state=None) -> TrainResult:
        """Run ``epochs`` over the pipeline; ONE host sync at the very end.

        The per-epoch loop is pure dispatch: shuffled batches come off the
        pipeline's jitted program, the fused epoch is one ``lax.scan``
        dispatch, and per-epoch losses accumulate as device scalars.
        """
        if params is None:
            params, opt_state = self.init(pipe.k)
        elif opt_state is None:
            opt_state = self.opt.init(params)
        t0 = time.perf_counter()
        losses = []
        with metrics.profile_stage("ml.train", model=self.model.name,
                                   opt=self.opt.name) as rec:
            for e in range(epochs):
                Xb, yb = pipe.epoch_arrays(e)
                if self.fuse:
                    params, opt_state, loss = self.run_epoch(
                        params, opt_state, Xb, yb)
                else:
                    loss = None
                    for i in range(pipe.num_batches):
                        params, opt_state, loss = self.train_step(
                            params, opt_state, Xb[i], yb[i])
                losses.append(loss)
            # the ONLY steady-loop sync: pull the stacked loss history
            hist = np.asarray(jax.device_get(jnp.stack(losses)),
                              dtype=np.float32)
            syncs.note_sync()
            rows = pipe.rows_per_epoch * epochs
            if rec is not None:
                rec.out_rows = rows
        dt_ms = (time.perf_counter() - t0) * 1e3
        if metrics.recording():
            metrics.count("ml.train.epochs", epochs)
            metrics.count("ml.train.rows", rows)
            metrics.observe("ml.train.epoch_ms", dt_ms / max(epochs, 1))
            metrics.ledger_add(f"ml.train:{self.model.name}",
                               train_ms=dt_ms, epochs=epochs, rows=rows)
        return TrainResult(params, opt_state, hist, self.model)
