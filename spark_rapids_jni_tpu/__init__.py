"""spark_rapids_jni_tpu — a TPU-native Spark acceleration layer.

A from-scratch JAX/XLA/Pallas/PJRT framework with the capabilities of
NVIDIA's spark-rapids-jni (CUDA/libcudf) reference: device columnar tables,
JCUDF row↔column transcode, Parquet footer parse/prune/serialize, a columnar
op library, ICI shuffle, and fault-injection tooling.  See SURVEY.md for the
reference structural analysis this build follows.
"""

import os as _os

import jax as _jax

# The JCUDF type surface includes int64/float64/decimal64 columns
# (tests/row_conversion.cpp:546-707 in the reference); JAX needs x64 enabled
# for those payloads.  NOTE: this is process-global JAX config — embedding
# applications that must keep 32-bit JAX defaults can opt out with
# SPARK_RAPIDS_TPU_NO_X64=1 (64-bit column types then raise at use).
if _os.environ.get("SPARK_RAPIDS_TPU_NO_X64", "0") != "1":
    _jax.config.update("jax_enable_x64", True)

from . import types  # noqa: E402
from .types import (  # noqa: E402,F401
    DType, TypeId,
    int8, int16, int32, int64, uint8, uint16, uint32, uint64,
    float32, float64, bool8, string,
    timestamp_days, timestamp_seconds, timestamp_ms, timestamp_us, timestamp_ns,
    decimal32, decimal64,
)
from .column import Column, Table  # noqa: E402,F401
from .rowconv import (  # noqa: E402,F401
    RowLayout, compute_row_layout, build_batches,
    convert_to_rows, convert_from_rows,
)

# stamped by ci/build_info.py (build/build-info:26-40 analog); falls back
# to the static base version when no build provenance has been generated
try:
    from .version_info import version as __version__  # noqa: F401
    from . import version_info  # noqa: F401
except ImportError:
    from ._version import BASE_VERSION as __version__  # noqa: F401
    version_info = None
