"""Data type system for the TPU-native Spark acceleration layer.

Capability parity notes (reference = spark-rapids-jni @ /root/reference):

* The reference marshals a column schema across JNI as parallel ``int[] typeIds``
  / ``int[] scales`` arrays (``RowConversion.java:110-120``) and rebuilds
  ``cudf::data_type`` objects with ``make_data_type(type, scale)``
  (``RowConversionJni.cpp:58-61``).  ``DType`` below is the same (type_id, scale)
  pair; decimal types are represented as scaled integers exactly as the
  reference does (``RowConversion.java:114-118``).
* The fixed-width byte sizes drive the JCUDF row layout
  (``row_conversion.cu:1281-1306``): each fixed-width column occupies
  ``itemsize`` bytes aligned to ``itemsize``; compound (string) columns occupy
  an 8-byte (offset:u32, length:u32) slot aligned to 4 bytes
  (``row_conversion.cu:1342-1350``).

This is a fresh design: dtypes map onto JAX/XLA storage types so that all
device compute happens on TPU-friendly lanes (int8..int64, float32/float64,
bool), and decimal/timestamp semantics live in metadata, not in the kernels.
"""

from __future__ import annotations

import dataclasses
import enum

import jax.numpy as jnp
import numpy as np


class TypeId(enum.IntEnum):
    """Stable type identifiers, used by the JNI/C-ABI surface.

    The numeric values form this framework's own stable ABI (documented in
    ``cpp/spark_rapids_tpu.h``); they intentionally cover the same logical type
    surface the reference exercises in its test matrix
    (``tests/row_conversion.cpp:546-707``: int8/16/32/64, float32/64, bool,
    timestamps, decimal32/64) plus strings.
    """

    EMPTY = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    UINT8 = 5
    UINT16 = 6
    UINT32 = 7
    UINT64 = 8
    FLOAT32 = 9
    FLOAT64 = 10
    BOOL8 = 11
    TIMESTAMP_DAYS = 12
    TIMESTAMP_SECONDS = 13
    TIMESTAMP_MILLISECONDS = 14
    TIMESTAMP_MICROSECONDS = 15
    TIMESTAMP_NANOSECONDS = 16
    DURATION_DAYS = 17
    DURATION_SECONDS = 18
    DURATION_MILLISECONDS = 19
    DURATION_MICROSECONDS = 20
    DURATION_NANOSECONDS = 21
    DECIMAL32 = 22
    DECIMAL64 = 23
    STRING = 24
    LIST = 25
    STRUCT = 26
    DECIMAL128 = 27


# Storage dtype (the JAX/numpy dtype holding the column's fixed-width payload).
_STORAGE: dict[TypeId, np.dtype] = {
    TypeId.INT8: np.dtype(np.int8),
    TypeId.INT16: np.dtype(np.int16),
    TypeId.INT32: np.dtype(np.int32),
    TypeId.INT64: np.dtype(np.int64),
    TypeId.UINT8: np.dtype(np.uint8),
    TypeId.UINT16: np.dtype(np.uint16),
    TypeId.UINT32: np.dtype(np.uint32),
    TypeId.UINT64: np.dtype(np.uint64),
    TypeId.FLOAT32: np.dtype(np.float32),
    TypeId.FLOAT64: np.dtype(np.float64),
    # BOOL8 is stored as one byte, value 0/1 (JCUDF stores bools as a full
    # byte; see the layout example in RowConversion.java:60-67).
    TypeId.BOOL8: np.dtype(np.uint8),
    TypeId.TIMESTAMP_DAYS: np.dtype(np.int32),
    TypeId.TIMESTAMP_SECONDS: np.dtype(np.int64),
    TypeId.TIMESTAMP_MILLISECONDS: np.dtype(np.int64),
    TypeId.TIMESTAMP_MICROSECONDS: np.dtype(np.int64),
    TypeId.TIMESTAMP_NANOSECONDS: np.dtype(np.int64),
    TypeId.DURATION_DAYS: np.dtype(np.int32),
    TypeId.DURATION_SECONDS: np.dtype(np.int64),
    TypeId.DURATION_MILLISECONDS: np.dtype(np.int64),
    TypeId.DURATION_MICROSECONDS: np.dtype(np.int64),
    TypeId.DURATION_NANOSECONDS: np.dtype(np.int64),
    TypeId.DECIMAL32: np.dtype(np.int32),
    TypeId.DECIMAL64: np.dtype(np.int64),
}

_VARIABLE_WIDTH = frozenset({TypeId.STRING, TypeId.LIST})


@dataclasses.dataclass(frozen=True)
class DType:
    """A logical column type: (type_id, scale).

    ``scale`` is only meaningful for DECIMAL32/DECIMAL64 and follows the
    reference convention (``RowConversion.java:114-118``): the stored integer
    ``unscaled`` represents the value ``unscaled * 10**scale`` (cudf uses
    negative scales for fractional digits).
    """

    id: TypeId
    scale: int = 0
    # Element/field types for nested columns: LIST has exactly one child (the
    # element type), STRUCT has one child per field.  Mirrors the cudf
    # lists/structs column hierarchy the reference builds on
    # (``row_conversion.cu:1264`` make_lists_column; SURVEY §2.9).
    children: tuple = ()

    def __post_init__(self):
        if self.scale != 0 and self.id not in (
                TypeId.DECIMAL32, TypeId.DECIMAL64, TypeId.DECIMAL128):
            raise ValueError(f"scale only valid for decimal types, got {self.id!r}")
        if self.id == TypeId.LIST and len(self.children) != 1:
            raise ValueError("LIST dtype requires exactly one child (element) type")
        if self.id == TypeId.STRUCT and not self.children:
            raise ValueError("STRUCT dtype requires at least one field type")
        if self.children and self.id not in (TypeId.LIST, TypeId.STRUCT):
            raise ValueError(f"children only valid for nested types, got {self.id!r}")

    # -- classification -----------------------------------------------------
    @property
    def is_fixed_width(self) -> bool:
        return self.id in _STORAGE

    @property
    def is_variable_width(self) -> bool:
        return self.id in _VARIABLE_WIDTH

    @property
    def is_decimal(self) -> bool:
        return self.id in (TypeId.DECIMAL32, TypeId.DECIMAL64, TypeId.DECIMAL128)

    @property
    def is_nested(self) -> bool:
        return self.id in (TypeId.LIST, TypeId.STRUCT)

    @property
    def is_timestamp(self) -> bool:
        return TypeId.TIMESTAMP_DAYS <= self.id <= TypeId.TIMESTAMP_NANOSECONDS

    @property
    def is_numeric(self) -> bool:
        return TypeId.INT8 <= self.id <= TypeId.FLOAT64

    # -- storage ------------------------------------------------------------
    @property
    def storage(self) -> np.dtype:
        """numpy storage dtype of the fixed-width payload."""
        if not self.is_fixed_width:
            raise TypeError(f"{self.id.name} has no fixed-width storage dtype")
        return _STORAGE[self.id]

    @property
    def jnp_storage(self):
        return jnp.dtype(self.storage)

    @property
    def itemsize(self) -> int:
        """Bytes one value occupies in the JCUDF row.

        Fixed-width: the storage size (``row_conversion.cu:1288-1295``).
        Variable-width: an 8-byte (offset, length) uint32 pair
        (``row_conversion.cu:1342-1350``).
        """
        if self.is_variable_width:
            return 8
        if self.id == TypeId.DECIMAL128:
            return 16
        return self.storage.itemsize

    @property
    def row_alignment(self) -> int:
        """Alignment of this column's slot within a JCUDF row.

        Fixed-width columns align to their own size (DECIMAL128 to 16,
        matching the reference's align-to-size rule,
        ``row_conversion.cu:1331-1370``); variable-width slots align to 4
        (two uint32s) — ``row_conversion.cu:1348-1350``.
        """
        if self.is_variable_width:
            return 4
        if self.id == TypeId.DECIMAL128:
            return 16
        return self.storage.itemsize

    def __repr__(self) -> str:
        if self.is_decimal:
            return f"DType({self.id.name}, scale={self.scale})"
        return f"DType({self.id.name})"


# Convenience singletons mirroring the reference's commonly used types.
int8 = DType(TypeId.INT8)
int16 = DType(TypeId.INT16)
int32 = DType(TypeId.INT32)
int64 = DType(TypeId.INT64)
uint8 = DType(TypeId.UINT8)
uint16 = DType(TypeId.UINT16)
uint32 = DType(TypeId.UINT32)
uint64 = DType(TypeId.UINT64)
float32 = DType(TypeId.FLOAT32)
float64 = DType(TypeId.FLOAT64)
bool8 = DType(TypeId.BOOL8)
timestamp_days = DType(TypeId.TIMESTAMP_DAYS)
timestamp_seconds = DType(TypeId.TIMESTAMP_SECONDS)
timestamp_ms = DType(TypeId.TIMESTAMP_MILLISECONDS)
timestamp_us = DType(TypeId.TIMESTAMP_MICROSECONDS)
timestamp_ns = DType(TypeId.TIMESTAMP_NANOSECONDS)
string = DType(TypeId.STRING)


def decimal32(scale: int) -> DType:
    return DType(TypeId.DECIMAL32, scale)


def decimal64(scale: int) -> DType:
    return DType(TypeId.DECIMAL64, scale)


def decimal128(scale: int) -> DType:
    """128-bit decimal.

    JAX/XLA has no int128 lane type, so the payload is stored as two int64
    lanes per row — ``data`` is [n, 2] with column 0 = low 64 bits (as the
    int64 bit pattern of the uint64 low word) and column 1 = high 64 bits
    (sign-carrying).  All arithmetic is done on the lane pair with explicit
    carries (``ops/decimal128.py``) — a TPU-native stand-in for cudf's
    ``__int128_t`` fixed_point columns.
    """
    return DType(TypeId.DECIMAL128, scale)


def list_(element: DType) -> DType:
    """LIST type (Arrow/cudf lists column: int32 offsets + child column)."""
    return DType(TypeId.LIST, 0, (element,))


def struct_(*fields: DType) -> DType:
    """STRUCT type (cudf structs column: parallel child columns)."""
    return DType(TypeId.STRUCT, 0, tuple(fields))


def from_numpy(dt: np.dtype) -> DType:
    """Map a numpy dtype onto the closest logical DType."""
    dt = np.dtype(dt)
    if dt == np.bool_:
        return bool8
    for tid in (
        TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
        TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64,
        TypeId.FLOAT32, TypeId.FLOAT64,
    ):
        if dt == _STORAGE[tid]:
            return DType(tid)
    raise TypeError(f"no DType mapping for numpy dtype {dt}")
