"""Per-query HBM budgets with admission control (arena subsystem core).

The reference stack sizes an RMM pool once at startup and lets libcudf
allocate from it; spark-rapids adds per-task memory tracking and a spill
framework on top (SURVEY §5.5).  The TPU rebuild cannot own raw HBM —
XLA/PJRT's BFC arena is the allocator — so the budget layer works at the
level the engine *can* see: every large allocation site (join
pair-expansion buffers, build-side indexes, parquet scan slabs, shuffle
buckets) declares its bytes here BEFORE dispatching, and the ledger
answers admit / spill-then-admit / reject.

Ledger model
------------
One process-wide ledger (``in_use`` / ``peak``) plus an optional
per-query :class:`QueryBudget` stack (thread-local).  The effective limit
at any charge is the innermost query budget's limit, else the process
limit from ``SRJT_HBM_BUDGET``.  A charge that would exceed the limit
first asks ``memory.spill`` to reclaim LRU residents (build-index cache
entries and friends); if still over:

* ``strict=True``  — the charge rolls back and :class:`HbmBudgetExceeded`
  raises (explicit-allocation API, ``arena.alloc``).
* ``strict=False`` — the charge stands and ``arena.budget.soft_over``
  counts (ephemeral reservations: an admitted query must COMPLETE — the
  engine cannot spill a buffer XLA is about to materialize, so the soft
  path records the pressure instead of failing the query).

Sizing
------
``SRJT_HBM_BUDGET`` accepts ``512m`` / ``2g`` / plain bytes; empty /
``none`` / ``unlimited`` means no limit.  Without the env knob,
:func:`default_limit` sizes the budget from the recorded
``join.expand.pair_elements`` histogram (PR 2 telemetry): the largest
observed pair expansion × ~40 bytes/pair × headroom — the measured HBM
pressure point the ROADMAP names.

Discipline (same as ``utils.metrics``): every public entry gates on one
module bool; nothing here syncs a device value — all byte counts arrive
as host ints.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

from ..analysis import sanitize
from ..utils import flight, knobs, metrics

# pair-expansion working set per output pair in ops/join.py: pair_ids,
# left_idx, within, r_pos, right_idx int64 lanes + the matched mask
PAIR_EXPANSION_BYTES = 40
_HEADROOM = 4.0
_FLOOR_BYTES = 64 << 20

_LOCK = sanitize.tracked_rlock("memory.budget")      # shared with memory.spill (lock order:
#                                budget → spill registry, never reversed)

_enabled: bool = (knobs.get("SRJT_HBM_ARENA")
                  or bool(knobs.get("SRJT_HBM_BUDGET")))


class HbmBudgetExceeded(RuntimeError):
    """A strict charge exceeded the active budget even after spilling."""

    def __init__(self, requested: int, in_use: int, limit: int,
                 query: Optional[str], tag: str):
        self.requested = int(requested)
        self.in_use = int(in_use)
        self.limit = int(limit)
        self.query = query
        self.tag = tag
        super().__init__(
            f"HBM budget exceeded: {tag} wants {requested} B with "
            f"{in_use} B in use, limit {limit} B"
            + (f" (query {query})" if query else "")
            + " — raise SRJT_HBM_BUDGET or free residents")


def enabled() -> bool:
    return _enabled


def set_enabled(on: Optional[bool] = None) -> None:
    """Toggle the arena subsystem; ``None`` re-reads the env knobs."""
    global _enabled
    if on is None:
        _enabled = (knobs.get("SRJT_HBM_ARENA")
                    or bool(knobs.get("SRJT_HBM_BUDGET")))
    else:
        _enabled = bool(on)


def active() -> bool:
    """True when charges should be taken NOW: arena on, and not inside a
    ``syncs.replay`` re-trace (the replay re-runs plan Python whose
    allocations were already admitted by the capture run)."""
    if not _enabled:
        return False
    from ..utils import syncs
    return syncs.mode() != "replay"


def parse_bytes(s) -> Optional[int]:
    """``"512m"`` / ``"2g"`` / ``"65536"`` → bytes; None/empty/``none``/
    ``unlimited`` → None (no limit)."""
    if s is None:
        return None
    if isinstance(s, (int, float)):
        return int(s)
    t = s.strip().lower()
    if t in ("", "none", "unlimited", "off"):
        return None
    mult = 1
    if t[-1] in "kmgt":
        mult = 1 << (10 * ("kmgt".index(t[-1]) + 1))
        t = t[:-1]
    return int(float(t) * mult)


class QueryBudget:
    """One query's admission scope: a limit plus its own peak tracking."""

    __slots__ = ("name", "limit", "charged", "peak", "spills_at_entry")

    def __init__(self, name: str, limit: Optional[int]):
        self.name = name
        self.limit = limit
        self.charged = 0           # bytes this query charged (net)
        self.peak = 0              # high-water of the PROCESS ledger


class _Ledger:
    __slots__ = ("in_use", "peak")

    def __init__(self):
        self.in_use = 0
        self.peak = 0


_process = _Ledger()
_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current() -> Optional[QueryBudget]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def process_limit() -> Optional[int]:
    return parse_bytes(knobs.get("SRJT_HBM_BUDGET"))


def limit_now() -> Optional[int]:
    q = current()
    if q is not None and q.limit is not None:
        return q.limit
    return process_limit()


def default_limit() -> Optional[int]:
    """Budget sized from the recorded pair-expansion histogram (PR 2):
    largest observed expansion × ~40 B/pair × headroom, floored at 64 MiB.
    None (unlimited) when neither the env knob nor the histogram exist."""
    env = process_limit()
    if env is not None:
        return env
    h = metrics.snapshot()["histograms"].get("join.expand.pair_elements")
    if not h:
        return None
    return max(int(h["max"] * PAIR_EXPANSION_BYTES * _HEADROOM),
               _FLOOR_BYTES)


def in_use() -> int:
    return _process.in_use


def peak() -> int:
    return _process.peak


def reset() -> None:
    """Zero the ledgers (tests)."""
    with _LOCK:
        _process.in_use = 0
        _process.peak = 0
        _tls.stack = []


def _note_gauges() -> None:
    if metrics.recording():
        metrics.gauge("arena.bytes_in_use", _process.in_use)
        metrics.gauge_max("arena.peak_bytes", _process.peak)


def charge(nbytes: int, tag: str = "buf", *, strict: bool = False) -> bool:
    """Admit ``nbytes`` against the active budget.

    Over-limit charges first ask the spill registry to reclaim the
    deficit from LRU residents.  Returns True when the charge fits (or no
    limit applies); strict charges raise :class:`HbmBudgetExceeded`
    instead of standing over-limit."""
    if not active() or nbytes <= 0:
        return True
    n = int(nbytes)
    exc = None
    with _LOCK:
        _process.in_use += n
        limit = limit_now()
        if limit is not None and _process.in_use > limit:
            from . import spill
            spill.reclaim(_process.in_use - limit)
        fits = limit is None or _process.in_use <= limit
        if not fits and strict:
            _process.in_use -= n
            q = current()
            if metrics.recording():
                metrics.count("arena.budget.denied")
            exc = HbmBudgetExceeded(n, _process.in_use, limit,
                                    q.name if q else None, tag)
    if exc is not None:
        # incident fires OUTSIDE the ledger lock: the snapshot samples
        # live probes (scheduler queue depth etc.) that take their own
        # locks, and the black box must never order-invert against them
        flight.incident("hbm_budget", query=exc.query, tag=tag,
                        requested=n, in_use=exc.in_use, limit=exc.limit)
        raise exc
    with _LOCK:
        _process.peak = max(_process.peak, _process.in_use)
        q = current()
        if q is not None:
            q.charged += n
            q.peak = max(q.peak, _process.in_use)
        if not fits and metrics.recording():
            metrics.count("arena.budget.soft_over")
        _note_gauges()
        return fits


def release(nbytes: int) -> None:
    if not _enabled or nbytes <= 0:
        return
    with _LOCK:
        _process.in_use = max(_process.in_use - int(nbytes), 0)
        q = current()
        if q is not None:
            q.charged -= int(nbytes)
        _note_gauges()


@contextlib.contextmanager
def query_budget(name: str, limit_bytes=None, device=None, **attrs):
    """Per-query admission scope, composed with ``metrics.query_span``.

    ``limit_bytes`` accepts ints or ``"512m"`` strings; None sizes from
    ``SRJT_HBM_BUDGET`` / the pair-expansion histogram
    (:func:`default_limit`).  ``device`` labels the scope with the replica
    device serving the query (e.g. ``"cpu:3"``): the span is annotated and
    a per-device peak gauge recorded, so a multi-replica scheduler's arena
    pressure decomposes by device.  On exit the query span is annotated
    with the arena peak and the query's net spill activity, so Chrome
    traces carry the budget story next to the stage tree."""
    limit = parse_bytes(limit_bytes) if limit_bytes is not None \
        else default_limit()
    q = QueryBudget(name, limit)
    snap0 = metrics.snapshot()["counters"] if metrics.recording() else {}
    if device is not None:
        attrs = dict(attrs, device=device)
    with metrics.query_span(name, budget_bytes=limit or 0, **attrs) as sp:
        _stack().append(q)
        try:
            yield q
        finally:
            st = _stack()
            if st and st[-1] is q:
                st.pop()
            if sp is not None:
                snap1 = metrics.snapshot()["counters"]
                sp.annotate(
                    arena_peak_bytes=q.peak,
                    arena_spills=int(
                        snap1.get("arena.spill.events", 0)
                        - snap0.get("arena.spill.events", 0)))
            if metrics.recording():
                metrics.gauge_max("arena.query.peak_bytes", q.peak)
                if device is not None:
                    metrics.gauge_max(
                        "arena.query.peak_bytes."
                        + str(device).replace(":", ""), q.peak)
