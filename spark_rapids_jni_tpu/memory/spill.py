"""LRU registry of evictable device residents with host-spill/fault-back.

The spark-rapids analog is ``RapidsBufferCatalog`` + the device→host→disk
spill tiers: long-lived device residents (cached build-side join indexes,
promoted host-cache columns, parquet scan slabs) register here with their
byte footprint; when ``memory.budget`` sees pressure it walks this
registry in LRU order and asks residents to spill.

Spilling at this layer moves a resident's device arrays to pinned-enough
host RAM (``np.asarray`` — on the remote-TPU backend that is the tunnel
D2H; on CPU it is a view-copy) and drops the device references so XLA's
BFC arena can actually reuse the HBM.  Faulting back is ``jnp.asarray``
on next touch.  All payloads in this engine are integer/bit-pattern
arrays (FLOAT64 is stored as u32 bit pairs — the Column invariant), so a
spill→fault-back round trip is bit-exact on every backend.

Residents must be *re-derivable or self-contained*: the registry never
spills buffers a running plan holds references to — only caches that can
fault back (or rebuild) on their next touch.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from ..analysis import sanitize
from ..utils import flight, metrics
from . import budget

_reg: "OrderedDict[object, Resident]" = OrderedDict()


class Resident:
    """One evictable device-resident entry.

    ``spiller()`` must free the resident's device references and return
    the bytes it released; after it runs the entry leaves the registry
    (a fault-back re-registers it)."""

    __slots__ = ("key", "nbytes", "tag", "spiller")

    def __init__(self, key, nbytes: int, tag: str,
                 spiller: Callable[[], int]):
        self.key = key
        self.nbytes = int(nbytes)
        self.tag = tag
        self.spiller = spiller


def register(key, nbytes: int, tag: str,
             spiller: Callable[[], int]) -> None:
    """Track a device resident as evictable; charges the budget (soft —
    registering a cache entry must not fail the query; pressure instead
    spills older residents, possibly including this one later)."""
    if not budget.active():
        return
    budget.charge(nbytes, tag=tag, strict=False)
    with budget._LOCK:
        _reg[key] = Resident(key, nbytes, tag, spiller)
        _reg.move_to_end(key)


def unregister(key, *, release: bool = True) -> None:
    """Drop a resident (evicted, died with its arrays, or spilled)."""
    with budget._LOCK:
        r = _reg.pop(key, None)
    if r is not None and release:
        budget.release(r.nbytes)


def touch(key) -> None:
    """Mark a resident most-recently-used."""
    with budget._LOCK:
        if key in _reg:
            _reg.move_to_end(key)


def registered_bytes() -> int:
    with budget._LOCK:
        return sum(r.nbytes for r in _reg.values())


def resident_count() -> int:
    return len(_reg)


def reset() -> None:
    """Forget every resident without spilling (tests)."""
    with budget._LOCK:
        _reg.clear()


def reclaim(nbytes_needed: int) -> int:
    """Spill LRU residents until ``nbytes_needed`` bytes were released
    (or the registry runs dry).  Returns bytes actually freed."""
    freed = 0
    while freed < nbytes_needed:
        with budget._LOCK:
            if not _reg:
                break
            key, r = next(iter(_reg.items()))
            _reg.pop(key, None)
        with metrics.span("arena.spill", tag=r.tag, bytes=r.nbytes):
            try:
                got = int(r.spiller())
            except Exception:
                got = 0
        budget.release(r.nbytes)
        freed += got or r.nbytes
        if metrics.recording():
            metrics.count("arena.spill.events")
            metrics.count("arena.spill.bytes", r.nbytes)
            metrics.count(f"arena.spill.{r.tag}")
    return freed


class SpillableArrays:
    """A named bundle of device arrays that can round-trip through host
    RAM bit-exactly (the generic resident payload: build-index lanes,
    promoted columns).

    ``get()`` returns the device-array dict, faulting back from the host
    copies when spilled (counted as ``arena.faultback.*``); ``spill()``
    moves every array to host and drops the device references."""

    __slots__ = ("tag", "_dev", "_host", "nbytes", "_mu")

    def __init__(self, tag: str, arrays: dict):
        self.tag = tag
        self._dev: Optional[dict] = {k: v for k, v in arrays.items()}
        self._host: Optional[dict] = None
        self.nbytes = sum(int(getattr(a, "nbytes", 0) or 0)
                          for a in arrays.values() if a is not None)
        self._mu = sanitize.tracked_rlock("memory.spill")

    @property
    def spilled(self) -> bool:
        return self._dev is None

    def spill(self) -> int:
        """Device → host; returns bytes released (0 when already host)."""
        with self._mu:
            if self._dev is None:
                return 0
            self._host = {k: (None if a is None else np.asarray(a))
                          for k, a in self._dev.items()}
            self._dev = None
            return self.nbytes

    def get(self) -> dict:
        """The device-array dict, faulting back if spilled.  A fault-back
        that cannot re-upload (device OOM mid-restore) is an incident —
        the resident's data survives on the host, but the query that
        touched it is about to fail with the arena in a pressure state
        worth a black-box snapshot."""
        with self._mu:
            if self._dev is None:
                import jax.numpy as jnp
                try:
                    with metrics.span("arena.faultback", tag=self.tag,
                                      bytes=self.nbytes):
                        self._dev = {
                            k: (None if a is None else jnp.asarray(a))
                            for k, a in self._host.items()}
                except BaseException as e:
                    self._dev = None   # stay spilled; host copy is intact
                    flight.incident("spill_faultback", tag=self.tag,
                                    nbytes=self.nbytes, error=repr(e))
                    raise
                self._host = None
                if metrics.recording():
                    metrics.count("arena.faultback.events")
                    metrics.count("arena.faultback.bytes", self.nbytes)
            return self._dev


class SpillableTable:
    """In-place host spill for a whole :class:`~..column.Table` (parquet
    fused-scan outputs, exec-prefetch staged request tables).

    :class:`SpillableArrays` works for payloads whose OWNER re-fetches
    lanes through ``get()``; a scan-output table is instead held directly
    by the caller, so eviction must work in place: :meth:`spill` replaces
    every column's device arrays with their host ``np`` copies (Column
    payload fields are plain dataclass attributes, and the op library
    accepts np arrays, re-uploading on next touch) — fault-back is
    therefore *implicit and bit-exact*: every payload in the engine is an
    integer/bit-pattern array (FLOAT64 rides as u32 bit pairs), so the
    host round trip preserves bits on every backend.  Offsets whose host
    mirror is already promoted into ``utils.hostcache`` spill for free
    when the mirror's dtype/shape match — the mirror IS the host copy.

    Holds only a weakref to the table: residency must not keep a dead
    request's working set alive."""

    __slots__ = ("tag", "_ref", "nbytes")

    def __init__(self, table, tag: str, on_death=None):
        self.tag = tag
        # the registry's spiller closure keeps THIS object (and so this
        # weakref + its death callback) alive exactly as long as the
        # registration itself
        self._ref = weakref.ref(table, on_death)
        self.nbytes = table_device_bytes(table)

    def spill(self) -> int:
        import jax

        from ..utils import hostcache
        t = self._ref()
        if t is None:
            return 0
        freed = 0
        for col in _concrete_columns(t):
            for field in _payload_fields(col):
                a = getattr(col, field, None)
                if a is None or not isinstance(a, jax.Array):
                    continue
                h = hostcache.peek(a)
                if (h is None or h.dtype != np.dtype(a.dtype)
                        or h.shape != a.shape):
                    h = np.asarray(a)
                setattr(col, field, h)
                freed += int(a.nbytes)
        if freed and metrics.recording():
            metrics.count("arena.spill.table_cols")
        return freed


def _payload_fields(col) -> tuple:
    """The column's spillable payload attributes.  Dict columns spill their
    CODES (touching ``data``/``offsets`` would materialize the byte payload
    — allocating under pressure, the opposite of spilling); the shared
    dictionary spills through its own entry in ``_concrete_columns``."""
    from ..column import DictColumn
    if isinstance(col, DictColumn):
        return ("codes", "validity")
    return ("data", "offsets", "validity")


def _concrete_columns(table):
    """The table's materialized columns, recursing into children; lazy
    columns that were never forced hold no device payload and are left
    untouched (forcing them here would ADD allocations under pressure)."""
    from ..column import DictColumn, LazyColumn
    out = []
    stack = list(table.columns)
    while stack:
        c = stack.pop()
        if isinstance(c, LazyColumn):
            if c._col is None:
                continue
            c = c._col
        out.append(c)
        if isinstance(c, DictColumn):
            stack.append(c.dictionary)
            if c._mat is not None:     # already-materialized bytes spill too
                stack.append(c._mat)
            continue
        if c.children:
            stack.extend(c.children)
    return out


def table_device_bytes(table) -> int:
    """Total bytes of the table's device-resident payload arrays."""
    import jax
    total = 0
    for col in _concrete_columns(table):
        for field in _payload_fields(col):
            a = getattr(col, field, None)
            if a is not None and isinstance(a, jax.Array):
                total += int(a.nbytes)
    return total


def register_table(table, tag: str) -> Optional[SpillableTable]:
    """Track a caller-held table's device payload as evictable (fused-scan
    outputs, staged request tables).  The registration dies with the
    table; a table touched again after spilling re-uploads implicitly and
    is NOT re-registered (the next scan/stage registers its own).  Returns
    the handle, or None when the arena is off / nothing is device-resident.
    """
    if not budget.active():
        return None
    with budget._LOCK:
        # idempotent per table object: a staged loader's scan output is
        # already registered — re-registering would double-charge it
        for r in _reg.values():
            s = getattr(r.spiller, "__self__", None)
            if isinstance(s, SpillableTable) and s._ref() is table:
                return s
    key = (tag, id(table))
    try:
        st = SpillableTable(table, tag, on_death=lambda _: unregister(key))
    except TypeError:
        return None
    if st.nbytes <= 0:
        return None
    register(key, st.nbytes, tag, st.spill)
    return st
