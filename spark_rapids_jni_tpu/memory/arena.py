"""Size-class slab arena over device memory (RMM pool analog, TPU-flavor).

XLA/PJRT owns physical HBM (its BFC arena is the allocator), and device
arrays are immutable — so this arena does what an allocation layer CAN do
above jax, in three tiers:

* **slabs** — ``alloc``/``free``/``trim``: uint8 device buffers rounded up
  to power-of-two size classes (min 256 B), kept on a per-class free list
  when freed and handed back by identity on the next matching ``alloc``.
  Freed-but-pooled slabs keep their HBM reserved (exactly like an RMM
  pool holds its arena), so a steady-state loop's scratch never churns
  the BFC allocator; ``trim()`` returns everything.
* **zeros cache** — ``zeros(shape, dtype)``: join null-fill and empty
  columns allocate identical all-zero arrays over and over; device arrays
  are immutable, so ONE pooled instance per (shape, dtype) serves every
  caller (LRU-capped, ``SRJT_ARENA_ZEROS_CAP``).
* **reservations** — ``reserve(nbytes)``: accounting-only admission for
  ephemeral buffers XLA materializes inside a dispatch (join
  pair-expansion lists — ~10× input on skewed keys — parquet scan slabs,
  shuffle buckets).  The bytes are charged to ``memory.budget`` for the
  context's lifetime; pressure spills LRU residents (``memory.spill``)
  before the dispatch runs.

Per-device bytes-in-use / high-water are tracked for every slab and
reservation and flow into the ``utils.metrics`` registry as
``arena.bytes_in_use`` / ``arena.peak_bytes`` /
``arena.device{i}.bytes_in_use`` gauges (Chrome-trace sidecar included).

Strictness: ``alloc`` is admission-controlled (raises
:class:`~.budget.HbmBudgetExceeded` over budget); ``reserve`` defaults to
soft — an admitted query completes with recorded pressure rather than
failing mid-plan (see ``memory.budget``).
"""

from __future__ import annotations

import contextlib
import os
from collections import OrderedDict

from ..analysis import sanitize
from ..utils import knobs, metrics
from . import budget

MIN_CLASS = 256

_lock = sanitize.tracked_rlock("memory.arena")
_free: dict[tuple, list] = {}            # (class, device) → [u8 arrays]
_zeros: "OrderedDict[tuple, object]" = OrderedDict()
_zeros_bytes = 0

_in_use = 0          # live slab bytes (class-rounded)
_pooled = 0          # freed slab bytes retained on free lists
_peak = 0
_dev_in_use: dict[int, int] = {}
_dev_peak: dict[int, int] = {}


def size_class(nbytes: int) -> int:
    """Next power-of-two ≥ nbytes, floored at ``MIN_CLASS`` (alignment:
    every slab length is a multiple of 256, so any fixed-width dtype view
    tiles it exactly)."""
    n = max(int(nbytes), MIN_CLASS)
    return 1 << (n - 1).bit_length()


def _zeros_cap() -> int:
    return budget.parse_bytes(knobs.get("SRJT_ARENA_ZEROS_CAP")) or 0


def _device_id(arr) -> int:
    try:
        return min(d.id for d in arr.devices())
    except Exception:
        return 0


class Slab:
    """One arena buffer: a uint8 device array of ``nbytes`` (the size
    class) backing a request of ``requested`` bytes."""

    __slots__ = ("data", "nbytes", "requested", "tag", "_freed")

    def __init__(self, data, nbytes: int, requested: int, tag: str):
        self.data = data
        self.nbytes = nbytes
        self.requested = requested
        self.tag = tag
        self._freed = False


def _note_gauges() -> None:
    if not metrics.recording():
        return
    metrics.gauge("arena.slab_bytes_in_use", _in_use)
    metrics.gauge("arena.pooled_bytes", _pooled)
    for i, v in _dev_in_use.items():
        metrics.gauge(f"arena.device{i}.bytes_in_use", v)
        metrics.gauge_max(f"arena.device{i}.peak_bytes", _dev_peak[i])


def alloc(nbytes: int, tag: str = "scratch") -> Slab:
    """A device slab of ≥ ``nbytes`` zero bytes.  Reuses a pooled slab of
    the same size class when one exists (identity reuse — the returned
    buffer IS the donated one); otherwise admission-checks the budget
    (strict: raises :class:`~.budget.HbmBudgetExceeded`) and allocates."""
    global _in_use, _pooled, _peak
    cls = size_class(nbytes)
    import jax
    dev = 0
    try:
        dev = jax.local_devices()[0].id
    except Exception:
        pass
    with _lock:
        stack = _free.get((cls, dev))
        if stack:
            data = stack.pop()
            _pooled -= cls
            _in_use += cls
            if metrics.recording():
                metrics.count("arena.reuse.hits")
                metrics.count("arena.reuse.bytes", cls)
            _note_gauges()
            return Slab(data, cls, int(nbytes), tag)
    # new slab: admit first so a denied alloc leaves no dangling buffer
    budget.charge(cls, tag=f"arena.{tag}", strict=True)
    import jax.numpy as jnp
    data = jnp.zeros(cls, jnp.uint8)
    dev = _device_id(data)
    with _lock:
        _in_use += cls
        _peak = max(_peak, _in_use + _pooled)
        _dev_in_use[dev] = _dev_in_use.get(dev, 0) + cls
        _dev_peak[dev] = max(_dev_peak.get(dev, 0), _dev_in_use[dev])
        if metrics.recording():
            metrics.count("arena.alloc.calls")
            metrics.count("arena.alloc.bytes", cls)
        _note_gauges()
    return Slab(data, cls, int(nbytes), tag)


def free(slab: Slab) -> None:
    """Donate a slab back to its size-class free list.  The HBM stays
    reserved (pooled) for the next same-class ``alloc``; ``trim()``
    returns it to the backing allocator and the budget."""
    global _in_use, _pooled
    if slab._freed:
        return
    slab._freed = True
    dev = _device_id(slab.data)
    with _lock:
        _free.setdefault((slab.nbytes, dev), []).append(slab.data)
        _in_use -= slab.nbytes
        _pooled += slab.nbytes
        _note_gauges()
    slab.data = None


def trim() -> int:
    """Drop every pooled slab and cached zeros array; returns the bytes
    released back to the device allocator."""
    global _pooled, _zeros_bytes
    with _lock:
        released = _pooled
        for (cls, dev), stack in _free.items():
            d = _dev_in_use
            d[dev] = max(d.get(dev, 0) - cls * len(stack), 0)
        _free.clear()
        _pooled = 0
        _zeros.clear()
        _zeros_bytes = 0
        _note_gauges()
    budget.release(released)
    return released


def zeros(shape, dtype):
    """A pooled all-zeros device array (immutable, so one instance per
    (shape, dtype) serves every caller).  Falls through to a plain
    ``jnp.zeros`` when the arena is off or a replay trace is active."""
    global _zeros_bytes
    import jax.numpy as jnp
    if not budget.active():
        return jnp.zeros(shape, dtype)
    key = (tuple(shape) if isinstance(shape, (tuple, list)) else (shape,),
           jnp.dtype(dtype).str)
    with _lock:
        hit = _zeros.get(key)
        if hit is not None:
            _zeros.move_to_end(key)
            if metrics.recording():
                metrics.count("arena.zeros.hits")
            return hit
    arr = jnp.zeros(shape, dtype)
    import jax
    if isinstance(arr, jax.core.Tracer):
        return arr                       # inside a trace: never pool
    n = int(arr.nbytes)
    cap = _zeros_cap()
    if cap <= 0 or n > cap:
        return arr                       # pooling off / too big to pool
    with _lock:
        _zeros[key] = arr
        _zeros_bytes += n
        while _zeros_bytes > cap and len(_zeros) > 1:
            _, old = _zeros.popitem(last=False)
            _zeros_bytes -= int(old.nbytes)
    return arr


_NOOP = contextlib.nullcontext()


@contextlib.contextmanager
def _reserve_cm(nbytes: int, tag: str, strict: bool):
    budget.charge(nbytes, tag=tag, strict=strict)
    try:
        yield
    finally:
        budget.release(nbytes)


def reserve(nbytes: int, tag: str = "ephemeral", *, strict: bool = False):
    """Admission context for an ephemeral device buffer of known size:
    charges the budget for the context's lifetime (spilling LRU residents
    under pressure), releases on exit.  Returns a shared no-op context
    when the arena is off or a replay trace is active — zero allocation
    on the gated-off hot path."""
    if not budget.active() or nbytes <= 0:
        return _NOOP
    return _reserve_cm(int(nbytes), tag, strict)


def stats() -> dict:
    """Arena snapshot: slab ledgers, pool occupancy, per-device bytes."""
    with _lock:
        return {
            "slab_bytes_in_use": _in_use,
            "pooled_bytes": _pooled,
            "peak_bytes": _peak,
            "zeros_bytes": _zeros_bytes,
            "free_slabs": {f"{cls}@{dev}": len(v)
                           for (cls, dev), v in _free.items() if v},
            "budget_in_use": budget.in_use(),
            "budget_peak": budget.peak(),
            "device_bytes_in_use": dict(_dev_in_use),
            "device_peak_bytes": dict(_dev_peak),
        }


def reset() -> None:
    """Drop pools and ledgers (tests)."""
    global _in_use, _pooled, _peak, _zeros_bytes
    with _lock:
        _free.clear()
        _zeros.clear()
        _in_use = _pooled = _peak = _zeros_bytes = 0
        _dev_in_use.clear()
        _dev_peak.clear()
