"""Pooled device-memory subsystem: slab arena, per-query budgets, spill.

The TPU-native answer to the reference stack's RMM pool + spark-rapids
spill framework (ROADMAP "HBM arena" item).  Three layers:

* :mod:`.arena`  — size-class slab pool (identity reuse of donated
  slabs), pooled zeros cache, and accounting reservations for ephemeral
  buffers; per-device bytes-in-use / high-water gauges.
* :mod:`.budget` — per-query admission control (:func:`query_budget`
  composes with ``metrics.query_span``), sized from ``SRJT_HBM_BUDGET``
  or the recorded ``join.expand.pair_elements`` histogram; strict charges
  raise :class:`HbmBudgetExceeded`.
* :mod:`.spill`  — LRU registry of evictable device residents (join
  build-index cache, promoted host-cache columns) that spill to host RAM
  under pressure and fault back bit-exactly on touch.

Default **off**: the whole subsystem gates on ``SRJT_HBM_ARENA=1`` (or a
set ``SRJT_HBM_BUDGET``), and every instrumented call site is one bool
check away from the pre-arena behavior.
"""

from . import arena, budget, spill  # noqa: F401
from .budget import (HbmBudgetExceeded, active, enabled,  # noqa: F401
                     parse_bytes, query_budget, set_enabled)
from .arena import reserve  # noqa: F401

__all__ = ["arena", "budget", "spill", "HbmBudgetExceeded", "active",
           "enabled", "parse_bytes", "query_budget", "reserve",
           "set_enabled"]
