"""Always-on flight recorder: the serving runtime's black box.

``utils.metrics`` answers "how fast" when someone turned it on BEFORE the
run; production incidents happen when nobody did.  The reference ships a
fault-injection sidecar (``libcufaultinj.so``) precisely because
Spark-on-accelerator deployments live or die on after-the-fact diagnosis
— this module is the recorder half of that story: a bounded, thread-safe
ring buffer of recent request/stage/event records that runs EVEN WHEN the
metrics/trace knobs are off, and on any incident dumps a structured JSON
snapshot an operator can read cold.

Discipline
----------
* **Cheap enough to never turn off.**  One event is one small dict built
  by the caller and one ``deque.append`` under a lock; record sites are
  per-REQUEST (submit, dequeue, admit, dispatch, resolve), never per-row
  or per-dispatch-inner-loop.  The ``serve_bench`` overhead measurement
  (SERVE_BENCH.json ``flight_overhead``) holds the steady-state cost
  under 2%.
* **Records are atomic.**  An event dict is fully built before it enters
  the ring and never mutated after; concurrent writers interleave whole
  records, never fields (``tests/test_flight.py`` hammers this from 4+
  threads).
* **Incidents never raise.**  A failed snapshot write is a counter, not a
  second failure riding the first.

Knobs
-----
  SRJT_FLIGHT=0|1            master gate (default ON — this is the
                             black box; turning it off is the exception)
  SRJT_FLIGHT_N=<n>          ring capacity in events (default 512)
  SRJT_INCIDENT_DIR=<dir>    where incident snapshots land; unset means
                             incidents are counted + ring-recorded but
                             not written to disk
  SRJT_INCIDENT_PER_KIND=<n> per-kind snapshot cap per process (default
                             5 — a breach storm must not fill the disk)

Snapshot shape (one JSON object per file)::

  {"kind": ..., "ts": ..., "request_id": ..., "batch": [...],
   "fields": {...},          # incident-site details
   "events": [...],          # the ring, oldest → newest
   "metrics": {...},         # counters/gauges/histograms snapshot
   "probes": {...}}          # live registered probes (queue depth,
                             # plan-cache stats, arena gauges, ...)
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Optional

from ..analysis import sanitize
from . import knobs, metrics, structured_log

_enabled: bool = knobs.get("SRJT_FLIGHT")

_lock = sanitize.tracked_lock("utils.flight")
_ring: "collections.deque[dict]" = collections.deque(
    maxlen=max(knobs.get("SRJT_FLIGHT_N"), 8))
_probes: dict[str, Callable[[], Any]] = {}
_incident_counts: dict[str, int] = {}
_incident_seq = 0


def enabled() -> bool:
    return _enabled


def set_enabled(on: Optional[bool] = None) -> None:
    """Toggle the recorder at runtime; ``None`` re-reads the env knob."""
    global _enabled
    if on is None:
        _enabled = knobs.get("SRJT_FLIGHT")
    else:
        _enabled = bool(on)


def set_capacity(n: int) -> None:
    """Resize the ring (tests); keeps the newest events."""
    global _ring
    with _lock:
        _ring = collections.deque(_ring, maxlen=max(int(n), 8))


def reset() -> None:
    """Drop every recorded event and incident budget (tests)."""
    with _lock:
        _ring.clear()
        _incident_counts.clear()


def record(kind: str, **fields) -> None:
    """Append one event to the ring.  The dict is complete before it
    enters the ring — concurrent appends interleave records, not keys."""
    if not _enabled:
        return
    ev = {"ts": round(time.time(), 6), "tid": threading.get_ident(),
          "kind": kind}
    ev.update(fields)
    with _lock:
        _ring.append(ev)


def events(last: Optional[int] = None, *,
           request_id: Optional[str] = None) -> list[dict]:
    """The ring's events oldest → newest (copies).  ``last`` keeps only
    the newest N; ``request_id`` filters to one request's lifecycle."""
    with _lock:
        evs = list(_ring)
    if request_id is not None:
        evs = [e for e in evs
               if e.get("rid") == request_id
               or request_id in (e.get("batch") or ())]
    if last is not None:
        evs = evs[-int(last):]
    return [dict(e) for e in evs]


# --- live-state probes ------------------------------------------------------


def register_probe(name: str, fn: Callable[[], Any]) -> None:
    """Register a zero-arg callable sampled into every incident snapshot
    (scheduler queue depth, plan-cache stats, admission in-flight bytes).
    Re-registering a name replaces the previous probe."""
    with _lock:
        _probes[name] = fn


def unregister_probe(name: str) -> None:
    with _lock:
        _probes.pop(name, None)


def sample_probes() -> dict:
    """Every registered probe's current value; a probe that raises
    reports its error string instead of killing the snapshot."""
    with _lock:
        items = list(_probes.items())
    out = {}
    for name, fn in items:
        try:
            out[name] = fn()
        except Exception as e:          # incident paths must not re-fail
            out[name] = f"<probe error: {e!r}>"
    return out


# --- incidents --------------------------------------------------------------


def incident_dir() -> Optional[str]:
    return knobs.get("SRJT_INCIDENT_DIR")


def incident(kind: str, *, request_id: Optional[str] = None,
             batch: Optional[list] = None, **fields) -> Optional[str]:
    """Record an incident: one ring event + ``flight.incidents`` counter
    + structured log line always; a JSON snapshot file when
    ``SRJT_INCIDENT_DIR`` is set and the per-kind cap allows.  Returns
    the snapshot path (None when not written).  Never raises."""
    global _incident_seq
    try:
        record(f"incident:{kind}", rid=request_id, batch=batch, **fields)
        if metrics.enabled():
            metrics.count("flight.incidents", in_trace=True)
            metrics.count(f"flight.incident.{kind}", in_trace=True)
        structured_log.event(f"incident.{kind}", request_id=request_id,
                             **{k: v for k, v in fields.items()
                                if isinstance(v, (str, int, float, bool))})
        out_dir = incident_dir()
        if not _enabled or out_dir is None:
            return None
        cap = max(knobs.get("SRJT_INCIDENT_PER_KIND"), 1)
        with _lock:
            n = _incident_counts.get(kind, 0)
            if n >= cap:
                return None
            _incident_counts[kind] = n + 1
            _incident_seq += 1
            seq = _incident_seq
        snap = {
            "kind": kind,
            "ts": round(time.time(), 6),
            "request_id": request_id,
            "batch": list(batch) if batch else [],
            "fields": fields,
            "events": events(),
            "metrics": metrics.snapshot(),
            "probes": sample_probes(),
        }
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"incident-{kind}-{os.getpid()}-{seq}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=1, default=repr)
        os.replace(tmp, path)          # readers never see a torn file
        return path
    except Exception:
        try:
            if metrics.enabled():
                metrics.count("flight.incident.write_failed", in_trace=True)
        except Exception:
            pass
        return None
