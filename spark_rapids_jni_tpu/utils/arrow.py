"""Arrow interchange (cudf ``to_arrow``/``from_arrow`` analog).

cudf columns ARE Arrow layout on device; this framework's columns are the
same layout in HBM (data + int32 offsets + validity), so interchange is a
buffer-level mapping, not a conversion: fixed-width payloads, string
offsets/chars, single-level lists, and decimals (Arrow decimal128 ↔ the
[n,2] int64 lane representation).  pyarrow is an optional dependency —
import errors surface only when these functions are called.
"""

from __future__ import annotations

import numpy as np

from .. import types as T
from ..column import Column, Table


def _pa():
    import pyarrow as pa
    return pa


_PA_FIXED = {
    "int8": T.int8, "int16": T.int16, "int32": T.int32, "int64": T.int64,
    "uint8": T.uint8, "uint16": T.uint16, "uint32": T.uint32,
    "uint64": T.uint64, "float": T.float32, "double": T.float64,
    "date32[day]": T.timestamp_days,
    "timestamp[s]": T.timestamp_seconds, "timestamp[ms]": T.timestamp_ms,
    "timestamp[us]": T.timestamp_us, "timestamp[ns]": T.timestamp_ns,
}


def from_arrow(arr) -> Column:
    """pyarrow Array / ChunkedArray → device Column."""
    pa = _pa()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
    if pa.types.is_decimal(t):
        from ..ops import decimal128 as d128
        import decimal
        with decimal.localcontext() as ctx:
            ctx.prec = 41      # default 28-digit context would round d128
            vals = [None if v is None else int(v.scaleb(t.scale))
                    for v in arr.to_pylist()]
        col = d128.from_pyints(vals, scale=-t.scale)
        if t.precision <= 18:
            from ..ops import cast
            narrow_to = (T.decimal32(-t.scale) if t.precision <= 9
                         else T.decimal64(-t.scale))
            return cast(col, narrow_to)
        return col
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return Column.strings_from_list(arr.to_pylist())
    if pa.types.is_list(t):
        return Column.list_from_pylist(arr.to_pylist())
    if pa.types.is_boolean(t):
        data = np.asarray([bool(v) if v is not None else False
                           for v in arr.to_pylist()], np.uint8)
        return Column.from_numpy(data, T.bool8, validity)
    key = str(t)
    if key in _PA_FIXED:
        dt = _PA_FIXED[key]
        if validity is not None:
            # fill nulls in ARROW space: to_numpy on a nullable int array
            # falls back to float64 and corrupts values above 2^53
            arr = arr.fill_null(pa.scalar(0, t))
        np_arr = np.asarray(arr.to_numpy(zero_copy_only=False))
        # datetime64 payloads → raw storage
        np_arr = np_arr.astype(dt.storage, casting="unsafe")
        return Column.from_numpy(np_arr, dt, validity)
    raise NotImplementedError(f"from_arrow: unsupported Arrow type {t}")


def to_arrow(col: Column):
    """Device Column → pyarrow Array (host copy)."""
    pa = _pa()
    dt = col.dtype
    if dt.id == T.TypeId.STRING:
        return pa.array(col.to_pylist(), pa.string())
    if dt.id == T.TypeId.LIST:
        return pa.array(col.to_pylist())
    if dt.is_decimal:
        scale = -dt.scale
        vals = col.to_pylist()
        import decimal
        with decimal.localcontext() as ctx:
            ctx.prec = 41      # default context rounds 29+ digit values
            converted = [None if v is None else
                         decimal.Decimal(v).scaleb(-scale) for v in vals]
        return pa.array(converted, pa.decimal128(38, scale))
    if dt.id == T.TypeId.BOOL8:
        return pa.array(col.to_pylist(), pa.bool_())
    if dt.id == T.TypeId.TIMESTAMP_DAYS:
        return pa.array(col.to_pylist(), pa.date32())
    if dt.is_timestamp:
        unit = {T.TypeId.TIMESTAMP_SECONDS: "s",
                T.TypeId.TIMESTAMP_MILLISECONDS: "ms",
                T.TypeId.TIMESTAMP_MICROSECONDS: "us",
                T.TypeId.TIMESTAMP_NANOSECONDS: "ns"}[dt.id]
        return pa.array(col.to_pylist(), pa.timestamp(unit))
    return pa.array(col.to_pylist(), pa.from_numpy_dtype(dt.storage))


def table_from_arrow(tbl) -> Table:
    """pyarrow Table → device Table (column order preserved)."""
    return Table([from_arrow(tbl.column(i))
                  for i in range(tbl.num_columns)])


def table_to_arrow(table: Table, names=None):
    """Device Table → pyarrow Table."""
    pa = _pa()
    names = names or [f"c{i}" for i in range(table.num_columns)]
    # from_arrays keeps duplicate names (a dict would silently drop them)
    return pa.Table.from_arrays([to_arrow(c) for c in table.columns],
                                names=list(names))
