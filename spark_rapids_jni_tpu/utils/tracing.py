"""Tracing / profiling hooks.

The reference instruments every public entry with NVTX ranges
(``CUDF_FUNC_RANGE()`` at ``NativeParquetJni.cpp:136,392,469,524,553,578,668``)
and exposes a Java-side toggle (``pom.xml:86,490``).  The TPU-native
equivalents are ``jax.named_scope`` (shows up in XLA HLO + xprof) and
``jax.profiler`` trace annotations; both degrade to no-ops off-device.
"""

from __future__ import annotations

import contextlib
import functools
import os

import jax

_ENABLED = os.environ.get("SPARK_RAPIDS_TPU_TRACE", "1") not in ("0", "false")


@contextlib.contextmanager
def func_range(name: str):
    """NVTX-range analog: a named scope visible in HLO and xprof traces."""
    if not _ENABLED:
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def traced(name: str | None = None):
    """Decorator form of :func:`func_range` (CUDF_FUNC_RANGE analog)."""

    def wrap(fn):
        scope = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with func_range(scope):
                return fn(*args, **kwargs)

        return inner

    return wrap
