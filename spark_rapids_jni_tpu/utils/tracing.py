"""Tracing / profiling hooks.

The reference instruments every public entry with NVTX ranges
(``CUDF_FUNC_RANGE()`` at ``NativeParquetJni.cpp:136,392,469,524,553,578,668``)
and exposes a Java-side toggle (``pom.xml:86,490``).  The TPU-native
equivalents are ``jax.named_scope`` (shows up in XLA HLO + xprof) and
``jax.profiler`` trace annotations; both degrade to no-ops off-device.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time

import jax

_ENABLED = os.environ.get("SPARK_RAPIDS_TPU_TRACE", "1") not in ("0", "false")


@contextlib.contextmanager
def func_range(name: str):
    """NVTX-range analog: a named scope visible in HLO and xprof traces."""
    if not _ENABLED:
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def traced(name: str | None = None):
    """Decorator form of :func:`func_range` (CUDF_FUNC_RANGE analog).

    Also feeds the structured-log knob (``SPARK_RAPIDS_TPU_LOG``,
    ``utils.structured_log``): when enabled, each call emits one event
    record with wall-time duration — the RMM-logging/spdlog analog."""

    def wrap(fn):
        scope = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            from . import structured_log as slog
            if slog.enabled():
                t0 = time.perf_counter()
                with func_range(scope):
                    out = fn(*args, **kwargs)
                slog.event(scope, duration_s=time.perf_counter() - t0)
                return out
            with func_range(scope):
                return fn(*args, **kwargs)

        return inner

    return wrap
