"""Tracing / profiling hooks.

The reference instruments every public entry with NVTX ranges
(``CUDF_FUNC_RANGE()`` at ``NativeParquetJni.cpp:136,392,469,524,553,578,668``)
and exposes a Java-side toggle (``pom.xml:86,490``).  The TPU-native
equivalents are ``jax.named_scope`` (shows up in XLA HLO + xprof) and
``jax.profiler`` trace annotations; both degrade to no-ops off-device.

The knob (``SPARK_RAPIDS_TPU_TRACE``) is read at import AND re-checkable at
runtime: :func:`set_enabled` flips it (parity with
``structured_log.configure`` — tests and the hot knob need the toggle
without a process restart).

``@traced`` entries additionally feed two sinks when their knobs are on:

* ``utils.structured_log`` — one event record with wall-time duration per
  call (the RMM-logging/spdlog analog);
* ``utils.metrics`` — one span in the per-query span tree (the NVTX range
  upgraded into a hierarchy; see ``utils/metrics.py``).
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Optional

import jax


def _read_env() -> bool:
    return os.environ.get("SPARK_RAPIDS_TPU_TRACE", "1") not in ("0", "false")


_ENABLED = _read_env()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: Optional[bool] = None) -> None:
    """Toggle tracing at runtime; ``None`` re-reads the env knob."""
    global _ENABLED
    _ENABLED = _read_env() if on is None else bool(on)


@contextlib.contextmanager
def func_range(name: str):
    """NVTX-range analog: a named scope visible in HLO and xprof traces."""
    if not _ENABLED:
        yield
        return
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def traced(name: str | None = None):
    """Decorator form of :func:`func_range` (CUDF_FUNC_RANGE analog).

    Also feeds the structured-log knob (``SPARK_RAPIDS_TPU_LOG``,
    ``utils.structured_log``): when enabled, each call emits one event
    record with wall-time duration — the RMM-logging/spdlog analog.
    With metrics on (``SPARK_RAPIDS_TPU_METRICS``, ``utils.metrics``),
    each call records one span in the current span tree."""

    def wrap(fn):
        scope = name or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            from . import metrics
            from . import structured_log as slog
            rec = metrics.recording()
            log = slog.enabled()
            if not (rec or log):
                with func_range(scope):
                    return fn(*args, **kwargs)
            t0 = time.perf_counter()
            ctx = metrics.span(scope) if rec else contextlib.nullcontext()
            with ctx, func_range(scope):
                out = fn(*args, **kwargs)
            if log:
                slog.event(scope, duration_s=time.perf_counter() - t0)
            return out

        return inner

    return wrap
