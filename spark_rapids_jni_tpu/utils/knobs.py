"""Central registry of every ``SRJT_*`` environment knob.

Before this module, knob reads were scattered ``os.environ.get("SRJT_...")``
calls with the default, the parse semantics, and the documentation living
at each call site — three copies per knob that drift independently, and no
single place an operator (or the README generator, or the lint gate) can
enumerate.  This registry is that place: one :class:`Knob` per name with
its default, parser, and a one-line doc.  The static-analysis knob pass
(``analysis/knobpass.py``, rule ``knob-env``) fails CI on any direct
``SRJT_*`` environ read outside this file, and rule ``knob-undoc`` fails
on registered knobs missing from the README table (regenerated with
``python tools/srjt_lint.py --knob-table``).

Behavior contract: :func:`get` re-reads the environment on every call —
exactly what the scattered call sites did — so runtime toggles
(``metrics.set_enabled(None)`` style) keep working.  Parsers reproduce
each site's historical semantics bit-for-bit (e.g. the serving gates
treat ``0``/``off``/``false``/empty as off, while
``SRJT_STREAM_ALLOW_APPROX`` is opt-IN on ``1``/``true``/``on`` only).

This module is deliberately dependency-free (stdlib ``os`` only) so the
lint tool can load it standalone, without importing the package (and its
jax dependency).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

__all__ = ["Knob", "REGISTRY", "register", "get", "markdown_table",
           "parse_bytes"]


# --- parsers ----------------------------------------------------------------
# Each returns the value the historical call site computed from the raw
# environment string.  ``raw`` may be None only when the knob's default is
# None (unset-means-unset knobs).


def _int(raw: str) -> int:
    return int(raw)


def _float(raw: str) -> float:
    return float(raw)


def _str(raw: Optional[str]) -> Optional[str]:
    return raw


def _on_unless_off(raw: str) -> bool:
    """The package's standard gate: anything except 0/off/false/empty."""
    return raw.lower() not in ("0", "off", "false", "")


def _on_unless_0_off(raw: str) -> bool:
    """Gate variant used by the scan/dict/xpack paths: 0/off disable."""
    return raw.lower() not in ("0", "off")


def _opt_in(raw: str) -> bool:
    """Opt-in gate: only 1/true/on enable (``SRJT_STREAM_ALLOW_APPROX``)."""
    return raw.lower() in ("1", "true", "on")


def _is_1(raw: str) -> bool:
    return raw == "1"


def _not_0(raw: str) -> bool:
    return raw != "0"


def _opt_float(raw: Optional[str]) -> Optional[float]:
    """None/empty/whitespace → None, else float (SLO objectives)."""
    if raw is None or not raw.strip():
        return None
    return float(raw)


def _opt_int(raw: Optional[str]) -> Optional[int]:
    """None/empty → None, else int (ports, dynamic-default counts)."""
    if raw is None or not raw:
        return None
    return int(raw)


def _opt_str(raw: Optional[str]) -> Optional[str]:
    """None/empty → None, else the string (paths, rule lists)."""
    return raw or None


def parse_bytes(raw) -> Optional[int]:
    """``"512m"`` / ``"2g"`` / ``"65536"`` → bytes; None/empty/``none``/
    ``unlimited``/``off`` → None (no limit).  Mirror of
    ``memory.budget.parse_bytes`` (kept here too so this module stays
    loadable without the package)."""
    if raw is None:
        return None
    if isinstance(raw, (int, float)):
        return int(raw)
    t = raw.strip().lower()
    if t in ("", "none", "unlimited", "off"):
        return None
    mult = 1
    if t[-1] in "kmgt":
        mult = 1 << (10 * ("kmgt".index(t[-1]) + 1))
        t = t[:-1]
    return int(float(t) * mult)


class Knob:
    """One registered environment knob: name, raw default, parser, doc."""

    __slots__ = ("name", "default", "parse", "doc", "section")

    def __init__(self, name: str, default: Optional[str],
                 parse: Callable[[Optional[str]], Any], doc: str,
                 section: str):
        self.name = name
        self.default = default       # raw string default; None = unset
        self.parse = parse
        self.doc = doc
        self.section = section

    def value(self) -> Any:
        """Parsed current value: environment override, else the default."""
        return self.parse(os.environ.get(self.name, self.default))


REGISTRY: dict[str, Knob] = {}


def register(name: str, default: Optional[str], parse, doc: str,
             section: str = "general") -> Knob:
    k = Knob(name, default, parse, doc, section)
    REGISTRY[name] = k
    return k


def get(name: str) -> Any:
    """The parsed value of registered knob ``name`` (re-reads the
    environment on every call).  Raises ``KeyError`` for unregistered
    names — register in this file first; the lint gate enforces it."""
    return REGISTRY[name].value()


def is_registered(name: str) -> bool:
    return name in REGISTRY


# --- the registry -----------------------------------------------------------
# Grouped by subsystem; ``section`` drives the README table's grouping.

# serving runtime (exec/)
register("SRJT_EXEC", "0", _on_unless_off,
         "serving-runtime gate for deployments (`exec.enabled()`)",
         "exec")
register("SRJT_EXEC_WORKERS", "4", _int,
         "worker threads pulling from the request queue", "exec")
register("SRJT_EXEC_QUEUE_DEPTH", "32", _int,
         "bounded queue depth; past it `submit` raises `ExecQueueFull`",
         "exec")
register("SRJT_EXEC_COALESCE_MS", "4", _float,
         "cross-request coalesce window (ms); `0` disables batching",
         "exec")
register("SRJT_EXEC_COALESCE_MAX", "16", _int,
         "max requests per coalesced batch", "exec")
register("SRJT_EXEC_DEADLINE", None, _opt_float,
         "default end-to-end timeout (s) for requests submitted without "
         "one", "exec")
register("SRJT_EXEC_INFLIGHT_BYTES", None, parse_bytes,
         "per-device in-flight admission cap (`512m` forms; unset = no "
         "gate)", "exec")
register("SRJT_EXEC_PREFETCH_DEPTH", "2", _int,
         "staged working sets held ahead of execution", "exec")
register("SRJT_EXEC_PLAN_CACHE_CAP", "32", _int,
         "compiled-plan LRU entry cap", "exec")
register("SRJT_EXEC_PLAN_SIZE_FP", "1", _on_unless_off,
         "size-fingerprint plan sharing across refreshed same-shape data",
         "exec")
register("SRJT_EXEC_DEVICES", "1", _int,
         "replicas (one per local device); `>1` enables multi-device "
         "serving", "exec")
register("SRJT_EXEC_RECOVERY", "1", _on_unless_off,
         "quarantine→probe→recovery lifecycle; `0` pins the legacy "
         "terminal-quarantine contract", "exec")
register("SRJT_EXEC_PROBE_BASE_S", "0.05", _float,
         "first recovery-probe delay (doubles per failure, jittered)",
         "exec")
register("SRJT_EXEC_PROBE_MAX_S", "2.0", _float,
         "probe backoff ceiling", "exec")
register("SRJT_EXEC_EJECT_AFTER", "3", _int,
         "consecutive failed canaries before permanent ejection", "exec")
register("SRJT_EXEC_RELOCATE_MAX", None, _opt_int,
         "max failover hops per request before it errors (default: the "
         "device count)", "exec")

# AOT plan-artifact store (exec/artifacts.py)
register("SRJT_AOT_DIR", None, _opt_str,
         "root of the persistent plan-artifact store (capture tapes + "
         "warm-up manifest + the XLA executable cache under `<dir>/xla`); "
         "unset disables AOT persistence", "aot")
register("SRJT_AOT_GEOM_BUCKETS", "1", _on_unless_off,
         "pow2-bucket input geometry in artifact keys so nearby dataset "
         "sizes share one artifact; `0` keys on exact shapes", "aot")
register("SRJT_AOT_WARMUP", "8", _int,
         "manifest entries (ranked by compile-ledger cost) the scheduler "
         "pre-hydrates in the background at startup; `0` disables the "
         "warm-up thread", "aot")
register("SRJT_AOT_XLA_CACHE", "1", _on_unless_off,
         "point JAX's persistent compilation cache at `<SRJT_AOT_DIR>/"
         "xla` (skipped when a cache dir is already configured); `0` "
         "leaves the JAX config untouched", "aot")

# SLO watchdog (exec/slo.py)
register("SRJT_SLO_P50_MS", None, _opt_float,
         "rolling-window p50 latency objective per query class", "slo")
register("SRJT_SLO_P95_MS", None, _opt_float,
         "rolling-window p95 latency objective per query class", "slo")
register("SRJT_SLO_P99_MS", None, _opt_float,
         "rolling-window p99 latency objective per query class", "slo")
register("SRJT_SLO_ERROR_RATE", None, _opt_float,
         "error-rate objective in [0, 1]", "slo")
register("SRJT_SLO_DEADLINE_RATE", None, _opt_float,
         "deadline-breach-rate objective in [0, 1]", "slo")
register("SRJT_SLO_DEFER_RATE", None, _opt_float,
         "admission-defer-rate objective in [0, 1]", "slo")
register("SRJT_SLO_DEGRADE_RATE", None, _opt_float,
         "degraded-admission-rate objective in [0, 1]", "slo")
register("SRJT_SLO_RELOCATE_RATE", None, _opt_float,
         "failover-relocation-rate objective in [0, 1]", "slo")
register("SRJT_SLO_WINDOW_S", "60", _float,
         "rolling window length (s)", "slo")
register("SRJT_SLO_MIN_N", "8", _int,
         "minimum window population before any verdict", "slo")
register("SRJT_SLO_COOLDOWN_S", "30", _float,
         "per-(class, objective) re-alarm holdoff (s)", "slo")

# memory arena (memory/)
register("SRJT_HBM_ARENA", "0", _on_unless_off,
         "master gate for the arena subsystem", "memory")
register("SRJT_HBM_BUDGET", None, _str,
         "process/query byte limit (`512m`, `2g`, plain bytes); setting "
         "it also enables the arena", "memory")
register("SRJT_INDEX_CACHE_CAP", "512m", _str,
         "build-index cache LRU byte cap "
         "(`join.build_index.evictions` counts)", "memory")
register("SRJT_ARENA_ZEROS_CAP", "16m", _str,
         "pooled-zeros cache cap (`0` disables pooling)", "memory")
register("SRJT_HOSTCACHE_CAP", "256m", _str,
         "host-mirror cache LRU byte cap "
         "(`arena.hostcache.evictions` counts)", "memory")

# observability (utils/)
register("SRJT_METRICS_WINDOW_N", "1024", _int,
         "bounded per-histogram sample tail feeding rolling percentiles",
         "observability")
register("SRJT_METRICS_PORT", None, _opt_str,
         "serve `metrics.to_prometheus()` on "
         "`http://0.0.0.0:<port>/metrics`", "observability")
register("SRJT_FLIGHT", "1", _on_unless_off,
         "flight-recorder master gate (leave on: steady-state cost "
         "budget <2%)", "observability")
register("SRJT_FLIGHT_N", "512", _int,
         "flight-recorder ring capacity in events", "observability")
register("SRJT_INCIDENT_DIR", None, _opt_str,
         "where incident snapshots land; unset = incidents counted + "
         "ring-recorded, not written", "observability")
register("SRJT_INCIDENT_PER_KIND", "5", _int,
         "per-kind snapshot cap per process (breach storms must not "
         "fill the disk)", "observability")
register("SRJT_SANITIZE", "0", _str,
         "runtime sanitizers: `1` files flight incidents on lock-order "
         "inversions and hot-path retraces, `strict` raises instead "
         "(CI smokes run strict)", "observability")
register("SRJT_PROFILE", "0", _on_unless_off,
         "per-plan-node runtime profiling (`plan/profile.py`): rows/"
         "bytes/time per executed node, `explain_analyze()` rendering; "
         "off = one bool check on the executor path", "observability")
register("SRJT_PROFILE_DEVICE_TIME", "1", _on_unless_0_off,
         "fence each profiled node's output (`block_until_ready`) to "
         "attribute device time; `0`/`off` records host wall only",
         "observability")
register("SRJT_PROFILE_VALIDITY", "0", _opt_in,
         "per-node validity density in profiles (adds one scalar sync "
         "per nullable column per node, recorded on the capture/replay "
         "tape — keep the knob stable across a compiled plan's "
         "lifetime)", "observability")
register("SRJT_PROFILE_DIR", None, _opt_str,
         "directory where per-query profile JSON artifacts land on "
         "profile close; unset = profiles kept in memory only",
         "observability")

# ops / joins
register("SRJT_JOIN_ENGINE", None, _str,
         "force the join engine: `dense` or `sorted` (default: planner "
         "choice)", "ops")

# rowconv
register("SRJT_RAGGED_DMA", "auto", _on_unless_0_off,
         "Pallas ragged DMA path on TPU backends; `0`/`off` forces the "
         "XLA gather fallback", "rowconv")
register("SRJT_FIXED_CONCAT", None, _opt_str,
         "A/B override for the fixed-width word engine: `1`/`on` forces "
         "concat, anything else set forces perm", "rowconv")
register("SRJT_XPACK", "1", _on_unless_0_off,
         "native xpack fast path for row conversion; `0`/`off` falls "
         "back to the reference composer", "rowconv")
register("SRJT_PALLAS_PACKWIN", "0", _str,
         "Pallas `pack_windows` kernel for the var-width row combine: "
         "`1`/`on` on TPU, `interpret` forces interpreter mode (CI "
         "parity), default off → lax window combine", "rowconv")
register("SRJT_PALLAS_EXTRACT", "0", _str,
         "Pallas `extract_group_windows` kernel for var-width char "
         "extraction: `1`/`on` on TPU, `interpret` forces interpreter "
         "mode (CI parity), default off → lax slab gather", "rowconv")

# plan optimizer
register("SRJT_PLAN_OPT", "1", _not_0,
         "`0` disables all plan rewrites (lower the raw tree)", "plan")
register("SRJT_PLAN_RULES", None, _opt_str,
         "comma-separated allowlist of optimizer rule names", "plan")
register("SRJT_PLAN_MAX_PASSES", "10", _int,
         "optimizer fixpoint pass bound", "plan")
register("SRJT_PLAN_STATS_CAP", "4096", _int,
         "cardinality-stats LRU entry cap", "plan")
register("SRJT_PLAN_STATS_PATH", None, _opt_str,
         "JSON sidecar for cardinality stats: loaded at first use for "
         "warm priors, saved atomically at exit", "plan")
register("SRJT_AQE", "0", _opt_in,
         "adaptive query execution: stage-wise replanning on observed "
         "cardinalities (join reorder, engine flips, skew salting)",
         "plan")
register("SRJT_AQE_SKEW_FACTOR", "4.0", _float,
         "hot-key skew ratio (hottest/mean) at or above which AQE salts "
         "the repartition join", "plan")
register("SRJT_AQE_REPLAN_MIN_ROWS", "64", _int,
         "AQE skips join reorder when every pending input is smaller "
         "than this (replan overhead not worth it)", "plan")

# SQL front-end
register("SRJT_SQL_CACHE", "1", _on_unless_0_off,
         "memoize SQL text → optimized plan tree per (text, params, "
         "schema) so repeat submissions skip parse+bind+optimize; "
         "`0`/`off` reparses every call (bench baseline)", "sql")
register("SRJT_SQL_CACHE_CAP", "256", _int,
         "parsed-plan memo entry cap (LRU)", "sql")
register("SRJT_SQL_MAX_LEN", "262144", _int,
         "reject SQL text longer than this many characters before "
         "tokenizing (serving-surface input bound)", "sql")

# parquet scan
register("SRJT_DICT_STRINGS", "1", _on_unless_0_off,
         "dictionary-encoded string fast path; `0`/`off` reverts to "
         "eager materialization for differential testing", "parquet")
register("SRJT_FUSED_SCAN", "1", _on_unless_0_off,
         "fused multi-row-group scan assembly; `0`/`off` decodes row "
         "groups independently", "parquet")
register("SRJT_STAGE_SLABS", "1", _on_unless_0_off,
         "coalesced h2d staging: a row group's raw pages/levels/"
         "dictionaries upload as a few large slabs instead of per-buffer "
         "`device_put`s; `0`/`off` reverts to per-buffer uploads",
         "parquet")
register("SRJT_STAGE_SLAB_BYTES", "64m", parse_bytes,
         "slab size cap for the coalescing stager (`64m` forms); a flush "
         "splits into multiple transfers past it", "parquet")
register("SRJT_STAGE_PIPELINE", "1", _on_unless_0_off,
         "double-buffered row-group pipeline: walk/decompress row group "
         "k+1 on host while k's slabs transfer; `0`/`off` stages "
         "synchronously", "parquet")
register("SRJT_STAGE_PIPELINE_DEPTH", "2", _int,
         "row groups walked ahead of the transfer stage (pipeline "
         "buffer bound)", "parquet")
register("SRJT_SCAN_DONATE", "auto", _str,
         "donate staged input slabs to the fused decode program (XLA "
         "reuses the buffers for outputs): `auto` = non-CPU backends, "
         "`1`/`on` forces, `0`/`off` disables", "parquet")
register("SRJT_FUSED_FILTER", "1", _on_unless_0_off,
         "fused scan→filter: planner row predicates prune rows on the "
         "staged host metadata (dictionary entries evaluated once, codes "
         "masked) before strings/wide columns materialize; `0`/`off` "
         "decodes all rows and filters after", "parquet")
register("SRJT_PALLAS_DICT_GATHER", "0", _str,
         "Pallas dictionary-index gather in the scan decode: `1`/`on` on "
         "TPU, `interpret` forces interpreter mode (CI parity), default "
         "off → lax gather", "parquet")
register("SRJT_PALLAS_TRANSPOSE", "0", _str,
         "Pallas byte→word transpose for PLAIN payload decode: `1`/`on` "
         "on TPU, `interpret` forces interpreter mode (CI parity), "
         "default off → strided lax transpose", "parquet")

# ml handoff (ml/)
register("SRJT_ML_PACK", "rowconv", _str,
         "feature-pack engine: `rowconv` reinterprets the JCUDF fixed-width "
         "row stream as the feature matrix (zero-copy), `stack` is the "
         "reference lane-stack A/B", "ml")
register("SRJT_ML_BATCH", "256", _int,
         "default minibatch size for `ml.pipeline.BatchPipeline`", "ml")
register("SRJT_ML_SEED", "0", _int,
         "default PRNG seed for the device-side epoch shuffle", "ml")
register("SRJT_ML_SHUFFLE", "feistel", _str,
         "epoch-shuffle engine: `feistel` is the sort-free O(n) Feistel "
         "bijection, `sort` is `jax.random.permutation` (single-threaded "
         "O(n log n) sort on XLA:CPU) kept as the cross-check", "ml")
register("SRJT_ML_EPOCH_FUSE", "1", _on_unless_0_off,
         "fuse each training epoch into one jitted `lax.scan` dispatch; "
         "`0`/`off` dispatches per-batch steps", "ml")
register("SRJT_ML_DONATE", "auto", _str,
         "donate minibatch buffers into the jitted train step/epoch "
         "(`1`/`on`, `0`/`off`, `auto` = on for non-CPU backends where "
         "XLA implements donation)", "ml")

# streaming
register("SRJT_STREAM_ALLOW_APPROX", "0", _opt_in,
         "allow approximate incremental states (`1`/`true`/`on` only)",
         "stream")

# tools / benches (registered so the lint gate covers every read; the
# tools read through this registry too)
register("SRJT_SERVE_WORKERS", "4", _int,
         "serve_bench worker count", "tools")
register("SRJT_QB_METRICS", "1", _on_unless_0_off,
         "query_bench metrics collection; `0`/`off` disables", "tools")
register("SRJT_QB_TRACE_DIR", None, _opt_str,
         "query_bench per-query Chrome-trace export directory", "tools")
register("SRJT_QB_RESUME", None, _str,
         "query_bench crash-resume marker (`1` = resume into the "
         "existing output file)", "tools")
register("SRJT_QB_TRIES", "0", _int,
         "query_bench crash-resume attempt counter", "tools")
register("SRJT_QB_STEADY", "1", _on_unless_0_off,
         "query_bench steady-state (compiled replay) sweep; `0`/`off` "
         "skips it", "tools")
register("SRJT_QB_STEADY_CAP", "10", _float,
         "query_bench per-query steady-sweep time budget (s)", "tools")
register("SRJT_QB_EXPLAIN", "0", _is_1,
         "query_bench records `plan.explain` output per query", "tools")
register("SRJT_QB_PROFILE", "0", _is_1,
         "query_bench attaches per-plan-node profiles (`--profile`) to "
         "QUERY_BENCH.json entries", "tools")
register("SRJT_QB_SQL", "0", _is_1,
         "query_bench compiles the TPC-DS mix from `models/tpcds_sql.py` "
         "SQL text (`--sql`) instead of prebuilt plan trees", "tools")
register("SRJT_BENCH_TRIES", "0", _int,
         "bench.py crash-resume attempt counter", "tools")
register("SRJT_BENCH_BUDGET_S", "1200", _float,
         "bench.py total wall-clock budget (s)", "tools")


# --- README table -----------------------------------------------------------

_SECTION_TITLES = {
    "exec": "Serving runtime (`exec/`)",
    "aot": "AOT artifact store (`exec/artifacts.py`)",
    "slo": "SLO watchdog (`exec/slo.py`)",
    "memory": "Memory arena (`memory/`)",
    "observability": "Observability (`utils/`)",
    "ops": "Joins (`ops/`)",
    "rowconv": "Row conversion (`rowconv/`)",
    "plan": "Plan optimizer (`plan/`)",
    "sql": "SQL front-end (`sql/`)",
    "parquet": "Parquet scan (`parquet/`)",
    "ml": "ML handoff (`ml/`)",
    "stream": "Streaming (`stream/`)",
    "tools": "Tools & benches",
    "general": "General",
}


def markdown_table() -> str:
    """The full knob catalog as grouped markdown tables — the generator
    behind the README's "Knob registry" section (`tools/srjt_lint.py
    --knob-table` refreshes it in place)."""
    out = []
    seen_sections = []
    for k in REGISTRY.values():
        if k.section not in seen_sections:
            seen_sections.append(k.section)
    for sec in seen_sections:
        out.append(f"**{_SECTION_TITLES.get(sec, sec)}**\n")
        out.append("| knob | default | meaning |")
        out.append("|---|---|---|")
        for k in REGISTRY.values():
            if k.section != sec:
                continue
            default = "unset" if k.default is None else f"`{k.default}`"
            out.append(f"| `{k.name}` | {default} | {k.doc} |")
        out.append("")
    return "\n".join(out)


if __name__ == "__main__":
    print(markdown_table())
