"""Host-sync accounting + weak result caches for the two-phase ops.

On the remote-TPU backend every device→host scalar sync costs ~65-110 ms,
so a multi-op query plan's wall time is often `sync_count × tunnel RTT`
rather than compute (round-2 evidence: Mortgage spent ~300 s producing 300
rows).  Two countermeasures live here:

* :func:`scalar` — the ONE funnel for intentional scalar syncs (group
  counts, string widths, char totals).  It counts them, so
  ``tools/query_bench.py`` can report a syncs-per-query figure and
  regressions are visible.
* weak per-array caches (:func:`memo_get` / :func:`memo_put`) keyed on
  device-array identity — dictionary encodes and string widths are pure
  functions of their column payloads, and analytics plans re-touch the
  same dimension columns — a repeated DIRECT touch of a base-table
  column skips its sync (post-gather copies are fresh arrays and
  legitimately re-resolve).  Entries drop with the arrays (weakrefs).
"""

from __future__ import annotations

import contextlib
import threading
# weakref handled by hostcache.WeakIdMemo
from typing import Any

from ..analysis import sanitize

# The sync counter is bumped from every exec-runtime worker thread; an
# unguarded `_count += 1` is a read-modify-write that loses updates under
# contention (found by srjt_lint conc-global-augassign; regression:
# tests/test_analysis.py::test_sync_count_thread_safe).
_count = 0
_count_mu = sanitize.tracked_lock("utils.syncs.count")

# --- capture/replay: compile a whole multi-op plan into ONE jit program ----
#
# Every dynamic size in the op library (join match totals, group counts,
# string widths, compaction counts) resolves through :func:`scalar`.  A
# *capture* run executes the plan eagerly and records the resolved sizes in
# order; a *replay* run pops them instead of syncing — so the same plan
# code traces under ``jax.jit`` with every shape static (the device value
# arriving at ``scalar`` is a tracer and is simply not synced).  Both modes
# disable the weak memos so capture and replay visit the SAME sequence of
# resolution sites (a memo hit in one mode but not the other would
# misalign the recorded sizes).  See ``models/compiled.py``.
#
# The mode and tape are THREAD-LOCAL: a jit trace executes its Python body
# on the calling thread, so a capture/replay on one exec-runtime worker
# must not flip the mode (or pop sizes from the tape) of a query running
# concurrently on another worker.

_tls = threading.local()    # .mode, .tape, .tape_pos, .seen


def mode() -> str:
    return getattr(_tls, "mode", "normal")


@contextlib.contextmanager
def capture(tape: list[int]):
    """Eager run recording every resolved size into ``tape`` (in order)."""
    if mode() != "normal":
        raise RuntimeError(f"cannot capture while in {mode()} mode")
    _tls.mode, _tls.tape = "capture", tape
    try:
        yield tape
    finally:
        _tls.mode, _tls.tape = "normal", []


@contextlib.contextmanager
def replay(tape: list[int], collect: list | None = None):
    """Traced run resolving sizes from ``tape`` instead of device syncs.

    ``collect``, when given, receives the value that ARRIVED at each
    :func:`scalar` call (a tracer under jit) in tape order — the raw
    material for a device-side size-vector program that can check a tape
    against refreshed data (``models/compiled.py`` staleness guard)."""
    if mode() != "normal":
        raise RuntimeError(f"cannot replay while in {mode()} mode")
    _tls.mode, _tls.tape, _tls.tape_pos, _tls.seen = \
        "replay", list(tape), 0, collect
    try:
        yield
        if _tls.tape_pos != len(_tls.tape):
            raise RuntimeError(
                f"replay consumed {_tls.tape_pos} of {len(_tls.tape)} "
                "recorded sizes — plan diverged from the capture run")
    finally:
        _tls.mode, _tls.tape, _tls.tape_pos, _tls.seen = \
            "normal", [], 0, None


def scalar(x) -> int:
    """int(x) with sync accounting — use for every intentional D2H scalar."""
    global _count
    if mode() == "replay":
        if _tls.tape_pos >= len(_tls.tape):
            raise RuntimeError(
                "replay tape exhausted — plan diverged from the capture run")
        if _tls.seen is not None:
            _tls.seen.append(x)
        v = _tls.tape[_tls.tape_pos]
        _tls.tape_pos += 1
        return v
    with _count_mu:
        _count += 1
    v = int(x)
    if mode() == "capture":
        _tls.tape.append(v)
    return v


def note_sync(k: int = 1) -> None:
    """Count ``k`` intentional D2H syncs that do not flow through
    :func:`scalar` (e.g. a stacked size-vector pull) — keeps the
    syncs-per-query funnel honest for non-scalar transfers."""
    global _count
    with _count_mu:
        _count += k


def sync_count() -> int:
    return _count


def reset_sync_count() -> int:
    global _count
    with _count_mu:
        old, _count = _count, 0
    return old


# --- weak memo keyed on device-array identity (shared mechanism with the
# host-mirror cache: utils.hostcache.WeakIdMemo) -----------------------------

from .hostcache import WeakIdMemo

_MEMOS: dict[str, WeakIdMemo] = {}


def memo_get(tag: str, arrays) -> Any:
    """Cached value for (tag, arrays) — None on miss or if any array died.
    Disabled under capture/replay (see the mode note above)."""
    if mode() != "normal":
        return None
    memo = _MEMOS.get(tag)
    return None if memo is None else memo.get(arrays)


def memo_put(tag: str, arrays, value) -> None:
    if mode() != "normal":
        return
    _MEMOS.setdefault(tag, WeakIdMemo()).put(arrays, value)
