"""Host-sync accounting + weak result caches for the two-phase ops.

On the remote-TPU backend every device→host scalar sync costs ~65-110 ms,
so a multi-op query plan's wall time is often `sync_count × tunnel RTT`
rather than compute (round-2 evidence: Mortgage spent ~300 s producing 300
rows).  Two countermeasures live here:

* :func:`scalar` — the ONE funnel for intentional scalar syncs (group
  counts, string widths, char totals).  It counts them, so
  ``tools/query_bench.py`` can report a syncs-per-query figure and
  regressions are visible.
* weak per-array caches (:func:`memo_get` / :func:`memo_put`) keyed on
  device-array identity — dictionary encodes and string widths are pure
  functions of their column payloads, and analytics plans re-touch the
  same dimension columns — a repeated DIRECT touch of a base-table
  column skips its sync (post-gather copies are fresh arrays and
  legitimately re-resolve).  Entries drop with the arrays (weakrefs).
"""

from __future__ import annotations

# weakref handled by hostcache.WeakIdMemo
from typing import Any

_count = 0


def scalar(x) -> int:
    """int(x) with sync accounting — use for every intentional D2H scalar."""
    global _count
    _count += 1
    return int(x)


def sync_count() -> int:
    return _count


def reset_sync_count() -> int:
    global _count
    old, _count = _count, 0
    return old


# --- weak memo keyed on device-array identity (shared mechanism with the
# host-mirror cache: utils.hostcache.WeakIdMemo) -----------------------------

from .hostcache import WeakIdMemo

_MEMOS: dict[str, WeakIdMemo] = {}


def memo_get(tag: str, arrays) -> Any:
    """Cached value for (tag, arrays) — None on miss or if any array died."""
    memo = _MEMOS.get(tag)
    return None if memo is None else memo.get(arrays)


def memo_put(tag: str, arrays, value) -> None:
    _MEMOS.setdefault(tag, WeakIdMemo()).put(arrays, value)
