"""Host mirrors of device metadata arrays (offsets), weakly cached.

On the remote-TPU backend every ``np.asarray(device_array)`` is a tunnel
round-trip that streams at single-digit MB/s (measured round 3: a 1M-row
offsets pull costs ~2.7 s).  The JCUDF variable-width paths need string
offsets host-side for batching and DMA geometry, but those offsets are
almost always *born* on the host (``strings_from_list``, Parquet decode,
``_slice_column`` arithmetic) — so producers seed this cache and consumers
get their host copy back for free instead of re-downloading it.

Entries are keyed by the device array's identity and dropped by a weakref
callback when the device array is garbage-collected.  The cache is an
optimization only — a miss falls back to the transfer.
"""

from __future__ import annotations

import weakref

import numpy as np

# id(device_array) -> (weakref with cleanup callback, host mirror)
_HOST: dict[int, tuple[weakref.ref, np.ndarray]] = {}


def seed(device_arr, host_arr: np.ndarray) -> None:
    """Record ``host_arr`` as the host mirror of ``device_arr``."""
    key = id(device_arr)
    try:
        r = weakref.ref(device_arr, lambda _, k=key: _HOST.pop(k, None))
    except TypeError:
        return  # not weakref-able — cache is best-effort
    _HOST[key] = (r, host_arr)


def host_i64(device_arr) -> np.ndarray:
    """Host int64 copy of a device int array, cached across calls."""
    entry = _HOST.get(id(device_arr))
    if entry is not None and entry[0]() is device_arr:
        h = entry[1]
        return h if h.dtype == np.int64 else h.astype(np.int64)
    out = np.asarray(device_arr).astype(np.int64)
    seed(device_arr, out)
    return out
