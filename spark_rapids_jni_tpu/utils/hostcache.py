"""Host mirrors of device metadata arrays (offsets), weakly cached.

On the remote-TPU backend every ``np.asarray(device_array)`` is a tunnel
round-trip that streams at single-digit MB/s (measured round 3: a 1M-row
offsets pull costs ~2.7 s).  The JCUDF variable-width paths need string
offsets host-side for batching and DMA geometry, but those offsets are
almost always *born* on the host (``strings_from_list``, Parquet decode,
``_slice_column`` arithmetic) — so producers seed this cache and consumers
get their host copy back for free instead of re-downloading it.

Entries are keyed by the device array's identity and dropped by a weakref
callback when the device array is garbage-collected.  The cache is an
optimization only — a miss falls back to the transfer.
"""

from __future__ import annotations

import weakref
from typing import Any

import numpy as np


class WeakIdMemo:
    """Weak cache keyed on the IDENTITY of one or more (device) arrays.

    The shared mechanism behind the host-mirror cache here and the
    dictionary/width memos in ``utils.syncs``: entries key on ``id()`` of
    the arrays, hold weakrefs with cleanup callbacks so values drop when
    any keyed array is garbage-collected, and an ``is``-identity check
    guards against id recycling.  Best-effort: non-weakref-able keys are
    simply not cached.
    """

    def __init__(self) -> None:
        self._d: dict[tuple, tuple] = {}

    def get(self, arrays) -> Any:
        entry = self._d.get(tuple(id(a) for a in arrays))
        if entry is None:
            return None
        refs, value = entry
        for r, a in zip(refs, arrays):
            if r() is not a:
                return None
        return value

    def put(self, arrays, value) -> None:
        key = tuple(id(a) for a in arrays)
        try:
            refs = tuple(
                weakref.ref(a, lambda _, k=key: self._d.pop(k, None))
                for a in arrays)
        except TypeError:
            return
        self._d[key] = (refs, value)


_HOST = WeakIdMemo()


def seed(device_arr, host_arr: np.ndarray) -> None:
    """Record ``host_arr`` as the host mirror of ``device_arr``."""
    _HOST.put((device_arr,), host_arr)


def peek(device_arr):
    """The cached host mirror, or None — never triggers a transfer.

    Misses deliberately under syncs capture/replay: a mirror hit would let
    the capture run skip a size-resolution site that the replay trace (on
    fresh tracers) cannot skip, misaligning the recorded tape."""
    from . import syncs
    if syncs.mode() != "normal":
        return None
    return _HOST.get((device_arr,))


def host_i64(device_arr) -> np.ndarray:
    """Host int64 copy of a device int array, cached across calls."""
    h = peek(device_arr)
    if h is not None:
        return h if h.dtype == np.int64 else h.astype(np.int64)
    out = np.asarray(device_arr).astype(np.int64)
    seed(device_arr, out)
    return out
