"""Host mirrors of device metadata arrays (offsets), weakly cached.

On the remote-TPU backend every ``np.asarray(device_array)`` is a tunnel
round-trip that streams at single-digit MB/s (measured round 3: a 1M-row
offsets pull costs ~2.7 s).  The JCUDF variable-width paths need string
offsets host-side for batching and DMA geometry, but those offsets are
almost always *born* on the host (``strings_from_list``, Parquet decode,
``_slice_column`` arithmetic) — so producers seed this cache and consumers
get their host copy back for free instead of re-downloading it.

Entries are keyed by the device array's identity and dropped by a weakref
callback when the device array is garbage-collected.  The cache is an
optimization only — a miss falls back to the transfer.  The host-mirror
instance is additionally byte-capped (``SRJT_HOSTCACHE_CAP``, default
256 MiB): past the cap the least-recently-used mirror is dropped and
``arena.hostcache.evictions`` counts it — long scans over many files no
longer grow host RSS without bound.
"""

from __future__ import annotations

import os
import weakref
from collections import OrderedDict
from typing import Any, Callable, Optional

import numpy as np

from ..analysis import sanitize


class WeakIdMemo:
    """Weak cache keyed on the IDENTITY of one or more (device) arrays.

    The shared mechanism behind the host-mirror cache here and the
    dictionary/width memos in ``utils.syncs``: entries key on ``id()`` of
    the arrays, hold weakrefs with cleanup callbacks so values drop when
    any keyed array is garbage-collected, and an ``is``-identity check
    guards against id recycling.  Best-effort: non-weakref-able keys are
    simply not cached.

    ``cap_bytes`` (a value or a zero-arg callable, None = unbounded)
    turns the memo into a byte-capped LRU over ``value.nbytes``;
    ``on_evict`` fires once per capacity eviction (not for weakref
    deaths).

    Thread-safety: map mutation is guarded by an RLock (reentrant — a
    weakref death callback can fire at a GC point inside ``put`` on the
    thread already holding it).  ``on_evict`` callbacks fire AFTER the
    lock is released so they may take other locks (metrics, arena)
    without ordering against this one.
    """

    def __init__(self, cap_bytes=None,
                 on_evict: Optional[Callable[[], None]] = None) -> None:
        self._d: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self._cap = cap_bytes
        self._on_evict = on_evict
        self._mu = sanitize.tracked_rlock("utils.hostcache.memo")

    def _cap_now(self) -> Optional[int]:
        c = self._cap
        return c() if callable(c) else c

    def _pop(self, key) -> None:
        with self._mu:
            entry = self._d.pop(key, None)
            if entry is not None:
                self._bytes -= entry[2]

    def get(self, arrays) -> Any:
        key = tuple(id(a) for a in arrays)
        with self._mu:
            entry = self._d.get(key)
            if entry is None:
                return None
            refs, value, _ = entry
            for r, a in zip(refs, arrays):
                if r() is not a:
                    return None
            self._d.move_to_end(key)
            return value

    def put(self, arrays, value) -> None:
        key = tuple(id(a) for a in arrays)
        try:
            refs = tuple(
                weakref.ref(a, lambda _, k=key: self._pop(k))
                for a in arrays)
        except TypeError:
            return
        nbytes = int(getattr(value, "nbytes", 0) or 0)
        evictions = 0
        with self._mu:
            self._pop(key)
            self._d[key] = (refs, value, nbytes)
            self._bytes += nbytes
            cap = self._cap_now()
            if cap is not None:
                while self._bytes > cap and len(self._d) > 1:
                    lru = next(iter(self._d))
                    if lru == key:
                        break
                    self._pop(lru)
                    evictions += 1
        if self._on_evict is not None:
            for _ in range(evictions):
                self._on_evict()

    def nbytes(self) -> int:
        return self._bytes


def _host_cap() -> Optional[int]:
    from ..memory.budget import parse_bytes
    from . import knobs
    return parse_bytes(knobs.get("SRJT_HOSTCACHE_CAP"))


def _count_host_eviction() -> None:
    from . import metrics
    if metrics.recording():
        metrics.count("arena.hostcache.evictions")


_HOST = WeakIdMemo(cap_bytes=_host_cap, on_evict=_count_host_eviction)


def seed(device_arr, host_arr: np.ndarray) -> None:
    """Record ``host_arr`` as the host mirror of ``device_arr``."""
    _HOST.put((device_arr,), host_arr)


def peek(device_arr):
    """The cached host mirror, or None — never triggers a transfer.

    Misses deliberately under syncs capture/replay: a mirror hit would let
    the capture run skip a size-resolution site that the replay trace (on
    fresh tracers) cannot skip, misaligning the recorded tape."""
    from . import syncs
    if syncs.mode() != "normal":
        return None
    return _HOST.get((device_arr,))


def host_i64(device_arr) -> np.ndarray:
    """Host int64 copy of a device int array, cached across calls."""
    h = peek(device_arr)
    if h is not None:
        return h if h.dtype == np.int64 else h.astype(np.int64)
    out = np.asarray(device_arr).astype(np.int64)
    seed(device_arr, out)
    return out
