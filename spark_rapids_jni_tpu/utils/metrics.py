"""Process-wide metrics registry + hierarchical span recorder.

The reference instruments every public entry with NVTX ranges and threads
an RMM logging level through the build (SURVEY §5.5); this module is the
query-level half of that story the TPU rebuild was missing: counters
(join-engine choice, build-index cache hits, tape lengths, pages decoded,
bytes shuffled), gauges (HBM live-byte watermarks), histograms (expansion
pair totals), and a per-query SPAN TREE that upgrades the flat
``tracing.func_range`` wall-time events into a parent/child stage
hierarchy exportable as Chrome-trace JSON (``chrome://tracing`` /
Perfetto-loadable) and as a structured summary dict.

Knobs
-----
  SPARK_RAPIDS_TPU_METRICS=0|1        (default off)
  SPARK_RAPIDS_TPU_METRICS_TRACE=<p>  default export path for
                                      :func:`export_chrome_trace`

Discipline
----------
* **Zero overhead when disabled.**  Every public entry is gated on ONE
  module-level bool; :func:`span` returns a shared ``nullcontext`` without
  allocating, counters return before touching any dict.
* **Record around dispatch, never inside compiled bodies.**  All recording
  is Python-side (eager orchestration, capture runs, dispatch wrappers).
  Sites that re-trace under ``jax.jit`` replay (``utils.syncs`` replay
  mode) are skipped automatically — a replay trace would otherwise
  double-count the capture run's events and measure trace time instead of
  run time.  The one deliberate exception is
  ``count(..., in_trace=True)`` (e.g. ``compiled.recompile``), which
  records trace-time occurrences on purpose.
* No device syncs: values passed in must already be host ints/floats
  (the op library's sizes all flow through ``syncs.scalar`` anyway).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import re
import threading
import time

from ..analysis import sanitize
from . import knobs
from typing import Any, Optional

_enabled: bool = os.environ.get(
    "SPARK_RAPIDS_TPU_METRICS", "0").lower() not in ("0", "off", "false", "")

_lock = sanitize.tracked_lock("utils.metrics")
_counters: dict[str, float] = {}
_gauges: dict[str, float] = {}
_hists: dict[str, dict] = {}        # name -> {count,total,min,max,buckets}
# bounded (ts, value) sample tails per histogram, feeding the
# rolling-window percentile path (the SLO watchdog's quantiles); the
# log2 buckets above stay the process-lifetime story
_WINDOW_N = max(knobs.get("SRJT_METRICS_WINDOW_N"), 16)
_samples: dict[str, "collections.deque[tuple[float, float]]"] = {}

_EPOCH = time.perf_counter()        # trace time base (ts exported rel. us)

_tls = threading.local()            # per-thread open-span stack
_roots: list["Span"] = []           # completed root spans (all threads)

# compile-cost ledger: plan fingerprint → summed cost fields (capture_ms,
# trace_ms, traces, first_dispatch_ms, runs, cache_hits, ...) — the
# per-plan attribution of where compilation wall time went
# (``models/compiled.py`` and ``exec/plan_cache.py`` feed it)
_ledger: dict[str, dict[str, float]] = {}

# installed by ``plan/profile.py`` when that module loads; ops-layer
# sites report into the active node profile through :func:`profile_op`
# without importing plan/ (no cycle, no cost when profiling never loads)
_profile_op_hook = None


def enabled() -> bool:
    return _enabled


def set_enabled(on: Optional[bool] = None) -> None:
    """Toggle metrics at runtime; ``None`` re-reads the env knob."""
    global _enabled
    if on is None:
        _enabled = os.environ.get(
            "SPARK_RAPIDS_TPU_METRICS",
            "0").lower() not in ("0", "off", "false", "")
    else:
        _enabled = bool(on)


def recording() -> bool:
    """True when events should be recorded NOW: metrics on, and not inside
    a ``syncs.replay`` re-trace (which re-runs the already-recorded plan
    Python under ``jax.jit``)."""
    if not _enabled:
        return False
    from . import syncs
    return syncs.mode() != "replay"


def reset() -> None:
    """Drop all counters, gauges, histograms, completed spans, and the
    compile-cost ledger."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _samples.clear()
        _roots.clear()
        _ledger.clear()


def profile_op(name: str, **fields) -> None:
    """Report one op-level event (host-visible fields only — already
    resolved ints/strings, never device values) into the active plan-node
    profile.  A no-op until ``plan/profile.py`` is loaded AND a profile is
    active; ops-layer sites call this instead of importing plan/."""
    hook = _profile_op_hook
    if hook is not None:
        hook(name, **fields)


_profile_stage_hook = None      # plan/profile.stage once that module loads


def profile_stage(name: str, **fields):
    """Context manager opening a synthetic stage record (ml/ feature pack,
    train, predict) under the active plan-node profile — the non-plan-node
    twin of :func:`profile_op`, same no-import-cycle indirection.  Yields
    the open record (or None when no profile is active) so the stage can
    set output facts like ``out_rows``."""
    hook = _profile_stage_hook
    if hook is None:
        return contextlib.nullcontext()
    return hook(name, **fields)


# --- compile-cost ledger -----------------------------------------------------


def ledger_add(plan: str, *, in_trace: bool = False, **fields) -> None:
    """Accumulate numeric cost ``fields`` (ms, counts) under ``plan`` —
    a plan fingerprint or query name.  Same gating discipline as
    :func:`count`: no-op when disabled; ``in_trace=True`` records even
    under a replay trace (trace time is MEASURED at trace time)."""
    if not _enabled:
        return
    if not in_trace and not recording():
        return
    with _lock:
        e = _ledger.setdefault(plan, {})
        for k, v in fields.items():
            e[k] = e.get(k, 0) + v


def ledger_snapshot() -> dict[str, dict[str, float]]:
    """The compile-cost ledger as plain dicts (deep-copied):
    plan → {capture_ms, trace_ms, traces, first_dispatch_ms, runs,
    cache_hits, ...}.  ``traces`` counts jit (re)traces of the plan body;
    ``traces - 1`` of them are recompiles."""
    with _lock:
        return {k: dict(v) for k, v in _ledger.items()}


# --- counters / gauges / histograms ----------------------------------------


def count(name: str, value: float = 1, *, in_trace: bool = False) -> None:
    """Add ``value`` to counter ``name`` (no-op when disabled or replaying;
    ``in_trace=True`` records even under a replay trace — for events whose
    occurrence IS the trace, e.g. recompiles)."""
    if not _enabled:
        return
    if not in_trace and not recording():
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


def counter_value(name: str, default: float = 0) -> float:
    """Read counter ``name`` (``default`` when never incremented)."""
    with _lock:
        return _counters.get(name, default)


def gauge(name: str, value: float) -> None:
    """Set gauge ``name`` to ``value``."""
    if not recording():
        return
    with _lock:
        _gauges[name] = value


def gauge_max(name: str, value: float) -> None:
    """High-water gauge: keep the max of all samples (HBM watermarks)."""
    if not recording():
        return
    with _lock:
        if value > _gauges.get(name, float("-inf")):
            _gauges[name] = value


def observe(name: str, value: float) -> None:
    """Record one histogram observation (count/total/min/max + log2
    buckets — enough for skew questions without storing samples)."""
    if not recording():
        return
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = {"count": 0, "total": 0, "min": value,
                                "max": value, "buckets": {}}
        h["count"] += 1
        h["total"] += value
        h["min"] = min(h["min"], value)
        h["max"] = max(h["max"], value)
        b = f"<=2^{max(int(value), 0).bit_length()}"
        h["buckets"][b] = h["buckets"].get(b, 0) + 1
        s = _samples.get(name)
        if s is None:
            s = _samples[name] = collections.deque(maxlen=_WINDOW_N)
        s.append((time.monotonic(), value))


def percentile(name: str, q: float,
               window_s: Optional[float] = None) -> Optional[float]:
    """The ``q``-th percentile (0..100) of histogram ``name``.

    ``window_s=None`` (default) estimates over the PROCESS LIFETIME from
    the log2 buckets: the answer is the upper edge of the bucket holding
    the quantile, clamped to the observed min/max — coarse (≤2× off) but
    storage-free; serving latency tails need the magnitude, not the
    digit.

    ``window_s`` computes an EXACT quantile (nearest-rank) over the
    retained sample tail restricted to the last ``window_s`` seconds —
    the rolling view the SLO watchdog alarms on.  The tail is bounded
    (``SRJT_METRICS_WINDOW_N``, default 1024 newest observations), so a
    long window over a hot histogram sees the newest N, never unbounded
    storage.  Returns None when no observation falls in the window
    (including the empty-histogram case); a single in-window sample is
    its own percentile at every q."""
    q = min(max(q, 0.0), 100.0)
    if window_s is not None:
        cutoff = time.monotonic() - max(float(window_s), 0.0)
        with _lock:
            s = _samples.get(name)
            vals = [v for ts, v in s if ts >= cutoff] if s else []
        if not vals:
            return None
        vals.sort()
        rank = max(int(-(-len(vals) * q // 100)), 1)   # ceil, 1-based
        return float(vals[min(rank, len(vals)) - 1])
    with _lock:
        h = _hists.get(name)
        if h is None or not h["count"]:
            return None
        lo, hi, total = h["min"], h["max"], h["count"]
        edges = sorted((int(k.rsplit("^", 1)[1]), c)
                       for k, c in h["buckets"].items())
    target = total * q / 100.0
    cum = 0
    for exp, c in edges:
        cum += c
        if cum >= target:
            return float(min(max(float(1 << exp), lo), hi))
    return float(hi)


# --- span recorder ----------------------------------------------------------


class Span:
    """One timed range; completed children hang off ``children``."""

    __slots__ = ("name", "attrs", "t0", "dur", "tid", "children")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.t0 = 0.0           # seconds since _EPOCH, set on __enter__
        self.dur = 0.0          # seconds
        self.tid = 0
        self.children: list[Span] = []

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        self.tid = threading.get_ident()
        self.t0 = time.perf_counter() - _EPOCH
        return self

    def __exit__(self, *exc) -> None:
        self.dur = (time.perf_counter() - _EPOCH) - self.t0
        stack = _tls.stack
        stack.pop()
        if stack:
            stack[-1].children.append(self)
        else:
            with _lock:
                _roots.append(self)

    def as_dict(self) -> dict:
        d = {"name": self.name, "start_ms": round(self.t0 * 1e3, 3),
             "dur_ms": round(self.dur * 1e3, 3)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.as_dict() for c in self.children]
        return d


_NOOP = contextlib.nullcontext()


def span(name: str, **attrs):
    """Context manager recording a span under the current thread's open
    span (or as a new root).  Returns a shared no-op context when disabled
    or under a replay trace — zero allocation on the hot path."""
    if not recording():
        return _NOOP
    return Span(name, attrs)


def current_span() -> Optional[Span]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def annotate(**attrs) -> None:
    """Attach attributes to the innermost open span (no-op without one)."""
    if not recording():
        return
    sp = current_span()
    if sp is not None:
        sp.attrs.update(attrs)


@contextlib.contextmanager
def query_span(name: str, **attrs):
    """Root span for one query execution, with HBM watermark samples
    taken before and after (around dispatch — never inside it)."""
    if not recording():
        yield None
        return
    pre = sample_hbm("pre")
    with span(f"query:{name}", **attrs) as sp:
        yield sp
    post = sample_hbm("post")
    if pre is not None and post is not None:
        sp.annotate(hbm_pre_bytes=pre, hbm_post_bytes=post)


# --- HBM accounting ---------------------------------------------------------


def sample_hbm(tag: str = "sample") -> Optional[int]:
    """Sample live device memory: sum of ``jax.live_arrays()`` byte sizes
    plus per-device allocator stats where the backend exposes them.  On
    backends with no ``memory_stats()`` (CPU, some PJRT builds) the
    per-device gauges fall back to an estimate from ``jax.live_arrays()``
    grouped by placement (sharded arrays split evenly across their
    devices).  Updates ``hbm.live_bytes`` and the ``hbm.live_bytes.peak``
    high-water gauge; returns the live-byte total (None when disabled)."""
    if not recording():
        return None
    import jax
    arrays = []
    try:
        arrays = list(jax.live_arrays())
    except Exception:
        pass
    live = sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays)
    gauge("hbm.live_bytes", live)
    gauge_max("hbm.live_bytes.peak", live)
    any_stats = False
    try:
        for i, d in enumerate(jax.local_devices()):
            stats = getattr(d, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if not stats:
                continue
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                any_stats = True
                gauge(f"hbm.device{i}.bytes_in_use", int(in_use))
                gauge_max(f"hbm.device{i}.peak_bytes_in_use",
                          int(stats.get("peak_bytes_in_use", in_use)))
    except Exception:
        pass
    if not any_stats:
        # allocator-stats fallback: estimate per-device occupancy from the
        # live-array census so the gauges exist on every backend
        try:
            devs = jax.local_devices()
            index = {d: i for i, d in enumerate(devs)}
            per = [0] * len(devs)
            for a in arrays:
                try:
                    placement = list(a.devices())
                except Exception:
                    continue
                n = int(getattr(a, "nbytes", 0) or 0)
                if not placement or not n:
                    continue
                share = n // len(placement)   # sharded: even split
                for d in placement:
                    i = index.get(d)
                    if i is not None:
                        per[i] += share
            for i, v in enumerate(per):
                gauge(f"hbm.device{i}.bytes_in_use", v)
                gauge_max(f"hbm.device{i}.peak_bytes_in_use", v)
        except Exception:
            pass
    return live


# --- export -----------------------------------------------------------------


def snapshot() -> dict:
    """Counters/gauges/histograms/ledger as plain dicts (deep-copied)."""
    with _lock:
        return {"counters": dict(_counters), "gauges": dict(_gauges),
                "histograms": {k: {**v, "buckets": dict(v["buckets"])}
                               for k, v in _hists.items()},
                "ledger": {k: dict(v) for k, v in _ledger.items()}}


def span_roots() -> list[dict]:
    """Completed root span trees (dict form), in completion order."""
    with _lock:
        return [s.as_dict() for s in _roots]


def _walk(spans, fn):
    for s in spans:
        fn(s)
        _walk(s.children, fn)


def stage_breakdown() -> dict[str, dict]:
    """Aggregate all completed spans by name: call count, total/max ms —
    the per-query stage table ``tools/query_bench.py`` emits."""
    agg: dict[str, dict] = {}

    def add(s: Span):
        e = agg.setdefault(s.name, {"count": 0, "total_ms": 0.0,
                                    "max_ms": 0.0})
        e["count"] += 1
        e["total_ms"] += s.dur * 1e3
        e["max_ms"] = max(e["max_ms"], s.dur * 1e3)

    with _lock:
        _walk(list(_roots), add)
    for e in agg.values():
        e["total_ms"] = round(e["total_ms"], 3)
        e["max_ms"] = round(e["max_ms"], 3)
    return agg


def summary() -> dict:
    """One structured dict: counters, gauges, histograms, span aggregate."""
    return {**snapshot(), "spans": stage_breakdown()}


def chrome_trace() -> dict:
    """The recorded spans + counters in Chrome-trace (JSON object) format.

    Spans become complete ("ph": "X") events with microsecond ts/dur;
    counters/gauges ride along both as trailing counter events and under
    the ``srjtCounters``/``srjtGauges``/``srjtHistograms`` keys (the
    object format ignores unknown top-level keys, so Perfetto and
    ``chrome://tracing`` both load it and ``tools/trace_report.py`` gets
    the registry without re-aggregating events)."""
    pid = os.getpid()
    events: list[dict] = []
    end_us = 0.0

    def emit(s: Span):
        nonlocal end_us
        ev = {"name": s.name, "cat": "srjt", "ph": "X", "pid": pid,
              "tid": s.tid, "ts": round(s.t0 * 1e6, 3),
              "dur": round(s.dur * 1e6, 3)}
        if s.attrs:
            ev["args"] = {k: v for k, v in s.attrs.items()}
        events.append(ev)
        end_us = max(end_us, (s.t0 + s.dur) * 1e6)

    with _lock:
        _walk(list(_roots), emit)
        counters = dict(_counters)
        gauges = dict(_gauges)
        hists = {k: {**v, "buckets": dict(v["buckets"])}
                 for k, v in _hists.items()}
        ledger = {k: dict(v) for k, v in _ledger.items()}
    for k, v in sorted(counters.items()):
        events.append({"name": k, "cat": "srjt", "ph": "C", "pid": pid,
                       "ts": round(end_us, 3), "args": {"value": v}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "srjtCounters": counters, "srjtGauges": gauges,
            "srjtHistograms": hists, "srjtLedger": ledger}


def export_chrome_trace(path: Optional[str] = None) -> str:
    """Write :func:`chrome_trace` as JSON; returns the path written.
    Default path: ``SPARK_RAPIDS_TPU_METRICS_TRACE`` or
    ``srjt_trace.json``."""
    path = path or os.environ.get("SPARK_RAPIDS_TPU_METRICS_TRACE",
                                  "srjt_trace.json")
    with open(path, "w") as f:
        json.dump(chrome_trace(), f)
    return path


# --- Prometheus export ------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """``exec.queue_wait_ms`` → ``srjt_exec_queue_wait_ms`` (the
    text-format metric-name grammar admits ``[a-zA-Z_:][a-zA-Z0-9_:]*``)."""
    n = "srjt_" + _PROM_BAD.sub("_", name)
    if not re.match(r"[a-zA-Z_:]", n[0]):
        n = "_" + n
    return n


def _prom_num(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 2 ** 53 else repr(f)


def _prom_label(v: str) -> str:
    """Escape a label VALUE for the text exposition grammar (the CI lint
    admits ``[^"]*`` between the quotes — strip anything that would
    close or continue the quoted string)."""
    return str(v).replace("\\", "_").replace('"', "_").replace("\n", "_")


def to_prometheus() -> str:
    """The registry in Prometheus text exposition format (version 0.0.4).

    Counters and gauges export directly; every histogram exports as a
    native Prometheus histogram — cumulative ``_bucket{le="..."}`` series
    built from the log2 buckets, plus ``_sum`` and ``_count`` — so a
    scrape of the serving runtime yields rate()-able latency and
    admission series without any sidecar.  The output is linted against
    the grammar in CI (``ci/exec_smoke.sh``)."""
    with _lock:
        counters = dict(_counters)
        gauges = dict(_gauges)
        hists = {k: {**v, "buckets": dict(v["buckets"])}
                 for k, v in _hists.items()}
        ledger = {k: dict(v) for k, v in _ledger.items()}
    lines: list[str] = []
    for name, v in sorted(counters.items()):
        p = _prom_name(name)
        lines.append(f"# TYPE {p} counter")
        lines.append(f"{p} {_prom_num(v)}")
    for name, v in sorted(gauges.items()):
        p = _prom_name(name)
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {_prom_num(v)}")
    for name, h in sorted(hists.items()):
        p = _prom_name(name)
        lines.append(f"# TYPE {p} histogram")
        edges = sorted((int(k.rsplit("^", 1)[1]), c)
                       for k, c in h["buckets"].items())
        cum = 0
        for exp, c in edges:
            cum += c
            lines.append(f'{p}_bucket{{le="{float(1 << exp)!r}"}} {cum}')
        lines.append(f'{p}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{p}_sum {_prom_num(h['total'])}")
        lines.append(f"{p}_count {h['count']}")
    if ledger:
        # compile-cost attribution: one labeled series per (plan, field)
        # — `rate(srjt_compile_ledger{kind="trace_ms"}[5m])` answers "who
        # is recompiling" straight off a scrape
        p = "srjt_compile_ledger"
        lines.append(f"# TYPE {p} counter")
        for plan, e in sorted(ledger.items()):
            for k, v in sorted(e.items()):
                lines.append(f'{p}{{plan="{_prom_label(plan)}",'
                             f'kind="{_prom_label(k)}"}} {_prom_num(v)}')
    return "\n".join(lines) + ("\n" if lines else "")


_http_server = None
_http_lock = sanitize.tracked_lock("utils.metrics.http")


def start_http_server(port: Optional[int] = None):
    """Serve :func:`to_prometheus` on ``http://0.0.0.0:<port>/metrics``
    from a daemon thread (the ops scrape surface; ``SRJT_METRICS_PORT``).
    Idempotent — one server per process; returns it (``.server_port``
    carries the bound port, useful with ``port=0`` in tests), or None
    when no port is configured."""
    global _http_server
    if port is None:
        port = knobs.get("SRJT_METRICS_PORT")
        if not port:
            return None
    port = int(port)
    with _http_lock:
        if _http_server is not None:
            return _http_server
        import http.server

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):            # noqa: N802 (stdlib API name)
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = to_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):    # scrapes must not spam stderr
                pass

        _http_server = http.server.ThreadingHTTPServer(
            ("0.0.0.0", port), _Handler)
        threading.Thread(target=_http_server.serve_forever,
                         name="srjt-metrics-http", daemon=True).start()
        return _http_server


def stop_http_server() -> None:
    """Shut the scrape endpoint down (tests)."""
    global _http_server
    with _http_lock:
        if _http_server is not None:
            _http_server.shutdown()
            _http_server.server_close()
            _http_server = None
