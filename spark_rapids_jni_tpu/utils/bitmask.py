"""Validity bitmask utilities.

The reference stores validity as an Arrow-style little-endian bitmask and
transposes it between column bitmasks and per-row validity bytes with warp
ballot tricks (``row_conversion.cu:710-810`` col→row, ``:1010-1116`` row→col;
bit utilities ``word_index``/``bit_is_set`` come from libcudf,
``row_conversion.cu:416,512``).

On TPU there are no warps or ballots; the idiomatic equivalent keeps validity
as a boolean vector on-device (one lane per row — VPU-friendly, fuses into any
elementwise op) and packs/unpacks to the little-endian bitmask with a reshape +
weighted-sum, which XLA lowers to a handful of vector ops.  The
``__ballot_sync`` bit-transpose trick (``row_conversion.cu:765-776``) becomes
``pack_bool_matrix``: an (8,)-weighted reduction along the column axis.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_BIT_WEIGHTS = np.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=np.uint8)


def pack_bits(valid: jnp.ndarray) -> jnp.ndarray:
    """Pack a boolean vector [n] into a little-endian bitmask of uint8 [⌈n/8⌉].

    Bit ``i`` of byte ``j`` is element ``j*8 + i`` (Arrow/cudf bit order).
    """
    n = valid.shape[0]
    nbytes = -(-n // 8)
    padded = jnp.zeros((nbytes * 8,), dtype=jnp.uint8).at[:n].set(
        valid.astype(jnp.uint8))
    return (padded.reshape(nbytes, 8) * jnp.asarray(_BIT_WEIGHTS)).sum(
        axis=1, dtype=jnp.uint8)


def unpack_bits(mask: jnp.ndarray, n: int) -> jnp.ndarray:
    """Unpack a little-endian uint8 bitmask into a boolean vector [n]."""
    bits = (mask[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1
    return bits.reshape(-1)[:n].astype(jnp.bool_)


def pack_bool_matrix(valid: jnp.ndarray) -> jnp.ndarray:
    """Pack a bool matrix [rows, cols] into row-validity bytes [rows, ⌈cols/8⌉].

    This is the TPU replacement for the reference's per-warp ballot transpose
    (``row_conversion.cu:748-778``): each output byte holds the validity bits
    of 8 consecutive columns of one row, bit i = column ``byte*8 + i``
    (matching the JCUDF validity byte layout, ``RowConversion.java:56-58``).
    """
    rows, cols = valid.shape
    nbytes = -(-cols // 8)
    padded = jnp.zeros((rows, nbytes * 8), dtype=jnp.uint8).at[:, :cols].set(
        valid.astype(jnp.uint8))
    return (padded.reshape(rows, nbytes, 8) * jnp.asarray(_BIT_WEIGHTS)).sum(
        axis=2, dtype=jnp.uint8)


def unpack_bool_matrix(row_bytes: jnp.ndarray, cols: int) -> jnp.ndarray:
    """Inverse of :func:`pack_bool_matrix`: [rows, ⌈cols/8⌉] → bool [rows, cols]."""
    rows, nbytes = row_bytes.shape
    bits = (row_bytes[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)[None, None, :]) & 1
    return bits.reshape(rows, nbytes * 8)[:, :cols].astype(jnp.bool_)


# numpy twins (host-side oracle / test reference)

def pack_bits_np(valid: np.ndarray) -> np.ndarray:
    return np.packbits(np.asarray(valid, dtype=np.uint8), bitorder="little")


def unpack_bits_np(mask: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(np.asarray(mask, dtype=np.uint8),
                         count=n, bitorder="little").astype(bool)
