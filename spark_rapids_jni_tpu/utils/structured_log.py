"""Structured logging knob (SURVEY §5.5 observability parity).

The reference threads an RMM logging level from Maven into CMake
(``pom.xml:81``, ``CMakeLists.txt:61-69``) and uses runtime-configurable
spdlog in the fault injector (``faultinj.cu:379-386``).  The TPU-native
equivalent is one env knob:

  SPARK_RAPIDS_TPU_LOG=off|text|json     (default off)
  SPARK_RAPIDS_TPU_LOG_FILE=<path>       (default stderr)

When enabled, every ``@traced`` public entry emits one event record with
wall-time duration — ``text`` for humans, ``json`` (one object per line)
for log pipelines.  Re-read per process start; ``configure()`` overrides
at runtime (the injector-style hot knob).
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Optional

from ..analysis import sanitize

_lock = sanitize.tracked_lock("utils.structured_log")
_mode: str = os.environ.get("SPARK_RAPIDS_TPU_LOG", "off").lower()
_path: Optional[str] = os.environ.get("SPARK_RAPIDS_TPU_LOG_FILE")
_stream = None
_tls = threading.local()               # per-thread bound context fields


def bind(**fields) -> None:
    """Bind fields onto every subsequent :func:`event` from THIS thread
    (until :func:`unbind`): the serving workers bind ``request_id`` so a
    request's whole log trail greps by one key."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = _tls.ctx = {}
    ctx.update(fields)


def unbind(*names) -> None:
    """Drop bound fields by name; no names drops everything."""
    ctx = getattr(_tls, "ctx", None)
    if not ctx:
        return
    if not names:
        ctx.clear()
    for n in names:
        ctx.pop(n, None)


@contextlib.contextmanager
def bound(**fields):
    """Context-managed :func:`bind`: fields apply inside, restore after."""
    ctx = getattr(_tls, "ctx", None)
    saved = dict(ctx) if ctx else {}
    bind(**fields)
    try:
        yield
    finally:
        if getattr(_tls, "ctx", None) is not None:
            _tls.ctx.clear()
            _tls.ctx.update(saved)


def _close_stream_locked() -> None:
    """Close + reset the lazily-opened stream.  Caller holds ``_lock`` —
    every writer goes through :func:`event` (which holds the lock across
    the ``_out()`` lookup AND the write), so no thread can be mid-write on
    the stream being closed."""
    global _stream
    if _stream is not None:
        try:
            _stream.close()
        except ValueError:        # already closed externally
            pass
        _stream = None


def configure(mode: str | None = None, path: str | None = None) -> None:
    """Override the env configuration at runtime ('off'|'text'|'json').

    Lock-consistent with :func:`event`: a path change or a flip to
    ``off`` closes the open stream under the same lock writers hold, so
    concurrent ``event()`` calls either finish on the old stream or open
    the new one — never write to a closed file."""
    global _mode, _path
    with _lock:
        if mode is not None:
            _mode = mode.lower()
            if _mode == "off":
                _close_stream_locked()
        if path is not None:
            _path = path
            _close_stream_locked()


def enabled() -> bool:
    return _mode in ("text", "json")


def _out():
    """The output stream.  Caller must hold ``_lock``; reopens if a
    ``configure`` closed the stream since the last write."""
    global _stream
    if _path is None:
        return sys.stderr
    if _stream is None or _stream.closed:
        _stream = open(_path, "a", buffering=1)
    return _stream


def event(name: str, duration_s: float | None = None, **fields) -> None:
    """Emit one structured event (no-op when the knob is off).  Fields
    bound on this thread via :func:`bind` merge in under the call's own
    fields (explicit wins)."""
    if not enabled():
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx:
        fields = {**ctx, **fields}
    with _lock:
        if not enabled():         # re-check: racing configure(mode='off')
            return
        out = _out()
        if _mode == "json":
            rec = {"ts": time.time(), "event": name}
            if duration_s is not None:
                rec["duration_ms"] = round(duration_s * 1e3, 3)
            rec.update(fields)
            out.write(json.dumps(rec) + "\n")
        else:
            extra = " ".join(f"{k}={v}" for k, v in fields.items())
            dur = (f" {duration_s * 1e3:.3f}ms"
                   if duration_s is not None else "")
            out.write(f"[srjt] {name}{dur}{' ' + extra if extra else ''}\n")
        out.flush()
