"""FLOAT64 bit-pattern <-> value conversion.

Device invariant (see ``column.Column``): FLOAT64 columns carry their IEEE754
*bit pattern* as uint32 [n, 2] (little-endian lo, hi half-words), never a
float64 array.  Rationale, measured on the target chip (tools/profile runs,
round 3):

* ``lax.bitcast_convert_type`` on float64 fails to compile on XLA:TPU in any
  direction (f64->u32, f64->i64, i64->f64) — the backend emulates f64 and
  exposes no bit-level view of it;
* the emulated f64 arithmetic is NOT bit-faithful IEEE754: denormals flush
  to zero and last-bit rounding differs from the host.

With bits as the storage, the JCUDF transcode (``rowconv/convert.py``) and
Parquet DOUBLE decode move bytes exactly on every backend and never touch
f64 arithmetic — this replaces round 2's per-call host round-trip
(``convert._stage``/``_unstage``, VERDICT r2 weak #2).  Compute ops convert
at their boundaries via :func:`from_bits` / :func:`to_bits`:

* on backends with native f64 bitcast (CPU — where the test suite runs) the
  conversion is a bitcast: exact, including NaN payloads and denormals;
* on TPU it is *arithmetic* bit assembly/extraction built from operations
  the emulation performs exactly where it can (power-of-two scaling,
  compares).  The emulation itself carries only ~47-49 effective mantissa
  bits AND an f32-like exponent window (measured on the target chip,
  round 3: 2^126 survives, 2^127 -> inf; gradual underflow below ~2^-126),
  so decoded values land within a few ulps of the IEEE value inside that
  window — the closest the hardware can represent — are exact for powers
  of two, +-0 and +-inf, and degrade to +-inf / 0 outside it; NaNs
  canonicalize to 0x7FF8_0000_0000_0000.  This is the same precision every
  f64 *computation* on this backend already has (a plain ``jnp.sum`` of
  1e300 is inf on this chip); anything needing bit-exactness (transcode,
  shuffle, Parquet) moves the stored bits untouched and never calls these
  functions.

Reference parity: the reference gets f64 bit access for free in CUDA
(``row_conversion.cu`` copies raw bytes); this module is the TPU-native
equivalent capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Descending powers of two covering |exponent| <= 1023 for the binary
# decompositions below.
_EXP_STEPS = (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)


def backend_has_f64_bitcast() -> bool:
    """True where ``bitcast_convert_type`` supports f64 (CPU/GPU, not TPU)."""
    return jax.default_backend() != "tpu"


def np_to_bits(arr: np.ndarray) -> np.ndarray:
    """Host-side exact conversion: f64 [n] -> u32 [n, 2] (lo, hi)."""
    a = np.ascontiguousarray(arr, dtype=np.float64)
    return a.view(np.uint32).reshape(a.shape[0], 2)


def np_from_bits(bits: np.ndarray) -> np.ndarray:
    """Host-side exact conversion: u32 [n, 2] -> f64 [n]."""
    b = np.ascontiguousarray(bits, dtype=np.uint32)
    return b.view(np.float64).reshape(b.shape[0])


def is_nan_bits(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """NaN test straight from the bit pattern (max exponent, mantissa != 0)."""
    return (((hi & jnp.uint32(0x7FF00000)) == jnp.uint32(0x7FF00000))
            & (((hi & jnp.uint32(0xFFFFF)) != 0) | (lo != 0)))


def group_key_lanes(bits: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(lo, hi) u32 lanes for EQUALITY comparison with Spark grouping
    semantics: -0.0 equals 0.0 and all NaN payloads are one value — both
    canonicalized so plain bit equality gives the right answer."""
    lo, hi = bits[:, 0], bits[:, 1]
    nan = is_nan_bits(lo, hi)
    neg_zero = (hi == jnp.uint32(0x80000000)) & (lo == 0)
    hi = jnp.where(nan, jnp.uint32(0x7FF80000),
                   jnp.where(neg_zero, jnp.uint32(0), hi))
    lo = jnp.where(nan | neg_zero, jnp.uint32(0), lo)
    return lo, hi


def monotone_lanes(lo: jnp.ndarray, hi: jnp.ndarray):
    """The classic order-preserving bits→uint map on (lo, hi) u32 lanes:
    negatives inverted, positives sign-flipped.  Callers decide NaN
    handling BEFORE this map.  Single source for sort keys, join keys and
    any other ordered-comparison consumer (they must stay in lockstep)."""
    neg = (hi >> jnp.uint32(31)) != 0
    hi_k = jnp.where(neg, ~hi, hi ^ jnp.uint32(0x80000000))
    lo_k = jnp.where(neg, ~lo, lo)
    return lo_k, hi_k


def ordered_key_u64(bits: jnp.ndarray) -> jnp.ndarray:
    """One u64 key per row that is exact for BOTH Spark equality
    (-0.0 == 0.0, all NaNs one value — ``group_key_lanes``) and numeric
    order (monotone map) — the join-key form."""
    lo, hi = group_key_lanes(bits)
    lo_k, hi_k = monotone_lanes(lo, hi)
    return (hi_k.astype(jnp.uint64) << 32) | lo_k.astype(jnp.uint64)


def equality_key_u64(bits: jnp.ndarray) -> jnp.ndarray:
    """Canonicalized u64 bit key: equality-only form (membership tests)."""
    lo, hi = group_key_lanes(bits)
    return (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)


def np_equality_key_u64(arr: np.ndarray) -> np.ndarray:
    """Host-side exact probe keys under the same canonicalization as
    :func:`equality_key_u64` (-0.0 → +0.0, all NaNs → one quiet NaN)."""
    a = np.ascontiguousarray(arr, dtype=np.float64)
    bits = a.view(np.uint64)
    bits = np.where(np.isnan(a), np.uint64(0x7FF8000000000000), bits)
    bits = np.where(bits == np.uint64(1) << 63, np.uint64(0), bits)
    return bits


def _pow2(h: jnp.ndarray) -> jnp.ndarray:
    """Exact 2.0**h for int32 h in [-537, 537] (power-of-two products are
    exact scalings in the TPU's f64 emulation)."""
    ah = jnp.abs(h)
    p = jnp.ones(h.shape, jnp.float64)
    for k in _EXP_STEPS:
        p = jnp.where((ah & k) != 0, p * np.float64(2.0 ** k), p)
    return jnp.where(h < 0, 1.0 / p, p)


def from_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """Device u32 [n, 2] bit pattern -> f64 [n] values."""
    if backend_has_f64_bitcast():
        return jax.lax.bitcast_convert_type(bits, jnp.float64)
    lo = bits[:, 0].astype(jnp.int64)
    hi = bits[:, 1].astype(jnp.int64)
    sign_neg = (hi >> 31) != 0
    e = ((hi >> 20) & 0x7FF).astype(jnp.int32)
    mant = ((hi & 0xFFFFF) << 32) | lo
    mant_f = mant.astype(jnp.float64)                     # < 2^52: exact
    frac = jnp.where(e > 0, mant_f + np.float64(2.0 ** 52), mant_f)
    ee = jnp.where(e > 0, e, 1) - 1075                    # [-1074, 971]
    h1 = ee // 2
    val = frac * _pow2(h1) * _pow2(ee - h1)
    inf = jnp.asarray(np.inf, jnp.float64)
    val = jnp.where(e == 0x7FF,
                    jnp.where(mant == 0, inf, jnp.asarray(np.nan, jnp.float64)),
                    val)
    return jnp.where(sign_neg, -val, val)


def to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """Device f64 [n] values -> u32 [n, 2] bit pattern."""
    if backend_has_f64_bitcast():
        return jax.lax.bitcast_convert_type(x, jnp.uint32)
    x = x.astype(jnp.float64)
    is_nan = x != x
    # 1/x distinguishes -0.0 (-> -inf); NaN compares false -> positive.
    sign_neg = (x < 0) | ((x == 0) & (1.0 / jnp.where(x == 0, x, 1.0) < 0))
    a = jnp.abs(x)
    is_inf = a == jnp.asarray(np.inf, jnp.float64)
    finite_pos = (~is_nan) & (~is_inf) & (a > 0)
    a_safe = jnp.where(finite_pos, a, 1.0)
    # Normalize a_safe into [1, 2), accumulating floor(log2 a) in e.  Every
    # scale factor must stay INSIDE the emulation's f32-like exponent
    # window (2^127 -> inf on this backend), so the lift uses three
    # conditional x2^75 steps (covers |x| >= 2^-225, far below the
    # emulation's ~2^-149 floor) and the descent tops out at 2^64
    # (64+32+...+1 = 127 covers the window's 2^127 ceiling).
    e = jnp.zeros(x.shape, jnp.int32)
    for _ in range(3):
        tiny = a_safe < 1.0
        a_safe = jnp.where(tiny, a_safe * np.float64(2.0 ** 75), a_safe)
        e = e - jnp.where(tiny, jnp.int32(75), jnp.int32(0))
    for k in (64, 32, 16, 8, 4, 2, 1):
        c = a_safe >= np.float64(2.0 ** k)
        a_safe = jnp.where(c, a_safe * np.float64(2.0 ** -k), a_safe)
        e = e + jnp.where(c, jnp.int32(k), jnp.int32(0))
    mant_f = (a_safe - 1.0) * np.float64(2.0 ** 52)       # exact when a has
    mant = jnp.rint(mant_f).astype(jnp.int64)             # <= 52 mantissa bits
    roll = mant >= (1 << 52)                              # rounding carry
    mant = jnp.where(roll, 0, mant)
    e = e + roll.astype(jnp.int32)
    biased = e + 1023
    # Underflow flushes to signed zero (the emulation cannot hold denormals);
    # overflow — or a magnitude beyond the descent's 2^127 reach, possible
    # only on native-f64 backends exercising this path — saturates to inf.
    to_inf = is_inf | (finite_pos & ((biased >= 0x7FF) | (a_safe >= 2.0)))
    # a_safe < 1 after the lifts means |x| < 2^-225 — below the lift range
    # (possible only on native-f64 backends exercising this path): flush to
    # signed zero, symmetric with the a_safe >= 2 overflow guard above.
    to_zero = ((~is_nan) & (~to_inf)
               & ((a == 0) | (biased <= 0) | (a_safe < 1.0)))
    biased = jnp.where(to_zero, 0, jnp.where(to_inf, 0x7FF, biased))
    mant = jnp.where(to_zero | to_inf, 0, mant)
    biased = jnp.where(is_nan, 0x7FF, biased)
    mant = jnp.where(is_nan, jnp.int64(1) << 51, mant)    # canonical quiet NaN
    sign_bit = jnp.where(is_nan, jnp.int64(0), sign_neg.astype(jnp.int64))
    hi = ((sign_bit << 31) | (biased.astype(jnp.int64) << 20)
          | (mant >> 32)).astype(jnp.uint32)
    lo = (mant & 0xFFFFFFFF).astype(jnp.uint32)
    return jnp.stack([lo, hi], axis=1)
