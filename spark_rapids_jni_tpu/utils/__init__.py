from . import bitmask, tracing  # noqa: F401
