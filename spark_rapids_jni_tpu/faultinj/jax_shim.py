"""JAX-boundary fault interception — the CUPTI-shim analog.

The reference's ``libcufaultinj.so`` subscribes to CUPTI's runtime+driver
callback domains and therefore sees *every* CUDA API call, not just named
framework functions (``faultinj.cu:125-131``).  The TPU equivalent of "the
API layer below the framework" is JAX's dispatch machinery; this module
monkeypatches the three churn points every device interaction funnels
through and routes them to the same injector/rule engine as the framework
sites:

=================  ==========================================  ============
site name          patched seam                                CUDA analog
=================  ==========================================  ============
``jax.device_put``  ``jax._src.dispatch.device_put_p.impl``     cudaMemcpy
``jax.compile``     ``jax._src.compiler.backend_compile``       cuModuleLoad
``jax.execute``     ``pxla.ExecuteReplicated.__call__``         cuLaunchKernel
=================  ==========================================  ============

While installed, JAX's C++ dispatch fastpath is additionally disabled
(``pjit._get_fastpath_data`` → None) so the ``jax.execute`` seam sees
REPEAT executions of cached signatures too — parity with CUPTI, which sees
every call.  See :func:`install` for the cost model.

Rules use the same JSON schema (percent / interceptionCount /
injectionType, ``faultinj/README.md:104-141``) keyed by the site names
above (or ``"*"``).  ``substitute`` is not meaningful at this layer (there
is no scalar return code to overwrite) and is treated as ``device_error``.

Usage::

    from spark_rapids_jni_tpu.faultinj import jax_shim
    jax_shim.install()          # idempotent
    ...
    jax_shim.uninstall()
"""

from __future__ import annotations

import functools

from ..analysis import sanitize
from .injector import get_injector

_LOCK = sanitize.tracked_lock("faultinj.jax_shim")
_PATCHED: dict[str, tuple] = {}


def _intercept(site: str, fn, *args, **kwargs):
    hit = get_injector().check(site)
    if hit is not None:
        # a substituted value makes no sense for compile/execute/transfer —
        # escalate like the reference's trap kernel
        from .injector import InjectedDeviceError
        raise InjectedDeviceError(
            f"[faultinj] injected device error at site {site!r}")
    return fn(*args, **kwargs)


def install() -> list[str]:
    """Patch the JAX seams (idempotent).  Returns the site names active.

    Caches are cleared so existing executables re-enter the Python dispatch
    path, AND the C++ fastpath is disabled for the install's duration:
    ``pjit._get_fastpath_data`` is patched to return None, so the C++ pjit
    cache never stores an entry and EVERY execution — including repeats of
    an already-compiled signature — dispatches through Python and hits the
    ``jax.execute`` seam.  This closes the round-2 gap vs CUPTI (which sees
    every call, ``faultinj.cu:125-131``): a long-running executor's steady
    state is exactly repeat executions.  Documented cost: Python dispatch
    per call (~0.1-1 ms) instead of the C++ fastpath while installed;
    ``uninstall`` restores full-speed dispatch (the bypassed cache simply
    repopulates on the next call).
    """
    with _LOCK:
        if _PATCHED:
            return list(_PATCHED)
        import jax
        # resolve EVERY private seam before patching ANY: these move
        # between JAX releases, and a partial install that fails midway
        # would leave earlier shims stuck (retries short-circuit on the
        # non-empty _PATCHED)
        import jax._src.compiler as _compiler
        from jax._src.interpreters import pxla as _pxla
        import jax._src.dispatch as _dispatch
        import jax._src.pjit as _pjit
        orig_compile = _compiler.backend_compile
        orig_call = _pxla.ExecuteReplicated.__call__
        orig_put = _dispatch.device_put_p.impl
        orig_fastpath = _pjit._get_fastpath_data

        jax.clear_caches()

        @functools.wraps(orig_fastpath)
        def no_fastpath(*a, **k):
            return None     # nothing cached ⇒ every call re-enters Python

        _pjit._get_fastpath_data = no_fastpath
        _PATCHED["jax._fastpath_off"] = (_pjit, "_get_fastpath_data",
                                         orig_fastpath)

        @functools.wraps(orig_compile)
        def compile_shim(*a, **k):
            return _intercept("jax.compile", orig_compile, *a, **k)

        @functools.wraps(orig_call)
        def call_shim(self, *a, **k):
            return _intercept("jax.execute", orig_call, self, *a, **k)

        @functools.wraps(orig_put)
        def put_shim(*a, **k):
            return _intercept("jax.device_put", orig_put, *a, **k)

        _compiler.backend_compile = compile_shim
        _PATCHED["jax.compile"] = (_compiler, "backend_compile", orig_compile)
        _pxla.ExecuteReplicated.__call__ = call_shim
        _PATCHED["jax.execute"] = (_pxla.ExecuteReplicated, "__call__",
                                   orig_call)
        _dispatch.device_put_p.impl = put_shim
        _PATCHED["jax.device_put"] = (_dispatch.device_put_p, "impl", orig_put)
        return list(_PATCHED)


def uninstall() -> None:
    with _LOCK:
        for holder, name, orig in _PATCHED.values():
            setattr(holder, name, orig)
        _PATCHED.clear()


def installed() -> bool:
    return bool(_PATCHED)
