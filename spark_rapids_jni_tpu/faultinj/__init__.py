from .injector import (FaultInjector, fault_site, get_injector,  # noqa: F401
                       enable, disable)
from .resilience import DeviceQuarantined, ResilientExecutor  # noqa: F401
from . import jax_shim  # noqa: F401
