from .injector import (FaultInjector, fault_site, get_injector,  # noqa: F401
                       enable, disable)
