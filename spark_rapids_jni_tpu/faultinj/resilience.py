"""Retry/quarantine/recovery policy harness — the "framework above" contract.

The reference's fault injector exists to prove that the framework above the
native library (Spark + the RAPIDS plugin) reacts correctly to GPU faults:
non-fatal errors are retried, fatal errors quarantine the executor, and
nothing deadlocks (``faultinj/README.md:3-16``).  This module provides the
same contract for this framework so resilience tests have a first-party
subject: a :class:`ResilientExecutor` that classifies failures from the
device layer (including the JAX-boundary shim's injections) and applies
Spark-like policy.

Lifecycle (the executor-replacement model, one state machine per device)::

    healthy ──fatal fault──▶ quarantined ──recover()──▶ probation
       ▲                          ▲                         │
       │                          └────fault during─────────┤
       └──────────first successful submit (canary)──────────┘

``quarantined`` fails every submit fast — the scheduler drains and
relocates that replica's work.  ``recover()`` (called by the scheduler's
recovery probe) moves to ``probation``: the next submit is the canary —
success re-admits the executor, another fatal fault re-quarantines it
(and the probe's backoff/ejection policy decides what happens next).

Transient faults (allocation failures) retry in place with JITTERED
EXPONENTIAL backoff: ``backoff_s`` seeds the schedule, each retry doubles
it up to ``backoff_max_s``, and a uniform jitter factor decorrelates
replicas retrying into the same pressure spike (the classic thundering-
herd fix; Spark's task-retry backoff does the same).
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional

from ..analysis import sanitize
from ..utils import flight
from .injector import InjectedDeviceError, InjectedOomError


class DeviceQuarantined(RuntimeError):
    """The executor refused work because a fatal device fault occurred."""


class ResilientExecutor:
    """Runs device closures with retry (transient) / quarantine (fatal) /
    probation (recovery canary).

    Policy mirrors the Spark executor contract the reference's tool tests
    (``faultinj/README.md:3-16``): allocation failures and other transient
    errors are retried up to ``max_retries`` with jittered exponential
    backoff; a device error (the PTX-trap analog,
    :class:`InjectedDeviceError`) is fatal — the executor quarantines
    itself and every subsequent submit fails fast until a recovery probe
    calls :meth:`recover` and a canary submit succeeds.

    ``device`` names the device this executor fronts (e.g. ``"cpu:3"``) so
    quarantine incidents and recovery events carry per-device identity in
    a multi-replica scheduler.
    """

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.0,
                 backoff_max_s: float = 2.0, jitter: float = 0.5,
                 device: Optional[str] = None, seed: Optional[int] = None):
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.jitter = max(float(jitter), 0.0)
        self.device = device
        self._mu = sanitize.tracked_lock("faultinj.resilience")
        self.state = "healthy"          # healthy | quarantined | probation
        self.retry_count = 0            # observability
        self.fatal_count = 0
        self.recovery_count = 0
        self._rng = random.Random(seed)

    @property
    def quarantined(self) -> bool:
        """Back-compat view: True while submits fail fast."""
        return self.state == "quarantined"

    def backoff_delay(self, attempt: int) -> float:
        """The sleep before retry ``attempt`` (1-based): exponential in
        the attempt, capped at ``backoff_max_s``, with multiplicative
        uniform jitter in ``[1, 1+jitter]``.  0 when backoff is off."""
        if self.backoff_s <= 0:
            return 0.0
        base = min(self.backoff_s * (2.0 ** (attempt - 1)),
                   self.backoff_max_s)
        return base * (1.0 + self.jitter * self._rng.random())

    def recover(self) -> bool:
        """Move a quarantined executor to probation: the NEXT submit is
        the canary — success re-admits, a fatal fault re-quarantines.
        Returns False (no-op) unless currently quarantined."""
        with self._mu:
            if self.state != "quarantined":
                return False
            self.state = "probation"
        flight.record("resilience.probation", device=self.device)
        return True

    def fail_probation(self) -> None:
        """Abort an unfinished canary: probation falls back to
        quarantined (a canary that errored without a fatal fault —
        e.g. a miscompare — must not leave the executor half-admitted)."""
        with self._mu:
            if self.state == "probation":
                self.state = "quarantined"

    def _quarantine(self, exc: BaseException) -> None:
        with self._mu:
            self.fatal_count += 1
            self.state = "quarantined"
            fatal = self.fatal_count
        flight.incident("quarantine", device=self.device, error=repr(exc),
                        fatal_count=fatal)

    def submit(self, fn: Callable[[], Any]) -> Any:
        with self._mu:
            if self.state == "quarantined":
                raise DeviceQuarantined(
                    f"executor is quarantined (device {self.device})")
            probation = self.state == "probation"
        attempts = 0
        while True:
            try:
                out = fn()
            except InjectedDeviceError as e:
                # fatal: device state unknown — quarantine (the plugin's
                # "shut down the executor so the cluster manager replaces
                # it" behavior; here replacement is the recovery probe)
                self._quarantine(e)
                raise DeviceQuarantined(
                    "fatal device fault — executor quarantined "
                    f"(device {self.device})")
            except (InjectedOomError, MemoryError):
                attempts += 1
                if attempts > self.max_retries:
                    raise
                self.retry_count += 1
                delay = self.backoff_delay(attempts)
                if delay:
                    time.sleep(delay)
                continue
            if probation:
                with self._mu:
                    if self.state == "probation":
                        self.state = "healthy"
                        self.recovery_count += 1
                flight.record("resilience.recovered", device=self.device,
                              recovery_count=self.recovery_count)
            return out
