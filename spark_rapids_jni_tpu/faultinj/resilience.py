"""Retry/quarantine policy harness — the "framework above" contract.

The reference's fault injector exists to prove that the framework above the
native library (Spark + the RAPIDS plugin) reacts correctly to GPU faults:
non-fatal errors are retried, fatal errors quarantine the executor, and
nothing deadlocks (``faultinj/README.md:3-16``).  This module provides the
same contract for this framework so resilience tests have a first-party
subject: a :class:`ResilientExecutor` that classifies failures from the
device layer (including the JAX-boundary shim's injections) and applies
Spark-like policy.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from ..utils import flight
from .injector import InjectedDeviceError, InjectedOomError


class DeviceQuarantined(RuntimeError):
    """The executor refused work because a fatal device fault occurred."""


class ResilientExecutor:
    """Runs device closures with retry (transient) / quarantine (fatal).

    Policy mirrors the Spark executor contract the reference's tool tests
    (``faultinj/README.md:3-16``): allocation failures and other transient
    errors are retried up to ``max_retries`` with backoff; a device error
    (the PTX-trap analog, :class:`InjectedDeviceError`) is fatal — the
    executor quarantines itself and every subsequent submit fails fast.
    """

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.0):
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.quarantined = False
        self.retry_count = 0      # observability
        self.fatal_count = 0

    def submit(self, fn: Callable[[], Any]) -> Any:
        if self.quarantined:
            raise DeviceQuarantined("executor is quarantined")
        attempts = 0
        while True:
            try:
                return fn()
            except InjectedDeviceError as e:
                # fatal: device state unknown — quarantine (the plugin's
                # "shut down the executor so the cluster manager replaces
                # it" behavior)
                self.fatal_count += 1
                self.quarantined = True
                flight.incident("quarantine", error=repr(e),
                                fatal_count=self.fatal_count)
                raise DeviceQuarantined(
                    "fatal device fault — executor quarantined")
            except (InjectedOomError, MemoryError):
                attempts += 1
                if attempts > self.max_retries:
                    raise
                self.retry_count += 1
                if self.backoff_s:
                    time.sleep(self.backoff_s)
