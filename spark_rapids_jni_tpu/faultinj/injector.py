"""Fault injection shim — resilience-testing tool (libcufaultinj parity).

The reference ships ``libcufaultinj.so``: a CUPTI interceptor loaded via
``CUDA_INJECTION64_PATH`` that matches CUDA API callbacks against a JSON
config and injects faults so the framework above can prove its retry/
quarantine logic (``faultinj/faultinj.cu``, ``faultinj/README.md:3-16``;
SURVEY §2.6, §3.4).  TPU translation: there is no CUPTI; the interception
point is this framework's own dispatch layer plus the patchable JAX host APIs
(device_put / jit-compile).  Parity preserved feature-for-feature:

* config matched by site name or ``"*"`` (``faultinj.cu:142-152``)
* per-rule ``percent`` dice and decrementing ``interceptionCount`` budget
  under a lock (``faultinj.cu:247-315``)
* injection types: raise (the CUDA trap/assert analogs become exception
  classes) or substituted return value (``faultinj.cu:317-340``)
* hot reload of the JSON config — a watcher thread picks up edits without
  restarting, mtime-polling standing in for inotify (``faultinj.cu:419-470``)
* seeded RNG for reproducible schedules (``faultinj.cu:96-100``)

Config schema (mirrors ``faultinj/README.md:104-141``)::

    {
      "logLevel": "info",
      "dynamic": true,                  # hot reload on/off
      "seed": 42,
      "sites": {
        "convert_to_rows": {
          "percent": 50,                # dice per interception
          "interceptionCount": 10,      # budget; -1 = unlimited
          "injectionType": "device_error"   # or "oom", "substitute"
          "substituteResult": null          # for injectionType substitute
        },
        "*": { ... }                    # wildcard, lowest precedence
      }
    }

Two extensions over the reference schema serve the chaos harness
(multi-device serving, ``exec/scheduler.py``):

* ``device`` — the rule fires only when the interception happens inside a
  matching :func:`device_scope` (the scheduler wraps each replica's
  dispatch in its device's scope).  The analog of pinning libcufaultinj
  to one GPU's CUDA context.  A device-mismatched named rule does NOT
  fall through to ``"*"`` — the site is configured, just not for this
  device.
* ``maxHits`` (alias ``max_hits``) — an absolute cap on how many times
  the rule fires, independent of ``interceptionCount`` (which budgets
  *interceptions*, i.e. dice rolls).  ``maxHits: 1`` is the one-shot
  kill used by ``ci/chaos_smoke.sh``: exactly one fatal fault, then the
  device is genuinely healthy again for the recovery probe's canary.
"""

from __future__ import annotations

import functools
import json
import os
import random
import threading
import time
from typing import Any, Callable, Optional

from ..analysis import sanitize

ENV_CONFIG_PATH = "FAULT_INJECTOR_CONFIG_PATH"   # same env var as faultinj.cu:93


class InjectedDeviceError(RuntimeError):
    """Analog of the injected PTX trap: the device is gone (fatal)."""


class InjectedOomError(MemoryError):
    """Injected allocation failure (RMM OOM analog)."""


_INJECTION_TYPES = ("device_error", "oom", "substitute")

# thread-local device scope: the scheduler marks which replica's device a
# worker thread is currently dispatching for, so device-targeted rules can
# discriminate (the CUDA-context analog; one process, many logical devices)
_tls = threading.local()


class device_scope:
    """Mark the current thread as dispatching on device ``name`` (e.g.
    ``"cpu:3"``); nestable context manager."""

    def __init__(self, name: Optional[str]):
        self.name = name
        self._prev: Optional[str] = None

    def __enter__(self) -> "device_scope":
        self._prev = getattr(_tls, "device", None)
        _tls.device = self.name
        return self

    def __exit__(self, *exc) -> None:
        _tls.device = self._prev


def current_device() -> Optional[str]:
    """The innermost :class:`device_scope` name on this thread, or None."""
    return getattr(_tls, "device", None)


class _Rule:
    def __init__(self, spec: dict):
        self.percent = float(spec.get("percent", 100.0))
        self.count = int(spec.get("interceptionCount", -1))
        self.injection_type = spec.get("injectionType", "device_error")
        if self.injection_type not in _INJECTION_TYPES:
            raise ValueError(f"unknown injectionType {self.injection_type!r}")
        self.substitute = spec.get("substituteResult")
        self.device = spec.get("device")         # None = any device
        mh = spec.get("maxHits", spec.get("max_hits", -1))
        self.max_hits = int(mh) if mh is not None else -1
        self.hits = 0


class FaultInjector:
    def __init__(self):
        self._lock = sanitize.tracked_lock("faultinj.injector")
        self._rules: dict[str, _Rule] = {}
        self._rng = random.Random()
        self._enabled = False
        self._config_path: Optional[str] = None
        self._watcher: Optional[threading.Thread] = None
        self._watcher_stop = threading.Event()
        self._mtime = 0.0
        self.injected_count = 0   # observability: how many faults fired

    # -- config -------------------------------------------------------------
    def load_dict(self, cfg: dict) -> None:
        """Arm rules from an in-memory config dict (same schema as the
        JSON file, minus ``dynamic``) — the chaos harness's programmatic
        entry point for mid-run fault schedules."""
        rules = {name: _Rule(spec)
                 for name, spec in cfg.get("sites", {}).items()}
        with self._lock:
            self._rules = rules
            self._rng = random.Random(cfg.get("seed"))

    def load_config(self, path: str) -> None:
        with open(path) as f:
            cfg = json.load(f)
        self.load_dict(cfg)
        with self._lock:
            self._config_path = path
            self._mtime = os.path.getmtime(path)
        if cfg.get("dynamic"):
            if self._watcher is None:
                self._start_watcher()
        elif self._watcher is not None:
            # config edited to dynamic:false → freeze the schedule
            self._watcher_stop.set()
            self._watcher = None

    def _start_watcher(self) -> None:
        # mtime polling in a daemon thread — the portable stand-in for the
        # reference's inotify watcher (faultinj.cu:419-470)
        self._watcher_stop.clear()

        def watch():
            while not self._watcher_stop.wait(0.25):
                path = self._config_path
                if not path:
                    continue
                try:
                    m = os.path.getmtime(path)
                except OSError:
                    continue
                if m != self._mtime:
                    # record the observed mtime first so a bad edit is not
                    # re-parsed on every poll until the file changes again
                    self._mtime = m
                    try:
                        self.load_config(path)
                    except Exception:
                        pass   # keep the old config on a bad edit; the
                        # watcher must survive any parse/coerce error
                        # (TypeError from e.g. "percent": null included)

        self._watcher = threading.Thread(target=watch, daemon=True,
                                         name="faultinj-watcher")
        self._watcher.start()

    # -- lifecycle ----------------------------------------------------------
    def enable(self, config_path: Optional[str] = None) -> None:
        path = config_path or os.environ.get(ENV_CONFIG_PATH)
        if path:
            self.load_config(path)
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False
        self._watcher_stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=2)
            self._watcher = None
        with self._lock:
            self._rules = {}
            self.injected_count = 0

    # -- interception -------------------------------------------------------
    def check(self, site: str):
        """Called at a fault site.  Returns None (no fault), raises, or
        returns (True, substitute_value) for a substituted result."""
        if not self._enabled:
            return None
        dev = current_device()
        with self._lock:
            rule = self._rules.get(site) or self._rules.get("*")
            if rule is None:
                return None
            if rule.device is not None and rule.device != dev:
                return None
            if rule.count == 0:
                return None
            if rule.max_hits >= 0 and rule.hits >= rule.max_hits:
                return None
            if self._rng.uniform(0, 100) >= rule.percent:
                return None
            if rule.count > 0:
                rule.count -= 1
            rule.hits += 1
            self.injected_count += 1
            injection_type = rule.injection_type
            substitute = rule.substitute
        if injection_type == "device_error":
            raise InjectedDeviceError(
                f"[faultinj] injected device error at site {site!r}")
        if injection_type == "oom":
            raise InjectedOomError(
                f"[faultinj] injected allocation failure at site {site!r}")
        return (True, substitute)


_global = FaultInjector()


def get_injector() -> FaultInjector:
    return _global


def enable(config_path: Optional[str] = None) -> None:
    _global.enable(config_path)


def disable() -> None:
    _global.disable()


def fault_site(name: str) -> Callable:
    """Decorator marking a framework dispatch point as an injectable site."""

    def wrap(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def inner(*args: Any, **kwargs: Any):
            hit = _global.check(name)
            if hit is not None:
                return hit[1]
            return fn(*args, **kwargs)

        inner.__fault_site__ = name
        return inner

    return wrap
