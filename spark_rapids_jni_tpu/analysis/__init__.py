"""First-party static analysis + runtime sanitizers.

Two halves, one discipline:

* **Static** (``core``, ``concurrency``, ``tracepass``, ``knobpass``) —
  AST passes over the whole package run by ``tools/srjt_lint.py`` and
  gated in CI (``ci/lint_smoke.sh``).  They catch the bug classes this
  repo has historically found *by hand*: lock-order inversions and
  unguarded shared mutation (hostcache/join_plan LRU races, prefetch
  take-before-load), trace-poisoning host syncs and silent retraces
  (PR 11's ``jax.default_device`` recompile), and knob drift (environ
  reads whose defaults/docs live nowhere).
* **Runtime** (``sanitize``) — ``SRJT_SANITIZE=1`` arms a lock-order
  watchdog and a retrace tripwire in the live process; ``strict`` makes
  violations raise (the CI chaos/exec smokes run strict).

This ``__init__`` stays import-light on purpose: ``analysis.sanitize``
is imported by hot modules (``utils``, ``exec``) at process start, so
nothing here may pull in jax or the rest of the package.
"""

from __future__ import annotations

__all__ = ["core", "concurrency", "tracepass", "knobpass", "sanitize"]
