"""Concurrency pass: lock graph, inversion cycles, unguarded mutation.

The serving stack holds ~23 lock sites across ``exec/``, ``memory/``,
``stream/`` and ``utils/`` and a history of hand-found races.  This pass
rebuilds the discipline a reviewer applies by eye, mechanically:

``conc-lock-order``
    Build the global lock-acquisition graph: an edge L→M means some code
    path acquires M (directly, or via a resolvable call chain) while
    holding L.  A cycle across distinct locks is a potential deadlock —
    two threads entering the cycle from different locks can each block
    on the other's held lock.  Reentrant reacquisition (L→L) is not an
    edge; RLocks make it legal and the runtime watchdog ignores it too.

``conc-mixed-guard``
    A ``self._x`` attribute (or module global) written under a lock in
    one method and without it in another is almost always a race: the
    locked sites prove the author considered it shared.  ``__init__``
    writes are construction and exempt.

``conc-global-augassign``
    ``global x; x += 1`` with no lock held is a read-modify-write that
    loses updates under threads (the exact shape of the historical
    ``utils/syncs.py`` sync-counter race).

Resolution is deliberately conservative: bare calls resolve within the
module, ``self.m()`` within the class, ``alias.f()`` through package
imports — unresolvable calls contribute no edges (missed edges are
acceptable; invented ones are not).
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, Source

__all__ = ["run", "LockCatalog"]

_PKG = "spark_rapids_jni_tpu"

_LOCK_FACTORIES = {"Lock", "RLock", "Condition",
                   "tracked_lock", "tracked_rlock", "tracked_condition"}


def _module_name(rel: str) -> Optional[str]:
    """``spark_rapids_jni_tpu/memory/budget.py`` → ``memory.budget``;
    None for files outside the package (tools, bench)."""
    if not rel.startswith(_PKG + "/"):
        return None
    parts = rel[len(_PKG) + 1:-3].split("/")      # strip pkg/ and .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else ""


def _is_lock_create(node: ast.expr) -> bool:
    """True when ``node`` constructs a lock/condition (``threading.Lock()``,
    ``sanitize.tracked_rlock(...)``, ``threading.Condition(...)``, ...)."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    return name in _LOCK_FACTORIES


class _Module:
    def __init__(self, src: Source, mod: str):
        self.src = src
        self.mod = mod
        self.globals_locks: set[str] = set()      # module-level lock names
        self.functions: dict[str, ast.FunctionDef] = {}
        self.classes: dict[str, dict[str, ast.FunctionDef]] = {}
        self.class_locks: dict[str, set[str]] = {}  # class -> self attrs
        # alias -> module name ("budget" -> "memory.budget")
        self.mod_aliases: dict[str, str] = {}
        # name -> (module, name) for `from .x import _LOCK` style
        self.name_aliases: dict[str, tuple[str, str]] = {}


class LockCatalog:
    """Phase 1 over every package source: locks, functions, imports."""

    def __init__(self, sources: list[Source]):
        self.modules: dict[str, _Module] = {}
        for src in sources:
            mod = _module_name(src.rel)
            if mod is None:
                continue
            self.modules[mod] = self._scan(src, mod)

    def _scan(self, src: Source, mod: str) -> _Module:
        m = _Module(src, mod)
        pkg_parts = mod.split(".")[:-1] if mod else []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                        if node.level <= len(pkg_parts) + 1 else None
                    if base is None:
                        continue
                    prefix = ".".join(base + ([node.module]
                                              if node.module else []))
                    for alias in node.names:
                        name = alias.asname or alias.name
                        if node.module:
                            # from .x import y: y is attr of module x
                            tgt = prefix
                            m.name_aliases[name] = (tgt, alias.name)
                            m.mod_aliases[name] = (tgt + "." + alias.name)
                        else:
                            # from . import x: x is a module
                            m.mod_aliases[name] = \
                                (prefix + "." if prefix else "") + alias.name
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_create(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        m.globals_locks.add(t.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods: dict[str, ast.FunctionDef] = {}
                attrs: set[str] = set()
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        methods[sub.name] = sub
                        for n2 in ast.walk(sub):
                            if (isinstance(n2, ast.Assign)
                                    and _is_lock_create(n2.value)):
                                for t in n2.targets:
                                    if (isinstance(t, ast.Attribute)
                                            and isinstance(t.value, ast.Name)
                                            and t.value.id == "self"):
                                        attrs.add(t.attr)
                self.classes_register(m, node.name, methods, attrs)
        return m

    @staticmethod
    def classes_register(m: _Module, cls: str, methods, attrs) -> None:
        m.classes[cls] = methods
        m.class_locks[cls] = attrs

    # --- resolution ---------------------------------------------------------

    def lock_id(self, m: _Module, cls: Optional[str],
                expr: ast.expr) -> Optional[str]:
        """Resolve a lock expression to a stable global identity string,
        or None when it isn't a known lock."""
        if isinstance(expr, ast.Name):
            if expr.id in m.globals_locks:
                return f"{m.mod}.{expr.id}"
            al = m.name_aliases.get(expr.id)
            if al is not None:
                tgt = self.modules.get(al[0])
                if tgt is not None and al[1] in tgt.globals_locks:
                    return f"{al[0]}.{al[1]}"
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    if expr.attr in m.class_locks.get(cls, ()):
                        return f"{m.mod}.{cls}.{expr.attr}"
                    return None
                tgt_mod = m.mod_aliases.get(base.id)
                if tgt_mod is not None:
                    tgt = self.modules.get(tgt_mod)
                    if tgt is not None and expr.attr in tgt.globals_locks:
                        return f"{tgt_mod}.{expr.attr}"
        return None

    def resolve_call(self, m: _Module, cls: Optional[str],
                     call: ast.Call) -> Optional[tuple]:
        """→ (module, class_or_None, func_name) for calls we can pin to a
        package function/method; None otherwise."""
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in m.functions:
                return (m.mod, None, f.id)
            al = m.name_aliases.get(f.id)
            if al is not None:
                tgt = self.modules.get(al[0])
                if tgt is not None and al[1] in tgt.functions:
                    return (al[0], None, al[1])
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self" and cls is not None:
                if f.attr in m.classes.get(cls, {}):
                    return (m.mod, cls, f.attr)
                return None
            tgt_mod = m.mod_aliases.get(f.value.id)
            if tgt_mod is not None:
                tgt = self.modules.get(tgt_mod)
                if tgt is not None and f.attr in tgt.functions:
                    return (tgt_mod, None, f.attr)
        return None

    def all_functions(self):
        """Yield (fid, module, cls, node) for every function/method."""
        for m in self.modules.values():
            for name, node in m.functions.items():
                yield (m.mod, None, name), m, None, node
            for cls, methods in m.classes.items():
                for name, node in methods.items():
                    yield (m.mod, cls, name), m, cls, node


class _FuncWalker(ast.NodeVisitor):
    """Walk one function tracking the held-lock stack; record direct
    acquisitions, nested-acquisition edges, and calls made while
    holding."""

    def __init__(self, cat: LockCatalog, m: _Module, cls: Optional[str]):
        self.cat = cat
        self.m = m
        self.cls = cls
        self.held: list[str] = []
        self.acquired: set[str] = set()
        # (held_lock, acquired_lock, line)
        self.edges: list[tuple[str, str, int]] = []
        # (callee_fid, held_snapshot, line)
        self.calls: list[tuple[tuple, tuple, int]] = []
        # (lock_id_or_None, line, node) for every with-entered lock
        self.with_locks: list[tuple[Optional[str], int]] = []

    def visit_FunctionDef(self, node):     # don't descend into nested defs
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _note_acquire(self, lock: Optional[str], line: int) -> None:
        if lock is None:
            return
        self.acquired.add(lock)
        for h in self.held:
            if h != lock:
                self.edges.append((h, lock, line))

    def visit_With(self, node: ast.With):
        entered = []
        for item in node.items:
            lock = self.cat.lock_id(self.m, self.cls, item.context_expr)
            self.with_locks.append((lock, node.lineno))
            self._note_acquire(lock, node.lineno)
            if lock is not None:
                entered.append(lock)
                self.held.append(lock)
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self.held.pop()

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            lock = self.cat.lock_id(self.m, self.cls, f.value)
            self._note_acquire(lock, node.lineno)
        fid = self.cat.resolve_call(self.m, self.cls, node)
        if fid is not None and self.held:
            self.calls.append((fid, tuple(self.held), node.lineno))
        self.generic_visit(node)


def _walk_function(cat: LockCatalog, m: _Module, cls: Optional[str],
                   node: ast.FunctionDef) -> _FuncWalker:
    w = _FuncWalker(cat, m, cls)
    for stmt in node.body:
        w.visit(stmt)
    return w


def _lock_order_findings(cat: LockCatalog,
                         walks: dict[tuple, _FuncWalker]) -> list[Finding]:
    # may-acquire fixpoint
    may: dict[tuple, set[str]] = {fid: set(w.acquired)
                                  for fid, w in walks.items()}
    changed = True
    while changed:
        changed = False
        for fid, w in walks.items():
            for callee, _held, _ln in w.calls:
                callee_may = may.get(callee)
                if callee_may and not callee_may <= may[fid]:
                    may[fid] |= callee_may
                    changed = True

    # edges: direct (nested with/acquire) + via resolvable calls
    edges: dict[tuple[str, str], tuple[str, int]] = {}

    def add_edge(a: str, b: str, rel: str, line: int):
        if a != b and (a, b) not in edges:
            edges[(a, b)] = (rel, line)

    for fid, w in walks.items():
        rel = cat.modules[fid[0]].src.rel if fid[0] in cat.modules else "?"
        for a, b, ln in w.edges:
            add_edge(a, b, rel, ln)
        for callee, held, ln in w.calls:
            for b in may.get(callee, ()):
                for a in held:
                    add_edge(a, b, rel, ln)

    # cycles = SCCs with >1 node (self-loops already excluded)
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    sccs = _tarjan(graph)
    findings = []
    for scc in sccs:
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        # anchor at the lexically first edge inside the cycle
        anchor = min(((rel, ln) for (a, b), (rel, ln) in edges.items()
                      if a in scc and b in scc), default=("?", 0))
        findings.append(Finding(
            rule="conc-lock-order", path=anchor[0], line=anchor[1],
            message="lock-order cycle between " + " <-> ".join(cyc)))
    return findings


def _tarjan(graph: dict[str, set[str]]) -> list[set[str]]:
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    out: list[set[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan (package files can nest deep)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out


def _mixed_guard_findings(cat: LockCatalog,
                          walks: dict[tuple, _FuncWalker]) -> list[Finding]:
    """Attrs/globals written both under a lock and unguarded."""
    findings = []
    # --- self attributes, per class ---
    for m in cat.modules.values():
        for cls, methods in m.classes.items():
            guarded: set[str] = set()
            unguarded: dict[str, tuple[int, str]] = {}

            for name, node in methods.items():
                writes = _attr_writes(cat, m, cls, node)
                for attr, line, under in writes:
                    if attr in m.class_locks.get(cls, ()):
                        continue
                    if under:
                        guarded.add(attr)
                    elif name != "__init__":
                        unguarded.setdefault(attr, (line, name))
            for attr in sorted(guarded & set(unguarded)):
                line, meth = unguarded[attr]
                findings.append(Finding(
                    rule="conc-mixed-guard", path=m.src.rel, line=line,
                    message=f"self.{attr} written without a lock in "
                            f"{cls}.{meth} but lock-guarded elsewhere in "
                            f"{cls}"))
    return findings


def _attr_writes(cat: LockCatalog, m: _Module, cls: str,
                 fn: ast.FunctionDef) -> list[tuple[str, int, bool]]:
    """(attr, line, under_lock) for every ``self.x`` assignment target."""
    out: list[tuple[str, int, bool]] = []

    class W(_FuncWalker):
        def _note_write(self, node):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.append((t.attr, t.lineno, bool(self.held)))

        def visit_Assign(self, node):
            self._note_write(node)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._note_write(node)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            self._note_write(node)
            self.generic_visit(node)

    w = W(cat, m, cls)
    for stmt in fn.body:
        w.visit(stmt)
    return out


def _global_augassign_findings(cat: LockCatalog) -> list[Finding]:
    findings = []
    for m in cat.modules.values():
        for fid, _m, cls, node in _functions_of(m):
            decl: set[str] = set()
            for n2 in ast.walk(node):
                if isinstance(n2, ast.Global):
                    decl.update(n2.names)
            if not decl:
                continue

            class W(_FuncWalker):
                def visit_AugAssign(self, w_node):
                    t = w_node.target
                    if (isinstance(t, ast.Name) and t.id in decl
                            and not self.held):
                        findings.append(Finding(
                            rule="conc-global-augassign", path=m.src.rel,
                            line=w_node.lineno,
                            message=f"global {t.id} mutated via augmented "
                                    "assignment with no lock held"))
                    self.generic_visit(w_node)

            w = W(cat, m, cls)
            for stmt in node.body:
                w.visit(stmt)
    return findings


def _functions_of(m: _Module):
    for name, node in m.functions.items():
        yield (m.mod, None, name), m, None, node
    for cls, methods in m.classes.items():
        for name, node in methods.items():
            yield (m.mod, cls, name), m, cls, node


def run(sources: list[Source]) -> list[Finding]:
    """All concurrency findings over the package sources."""
    cat = LockCatalog(sources)
    walks: dict[tuple, _FuncWalker] = {}
    for fid, m, cls, node in cat.all_functions():
        walks[fid] = _walk_function(cat, m, cls, node)
    findings = []
    findings += _lock_order_findings(cat, walks)
    findings += _mixed_guard_findings(cat, walks)
    findings += _global_augassign_findings(cat)
    return findings
