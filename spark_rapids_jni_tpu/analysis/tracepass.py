"""Retrace/host-sync pass: trace-poisoning patterns in jit-reachable code.

The capture/replay compiler (``models/compiled.py``) executes the op
library's Python bodies under ``jax.jit`` tracing.  In that world a
``float()``/``int()``/``bool()``/``.item()`` on a device value is a
ConcretizationError at best and a silent per-call host sync at worst
(~65-110 ms each on the remote-TPU tunnel), Python branching on an array
value bakes one side into the trace, and iterating an unordered ``set``
into a fingerprint makes "same plan" hash differently run to run — the
bug class behind PR 11's silent ``jax.default_device`` recompile.

Rules (scope: ``ops/``, ``rowconv/``, ``plan/lower.py``,
``models/compiled.py`` — the traced-reachable tree; ``trace-iter``
additionally runs package-wide over fingerprint/cache-key functions):

``trace-host-sync``
    ``int()``/``float()``/``bool()`` whose argument contains a
    ``jnp.``/``jax.`` expression (or a device-style reduction method
    like ``.sum()``), any ``.item()`` call, and ``np.asarray``/
    ``np.array`` over a ``jnp`` expression.  The one sanctioned funnel
    is ``utils.syncs.scalar`` — it counts the sync and resolves from the
    tape under replay.

``trace-branch``
    ``if``/``while`` predicates containing a direct ``jnp.``/``jax.``
    call — data-dependent Python control flow does not trace.

``trace-iter``
    Iteration over a ``set``/``frozenset`` inside a function whose name
    says it computes a fingerprint/cache key — unordered iteration feeds
    nondeterminism straight into plan identity.
"""

from __future__ import annotations

import ast
import re

from .core import Finding, Source

__all__ = ["run", "TRACE_SCOPE_DIRS", "TRACE_SCOPE_FILES"]

TRACE_SCOPE_DIRS = ("spark_rapids_jni_tpu/ops/",
                    "spark_rapids_jni_tpu/rowconv/")
TRACE_SCOPE_FILES = ("spark_rapids_jni_tpu/plan/lower.py",
                     "spark_rapids_jni_tpu/models/compiled.py")

_REDUCTIONS = {"sum", "min", "max", "mean", "prod", "any", "all",
               "argmin", "argmax"}
_KEY_FN_RE = re.compile(
    r"fingerprint|cache_key|plan_key|size_key|_fp\b|\bfp_|hash_", re.I)


def in_trace_scope(rel: str) -> bool:
    return rel.startswith(TRACE_SCOPE_DIRS) or rel in TRACE_SCOPE_FILES


def _is_sanctioned_sync(node: ast.Call) -> bool:
    """``syncs.scalar(...)`` / ``scalar(...)`` — the one approved funnel.
    It counts the sync eagerly and resolves from the tape under replay
    (returning a plain int), so its result is host-safe to branch on."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "scalar" \
            and isinstance(f.value, ast.Name) and f.value.id == "syncs":
        return True
    return isinstance(f, ast.Name) and f.id == "scalar"


def _contains_device_expr(node: ast.expr) -> bool:
    """Heuristic: does the expression tree contain a ``jnp.``/``jax.``
    call or a reduction-style method call?  That is our stand-in for "a
    traced value" — a static pass can't see dynamic types, and this
    shape covers every host-sync regression this repo has actually had.
    ``syncs.scalar(...)`` subtrees are pruned: their results are tape
    ints, not traced values."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Call):
            if _is_sanctioned_sync(n):
                continue                      # prune: result is a host int
            f = n.func
            if isinstance(f, ast.Attribute):
                root = f.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in ("jnp", "jax",
                                                              "lax"):
                    return True
                # x.sum()/x.max()/... counts only when the receiver itself
                # involves jnp/jax — bare numpy host arrays (offs_np etc.)
                # reduce with the same method names and are NOT syncs
                if f.attr in _REDUCTIONS and any(
                        isinstance(d, ast.Name)
                        and d.id in ("jnp", "jax", "lax")
                        for d in ast.walk(f.value)):
                    return True
        stack.extend(ast.iter_child_nodes(n))
    return False


def _host_sync_findings(src: Source) -> list[Finding]:
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not node.args and not node.keywords:
            out.append(Finding(
                rule="trace-host-sync", path=src.rel, line=node.lineno,
                message=".item() forces a device->host sync in traced "
                        "code; route sizes through syncs.scalar"))
            continue
        name = None
        if isinstance(f, ast.Name) and f.id in ("int", "float", "bool"):
            name = f.id
        elif (isinstance(f, ast.Attribute)
              and f.attr in ("asarray", "array")
              and isinstance(f.value, ast.Name) and f.value.id == "np"):
            name = f"np.{f.attr}"
        if name is None or not node.args:
            continue
        if _contains_device_expr(node.args[0]):
            out.append(Finding(
                rule="trace-host-sync", path=src.rel, line=node.lineno,
                message=f"{name}() over a device expression forces a "
                        "host sync in traced code; route through "
                        "syncs.scalar"))
    return out


def _branch_findings(src: Source) -> list[Finding]:
    out = []
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.If, ast.While)) \
                and _contains_device_expr(node.test):
            kw = "if" if isinstance(node, ast.If) else "while"
            out.append(Finding(
                rule="trace-branch", path=src.rel, line=node.lineno,
                message=f"`{kw}` predicate evaluates a device expression "
                        "— data-dependent Python control flow does not "
                        "trace (use jnp.where / lax.cond)"))
    return out


def _is_set_expr(node: ast.expr) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    return False


def _iter_findings(src: Source) -> list[Finding]:
    out = []
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _KEY_FN_RE.search(fn.name):
            continue
        for node in ast.walk(fn):
            it = None
            if isinstance(node, ast.For):
                it = node.iter
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp)):
                it = node.generators[0].iter
            if it is not None and _is_set_expr(it):
                out.append(Finding(
                    rule="trace-iter", path=src.rel, line=node.lineno,
                    message=f"unordered set iteration inside key/"
                            f"fingerprint function `{fn.name}` — sort "
                            "before hashing"))
    return out


def run(sources: list[Source]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        if in_trace_scope(src.rel):
            findings += _host_sync_findings(src)
            findings += _branch_findings(src)
        if src.rel.startswith("spark_rapids_jni_tpu/"):
            findings += _iter_findings(src)
    return findings
