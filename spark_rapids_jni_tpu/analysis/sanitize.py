"""Runtime sanitizers: lock-order watchdog + retrace tripwire.

Static analysis sees the shapes it can resolve; this module watches the
*live* process.  ``SRJT_SANITIZE=1`` arms both sanitizers in incident
mode — violations file a flight-recorder incident (kind ``lock_order``
or ``retrace``) with the offending stacks and keep going.
``SRJT_SANITIZE=strict`` raises instead; the CI chaos/exec smokes run
strict so an inversion or an unexpected recompile fails the build, not
the pager.

Lock-order watchdog
    Lock sites create their primitives through :func:`tracked_lock` /
    :func:`tracked_rlock` (and build conditions as
    ``threading.Condition(tracked_lock("name"))``).  Off (the default),
    these return plain ``threading`` primitives — zero overhead, chosen
    once at creation.  On, each wrapper maintains a per-thread held
    stack and a process-global acquisition DAG: acquiring M while
    holding L records edge L→M with the first-seen acquisition stack;
    if a path M→…→L already exists, two threads can deadlock by
    entering from opposite ends — that's the violation.  Reentrant
    reacquisition (RLocks) records no edge.  The watchdog's own mutex
    is held only for graph bookkeeping, never while blocking on a user
    lock.

Retrace tripwire
    ``models/compiled.py`` calls :func:`note_trace(key)` from inside its
    traced body — each execution of that body IS one XLA trace.  The
    first trace per key is warmup; any further trace without an
    enclosing :func:`allow_retrace` (the vmap program build is a
    legitimate second trace) is the silent-recompile class behind
    PR 11's ``jax.default_device`` regression, and trips.

This module imports only the stdlib at module level — it is pulled in by
``utils.metrics`` and friends at process start, before the package (or
jax) is fully importable.
"""

from __future__ import annotations

import contextlib
import os
import threading
import traceback
from typing import Optional

__all__ = ["mode", "enabled", "strict", "tracked_lock", "tracked_rlock",
           "note_trace", "allow_retrace", "reset",
           "LockOrderError", "RetraceError"]


def mode() -> str:
    """``"off"`` | ``"on"`` | ``"strict"`` — read from the environment on
    every call (lock sites sample it once at creation)."""
    # Read directly, not via utils.knobs: this module must import before
    # the utils package exists (metrics/flight import it at their own
    # import time).  SRJT_SANITIZE is registered + documented in knobs.py.
    raw = os.environ.get(  # srjt-lint: disable=knob-env
        "SRJT_SANITIZE", "0").strip().lower()
    if raw in ("", "0", "off", "false"):
        return "off"
    return "strict" if raw == "strict" else "on"


def enabled() -> bool:
    return mode() != "off"


def strict() -> bool:
    return mode() == "strict"


class LockOrderError(RuntimeError):
    """Strict-mode lock-order inversion."""


class RetraceError(RuntimeError):
    """Strict-mode unexpected recompile."""


# --- lock-order watchdog ----------------------------------------------------

_tls = threading.local()            # .held: list[str], .suppress: bool
_mu = threading.Lock()              # guards the three dicts below ONLY
_graph: dict[str, set[str]] = {}    # edge a -> b: acquired b while holding a
_edge_stacks: dict[tuple, str] = {}  # first-seen stack per edge
_violations: list[dict] = []        # recorded inversions (tests/ops)


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _path(graph: dict, a: str, b: str) -> Optional[list]:
    """A path a→…→b in ``graph`` (callers hold ``_mu``), else None."""
    stack = [(a, [a])]
    seen = {a}
    while stack:
        node, path = stack.pop()
        for nxt in graph.get(node, ()):
            if nxt == b:
                return path + [b]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _on_acquired(name: str) -> None:
    """Record edges held→name; detect inversions.  Called after the inner
    lock is held; takes only ``_mu`` and only briefly."""
    if getattr(_tls, "suppress", False):
        return
    held = _held()
    if name in held:                 # reentrant (RLock): no edge, no push
        held.append(name)
        return
    inversion = None
    if held:
        uniq = []
        for h in held:
            if h != name and h not in uniq:
                uniq.append(h)
        with _mu:
            for h in uniq:
                cyc = _path(_graph, name, h)
                if cyc is not None:
                    if inversion is None:
                        inversion = {
                            "acquiring": name,
                            "while_holding": h,
                            "established_path": cyc,
                            "prior_stack": _edge_stacks.get(
                                (cyc[0], cyc[1]), "<unknown>"),
                        }
                    # do NOT record the cycle-closing edge: the graph
                    # stays a DAG of established orders, so the correct
                    # order keeps working and every future inverted
                    # acquisition still trips
                    continue
                edge = (h, name)
                if name not in _graph.setdefault(h, set()):
                    _graph[h].add(name)
                    _edge_stacks[edge] = "".join(
                        traceback.format_stack(limit=12))
            if inversion is not None:
                _violations.append(inversion)
    held.append(name)
    if inversion is not None:
        _report_lock_order(inversion)


def _on_released(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


def _report_lock_order(v: dict) -> None:
    _tls.suppress = True
    try:
        here = "".join(traceback.format_stack(limit=12))
        try:
            from ..utils import flight
            flight.incident(
                "lock_order",
                acquiring=v["acquiring"],
                while_holding=v["while_holding"],
                established_path=" -> ".join(v["established_path"]),
                stack=here,
                prior_stack=v["prior_stack"])
        except Exception:
            pass
        if strict():
            raise LockOrderError(
                f"lock-order inversion: acquiring {v['acquiring']!r} while "
                f"holding {v['while_holding']!r}, but the established "
                f"order is {' -> '.join(v['established_path'])}\n"
                f"--- first-seen acquisition stack ---\n{v['prior_stack']}")
    finally:
        _tls.suppress = False


class _TrackedLock:
    """A ``threading.Lock`` that feeds the watchdog.  Works as the inner
    lock of a ``threading.Condition`` (supports the ``acquire(0)``
    probe its ``_is_owned`` fallback uses)."""

    _reentrant = False

    def __init__(self, name: str):
        self._name = name
        self._inner = self._make()

    @staticmethod
    def _make():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            try:
                _on_acquired(self._name)
            except BaseException:
                # strict-mode LockOrderError: back the acquisition out so
                # the caller's `with` (whose __exit__ never runs) does not
                # leave the lock held forever
                _on_released(self._name)
                self._inner.release()
                raise
        return ok

    def release(self) -> None:
        _on_released(self._name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        if not self.acquire():
            raise RuntimeError(f"failed to acquire {self._name}")
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        kind = "rlock" if self._reentrant else "lock"
        return f"<tracked {kind} {self._name!r}>"


class _TrackedRLock(_TrackedLock):
    _reentrant = True

    @staticmethod
    def _make():
        return threading.RLock()

    def locked(self) -> bool:            # RLock has no .locked() pre-3.12
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True


def tracked_lock(name: str):
    """A mutex named for the watchdog's graph; plain ``threading.Lock``
    when the sanitizer is off (decided here, at creation)."""
    if not enabled():
        return threading.Lock()
    return _TrackedLock(name)


def tracked_rlock(name: str):
    if not enabled():
        return threading.RLock()
    return _TrackedRLock(name)


# --- retrace tripwire -------------------------------------------------------

_trace_counts: dict[str, int] = {}
_retrace_events: list[dict] = []


def note_trace(key: str) -> None:
    """Called from inside a traced body: one call = one XLA trace of plan
    ``key``.  First is warmup; later ones outside :func:`allow_retrace`
    trip the wire."""
    if not enabled():
        return
    if getattr(_tls, "allow_retrace", 0) > 0:
        return
    with _mu:
        n = _trace_counts.get(key, 0) + 1
        _trace_counts[key] = n
    if n <= 1:
        return
    ev = {"key": key, "count": n,
          "stack": "".join(traceback.format_stack(limit=16))}
    with _mu:
        _retrace_events.append(ev)
    _tls.suppress = True
    try:
        try:
            from ..utils import flight
            flight.incident("retrace", plan_key=key, compiles=n,
                            stack=ev["stack"])
        except Exception:
            pass
        if strict():
            raise RetraceError(
                f"unexpected recompile: plan {key!r} traced {n} times "
                f"(first trace is warmup; wrap legitimate rebuilds in "
                f"sanitize.allow_retrace())\n{ev['stack']}")
    finally:
        _tls.suppress = False


@contextlib.contextmanager
def allow_retrace():
    """Legitimise retraces in the dynamic extent (e.g. building the
    vmapped variant re-traces the same plan body on purpose)."""
    prev = getattr(_tls, "allow_retrace", 0)
    _tls.allow_retrace = prev + 1
    try:
        yield
    finally:
        _tls.allow_retrace = prev


# --- introspection / tests --------------------------------------------------


def violations() -> list[dict]:
    with _mu:
        return list(_violations)


def retrace_events() -> list[dict]:
    with _mu:
        return list(_retrace_events)


def reset() -> None:
    """Drop the acquisition graph, recorded violations, and trace counts
    (tests).  Held stacks are per-thread and owned by their threads."""
    with _mu:
        _graph.clear()
        _edge_stacks.clear()
        _violations.clear()
        _trace_counts.clear()
        _retrace_events.clear()
