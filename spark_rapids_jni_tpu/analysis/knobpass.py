"""Knob pass: every ``SRJT_*`` environment read goes through the registry.

``utils/knobs.py`` is the single source of truth for knob names,
defaults, parse semantics, and docs — the README table is generated from
it.  This pass keeps that true mechanically:

``knob-env``
    A direct ``os.environ.get("SRJT_...")`` / ``os.environ["SRJT_..."]``
    / ``os.getenv("SRJT_...")`` READ anywhere outside ``utils/knobs.py``.
    Writes (``os.environ["SRJT_X"] = ...``) are fine — tests and the
    crash-resume benches set knobs; only reads must funnel through
    :func:`knobs.get` so defaults and parsing can't fork.

``knob-unregistered``
    ``knobs.get("SRJT_X")`` where ``SRJT_X`` is not registered — it
    would raise ``KeyError`` at runtime; catch it in CI instead.

``knob-undoc``
    A registered knob whose name does not appear in README.md.  Run
    ``python tools/srjt_lint.py --knob-table`` to refresh the generated
    table in place.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Finding, Source

__all__ = ["run", "load_registry"]

_KNOBS_REL = "spark_rapids_jni_tpu/utils/knobs.py"


def load_registry(root: str) -> dict:
    """Load ``utils/knobs.py`` standalone (no package import, no jax) and
    return its ``REGISTRY``."""
    import importlib.util
    import os
    path = os.path.join(root, _KNOBS_REL)
    spec = importlib.util.spec_from_file_location("_srjt_knobs_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.REGISTRY


def _knob_name(node: ast.expr) -> Optional[str]:
    """The SRJT_* name in a string-ish expression, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value.startswith("SRJT_") else None
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str) \
                and first.value.startswith("SRJT_"):
            return first.value + "*"
    return None


def _is_environ(node: ast.expr) -> bool:
    """``os.environ`` or a bare ``environ`` name."""
    if isinstance(node, ast.Attribute) and node.attr == "environ" \
            and isinstance(node.value, ast.Name) and node.value.id == "os":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def _env_read_findings(src: Source) -> list[Finding]:
    out = []
    for node in ast.walk(src.tree):
        name = None
        if isinstance(node, ast.Call):
            f = node.func
            # os.environ.get("SRJT_X"[, default]) / environ.get(...)
            if isinstance(f, ast.Attribute) and f.attr == "get" \
                    and _is_environ(f.value) and node.args:
                name = _knob_name(node.args[0])
            # os.getenv("SRJT_X")
            elif isinstance(f, ast.Attribute) and f.attr == "getenv" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "os" and node.args:
                name = _knob_name(node.args[0])
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and _is_environ(node.value):
            name = _knob_name(node.slice)
        if name is not None:
            out.append(Finding(
                rule="knob-env", path=src.rel, line=node.lineno,
                message=f"direct environ read of {name}; use "
                        "utils.knobs.get so the default/parser/doc live "
                        "in one place"))
    return out


def _unregistered_findings(src: Source, registered: set[str]) \
        -> list[Finding]:
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        is_get = (isinstance(f, ast.Attribute) and f.attr == "get"
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "knobs")
        if not is_get:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith("SRJT_") \
                and arg.value not in registered:
            out.append(Finding(
                rule="knob-unregistered", path=src.rel, line=node.lineno,
                message=f"knobs.get({arg.value!r}) but {arg.value} is not "
                        "registered in utils/knobs.py"))
    return out


def run(sources: list[Source], registered: set[str],
        readme_text: Optional[str] = None) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        if src.rel == _KNOBS_REL:
            continue
        findings += _env_read_findings(src)
        findings += _unregistered_findings(src, registered)
    if readme_text is not None:
        for name in sorted(registered):
            if name not in readme_text:
                findings.append(Finding(
                    rule="knob-undoc", path="README.md", line=1,
                    message=f"registered knob {name} is missing from the "
                            "README knob table (regenerate with "
                            "tools/srjt_lint.py --knob-table)"))
    return findings
