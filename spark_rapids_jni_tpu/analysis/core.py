"""Shared machinery for the static-analysis passes.

A *finding* is (rule id, path, line, message).  Findings are suppressible
two ways, mirroring how mature linters ratchet a legacy tree:

* inline — a ``# srjt-lint: disable=<rule>[,<rule>...]`` comment on the
  finding's line (or the preceding line, for findings on multi-line
  statements) silences those rules there, with the comment itself serving
  as the in-situ justification;
* baseline — ``ci/lint_baseline.json`` holds accepted pre-existing
  findings.  Baseline entries match on (rule, path, message) and NOT on
  line number, so unrelated edits that shift lines don't resurrect them;
  the gate fails only on findings outside the baseline, so it starts
  green and ratchets as entries are fixed and removed.

This module is stdlib-only (ast/json/os/re/tokenize) — the lint tool must
run without importing the package or jax.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Iterable, Optional

__all__ = ["Finding", "Source", "load_source", "collect_sources",
           "Baseline", "filter_findings"]

_DISABLE_RE = re.compile(r"#\s*srjt-lint:\s*disable=([\w\-, ]+)")


@dataclass(frozen=True)
class Finding:
    """One lint finding with a stable rule id and a location."""
    rule: str       # e.g. "conc-lock-order"
    path: str       # repo-relative, forward slashes
    line: int       # 1-based
    message: str

    def key(self) -> tuple:
        """Baseline identity — deliberately line-free (see module doc)."""
        return (self.rule, self.path, self.message)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Source:
    """A parsed source file plus its inline-suppression map."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=rel)
        # line -> set of rule ids disabled on that line
        self.suppressions: dict[int, set[str]] = {}
        try:
            toks = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _DISABLE_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                    self.suppressions.setdefault(
                        tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        """True when ``rule`` is disabled on ``line`` or the line above
        (the comment often sits on its own line before a long
        statement)."""
        for ln in (line, line - 1):
            rules = self.suppressions.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


def load_source(path: str, root: str) -> Optional[Source]:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        return Source(path, rel, text)
    except (OSError, SyntaxError, ValueError):
        return None


def collect_sources(root: str, *, subdirs: Iterable[str],
                    extra_files: Iterable[str] = (),
                    exclude_dirs: Iterable[str] = ("tests", ".git",
                                                   "__pycache__")) \
        -> list[Source]:
    """Parse every ``.py`` under ``root/<subdir>`` (recursively) plus
    ``extra_files`` (root-relative), skipping ``exclude_dirs`` by
    basename.  Unparseable files are skipped, not fatal — the lint gate
    must not fall over on a scratch file."""
    out: list[Source] = []
    seen: set[str] = set()
    excl = set(exclude_dirs)
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d not in excl)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                if p in seen:
                    continue
                seen.add(p)
                src = load_source(p, root)
                if src is not None:
                    out.append(src)
    for rel in extra_files:
        p = os.path.join(root, rel)
        if p in seen or not os.path.isfile(p):
            continue
        seen.add(p)
        src = load_source(p, root)
        if src is not None:
            out.append(src)
    return out


class Baseline:
    """The checked-in accepted-findings file (JSON list of objects)."""

    def __init__(self, entries: Iterable[Finding] = ()):
        self._keys = {f.key() for f in entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.isfile(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        return cls(Finding(rule=e["rule"], path=e["path"],
                           line=int(e.get("line", 0)),
                           message=e["message"])
                   for e in raw)

    @staticmethod
    def write(path: str, findings: Iterable[Finding]) -> None:
        entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                    "message": f.message}
                   for f in sorted(findings,
                                   key=lambda f: (f.path, f.line, f.rule))]
        with open(path, "w", encoding="utf-8") as f:
            json.dump(entries, f, indent=1)
            f.write("\n")

    def contains(self, finding: Finding) -> bool:
        return finding.key() in self._keys

    def __len__(self) -> int:
        return len(self._keys)


def filter_findings(findings: Iterable[Finding], sources: dict[str, "Source"],
                    baseline: Optional[Baseline] = None) -> list[Finding]:
    """Drop inline-suppressed and baselined findings; sort the rest."""
    out = []
    for f in findings:
        src = sources.get(f.path)
        if src is not None and src.suppressed(f.rule, f.line):
            continue
        if baseline is not None and baseline.contains(f):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out
