"""Device-side Parquet scan (BASELINE config #2 — "GB/s columnar scan").

Round 2 decoded every page byte in host NumPy loops and uploaded finished
columns (`decode.py`); the reference's scan is a GPU engine (libcudf decode
built into the artifact, ``build-libcudf.xml:48-64``).  This module moves
the byte-level decode ONTO the chip for the hot shapes:

  host (staging, like the reference's host buffers):
      footer/thrift parse → page walk → decompression (native snappy in
      ``libsrjt.so``) → concatenate raw PLAIN payloads / host-decode tiny
      run-length metadata (def levels, dictionary indices' RLE headers)
  device (one jitted program per column):
      PLAIN bitcast u8 → typed lanes  (f64 → u32 bit pairs, the Column
      invariant — no f64 arithmetic anywhere)
      dictionary index gather          (typed dict values resident)
      def-level expansion              (cumsum positions + masked gather)

Round 4 extends the device tier to PLAIN strings (the native
``srjt_byte_array_offsets`` walker stages the sequential offsets
recurrence; ONE device segmented gather strips the length prefixes —
``rowconv/xpack.segmented_gather``) and BOOLEAN bit-unpack.  Columns
outside the fast path (dictionary strings, INT96, DELTA_*, nested) fall
back to the host decoder transparently — correctness first, the fast path
covers the scan-heavy analytics shapes.

``scan_table`` mirrors ``decode.read_table`` and is differentially tested
against it (tests/test_device_scan.py).
"""

from __future__ import annotations

import functools
import struct as _struct
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, DictColumn, Table
from ..utils import flight, knobs, metrics, syncs
from ..utils.tracing import traced
from . import decode as D
from . import staging
from .footer import extract_footer_bytes
from .thrift import parse_struct

_PLAIN_PHYS = {D.PT_INT32: 4, D.PT_INT64: 8, D.PT_FLOAT: 4, D.PT_DOUBLE: 8}


def _stage_wave(stager, *arrays):
    """Upload host arrays in ONE coalesced slab wave when a stager is
    given (queue all, then resolve — the first resolve flushes the whole
    wave), else the eager per-buffer ``jnp.asarray``."""
    if stager is None:
        return tuple(jnp.asarray(a) for a in arrays)
    hs = [staging.asarray(a, stager) for a in arrays]
    return tuple(staging.resolve(h) for h in hs)


def _resolve_args(args):
    return tuple(staging.resolve(a) for a in args)


def _walk_chunk_raw(file_bytes: bytes, chunk, max_def: int, max_rep: int,
                    type_len: int = 0):
    """Page walk that KEEPS raw PLAIN payload bytes (or dictionary+index
    run plans) instead of decoding values.  Returns None when the chunk
    needs the host decoder (unsupported physical type / encoding /
    nesting).

    Definition levels and dictionary indices are *not* decoded here
    (round 5): only their run HEADERS are walked (``rle_device.parse_runs``
    — O(#runs) host metadata, like page headers) and the bit-stream
    payload expands on device.  ``present_count`` provides the per-page
    present-value total the payload slicing needs.  FIXED_LEN_BYTE_ARRAY
    chunks (width ≤ 16 — the parquet DECIMAL carrier) are fixed-width
    too: their payload is kept raw and assembled into decimal limbs on
    device."""
    from . import rle_device as RLE
    md = chunk.get(D.CC.META_DATA)
    phys = md.get(D.CMD.TYPE)
    is_flba = (phys == D.PT_FIXED_LEN_BYTE_ARRAY
               and 0 < type_len <= 16)
    is_str = phys == D.PT_BYTE_ARRAY
    is_bool = phys == D.PT_BOOLEAN
    if (phys not in _PLAIN_PHYS and not (is_flba or is_str or is_bool)) \
            or max_rep > 0:
        return None
    width = (type_len if is_flba
             else _PLAIN_PHYS.get(phys, 0))
    codec = md.get(D.CMD.CODEC, 0)
    num_values = md.get(D.CMD.NUM_VALUES)
    start = md.get(D.CMD.DATA_PAGE_OFFSET)
    dict_off = md.get(D.CMD.DICT_PAGE_OFFSET)
    if dict_off is not None and dict_off < start:
        start = dict_off
    total = md.get(D.CMD.TOTAL_COMPRESSED_SIZE)
    stream = D._PageStream(file_bytes[start:start + total], codec)

    # def-level streams expand on device only when the whole expansion is
    # a bit test (flat optional column, max_def == 1) and no host stage
    # needs the concrete mask; the PLAIN-string native offsets walker
    # scatters by validity on host, so string chunks keep np levels
    def_bw = D._bit_width(max_def)
    use_plan_defs = max_def == 1 and not is_str

    def _levels(buf: bytes, n: int):
        """→ (entry, n_present): entry is None | ("np", arr) |
        ("plan", RunPlan, n_present)."""
        if use_plan_defs:
            plan = RLE.parse_runs(buf, def_bw, n)
            if plan is not None:
                npres = RLE.present_count(plan, max_def)
                if npres == n:
                    return None, n               # no nulls in this page
                return ("plan", plan, npres), npres
        defs = D.decode_rle_bitpacked_hybrid(buf, def_bw, n)
        return ("np", defs == max_def), int((defs == max_def).sum())

    dictionary = None
    payloads, idx_parts, def_parts, ns, npres_l = [], [], [], [], []
    decoded = 0
    while decoded < num_values:
        header, raw = stream.next_page()
        ptype = header.get(D.PH.TYPE)
        usize = header.get(D.PH.UNCOMPRESSED_SIZE)
        if metrics.recording() and ptype in (D.PAGE_DATA, D.PAGE_DICTIONARY):
            metrics.count("parquet.pages.dict" if ptype == D.PAGE_DICTIONARY
                          else "parquet.pages.data")
        if ptype == D.PAGE_DICTIONARY:
            dph = header.get(D.PH.DICT_PAGE)
            data = D._decompress(raw, codec, usize)
            m = dph.get(D.DPH.NUM_VALUES)
            if is_bool:
                return None
            if is_str:
                # dictionary strings (round 5): keep the dict page RAW —
                # the native walker stages the sequential offsets
                # recurrence, chars stay bytes for the device gather
                offs = D.byte_array_offsets(data, m)
                if offs is None:
                    return None
                dictionary = (bytes(data), offs)
            elif is_flba:   # fixed-width byte strings -> host limb decode
                dictionary = D._be_decimal_to_lanes(
                    np.frombuffer(data, np.uint8, m * type_len), type_len)
            else:
                dictionary = np.frombuffer(
                    data, dtype=D._PHYS_NP[phys], count=m)
            continue
        if ptype == D.PAGE_DATA:
            dph = header.get(D.PH.DATA_PAGE)
            n = dph.get(D.DPH.NUM_VALUES)
            enc = dph.get(D.DPH.ENCODING)
            data = D._decompress(raw, codec, usize)
            pos = 0
            dentry, n_present = None, n
            if max_def > 0:
                (ln,) = _struct.unpack_from("<I", data, pos)
                pos += 4
                dentry, n_present = _levels(data[pos:pos + ln], n)
                pos += ln
            page_vals = data[pos:]
        elif ptype == D.PAGE_DATA_V2:
            dph = header.get(D.PH.DATA_PAGE_V2)
            n = dph.get(D.DPH2.NUM_VALUES)
            enc = dph.get(D.DPH2.ENCODING)
            dl_len = dph.get(D.DPH2.DEF_LEVELS_BYTE_LENGTH, 0)
            body = raw[dl_len:]
            if dph.get(D.DPH2.IS_COMPRESSED, True):
                body = D._decompress(body, codec, usize - dl_len)
            dentry, n_present = None, n
            if max_def > 0 and dl_len:
                dentry, n_present = _levels(raw[:dl_len], n)
            page_vals = body
        else:
            continue

        if enc == D.ENC_PLAIN and is_str:
            offs = D.byte_array_offsets(page_vals, n_present)
            if offs is None:
                return None              # no native walker: host path
            payloads.append((bytes(page_vals), offs))
            idx_parts.append(None)
        elif enc == D.ENC_PLAIN and is_bool:
            need = (n_present + 7) // 8
            if len(page_vals) < need:
                return None
            payloads.append(bytes(page_vals[:need]))
            idx_parts.append(None)
        elif enc == D.ENC_PLAIN:
            payloads.append(page_vals[:n_present * width])
            idx_parts.append(None)
        elif enc in (D.ENC_PLAIN_DICTIONARY, D.ENC_RLE_DICTIONARY):
            if dictionary is None:
                return None
            if len(page_vals) == 0:
                # zero present values / truncated page: degrade to the host
                # decoder like every other unsupported shape
                return None
            bw = page_vals[0]
            plan = RLE.parse_runs(bytes(page_vals[1:]), bw, n_present) \
                if n_present else RLE.parse_runs(b"", 0, 0)
            if plan is not None and n_present:
                idx_parts.append(("plan", plan))
            elif n_present:
                idx_parts.append(("np", D.decode_rle_bitpacked_hybrid(
                    page_vals[1:], bw, n_present).astype(np.int32)))
            else:
                idx_parts.append(("np", np.zeros(0, np.int32)))
            payloads.append(None)
        else:
            return None
        def_parts.append(dentry)
        ns.append(n)
        npres_l.append(n_present)
        decoded += n

    has_plain = any(p is not None for p in payloads)
    has_dict = any(i is not None for i in idx_parts)
    if has_plain and has_dict:
        return None                  # mixed-encoding chunk: host fallback
    n_total = int(sum(ns))
    valid = _assemble_valid(def_parts, ns, force_np=is_str)
    if has_dict:
        kind = "dict_str" if is_str else "dict"
        return (kind, phys, dictionary,
                [i for i in idx_parts if i is not None], valid, n_total)
    if is_str:
        # per-page (payload, offs) → one stream + global segment geometry
        base = 0
        starts_all, lens_all, bufs = [], [], []
        for payload_p, offs in payloads:
            k = offs.shape[0] - 1
            lens = offs[1:] - offs[:-1]
            starts_all.append(base + offs[:-1].astype(np.int64)
                              + 4 * np.arange(1, k + 1, dtype=np.int64))
            lens_all.append(lens)
            bufs.append(payload_p)
            base += len(payload_p)
        return ("plain_str", phys, None,
                (b"".join(bufs), np.concatenate(starts_all),
                 np.concatenate(lens_all)), valid, n_total)
    if is_bool:
        if len(payloads) > 1 and any(k % 8 for k in npres_l[:-1]):
            return None     # bit-misaligned page boundary: host path
        return ("plain_bool", phys, None, b"".join(payloads), valid,
                n_total)
    payload = b"".join(payloads)
    return ("plain", phys, None, payload, valid, n_total)


def _assemble_valid(def_parts, ns, force_np: bool):
    """Chunk-level validity from per-page level entries: None (no nulls),
    a host bool array, or ("plans", [(RunPlan|None, n)]) for device
    expansion."""
    if not any(d is not None for d in def_parts):
        return None
    if force_np or any(d is not None and d[0] == "np" for d in def_parts):
        from . import rle_device as RLE
        segs = []
        for d, k in zip(def_parts, ns):
            if d is None:
                segs.append(np.ones(k, bool))
            elif d[0] == "np":
                segs.append(d[1])
            else:
                segs.append(RLE.expand_np(d[1]) == 1)
        valid = np.concatenate(segs)
        return None if valid.all() else valid
    return ("plans", [(None if d is None else d[1], k)
                      for d, k in zip(def_parts, ns)])


def _u8_to_u32_flat(raw: jnp.ndarray) -> jnp.ndarray:
    """u8 [4k] → u32 [k] little-endian via wide-block strided slices —
    measured several times faster than the narrow-minor [k,4] bitcast on
    TPU (the relayout dominates; see xpack._u8_to_u32_rows).  Behind
    SRJT_PALLAS_TRANSPOSE the same combine runs as a blocked Pallas
    kernel (rowconv.xpallas.try_u8_to_u32) — bit-identical output."""
    from ..rowconv import xpallas
    k = raw.shape[0] // 4
    pad = (-raw.shape[0]) % 512
    b = jnp.pad(raw, (0, pad))
    w = xpallas.try_u8_to_u32(b)
    if w is not None:
        return w[:k]
    b = b.reshape(-1, 512)
    parts = [b[:, j::4].astype(jnp.uint32) for j in range(4)]
    w = (parts[0] | (parts[1] << 8) | (parts[2] << 16) | (parts[3] << 24))
    return w.reshape(-1)[:k]


@functools.partial(jax.jit, static_argnums=0)
def _device_plain_w(phys: int, words: jnp.ndarray,
                    valid: Optional[jnp.ndarray]):
    """u32 word payload [k*itemsize/4] → typed [k] (+ def-level
    expansion).  PLAIN fixed payloads are always 4-byte aligned, so the
    u8→u32 step happens on HOST as a free ``np.frombuffer`` view and the
    device decode collapses to bitcasts/reshapes (round 5 — the strided
    u8 lane extraction was the round-4 scan's cost center at ~9 GB/s)."""
    if phys == D.PT_DOUBLE:
        typed = words.reshape(-1, 2)       # IS the f64 bit-pair storage
    elif phys == D.PT_FLOAT:
        typed = jax.lax.bitcast_convert_type(words, jnp.float32)
    elif phys == D.PT_INT64:
        # bitcast packs the last axis LSW-first on the little-endian
        # backends — 2x the u64 shift/or assembly on chip (33.8 vs 18.4
        # GB/s measured round 5)
        typed = jax.lax.bitcast_convert_type(words.reshape(-1, 2),
                                             jnp.int64)
    else:
        typed = jax.lax.bitcast_convert_type(words, jnp.int32)
    if valid is None:
        return typed
    if typed.shape[0] == 0:
        shape = (valid.shape[0],) + typed.shape[1:]
        return jnp.zeros(shape, typed.dtype)
    pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0,
                   typed.shape[0] - 1)
    full = typed[pos]
    zero = jnp.zeros((), typed.dtype)
    if typed.ndim == 2:
        return jnp.where(valid[:, None], full, zero)
    return jnp.where(valid, full, zero)


@functools.partial(jax.jit, static_argnums=0)
def _device_plain(phys: int, raw: jnp.ndarray,
                  valid: Optional[jnp.ndarray]):
    """u8 payload [k*itemsize] → typed [k] (+ def-level expansion to the
    full row count when ``valid`` is given).

    FLOAT64 lands as u32 [n, 2] bit pairs (the Column invariant) — the
    decode is pure byte movement, exact on every backend."""
    if phys == D.PT_DOUBLE:
        typed = _u8_to_u32_flat(raw).reshape(-1, 2)         # [k, 2]
    elif phys == D.PT_FLOAT:
        typed = jax.lax.bitcast_convert_type(_u8_to_u32_flat(raw),
                                             jnp.float32)
    elif phys == D.PT_INT64:
        w = _u8_to_u32_flat(raw).reshape(-1, 2)
        typed = (w[:, 0].astype(jnp.uint64)
                 | (w[:, 1].astype(jnp.uint64) << 32)).astype(jnp.int64)
    else:
        typed = jax.lax.bitcast_convert_type(_u8_to_u32_flat(raw),
                                             jnp.int32)
    if valid is None:
        return typed
    if typed.shape[0] == 0:        # all-null column: nothing to gather
        shape = (valid.shape[0],) + typed.shape[1:]
        return jnp.zeros(shape, typed.dtype)
    # def-level expansion: present value i sits at the i-th valid slot
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    pos = jnp.clip(pos, 0, typed.shape[0] - 1)
    full = typed[pos]
    zero = jnp.zeros((), typed.dtype)
    if typed.ndim == 2:
        return jnp.where(valid[:, None], full, zero)
    return jnp.where(valid, full, zero)


@functools.partial(jax.jit, static_argnums=0)
def _device_dict(phys: int, dict_vals: jnp.ndarray, idx: jnp.ndarray,
                 valid: Optional[jnp.ndarray]):
    """Dictionary gather on device (+ def-level expansion)."""
    if valid is None:
        return dict_vals[idx]
    if idx.shape[0] == 0:          # all-null column: nothing to gather
        shape = (valid.shape[0],) + dict_vals.shape[1:]
        return jnp.zeros(shape, dict_vals.dtype)
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    pos = jnp.clip(pos, 0, idx.shape[0] - 1)
    full = dict_vals[idx[pos]]
    zero = jnp.zeros((), dict_vals.dtype)
    if full.ndim == 2:
        return jnp.where(valid[:, None], full, zero)
    return jnp.where(valid, full, zero)


@functools.partial(jax.jit, static_argnums=0)
def _device_flba_decimal(width: int, raw: jnp.ndarray,
                         valid: Optional[jnp.ndarray]):
    """FIXED_LEN_BYTE_ARRAY decimal payload (big-endian two's complement,
    ``width`` ≤ 16 bytes) → int64 [k, 2] (lo, hi) limb pairs on device —
    the DECIMAL128 Column payload — with sign extension and def-level
    expansion.  Mirrors the host oracle ``decode._be_decimal_to_lanes``."""
    b = raw.reshape(-1, width).astype(jnp.int64)          # BE bytes, [k, w]
    neg = b[:, 0] >= 128
    fill = jnp.where(neg, jnp.int64(0xFF), jnp.int64(0))

    def byte(i):                       # little-endian byte i of the value
        return b[:, width - 1 - i] if i < width else fill

    lo = byte(0)
    for i in range(1, 8):
        lo = lo | (byte(i) << (8 * i))
    hi = byte(8)
    for i in range(9, 16):
        hi = hi | (byte(i) << (8 * (i - 8)))
    typed = jnp.stack([lo, hi], axis=1)                   # [k, 2]
    if valid is None:
        return typed
    if typed.shape[0] == 0:
        return jnp.zeros((valid.shape[0], 2), jnp.int64)
    pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0,
                   typed.shape[0] - 1)
    return jnp.where(valid[:, None], typed[pos], jnp.int64(0))


@functools.partial(jax.jit, static_argnums=0)
def _device_bool(k: int, bits: jnp.ndarray,
                 valid: Optional[jnp.ndarray]):
    """BOOLEAN bit-unpack on device: packed LSB-first bits → u8 0/1 [k]
    (+ def-level expansion)."""
    b = bits[:(k + 7) // 8]
    vals = ((b[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1)
    vals = vals.reshape(-1)[:k].astype(jnp.uint8)
    if valid is None:
        return vals
    if k == 0:
        return jnp.zeros(valid.shape[0], jnp.uint8)
    pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0, k - 1)
    return jnp.where(valid, vals[pos], jnp.uint8(0))


def _upload_dict(phys: int, dictionary: np.ndarray, stager=None):
    """Typed dictionary page upload — a deferred slab Handle when a
    stager is given (the spec arg resolves after the file-wide flush)."""
    if phys == D.PT_DOUBLE:
        from ..utils import f64bits
        dictionary = f64bits.np_to_bits(dictionary)
    return staging.asarray(dictionary, stager)


def _valid_needs_np(parts) -> bool:
    return any(isinstance(p[4], np.ndarray) for p in parts)


def _valid_np_concat(parts):
    """Normalize all chunks' validity to one host bool array (or None)."""
    from . import rle_device as RLE
    if not any(p[4] is not None for p in parts):
        return None
    segs = []
    for p in parts:
        v = p[4]
        if v is None:
            segs.append(np.ones(p[5], bool))
        elif isinstance(v, np.ndarray):
            segs.append(v)
        else:
            for plan, k in v[1]:
                segs.append(np.ones(k, bool) if plan is None
                            else RLE.expand_np(plan) == 1)
    return np.concatenate(segs)


def _valid_device_concat(parts, stager=None):
    """Device validity: per-page def-level plans expand on chip (bit
    test), all-valid pages are ones.  None when no chunk has nulls."""
    from . import rle_device as RLE
    if not any(p[4] is not None for p in parts):
        return None
    segs = []
    for p in parts:
        v = p[4]
        if v is None:
            segs.append(jnp.ones(p[5], jnp.bool_))
        elif isinstance(v, np.ndarray):
            segs.append(_stage_wave(stager, v)[0])
        else:
            for plan, k in v[1]:
                segs.append(jnp.ones(k, jnp.bool_) if plan is None
                            else RLE.expand_device(plan, stager) == 1)
    return segs[0] if len(segs) == 1 else jnp.concatenate(segs)


def _idx_device_concat(entries, stager=None) -> jnp.ndarray:
    """Dictionary-index entries (("plan", RunPlan) | ("np", arr)) →
    one int32 device vector; run plans expand on chip."""
    from . import rle_device as RLE
    if all(e[0] == "plan" for e in entries):
        segs = [RLE.expand_device(e[1], stager) for e in entries]
        return segs[0] if len(segs) == 1 else jnp.concatenate(segs)
    return _stage_wave(stager, np.concatenate(
        [RLE.expand_np(e[1]) if e[0] == "plan" else e[1]
         for e in entries]).astype(np.int32))[0]


@functools.partial(jax.jit, static_argnums=(3,))
def _dict_str_rows(dict_lens: jnp.ndarray, idx: jnp.ndarray, valid,
                   g: int = 8):
    """Per-output-row dictionary entry + chars length (def-level expanded)
    and the packing stats — shared by the planning sync and the chars
    program so the two cannot drift."""
    from ..rowconv import xpack
    if valid is None:
        idx_full = idx
        lens_row = dict_lens[idx_full].astype(jnp.int32)
    else:
        pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0,
                       max(int(idx.shape[0]) - 1, 0))
        idx_full = jnp.where(valid, idx[pos] if idx.shape[0] else 0, 0)
        lens_row = jnp.where(valid, dict_lens[idx_full], 0).astype(
            jnp.int32)
    dst = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(lens_row)])
    return idx_full, lens_row, dst, xpack.dst_combine_stats(dst, g)


@functools.partial(jax.jit, static_argnums=(0,))
def _dict_str_chars(geom, dictmat: jnp.ndarray, dict_lens: jnp.ndarray,
                    idx: jnp.ndarray, valid):
    """Dictionary-string column body: padded dict rows [Ds, Lw] gathered
    per output row, then packed to the Arrow chars stream + offsets with
    the xpack combine — all on device, one program."""
    from ..rowconv import xpack, xpallas
    n, g, Bd, P, nwin, total = geom
    idx_full, lens_row, dst, _ = _dict_str_rows(dict_lens, idx, valid, g)
    piece = xpallas.try_gather_rows(dictmat, idx_full)
    if piece is None:
        piece = dictmat[idx_full]                   # [n, Lw] u32 rows
    chars = xpack._combine_to_stream(piece, lens_row, dst, n, g, Bd, P,
                                     nwin, total)
    return chars, dst


# --- per-file fused decode (round 5) ---------------------------------------
#
# The final per-column device programs join ONE jitted per-file program
# (the libcudf analog decodes a whole row group in one kernel wave): host
# staging + the small metadata programs (index expansion, packing stats)
# run eagerly per column, then every column's heavy decode body inlines
# into a single dispatch.  Builders take (statics, args) with the
# validity's presence encoded in statics so arg tuples stay None-free.

def _build_plain(statics, args):
    phys, dt, has_valid = statics
    raw, valid = (args[0], args[1] if has_valid else None)
    data = (_device_plain_w(phys, raw, valid)
            if raw.dtype == jnp.uint32 else _device_plain(phys, raw, valid))
    if dt.id != T.TypeId.FLOAT64 and data.dtype != jnp.dtype(dt.storage):
        data = data.astype(dt.storage)     # logical narrowing (date32 etc.)
    return data


def _build_flba(statics, args):
    width, dt, has_valid = statics
    raw, valid = (args[0], args[1] if has_valid else None)
    data = _device_flba_decimal(width, raw, valid)
    if dt.id == T.TypeId.DECIMAL128:
        return data
    return data[:, 0].astype(dt.storage)   # lo limb for <= 18 digits


def _build_bool(statics, args):
    k, has_valid = statics
    bits, valid = (args[0], args[1] if has_valid else None)
    return _device_bool(k, bits, valid)


def _build_dict(statics, args):
    phys, dt, is_flba, has_valid = statics
    dict_dev, idx = args[0], args[1]
    valid = args[2] if has_valid else None
    data = _device_dict(phys, dict_dev, idx, valid)
    if is_flba:
        if dt.id == T.TypeId.DECIMAL128:
            return data
        return data[:, 0].astype(dt.storage)
    if dt.id != T.TypeId.FLOAT64 and data.dtype != jnp.dtype(dt.storage):
        data = data.astype(dt.storage)
    return data


def _build_pstr(statics, args):
    from ..rowconv import xpack
    (geom,) = statics
    payload, st, ln, dst = args
    return xpack.segmented_gather(geom, payload, st, ln, dst)


def _build_dstr(statics, args):
    geom, has_valid = statics
    dictmat, dict_lens, idx = args[0], args[1], args[2]
    valid = args[3] if has_valid else None
    return _dict_str_chars(geom, dictmat, dict_lens, idx, valid)


def _build_dcode(statics, args):
    """Dictionary-string CODES column body: def-level expansion of the RLE
    index stream to one int32 code per output row (null slots hold 0) —
    the whole string decode when the scan keeps the dictionary
    (:class:`DictColumn` output; bytes materialize at the output boundary,
    if ever)."""
    (has_valid,) = statics
    idx = args[0]
    valid = args[1] if has_valid else None
    if valid is None:
        return idx.astype(jnp.int32)
    pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0,
                   max(int(idx.shape[0]) - 1, 0))
    filled = idx[pos] if idx.shape[0] else jnp.zeros_like(pos)
    return jnp.where(valid, filled, 0).astype(jnp.int32)


_BUILDERS = {"plain": _build_plain, "flba": _build_flba,
             "bool": _build_bool, "dict": _build_dict,
             "pstr": _build_pstr, "dstr": _build_dstr,
             "dcode": _build_dcode}


@functools.partial(jax.jit, static_argnums=(0,))
def _decode_file_jit(plan, arrays):
    """plan: tuple of (builder key, statics, n_args) per column; arrays:
    the flat device-arg tuple.  One dispatch decodes the whole file."""
    outs = []
    i = 0
    for key, statics, k in plan:
        outs.append(_BUILDERS[key](statics, arrays[i:i + k]))
        i += k
    return tuple(outs)


# per-builder donate pattern over the arg tuple (validity is always the
# LAST arg when present and is NEVER donated: the assemble closures keep
# it alive as the Column's validity).  Every other staged input — raw
# payload slabs, index vectors, dictionary pages, gather geometry — is
# consumed exactly once by the decode body, so its HBM can be handed to
# the outputs instead of doubling the scan footprint.
_DONATE = {"plain": (True, False), "flba": (True, False),
           "bool": (True, False), "dict": (True, True, False),
           "pstr": (True, True, True, True),
           "dstr": (True, True, True, False),
           "dcode": (True, False)}


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
def _decode_file_jit_donated(plan, donated, kept):
    """``_decode_file_jit`` with the single-use input buffers donated.
    plan entries carry (key, statics, donate mask); the flat args split
    into the donated tuple and the kept tuple (validity arrays)."""
    outs = []
    di = ki = 0
    for key, statics, mask in plan:
        args = []
        for m in mask:
            if m:
                args.append(donated[di])
                di += 1
            else:
                args.append(kept[ki])
                ki += 1
        outs.append(_BUILDERS[key](statics, tuple(args)))
    return tuple(outs)


def _dict_strings_enabled() -> bool:
    """SRJT_DICT_STRINGS: keep dictionary-encoded string columns as
    :class:`DictColumn` codes (default on; 0/off reverts to eager
    materialization for differential testing)."""
    return knobs.get("SRJT_DICT_STRINGS")


def _scan_dict_str(parts, jvalid, n_total: int, stager=None):
    """Dictionary-encoded strings fully on device (round 5).

    Host stages only metadata: the dict page's offsets recurrence (native
    walker) and the index run headers.  Device: one ``segmented_gather``
    strips the dict page's length prefixes to a contiguous chars stream,
    ``extract_group_windows`` widens it to a padded [D, Lw] row matrix,
    the RLE index runs expand to positions, and the xpack combine packs
    each row's dictionary entry into the Arrow chars stream + offsets.
    The only sync is ONE stacked packing-geometry pull — the libcudf
    dict-string decode analog (SURVEY §2.9)."""
    from ..rowconv import xpack, xpallas

    # merge per-chunk dictionaries (usually byte-identical)
    dicts = [p[2] for p in parts]
    base = dicts[0]
    same = all(d is base or (d[0] == base[0]
                             and np.array_equal(d[1], base[1]))
               for d in dicts[1:])
    merged = [base] if same else dicts
    payload = b"".join(d[0] for d in merged)
    pbase = 0
    starts_l, lens_l, entc = [], [], []
    for d in merged:
        offs = d[1]
        k = offs.shape[0] - 1
        lens = (offs[1:] - offs[:-1]).astype(np.int32)
        starts_l.append(pbase + offs[:-1].astype(np.int64)
                        + 4 * np.arange(1, k + 1, dtype=np.int64))
        lens_l.append(lens)
        entc.append(k)
        pbase += len(d[0])
    starts = np.concatenate(starts_l)
    lens = np.concatenate(lens_l)
    Ds = int(lens.shape[0])
    if Ds == 0:
        return None
    dict_offs = np.zeros(Ds + 1, np.int64)
    np.cumsum(lens, out=dict_offs[1:])
    if pbase >= 2**31 or int(dict_offs[-1]) >= 2**31:
        return None

    # indices (device), offset-rebased when dictionaries were merged
    idx_all = []
    off = 0
    for ci, p in enumerate(parts):
        part_idx = _idx_device_concat(p[3], stager)
        idx_all.append(part_idx + off if off else part_idx)
        if not same:
            off += entc[ci]
    idx = jnp.concatenate(idx_all) if len(idx_all) > 1 else idx_all[0]

    # device dict: strip prefixes → contiguous chars → padded row matrix
    total_chars = int(dict_offs[-1])
    Lmax = int(lens.max(initial=0))
    Lw = xpack._bucket(max(-(-Lmax // 4), 1), 4)
    if Lw > 512 and not _dict_strings_enabled():
        # the entry-width cap guards the padded [Ds, Lw] matrix of the
        # materializing path only — the codes path never builds it
        return xpack._reject("dict_str_entry_len", Lw=Lw)
    if total_chars:
        geom_sg = xpack.plan_segmented_gather(starts, lens, dict_offs)
        if geom_sg is None:
            return None
        jpay, jst, jln, jdo = _stage_wave(
            stager, np.frombuffer(payload, np.uint8),
            starts.astype(np.int32), lens, dict_offs.astype(np.int32))
        chars_dict = xpack.segmented_gather(geom_sg, jpay, jst, jln, jdo)
    else:
        chars_dict = jnp.zeros(0, jnp.uint8)

    if _dict_strings_enabled():
        # DICTIONARY FAST PATH (default): stop here.  The column stays as
        # int32 codes + the contiguous dictionary just built — no padded
        # row matrix, no packing-geometry sync, no chars stream.  Bytes
        # materialize lazily at the output boundary (DictColumn), and
        # predicates/joins/groupbys/sorts run on the codes.
        from ..utils import hostcache
        doffs32 = _stage_wave(stager, dict_offs.astype(np.int32))[0]
        hostcache.seed(doffs32, dict_offs.astype(np.int64))
        dict_col = Column(T.string, chars_dict, doffs32)
        metrics.count("plan.scan.dict_cols")
        statics = (jvalid is not None,)
        args = (idx,) + ((jvalid,) if jvalid is not None else ())

        def assemble_codes(out):
            return DictColumn(out, dict_col, jvalid)
        return ("dcode", statics, args, assemble_codes)

    # padded dictionary row matrix: Pallas row extraction (host offsets,
    # zero-padded rows — the combine masks each row to its length, so the
    # two builds yield byte-identical chars) or the XLA group windows
    dictmat = None
    if total_chars:
        xr = xpallas.try_extract_rows(chars_dict, dict_offs, Lw * 4)
        if xr is not None:
            dictmat = jax.lax.bitcast_convert_type(
                xr.reshape(Ds, Lw, 4), jnp.uint32)
    if dictmat is None:
        g = 8
        gidx = np.minimum(np.arange(0, Ds + g, g), Ds)
        span = int((dict_offs[gidx[1:]]
                    - dict_offs[gidx[:-1]]).max(initial=1))
        B = xpack._bucket(max(span, 64), 64)
        if B > (1 << 20):
            return xpack._reject("dict_str_slab", B=B)
        dictmat = xpack.extract_group_windows(
            chars_dict, _stage_wave(stager, dict_offs.astype(np.int32))[0],
            Ds, g, B, Lw)
    dict_lens = _stage_wave(stager, lens)[0]

    # packing geometry: one stacked sync per adaptive-g try (short dict
    # entries need LARGE groups or the window combine's P cap blows —
    # same adaptation as xpack.plan_from_rows)
    gs = (8, 32, 128)
    geom = None
    for g in gs:
        syncs.note_sync()
        stats = np.asarray(_dict_str_rows(dict_lens, idx, jvalid, g)[3])
        total, dspan, max_p = (int(x) for x in stats)
        if total >= 2**31:
            return None
        if total == 0:
            offs32 = jnp.zeros(n_total + 1, jnp.int32)
            col0 = Column(T.string, jnp.zeros(0, jnp.uint8), offs32,
                          jvalid)
            return ("const", (), (), lambda _out: col0)
        combine = xpack.plan_combine(total, dspan, max_p, "dict_str_caps",
                                     final=(g == gs[-1]))
        if combine is not None:
            Bd, P, nwin = combine
            geom = (n_total, g, Bd, P, nwin, total)
            break
    if geom is None:
        return None
    statics = (geom, jvalid is not None)
    args = (dictmat, dict_lens, idx) + ((jvalid,) if jvalid is not None
                                        else ())

    def assemble(out):
        chars, dst = out
        return Column(T.string, chars, dst, jvalid)
    return ("dstr", statics, args, assemble)


def scan_column_device(file_bytes: bytes, chunks, leaf) -> Optional[Column]:
    """All row groups of one column via the device path; None → fall back.
    Eager form of :func:`stage_column_device` (single-column callers)."""
    spec = stage_column_device(file_bytes, chunks, leaf)
    if spec is None:
        return None
    key, statics, args, assemble = spec
    if key == "const":
        return assemble(None)
    return assemble(_BUILDERS[key](statics, _resolve_args(args)))


def _walk_column(file_bytes: bytes, chunks, leaf):
    """Host page walk for every chunk of one column — pure host work (no
    device calls), the producer half of the staged scan pipeline.
    None → host fallback."""
    parts = []
    for chunk in chunks:
        part = _walk_chunk_raw(file_bytes, chunk, leaf.max_def, leaf.max_rep,
                               leaf.type_len or 0)
        if part is None:
            return None
        parts.append(part)
    return parts


def stage_column_device(file_bytes: bytes, chunks, leaf, stager=None):
    """Host staging for one column → deferred decode spec
    (key, statics, device-arg tuple, assemble) or None (host fallback).
    The heavy decode body runs later — alone (scan_column_device) or
    inlined into the per-file fused program (_decode_file_jit).  With a
    ``staging.SlabStager`` the raw page buffers queue as slab handles
    (resolved by the caller after the file-wide flush)."""
    parts = _walk_column(file_bytes, chunks, leaf)
    if parts is None:
        return None
    return _stage_column_parts(parts, leaf, stager)


def _stage_column_parts(parts, leaf, stager=None):
    """Device staging from walked raw parts (the consumer half)."""
    kinds = {p[0] for p in parts}
    physes = {p[1] for p in parts}
    if len(kinds) > 1 or len(physes) > 1:
        return None
    kind, phys = parts[0][0], parts[0][1]
    dt = leaf.logical_dtype()
    if dt.id == T.TypeId.LIST:
        return None
    is_flba = phys == D.PT_FIXED_LEN_BYTE_ARRAY
    if is_flba and not dt.is_decimal:
        return None   # non-decimal fixed-size binary (UUIDs): host path
    if kind in ("plain_str", "dict_str") and dt.id != T.TypeId.STRING:
        return None   # BYTE_ARRAY decimals etc.: host path

    n_total = int(sum(p[5] for p in parts))
    if kind == "plain_str":
        # the native offsets walker scatters by validity on HOST — np mask
        valid_np = _valid_np_concat(parts)
        jvalid = (None if valid_np is None
                  else _stage_wave(stager, valid_np)[0])
    else:
        # def levels expand ON DEVICE (bit test over the run plans)
        valid_np = None
        jvalid = _valid_device_concat(parts, stager)
    hv = jvalid is not None
    vtail = (jvalid,) if hv else ()

    if kind == "dict_str":
        return _scan_dict_str(parts, jvalid, n_total, stager)

    if kind == "plain_str":
        # strings fully on device: the char bytes never round through a
        # host loop — prefixes stripped by one segmented gather (the same
        # slab/roll machinery as the JCUDF transcode)
        from ..rowconv import xpack
        from ..utils import hostcache
        base = 0
        bufs, starts, lens = [], [], []
        for p in parts:
            payload_p, st, ln = p[3]
            bufs.append(payload_p)
            starts.append(st + base)
            lens.append(ln)
            base += len(payload_p)
        payload = b"".join(bufs)
        st = np.concatenate(starts) if starts else np.zeros(0, np.int64)
        ln = np.concatenate(lens) if lens else np.zeros(0, np.int32)
        dst = np.zeros(ln.shape[0] + 1, dtype=np.int64)
        np.cumsum(ln, out=dst[1:])
        geom = None
        if ln.shape[0] == 0 or dst[-1] == 0:
            chars = jnp.zeros(0, jnp.uint8)
        else:
            # the gather works in int32 positions; a concatenated multi-
            # chunk payload approaching 2 GiB would wrap the casts below
            # and corrupt the decode — fall back to the host path instead
            # (the native walker only guards per-page char totals)
            if (base >= 2**31 or int(dst[-1]) >= 2**31
                    or int(st.max(initial=0)) >= 2**31):
                return None
            geom = xpack.plan_segmented_gather(st, ln, dst)
            if geom is None:
                return None
            ln = ln.astype(np.int32)
            chars = None           # deferred: the fused segmented gather
        if valid_np is None:
            row_lens = ln
        else:
            row_lens = np.zeros(n_total, dtype=np.int64)
            row_lens[valid_np] = ln
        offs_np = np.zeros(n_total + 1, dtype=np.int64)
        np.cumsum(row_lens, out=offs_np[1:])
        joffs = jnp.asarray(offs_np.astype(np.int32))
        hostcache.seed(joffs, offs_np)
        if chars is not None:      # degenerate empty column: no jit body
            col0 = Column(T.string, chars, joffs, jvalid)
            return ("const", (), (), lambda _out: col0)
        # raw chars + gather geometry stay slab HANDLES until the caller's
        # file-wide flush — the whole file's strings ride a few transfers
        return ("pstr", (geom,),
                (staging.asarray(np.frombuffer(payload, np.uint8), stager),
                 staging.asarray(st.astype(np.int32), stager),
                 staging.asarray(ln, stager),
                 staging.asarray(dst.astype(np.int32), stager)),
                lambda out: Column(T.string, out, joffs, jvalid))

    if kind == "plain_bool":
        def _npres(p):
            v = p[4]
            if v is None:
                return p[5]
            if isinstance(v, np.ndarray):
                return int(v.sum())
            from . import rle_device as RLE
            return sum(k if plan is None else RLE.present_count(plan, 1)
                       for plan, k in v[1])
        npresent = [_npres(p) for p in parts]
        if len(parts) > 1 and any(k % 8 for k in npresent[:-1]):
            return None   # bit-misaligned chunk boundary: host path
        payload = b"".join(p[3] for p in parts)
        k = int(sum(npresent))
        bits = staging.asarray(np.frombuffer(payload, np.uint8), stager)
        return ("bool", (k, hv), (bits,) + vtail,
                lambda out: Column(T.bool8, out, validity=jvalid))

    if kind == "plain":
        payload = b"".join(p[3] for p in parts)
        if is_flba:
            raw = staging.asarray(np.frombuffer(payload, dtype=np.uint8),
                                  stager)
            return ("flba", (leaf.type_len, dt, hv), (raw,) + vtail,
                    lambda out: Column(dt, out, validity=jvalid))
        # 4/8-byte payloads are 4-aligned: the u8→u32 step is a FREE host
        # view, and the device decode is bitcasts/reshapes only
        raw = staging.asarray(np.frombuffer(payload, dtype=np.uint32)
                              if len(payload) % 4 == 0
                              else np.frombuffer(payload, dtype=np.uint8),
                              stager)
        return ("plain", (phys, dt, hv), (raw,) + vtail,
                lambda out: Column(dt, out, validity=jvalid))
    else:
        dicts = [p[2] for p in parts]
        base = dicts[0]
        if any(d is not base and not np.array_equal(d, base)
               for d in dicts[1:]):
            # per-row-group dictionaries differ: rebase indices (the
            # per-chunk run plans expand on device, offset added there)
            idx_all = []
            offset = 0
            merged = np.concatenate(dicts)
            for p in parts:
                part_idx = _idx_device_concat(p[3], stager)
                idx_all.append(part_idx + offset if offset else part_idx)
                offset += p[2].shape[0]
            dict_dev = _upload_dict(phys, merged, stager)
            idx = jnp.concatenate(idx_all) if len(idx_all) > 1 \
                else idx_all[0]
        else:
            dict_dev = _upload_dict(phys, base, stager)
            idx_all = [_idx_device_concat(p[3], stager) for p in parts]
            idx = jnp.concatenate(idx_all) if len(idx_all) > 1 \
                else idx_all[0]
        return ("dict", (phys, dt, is_flba, hv),
                (dict_dev, idx) + vtail,
                lambda out: Column(dt, out, validity=jvalid))


def _chunk_minmax(chunk, leaf):
    """(min, max) bounds from a column chunk's footer Statistics, or None
    when the stats are absent/undecodable.  INT32/INT64 decode to ints,
    BYTE_ARRAY returns raw bytes bounds (unsigned lexicographic — the
    UTF8 logical order), FLBA DECIMAL decodes big-endian two's-complement
    to the unscaled int the runtime predicate also compares against.

    BYTE_ARRAY/FLBA read ONLY the logical ``min_value``/``max_value``
    fields — the deprecated MIN/MAX pair used signed (or undefined) byte
    order and cannot be trusted for these types.  Writers may truncate
    the logical bounds (min rounded down, max rounded up): they remain
    valid BOUNDS, which is all a disjointness test needs."""
    md = chunk.get(D.CC.META_DATA)
    st = md.get(D.CMD.STATISTICS)
    if st is None:
        return None
    phys = leaf.phys
    if phys in (D.PT_INT32, D.PT_INT64):
        fmt, size = ("<i", 4) if phys == D.PT_INT32 else ("<q", 8)

        def dec(v):
            # explicit None check: b"\x00..." is a perfectly valid
            # (falsy-looking) PLAIN-encoded bound
            if v is None or not isinstance(v, (bytes, bytearray)) \
                    or len(v) != size:
                return None
            return _struct.unpack(fmt, bytes(v))[0]

        mn = dec(st.get(D.ST.MIN_VALUE))
        if mn is None:
            mn = dec(st.get(D.ST.MIN))
        mx = dec(st.get(D.ST.MAX_VALUE))
        if mx is None:
            mx = dec(st.get(D.ST.MAX))
    elif phys == D.PT_BYTE_ARRAY:
        mn = st.get(D.ST.MIN_VALUE)
        mx = st.get(D.ST.MAX_VALUE)
        mn = bytes(mn) if isinstance(mn, (bytes, bytearray)) else None
        mx = bytes(mx) if isinstance(mx, (bytes, bytearray)) else None
    elif phys == D.PT_FIXED_LEN_BYTE_ARRAY:
        try:
            if not leaf.logical_dtype().is_decimal:
                return None
        except Exception:
            return None
        width = leaf.type_len

        def dec(v):
            if not isinstance(v, (bytes, bytearray)) \
                    or (width and len(v) != width):
                return None
            return int.from_bytes(bytes(v), "big", signed=True)

        mn = dec(st.get(D.ST.MIN_VALUE))
        mx = dec(st.get(D.ST.MAX_VALUE))
    else:
        return None
    if mn is None or mx is None:
        return None
    return mn, mx


def _group_disjoint(mn, mx, op: str, val) -> bool:
    """True when NO value in [mn, mx] can satisfy ``col <op> val`` — the
    row group provably contains no matching rows.  Works for any totally
    ordered bound type (int bounds vs int literal, bytes bounds vs bytes
    literal).  Null rows need no consideration: planner predicates fail
    nulls, and parquet min/max statistics ignore them."""
    if op == "eq":
        return val < mn or val > mx
    if op == "lt":
        return mn >= val
    if op == "le":
        return mn > val
    if op == "gt":
        return mx <= val
    if op == "ge":
        return mx < val
    return False


def _prune_row_groups(groups_list, leaves, names, conds):
    """Indices of row groups that may contain matching rows.  ``conds``
    is a list of ``(column_name, op, value)`` conjuncts with int or bytes
    values (planner contract: ALL must hold, so any single disjoint
    conjunct drops the group).  Groups without usable statistics — or
    whose statistic type does not match the literal type — are always
    kept."""
    name_to_idx = {n: i for i, n in enumerate(names)}
    kept = []
    for gi, rg in enumerate(groups_list):
        chunks = rg.get(D.RG.COLUMNS).values
        drop = False
        for cname, op, val in conds:
            ci = name_to_idx.get(cname)
            if ci is None:
                continue
            mm = _chunk_minmax(chunks[ci], leaves[ci])
            if mm is None:
                continue
            if isinstance(val, bytes) != isinstance(mm[0], bytes):
                continue    # literal/statistic type mismatch: keep group
            if _group_disjoint(mm[0], mm[1], op, val):
                drop = True
                break
        if not drop:
            kept.append(gi)
    return kept


def _span_overlap_ms(a_spans, b_spans) -> float:
    """Σ pairwise intersection of two interval lists, in milliseconds —
    how long the host page walk ran concurrently with device staging."""
    total = 0.0
    for a0, a1 in a_spans:
        for b0, b1 in b_spans:
            total += max(0.0, min(a1, b1) - max(a0, b0))
    return total * 1000.0


@traced("parquet_scan_table_device")
def scan_table(file_bytes: bytes,
               columns: Optional[list[str]] = None,
               row_groups: Optional[list[int]] = None,
               rowgroup_predicate=None,
               row_predicate=None) -> Table:
    """``decode.read_table`` with the device fast path per column.

    All device-path columns decode in ONE fused jitted program per file
    (``_decode_file_jit``; ``SRJT_FUSED_SCAN=0`` reverts to per-column
    dispatches); host-fallback columns batch through ``decode.read_table``
    as before.  Raw page buffers upload through the slab stager
    (``SRJT_STAGE_SLABS``) — a few large coalesced transfers per file —
    and, under ``SRJT_STAGE_PIPELINE``, the host page walk of column k+1
    overlaps the device staging of column k (a producer thread feeds a
    bounded queue; ``parquet.stage.overlap`` flight events account the
    concurrency).  ``SRJT_SCAN_DONATE`` donates the single-use input
    slabs to the fused decode so the raw bytes don't double the scan's
    HBM footprint.

    ``row_groups`` selects row groups by index (None = all);
    ``rowgroup_predicate`` is a list of ``(column, op, int_value)``
    conjuncts (op in eq/lt/le/gt/ge) tested against footer statistics —
    row groups provably containing no matching rows are skipped BEFORE
    any page decode (the planner's filter-pushdown target; counters
    ``plan.scan.rowgroups_pruned`` / ``plan.scan.rowgroups_kept``).
    ``row_predicate`` (same conjunct shape, bytes literals allowed) goes
    further under ``SRJT_FUSED_FILTER``: supported conjuncts evaluate on
    the walked RAW parts — once per dictionary entry on dict columns —
    and prune rows before anything uploads or decodes (``parquet.
    rowfilter``).  The result table carries ``fused_filter_complete``
    so the planner knows whether a re-apply is still needed."""
    import os
    meta = parse_struct(extract_footer_bytes(file_bytes))
    leaves = D._leaf_schema_elements(meta)
    names = [leaf.name for leaf in leaves]
    want = list(range(len(leaves))) if columns is None else [
        names.index(c) for c in columns]
    groups = meta.get(D.FMD.ROW_GROUPS)
    groups_list = list(groups.values)
    kept = (list(range(len(groups_list))) if row_groups is None
            else sorted(set(row_groups)))
    if rowgroup_predicate:
        stat_kept = set(_prune_row_groups(groups_list, leaves, names,
                                          rowgroup_predicate))
        pruned = [gi for gi in kept if gi not in stat_kept]
        kept = [gi for gi in kept if gi in stat_kept]
        if metrics.recording():
            metrics.count("plan.scan.rowgroups_pruned", len(pruned))
            metrics.count("plan.scan.rowgroups_kept", len(kept))
        metrics.profile_op("scan.prune", rowgroups_pruned=len(pruned),
                           rowgroups_kept=len(kept))
    selecting = len(kept) < len(groups_list)
    if not kept:
        # every row group pruned: zero-row table via the host assembler
        return D.read_table(
            file_bytes, row_groups=[],
            columns=None if columns is None else [names[i] for i in want])
    chunk_lists = {i: [] for i in want}
    for gi in kept:
        chunks = groups_list[gi].get(D.RG.COLUMNS).values
        for i in want:
            chunk_lists[i].append(chunks[i])

    fused = knobs.get("SRJT_FUSED_SCAN")
    stager = staging.SlabStager() if staging.enabled() else None
    fallback: list[int] = []
    by_index: dict[int, Column] = {}
    deferred: list[tuple] = []          # (col index, key, statics, args,
    #                                      assemble)
    filter_state = None                 # (conds, complete) once pruned

    def _dispatch(i, spec):
        if spec is None:
            fallback.append(i)
            return
        key, statics, args, assemble = spec
        if key == "const":
            by_index[i] = assemble(None)
        elif fused:
            deferred.append((i, key, statics, args, assemble))
        else:
            by_index[i] = assemble(
                _BUILDERS[key](statics, _resolve_args(args)))

    use_filter = bool(row_predicate) and bool(knobs.get("SRJT_FUSED_FILTER"))
    pipelined = (stager is not None and not use_filter
                 and bool(knobs.get("SRJT_STAGE_PIPELINE"))
                 and len(want) > 1)
    if use_filter:
        # fused scan→filter: the predicate needs every wanted column's
        # walked parts before anything uploads; a host-fallback column
        # would re-read the file unpruned, so any fallback aborts the
        # prune (the planner re-applies the full mask as before)
        from . import rowfilter
        walked = {i: _walk_column(file_bytes, chunk_lists[i], leaves[i])
                  for i in want}
        if all(walked[i] is not None for i in want):
            pruned = rowfilter.apply(row_predicate, walked, leaves, names,
                                     want)
            if pruned is not None:
                walked, complete, n_kept = pruned
                filter_state = (complete,)
                flight.record("parquet.rowfilter", kept=n_kept,
                              complete=complete)
                if metrics.recording():
                    metrics.count("parquet.rowfilter.fused_scans")
                    metrics.count("parquet.rowfilter.rows_kept", n_kept)
        for i in want:
            _dispatch(i, None if walked[i] is None else
                      _stage_column_parts(walked[i], leaves[i], stager))
    elif pipelined:
        import queue as _qmod
        import threading
        import time
        depth = max(1, int(knobs.get("SRJT_STAGE_PIPELINE_DEPTH") or 2))
        ch: _qmod.Queue = _qmod.Queue(maxsize=depth)
        walk_spans: list[tuple[float, float]] = []

        def _producer():
            try:
                for i in want:
                    t0 = time.perf_counter()
                    parts = _walk_column(file_bytes, chunk_lists[i],
                                         leaves[i])
                    walk_spans.append((t0, time.perf_counter()))
                    ch.put((i, parts))
            except BaseException as exc:   # re-raised by the consumer
                ch.put((None, exc))

        th = threading.Thread(target=_producer, name="srjt-scan-walk",
                              daemon=True)
        stage_spans: list[tuple[float, float]] = []
        th.start()
        try:
            for _ in want:
                i, parts = ch.get()
                if i is None:
                    raise parts
                t0 = time.perf_counter()
                spec = (None if parts is None else
                        _stage_column_parts(parts, leaves[i], stager))
                stage_spans.append((t0, time.perf_counter()))
                _dispatch(i, spec)
        finally:
            # never leave the producer blocked on a bounded put
            while th.is_alive():
                try:
                    ch.get_nowait()
                except _qmod.Empty:
                    th.join(0.05)
            th.join()
        overlap_ms = _span_overlap_ms(walk_spans, stage_spans)
        flight.record("parquet.stage.overlap",
                      overlap_ms=round(overlap_ms, 3), columns=len(want))
        if metrics.recording():
            metrics.count("parquet.stage.overlap_ms",
                          int(round(overlap_ms)))
    else:
        for i in want:
            _dispatch(i, stage_column_device(file_bytes, chunk_lists[i],
                                             leaves[i], stager))
    if stager is not None:
        stager.flush()                 # file-wide slab wave (async)
    if deferred:
        deferred = [(i, key, statics, _resolve_args(args), assemble)
                    for i, key, statics, args, assemble in deferred]
        # admission for the fused scan's staged input slabs (the decode
        # outputs are the table itself — not ephemeral — so only the raw
        # page/dictionary buffers are reserved)
        from ..memory import arena
        scan_bytes = sum(int(getattr(a, "nbytes", 0) or 0)
                         for _, _, _, args, _ in deferred for a in args)
        with arena.reserve(scan_bytes, tag="parquet.scan"):
            if staging.donate_enabled():
                plan = tuple((key, statics, _DONATE[key][:len(args)])
                             for _, key, statics, args, _ in deferred)
                don, keep = [], []
                for _, key, _, args, _ in deferred:
                    for a, m in zip(args, _DONATE[key][:len(args)]):
                        (don if m else keep).append(a)
                don_bytes = sum(int(getattr(a, "nbytes", 0) or 0)
                                for a in don)
                flight.record("parquet.scan.donate", buffers=len(don),
                              bytes=don_bytes)
                if metrics.recording():
                    metrics.count("parquet.scan.donated_bytes", don_bytes)
                import warnings
                with warnings.catch_warnings():
                    # CPU PJRT ignores donation with a warning — forcing
                    # the knob there is a test mode, keep it quiet
                    warnings.filterwarnings("ignore",
                                            message=".*[Dd]onat.*")
                    outs = _decode_file_jit_donated(plan, tuple(don),
                                                    tuple(keep))
            else:
                plan = tuple((key, statics, len(args))
                             for _, key, statics, args, _ in deferred)
                flat = tuple(a for _, _, _, args, _ in deferred
                             for a in args)
                outs = _decode_file_jit(plan, flat)
        for (i, _, _, _, assemble), out in zip(deferred, outs):
            by_index[i] = assemble(out)
    if metrics.recording():
        # device/host split per scan — the fast-path coverage counter
        metrics.count("parquet.device_cols", len(want) - len(fallback))
        metrics.count("parquet.host_fallback_cols", len(fallback))
        metrics.annotate(device_cols=len(want) - len(fallback),
                         fallback_cols=len(fallback))
    if fallback:
        host = D.read_table(file_bytes,
                            columns=[names[i] for i in fallback],
                            row_groups=kept if selecting else None)
        for j, i in enumerate(fallback):
            by_index[i] = host[j]
    out = Table([by_index[i] for i in want])
    metrics.profile_op("scan", rows_out=out.num_rows, cols=len(want),
                       rowgroups=len(kept), fallback_cols=len(fallback))
    if filter_state is not None:
        # the planner checks this to skip the redundant re-apply: True
        # means every conjunct was evaluated and pruned at scan time
        out.fused_filter_complete = filter_state[0]
    # fused-scan outputs are evictable residents (HBM-arena follow-on):
    # under budget pressure the decoded columns host-spill IN PLACE and
    # fault back bit-exactly on their next op touch (no-op when the arena
    # is off — register_table gates on budget.active())
    from ..memory import spill as mspill
    mspill.register_table(out, "parquet.scan_out")
    return out


# API mirror: callers swap `from ..parquet import decode` for this module
read_table = scan_table
