"""Device-side Parquet scan (BASELINE config #2 — "GB/s columnar scan").

Round 2 decoded every page byte in host NumPy loops and uploaded finished
columns (`decode.py`); the reference's scan is a GPU engine (libcudf decode
built into the artifact, ``build-libcudf.xml:48-64``).  This module moves
the byte-level decode ONTO the chip for the hot shapes:

  host (staging, like the reference's host buffers):
      footer/thrift parse → page walk → decompression (native snappy in
      ``libsrjt.so``) → concatenate raw PLAIN payloads / host-decode tiny
      run-length metadata (def levels, dictionary indices' RLE headers)
  device (one jitted program per column):
      PLAIN bitcast u8 → typed lanes  (f64 → u32 bit pairs, the Column
      invariant — no f64 arithmetic anywhere)
      dictionary index gather          (typed dict values resident)
      def-level expansion              (cumsum positions + masked gather)

Round 4 extends the device tier to PLAIN strings (the native
``srjt_byte_array_offsets`` walker stages the sequential offsets
recurrence; ONE device segmented gather strips the length prefixes —
``rowconv/xpack.segmented_gather``) and BOOLEAN bit-unpack.  Columns
outside the fast path (dictionary strings, INT96, DELTA_*, nested) fall
back to the host decoder transparently — correctness first, the fast path
covers the scan-heavy analytics shapes.

``scan_table`` mirrors ``decode.read_table`` and is differentially tested
against it (tests/test_device_scan.py).
"""

from __future__ import annotations

import functools
import struct as _struct
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from ..utils.tracing import traced
from . import decode as D
from .footer import extract_footer_bytes
from .thrift import parse_struct

_PLAIN_PHYS = {D.PT_INT32: 4, D.PT_INT64: 8, D.PT_FLOAT: 4, D.PT_DOUBLE: 8}


def _walk_chunk_raw(file_bytes: bytes, chunk, max_def: int, max_rep: int,
                    type_len: int = 0):
    """Page walk that KEEPS raw PLAIN payload bytes (or dictionary+indices)
    instead of decoding values.  Returns None when the chunk needs the
    host decoder (unsupported physical type / encoding / nesting).

    FIXED_LEN_BYTE_ARRAY chunks (width ≤ 16 — the parquet DECIMAL carrier)
    are fixed-width too: their payload is kept raw and assembled into
    decimal limbs on device."""
    md = chunk.get(D.CC.META_DATA)
    phys = md.get(D.CMD.TYPE)
    is_flba = (phys == D.PT_FIXED_LEN_BYTE_ARRAY
               and 0 < type_len <= 16)
    is_str = phys == D.PT_BYTE_ARRAY
    is_bool = phys == D.PT_BOOLEAN
    if (phys not in _PLAIN_PHYS and not (is_flba or is_str or is_bool)) \
            or max_rep > 0:
        return None
    width = (type_len if is_flba
             else _PLAIN_PHYS.get(phys, 0))
    codec = md.get(D.CMD.CODEC, 0)
    num_values = md.get(D.CMD.NUM_VALUES)
    start = md.get(D.CMD.DATA_PAGE_OFFSET)
    dict_off = md.get(D.CMD.DICT_PAGE_OFFSET)
    if dict_off is not None and dict_off < start:
        start = dict_off
    total = md.get(D.CMD.TOTAL_COMPRESSED_SIZE)
    stream = D._PageStream(file_bytes[start:start + total], codec)

    dictionary = None
    payloads, idx_parts, def_parts, ns = [], [], [], []
    decoded = 0
    while decoded < num_values:
        header, raw = stream.next_page()
        ptype = header.get(D.PH.TYPE)
        usize = header.get(D.PH.UNCOMPRESSED_SIZE)
        if ptype == D.PAGE_DICTIONARY:
            if is_str or is_bool:
                # dictionary-encoded strings: host path (round-4 device
                # scope is the PLAIN string stream)
                return None
            dph = header.get(D.PH.DICT_PAGE)
            data = D._decompress(raw, codec, usize)
            m = dph.get(D.DPH.NUM_VALUES)
            if is_flba:   # fixed-width byte strings -> host limb decode
                dictionary = D._be_decimal_to_lanes(
                    np.frombuffer(data, np.uint8, m * type_len), type_len)
            else:
                dictionary = np.frombuffer(
                    data, dtype=D._PHYS_NP[phys], count=m)
            continue
        if ptype == D.PAGE_DATA:
            dph = header.get(D.PH.DATA_PAGE)
            n = dph.get(D.DPH.NUM_VALUES)
            enc = dph.get(D.DPH.ENCODING)
            data = D._decompress(raw, codec, usize)
            pos = 0
            defs = None
            if max_def > 0:
                (ln,) = _struct.unpack_from("<I", data, pos)
                pos += 4
                defs = D.decode_rle_bitpacked_hybrid(
                    data[pos:pos + ln], D._bit_width(max_def), n)
                pos += ln
            page_vals = data[pos:]
        elif ptype == D.PAGE_DATA_V2:
            dph = header.get(D.PH.DATA_PAGE_V2)
            n = dph.get(D.DPH2.NUM_VALUES)
            enc = dph.get(D.DPH2.ENCODING)
            dl_len = dph.get(D.DPH2.DEF_LEVELS_BYTE_LENGTH, 0)
            body = raw[dl_len:]
            if dph.get(D.DPH2.IS_COMPRESSED, True):
                body = D._decompress(body, codec, usize - dl_len)
            defs = None
            if max_def > 0 and dl_len:
                defs = D.decode_rle_bitpacked_hybrid(
                    raw[:dl_len], D._bit_width(max_def), n)
            page_vals = body
        else:
            continue

        n_present = n if defs is None else int((defs == max_def).sum())
        if enc == D.ENC_PLAIN and is_str:
            offs = D.byte_array_offsets(page_vals, n_present)
            if offs is None:
                return None              # no native walker: host path
            payloads.append((bytes(page_vals), offs))
            idx_parts.append(None)
        elif enc == D.ENC_PLAIN and is_bool:
            need = (n_present + 7) // 8
            if len(page_vals) < need:
                return None
            payloads.append(bytes(page_vals[:need]))
            idx_parts.append(None)
        elif enc == D.ENC_PLAIN:
            payloads.append(page_vals[:n_present * width])
            idx_parts.append(None)
        elif enc in (D.ENC_PLAIN_DICTIONARY, D.ENC_RLE_DICTIONARY):
            if dictionary is None:
                return None
            if len(page_vals) == 0:
                # zero present values / truncated page: degrade to the host
                # decoder like every other unsupported shape
                return None
            bw = page_vals[0]
            idx_parts.append(D.decode_rle_bitpacked_hybrid(
                page_vals[1:], bw, n_present).astype(np.int32))
            payloads.append(None)
        else:
            return None
        def_parts.append(defs)
        ns.append(n)
        decoded += n

    has_plain = any(p is not None for p in payloads)
    has_dict = any(i is not None for i in idx_parts)
    if has_plain and has_dict:
        return None                  # mixed-encoding chunk: host fallback
    n_total = int(sum(ns))
    valid = None
    if max_def > 0 and any(d is not None for d in def_parts):
        valid = np.concatenate(
            [d == max_def if d is not None else np.ones(k, bool)
             for d, k in zip(def_parts, ns)])
        if valid.all():
            valid = None
    if has_dict:
        return ("dict", phys, dictionary, np.concatenate(idx_parts),
                valid, n_total)
    if is_str:
        # per-page (payload, offs) → one stream + global segment geometry
        base = 0
        starts_all, lens_all, bufs = [], [], []
        for payload_p, offs in payloads:
            k = offs.shape[0] - 1
            lens = offs[1:] - offs[:-1]
            starts_all.append(base + offs[:-1].astype(np.int64)
                              + 4 * np.arange(1, k + 1, dtype=np.int64))
            lens_all.append(lens)
            bufs.append(payload_p)
            base += len(payload_p)
        return ("plain_str", phys, None,
                (b"".join(bufs), np.concatenate(starts_all),
                 np.concatenate(lens_all)), valid, n_total)
    if is_bool:
        if len(payloads) > 1 and any(
                (k if d is None else int((d == max_def).sum())) % 8
                for d, k in list(zip(def_parts, ns))[:-1]):
            return None     # bit-misaligned page boundary: host path
        return ("plain_bool", phys, None, b"".join(payloads), valid,
                n_total)
    payload = b"".join(payloads)
    return ("plain", phys, None, payload, valid, n_total)


def _u8_to_u32_flat(raw: jnp.ndarray) -> jnp.ndarray:
    """u8 [4k] → u32 [k] little-endian via wide-block strided slices —
    measured several times faster than the narrow-minor [k,4] bitcast on
    TPU (the relayout dominates; see xpack._u8_to_u32_rows)."""
    k = raw.shape[0] // 4
    pad = (-raw.shape[0]) % 512
    b = jnp.pad(raw, (0, pad)).reshape(-1, 512)
    parts = [b[:, j::4].astype(jnp.uint32) for j in range(4)]
    w = (parts[0] | (parts[1] << 8) | (parts[2] << 16) | (parts[3] << 24))
    return w.reshape(-1)[:k]


@functools.partial(jax.jit, static_argnums=0)
def _device_plain(phys: int, raw: jnp.ndarray,
                  valid: Optional[jnp.ndarray]):
    """u8 payload [k*itemsize] → typed [k] (+ def-level expansion to the
    full row count when ``valid`` is given).

    FLOAT64 lands as u32 [n, 2] bit pairs (the Column invariant) — the
    decode is pure byte movement, exact on every backend."""
    if phys == D.PT_DOUBLE:
        typed = _u8_to_u32_flat(raw).reshape(-1, 2)         # [k, 2]
    elif phys == D.PT_FLOAT:
        typed = jax.lax.bitcast_convert_type(_u8_to_u32_flat(raw),
                                             jnp.float32)
    elif phys == D.PT_INT64:
        w = _u8_to_u32_flat(raw).reshape(-1, 2)
        typed = (w[:, 0].astype(jnp.uint64)
                 | (w[:, 1].astype(jnp.uint64) << 32)).astype(jnp.int64)
    else:
        typed = jax.lax.bitcast_convert_type(_u8_to_u32_flat(raw),
                                             jnp.int32)
    if valid is None:
        return typed
    if typed.shape[0] == 0:        # all-null column: nothing to gather
        shape = (valid.shape[0],) + typed.shape[1:]
        return jnp.zeros(shape, typed.dtype)
    # def-level expansion: present value i sits at the i-th valid slot
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    pos = jnp.clip(pos, 0, typed.shape[0] - 1)
    full = typed[pos]
    zero = jnp.zeros((), typed.dtype)
    if typed.ndim == 2:
        return jnp.where(valid[:, None], full, zero)
    return jnp.where(valid, full, zero)


@functools.partial(jax.jit, static_argnums=0)
def _device_dict(phys: int, dict_vals: jnp.ndarray, idx: jnp.ndarray,
                 valid: Optional[jnp.ndarray]):
    """Dictionary gather on device (+ def-level expansion)."""
    if valid is None:
        return dict_vals[idx]
    if idx.shape[0] == 0:          # all-null column: nothing to gather
        shape = (valid.shape[0],) + dict_vals.shape[1:]
        return jnp.zeros(shape, dict_vals.dtype)
    pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
    pos = jnp.clip(pos, 0, idx.shape[0] - 1)
    full = dict_vals[idx[pos]]
    zero = jnp.zeros((), dict_vals.dtype)
    if full.ndim == 2:
        return jnp.where(valid[:, None], full, zero)
    return jnp.where(valid, full, zero)


@functools.partial(jax.jit, static_argnums=0)
def _device_flba_decimal(width: int, raw: jnp.ndarray,
                         valid: Optional[jnp.ndarray]):
    """FIXED_LEN_BYTE_ARRAY decimal payload (big-endian two's complement,
    ``width`` ≤ 16 bytes) → int64 [k, 2] (lo, hi) limb pairs on device —
    the DECIMAL128 Column payload — with sign extension and def-level
    expansion.  Mirrors the host oracle ``decode._be_decimal_to_lanes``."""
    b = raw.reshape(-1, width).astype(jnp.int64)          # BE bytes, [k, w]
    neg = b[:, 0] >= 128
    fill = jnp.where(neg, jnp.int64(0xFF), jnp.int64(0))

    def byte(i):                       # little-endian byte i of the value
        return b[:, width - 1 - i] if i < width else fill

    lo = byte(0)
    for i in range(1, 8):
        lo = lo | (byte(i) << (8 * i))
    hi = byte(8)
    for i in range(9, 16):
        hi = hi | (byte(i) << (8 * (i - 8)))
    typed = jnp.stack([lo, hi], axis=1)                   # [k, 2]
    if valid is None:
        return typed
    if typed.shape[0] == 0:
        return jnp.zeros((valid.shape[0], 2), jnp.int64)
    pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0,
                   typed.shape[0] - 1)
    return jnp.where(valid[:, None], typed[pos], jnp.int64(0))


@functools.partial(jax.jit, static_argnums=0)
def _device_bool(k: int, bits: jnp.ndarray,
                 valid: Optional[jnp.ndarray]):
    """BOOLEAN bit-unpack on device: packed LSB-first bits → u8 0/1 [k]
    (+ def-level expansion)."""
    b = bits[:(k + 7) // 8]
    vals = ((b[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1)
    vals = vals.reshape(-1)[:k].astype(jnp.uint8)
    if valid is None:
        return vals
    if k == 0:
        return jnp.zeros(valid.shape[0], jnp.uint8)
    pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0, k - 1)
    return jnp.where(valid, vals[pos], jnp.uint8(0))


def _upload_dict(phys: int, dictionary: np.ndarray) -> jnp.ndarray:
    if phys == D.PT_DOUBLE:
        from ..utils import f64bits
        return jnp.asarray(f64bits.np_to_bits(dictionary))
    return jnp.asarray(dictionary)


def scan_column_device(file_bytes: bytes, chunks, leaf) -> Optional[Column]:
    """All row groups of one column via the device path; None → fall back."""
    parts = []
    for chunk in chunks:
        part = _walk_chunk_raw(file_bytes, chunk, leaf.max_def, leaf.max_rep,
                               leaf.type_len or 0)
        if part is None:
            return None
        parts.append(part)
    kinds = {p[0] for p in parts}
    physes = {p[1] for p in parts}
    if len(kinds) > 1 or len(physes) > 1:
        return None
    kind, phys = parts[0][0], parts[0][1]
    dt = leaf.logical_dtype()
    if dt.id == T.TypeId.LIST:
        return None
    is_flba = phys == D.PT_FIXED_LEN_BYTE_ARRAY
    if is_flba and not dt.is_decimal:
        return None   # non-decimal fixed-size binary (UUIDs): host path
    if kind == "plain_str" and dt.id != T.TypeId.STRING:
        return None   # BYTE_ARRAY decimals etc.: host path

    valid_np = None
    if any(p[4] is not None for p in parts):
        valid_np = np.concatenate(
            [p[4] if p[4] is not None else np.ones(p[5], bool)
             for p in parts])
    jvalid = None if valid_np is None else jnp.asarray(valid_np)
    n_total = int(sum(p[5] for p in parts))

    if kind == "plain_str":
        # strings fully on device: the char bytes never round through a
        # host loop — prefixes stripped by one segmented gather (the same
        # slab/roll machinery as the JCUDF transcode)
        from ..rowconv import xpack
        from ..utils import hostcache
        base = 0
        bufs, starts, lens = [], [], []
        for p in parts:
            payload_p, st, ln = p[3]
            bufs.append(payload_p)
            starts.append(st + base)
            lens.append(ln)
            base += len(payload_p)
        payload = b"".join(bufs)
        st = np.concatenate(starts) if starts else np.zeros(0, np.int64)
        ln = np.concatenate(lens) if lens else np.zeros(0, np.int32)
        dst = np.zeros(ln.shape[0] + 1, dtype=np.int64)
        np.cumsum(ln, out=dst[1:])
        if ln.shape[0] == 0 or dst[-1] == 0:
            chars = jnp.zeros(0, jnp.uint8)
        else:
            # the gather works in int32 positions; a concatenated multi-
            # chunk payload approaching 2 GiB would wrap the casts below
            # and corrupt the decode — fall back to the host path instead
            # (the native walker only guards per-page char totals)
            if (base >= 2**31 or int(dst[-1]) >= 2**31
                    or int(st.max(initial=0)) >= 2**31):
                return None
            geom = xpack.plan_segmented_gather(st, ln, dst)
            if geom is None:
                return None
            chars = xpack.segmented_gather(
                geom, jnp.asarray(np.frombuffer(payload, np.uint8)),
                jnp.asarray(st.astype(np.int32)),
                jnp.asarray(ln.astype(np.int32)),
                jnp.asarray(dst.astype(np.int32)))
        if valid_np is None:
            row_lens = ln
        else:
            row_lens = np.zeros(n_total, dtype=np.int64)
            row_lens[valid_np] = ln
        offs_np = np.zeros(n_total + 1, dtype=np.int64)
        np.cumsum(row_lens, out=offs_np[1:])
        joffs = jnp.asarray(offs_np.astype(np.int32))
        hostcache.seed(joffs, offs_np)
        return Column(T.string, chars, joffs, jvalid)

    if kind == "plain_bool":
        npresent = [p[5] if p[4] is None else int(p[4].sum())
                    for p in parts]
        if len(parts) > 1 and any(k % 8 for k in npresent[:-1]):
            return None   # bit-misaligned chunk boundary: host path
        payload = b"".join(p[3] for p in parts)
        k = int(sum(npresent))
        bits = jnp.asarray(np.frombuffer(payload, np.uint8))
        data = _device_bool(k, bits, jvalid)
        return Column(T.bool8, data, validity=jvalid)

    if kind == "plain":
        payload = b"".join(p[3] for p in parts)
        raw = jnp.asarray(np.frombuffer(payload, dtype=np.uint8))
        if is_flba:
            data = _device_flba_decimal(leaf.type_len, raw, jvalid)
        else:
            data = _device_plain(phys, raw, jvalid)
    else:
        dicts = [p[2] for p in parts]
        base = dicts[0]
        if any(d is not base and not np.array_equal(d, base)
               for d in dicts[1:]):
            # per-row-group dictionaries differ: rebase indices
            idx_all = []
            offset = 0
            merged = np.concatenate(dicts)
            for p in parts:
                idx_all.append(p[3] + offset)
                offset += p[2].shape[0]
            dict_dev = _upload_dict(phys, merged)
            idx = jnp.asarray(np.concatenate(idx_all))
        else:
            dict_dev = _upload_dict(phys, base)
            idx = jnp.asarray(np.concatenate([p[3] for p in parts]))
        data = _device_dict(phys, dict_dev, idx, jvalid)
    if is_flba:
        # decimal narrowing mirrors the host path: lo limb for ≤18 digits
        if dt.id == T.TypeId.DECIMAL128:
            return Column(dt, data, validity=jvalid)
        return Column(dt, data[:, 0].astype(dt.storage), validity=jvalid)
    storage = dt.storage
    if dt.id != T.TypeId.FLOAT64 and data.dtype != storage:
        data = data.astype(storage)        # logical narrowing (date32 etc.)
    return Column(dt, data, validity=jvalid)


@traced("parquet_scan_table_device")
def scan_table(file_bytes: bytes,
               columns: Optional[list[str]] = None) -> Table:
    """``decode.read_table`` with the device fast path per column."""
    meta = parse_struct(extract_footer_bytes(file_bytes))
    leaves = D._leaf_schema_elements(meta)
    names = [leaf.name for leaf in leaves]
    want = list(range(len(leaves))) if columns is None else [
        names.index(c) for c in columns]
    groups = meta.get(D.FMD.ROW_GROUPS)
    chunk_lists = {i: [] for i in want}
    for rg in groups.values:
        chunks = rg.get(D.RG.COLUMNS).values
        for i in want:
            chunk_lists[i].append(chunks[i])

    cols = []
    fallback: list[int] = []
    by_index: dict[int, Column] = {}
    for i in want:
        col = scan_column_device(file_bytes, chunk_lists[i], leaves[i])
        if col is None:
            fallback.append(i)
        else:
            by_index[i] = col
    if fallback:
        host = D.read_table(file_bytes, columns=[names[i] for i in fallback])
        for j, i in enumerate(fallback):
            by_index[i] = host[j]
    for i in want:
        cols.append(by_index[i])
    return Table(cols)


# API mirror: callers swap `from ..parquet import decode` for this module
read_table = scan_table
