"""Coalesced async host→device staging for the parquet scan.

Round 5 uploaded every raw page payload, level stream, and dictionary
with its own ``jnp.asarray`` — dozens of small synchronous transfers per
file, measured at 0.031 GB/s end to end (SCAN_BENCH ``h2d_gbps``).  The
reference stages a row group's pages into pinned host slabs and issues
ONE cudaMemcpyAsync per slab so the copy engine streams at link rate
(SURVEY §5.5); the PJRT analog is the same shape:

* :class:`SlabStager` queues host buffers and, on ``flush``, packs them
  into one contiguous slab **per dtype** (uint8 payloads, uint32 word
  views, int32/int64 metadata) and issues a single non-blocking
  ``jax.device_put`` per slab.  Each queued buffer resolves to a device
  *slice* of its slab — the per-buffer arrays the decode programs
  consume are cheap device-side slices, not separate transfers.
* ``flush`` is asynchronous: the host thread returns as soon as the
  transfers are enqueued, so a pipelined caller can walk/decompress the
  next row group while the current one is in flight (the overlap the
  scan pipeline measures through ``parquet.stage.overlap_ms``).
* Slabs are capped at ``SRJT_STAGE_SLAB_BYTES`` — a flush larger than
  the cap splits into multiple transfers rather than one giant
  allocation.

Metrics: ``parquet.stage.slab_bytes`` / ``parquet.stage.transfers`` /
``parquet.stage.buffers`` per flush; the flight recorder keeps a
``parquet.stage.flush`` breadcrumb per slab wave.

``SRJT_STAGE_SLABS=0`` reverts every call site to the old per-buffer
``jnp.asarray`` uploads (the differential-testing baseline).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import flight, knobs, metrics


def enabled() -> bool:
    return bool(knobs.get("SRJT_STAGE_SLABS"))


def donate_enabled() -> bool:
    """SRJT_SCAN_DONATE: ``auto`` donates on non-CPU backends (CPU PJRT
    ignores donation and warns); ``1``/``on`` forces, ``0``/``off``
    disables."""
    raw = str(knobs.get("SRJT_SCAN_DONATE") or "auto").strip().lower()
    if raw in ("1", "on", "true", "force"):
        return True
    if raw in ("0", "off", "false", ""):
        return False
    return jax.default_backend() != "cpu"


class Handle:
    """One queued host buffer; resolves to a device slice after flush."""

    __slots__ = ("_stager", "_arr", "_slot", "_dev")

    def __init__(self, stager: "SlabStager", arr: np.ndarray):
        self._stager = stager
        self._arr = arr
        self._slot = None          # (slab index within dtype bucket, start)
        self._dev: Optional[jnp.ndarray] = None

    def get(self) -> jnp.ndarray:
        """The staged device array (flushes the owning stager if the
        buffer is still queued)."""
        if self._dev is None:
            self._stager.flush()
        return self._dev


class SlabStager:
    """Pack queued host buffers into per-dtype slabs; one async
    ``device_put`` per slab."""

    def __init__(self, slab_cap: Optional[int] = None):
        if slab_cap is None:
            slab_cap = knobs.get("SRJT_STAGE_SLAB_BYTES") or (64 << 20)
        self.slab_cap = max(int(slab_cap), 1 << 20)
        self._pending: list[Handle] = []
        self.slab_bytes = 0          # lifetime bytes shipped via slabs
        self.transfers = 0           # lifetime device_put count
        self.buffers = 0             # lifetime queued-buffer count

    # -- queueing ------------------------------------------------------------
    def add(self, arr: np.ndarray) -> Handle:
        """Queue a host array for the next flush; returns its handle."""
        arr = np.ascontiguousarray(arr)
        h = Handle(self, arr)
        if arr.size == 0:
            # degenerate: resolve immediately, never rides a slab
            h._dev = jnp.asarray(arr)
            h._arr = None
            return h
        self._pending.append(h)
        self.buffers += 1
        return h

    def asarray(self, arr: np.ndarray) -> Handle:
        return self.add(arr)

    # -- transfer ------------------------------------------------------------
    def flush(self) -> int:
        """Concatenate queued buffers per dtype and issue one non-blocking
        transfer per slab (split past ``slab_cap``).  Returns the number
        of transfers issued.  Handles resolve to device slices."""
        if not self._pending:
            return 0
        pending, self._pending = self._pending, []
        by_dtype: dict[np.dtype, list[Handle]] = {}
        for h in pending:
            by_dtype.setdefault(h._arr.dtype, []).append(h)
        issued = 0
        flush_bytes = 0
        for dt, handles in by_dtype.items():
            wave: list[Handle] = []
            wave_bytes = 0
            for h in handles:
                nb = h._arr.nbytes
                if wave and wave_bytes + nb > self.slab_cap:
                    issued += self._ship(dt, wave)
                    flush_bytes += wave_bytes
                    wave, wave_bytes = [], 0
                wave.append(h)
                wave_bytes += nb
            if wave:
                issued += self._ship(dt, wave)
                flush_bytes += wave_bytes
        self.slab_bytes += flush_bytes
        self.transfers += issued
        if metrics.recording():
            metrics.count("parquet.stage.slab_bytes", flush_bytes)
            metrics.count("parquet.stage.transfers", issued)
            metrics.count("parquet.stage.buffers", len(pending))
        flight.record("parquet.stage.flush", slabs=issued,
                      buffers=len(pending), bytes=flush_bytes)
        return issued

    def _ship(self, dt: np.dtype, wave: list[Handle]) -> int:
        if len(wave) == 1:
            # a lone buffer needs no repack — still one async transfer
            h = wave[0]
            h._dev = jax.device_put(h._arr)
            h._arr = None
            return 1
        slab = np.concatenate([h._arr.reshape(-1) for h in wave])
        dev = jax.device_put(slab)       # ONE transfer, non-blocking
        pos = 0
        for h in wave:
            n = h._arr.size
            shape = h._arr.shape
            sl = dev[pos:pos + n]
            h._dev = sl if len(shape) == 1 else sl.reshape(shape)
            h._arr = None
            pos += n
        return 1


def resolve(x):
    """``Handle`` → staged device array; anything else passes through.
    Spec builders queue uploads as handles so a whole file's metadata
    rides a few slabs; the scan resolves them after the final flush."""
    return x.get() if isinstance(x, Handle) else x


def asarray(arr: np.ndarray, stager: Optional[SlabStager] = None):
    """Upload ``arr``: queued on ``stager`` (deferred, coalesced) when
    one is given, else the eager per-buffer ``jnp.asarray``."""
    if stager is not None:
        return stager.add(arr)
    return jnp.asarray(arr)
