"""Fused scan→filter: prune rows on the walked RAW parts, before upload.

The round-5 pipeline decodes every selected row group in full and only
then applies the planner's predicate as a device mask + gather — strings
and wide columns materialize for rows the filter immediately discards.
The reference pushes the predicate into the scan itself (libcudf's
``parquet::read_parquet`` AST filter prunes rows inside the decode wave);
the TPU-native analog works on the HOST staging tier, where the walked
chunk parts still hold typed raw bytes:

* ``plain`` INT32/INT64 payloads compare as zero-copy ``np.frombuffer``
  views — one vectorized compare per conjunct;
* dictionary-encoded columns evaluate the predicate ONCE PER DICTIONARY
  ENTRY (an O(#entries) scan of the dict page), then the entry verdicts
  broadcast over the expanded code stream — the same trick DictColumn
  predicates use on device, applied before any byte reaches the chip;
* ``plain_str`` equality compares per literal byte over the candidate
  rows whose length matches (no per-row Python loop).

Null rows FAIL every conjunct, matching ``plan.lower.eval_mask``
(validity is ANDed into each condition's mask).  Pruning rewrites each
column's parts in place of the originals — typed payload bytes fancy-
indexed per row, string geometry filtered without touching the char
payload (the segmented gather compacts anyway), codes pruned as an
``("np", …)`` index entry — so the staged decode later in the scan sees
a smaller file, bit-identical to scan-then-filter.

Conjuncts the host tier cannot evaluate (float literals, ordered string
compares, unsupported encodings) are simply left for the planner's
re-apply; ``apply`` reports whether the pruned table is *complete*
(every conjunct handled) so ``plan.lower`` can skip the redundant mask.
"""

from __future__ import annotations

import numpy as np

from . import decode as D
from .device_scan import _PLAIN_PHYS

_INT_PHYS = (D.PT_INT32, D.PT_INT64)


def _cmp(op: str, a, v):
    if op == "eq":
        return a == v
    if op == "lt":
        return a < v
    if op == "le":
        return a <= v
    if op == "gt":
        return a > v
    if op == "ge":
        return a >= v
    return None


def _valid_np(p):
    """A part's row validity as a host bool array (None = all valid)."""
    v = p[4]
    if v is None or isinstance(v, np.ndarray):
        return v
    from . import rle_device as RLE
    return np.concatenate(
        [np.ones(k, bool) if plan is None else (RLE.expand_np(plan) == 1)
         for plan, k in v[1]])


def _codes_np(entries) -> np.ndarray:
    """Dictionary-index entries → one int32 code per PRESENT value."""
    from . import rle_device as RLE
    return np.concatenate(
        [RLE.expand_np(e[1]) if e[0] == "plan" else np.asarray(e[1])
         for e in entries]).astype(np.int32) if entries \
        else np.zeros(0, np.int32)


def _dict_entry_eq(data: bytes, offs: np.ndarray, val: bytes) -> np.ndarray:
    """Per-entry equality against a bytes literal, straight off the RAW
    dict page (entry j's chars start at ``offs[j] + 4*(j+1)`` — past j+1
    length prefixes)."""
    m = np.zeros(offs.shape[0] - 1, bool)
    lv = len(val)
    for j in range(m.shape[0]):
        ln = int(offs[j + 1] - offs[j])
        if ln == lv:
            s = int(offs[j]) + 4 * (j + 1)
            m[j] = data[s:s + ln] == val
    return m


def _part_mask(p, op: str, val):
    """Row mask [p.n_total] for one conjunct over one walked part, or
    None (shape outside the host tier's envelope)."""
    kind, phys = p[0], p[1]
    pm = None
    if kind == "plain" and isinstance(val, int) and phys in _INT_PHYS:
        dt = np.int32 if phys == D.PT_INT32 else np.int64
        pm = _cmp(op, np.frombuffer(p[3], dtype=dt), val)
    elif kind == "dict" and isinstance(val, int) and phys in _INT_PHYS:
        ent = np.asarray(p[2])
        if ent.ndim == 1 and ent.dtype.kind in "iu":
            em = _cmp(op, ent, val)
            if em is not None:
                pm = em[_codes_np(p[3])]
    elif kind == "dict_str" and isinstance(val, bytes) and op == "eq":
        data, offs = p[2]
        pm = _dict_entry_eq(data, offs, val)[_codes_np(p[3])]
    elif kind == "plain_str" and isinstance(val, bytes) and op == "eq":
        _payload, st, ln = p[3]
        pm = ln == len(val)
        if len(val) and pm.any():
            pay = np.frombuffer(_payload, np.uint8)
            lit = np.frombuffer(val, np.uint8)
            cand = np.flatnonzero(pm)
            sub = np.ones(cand.shape[0], bool)
            base = st[cand]
            for k in range(len(val)):
                sub &= pay[base + k] == lit[k]
            pm = np.zeros(pm.shape[0], bool)
            pm[cand] = sub
    if pm is None:
        return None
    valid = _valid_np(p)
    if valid is None:
        return np.asarray(pm, bool)
    m = np.zeros(p[5], bool)
    m[valid] = pm                      # null rows fail, like eval_mask
    return m


def _column_mask(parts, op: str, val):
    masks = []
    for p in parts:
        m = _part_mask(p, op, val)
        if m is None:
            return None
        masks.append(m)
    return np.concatenate(masks) if len(masks) > 1 else masks[0]


def _prune_part(p, leaf, keep: np.ndarray):
    """One walked part with only the ``keep`` rows, same tuple shape."""
    kind, phys, dictionary, body, _valid, _n = p
    valid = _valid_np(p)
    keep_present = keep if valid is None else keep[valid]
    new_valid = None if valid is None else valid[keep]
    n_new = int(keep.sum())
    if kind == "plain":
        width = (leaf.type_len if phys == D.PT_FIXED_LEN_BYTE_ARRAY
                 else _PLAIN_PHYS[phys])
        vals = np.frombuffer(body, dtype=np.dtype((np.void, width)))
        new_body = vals[keep_present].tobytes()
    elif kind == "plain_bool":
        npres = keep_present.shape[0]
        bits = np.unpackbits(np.frombuffer(body, np.uint8),
                             bitorder="little")[:npres]
        new_body = np.packbits(bits[keep_present],
                               bitorder="little").tobytes()
    elif kind == "plain_str":
        payload, st, ln = body
        new_body = (payload, st[keep_present], ln[keep_present])
    elif kind in ("dict", "dict_str"):
        codes = _codes_np(body)
        new_body = [("np", codes[keep_present].astype(np.int32))]
    else:
        return None
    return (kind, phys, dictionary, new_body, new_valid, n_new)


def apply(conds, walked, leaves, names, want):
    """Evaluate supported ``(column, op, literal)`` conjuncts over the
    walked raw parts and prune every wanted column's rows.

    → ``(pruned_walked, complete, n_kept)``, or None when no conjunct is
    evaluable on this file (the caller stages the original parts and the
    planner's mask runs as before).  ``complete`` is True when EVERY
    conjunct was evaluated here — the planner may then skip its re-apply
    if the conjunct list covers the whole predicate."""
    name_to_idx = {n: i for i, n in enumerate(names)}
    first = walked.get(want[0]) if want else None
    if not first:
        return None
    n_rows = int(sum(p[5] for p in first))
    if n_rows == 0:
        return None
    keep = np.ones(n_rows, bool)
    handled = 0
    for cname, op, val in conds:
        ci = name_to_idx.get(cname)
        m = None
        if ci is not None and walked.get(ci) is not None:
            m = _column_mask(walked[ci], op, val)
        if m is None:
            continue
        keep &= m
        handled += 1
    if handled == 0:
        return None
    complete = handled == len(conds)
    n_kept = int(keep.sum())
    if n_kept == n_rows:
        # nothing to prune — skip the byte rewrite; ``complete`` still
        # lets the planner drop its (all-True) re-apply
        return walked, complete, n_kept
    out = {}
    for i in want:
        newparts = []
        pos = 0
        for p in walked[i]:
            pruned = _prune_part(p, leaves[i], keep[pos:pos + p[5]])
            pos += p[5]
            if pruned is None:
                return None
            newparts.append(pruned)
        out[i] = newparts
    return out, complete, n_kept
