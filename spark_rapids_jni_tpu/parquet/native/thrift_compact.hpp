// Generic Thrift Compact Protocol tree reader/writer.
//
// Native twin of ../thrift.py (see its module docstring for the design
// rationale): parses into a generic field tree rather than generated typed
// structs (the reference uses thrift codegen, NativeParquetJni.cpp:27-32),
// so unknown footer fields survive prune round trips and no thrift toolchain
// is needed at build time.  Size-bomb guards follow the reference
// (NativeParquetJni.cpp:536-540).
#pragma once

#include <cstdint>
#include <memory>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace srjt {

constexpr uint64_t kMaxStringSize = 100ull * 1000 * 1000;
constexpr uint64_t kMaxContainerSize = 1000ull * 1000;

enum TType : uint8_t {
  T_STOP = 0,
  T_BOOL_TRUE = 1,
  T_BOOL_FALSE = 2,
  T_BYTE = 3,
  T_I16 = 4,
  T_I32 = 5,
  T_I64 = 6,
  T_DOUBLE = 7,
  T_BINARY = 8,
  T_LIST = 9,
  T_SET = 10,
  T_MAP = 11,
  T_STRUCT = 12,
};

struct ThriftError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

struct Value;

struct Field {
  int32_t fid;
  uint8_t type;
  std::unique_ptr<Value> val;
};

struct Value {
  uint8_t type = T_STOP;
  int64_t i = 0;        // bool (0/1), byte, i16, i32, i64
  double d = 0;         // double
  std::string bin;      // binary / string
  uint8_t elem_type = 0;
  std::vector<Value> elems;                    // list / set
  uint8_t ktype = 0, vtype = 0;
  std::vector<std::pair<Value, Value>> pairs;  // map
  std::vector<Field> fields;                   // struct

  Field* find(int32_t fid) {
    for (auto& f : fields)
      if (f.fid == fid) return &f;
    return nullptr;
  }
  const Field* find(int32_t fid) const {
    for (auto const& f : fields)
      if (f.fid == fid) return &f;
    return nullptr;
  }
  int64_t get_i(int32_t fid, int64_t dflt) const {
    auto* f = find(fid);
    return f ? f->val->i : dflt;
  }
  bool has(int32_t fid) const { return find(fid) != nullptr; }
  void set_i(int32_t fid, uint8_t t, int64_t v);
};

class CompactReader {
 public:
  CompactReader(const uint8_t* buf, uint64_t len) : buf_(buf), len_(len) {}

  Value read_struct();

 private:
  uint8_t byte();
  uint64_t read_varint();
  int64_t read_zigzag();
  void read_value(uint8_t type, Value& out);

  const uint8_t* buf_;
  uint64_t len_;
  uint64_t pos_ = 0;
};

class CompactWriter {
 public:
  void write_struct(const Value& s);
  const std::vector<uint8_t>& buffer() const { return out_; }

 private:
  void write_varint(uint64_t n);
  void write_zigzag(int64_t n);
  void write_value(uint8_t type, const Value& v);

  std::vector<uint8_t> out_;
};

}  // namespace srjt
