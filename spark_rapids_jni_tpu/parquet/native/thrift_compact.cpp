#include "thrift_compact.hpp"

#include <cstring>

namespace srjt {

void Value::set_i(int32_t fid, uint8_t t, int64_t v) {
  if (auto* f = find(fid)) {
    f->type = t;
    f->val->type = t;
    f->val->i = v;
    return;
  }
  auto val = std::make_unique<Value>();
  val->type = t;
  val->i = v;
  Field nf{fid, t, std::move(val)};
  // keep fields ordered by id (thrift compact writes ascending deltas)
  size_t at = 0;
  while (at < fields.size() && fields[at].fid < fid) ++at;
  fields.insert(fields.begin() + at, std::move(nf));
}

uint8_t CompactReader::byte() {
  if (pos_ >= len_) throw ThriftError("unexpected end of thrift data");
  return buf_[pos_++];
}

uint64_t CompactReader::read_varint() {
  uint64_t result = 0;
  int shift = 0;
  while (true) {
    uint8_t b = byte();
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) return result;
    shift += 7;
    if (shift > 63) throw ThriftError("varint too long");
  }
}

int64_t CompactReader::read_zigzag() {
  uint64_t n = read_varint();
  return static_cast<int64_t>(n >> 1) ^ -static_cast<int64_t>(n & 1);
}

void CompactReader::read_value(uint8_t type, Value& out) {
  out.type = type;
  switch (type) {
    case T_BOOL_TRUE:
      out.i = 1;
      break;
    case T_BOOL_FALSE:
      out.i = 0;
      break;
    case T_BYTE:
      out.i = static_cast<int8_t>(byte());
      break;
    case T_I16:
    case T_I32:
    case T_I64:
      out.i = read_zigzag();
      break;
    case T_DOUBLE: {
      if (pos_ + 8 > len_) throw ThriftError("double past end");
      uint64_t bits = 0;
      std::memcpy(&bits, buf_ + pos_, 8);  // wire order is little-endian
      pos_ += 8;
      std::memcpy(&out.d, &bits, 8);
      break;
    }
    case T_BINARY: {
      uint64_t size = read_varint();
      if (size > kMaxStringSize) throw ThriftError("string size exceeds limit");
      if (pos_ + size > len_) throw ThriftError("string past end");
      out.bin.assign(reinterpret_cast<const char*>(buf_ + pos_), size);
      pos_ += size;
      break;
    }
    case T_LIST:
    case T_SET: {
      uint8_t header = byte();
      uint64_t size = (header >> 4) & 0x0F;
      out.elem_type = header & 0x0F;
      if (size == 15) size = read_varint();
      if (size > kMaxContainerSize)
        throw ThriftError("container size exceeds limit");
      out.elems.resize(size);
      if (out.elem_type == T_BOOL_TRUE || out.elem_type == T_BOOL_FALSE) {
        // in lists each bool is one byte (1=true, 2=false), unlike struct
        // fields where the value rides in the field header
        for (uint64_t i = 0; i < size; ++i) {
          out.elems[i].type = out.elem_type;
          out.elems[i].i = (byte() == 1) ? 1 : 0;
        }
      } else {
        for (uint64_t i = 0; i < size; ++i)
          read_value(out.elem_type, out.elems[i]);
      }
      break;
    }
    case T_MAP: {
      uint64_t size = read_varint();
      if (size > kMaxContainerSize) throw ThriftError("map size exceeds limit");
      if (size > 0) {
        uint8_t kv = byte();
        out.ktype = (kv >> 4) & 0x0F;
        out.vtype = kv & 0x0F;
        out.pairs.resize(size);
        for (uint64_t i = 0; i < size; ++i) {
          read_value(out.ktype, out.pairs[i].first);
          read_value(out.vtype, out.pairs[i].second);
        }
      }
      break;
    }
    case T_STRUCT: {
      Value s = read_struct();
      out.fields = std::move(s.fields);
      break;
    }
    default:
      throw ThriftError("unknown compact type " + std::to_string(type));
  }
}

Value CompactReader::read_struct() {
  Value out;
  out.type = T_STRUCT;
  int32_t last_fid = 0;
  while (true) {
    uint8_t header = byte();
    if (header == T_STOP) return out;
    uint8_t delta = (header >> 4) & 0x0F;
    uint8_t type = header & 0x0F;
    int32_t fid =
        delta ? last_fid + delta : static_cast<int32_t>(read_zigzag());
    Field f{fid, type, std::make_unique<Value>()};
    read_value(type, *f.val);
    out.fields.push_back(std::move(f));
    last_fid = fid;
  }
}

void CompactWriter::write_varint(uint64_t n) {
  while (true) {
    if ((n & ~0x7Full) == 0) {
      out_.push_back(static_cast<uint8_t>(n));
      return;
    }
    out_.push_back(static_cast<uint8_t>((n & 0x7F) | 0x80));
    n >>= 7;
  }
}

void CompactWriter::write_zigzag(int64_t n) {
  write_varint((static_cast<uint64_t>(n) << 1) ^
               static_cast<uint64_t>(n >> 63));
}

void CompactWriter::write_value(uint8_t type, const Value& v) {
  switch (type) {
    case T_BOOL_TRUE:
    case T_BOOL_FALSE:
      // only reached inside containers; structs encode bool in the header
      out_.push_back(v.i ? T_BOOL_TRUE : T_BOOL_FALSE);
      break;
    case T_BYTE:
      out_.push_back(static_cast<uint8_t>(v.i));
      break;
    case T_I16:
    case T_I32:
    case T_I64:
      write_zigzag(v.i);
      break;
    case T_DOUBLE: {
      uint64_t bits;
      std::memcpy(&bits, &v.d, 8);
      for (int b = 0; b < 8; ++b)
        out_.push_back(static_cast<uint8_t>(bits >> (8 * b)));
      break;
    }
    case T_BINARY:
      write_varint(v.bin.size());
      out_.insert(out_.end(), v.bin.begin(), v.bin.end());
      break;
    case T_LIST:
    case T_SET: {
      size_t size = v.elems.size();
      if (size < 15) {
        out_.push_back(static_cast<uint8_t>((size << 4) | v.elem_type));
      } else {
        out_.push_back(static_cast<uint8_t>(0xF0 | v.elem_type));
        write_varint(size);
      }
      for (auto const& e : v.elems) write_value(v.elem_type, e);
      break;
    }
    case T_MAP:
      write_varint(v.pairs.size());
      if (!v.pairs.empty()) {
        out_.push_back(static_cast<uint8_t>((v.ktype << 4) | v.vtype));
        for (auto const& [k, val] : v.pairs) {
          write_value(v.ktype, k);
          write_value(v.vtype, val);
        }
      }
      break;
    case T_STRUCT:
      write_struct(v);
      break;
    default:
      throw ThriftError("cannot write compact type " + std::to_string(type));
  }
}

void CompactWriter::write_struct(const Value& s) {
  int32_t last_fid = 0;
  for (auto const& f : s.fields) {
    uint8_t type = f.type;
    if (type == T_BOOL_TRUE || type == T_BOOL_FALSE)
      type = f.val->i ? T_BOOL_TRUE : T_BOOL_FALSE;
    int32_t delta = f.fid - last_fid;
    if (delta > 0 && delta <= 15) {
      out_.push_back(static_cast<uint8_t>((delta << 4) | type));
    } else {
      out_.push_back(type);
      write_zigzag(static_cast<int16_t>(f.fid));
    }
    if (type != T_BOOL_TRUE && type != T_BOOL_FALSE)
      write_value(type, *f.val);
    last_fid = f.fid;
  }
  out_.push_back(T_STOP);
}

}  // namespace srjt
