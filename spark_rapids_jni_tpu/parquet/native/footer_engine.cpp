// Parquet footer parse → prune → re-serialize: native engine, C ABI.
//
// Native twin of ../footer.py with identical semantics (that module's
// docstring lists the reference behaviors reproduced, all cited to
// NativeParquetJni.cpp).  Exposed through a plain C ABI (srjt_footer_*) so
// the Python layer binds via ctypes and a JVM can bind via JNI without any
// C++ ABI coupling — the handle-based surface mirrors the reference's
// jlong-handle protocol (NativeParquetJni.cpp:568-666).
//
// Case folding: ASCII-only tolower here; the reference's locale-based
// mbstowcs/towlower (NativeParquetJni.cpp:45-78) is locale-fragile, and the
// Python engine provides full-Unicode folding when needed.

#include <algorithm>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "thrift_compact.hpp"

namespace srjt {

// parquet.thrift field ids (public definition)
namespace fmd {
constexpr int32_t kSchema = 2, kNumRows = 3, kRowGroups = 4, kColumnOrders = 7;
}
namespace se {
constexpr int32_t kType = 1, kRepetitionType = 3, kName = 4, kNumChildren = 5,
                  kConvertedType = 6;
}
namespace rg {
constexpr int32_t kColumns = 1, kNumRows = 3, kFileOffset = 5,
                  kTotalCompressedSize = 6;
}
namespace cc {
constexpr int32_t kMetaData = 3;
}
namespace cmd {
constexpr int32_t kTotalCompressedSize = 7, kDataPageOffset = 9,
                  kDictionaryPageOffset = 11;
}

constexpr int64_t kConvertedMap = 1, kConvertedMapKeyValue = 2,
                  kConvertedList = 3;
constexpr int64_t kRepetitionRepeated = 2;

enum class Tag : int32_t { VALUE = 0, STRUCT = 1, LIST = 2, MAP = 3 };

static std::string ascii_lower(std::string s) {
  for (auto& c : s)
    if (c >= 'A' && c <= 'Z') c += 32;
  return s;
}

struct PruningMaps {
  std::vector<int> schema_map;
  std::vector<int> schema_num_children;
  std::vector<int> chunk_map;
};

// Expected-schema tree matcher (column_pruner, NativeParquetJni.cpp:112-437).
class ColumnPruner {
 public:
  explicit ColumnPruner(Tag tag = Tag::STRUCT) : tag_(tag) {}

  // Build from depth-first flattened (names, num_children, tags); the root
  // is excluded, parent_num_children counts its children
  // (NativeParquetJni.cpp:388-437).
  ColumnPruner(const std::vector<std::string>& names,
               const std::vector<int32_t>& num_children,
               const std::vector<int32_t>& tags, int32_t parent_num_children)
      : tag_(Tag::STRUCT) {
    if (parent_num_children == 0) return;
    std::vector<ColumnPruner*> tree_stack{this};
    std::vector<int32_t> left_stack{parent_num_children};
    for (size_t i = 0; i < names.size(); ++i) {
      auto [it, inserted] = tree_stack.back()->children_.try_emplace(
          names[i], static_cast<Tag>(tags[i]));
      (void)inserted;
      if (num_children[i] > 0) {
        tree_stack.push_back(&it->second);
        left_stack.push_back(num_children[i]);
      } else {
        while (!tree_stack.empty()) {
          if (--left_stack.back() > 0) break;
          tree_stack.pop_back();
          left_stack.pop_back();
        }
      }
    }
    if (!tree_stack.empty())
      throw std::invalid_argument("flattened schema arrays are inconsistent");
  }

  PruningMaps filter_schema(const std::vector<Value>& schema,
                            bool ignore_case) const {
    PruningMaps maps;
    size_t schema_idx = 0, chunk_idx = 0;
    filter(schema, ignore_case, schema_idx, chunk_idx, maps);
    return maps;
  }

 private:
  static std::string name_of(const Value& elem, bool fold) {
    auto* f = elem.find(se::kName);
    std::string n = f ? f->val->bin : "";
    return fold ? ascii_lower(n) : n;
  }
  static int64_t num_children_of(const Value& elem) {
    return elem.get_i(se::kNumChildren, 0);
  }
  static bool is_leaf(const Value& elem) { return elem.has(se::kType); }

  static void skip(const std::vector<Value>& schema, size_t& si, size_t& ci) {
    // skip subtree, advancing the chunk counter per leaf
    // (NativeParquetJni.cpp:160-180)
    int64_t to_skip = 1;
    while (to_skip > 0 && si < schema.size()) {
      const Value& elem = schema[si];
      if (is_leaf(elem)) ++ci;
      to_skip += num_children_of(elem) - 1;
      ++si;
    }
  }

  void filter(const std::vector<Value>& schema, bool ic, size_t& si,
              size_t& ci, PruningMaps& maps) const {
    switch (tag_) {
      case Tag::STRUCT:
        return filter_struct(schema, ic, si, ci, maps);
      case Tag::VALUE:
        return filter_value(schema, si, ci, maps);
      case Tag::LIST:
        return filter_list(schema, ic, si, ci, maps);
      case Tag::MAP:
        return filter_map(schema, ic, si, ci, maps);
    }
    throw std::runtime_error("unexpected pruner tag");
  }

  void filter_struct(const std::vector<Value>& schema, bool ic, size_t& si,
                     size_t& ci, PruningMaps& maps) const {
    const Value& elem = schema.at(si);
    if (is_leaf(elem))
      throw std::runtime_error("found a leaf node, but expected a struct");
    int64_t n = num_children_of(elem);
    maps.schema_map.push_back(si);
    size_t my_nc = maps.schema_num_children.size();
    maps.schema_num_children.push_back(0);
    ++si;
    for (int64_t c = 0; c < n && si < schema.size(); ++c) {
      auto it = children_.find(name_of(schema[si], ic));
      if (it != children_.end()) {
        ++maps.schema_num_children[my_nc];
        it->second.filter(schema, ic, si, ci, maps);
      } else {
        skip(schema, si, ci);
      }
    }
  }

  void filter_value(const std::vector<Value>& schema, size_t& si, size_t& ci,
                    PruningMaps& maps) const {
    const Value& elem = schema.at(si);
    if (!is_leaf(elem))
      throw std::runtime_error(
          "found a non-leaf entry when reading a leaf value");
    if (num_children_of(elem) != 0)
      throw std::runtime_error(
          "found an entry with children when reading a leaf value");
    maps.schema_map.push_back(si);
    maps.schema_num_children.push_back(0);
    ++si;
    maps.chunk_map.push_back(ci);
    ++ci;
  }

  void filter_list(const std::vector<Value>& schema, bool ic, size_t& si,
                   size_t& ci, PruningMaps& maps) const {
    const ColumnPruner& element = children_.at("element");
    const Value& elem = schema.at(si);
    std::string list_name = name_of(elem, false);
    if (is_leaf(elem))
      throw std::runtime_error("expected a list item, but found a single value");
    if (!elem.has(se::kConvertedType) ||
        elem.get_i(se::kConvertedType, -1) != kConvertedList)
      throw std::runtime_error("expected a list type, but it was not found");
    if (num_children_of(elem) != 1)
      throw std::runtime_error(
          "the structure of the outer list group is not standard");
    maps.schema_map.push_back(si);
    maps.schema_num_children.push_back(1);
    ++si;

    // LIST layout rules: standard 3-level vs legacy 2-level
    // (NativeParquetJni.cpp:271-299)
    const Value& rep = schema.at(si);
    if (rep.get_i(se::kRepetitionType, -1) != kRepetitionRepeated)
      throw std::runtime_error(
          "the structure of the list's child is not standard (non repeating)");
    bool rep_is_group = !is_leaf(rep);
    int64_t rep_nc = num_children_of(rep);
    std::string rep_name = name_of(rep, false);
    if (rep_is_group && rep_nc == 1 && rep_name != "array" &&
        rep_name != list_name + "_tuple") {
      maps.schema_map.push_back(si);
      maps.schema_num_children.push_back(1);
      ++si;
      element.filter(schema, ic, si, ci, maps);
    } else {
      element.filter(schema, ic, si, ci, maps);
    }
  }

  void filter_map(const std::vector<Value>& schema, bool ic, size_t& si,
                  size_t& ci, PruningMaps& maps) const {
    const ColumnPruner& key = children_.at("key");
    const ColumnPruner& value = children_.at("value");
    const Value& elem = schema.at(si);
    if (is_leaf(elem))
      throw std::runtime_error("expected a map item, but found a single value");
    int64_t conv = elem.get_i(se::kConvertedType, -1);
    if (conv != kConvertedMap && conv != kConvertedMapKeyValue)
      throw std::runtime_error("expected a map type, but it was not found");
    if (num_children_of(elem) != 1)
      throw std::runtime_error(
          "the structure of the outer map group is not standard");
    maps.schema_map.push_back(si);
    maps.schema_num_children.push_back(1);
    ++si;

    const Value& rep = schema.at(si);
    if (rep.get_i(se::kRepetitionType, -1) != kRepetitionRepeated)
      throw std::runtime_error("found non repeating map child");
    int64_t rep_nc = num_children_of(rep);
    if (rep_nc != 1 && rep_nc != 2)
      throw std::runtime_error("found map with wrong number of children");
    maps.schema_map.push_back(si);
    maps.schema_num_children.push_back(rep_nc);
    ++si;
    key.filter(schema, ic, si, ci, maps);
    if (rep_nc == 2) value.filter(schema, ic, si, ci, maps);
  }

  std::map<std::string, ColumnPruner> children_;
  Tag tag_;
};

// -- row-group filtering (NativeParquetJni.cpp:437-519) --------------------

static Value& columns_of(Value& group) {
  Field* f = group.find(rg::kColumns);
  if (!f || f->val->elems.empty())
    throw std::runtime_error("malformed footer: row group without columns");
  return *f->val;
}

static int64_t chunk_offset(const Value& chunk) {
  const Field* mdf = chunk.find(cc::kMetaData);
  if (!mdf)
    throw std::runtime_error("malformed footer: column chunk without metadata");
  const Value& md = *mdf->val;
  int64_t off = md.get_i(cmd::kDataPageOffset, 0);
  if (md.has(cmd::kDictionaryPageOffset)) {
    int64_t d = md.get_i(cmd::kDictionaryPageOffset, 0);
    if (off > d) off = d;
  }
  return off;
}

static bool invalid_file_offset(int64_t start, int64_t pre_start,
                                int64_t pre_size) {
  if (pre_start == 0 && start != 4) return true;
  return start < pre_start + pre_size;
}

static std::vector<Value> filter_groups(Value& meta, int64_t part_offset,
                                        int64_t part_length) {
  std::vector<Value> out;
  Field* gf = meta.find(fmd::kRowGroups);
  if (!gf || gf->val->elems.empty()) return out;
  auto& groups = gf->val->elems;
  bool first_has_md = columns_of(groups[0]).elems[0].has(cc::kMetaData);
  int64_t pre_start = 0, pre_size = 0;
  for (auto& group : groups) {
    auto& cols = columns_of(group).elems;
    int64_t start;
    if (first_has_md) {
      start = chunk_offset(cols[0]);
    } else {
      start = group.get_i(rg::kFileOffset, 0);
      if (invalid_file_offset(start, pre_start, pre_size))
        start = (pre_start == 0) ? 4 : pre_start + pre_size;
      pre_start = start;
      pre_size = group.get_i(rg::kTotalCompressedSize, 0);
    }
    int64_t total;
    if (group.has(rg::kTotalCompressedSize)) {
      total = group.get_i(rg::kTotalCompressedSize, 0);
    } else {
      total = 0;
      for (auto& c : cols) {
        const Field* mdf = c.find(cc::kMetaData);
        if (!mdf)
          throw std::runtime_error(
              "malformed footer: column chunk without metadata");
        total += mdf->val->get_i(cmd::kTotalCompressedSize, 0);
      }
    }
    int64_t mid = start + total / 2;
    if (mid >= part_offset && mid < part_offset + part_length)
      out.push_back(std::move(group));
  }
  return out;
}

static void filter_columns(std::vector<Value>& groups,
                           const std::vector<int>& chunk_map) {
  for (auto& group : groups) {
    auto& cols = columns_of(group).elems;
    std::vector<Value> kept;
    kept.reserve(chunk_map.size());
    for (int idx : chunk_map) kept.push_back(std::move(cols.at(idx)));
    cols = std::move(kept);
  }
}

struct FooterHandle {
  Value meta;
};

}  // namespace srjt

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

using srjt::FooterHandle;

static void fill_err(char* err, uint64_t err_len, const char* msg) {
  if (err && err_len) {
    std::strncpy(err, msg, err_len - 1);
    err[err_len - 1] = '\0';
  }
}

void* srjt_footer_read_and_filter(const uint8_t* buf, uint64_t len,
                                  int64_t part_offset, int64_t part_length,
                                  const char** names,
                                  const int32_t* num_children,
                                  const int32_t* tags, int32_t n,
                                  int32_t parent_num_children,
                                  int32_t ignore_case, char* err,
                                  uint64_t err_len) {
  try {
    auto handle = std::make_unique<FooterHandle>();
    srjt::CompactReader reader(buf, len);
    handle->meta = reader.read_struct();

    std::vector<std::string> names_v(names, names + n);
    std::vector<int32_t> nc_v(num_children, num_children + n);
    std::vector<int32_t> tags_v(tags, tags + n);
    srjt::ColumnPruner pruner(names_v, nc_v, tags_v, parent_num_children);

    srjt::Field* schema_f = handle->meta.find(srjt::fmd::kSchema);
    if (!schema_f) throw std::runtime_error("footer has no schema");
    auto& schema = schema_f->val->elems;
    auto maps = pruner.filter_schema(schema, ignore_case != 0);

    // gather + rewrite schema num_children (NativeParquetJni.cpp:595-605)
    std::vector<srjt::Value> new_schema;
    new_schema.reserve(maps.schema_map.size());
    for (size_t i = 0; i < maps.schema_map.size(); ++i) {
      srjt::Value elem = std::move(schema.at(maps.schema_map[i]));
      int nc = maps.schema_num_children[i];
      if (elem.has(srjt::se::kNumChildren) || nc != 0)
        elem.set_i(srjt::se::kNumChildren, srjt::T_I32, nc);
      new_schema.push_back(std::move(elem));
    }
    schema = std::move(new_schema);

    // column_orders gathered by chunk map (NativeParquetJni.cpp:606-613)
    if (auto* orders = handle->meta.find(srjt::fmd::kColumnOrders)) {
      std::vector<srjt::Value> kept;
      for (int idx : maps.chunk_map)
        kept.push_back(std::move(orders->val->elems.at(idx)));
      orders->val->elems = std::move(kept);
    }

    if (part_length >= 0) {
      auto kept = srjt::filter_groups(handle->meta, part_offset, part_length);
      if (auto* gf = handle->meta.find(srjt::fmd::kRowGroups))
        gf->val->elems = std::move(kept);
    }
    if (auto* gf = handle->meta.find(srjt::fmd::kRowGroups))
      srjt::filter_columns(gf->val->elems, maps.chunk_map);

    return handle.release();
  } catch (std::exception& e) {
    fill_err(err, err_len, e.what());
    return nullptr;
  }
}

int64_t srjt_footer_num_rows(void* h) {
  auto* handle = static_cast<FooterHandle*>(h);
  int64_t total = 0;
  if (auto* gf = handle->meta.find(srjt::fmd::kRowGroups))
    for (auto& g : gf->val->elems) total += g.get_i(srjt::rg::kNumRows, 0);
  return total;
}

int64_t srjt_footer_num_columns(void* h) {
  auto* handle = static_cast<FooterHandle*>(h);
  if (auto* sf = handle->meta.find(srjt::fmd::kSchema))
    if (!sf->val->elems.empty())
      return sf->val->elems[0].get_i(srjt::se::kNumChildren, 0);
  return 0;
}

// Serialize with full-file framing "PAR1" + thrift + u32 len + "PAR1"
// (NativeParquetJni.cpp:666-699).  Two-call protocol: pass null to size.
int64_t srjt_footer_serialize(void* h, uint8_t* out, uint64_t out_capacity,
                              char* err, uint64_t err_len) {
  try {
    auto* handle = static_cast<FooterHandle*>(h);
    srjt::CompactWriter writer;
    writer.write_struct(handle->meta);
    const auto& body = writer.buffer();
    uint64_t total = body.size() + 12;
    if (!out) return static_cast<int64_t>(total);
    if (out_capacity < total) {
      fill_err(err, err_len, "output buffer too small");
      return -1;
    }
    std::memcpy(out, "PAR1", 4);
    std::memcpy(out + 4, body.data(), body.size());
    uint32_t len32 = static_cast<uint32_t>(body.size());
    out[4 + body.size() + 0] = static_cast<uint8_t>(len32 & 0xFF);
    out[4 + body.size() + 1] = static_cast<uint8_t>((len32 >> 8) & 0xFF);
    out[4 + body.size() + 2] = static_cast<uint8_t>((len32 >> 16) & 0xFF);
    out[4 + body.size() + 3] = static_cast<uint8_t>((len32 >> 24) & 0xFF);
    std::memcpy(out + 8 + body.size(), "PAR1", 4);
    return static_cast<int64_t>(total);
  } catch (std::exception& e) {
    fill_err(err, err_len, e.what());
    return -1;
  }
}

void srjt_footer_free(void* h) { delete static_cast<FooterHandle*>(h); }

}  // extern "C"
