"""Parquet data-page decode → device columnar tables.

The reference gets Parquet decode for free from libcudf's CUDA reader
(SURVEY §2.9); this module is the TPU-framework equivalent scan path:
footer via ``footer.py``/the native engine, then page decode on host
(vectorized NumPy bit-twiddling) and a single upload into device columns.
A Pallas on-device bit-unpack is the planned optimization for the hot
encodings; the host path is the correctness baseline and fallback.

Supported (the TPC-H/TPC-DS working set, BASELINE configs #2-#4):
* physical types BOOLEAN, INT32, INT64, INT96 (legacy Impala timestamps),
  FLOAT, DOUBLE, BYTE_ARRAY, FIXED_LEN_BYTE_ARRAY
* converted types DECIMAL (int32/int64/FLBA → decimal32/64/128), DATE,
  TIMESTAMP_MILLIS/MICROS, UTF8
* encodings PLAIN, RLE, PLAIN_DICTIONARY / RLE_DICTIONARY,
  DELTA_BINARY_PACKED, DELTA_LENGTH_BYTE_ARRAY, DELTA_BYTE_ARRAY
* definition levels (RLE/bit-packed hybrid) for optional columns;
  repetition levels for single-level LIST columns (max_rep == 1, both the
  3-level LIST annotation and the legacy repeated-primitive form)
* codecs UNCOMPRESSED, GZIP/zlib (stdlib), and SNAPPY (pure-Python decoder
  in ``parquet/snappy.py``; python-snappy accelerates it when present)
* data page v1 and v2

Deeper repetition (lists of lists, max_rep > 1) is rejected.
"""

from __future__ import annotations

import struct as _struct
import zlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from ..faultinj import fault_site
from ..utils import metrics
from .footer import FMD, RG, CC, SE, extract_footer_bytes
from .thrift import CompactReader, Struct

try:
    import snappy as _snappy  # optional
except ImportError:
    _snappy = None

# parquet physical types
PT_BOOLEAN, PT_INT32, PT_INT64, PT_INT96, PT_FLOAT, PT_DOUBLE, PT_BYTE_ARRAY, \
    PT_FIXED_LEN_BYTE_ARRAY = range(8)
# encodings
ENC_PLAIN, _, ENC_PLAIN_DICTIONARY, ENC_RLE, ENC_BIT_PACKED, \
    ENC_DELTA_BINARY_PACKED, ENC_DELTA_LENGTH_BYTE_ARRAY, \
    ENC_DELTA_BYTE_ARRAY, ENC_RLE_DICTIONARY = range(9)
# codecs
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
# page types
PAGE_DATA, PAGE_INDEX, PAGE_DICTIONARY, PAGE_DATA_V2 = range(4)


class PH:          # PageHeader field ids (public parquet.thrift)
    TYPE = 1
    UNCOMPRESSED_SIZE = 2
    COMPRESSED_SIZE = 3
    DATA_PAGE = 5
    DICT_PAGE = 7
    DATA_PAGE_V2 = 8


class DPH:         # DataPageHeader
    NUM_VALUES = 1
    ENCODING = 2
    DEF_LEVEL_ENCODING = 3
    REP_LEVEL_ENCODING = 4


class DPH2:        # DataPageHeaderV2
    NUM_VALUES = 1
    NUM_NULLS = 2
    NUM_ROWS = 3
    ENCODING = 4
    DEF_LEVELS_BYTE_LENGTH = 5
    REP_LEVELS_BYTE_LENGTH = 6
    IS_COMPRESSED = 7


class CMD:         # ColumnMetaData (decode-relevant fields)
    TYPE = 1
    ENCODINGS = 2
    PATH = 3
    CODEC = 4
    NUM_VALUES = 5
    TOTAL_COMPRESSED_SIZE = 7
    DATA_PAGE_OFFSET = 9
    INDEX_PAGE_OFFSET = 10
    DICT_PAGE_OFFSET = 11
    STATISTICS = 12


class ST:          # Statistics (row-group pruning fields)
    MAX = 1        # deprecated physical-order max (fallback)
    MIN = 2        # deprecated physical-order min (fallback)
    NULL_COUNT = 3
    DISTINCT_COUNT = 4
    MAX_VALUE = 5  # logical-order max (preferred)
    MIN_VALUE = 6  # logical-order min (preferred)


_PHYS_NP = {PT_INT32: np.dtype("<i4"), PT_INT64: np.dtype("<i8"),
            PT_FLOAT: np.dtype("<f4"), PT_DOUBLE: np.dtype("<f8")}
_PHYS_DT = {PT_INT32: T.int32, PT_INT64: T.int64,
            PT_FLOAT: T.float32, PT_DOUBLE: T.float64,
            PT_BOOLEAN: T.bool8, PT_BYTE_ARRAY: T.string,
            PT_INT96: T.timestamp_ns,
            PT_FIXED_LEN_BYTE_ARRAY: T.string}

# ConvertedType enum values (public parquet.thrift)
CT_UTF8, CT_MAP, CT_MAP_KEY_VALUE, CT_LIST, CT_ENUM, CT_DECIMAL, CT_DATE, \
    CT_TIME_MILLIS, CT_TIME_MICROS, CT_TIMESTAMP_MILLIS, \
    CT_TIMESTAMP_MICROS = range(11)

# SchemaElement decimal metadata (parquet.thrift SchemaElement)
SE_SCALE, SE_PRECISION = 7, 8

_JULIAN_UNIX_EPOCH = 2440588   # Julian day number of 1970-01-01


def _decompress(data: bytes, codec: int, uncompressed_size: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_GZIP:
        return zlib.decompress(data, wbits=31)
    if codec == CODEC_SNAPPY:
        if _snappy is not None:          # optional C accelerator
            return _snappy.decompress(data)
        out = _native_snappy(data, uncompressed_size)
        if out is not None:              # in-tree native decoder (libsrjt)
            return out
        from . import snappy as _pysnappy
        return _pysnappy.decompress(data, expected_size=uncompressed_size)
    raise NotImplementedError(f"unsupported parquet codec {codec}")


def _native_snappy(data: bytes, uncompressed_size: int):
    """Raw-snappy via the in-tree native lib; None if unavailable/invalid."""
    import ctypes
    from .. import native as _native
    lib = _native.load()
    if lib is None or uncompressed_size is None:
        return None
    try:
        fn = lib.srjt_snappy_decompress   # bound in native._bind()
    except AttributeError:
        return None                      # stale .so without the symbol
    out = ctypes.create_string_buffer(uncompressed_size)
    rc = fn(data, len(data), out, uncompressed_size)
    if rc != uncompressed_size:
        return None                      # fall through to the pure decoder
    return out.raw


def _bit_width(max_level: int) -> int:
    return int(max_level).bit_length()


def decode_rle_bitpacked_hybrid(buf: bytes, bit_width: int,
                                count: int) -> np.ndarray:
    """RLE/bit-packed hybrid (parquet format): returns uint32 [count].

    Vectorized per run: bit-packed groups unpack via np.unpackbits
    little-endian reassembly; RLE runs are a fill.
    """
    out = np.empty(count, dtype=np.uint32)
    pos = 0
    written = 0
    if bit_width == 0:
        out[:] = 0
        return out
    while written < count:
        header = 0
        shift = 0
        while True:
            b = buf[pos]; pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:   # bit-packed run: (header>>1) groups of 8 values
            groups = header >> 1
            n_vals = groups * 8
            n_bytes = groups * bit_width
            chunk = np.frombuffer(buf, dtype=np.uint8, count=n_bytes,
                                  offset=pos)
            pos += n_bytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(n_vals, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.uint32))
            decoded = (vals.astype(np.uint32) * weights).sum(axis=1,
                                                             dtype=np.uint32)
            take = min(n_vals, count - written)
            out[written:written + take] = decoded[:take]
            written += take
        else:            # RLE run: value stored in ceil(bit_width/8) bytes
            run_len = header >> 1
            n_bytes = (bit_width + 7) // 8
            val = int.from_bytes(buf[pos:pos + n_bytes], "little")
            pos += n_bytes
            take = min(run_len, count - written)
            out[written:written + take] = val
            written += take
    return out


def _uleb128(buf: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]; pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _unpack_bits_le(chunk: np.ndarray, n_vals: int, bw: int) -> np.ndarray:
    """Little-endian bit-unpack: n_vals values of bw bits → int64."""
    if bw == 0:
        return np.zeros(n_vals, dtype=np.int64)
    bits = np.unpackbits(chunk, bitorder="little")[:n_vals * bw]
    weights = (1 << np.arange(bw, dtype=np.int64))
    return (bits.reshape(n_vals, bw).astype(np.int64) * weights).sum(axis=1)


def decode_delta_binary_packed(buf: bytes, pos: int = 0
                               ) -> tuple[np.ndarray, int]:
    """DELTA_BINARY_PACKED → (int64 values, end position).

    Layout (parquet encodings spec): ULEB128 block_size, miniblocks/block,
    total count, zigzag first value; then per block a zigzag min-delta,
    one bitwidth byte per miniblock, and LE-bit-packed deltas.  Values are
    first + prefix-sums of (min_delta + delta) — the cumsum is one
    vectorized pass per miniblock.
    """
    block_size, pos = _uleb128(buf, pos)
    n_mini, pos = _uleb128(buf, pos)
    total, pos = _uleb128(buf, pos)
    first_raw, pos = _uleb128(buf, pos)
    first = _zigzag(first_raw)
    vals_per_mini = block_size // n_mini
    deltas = []
    remaining = total - 1
    while remaining > 0:
        min_raw, pos = _uleb128(buf, pos)
        min_delta = _zigzag(min_raw)
        bws = np.frombuffer(buf, np.uint8, n_mini, pos)
        pos += n_mini
        for m in range(n_mini):
            if remaining <= 0:
                # trailing miniblock bytes of the last block still occupy
                # the stream for non-zero bitwidths
                pos += (int(bws[m]) * vals_per_mini) // 8
                continue
            bw = int(bws[m])
            nbytes = (bw * vals_per_mini) // 8
            chunk = np.frombuffer(buf, np.uint8, nbytes, pos)
            pos += nbytes
            d = _unpack_bits_le(chunk, vals_per_mini, bw) + min_delta
            take = min(vals_per_mini, remaining)
            deltas.append(d[:take])
            remaining -= take
    if deltas:
        all_d = np.concatenate(deltas)
        out = np.empty(total, dtype=np.int64)
        out[0] = first
        np.cumsum(all_d, out=out[1:])
        out[1:] += first
    else:
        out = np.full(max(total, 0), first, dtype=np.int64)
    return out, pos


def _decode_delta_length_byte_array(data: bytes, n: int):
    lengths, pos = decode_delta_binary_packed(data)
    chars = np.frombuffer(data, np.uint8, int(lengths.sum()), pos)
    return chars.copy(), lengths.astype(np.int32)


def _decode_delta_byte_array(data: bytes, n: int):
    """DELTA_BYTE_ARRAY: shared-prefix lengths + suffix stream.

    Reconstruction is inherently sequential (each value references the
    previous one) — host loop, matching the spec's reference decoding.
    """
    prefix_lens, pos = decode_delta_binary_packed(data)
    suffix_lens, pos = decode_delta_binary_packed(data, pos)
    suffix = np.frombuffer(data, np.uint8, int(suffix_lens.sum()), pos)
    out_lens = (prefix_lens + suffix_lens).astype(np.int32)
    chars = np.empty(int(out_lens.sum()), dtype=np.uint8)
    prev_start = 0
    spos = cursor = 0
    for i in range(len(out_lens)):
        pl, sl = int(prefix_lens[i]), int(suffix_lens[i])
        start = cursor
        chars[cursor:cursor + pl] = chars[prev_start:prev_start + pl]
        cursor += pl
        chars[cursor:cursor + sl] = suffix[spos:spos + sl]
        cursor += sl
        spos += sl
        prev_start = start
    return chars, out_lens


def _decode_int96(data: bytes, n: int) -> np.ndarray:
    """INT96 legacy timestamps → int64 nanoseconds since the Unix epoch.

    Each value is 8 LE bytes of nanos-within-day + 4 LE bytes Julian day
    (the Impala convention the reference's Spark plugin must also honor).
    """
    raw = np.frombuffer(data, np.uint8, n * 12).reshape(n, 12)
    nanos = raw[:, :8].copy().view("<u8").reshape(n).astype(np.int64)
    days = raw[:, 8:].copy().view("<i4").reshape(n).astype(np.int64)
    return (days - _JULIAN_UNIX_EPOCH) * 86_400_000_000_000 + nanos


def _decode_plain(data: bytes, phys: int, n: int, type_len: int = 0):
    """PLAIN-encoded values → (values ndarray or (chars, lengths) for strings)."""
    if phys in _PHYS_NP:
        return np.frombuffer(data, dtype=_PHYS_NP[phys], count=n)
    if phys == PT_BOOLEAN:
        return np.unpackbits(np.frombuffer(data, dtype=np.uint8),
                             count=n, bitorder="little").astype(np.uint8)
    if phys == PT_INT96:
        return _decode_int96(data, n)
    if phys == PT_FIXED_LEN_BYTE_ARRAY:
        chars = np.frombuffer(data, np.uint8, n * type_len).copy()
        return chars, np.full(n, type_len, dtype=np.int32)
    if phys == PT_BYTE_ARRAY:
        offs = byte_array_offsets(data, n)
        if offs is not None:
            # native walk, then ONE vectorized gather strips the 4-byte
            # prefixes: char k belongs to row_of(k) and sits 4*(row+1)
            # prefix bytes past its packed position
            lengths = offs[1:] - offs[:-1]
            arr = np.frombuffer(data, dtype=np.uint8)
            total = int(offs[-1])
            row_of = np.repeat(np.arange(n, dtype=np.int64), lengths)
            chars = arr[np.arange(total, dtype=np.int64) + 4 * (row_of + 1)]
            return chars, lengths
        # pure-python fallback (no native lib): walk the prefixes
        lengths = np.empty(n, dtype=np.int32)
        starts = np.empty(n, dtype=np.int64)
        pos = 0
        for i in range(n):
            (ln,) = _struct.unpack_from("<I", data, pos)
            pos += 4
            starts[i] = pos
            lengths[i] = ln
            pos += ln
        total = int(lengths.sum())
        chars = np.empty(total, dtype=np.uint8)
        arr = np.frombuffer(data, dtype=np.uint8)
        cursor = 0
        for i in range(n):
            chars[cursor:cursor + lengths[i]] = \
                arr[starts[i]:starts[i] + lengths[i]]
            cursor += lengths[i]
        return chars, lengths
    raise NotImplementedError(f"unsupported physical type {phys}")


def byte_array_offsets(data: bytes, n: int) -> "np.ndarray | None":
    """Arrow char offsets [n+1] of a PLAIN BYTE_ARRAY payload via the
    native walker (the offsets recurrence is sequential — C-rate, not
    Python-rate); None when the native lib is absent or input malformed."""
    from .. import native as _native
    lib = _native.load()
    if lib is None:
        return None
    try:
        fn = lib.srjt_byte_array_offsets
    except AttributeError:
        return None
    offs = np.empty(n + 1, dtype=np.int32)
    rc = fn(data, len(data), n, offs.ctypes.data)
    if rc < 0:
        return None
    return offs


class _PageStream:
    """Sequential reader over a column chunk's pages."""

    def __init__(self, buf: bytes, codec: int):
        self.buf = buf
        self.pos = 0
        self.codec = codec

    def next_page(self):
        reader = CompactReader(self.buf, self.pos)
        header = reader.read_struct()
        self.pos = reader.pos
        comp_size = header.get(PH.COMPRESSED_SIZE)
        raw = self.buf[self.pos:self.pos + comp_size]
        self.pos += comp_size
        return header, raw


_VARLEN_PHYS = (PT_BYTE_ARRAY, PT_FIXED_LEN_BYTE_ARRAY)


def _decode_chunk(file_bytes: bytes, chunk: Struct, max_def: int,
                  max_rep: int = 0, type_len: int = 0):
    """Decode one column chunk → (values, lengths_or_none, defs, reps).

    ``values``/``lengths`` cover only the PRESENT slots (def == max_def);
    ``defs``/``reps`` are per-slot level arrays (None when the schema has
    none) — callers assemble validity / list structure from them.
    """
    md = chunk.get(CC.META_DATA)
    phys = md.get(CMD.TYPE)
    codec = md.get(CMD.CODEC, 0)
    num_values = md.get(CMD.NUM_VALUES)
    start = md.get(CMD.DATA_PAGE_OFFSET)
    dict_off = md.get(CMD.DICT_PAGE_OFFSET)
    if dict_off is not None and dict_off < start:
        start = dict_off
    total = md.get(CMD.TOTAL_COMPRESSED_SIZE)
    stream = _PageStream(file_bytes[start:start + total], codec)

    rec = metrics.recording()      # one check per chunk, not per page
    if rec:
        codec_name = {CODEC_UNCOMPRESSED: "uncompressed",
                      CODEC_SNAPPY: "snappy",
                      CODEC_GZIP: "gzip"}.get(codec, f"codec{codec}")
        metrics.count("parquet.chunks")
        metrics.count("parquet.bytes.compressed", total)
        metrics.count(f"parquet.codec.{codec_name}.chunks")

    dictionary = None
    vals_parts, len_parts, def_parts, rep_parts = [], [], [], []
    decoded = 0
    while decoded < num_values:
        header, raw = stream.next_page()
        ptype = header.get(PH.TYPE)
        usize = header.get(PH.UNCOMPRESSED_SIZE)
        if rec and ptype in (PAGE_DATA, PAGE_DATA_V2, PAGE_DICTIONARY):
            metrics.count("parquet.pages.dict" if ptype == PAGE_DICTIONARY
                          else "parquet.pages.data")
            metrics.count("parquet.bytes.uncompressed", usize or 0)
        if ptype == PAGE_DICTIONARY:
            dph = header.get(PH.DICT_PAGE)
            data = _decompress(raw, codec, usize)
            dictionary = _decode_plain(data, phys, dph.get(DPH.NUM_VALUES),
                                       type_len)
            continue
        if ptype == PAGE_DATA:
            dph = header.get(PH.DATA_PAGE)
            n = dph.get(DPH.NUM_VALUES)
            enc = dph.get(DPH.ENCODING)
            data = _decompress(raw, codec, usize)
            pos = 0
            defs = reps = None
            if max_rep > 0:   # repetition levels precede definition levels
                (ln,) = _struct.unpack_from("<I", data, pos)
                pos += 4
                reps = decode_rle_bitpacked_hybrid(
                    data[pos:pos + ln], _bit_width(max_rep), n)
                pos += ln
            if max_def > 0:
                (ln,) = _struct.unpack_from("<I", data, pos)
                pos += 4
                defs = decode_rle_bitpacked_hybrid(
                    data[pos:pos + ln], _bit_width(max_def), n)
                pos += ln
            page_vals = data[pos:]
        elif ptype == PAGE_DATA_V2:
            dph = header.get(PH.DATA_PAGE_V2)
            n = dph.get(DPH2.NUM_VALUES)
            enc = dph.get(DPH2.ENCODING)
            dl_len = dph.get(DPH2.DEF_LEVELS_BYTE_LENGTH, 0)
            rl_len = dph.get(DPH2.REP_LEVELS_BYTE_LENGTH, 0)
            defs = reps = None
            body = raw[dl_len + rl_len:]
            if dph.get(DPH2.IS_COMPRESSED, True):
                body = _decompress(
                    body, codec, usize - dl_len - rl_len)
            if max_rep > 0 and rl_len:
                reps = decode_rle_bitpacked_hybrid(
                    raw[:rl_len], _bit_width(max_rep), n)
            if max_def > 0 and dl_len:
                defs = decode_rle_bitpacked_hybrid(
                    raw[rl_len:rl_len + dl_len], _bit_width(max_def), n)
            page_vals = body
        else:
            continue  # index pages etc.

        n_present = n if defs is None else int((defs == max_def).sum())
        if enc == ENC_PLAIN:
            vals = _decode_plain(page_vals, phys, n_present, type_len)
        elif enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            if dictionary is None:
                raise ValueError("dictionary-encoded page before dictionary")
            bw = page_vals[0]
            idx = decode_rle_bitpacked_hybrid(page_vals[1:], bw, n_present)
            if phys in _VARLEN_PHYS:
                dchars, dlens = dictionary
                dstarts = np.zeros(len(dlens) + 1, dtype=np.int64)
                np.cumsum(dlens, out=dstarts[1:])
                lens = dlens[idx].astype(np.int64)
                total_c = int(lens.sum())
                # one vectorized gather instead of a per-row copy loop:
                # char k of the output copies from its row's dictionary
                # entry at (entry start + position within the row)
                out_offs = np.zeros(idx.shape[0] + 1, np.int64)
                np.cumsum(lens, out=out_offs[1:])
                src = (np.repeat(dstarts[idx], lens)
                       + np.arange(total_c, dtype=np.int64)
                       - np.repeat(out_offs[:-1], lens))
                chars = dchars[src]
                vals = (chars, lens.astype(np.int32))
            else:
                vals = dictionary[idx]
        elif enc == ENC_DELTA_BINARY_PACKED and phys in (PT_INT32, PT_INT64):
            decoded_vals, _ = decode_delta_binary_packed(page_vals)
            vals = decoded_vals[:n_present].astype(_PHYS_NP[phys])
        elif enc == ENC_DELTA_LENGTH_BYTE_ARRAY and phys == PT_BYTE_ARRAY:
            vals = _decode_delta_length_byte_array(page_vals, n_present)
        elif enc == ENC_DELTA_BYTE_ARRAY and phys in _VARLEN_PHYS:
            vals = _decode_delta_byte_array(page_vals, n_present)
        else:
            raise NotImplementedError(f"unsupported encoding {enc}")

        if phys in _VARLEN_PHYS:
            vals_parts.append(vals[0])
            len_parts.append(vals[1])
        else:
            vals_parts.append(vals)
        if defs is not None:
            def_parts.append(defs)
        if reps is not None:
            rep_parts.append(reps)
        decoded += n

    if rec:
        metrics.count("parquet.values_decoded", decoded)
    defs_all = np.concatenate(def_parts) if def_parts else None
    reps_all = np.concatenate(rep_parts) if rep_parts else None
    if phys in _VARLEN_PHYS:
        chars = (np.concatenate(vals_parts) if vals_parts
                 else np.zeros(0, np.uint8))
        lens = (np.concatenate(len_parts) if len_parts
                else np.zeros(0, np.int32))
        return chars, lens, defs_all, reps_all
    values = (np.concatenate(vals_parts) if vals_parts
              else np.zeros(0, np.int32))
    return values, None, defs_all, reps_all


class _Leaf:
    """One leaf column's schema facts, gathered by the depth-first walk."""

    def __init__(self, elem, max_def, max_rep, d_list, path):
        self.elem = elem
        self.max_def = max_def          # def level meaning "value present"
        self.max_rep = max_rep          # 0 = flat, 1 = single-level list
        self.d_list = d_list            # def level at the repeated node
        self.path = path
        # user-facing column name: struct leaves keep their full dotted
        # path (each leaf is a distinct output column); LIST leaves take
        # the outer field name (the chunk path is "name.list.element")
        self.name = path.split(".")[0] if max_rep > 0 else path

    @property
    def phys(self):
        return self.elem.get(SE.TYPE)

    @property
    def type_len(self):
        return self.elem.get(SE.TYPE_LENGTH, 0) or 0

    def logical_dtype(self) -> T.DType:
        """Element-level logical dtype from physical + converted type."""
        phys = self.phys
        ct = self.elem.get(SE.CONVERTED_TYPE)
        if ct == CT_DECIMAL:
            scale = -(self.elem.get(SE_SCALE, 0) or 0)
            precision = self.elem.get(SE_PRECISION, 0) or 0
            if phys == PT_INT32:
                return T.decimal32(scale)
            if phys == PT_INT64:
                return T.decimal64(scale)
            if phys in _VARLEN_PHYS:
                if precision and precision <= 9:
                    return T.decimal32(scale)
                if precision and precision <= 18:
                    return T.decimal64(scale)
                return T.decimal128(scale)
            raise NotImplementedError(f"DECIMAL on physical type {phys}")
        if ct == CT_DATE and phys == PT_INT32:
            return T.timestamp_days
        if ct == CT_TIMESTAMP_MILLIS and phys == PT_INT64:
            return T.timestamp_ms
        if ct == CT_TIMESTAMP_MICROS and phys == PT_INT64:
            return T.timestamp_us
        return _PHYS_DT[phys]


class NestedDecodeUnsupported(NotImplementedError):
    """The file's schema needs nested decode (list-of-list or MAP).

    Raised while walking the footer schema — BEFORE any chunk I/O or page
    decode — so callers see the offending column paths up front instead of
    a failure deep inside the decode pipeline.  The footer pruner
    deliberately keeps accepting these schemas (other columns of the same
    file remain readable once projection prunes the nested ones)."""


def _leaf_schema_elements(meta: Struct) -> list[_Leaf]:
    """Depth-first walk: leaves with def/rep depths (Dremel levels).

    Raises :class:`NestedDecodeUnsupported` for schema shapes the decoder
    cannot produce columns for: repetition depth > 1 (lists of lists) and
    MAP/MAP_KEY_VALUE groups (their key/value leaves would alias one
    output column name)."""
    schema = meta.get(FMD.SCHEMA).values
    out: list[_Leaf] = []
    bad: list[str] = []

    def walk(idx: int, depth_def: int, depth_rep: int, d_list: int,
             prefix: str):
        elem = schema[idx]
        n = elem.get(SE.NUM_CHILDREN, 0) or 0
        name = elem.get(SE.NAME, b"").decode("utf-8")
        rep = elem.get(SE.REPETITION_TYPE, 0)
        # optional (1) adds a definition level; repeated (2) adds both a
        # definition and a repetition level
        my_def = depth_def + (1 if rep in (1, 2) else 0)
        my_rep = depth_rep + (1 if rep == 2 else 0)
        my_dlist = my_def if rep == 2 else d_list
        path = f"{prefix}.{name}" if prefix else name
        ct = elem.get(SE.CONVERTED_TYPE)
        if my_rep > 1:
            bad.append(f"{path} (nested lists, max_rep > 1)")
        elif n and ct in (CT_MAP, CT_MAP_KEY_VALUE):
            bad.append(f"{path} (MAP)")
        idx += 1
        if n == 0:
            out.append(_Leaf(elem, my_def, my_rep, my_dlist, path))
            return idx
        for _ in range(n):
            idx = walk(idx, my_def, my_rep, my_dlist, path)
        return idx

    idx = 1
    root_children = schema[0].get(SE.NUM_CHILDREN, 0) or 0
    for _ in range(root_children):
        idx = walk(idx, 0, 0, 0, "")
    if bad:
        raise NestedDecodeUnsupported(
            "nested decode unsupported: " + ", ".join(bad))
    return out


def _be_varlen_decimal_to_lanes(chars: np.ndarray,
                                lens: np.ndarray) -> np.ndarray:
    """Variable-length BYTE_ARRAY decimals (parquet-mr/Hive legacy writers)
    → [n, 2] int64 lane pairs.  Per-value host loop — cold legacy path."""
    n = lens.shape[0]
    lanes = np.zeros((n, 2), dtype=np.int64)
    raw = chars.tobytes()
    pos = 0
    for i in range(n):
        ln = int(lens[i])
        v = int.from_bytes(raw[pos:pos + ln], "big", signed=True) if ln else 0
        pos += ln
        u = v & ((1 << 128) - 1)
        lo = u & ((1 << 64) - 1)
        hi = u >> 64
        lanes[i, 0] = np.int64(lo - (1 << 64) if lo >= (1 << 63) else lo)
        lanes[i, 1] = np.int64(hi - (1 << 64) if hi >= (1 << 63) else hi)
    return lanes


def _be_decimal_to_lanes(chars: np.ndarray, width: int) -> np.ndarray:
    """Big-endian two's-complement FLBA decimals → [n, 2] int64 lane pairs."""
    n = chars.shape[0] // width if width else 0
    b = chars.reshape(n, width)
    sign = b[:, 0] >= 0x80
    full = np.empty((n, 16), dtype=np.uint8)
    full[:, :16 - width] = np.where(sign, 0xFF, 0)[:, None]
    full[:, 16 - width:] = b
    hi = full[:, :8].copy().view(">i8").reshape(n).astype(np.int64)
    # read big-endian VALUE first (astype converts), then reinterpret the
    # native bits as int64 — a direct .view on the BE array would byteswap
    lo = (full[:, 8:].copy().view(">u8").reshape(n)
          .astype(np.uint64).view(np.int64))
    return np.stack([lo, hi], axis=1)


def _present_leaf_column(leaf: _Leaf, values, lens, valid) -> Column:
    """Build the element-level Column from present-slot arrays + validity."""
    dt = leaf.logical_dtype()
    phys = leaf.phys
    jvalid = None if valid is None else jnp.asarray(valid)
    nrows = valid.shape[0] if valid is not None else _n_present(leaf, values,
                                                               lens)
    if phys in _VARLEN_PHYS and dt.is_decimal:
        width = leaf.type_len
        if phys == PT_BYTE_ARRAY or not width:
            lanes = _be_varlen_decimal_to_lanes(values, lens)
        else:
            lanes = _be_decimal_to_lanes(values, width)
        if valid is not None:
            expanded = np.zeros((nrows, 2), dtype=np.int64)
            expanded[valid] = lanes
            lanes = expanded
        if dt.id == T.TypeId.DECIMAL128:
            return Column(dt, jnp.asarray(lanes), validity=jvalid)
        narrow = lanes[:, 0].astype(dt.storage)
        return Column(dt, jnp.asarray(narrow), validity=jvalid)
    if phys in _VARLEN_PHYS:
        # strings (incl. fixed-len binary): re-expand lengths over nulls
        if valid is not None:
            full_lens = np.zeros(nrows, dtype=np.int64)
            full_lens[valid] = lens
        else:
            full_lens = lens.astype(np.int64)
        offs = np.zeros(full_lens.shape[0] + 1, dtype=np.int32)
        np.cumsum(full_lens, out=offs[1:])
        joffs = jnp.asarray(offs)
        from ..utils import hostcache
        hostcache.seed(joffs, offs.astype(np.int64))
        return Column(T.string if not dt.is_decimal else dt,
                      jnp.asarray(values), joffs, jvalid)
    if valid is not None:
        full = np.zeros(nrows, dtype=values.dtype)
        full[valid] = values
        values = full
    host = np.ascontiguousarray(values, dtype=dt.storage)
    if dt.id == T.TypeId.FLOAT64:
        # Column invariant: f64 payloads upload as u32 bit pairs (exact)
        from ..utils import f64bits
        host = f64bits.np_to_bits(host)
    return Column(dt, jnp.asarray(host), validity=jvalid)


def _n_present(leaf, values, lens):
    return lens.shape[0] if leaf.phys in _VARLEN_PHYS else values.shape[0]


def _concat_parts(leaf: _Leaf, parts):
    """(values, lens_or_none) concatenated across row-group parts."""
    values = np.concatenate([p[0] for p in parts])
    lens = (np.concatenate([p[1] for p in parts])
            if leaf.phys in _VARLEN_PHYS else None)
    return values, lens


def _assemble_flat(leaf: _Leaf, parts) -> Column:
    """Concatenate row-group parts of a flat column into one Column."""
    defs = None
    if any(p[2] is not None for p in parts):
        defs = np.concatenate(
            [p[2] if p[2] is not None
             else np.full(_n_present(leaf, p[0], p[1]), leaf.max_def,
                          dtype=np.uint32) for p in parts])
    valid = None if defs is None else defs == leaf.max_def
    values, lens = _concat_parts(leaf, parts)
    return _present_leaf_column(leaf, values, lens, valid)


def _assemble_list(leaf: _Leaf, parts) -> Column:
    """Dremel assembly of a single-level LIST column.

    Per slot: rep == 0 starts a new row.  def >= d_list ⇒ the slot is an
    element (null element unless def == max_def); def == d_list-1 ⇒ empty
    list; def < d_list-1 ⇒ the list itself is null at some ancestor.
    """
    defs = np.concatenate([p[2] for p in parts])
    reps = np.concatenate([p[3] if p[3] is not None
                           else np.zeros(p[2].shape[0], np.uint32)
                           for p in parts])
    is_elem = defs >= leaf.d_list
    row_start = reps == 0
    nrows = int(row_start.sum())
    # list lengths: count element slots per row
    row_id = np.cumsum(row_start) - 1
    lengths = np.zeros(nrows, dtype=np.int64)
    np.add.at(lengths, row_id[is_elem], 1)
    offsets = np.zeros(nrows + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    # list validity from each row's first slot
    first_defs = defs[row_start]
    list_valid = first_defs >= leaf.d_list - 1
    jlist_valid = None if list_valid.all() else jnp.asarray(list_valid)
    # element column from element slots
    elem_valid = defs[is_elem] == leaf.max_def
    if leaf.max_def == leaf.d_list:      # required elements: all valid
        evalid = None
    else:
        evalid = elem_valid
    values, lens = _concat_parts(leaf, parts)
    child = _present_leaf_column(leaf, values, lens, evalid)
    dtype = T.list_(child.dtype)
    return Column(dtype, jnp.zeros((0,), jnp.uint8), jnp.asarray(offsets),
                  jlist_valid, [child])


def _empty_leaf_column(leaf: _Leaf) -> Column:
    """Zero-row Column for ``leaf`` (all row groups pruned)."""
    if leaf.phys in _VARLEN_PHYS:
        values = np.zeros(0, dtype=np.uint8)
        lens = np.zeros(0, dtype=np.int64)
    else:
        values = np.zeros(0, dtype=_PHYS_NP.get(leaf.phys, np.uint8))
        lens = None
    child = _present_leaf_column(leaf, values, lens, None)
    if leaf.max_rep > 0:
        return Column(T.list_(child.dtype), jnp.zeros((0,), jnp.uint8),
                      jnp.zeros((1,), jnp.int32), None, [child])
    return child


@fault_site("parquet_read_table")
def read_table(file_bytes: bytes,
               columns: Optional[list[str]] = None,
               row_groups: Optional[list[int]] = None) -> Table:
    """Read a parquet file into a device Table.

    ``columns`` selects by user-facing column name (for LIST columns, the
    outer field name — the underlying chunk path is ``name.list.element``).
    ``row_groups`` selects row groups by index (None = all; order within
    the file is preserved regardless of the order given) — the planner's
    statistics-driven pruning path.
    """
    from .thrift import parse_struct
    meta = parse_struct(extract_footer_bytes(file_bytes))
    leaves = _leaf_schema_elements(meta)
    names = [leaf.name for leaf in leaves]
    want = list(range(len(leaves))) if columns is None else [
        names.index(c) for c in columns]

    with metrics.span("parquet.read_table", columns=len(want),
                      file_bytes=len(file_bytes)):
        groups = meta.get(FMD.ROW_GROUPS)
        keep = (None if row_groups is None else set(row_groups))
        per_col_parts: dict[int, list] = {i: [] for i in want}
        for gi, rg in enumerate(groups.values):
            if keep is not None and gi not in keep:
                continue
            chunks = rg.get(RG.COLUMNS).values
            for i in want:
                leaf = leaves[i]
                per_col_parts[i].append(
                    _decode_chunk(file_bytes, chunks[i], leaf.max_def,
                                  leaf.max_rep, leaf.type_len))

        cols = []
        for i in want:
            leaf = leaves[i]
            parts = per_col_parts[i]
            if not parts:
                cols.append(_empty_leaf_column(leaf))
            elif leaf.max_rep > 0:
                cols.append(_assemble_list(leaf, parts))
            else:
                cols.append(_assemble_flat(leaf, parts))
        return Table(cols)
