"""Parquet data-page decode → device columnar tables.

The reference gets Parquet decode for free from libcudf's CUDA reader
(SURVEY §2.9); this module is the TPU-framework equivalent scan path:
footer via ``footer.py``/the native engine, then page decode on host
(vectorized NumPy bit-twiddling) and a single upload into device columns.
A Pallas on-device bit-unpack is the planned optimization for the hot
encodings; the host path is the correctness baseline and fallback.

Supported (the TPC-H/TPC-DS working set, BASELINE configs #2-#4):
* physical types BOOLEAN, INT32, INT64, FLOAT, DOUBLE, BYTE_ARRAY
* encodings PLAIN, RLE, PLAIN_DICTIONARY / RLE_DICTIONARY
* definition levels (RLE/bit-packed hybrid) for optional flat columns
* codecs UNCOMPRESSED, GZIP/zlib (stdlib), and SNAPPY (pure-Python decoder
  in ``parquet/snappy.py``; python-snappy accelerates it when present)
* data page v1 and v2

Nested columns (max repetition level > 0) are rejected for now.
"""

from __future__ import annotations

import struct as _struct
import zlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from ..column import Column, Table
from ..faultinj import fault_site
from .footer import FMD, RG, CC, SE, extract_footer_bytes
from .thrift import CompactReader, Struct

try:
    import snappy as _snappy  # optional
except ImportError:
    _snappy = None

# parquet physical types
PT_BOOLEAN, PT_INT32, PT_INT64, PT_INT96, PT_FLOAT, PT_DOUBLE, PT_BYTE_ARRAY, \
    PT_FIXED_LEN_BYTE_ARRAY = range(8)
# encodings
ENC_PLAIN, _, ENC_PLAIN_DICTIONARY, ENC_RLE, ENC_BIT_PACKED, \
    ENC_DELTA_BINARY_PACKED, ENC_DELTA_LENGTH_BYTE_ARRAY, \
    ENC_DELTA_BYTE_ARRAY, ENC_RLE_DICTIONARY = range(9)
# codecs
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_GZIP = 0, 1, 2
# page types
PAGE_DATA, PAGE_INDEX, PAGE_DICTIONARY, PAGE_DATA_V2 = range(4)


class PH:          # PageHeader field ids (public parquet.thrift)
    TYPE = 1
    UNCOMPRESSED_SIZE = 2
    COMPRESSED_SIZE = 3
    DATA_PAGE = 5
    DICT_PAGE = 7
    DATA_PAGE_V2 = 8


class DPH:         # DataPageHeader
    NUM_VALUES = 1
    ENCODING = 2
    DEF_LEVEL_ENCODING = 3
    REP_LEVEL_ENCODING = 4


class DPH2:        # DataPageHeaderV2
    NUM_VALUES = 1
    NUM_NULLS = 2
    NUM_ROWS = 3
    ENCODING = 4
    DEF_LEVELS_BYTE_LENGTH = 5
    REP_LEVELS_BYTE_LENGTH = 6
    IS_COMPRESSED = 7


class CMD:         # ColumnMetaData (decode-relevant fields)
    TYPE = 1
    ENCODINGS = 2
    PATH = 3
    CODEC = 4
    NUM_VALUES = 5
    TOTAL_COMPRESSED_SIZE = 7
    DATA_PAGE_OFFSET = 9
    INDEX_PAGE_OFFSET = 10
    DICT_PAGE_OFFSET = 11


_PHYS_NP = {PT_INT32: np.dtype("<i4"), PT_INT64: np.dtype("<i8"),
            PT_FLOAT: np.dtype("<f4"), PT_DOUBLE: np.dtype("<f8")}
_PHYS_DT = {PT_INT32: T.int32, PT_INT64: T.int64,
            PT_FLOAT: T.float32, PT_DOUBLE: T.float64,
            PT_BOOLEAN: T.bool8, PT_BYTE_ARRAY: T.string}


def _decompress(data: bytes, codec: int, uncompressed_size: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_GZIP:
        return zlib.decompress(data, wbits=31)
    if codec == CODEC_SNAPPY:
        if _snappy is not None:          # optional C accelerator
            return _snappy.decompress(data)
        from . import snappy as _pysnappy
        return _pysnappy.decompress(data, expected_size=uncompressed_size)
    raise NotImplementedError(f"unsupported parquet codec {codec}")


def _bit_width(max_level: int) -> int:
    return int(max_level).bit_length()


def decode_rle_bitpacked_hybrid(buf: bytes, bit_width: int,
                                count: int) -> np.ndarray:
    """RLE/bit-packed hybrid (parquet format): returns uint32 [count].

    Vectorized per run: bit-packed groups unpack via np.unpackbits
    little-endian reassembly; RLE runs are a fill.
    """
    out = np.empty(count, dtype=np.uint32)
    pos = 0
    written = 0
    if bit_width == 0:
        out[:] = 0
        return out
    while written < count:
        header = 0
        shift = 0
        while True:
            b = buf[pos]; pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:   # bit-packed run: (header>>1) groups of 8 values
            groups = header >> 1
            n_vals = groups * 8
            n_bytes = groups * bit_width
            chunk = np.frombuffer(buf, dtype=np.uint8, count=n_bytes,
                                  offset=pos)
            pos += n_bytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(n_vals, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.uint32))
            decoded = (vals.astype(np.uint32) * weights).sum(axis=1,
                                                             dtype=np.uint32)
            take = min(n_vals, count - written)
            out[written:written + take] = decoded[:take]
            written += take
        else:            # RLE run: value stored in ceil(bit_width/8) bytes
            run_len = header >> 1
            n_bytes = (bit_width + 7) // 8
            val = int.from_bytes(buf[pos:pos + n_bytes], "little")
            pos += n_bytes
            take = min(run_len, count - written)
            out[written:written + take] = val
            written += take
    return out


def _decode_plain(data: bytes, phys: int, n: int):
    """PLAIN-encoded values → (values ndarray or (chars, lengths) for strings)."""
    if phys in _PHYS_NP:
        return np.frombuffer(data, dtype=_PHYS_NP[phys], count=n)
    if phys == PT_BOOLEAN:
        return np.unpackbits(np.frombuffer(data, dtype=np.uint8),
                             count=n, bitorder="little").astype(np.uint8)
    if phys == PT_BYTE_ARRAY:
        # length-prefixed strings — vectorized walk of the length prefixes
        lengths = np.empty(n, dtype=np.int32)
        starts = np.empty(n, dtype=np.int64)
        pos = 0
        for i in range(n):
            (ln,) = _struct.unpack_from("<I", data, pos)
            pos += 4
            starts[i] = pos
            lengths[i] = ln
            pos += ln
        total = int(lengths.sum())
        chars = np.empty(total, dtype=np.uint8)
        arr = np.frombuffer(data, dtype=np.uint8)
        cursor = 0
        for i in range(n):
            chars[cursor:cursor + lengths[i]] = \
                arr[starts[i]:starts[i] + lengths[i]]
            cursor += lengths[i]
        return chars, lengths
    raise NotImplementedError(f"unsupported physical type {phys}")


class _PageStream:
    """Sequential reader over a column chunk's pages."""

    def __init__(self, buf: bytes, codec: int):
        self.buf = buf
        self.pos = 0
        self.codec = codec

    def next_page(self):
        reader = CompactReader(self.buf, self.pos)
        header = reader.read_struct()
        self.pos = reader.pos
        comp_size = header.get(PH.COMPRESSED_SIZE)
        raw = self.buf[self.pos:self.pos + comp_size]
        self.pos += comp_size
        return header, raw


def _decode_chunk(file_bytes: bytes, chunk: Struct, max_def: int):
    """Decode one flat column chunk → (values, lengths_or_none, valid_or_none)."""
    md = chunk.get(CC.META_DATA)
    phys = md.get(CMD.TYPE)
    codec = md.get(CMD.CODEC, 0)
    num_values = md.get(CMD.NUM_VALUES)
    start = md.get(CMD.DATA_PAGE_OFFSET)
    dict_off = md.get(CMD.DICT_PAGE_OFFSET)
    if dict_off is not None and dict_off < start:
        start = dict_off
    total = md.get(CMD.TOTAL_COMPRESSED_SIZE)
    stream = _PageStream(file_bytes[start:start + total], codec)

    dictionary = None
    vals_parts, len_parts, def_parts = [], [], []
    decoded = 0
    while decoded < num_values:
        header, raw = stream.next_page()
        ptype = header.get(PH.TYPE)
        usize = header.get(PH.UNCOMPRESSED_SIZE)
        if ptype == PAGE_DICTIONARY:
            dph = header.get(PH.DICT_PAGE)
            data = _decompress(raw, codec, usize)
            dictionary = _decode_plain(data, phys, dph.get(DPH.NUM_VALUES))
            continue
        if ptype == PAGE_DATA:
            dph = header.get(PH.DATA_PAGE)
            n = dph.get(DPH.NUM_VALUES)
            enc = dph.get(DPH.ENCODING)
            data = _decompress(raw, codec, usize)
            pos = 0
            defs = None
            if max_def > 0:
                (ln,) = _struct.unpack_from("<I", data, pos)
                pos += 4
                defs = decode_rle_bitpacked_hybrid(
                    data[pos:pos + ln], _bit_width(max_def), n)
                pos += ln
            page_vals = data[pos:]
        elif ptype == PAGE_DATA_V2:
            dph = header.get(PH.DATA_PAGE_V2)
            n = dph.get(DPH2.NUM_VALUES)
            enc = dph.get(DPH2.ENCODING)
            dl_len = dph.get(DPH2.DEF_LEVELS_BYTE_LENGTH, 0)
            rl_len = dph.get(DPH2.REP_LEVELS_BYTE_LENGTH, 0)
            if rl_len:
                raise NotImplementedError("nested (repeated) columns")
            defs = None
            levels = raw[:dl_len + rl_len]
            body = raw[dl_len + rl_len:]
            if dph.get(DPH2.IS_COMPRESSED, True):
                body = _decompress(
                    body, codec, usize - dl_len - rl_len)
            if max_def > 0 and dl_len:
                defs = decode_rle_bitpacked_hybrid(
                    levels, _bit_width(max_def), n)
            page_vals = body
        else:
            continue  # index pages etc.

        n_present = n if defs is None else int((defs == max_def).sum())
        if enc == ENC_PLAIN:
            vals = _decode_plain(page_vals, phys, n_present)
        elif enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            if dictionary is None:
                raise ValueError("dictionary-encoded page before dictionary")
            bw = page_vals[0]
            idx = decode_rle_bitpacked_hybrid(page_vals[1:], bw, n_present)
            if phys == PT_BYTE_ARRAY:
                dchars, dlens = dictionary
                dstarts = np.zeros(len(dlens) + 1, dtype=np.int64)
                np.cumsum(dlens, out=dstarts[1:])
                lens = dlens[idx]
                total_c = int(lens.sum())
                chars = np.empty(total_c, dtype=np.uint8)
                cur = 0
                for i, di in enumerate(idx):
                    chars[cur:cur + dlens[di]] = \
                        dchars[dstarts[di]:dstarts[di + 1]]
                    cur += dlens[di]
                vals = (chars, lens)
            else:
                vals = dictionary[idx]
        else:
            raise NotImplementedError(f"unsupported encoding {enc}")

        if phys == PT_BYTE_ARRAY:
            vals_parts.append(vals[0])
            len_parts.append(vals[1])
        else:
            vals_parts.append(vals)
        if defs is not None:
            def_parts.append(defs)
        decoded += n

    valid = None
    if def_parts:
        defs_all = np.concatenate(def_parts)
        valid = defs_all == max_def
    if phys == PT_BYTE_ARRAY:
        chars = (np.concatenate(vals_parts) if vals_parts
                 else np.zeros(0, np.uint8))
        lens = (np.concatenate(len_parts) if len_parts
                else np.zeros(0, np.int32))
        return chars, lens, valid
    values = (np.concatenate(vals_parts) if vals_parts
              else np.zeros(0, np.int32))
    return values, None, valid


def _leaf_schema_elements(meta: Struct):
    """Flat walk of the schema: leaves with (element, max_def_level, path)."""
    schema = meta.get(FMD.SCHEMA).values
    out = []
    # index 0 is the root
    def walk(idx: int, depth_def: int, prefix: str):
        elem = schema[idx]
        n = elem.get(SE.NUM_CHILDREN, 0) or 0
        name = elem.get(SE.NAME, b"").decode("utf-8")
        rep = elem.get(SE.REPETITION_TYPE, 0)
        # optional (1) adds a definition level; repeated (2) unsupported here
        my_def = depth_def + (1 if rep == 1 else 0)
        if rep == 2:
            raise NotImplementedError("nested (repeated) columns")
        path = f"{prefix}.{name}" if prefix else name
        idx += 1
        if n == 0:
            out.append((elem, my_def, path))
            return idx
        for _ in range(n):
            idx = walk(idx, my_def, path)
        return idx

    idx = 1
    root_children = schema[0].get(SE.NUM_CHILDREN, 0) or 0
    for _ in range(root_children):
        idx = walk(idx, 0, "")
    return out


@fault_site("parquet_read_table")
def read_table(file_bytes: bytes,
               columns: Optional[list[str]] = None) -> Table:
    """Read a (flat-schema) parquet file into a device Table."""
    from .thrift import parse_struct
    meta = parse_struct(extract_footer_bytes(file_bytes))
    leaves = _leaf_schema_elements(meta)
    names = [path for (_, _, path) in leaves]
    want = list(range(len(leaves))) if columns is None else [
        names.index(c) for c in columns]

    groups = meta.get(FMD.ROW_GROUPS)
    per_col_parts: dict[int, list] = {i: [] for i in want}
    for rg in groups.values:
        chunks = rg.get(RG.COLUMNS).values
        for i in want:
            elem, max_def, _ = leaves[i]
            per_col_parts[i].append(
                _decode_chunk(file_bytes, chunks[i], max_def))

    cols = []
    for i in want:
        elem, max_def, _ = leaves[i]
        phys = elem.get(SE.TYPE)
        dt = _PHYS_DT[phys]
        parts = per_col_parts[i]
        valid = None
        if any(p[2] is not None for p in parts):
            valid = np.concatenate(
                [p[2] if p[2] is not None
                 else np.ones(_part_rows(p, phys), dtype=bool) for p in parts])
        if phys == PT_BYTE_ARRAY:
            chars = np.concatenate([p[0] for p in parts])
            lens_present = np.concatenate([p[1] for p in parts])
            # re-expand lengths over nulls (null rows have no stored value)
            if valid is not None:
                lens = np.zeros(valid.shape[0], dtype=np.int64)
                lens[valid] = lens_present
            else:
                lens = lens_present.astype(np.int64)
            offs = np.zeros(lens.shape[0] + 1, dtype=np.int32)
            np.cumsum(lens, out=offs[1:])
            cols.append(Column(dt, jnp.asarray(chars), jnp.asarray(offs),
                               None if valid is None else jnp.asarray(valid)))
        else:
            vals_present = np.concatenate([p[0] for p in parts])
            if valid is not None:
                vals = np.zeros(valid.shape[0], dtype=vals_present.dtype)
                vals[valid] = vals_present
            else:
                vals = vals_present
            cols.append(Column(dt, jnp.asarray(
                np.ascontiguousarray(vals, dtype=dt.storage)),
                validity=None if valid is None else jnp.asarray(valid)))
    return Table(cols)


def _part_rows(part, phys):
    if phys == PT_BYTE_ARRAY:
        return part[1].shape[0]
    return part[0].shape[0]
