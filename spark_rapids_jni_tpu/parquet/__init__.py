from .footer import (  # noqa: F401
    ParquetFooter, StructElement, ValueElement, ListElement, MapElement,
    read_and_filter,
)
