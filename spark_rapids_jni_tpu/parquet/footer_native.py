"""ctypes binding to the native (C++) footer engine.

Loads ``native/libsrjt_parquet.so`` (building it with ``make`` on first use
if a toolchain is present) and exposes the same API as ``footer.py``.  The
handle-based C ABI mirrors the reference's JNI jlong-handle protocol
(``NativeParquetJni.cpp:568-666``): read_and_filter → handle; num_rows /
num_columns / serialize / free operate on the handle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from .footer import SchemaNode

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsrjt_parquet.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "-s"], check=True,
                       capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.srjt_footer_read_and_filter.restype = ctypes.c_void_p
        lib.srjt_footer_read_and_filter.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_uint64]
        lib.srjt_footer_num_rows.restype = ctypes.c_int64
        lib.srjt_footer_num_rows.argtypes = [ctypes.c_void_p]
        lib.srjt_footer_num_columns.restype = ctypes.c_int64
        lib.srjt_footer_num_columns.argtypes = [ctypes.c_void_p]
        lib.srjt_footer_serialize.restype = ctypes.c_int64
        lib.srjt_footer_serialize.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.srjt_footer_free.restype = None
        lib.srjt_footer_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


class NativeParquetFooter:
    """Owning wrapper over a native footer handle (AutoCloseable analog,
    ParquetFooter.java:27,124-130)."""

    def __init__(self, handle: int, lib: ctypes.CDLL):
        self._handle = handle
        self._lib = lib

    @property
    def num_rows(self) -> int:
        self._check()
        return self._lib.srjt_footer_num_rows(self._handle)

    @property
    def num_columns(self) -> int:
        self._check()
        return self._lib.srjt_footer_num_columns(self._handle)

    def serialize_thrift_file(self) -> bytes:
        self._check()
        err = ctypes.create_string_buffer(512)
        size = self._lib.srjt_footer_serialize(self._handle, None, 0, err, 512)
        if size < 0:
            raise RuntimeError(err.value.decode())
        buf = ctypes.create_string_buffer(size)
        got = self._lib.srjt_footer_serialize(self._handle, buf, size, err, 512)
        if got < 0:
            raise RuntimeError(err.value.decode())
        return buf.raw[:got]

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_footer_free(self._handle)
            self._handle = 0

    def _check(self):
        if not self._handle:
            raise ValueError("footer already closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_and_filter(buf: bytes, part_offset: int, part_length: int,
                    schema: SchemaNode,
                    ignore_case: bool = False) -> NativeParquetFooter:
    lib = load()
    if lib is None:
        raise RuntimeError("native parquet engine not available (build failed)")
    names, num_children, tags = schema.flatten_depth_first()
    if ignore_case:
        # the C ABI expects pre-folded expected names (the reference's Java
        # caller folds them the same way before crossing JNI); the engine
        # folds the footer-side names
        names = [s.lower() for s in names]
    n = len(names)
    names_arr = (ctypes.c_char_p * n)(*[s.encode("utf-8") for s in names])
    nc_arr = (ctypes.c_int32 * n)(*num_children)
    tags_arr = (ctypes.c_int32 * n)(*tags)
    err = ctypes.create_string_buffer(512)
    handle = lib.srjt_footer_read_and_filter(
        buf, len(buf), part_offset, part_length, names_arr, nc_arr, tags_arr,
        n, len(schema.children), 1 if ignore_case else 0, err, 512)
    if not handle:
        raise ValueError(f"footer read/filter failed: {err.value.decode()}")
    return NativeParquetFooter(handle, lib)
