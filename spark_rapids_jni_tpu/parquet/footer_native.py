"""ctypes binding to the native (C++) footer engine.

Loads ``native/libsrjt_parquet.so`` (building it with ``make`` on first use
if a toolchain is present) and exposes the same API as ``footer.py``.  The
handle-based C ABI mirrors the reference's JNI jlong-handle protocol
(``NativeParquetJni.cpp:568-666``): read_and_filter → handle; num_rows /
num_columns / serialize / free operate on the handle.
"""

from __future__ import annotations

import ctypes

from .. import native as native_lib
from .footer import SchemaNode

# symbol signatures are bound centrally by the unified artifact loader
load = native_lib.load
available = native_lib.available


class NativeParquetFooter:
    """Owning wrapper over a native footer handle (AutoCloseable analog,
    ParquetFooter.java:27,124-130)."""

    def __init__(self, handle: int, lib: ctypes.CDLL):
        self._handle = handle
        self._lib = lib

    @property
    def num_rows(self) -> int:
        self._check()
        return self._lib.srjt_footer_num_rows(self._handle)

    @property
    def num_columns(self) -> int:
        self._check()
        return self._lib.srjt_footer_num_columns(self._handle)

    def serialize_thrift_file(self) -> bytes:
        self._check()
        err = ctypes.create_string_buffer(512)
        size = self._lib.srjt_footer_serialize(self._handle, None, 0, err, 512)
        if size < 0:
            raise RuntimeError(err.value.decode())
        buf = ctypes.create_string_buffer(size)
        got = self._lib.srjt_footer_serialize(self._handle, buf, size, err, 512)
        if got < 0:
            raise RuntimeError(err.value.decode())
        return buf.raw[:got]

    def close(self) -> None:
        if self._handle:
            self._lib.srjt_footer_free(self._handle)
            self._handle = 0

    def _check(self):
        if not self._handle:
            raise ValueError("footer already closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_and_filter(buf: bytes, part_offset: int, part_length: int,
                    schema: SchemaNode,
                    ignore_case: bool = False) -> NativeParquetFooter:
    lib = load()
    if lib is None:
        raise RuntimeError("native parquet engine not available (build failed)")
    names, num_children, tags = schema.flatten_depth_first()
    if ignore_case:
        # the C ABI expects pre-folded expected names (the reference's Java
        # caller folds them the same way before crossing JNI); the engine
        # folds the footer-side names
        names = [s.lower() for s in names]
    n = len(names)
    names_arr = (ctypes.c_char_p * n)(*[s.encode("utf-8") for s in names])
    nc_arr = (ctypes.c_int32 * n)(*num_children)
    tags_arr = (ctypes.c_int32 * n)(*tags)
    err = ctypes.create_string_buffer(512)
    handle = lib.srjt_footer_read_and_filter(
        buf, len(buf), part_offset, part_length, names_arr, nc_arr, tags_arr,
        n, len(schema.children), 1 if ignore_case else 0, err, 512)
    if not handle:
        raise ValueError(f"footer read/filter failed: {err.value.decode()}")
    return NativeParquetFooter(handle, lib)
