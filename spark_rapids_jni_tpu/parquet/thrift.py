"""Generic Thrift Compact Protocol reader/writer.

The reference parses Parquet footers with Apache Thrift's TCompactProtocol
into *generated* typed structs (``NativeParquetJni.cpp:27-32,521-550``).
This implementation takes a different architecture on purpose: it parses into
a **generic field tree** (field-id → typed value, order preserved).  That
keeps the engine schema-agnostic — unknown fields survive a
parse→prune→serialize round trip verbatim, so footers written by newer
Parquet writers are never corrupted by pruning — and needs no thrift codegen
anywhere in the build.

Size-bomb guards mirror the reference (``NativeParquetJni.cpp:536-540``):
strings ≤ 100 MB, containers ≤ 1M elements.

Wire format implemented from the public Thrift Compact Protocol spec:
ULEB128 varints, zigzag ints, field-id delta headers, size-prefixed binaries,
list headers packing element type + size.
"""

from __future__ import annotations

import dataclasses
import struct as _struct
from typing import Any, Iterator, Optional

MAX_STRING_SIZE = 100 * 1000 * 1000   # NativeParquetJni.cpp:538
MAX_CONTAINER_SIZE = 1000 * 1000      # NativeParquetJni.cpp:540


class TType:
    STOP = 0
    BOOL_TRUE = 1     # compact: bool value lives in the field header
    BOOL_FALSE = 2
    BYTE = 3
    I16 = 4
    I32 = 5
    I64 = 6
    DOUBLE = 7
    BINARY = 8
    LIST = 9
    SET = 10
    MAP = 11
    STRUCT = 12


@dataclasses.dataclass
class Field:
    fid: int
    ttype: int
    value: Any


class Struct:
    """A generic thrift struct: ordered fields addressable by field id."""

    __slots__ = ("fields",)

    def __init__(self, fields: Optional[list[Field]] = None):
        self.fields: list[Field] = fields if fields is not None else []

    def get(self, fid: int, default=None):
        for f in self.fields:
            if f.fid == fid:
                return f.value
        return default

    def get_field(self, fid: int) -> Optional[Field]:
        for f in self.fields:
            if f.fid == fid:
                return f
        return None

    def has(self, fid: int) -> bool:
        return self.get_field(fid) is not None

    def set(self, fid: int, ttype: int, value) -> None:
        f = self.get_field(fid)
        if f is None:
            self.fields.append(Field(fid, ttype, value))
            self.fields.sort(key=lambda x: x.fid)
        else:
            f.ttype = ttype
            f.value = value

    def remove(self, fid: int) -> None:
        self.fields = [f for f in self.fields if f.fid != fid]

    def __repr__(self):
        return f"Struct({self.fields!r})"


@dataclasses.dataclass
class ListValue:
    elem_type: int
    values: list

    def __iter__(self) -> Iterator:
        return iter(self.values)

    def __len__(self):
        return len(self.values)


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

class ThriftError(ValueError):
    pass


class CompactReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    # -- primitives ---------------------------------------------------------
    def _byte(self) -> int:
        if self.pos >= len(self.buf):
            raise ThriftError("unexpected end of thrift data")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def read_varint(self) -> int:
        result = 0
        shift = 0
        while True:
            b = self._byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7
            if shift > 63:
                raise ThriftError("varint too long")

    def read_zigzag(self) -> int:
        n = self.read_varint()
        return (n >> 1) ^ -(n & 1)

    def read_binary(self) -> bytes:
        size = self.read_varint()
        if size > MAX_STRING_SIZE:
            raise ThriftError(f"string size {size} exceeds limit")
        if self.pos + size > len(self.buf):
            raise ThriftError("string extends past end of buffer")
        out = self.buf[self.pos:self.pos + size]
        self.pos += size
        return out

    def read_double(self) -> float:
        if self.pos + 8 > len(self.buf):
            raise ThriftError("double extends past end of buffer")
        (v,) = _struct.unpack_from("<d", self.buf, self.pos)
        self.pos += 8
        return v

    # -- values -------------------------------------------------------------
    def read_value(self, ttype: int):
        if ttype == TType.BOOL_TRUE:
            return True
        if ttype == TType.BOOL_FALSE:
            return False
        if ttype == TType.BYTE:
            b = self._byte()
            return b - 256 if b >= 128 else b
        if ttype in (TType.I16, TType.I32, TType.I64):
            return self.read_zigzag()
        if ttype == TType.DOUBLE:
            return self.read_double()
        if ttype == TType.BINARY:
            return self.read_binary()
        if ttype in (TType.LIST, TType.SET):
            return self.read_list()
        if ttype == TType.MAP:
            return self.read_map()
        if ttype == TType.STRUCT:
            return self.read_struct()
        raise ThriftError(f"unknown compact type {ttype}")

    def read_list(self) -> ListValue:
        header = self._byte()
        size = (header >> 4) & 0x0F
        elem_type = header & 0x0F
        if size == 15:
            size = self.read_varint()
        if size > MAX_CONTAINER_SIZE:
            raise ThriftError(f"container size {size} exceeds limit")
        if elem_type in (TType.BOOL_TRUE, TType.BOOL_FALSE):
            # in lists, each bool is one byte (1=true, 2=false) — unlike in
            # structs where the value lives in the field header
            return ListValue(elem_type,
                             [self._byte() == 1 for _ in range(size)])
        return ListValue(elem_type,
                         [self.read_value(elem_type) for _ in range(size)])

    def read_map(self):
        size = self.read_varint()
        if size > MAX_CONTAINER_SIZE:
            raise ThriftError(f"map size {size} exceeds limit")
        if size == 0:
            return (0, 0, [])
        kv = self._byte()
        ktype, vtype = (kv >> 4) & 0x0F, kv & 0x0F
        pairs = [(self.read_value(ktype), self.read_value(vtype))
                 for _ in range(size)]
        return (ktype, vtype, pairs)

    def read_struct(self) -> Struct:
        fields: list[Field] = []
        last_fid = 0
        while True:
            header = self._byte()
            if header == TType.STOP:
                return Struct(fields)
            delta = (header >> 4) & 0x0F
            ttype = header & 0x0F
            fid = last_fid + delta if delta else self.read_zigzag()
            fields.append(Field(fid, ttype, self.read_value(ttype)))
            last_fid = fid


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

class CompactWriter:
    def __init__(self):
        self.out = bytearray()

    def write_varint(self, n: int) -> None:
        while True:
            if n & ~0x7F == 0:
                self.out.append(n)
                return
            self.out.append((n & 0x7F) | 0x80)
            n >>= 7

    def write_zigzag(self, n: int) -> None:
        self.write_varint((n << 1) ^ (n >> 63) if n >= 0 else ((n << 1) ^ -1) & ((1 << 64) - 1))

    def write_binary(self, b: bytes) -> None:
        self.write_varint(len(b))
        self.out += b

    def write_value(self, ttype: int, value) -> None:
        if ttype in (TType.BOOL_TRUE, TType.BOOL_FALSE):
            # only reached inside lists; structs encode bools in the header
            self.out.append(TType.BOOL_TRUE if value else TType.BOOL_FALSE)
        elif ttype == TType.BYTE:
            self.out.append(value & 0xFF)
        elif ttype in (TType.I16, TType.I32, TType.I64):
            self.write_zigzag(value)
        elif ttype == TType.DOUBLE:
            self.out += _struct.pack("<d", value)
        elif ttype == TType.BINARY:
            self.write_binary(value)
        elif ttype in (TType.LIST, TType.SET):
            self.write_list(value)
        elif ttype == TType.MAP:
            self.write_map(value)
        elif ttype == TType.STRUCT:
            self.write_struct(value)
        else:
            raise ThriftError(f"cannot write compact type {ttype}")

    def write_list(self, lv: ListValue) -> None:
        size = len(lv.values)
        if size < 15:
            self.out.append((size << 4) | lv.elem_type)
        else:
            self.out.append(0xF0 | lv.elem_type)
            self.write_varint(size)
        for v in lv.values:
            self.write_value(lv.elem_type, v)

    def write_map(self, mv) -> None:
        ktype, vtype, pairs = mv
        self.write_varint(len(pairs))
        if pairs:
            self.out.append((ktype << 4) | vtype)
            for k, v in pairs:
                self.write_value(ktype, k)
                self.write_value(vtype, v)

    def write_struct(self, s: Struct) -> None:
        last_fid = 0
        for f in s.fields:
            ttype = f.ttype
            if ttype in (TType.BOOL_TRUE, TType.BOOL_FALSE):
                ttype = TType.BOOL_TRUE if f.value else TType.BOOL_FALSE
            delta = f.fid - last_fid
            if 0 < delta <= 15:
                self.out.append((delta << 4) | ttype)
            else:
                self.out.append(ttype)
                self.write_zigzag_i16(f.fid)
            if ttype not in (TType.BOOL_TRUE, TType.BOOL_FALSE):
                self.write_value(ttype, f.value)
            last_fid = f.fid
        self.out.append(TType.STOP)

    def write_zigzag_i16(self, n: int) -> None:
        self.write_varint(((n << 1) ^ (n >> 15)) & 0xFFFFFFFF)

    def getvalue(self) -> bytes:
        return bytes(self.out)


def parse_struct(buf: bytes) -> Struct:
    return CompactReader(buf).read_struct()


def serialize_struct(s: Struct) -> bytes:
    w = CompactWriter()
    w.write_struct(s)
    return w.getvalue()
