"""Pure-Python raw-Snappy decompressor.

Parquet data pages default to the Snappy codec in most writers (Spark,
pyarrow), and the image ships no ``python-snappy`` — the reference gets
Snappy via libcudf's nvcomp integration (SURVEY §2.9; nvcomp is shipped in
the reference jar, pom.xml:462-469).  This is a dependency-free decoder for
the raw Snappy block format (no framing, as used inside Parquet pages):

* preamble: uncompressed length as little-endian varint;
* elements: tag byte, low two bits select literal / 1-2-4-byte-offset copy
  (https format description lives in the public snappy repo's format_description.txt).

Throughput is host-Python element-rate (~50-150 MB/s on typical pages) —
adequate for footer-path tooling and tests; the device decode pipeline
(BASELINE config #2) treats page decompression as a host staging step the
same way the reference stages host buffers before H2D.
"""

from __future__ import annotations


class SnappyError(ValueError):
    pass


def decompress(buf: bytes | bytearray | memoryview,
               expected_size: int | None = None,
               max_size: int = 1 << 30) -> bytes:
    """Decompress a raw Snappy block.

    ``expected_size`` (when the caller knows it, e.g. from the Parquet page
    header) is validated against the stream's own length varint BEFORE the
    output buffer is allocated — the varint is untrusted input and may
    otherwise demand a multi-terabyte allocation.  ``max_size`` bounds the
    allocation when no expected size is available.
    """
    buf = memoryview(buf)
    # uncompressed-length varint
    n = 0
    shift = 0
    i = 0
    while True:
        if i >= len(buf):
            raise SnappyError("truncated length varint")
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
        if shift > 35:
            raise SnappyError("length varint too long")
    if expected_size is not None and n != expected_size:
        raise SnappyError(
            f"length varint {n} != page header size {expected_size}")
    if n > max_size:
        raise SnappyError(f"uncompressed length {n} exceeds cap {max_size}")

    out = bytearray(n)
    pos = 0
    L = len(buf)
    while i < L:
        tag = buf[i]
        i += 1
        t = tag & 3
        if t == 0:                       # literal
            ln = tag >> 2
            if ln >= 60:
                k = ln - 59              # 1..4 extra length bytes
                if i + k > L:
                    raise SnappyError("truncated literal length")
                ln = int.from_bytes(buf[i:i + k], "little")
                i += k
            ln += 1
            if i + ln > L or pos + ln > n:
                raise SnappyError("literal overruns buffer")
            out[pos:pos + ln] = buf[i:i + ln]
            i += ln
            pos += ln
            continue
        if t == 1:                       # copy, 3-bit length, 11-bit offset
            if i >= L:
                raise SnappyError("truncated copy-1")
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | buf[i]
            i += 1
        elif t == 2:                     # copy, 6-bit length, 16-bit offset
            if i + 2 > L:
                raise SnappyError("truncated copy-2")
            ln = (tag >> 2) + 1
            off = int.from_bytes(buf[i:i + 2], "little")
            i += 2
        else:                            # copy, 6-bit length, 32-bit offset
            if i + 4 > L:
                raise SnappyError("truncated copy-4")
            ln = (tag >> 2) + 1
            off = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        if off == 0 or off > pos or pos + ln > n:
            raise SnappyError("copy out of range")
        start = pos - off
        if off >= ln:
            out[pos:pos + ln] = out[start:start + ln]
        else:
            # overlapping copy: RLE-style run, repeat the period
            for j in range(ln):
                out[pos + j] = out[start + j]
        pos += ln
    if pos != n:
        raise SnappyError(f"decoded {pos} bytes, header said {n}")
    return bytes(out)
