"""Parquet footer parse → prune → re-serialize (host engine, CPU only).

Python engine with the same capability surface and semantics as the
reference's footer module (``NativeParquetJni.cpp``); a native C++ twin lives
in ``native/`` and is preferred when built (``footer_native.py``), with this
module doubling as the differential oracle.  Reference behaviors reproduced:

* column pruning against a Spark-side expected-schema tree with
  VALUE/STRUCT/LIST/MAP tags, case-(in)sensitive matching and subtree skip
  (``NativeParquetJni.cpp:101-437``), including the LIST layout rules
  (2-level legacy vs 3-level standard, ``:272-300``) and MAP
  MAP/MAP_KEY_VALUE with optional value (``:303-360``);
* row-group selection by split midpoint ∈ [part_offset, part_offset+len)
  with the PARQUET-2078 invalid-file_offset fallback (``:437-519``);
* column-chunk gather per surviving row group (``:552-560``);
* column_orders gathered by chunk map (``:606-613``); root num_children
  rewritten per surviving children (``:595-605``);
* re-serialization with full-file framing "PAR1" + thrift + len + "PAR1"
  (``:666-699``).

Unlike the reference (typed thrift codegen), pruning operates on a generic
field tree (see ``thrift.py``) so unknown/future footer fields survive
round trips untouched.
"""

from __future__ import annotations

import dataclasses
import struct as _struct
from typing import Optional, Sequence

from ..faultinj import fault_site
from .thrift import (CompactReader, CompactWriter, Field, ListValue, Struct,
                     ThriftError, TType, parse_struct, serialize_struct)

# -- field ids (public parquet.thrift definition) ---------------------------

class FMD:       # FileMetaData
    VERSION = 1
    SCHEMA = 2
    NUM_ROWS = 3
    ROW_GROUPS = 4
    KEY_VALUE_METADATA = 5
    CREATED_BY = 6
    COLUMN_ORDERS = 7


class SE:        # SchemaElement
    TYPE = 1
    TYPE_LENGTH = 2
    REPETITION_TYPE = 3
    NAME = 4
    NUM_CHILDREN = 5
    CONVERTED_TYPE = 6


class RG:        # RowGroup
    COLUMNS = 1
    TOTAL_BYTE_SIZE = 2
    NUM_ROWS = 3
    FILE_OFFSET = 5
    TOTAL_COMPRESSED_SIZE = 6


class CC:        # ColumnChunk
    FILE_PATH = 1
    FILE_OFFSET = 2
    META_DATA = 3


class CMD:       # ColumnMetaData
    TOTAL_COMPRESSED_SIZE = 7
    DATA_PAGE_OFFSET = 9
    DICTIONARY_PAGE_OFFSET = 11


CONVERTED_MAP = 1
CONVERTED_MAP_KEY_VALUE = 2
CONVERTED_LIST = 3
REPETITION_REPEATED = 2

MAGIC = b"PAR1"


# -- expected-schema DSL (ParquetFooter.java:35-93 analog) ------------------

TAG_VALUE, TAG_STRUCT, TAG_LIST, TAG_MAP = 0, 1, 2, 3


@dataclasses.dataclass
class SchemaNode:
    name: str
    tag: int
    children: list["SchemaNode"] = dataclasses.field(default_factory=list)

    def flatten_depth_first(self):
        """→ (names, num_children, tags) arrays, root excluded
        (ParquetFooter.java:136-185)."""
        names, num_children, tags = [], [], []

        def walk(node):
            for c in node.children:
                names.append(c.name)
                num_children.append(len(c.children))
                tags.append(c.tag)
                walk(c)

        walk(self)
        return names, num_children, tags


def ValueElement(name: str) -> SchemaNode:
    return SchemaNode(name, TAG_VALUE)


def StructElement(name: str, *children: SchemaNode) -> SchemaNode:
    return SchemaNode(name, TAG_STRUCT, list(children))


def ListElement(name: str, element: SchemaNode) -> SchemaNode:
    element = dataclasses.replace(element, name="element")
    return SchemaNode(name, TAG_LIST, [element])


def MapElement(name: str, key: SchemaNode, value: SchemaNode) -> SchemaNode:
    key = dataclasses.replace(key, name="key")
    value = dataclasses.replace(value, name="value")
    return SchemaNode(name, TAG_MAP, [key, value])


# -- pruner -----------------------------------------------------------------

class PruneError(ValueError):
    pass


@dataclasses.dataclass
class PruningMaps:
    schema_map: list[int]
    schema_num_children: list[int]
    chunk_map: list[int]


class ColumnPruner:
    """Expected-schema tree matcher (column_pruner, NativeParquetJni.cpp:112-437)."""

    def __init__(self, tag: int = TAG_STRUCT):
        self.tag = tag
        self.children: dict[str, "ColumnPruner"] = {}

    @classmethod
    def from_flat(cls, names: Sequence[str], num_children: Sequence[int],
                  tags: Sequence[int], parent_num_children: int,
                  fold_case: bool = False):
        """``fold_case`` lowercases the expected names so they can match the
        case-folded footer names — the reference folds both sides (the Java
        caller folds the expected names, the C++ side folds the footer's)."""
        root = cls(TAG_STRUCT)
        if parent_num_children == 0:
            return root
        stack = [(root, parent_num_children)]
        for name, n_c, t in zip(names, num_children, tags):
            if fold_case:
                name = name.lower()
            node = cls(t)
            stack[-1][0].children[name] = node
            if n_c > 0:
                stack.append((node, n_c))
            else:
                while stack:
                    parent, left = stack.pop()
                    if left - 1 > 0:
                        stack.append((parent, left - 1))
                        break
        if stack:
            raise ValueError("flattened schema arrays are inconsistent")
        return root

    @classmethod
    def from_tree(cls, root: SchemaNode, fold_case: bool = False):
        names, num_children, tags = root.flatten_depth_first()
        return cls.from_flat(names, num_children, tags, len(root.children),
                             fold_case)

    # -- matching -----------------------------------------------------------
    def filter_schema(self, schema: list[Struct], ignore_case: bool) -> PruningMaps:
        maps = PruningMaps([], [], [])
        state = [0, 0]  # schema index, chunk index
        self._filter(schema, ignore_case, state, maps)
        return maps

    # schema helpers
    @staticmethod
    def _name(elem: Struct, fold: bool) -> str:
        raw = elem.get(SE.NAME, b"")
        s = raw.decode("utf-8") if isinstance(raw, bytes) else raw
        return s.lower() if fold else s

    @staticmethod
    def _num_children(elem: Struct) -> int:
        return elem.get(SE.NUM_CHILDREN, 0) or 0

    @staticmethod
    def _is_leaf(elem: Struct) -> bool:
        return elem.has(SE.TYPE)

    def _skip(self, schema, state):
        """Skip current element + subtree, advancing the chunk counter for
        every leaf (NativeParquetJni.cpp:160-180)."""
        to_skip = 1
        while to_skip > 0 and state[0] < len(schema):
            elem = schema[state[0]]
            if self._is_leaf(elem):
                state[1] += 1
            to_skip += self._num_children(elem) - 1
            state[0] += 1

    def _filter(self, schema, ignore_case, state, maps):
        if self.tag == TAG_STRUCT:
            self._filter_struct(schema, ignore_case, state, maps)
        elif self.tag == TAG_VALUE:
            self._filter_value(schema, state, maps)
        elif self.tag == TAG_LIST:
            self._filter_list(schema, ignore_case, state, maps)
        elif self.tag == TAG_MAP:
            self._filter_map(schema, ignore_case, state, maps)
        else:
            raise PruneError(f"unexpected tag {self.tag}")

    def _filter_struct(self, schema, ignore_case, state, maps):
        elem = schema[state[0]]
        if self._is_leaf(elem):
            raise PruneError("found a leaf node, but expected a struct")
        n = self._num_children(elem)
        maps.schema_map.append(state[0])
        my_nc = len(maps.schema_num_children)
        maps.schema_num_children.append(0)
        state[0] += 1
        for _ in range(n):
            if state[0] >= len(schema):
                break
            child = schema[state[0]]
            name = self._name(child, ignore_case)
            found = self.children.get(name)
            if found is not None:
                maps.schema_num_children[my_nc] += 1
                found._filter(schema, ignore_case, state, maps)
            else:
                self._skip(schema, state)

    def _filter_value(self, schema, state, maps):
        elem = schema[state[0]]
        if not self._is_leaf(elem):
            raise PruneError("found a non-leaf entry when reading a leaf value")
        if self._num_children(elem) != 0:
            raise PruneError("found an entry with children when reading a leaf value")
        maps.schema_map.append(state[0])
        maps.schema_num_children.append(0)
        state[0] += 1
        maps.chunk_map.append(state[1])
        state[1] += 1

    def _filter_list(self, schema, ignore_case, state, maps):
        found = self.children["element"]
        elem = schema[state[0]]
        list_name = self._name(elem, False)
        if self._is_leaf(elem):
            raise PruneError("expected a list item, but found a single value")
        if elem.get(SE.CONVERTED_TYPE) != CONVERTED_LIST:
            raise PruneError("expected a list type, but it was not found")
        if self._num_children(elem) != 1:
            raise PruneError("the structure of the outer list group is not standard")
        maps.schema_map.append(state[0])
        maps.schema_num_children.append(1)
        state[0] += 1

        # Parquet LIST layout rules (NativeParquetJni.cpp:271-299): a
        # repeated group with one child not named "array"/"<list>_tuple" is
        # the standard 3-level form; anything else is the legacy 2-level form.
        rep = schema[state[0]]
        if rep.get(SE.REPETITION_TYPE) != REPETITION_REPEATED:
            raise PruneError("the structure of the list's child is not standard (non repeating)")
        rep_is_group = not self._is_leaf(rep)
        rep_nc = self._num_children(rep)
        rep_name = self._name(rep, False)
        if (rep_is_group and rep_nc == 1 and rep_name != "array"
                and rep_name != list_name + "_tuple"):
            maps.schema_map.append(state[0])
            maps.schema_num_children.append(1)
            state[0] += 1
            found._filter(schema, ignore_case, state, maps)
        else:
            found._filter(schema, ignore_case, state, maps)

    def _filter_map(self, schema, ignore_case, state, maps):
        key_found = self.children["key"]
        value_found = self.children["value"]
        elem = schema[state[0]]
        if self._is_leaf(elem):
            raise PruneError("expected a map item, but found a single value")
        if elem.get(SE.CONVERTED_TYPE) not in (CONVERTED_MAP,
                                               CONVERTED_MAP_KEY_VALUE):
            raise PruneError("expected a map type, but it was not found")
        if self._num_children(elem) != 1:
            raise PruneError("the structure of the outer map group is not standard")
        maps.schema_map.append(state[0])
        maps.schema_num_children.append(1)
        state[0] += 1

        rep = schema[state[0]]
        if rep.get(SE.REPETITION_TYPE) != REPETITION_REPEATED:
            raise PruneError("found non repeating map child")
        rep_nc = self._num_children(rep)
        if rep_nc not in (1, 2):
            raise PruneError("found map with wrong number of children")
        maps.schema_map.append(state[0])
        maps.schema_num_children.append(rep_nc)
        state[0] += 1
        key_found._filter(schema, ignore_case, state, maps)
        if rep_nc == 2:
            value_found._filter(schema, ignore_case, state, maps)


# -- row-group filtering ----------------------------------------------------

def _chunk_offset(chunk: Struct) -> int:
    """First-page offset of a column chunk (get_offset, NativeParquetJni.cpp:455-462)."""
    md = chunk.get(CC.META_DATA)
    off = md.get(CMD.DATA_PAGE_OFFSET, 0)
    dict_off = md.get(CMD.DICTIONARY_PAGE_OFFSET)
    if dict_off is not None and off > dict_off:
        off = dict_off
    return off


def _invalid_file_offset(start, pre_start, pre_size) -> bool:
    """PARQUET-2078 detection (NativeParquetJni.cpp:439-453)."""
    if pre_start == 0 and start != 4:
        return True
    return start < pre_start + pre_size


def filter_groups(meta: Struct, part_offset: int, part_length: int) -> list[Struct]:
    """Keep row groups whose midpoint falls in the split
    (filter_groups, NativeParquetJni.cpp:464-519)."""
    groups = meta.get(FMD.ROW_GROUPS)
    if groups is None or not len(groups):
        return []
    first_has_md = groups.values[0].get(RG.COLUMNS).values[0].has(CC.META_DATA)
    pre_start = 0
    pre_size = 0
    out = []
    for rg in groups.values:
        cols = rg.get(RG.COLUMNS)
        if first_has_md:
            start = _chunk_offset(cols.values[0])
        else:
            # file_offset of the first block holds the truth; later blocks
            # may not (PARQUET-2078)
            start = rg.get(RG.FILE_OFFSET, 0)
            if _invalid_file_offset(start, pre_start, pre_size):
                start = 4 if pre_start == 0 else pre_start + pre_size
            pre_start = start
            pre_size = rg.get(RG.TOTAL_COMPRESSED_SIZE, 0)
        total = rg.get(RG.TOTAL_COMPRESSED_SIZE)
        if total is None:
            total = sum(c.get(CC.META_DATA).get(CMD.TOTAL_COMPRESSED_SIZE, 0)
                        for c in cols.values)
        mid = start + total // 2
        if part_offset <= mid < part_offset + part_length:
            out.append(rg)
    return out


def filter_columns(groups: list[Struct], chunk_map: list[int]) -> None:
    """Gather surviving column chunks per row group
    (filter_columns, NativeParquetJni.cpp:552-560)."""
    for rg in groups:
        cols = rg.get(RG.COLUMNS)
        rg.get_field(RG.COLUMNS).value = ListValue(
            TType.STRUCT, [cols.values[i] for i in chunk_map])


# -- public API (ParquetFooter.java surface) --------------------------------

class ParquetFooter:
    """A parsed + filtered footer handle (ParquetFooter.java:27,95-130)."""

    def __init__(self, meta: Struct):
        self._meta = meta

    @property
    def num_rows(self) -> int:
        groups = self._meta.get(FMD.ROW_GROUPS)
        return sum(rg.get(RG.NUM_ROWS, 0) for rg in groups.values) if groups else 0

    @property
    def num_columns(self) -> int:
        schema = self._meta.get(FMD.SCHEMA)
        if schema is None or not len(schema):
            return 0
        return schema.values[0].get(SE.NUM_CHILDREN, 0) or 0

    def serialize_thrift_file(self) -> bytes:
        """"PAR1" + thrift + u32 length + "PAR1" (NativeParquetJni.cpp:666-699)."""
        body = serialize_struct(self._meta)
        return MAGIC + body + _struct.pack("<I", len(body)) + MAGIC


@fault_site("parquet_read_and_filter")
def read_and_filter(buf: bytes, part_offset: int, part_length: int,
                    schema: SchemaNode, ignore_case: bool = False) -> ParquetFooter:
    """Parse a raw footer thrift blob, prune columns, filter row groups.

    Mirrors ``Java_..._ParquetFooter_readAndFilter``
    (NativeParquetJni.cpp:568-626).  ``part_length < 0`` keeps all groups.
    """
    meta = parse_struct(buf)
    pruner = ColumnPruner.from_tree(schema, fold_case=ignore_case)
    schema_list = meta.get(FMD.SCHEMA)
    if schema_list is None:
        raise ValueError("footer has no schema")
    maps = pruner.filter_schema(schema_list.values, ignore_case)

    # gather + rewrite schema num_children
    new_schema = []
    for idx, n_c in zip(maps.schema_map, maps.schema_num_children):
        elem = schema_list.values[idx]
        if elem.has(SE.NUM_CHILDREN):
            elem.set(SE.NUM_CHILDREN, TType.I32, n_c)
        elif n_c:
            elem.set(SE.NUM_CHILDREN, TType.I32, n_c)
        new_schema.append(elem)
    meta.get_field(FMD.SCHEMA).value = ListValue(TType.STRUCT, new_schema)

    orders = meta.get(FMD.COLUMN_ORDERS)
    if orders is not None:
        meta.get_field(FMD.COLUMN_ORDERS).value = ListValue(
            orders.elem_type, [orders.values[i] for i in maps.chunk_map])

    groups_field = meta.get_field(FMD.ROW_GROUPS)
    if part_length >= 0 and groups_field is not None:
        kept = filter_groups(meta, part_offset, part_length)
        groups_field.value = ListValue(TType.STRUCT, kept)
    if groups_field is not None:
        filter_columns(groups_field.value.values, maps.chunk_map)
    return ParquetFooter(meta)


def extract_footer_bytes(file_bytes: bytes) -> bytes:
    """Pull the raw thrift footer out of a full parquet file."""
    if file_bytes[:4] != MAGIC or file_bytes[-4:] != MAGIC:
        raise ValueError("not a parquet file (missing PAR1 magic)")
    (length,) = _struct.unpack("<I", file_bytes[-8:-4])
    return file_bytes[-8 - length:-8]
