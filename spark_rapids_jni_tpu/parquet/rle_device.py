"""Device-side RLE/bit-packed hybrid expansion (parquet levels + indices).

The reference decodes definition levels and dictionary indices on the GPU
inside libcudf's page decode kernels (built into its artifact,
``build-libcudf.xml:48-64``).  The TPU-native split mirrors the rest of
the scan tier (``device_scan.py``): the *headers* of the hybrid stream —
a handful of varints, O(#runs) — are walked on host like page headers,
while the *payload* (the n·bit_width bit stream, the actual data volume)
is expanded to values on device with pure shifts/masks:

* the dominant shape — ONE bit-packed run covering the page (how
  parquet-mr writes dictionary indices) — reshapes the payload to
  ``[groups_of_8, bw]`` bytes and extracts all 8 values per group with
  static byte slices + shifts: fully vectorized, no gathers;
* general run mixes (def levels alternate RLE and bit-packed runs)
  locate each output's run with the marker-cumsum segment trick and
  funnel-shift its bits out of the payload word stream — two word
  gathers per value, still no scalar loops.

Run counts are bucketed so jit variants stay bounded; streams with
bit width > 24 (indices into >16M-entry dictionaries) or malformed
headers return None and the caller keeps its host path.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

_MAX_BW = 24        # funnel window: bw + 7 shift bits must fit in 31


def _bucket(x: int, lo: int = 8) -> int:
    if x <= lo:
        return lo
    p = lo
    while p < x:
        p <<= 1
    step = max(p // 8, 1)
    return -(-x // step) * step


@dataclasses.dataclass(frozen=True)
class RunPlan:
    """Host header walk of one hybrid stream (payload left raw)."""

    n: int                   # total output values
    bw: int                  # bit width
    counts: np.ndarray       # int64 [R] values per run
    is_bp: np.ndarray        # bool  [R] bit-packed (vs RLE) run
    rle_vals: np.ndarray     # int32 [R] value for RLE runs (0 for BP)
    bp_bit_base: np.ndarray  # int64 [R] run's first bit in the payload
    payload: bytes           # concatenated BIT-PACKED payload bytes only

    @property
    def single_bp(self) -> bool:
        return len(self.counts) == 1 and bool(self.is_bp[0])

    @property
    def all_rle(self) -> bool:
        return not self.is_bp.any()


def parse_runs(buf: bytes, bw: int, n: int) -> RunPlan | None:
    """Header-only walk (host metadata pass).  None → caller's host path."""
    if bw > _MAX_BW or n <= 0:
        return None
    if bw == 0:
        return RunPlan(n, 0, np.array([n], np.int64),
                       np.array([False]), np.zeros(1, np.int32),
                       np.zeros(1, np.int64), b"")
    pos = 0
    out = 0
    vbytes = (bw + 7) // 8
    counts, is_bp, vals, bases, pl = [], [], [], [], []
    plbits = 0
    L = len(buf)
    while out < n and pos < L:
        h = 0
        sh = 0
        while True:
            if pos >= L:
                return None
            byte = buf[pos]
            pos += 1
            h |= (byte & 0x7F) << sh
            sh += 7
            if not byte & 0x80:
                break
        if h & 1:
            groups = h >> 1
            nb = groups * bw
            if groups == 0 or pos + nb > L:
                return None
            counts.append(min(groups * 8, n - out))
            is_bp.append(True)
            vals.append(0)
            bases.append(plbits)
            pl.append(buf[pos:pos + nb])
            plbits += nb * 8
            pos += nb
        else:
            cnt = h >> 1
            if cnt == 0 or pos + vbytes > L:
                return None
            counts.append(min(cnt, n - out))
            is_bp.append(False)
            vals.append(int.from_bytes(buf[pos:pos + vbytes], "little"))
            bases.append(0)
            pos += vbytes
        out += counts[-1]
    if out < n:
        return None
    return RunPlan(n, bw, np.asarray(counts, np.int64),
                   np.asarray(is_bp, bool), np.asarray(vals, np.int32),
                   np.asarray(bases, np.int64), b"".join(pl))


def present_count(plan: RunPlan, target: int) -> int:
    """How many decoded values equal ``target`` — from headers + a
    vectorized popcount of bit-packed payloads (no full expansion).
    Metadata-grade host work: the PLAIN payload slicing needs this count
    before any device program can run."""
    total = 0
    for r in range(len(plan.counts)):
        cnt = int(plan.counts[r])
        if not plan.is_bp[r]:
            total += cnt if int(plan.rle_vals[r]) == target else 0
            continue
        bits = np.unpackbits(
            np.frombuffer(plan.payload, np.uint8,
                          offset=int(plan.bp_bit_base[r]) // 8,
                          count=-(-cnt * plan.bw // 8)),
            bitorder="little")
        vals = np.zeros(cnt, np.int64)
        for b in range(plan.bw):
            vals |= bits[b::plan.bw][:cnt].astype(np.int64) << b
        total += int((vals == target).sum())
    return total


def expand_np(plan: RunPlan) -> np.ndarray:
    """Host oracle expansion (vectorized numpy) — differential tests and
    the host fallback share it."""
    parts = []
    for r in range(len(plan.counts)):
        cnt = int(plan.counts[r])
        if not plan.is_bp[r]:
            parts.append(np.full(cnt, int(plan.rle_vals[r]), np.int32))
            continue
        bits = np.unpackbits(
            np.frombuffer(plan.payload, np.uint8,
                          offset=int(plan.bp_bit_base[r]) // 8,
                          count=-(-cnt * plan.bw // 8)),
            bitorder="little")
        vals = np.zeros(cnt, np.int32)
        for b in range(plan.bw):
            vals |= bits[b::plan.bw][:cnt].astype(np.int32) << b
        parts.append(vals)
    return np.concatenate(parts) if parts else np.zeros(0, np.int32)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _bp_single_jit(bw: int, n: int, rows_bytes: int,
                   payload: jnp.ndarray) -> jnp.ndarray:
    """ONE bit-packed run: [groups, bw]-byte reshape, 8 values per group
    via static slices — no gathers."""
    rows = jnp.pad(payload, (0, rows_bytes - payload.shape[0])).reshape(
        -1, bw)
    cols = []
    mask = jnp.uint32((1 << bw) - 1)
    for k in range(8):
        bit0 = k * bw
        j0 = bit0 // 8
        w = jnp.zeros((rows.shape[0],), jnp.uint32)
        for t in range(4):
            if j0 + t < bw:
                w = w | (rows[:, j0 + t].astype(jnp.uint32)
                         << jnp.uint32(8 * t))
        cols.append((w >> jnp.uint32(bit0 % 8)) & mask)
    out = jnp.stack(cols, axis=1).reshape(-1)
    return out[:n].astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _runs_jit(bw: int, n: int, Rb: int, starts: jnp.ndarray,
              is_bp: jnp.ndarray, rle_vals: jnp.ndarray,
              bit_base: jnp.ndarray, payload: jnp.ndarray) -> jnp.ndarray:
    """General run mix: marker-cumsum run lookup + funnel shift from the
    payload word stream (two word gathers per value)."""
    from ..rowconv.convert import _segment_of
    rid = _segment_of(starts, n)
    rid = jnp.clip(rid, 0, Rb - 1)
    j = jnp.arange(n, dtype=jnp.int32) - starts[rid]
    if payload.shape[0]:
        pw = payload.shape[0] // 4 + 2
        w32 = jnp.pad(payload, (0, pw * 4 - payload.shape[0]))
        w32 = jax.lax.bitcast_convert_type(w32.reshape(-1, 4), jnp.uint32)
        bitpos = (bit_base[rid] + j * bw).astype(jnp.int32)
        wi = jnp.clip(bitpos // 32, 0, w32.shape[0] - 2)
        lo = w32[wi]
        hi = w32[wi + 1]
        sh = (bitpos % 32).astype(jnp.uint32)
        v = jnp.where(sh == 0, lo,
                      (lo >> sh) | (hi << (jnp.uint32(32) - sh)))
        bp_val = (v & jnp.uint32((1 << bw) - 1)).astype(jnp.int32)
    else:
        bp_val = jnp.zeros((n,), jnp.int32)
    return jnp.where(is_bp[rid], bp_val, rle_vals[rid])


def _upload(pay: np.ndarray, stager) -> jnp.ndarray:
    """Payload upload; rides the scan's slab stager when one is given
    (flushing whatever else is queued in the same wave — the bitstream
    still lands in a coalesced slab rather than its own transfer)."""
    if stager is None:
        return jnp.asarray(pay)
    from . import staging
    return staging.resolve(staging.asarray(pay, stager))


def expand_device(plan: RunPlan, stager=None) -> jnp.ndarray:
    """Expand a parsed hybrid stream to int32 [n] on device."""
    n = plan.n
    if plan.bw == 0:
        return jnp.zeros((n,), jnp.int32)
    if plan.single_bp:
        rows = -(-n // 8)
        # a run can advertise more groups than ceil(n/8): the slice keeps
        # the pad amount non-negative (trailing payload is padding)
        pay = np.frombuffer(plan.payload, np.uint8)[:rows * plan.bw]
        return _bp_single_jit(plan.bw, n, rows * plan.bw,
                              _upload(pay, stager))
    R = len(plan.counts)
    Rb = _bucket(R, 4)
    starts = np.zeros(Rb + 1, np.int32)
    starts[1:R + 1] = np.cumsum(plan.counts)
    starts[R + 1:] = starts[R]
    is_bp = np.zeros(Rb, bool)
    is_bp[:R] = plan.is_bp
    vals = np.zeros(Rb, np.int32)
    vals[:R] = plan.rle_vals
    base = np.zeros(Rb, np.int64)
    base[:R] = plan.bp_bit_base
    return _runs_jit(plan.bw, n, Rb, jnp.asarray(starts),
                     jnp.asarray(is_bp), jnp.asarray(vals),
                     jnp.asarray(base),
                     _upload(np.frombuffer(plan.payload, np.uint8),
                             stager))
