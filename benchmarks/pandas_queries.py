"""Pandas implementations of the full TPC-DS query subset.

The host baseline counterpart of ``models/tpcds.py:QUERIES`` — every
plan re-expressed over pandas DataFrames so ``tools/query_host_baseline``
can time the identical work on the CPU (the stand-in for the BASELINE
north star's "CPU Spark" comparison; single-process pandas is what the
image provides).  Each function takes ``dfs`` (table name → DataFrame)
and returns a DataFrame/Series; result row counts are cross-checked
against the chip results in ``tests/test_pandas_queries.py``.

These are plan translations, not golden oracles — the per-query pandas
differentials in ``tests/test_tpcds*.py`` remain the correctness
authority for the framework.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def q3(dfs, manufact_id=436, moy=11):
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item[item.i_manufact_id == manufact_id],
                  left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd[dd.d_moy == moy], left_on="ss_sold_date_sk",
                right_on="d_date_sk"))
    return (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum())


def q42(dfs, manager_id=1, year=2000, moy=11):
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item[item.i_manager_id == manager_id],
                  left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd[(dd.d_moy == moy) & (dd.d_year == year)],
                left_on="ss_sold_date_sk", right_on="d_date_sk"))
    return (j.groupby(["d_year", "i_category_id", "i_category"],
                      as_index=False)["ss_ext_sales_price"].sum())


def q52(dfs, moy=12, year=2001):
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(dd[(dd.d_moy == moy) & (dd.d_year == year)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(item, left_on="ss_item_sk", right_on="i_item_sk"))
    return (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum())


def q55(dfs, manager_id=28):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item[item.i_manager_id == manager_id],
                 left_on="ss_item_sk", right_on="i_item_sk")
    return (j.groupby(["i_brand_id", "i_brand"], as_index=False)
            ["ss_ext_sales_price"].sum())


def q_state_rollup(dfs, state="TN"):
    ss, store = dfs["store_sales"], dfs["store"]
    j = ss.merge(store[store.s_state == state], left_on="ss_store_sk",
                 right_on="s_store_sk")
    return (j.groupby("s_state", as_index=False)
            .agg(s=("ss_sales_price_cents", "sum"),
                 m=("ss_quantity", "mean"),
                 c=("ss_quantity", "count")))


def q7(dfs, year=2000):
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(dd[dd.d_year == year], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
         .merge(item, left_on="ss_item_sk", right_on="i_item_sk"))
    return (j.groupby("i_item_id", as_index=False)
            .agg(q=("ss_quantity", "mean"),
                 lp=("ss_list_price_cents", "mean"),
                 sp=("ss_sales_price_cents", "mean")))


def q19(dfs, year=1999, moy=11, manager_lo=1, manager_hi=50):
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    itf = item[(item.i_manager_id >= manager_lo)
               & (item.i_manager_id <= manager_hi)]
    j = (ss.merge(itf, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd[(dd.d_moy == moy) & (dd.d_year == year)],
                left_on="ss_sold_date_sk", right_on="d_date_sk"))
    return (j.groupby(["i_brand_id", "i_brand", "i_manufact_id"],
                      as_index=False)["ss_ext_sales_price"].sum())


def q62(dfs, year=2000, qty_lo=10, qty_hi=60):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    ssf = ss[(ss.ss_quantity >= qty_lo) & (ss.ss_quantity <= qty_hi)]
    j = ssf.merge(dd[dd.d_year == year], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
    return j.groupby("d_moy", as_index=False)["ss_quantity"].count()


def q52_topn(dfs, moy=12, year=2001, n=10):
    out = q52(dfs, moy=moy, year=year)
    return out.sort_values(["ss_ext_sales_price", "i_brand_id"],
                           ascending=[False, True]).head(n)


def q65(dfs, frac=0.9):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    rev = j.groupby("i_brand_id", as_index=False)["ss_ext_sales_price"].sum()
    thr = rev.ss_ext_sales_price.mean() * frac
    return rev[rev.ss_ext_sales_price < thr]


def q_store_counts(dfs):
    ss, store = dfs["store_sales"], dfs["store"]
    j = store.merge(ss, left_on="s_store_sk", right_on="ss_store_sk",
                    how="left")
    return (j.groupby(["s_store_sk", "s_state"], as_index=False)
            ["ss_item_sk"].count())


def q67_rank(dfs, top_n=3):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    rev = (j.groupby(["i_category", "i_brand_id"], as_index=False)
           ["ss_ext_sales_price"].sum())
    rev = rev.sort_values(["i_category", "ss_ext_sales_price", "i_brand_id"],
                          ascending=[True, False, True])
    rev["rk"] = (rev.groupby("i_category")["ss_ext_sales_price"]
                 .rank(method="min", ascending=False).astype(int))
    return rev[rev.rk <= top_n]


def q_like_brands(dfs, pat="#1", cat_prefix="S"):
    ss, item = dfs["store_sales"], dfs["item"]
    itf = item[item.i_brand.str.contains(pat, regex=False)
               & item.i_category.str.startswith(cat_prefix)]
    j = ss.merge(itf, left_on="ss_item_sk", right_on="i_item_sk")
    return (j.groupby("i_category", as_index=False)
            ["ss_ext_sales_price"].sum())


def q_union_channels(dfs):
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    both = pd.concat([
        ss[["ss_item_sk", "ss_ext_sales_price"]]
        .rename(columns={"ss_item_sk": "item_sk",
                         "ss_ext_sales_price": "price"}),
        ws[["ws_item_sk", "ws_ext_sales_price"]]
        .rename(columns={"ws_item_sk": "item_sk",
                         "ws_ext_sales_price": "price"})])
    j = both.merge(item, left_on="item_sk", right_on="i_item_sk")
    return j.groupby("i_category", as_index=False)["price"].sum()


def q_lag_growth(dfs):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    rev = (j.groupby(["ss_store_sk", "d_year", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .sort_values(["ss_store_sk", "d_year", "d_moy"]))
    prev = rev.groupby("ss_store_sk")["ss_ext_sales_price"].shift(1)
    rev["delta"] = rev.ss_ext_sales_price - prev.fillna(0.0)
    return rev


def q_running_share(dfs, year=2000):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd[dd.d_year == year], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    rev = (j.groupby(["ss_store_sk", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .sort_values(["ss_store_sk", "d_moy"]))
    rev["cum"] = rev.groupby("ss_store_sk")["ss_ext_sales_price"].cumsum()
    return rev


def q_nunique_items(dfs):
    ss = dfs["store_sales"]
    return (ss.groupby("ss_store_sk", as_index=False)
            ["ss_item_sk"].nunique())


def q_having(dfs, min_total=1000.0):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    rev = j.groupby("i_brand_id", as_index=False)["ss_ext_sales_price"].sum()
    return rev[rev.ss_ext_sales_price > min_total]


def q_case_when(dfs, qty_cut=50):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    price = j.ss_ext_sales_price.fillna(0.0)
    bulk = j.ss_quantity.gt(qty_cut).fillna(False)
    j = j.assign(bulk_rev=np.where(bulk, price, 0.0),
                 retail_rev=np.where(bulk, 0.0, price))
    return (j.groupby("i_category", as_index=False)
            [["bulk_rev", "retail_rev"]].sum())


def q_distinct_pairs(dfs):
    item = dfs["item"]
    return item[["i_brand_id", "i_category_id"]].drop_duplicates()


def q_isin_states(dfs, states=("TN", "CA")):
    ss, store = dfs["store_sales"], dfs["store"]
    j = ss.merge(store[store.s_state.isin(list(states))],
                 left_on="ss_store_sk", right_on="s_store_sk")
    return (j.groupby("s_state", as_index=False)
            ["ss_ext_sales_price"].sum())


def _rollup(j, keys, aggs):
    """Pandas grouping-sets union with a Spark-style grouping_id."""
    frames = []
    for lvl in range(len(keys), -1, -1):
        sub = keys[:lvl]
        gid = sum(1 << (len(keys) - 1 - i) for i in range(lvl, len(keys)))
        if sub:
            g = j.groupby(sub, as_index=False).agg(**aggs)
        else:
            g = pd.DataFrame([{n: j[c].agg(f)
                               for n, (c, f) in aggs.items()}])
        for k in keys[lvl:]:
            g[k] = None
        g["grouping_id"] = gid
        frames.append(g)
    return pd.concat(frames, ignore_index=True)


def q36_rollup(dfs):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    return _rollup(j, ["i_category", "i_brand"],
                   {"rev": ("ss_ext_sales_price", "sum")})


def q86_rollup(dfs):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    return _rollup(j, ["d_year", "d_moy"],
                   {"rev": ("ss_ext_sales_price", "sum")})


def q27_cube(dfs):
    ss, item, store = dfs["store_sales"], dfs["item"], dfs["store"]
    j = (ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(store, left_on="ss_store_sk", right_on="s_store_sk"))
    frames = []
    for gid, sub in [(0, ["i_category", "s_state"]), (1, ["i_category"]),
                     (2, ["s_state"]), (3, [])]:
        if sub:
            g = j.groupby(sub, as_index=False).agg(
                mq=("ss_quantity", "mean"), rev=("ss_ext_sales_price", "sum"))
        else:
            g = pd.DataFrame([{"mq": j.ss_quantity.mean(),
                               "rev": j.ss_ext_sales_price.sum()}])
        g["grouping_id"] = gid
        frames.append(g)
    return pd.concat(frames, ignore_index=True)


def q5_grouping_sets(dfs):
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    both = pd.concat([
        ss[["ss_item_sk", "ss_ext_sales_price"]].assign(channel=0)
        .rename(columns={"ss_item_sk": "item_sk",
                         "ss_ext_sales_price": "price"}),
        ws[["ws_item_sk", "ws_ext_sales_price"]].assign(channel=1)
        .rename(columns={"ws_item_sk": "item_sk",
                         "ws_ext_sales_price": "price"})])
    j = both.merge(item, left_on="item_sk", right_on="i_item_sk")
    frames = []
    for sub in [["channel", "i_category"], ["channel"], []]:
        if sub:
            g = j.groupby(sub, as_index=False).agg(rev=("price", "sum"))
        else:
            g = pd.DataFrame([{"rev": j.price.sum()}])
        frames.append(g)
    return pd.concat(frames, ignore_index=True)


def q78_outer(dfs):
    ss, ws = dfs["store_sales"], dfs["web_sales"]
    s = (ss.groupby("ss_item_sk", as_index=False)
         ["ss_ext_sales_price"].sum())
    w = (ws.groupby("ws_item_sk", as_index=False)
         ["ws_ext_sales_price"].sum())
    j = s.merge(w, left_on="ss_item_sk", right_on="ws_item_sk",
                how="outer")
    j["key"] = j.ss_item_sk.fillna(j.ws_item_sk)
    j["s_rev"] = j.ss_ext_sales_price.fillna(0.0)
    j["w_rev"] = j.ws_ext_sales_price.fillna(0.0)
    return j[["key", "s_rev", "w_rev"]]


def q25_two_fact(dfs, year=2000):
    ss, ws, dd = dfs["store_sales"], dfs["web_sales"], dfs["date_dim"]
    ddf = dd[dd.d_year == year]
    js = ss.merge(ddf, left_on="ss_sold_date_sk", right_on="d_date_sk")
    jw = ws.merge(ddf, left_on="ws_sold_date_sk", right_on="d_date_sk")
    s = js.groupby("ss_item_sk", as_index=False)["ss_ext_sales_price"].sum()
    w = jw.groupby("ws_item_sk", as_index=False)["ws_ext_sales_price"].sum()
    return s.merge(w, left_on="ss_item_sk", right_on="ws_item_sk")


def q88_counts(dfs):
    ss = dfs["store_sales"]
    q = ss.ss_quantity
    return pd.DataFrame([{
        f"b{i}": int(((q >= lo) & (q <= hi)).sum())
        for i, (lo, hi) in enumerate([(1, 25), (26, 50), (51, 75),
                                      (76, 100)])}])


def q90_ratio(dfs):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    am = int((j.d_moy <= 6).sum())
    pm = int((j.d_moy > 6).sum())
    return pd.DataFrame([{"am": am, "pm": pm, "ratio": am / max(pm, 1)}])


def q29_minmax(dfs):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    return (j.groupby("i_brand_id", as_index=False)
            .agg(mn=("ss_quantity", "min"), mx=("ss_quantity", "max"),
                 mean=("ss_quantity", "mean")))


def q48_bands(dfs):
    ss, store = dfs["store_sales"], dfs["store"]
    q, p = ss.ss_quantity, ss.ss_sales_price_cents
    m = (((q >= 1) & (q <= 20) & (p < 50_00))
         | ((q >= 41) & (q <= 60) & (p > 150_00)))
    j = ss[m].merge(store, left_on="ss_store_sk", right_on="s_store_sk")
    return j.groupby("s_state", as_index=False)["ss_quantity"].sum()


def q13_avg_bands(dfs):
    ss = dfs["store_sales"]
    q, p = ss.ss_quantity, ss.ss_sales_price_cents
    out = {}
    for i, (lo, hi) in enumerate([(1, 33), (34, 66), (67, 100)]):
        m = (q >= lo) & (q <= hi) & p.notna()
        out[f"b{i}"] = float(p[m].sum() / max(int(m.sum()), 1) / 100.0)
    return pd.DataFrame([out])


def q96_count(dfs, year=2000, qty_min=80):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss[ss.ss_quantity >= qty_min].merge(
        dd[dd.d_year == year], left_on="ss_sold_date_sk",
        right_on="d_date_sk")
    return pd.DataFrame([{"rows": len(j),
                          "qty": int(j.ss_quantity.sum())}])


def q23_semi(dfs, min_sales=30):
    ss = dfs["store_sales"]
    freq = ss.groupby("ss_item_sk").size()
    keep = freq[freq > min_sales].index
    hits = ss[ss.ss_item_sk.isin(keep)]
    return pd.DataFrame([{"total": float(hits.ss_ext_sales_price.sum()),
                          "rows": len(hits)}])


def q16_anti(dfs):
    ss, item = dfs["store_sales"], dfs["item"]
    unsold = item[~item.i_item_sk.isin(ss.ss_item_sk.unique())]
    return unsold[["i_item_sk", "i_manufact_id"]]


def q_minmax_price(dfs):
    item = dfs["item"]
    return (item.groupby("i_category", as_index=False)
            .agg(mn=("i_current_price", "min"),
                 mx=("i_current_price", "max")))


def q_multi_measure(dfs):
    ss = dfs["store_sales"]
    return (ss.groupby("ss_store_sk", as_index=False)
            .agg(q=("ss_quantity", "sum"),
                 s=("ss_sales_price_cents", "sum"),
                 lp=("ss_list_price_cents", "mean")))


def q_rollup3(dfs):
    ss, dd, store = dfs["store_sales"], dfs["date_dim"], dfs["store"]
    j = (ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(store, left_on="ss_store_sk", right_on="s_store_sk"))
    return _rollup(j, ["d_year", "d_moy", "s_state"],
                   {"rev": ("ss_ext_sales_price", "sum")})


def q_first_last(dfs):
    ss = dfs["store_sales"]
    srt = ss.sort_values("ss_sold_date_sk", kind="stable")
    return (srt.groupby("ss_item_sk", as_index=False)
            .agg(first=("ss_sales_price_cents", "first"),
                 last=("ss_sales_price_cents", "last")))


def q_rownum_dedup(dfs, keep=2):
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    rev = (j.groupby(["ss_store_sk", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .sort_values(["ss_store_sk", "ss_ext_sales_price", "d_moy"],
                        ascending=[True, False, True]))
    rev["rn"] = rev.groupby("ss_store_sk").cumcount() + 1
    return rev[rev.rn <= keep]


def q_cross_ratio(dfs):
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    js = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    jw = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    s = js.groupby("i_category", as_index=False)["ss_ext_sales_price"].sum()
    w = jw.groupby("i_category", as_index=False)["ws_ext_sales_price"].sum()
    j = s.merge(w, on="i_category")
    j["ratio"] = j.ws_ext_sales_price / j.ss_ext_sales_price
    return j


def q_null_share(dfs):
    ws, item = dfs["web_sales"], dfs["item"]
    j = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    return (j.groupby("i_category", as_index=False)
            .agg(rows=("ws_item_sk", "count"),
                 nn=("ws_ext_sales_price", "count"),
                 s=("ws_ext_sales_price", "sum")))


def q17_stats(dfs):
    ss, store = dfs["store_sales"], dfs["store"]
    j = ss.merge(store, left_on="ss_store_sk", right_on="s_store_sk")
    return (j.groupby("s_state", as_index=False)
            .agg(m=("ss_quantity", "mean"), sd=("ss_quantity", "std"),
                 c=("ss_quantity", "count")))


def q8_intersect(dfs):
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    js = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    jw = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    both = np.intersect1d(js.i_category_id.unique(),
                          jw.i_category_id.unique())
    return pd.DataFrame({"i_category_id": np.sort(both)})


def q87_except(dfs):
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    js = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    jw = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    only = np.setdiff1d(js.i_brand_id.unique(), jw.i_brand_id.unique())
    return pd.DataFrame({"i_brand_id": np.sort(only)})


def q_dense_rank_cat(dfs, top_n=2):
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk"))
    rev = (j.groupby(["i_category", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum())
    rev["dr"] = (rev.groupby("i_category")["ss_ext_sales_price"]
                 .rank(method="dense", ascending=False).astype(int))
    return rev[rev.dr <= top_n]


def q34_baskets(dfs, qty_min=60):
    ss = dfs["store_sales"]
    per_item = (ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False)
                ["ss_quantity"].sum())
    big = per_item[per_item.ss_quantity >= qty_min]
    return (big.groupby("ss_store_sk", as_index=False)
            ["ss_item_sk"].count())


def q_channel_day(dfs):
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    s_rev = (ss.groupby(["ss_item_sk", "ss_sold_date_sk"], as_index=False)
             ["ss_ext_sales_price"].sum())
    w_rev = (ws.groupby(["ws_item_sk", "ws_sold_date_sk"], as_index=False)
             ["ws_ext_sales_price"].sum())
    j = s_rev.merge(w_rev, left_on=["ss_item_sk", "ss_sold_date_sk"],
                    right_on=["ws_item_sk", "ws_sold_date_sk"])
    j = j.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    return (j.groupby("i_category", as_index=False)
            .agg(s=("ss_ext_sales_price", "sum"),
                 w=("ws_ext_sales_price", "sum")))


def q_web_also_qty(dfs):
    ss, ws = dfs["store_sales"], dfs["web_sales"]
    pairs = ws[["ws_item_sk", "ws_sold_date_sk"]].drop_duplicates()
    j = ss.merge(pairs, left_on=["ss_item_sk", "ss_sold_date_sk"],
                 right_on=["ws_item_sk", "ws_sold_date_sk"])
    return (j.groupby("ss_store_sk", as_index=False)["ss_quantity"].sum())


def q_brand_rev_left(dfs, manager_id=28):
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item[item.i_manager_id == manager_id],
                 left_on="ss_item_sk", right_on="i_item_sk", how="left")
    return (j.groupby("i_brand_id", dropna=False, as_index=False)
            .agg(s=("ss_ext_sales_price", "sum"),
                 c=("ss_item_sk", "count")))


QUERIES = {
    "q3": q3, "q42": q42, "q52": q52, "q55": q55,
    "q_state_rollup": q_state_rollup, "q7": q7, "q19": q19, "q62": q62,
    "q52_topn": q52_topn, "q65": q65, "q_store_counts": q_store_counts,
    "q67_rank": q67_rank, "q_like_brands": q_like_brands,
    "q_union_channels": q_union_channels, "q_lag_growth": q_lag_growth,
    "q_running_share": q_running_share, "q_nunique_items": q_nunique_items,
    "q_having": q_having, "q_case_when": q_case_when,
    "q_distinct_pairs": q_distinct_pairs, "q_isin_states": q_isin_states,
    "q36_rollup": q36_rollup, "q86_rollup": q86_rollup,
    "q27_cube": q27_cube, "q5_grouping_sets": q5_grouping_sets,
    "q78_outer": q78_outer, "q25_two_fact": q25_two_fact,
    "q88_counts": q88_counts, "q90_ratio": q90_ratio,
    "q29_minmax": q29_minmax, "q48_bands": q48_bands,
    "q13_avg_bands": q13_avg_bands, "q96_count": q96_count,
    "q23_semi": q23_semi, "q16_anti": q16_anti,
    "q_minmax_price": q_minmax_price, "q_multi_measure": q_multi_measure,
    "q_rollup3": q_rollup3, "q_first_last": q_first_last,
    "q_rownum_dedup": q_rownum_dedup, "q_cross_ratio": q_cross_ratio,
    "q_null_share": q_null_share,
    "q17_stats": q17_stats, "q8_intersect": q8_intersect,
    "q87_except": q87_except, "q_dense_rank_cat": q_dense_rank_cat,
    "q34_baskets": q34_baskets, "q_channel_day": q_channel_day,
    "q_web_also_qty": q_web_also_qty, "q_brand_rev_left": q_brand_rev_left,
}
