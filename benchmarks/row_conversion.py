"""Row-conversion microbenchmarks — the reference's nvbench axes on TPU.

Mirrors ``src/main/cpp/benchmarks/row_conversion.cpp``:

* ``fixed_width``: 212-column cycled fixed-width schema × {1M, 4M} rows ×
  {to row, from row} (``:27-67, 140-143``).
* ``variable_or_fixed``: 155-column schema × {strings, no strings} ×
  direction, string states above 1M rows skipped ("memory issues",
  ``:117-120, 145-149``).

Throughput counts the JCUDF row bytes moved once per direction, the analog
of nvbench's global-memory-read summary.

Usage:  python -m benchmarks.row_conversion [--full] [--json OUT.jsonl]
"""

from __future__ import annotations

import argparse

import jax

from spark_rapids_jni_tpu import convert_to_rows, convert_from_rows

from .datagen import create_random_table, cycled_schema
from .harness import Bench, report, tie

FIXED_COLS = 212       # benchmarks/row_conversion.cpp:38
VARIABLE_COLS = 155    # benchmarks/row_conversion.cpp:74


def _make_closure(state, table):
    """Shared carry-chained closure machinery (harness.tie discipline):
    tie one payload buffer to the previous iteration's carry so chained
    iterations provably execute under a single final sync."""
    from spark_rapids_jni_tpu.column import Column, Table as _Table
    from spark_rapids_jni_tpu.rowconv.convert import RowBatch

    batches = convert_to_rows(table)
    state.bytes_per_iter = sum(b.num_bytes for b in batches)

    if state["direction"] == "to_row":
        fold_ci = next(i for i, c in enumerate(table.columns)
                       if c.dtype.is_fixed_width)

        def closure(carry):
            cols = list(table.columns)
            c0 = cols[fold_ci]
            cols[fold_ci] = Column(c0.dtype, tie(c0.data, carry),
                                   c0.offsets, c0.validity)
            return [b.data for b in convert_to_rows(_Table(cols))]
    else:
        schema = table.schema

        def closure(carry):
            outs = []
            for b in batches:
                bb = RowBatch(tie(b.data, carry), b.offsets)
                outs.extend(c.data for c in
                            convert_from_rows(bb, schema).columns)
            return outs
    return closure


def _row_conversion_bench(state):
    n_rows = state["rows"]
    with_strings = state.params.get("strings", False)
    n_cols = VARIABLE_COLS if "strings" in state.params else FIXED_COLS
    # short strings keep the 155-col row under the 1KB JCUDF row limit
    table = create_random_table(
        cycled_schema(n_cols, include_strings=with_strings), n_rows,
        max_string_len=10)
    return _make_closure(state, table)


def _spark_shaped_bench(state):
    """Realistic Spark row shape: a dozen fixed columns + two string columns
    of ~20 chars — the regime the ragged DMA engine targets (the 155-col
    synthetic state above routes to the XLA fallback by design)."""
    table = create_random_table(
        cycled_schema(12, include_strings=True, string_every=6),
        state["rows"], max_string_len=40)
    return _make_closure(state, table)


def build_benches(full: bool):
    rows = [1 << 20, 4 << 20] if full else [1 << 18]
    fixed = Bench("fixed_width", _row_conversion_bench,
                  axes={"rows": rows, "direction": ["to_row", "from_row"]})
    variable = Bench(
        "variable_or_fixed", _row_conversion_bench,
        axes={"rows": rows, "direction": ["to_row", "from_row"],
              "strings": [False, True]},
        # reference skips string states above 1M rows (:117-120)
        skip=lambda s: ("string case skipped above 1M rows"
                        if s["strings"] and s["rows"] > (1 << 20) else None))
    spark_shaped = Bench(
        "spark_shaped_strings", _spark_shaped_bench,
        axes={"rows": rows, "direction": ["to_row", "from_row"]},
        skip=lambda s: ("skipped above 1M rows"
                        if s["rows"] > (1 << 20) else None))
    return [fixed, variable, spark_shaped]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="the reference's full 1M/4M axes")
    ap.add_argument("--json", default=None, help="write JSON lines here")
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    print(f"devices: {jax.devices()}", flush=True)
    results = []
    for bench in build_benches(args.full):
        results.extend(bench.run(iters=args.iters))
    report(results, args.json)


if __name__ == "__main__":
    main()
