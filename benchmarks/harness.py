"""Tiny nvbench-style benchmark harness.

The reference drives its microbenchmarks with nvbench states and axes
(``benchmarks/row_conversion.cpp:140-149``: named int/string axes, per-state
timed regions, global-memory throughput summaries).  This is the framework's
equivalent: declare axes, get the cartesian product of states, time a
closure per state (warmup + measured iterations, device-synchronised), and
report a table plus machine-readable JSON lines.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import time
from typing import Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def sync(tree) -> None:
    """Force device execution to complete.

    ``jax.block_until_ready`` is NOT sufficient on remote-dispatch backends
    (observed on the axon-tunneled v5e: execution is deferred until bytes are
    requested, so block_until_ready returns immediately and timings measure
    dispatch rate, not device throughput).  Materializing one element of
    every output leaf forces the computation.
    """
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and getattr(leaf, "size", 1):
            np.asarray(jax.numpy.ravel(leaf)[0])


def chain_carry(tree) -> jnp.ndarray:
    """A cheap scalar data-dependent on every leaf of ``tree``.

    Feeding this into the next timed iteration chains the iterations so that
    one final :func:`sync` provably executes them all (a lazy backend would
    otherwise skip unmaterialized intermediate calls entirely).
    """
    acc = jnp.zeros((), jnp.int32)
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and getattr(leaf, "size", 1):
            acc = acc + jax.lax.convert_element_type(
                jnp.ravel(leaf)[0], jnp.int32)
    # bounded but NOT statically foldable (x % 1 would simplify to 0 and
    # sever the chain)
    return acc % jnp.int32(251)


@jax.jit
def tie(x, carry):
    """Return ``x`` unchanged but data-dependent on ``carry``.

    ``lax.optimization_barrier`` is opaque to XLA's simplifier, so the
    dependency survives without perturbing values — closures use this to
    chain their inputs to the previous iteration's outputs.
    """
    return jax.lax.optimization_barrier((x, carry))[0]


@dataclasses.dataclass
class State:
    """One point in the axis product; mirrors nvbench's state object."""

    params: Mapping[str, object]
    bytes_per_iter: int = 0      # set by the benchmark body for GB/s

    def __getitem__(self, name):
        return self.params[name]


@dataclasses.dataclass
class Result:
    bench: str
    params: Mapping[str, object]
    seconds: float
    gb_per_s: float


class Bench:
    def __init__(self, name: str, fn: Callable[[State], Callable[..., object]],
                 axes: Mapping[str, Sequence[object]],
                 skip: Callable[[State], str | None] = lambda s: None):
        """``fn(state)`` prepares inputs and returns the timed closure.

        The closure takes one argument — a scalar ``carry`` it must fold into
        its device inputs (e.g. add to one input column) — and returns its
        device outputs.  The harness chains iterations through the carry and
        forces execution once at the end (:func:`sync`), so the measured
        window is device time, amortizing the per-sync round-trip latency
        (~65-110 ms through the axon tunnel) across all iterations.  ``skip``
        may return a reason string (the reference skips >1M-row string
        states, ``benchmarks/row_conversion.cpp:117-120``).
        """
        self.name, self.fn, self.axes, self.skip = name, fn, axes, skip

    def states(self):
        names = list(self.axes)
        for combo in itertools.product(*(self.axes[n] for n in names)):
            yield State(dict(zip(names, combo)))

    def run(self, warmup: int = 2, iters: int = 5) -> list[Result]:
        results = []
        for state in self.states():
            reason = self.skip(state)
            tag = ", ".join(f"{k}={v}" for k, v in state.params.items())
            if reason:
                print(f"  SKIP {self.name}[{tag}]: {reason}", flush=True)
                continue
            closure = self.fn(state)
            carry = jnp.zeros((), jnp.int32)
            for _ in range(warmup):
                carry = chain_carry(closure(carry))
            sync(carry)
            t0 = time.perf_counter()
            carry = jnp.zeros((), jnp.int32)
            for _ in range(iters):
                carry = chain_carry(closure(carry))
            sync(carry)
            dt = (time.perf_counter() - t0) / iters
            gbps = state.bytes_per_iter / dt / 1e9 if state.bytes_per_iter else 0.0
            results.append(Result(self.name, dict(state.params), dt, gbps))
            print(f"  {self.name}[{tag}]: {dt * 1e3:.2f} ms"
                  + (f"  {gbps:.2f} GB/s" if gbps else ""), flush=True)
        return results


def report(results: Sequence[Result], json_path: str | None = None) -> None:
    """Markdown summary table + one JSON line per state (nvbench's dual
    human/CSV output)."""
    if not results:
        return
    keys = list(results[0].params)
    header = ["bench"] + keys + ["ms", "GB/s"]
    print("\n| " + " | ".join(header) + " |")
    print("|" + "|".join("---" for _ in header) + "|")
    lines = []
    for r in results:
        row = [r.bench] + [str(r.params[k]) for k in keys] \
            + [f"{r.seconds * 1e3:.2f}", f"{r.gb_per_s:.2f}"]
        print("| " + " | ".join(row) + " |")
        lines.append(json.dumps({"bench": r.bench, **r.params,
                                 "seconds": r.seconds,
                                 "gb_per_s": round(r.gb_per_s, 3)}))
    if json_path:
        with open(json_path, "w") as f:
            f.write("\n".join(lines) + "\n")
    print()
