"""Shared random ragged-data generator — the single oracle-input source for
both the CPU fallback tests (tests/test_ragged.py) and the on-chip sweep
(tools/tpu_check.py), so the two always exercise the same distributions."""

from __future__ import annotations

import numpy as np


def random_ragged(rng: np.random.Generator, n: int, M: int,
                  aligned: bool = False):
    """Returns (dense u8 [n, M] zero-padded, offsets int64 [n+1], flat)."""
    if aligned:
        sizes = rng.integers(1, M // 8 + 1, n) * 8
    else:
        sizes = rng.integers(0, M + 1, n)
    offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(sizes, out=offs[1:])
    dense = np.zeros((n, M), dtype=np.uint8)
    for r in range(n):
        dense[r, :sizes[r]] = rng.integers(1, 256, sizes[r])
    flat = (np.concatenate([dense[r, :sizes[r]] for r in range(n)])
            if offs[-1] else np.zeros(0, np.uint8))
    return dense, offs, flat
