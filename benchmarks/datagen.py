"""Random-table generation for benchmarks.

Equivalent of the cudf datagen library the reference benchmarks link
(``create_random_table``, ``benchmarks/row_conversion.cpp:31,105``;
``benchmarks/CMakeLists.txt:18-21``): build a table from a cycled dtype
schema with configurable null fraction and random strings.
"""

from __future__ import annotations

import string as _string
from typing import Optional, Sequence

import numpy as np

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Column, Table

# The reference's fixed-width bench cycles int8/16/32/64, float, bool over
# 212 columns (``benchmarks/row_conversion.cpp:38-47``); f64 excluded here
# for the same reason as bench.py (XLA:TPU f64 payloads stage via host).
FIXED_CYCLE = [sr.int64, sr.int32, sr.int16, sr.int8, sr.float32, sr.bool8]


def cycled_schema(n_cols: int, include_strings: bool = False,
                  string_every: int = 10):
    """n_cols-wide schema cycling FIXED_CYCLE, optionally a string column
    every ``string_every`` slots (the variable-width bench mixes ~1/10,
    ``benchmarks/row_conversion.cpp:74-88``)."""
    schema = []
    for i in range(n_cols):
        if include_strings and i % string_every == 0:
            schema.append(sr.string)
        else:
            schema.append(FIXED_CYCLE[i % len(FIXED_CYCLE)])
    return schema


def random_column(dt, n_rows: int, rng: np.random.Generator,
                  null_probability: Optional[float] = 0.1,
                  max_string_len: int = 32) -> Column:
    validity = (rng.random(n_rows) >= null_probability
                if null_probability else None)
    if dt == sr.string:
        alphabet = np.array(list(_string.ascii_letters + _string.digits))
        lens = rng.integers(0, max_string_len, n_rows)
        strs = ["".join(rng.choice(alphabet, size=l)) for l in lens]
        if validity is not None:
            strs = [s if v else None for s, v in zip(strs, validity)]
        return Column.strings_from_list(strs)
    st = dt.storage
    if st.kind == "f":
        arr = rng.standard_normal(n_rows).astype(st)
    elif dt == sr.bool8:
        arr = rng.integers(0, 2, n_rows).astype(np.uint8)
    else:
        info = np.iinfo(st)
        arr = rng.integers(info.min // 2, info.max // 2, n_rows, dtype=st)
    return Column.from_numpy(arr, dt, validity)


def create_random_table(schema: Sequence, n_rows: int, seed: int = 0,
                        null_probability: Optional[float] = 0.1,
                        max_string_len: int = 32) -> Table:
    rng = np.random.default_rng(seed)
    return Table([random_column(dt, n_rows, rng, null_probability,
                                max_string_len) for dt in schema])
