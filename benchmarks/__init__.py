"""Manual benchmark suite (nvbench-harness equivalent, SURVEY §2.8).

Like the reference's ``src/main/cpp/benchmarks`` (nvbench, never run in CI —
``CONTRIBUTING.md:223-231``), these are run by hand:

    python -m benchmarks.row_conversion            # quick axes
    python -m benchmarks.row_conversion --full     # the reference's axes
"""
