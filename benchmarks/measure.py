"""Shared on-chip timing harness: chained-fori-loop trip-count differencing.

The ONE implementation of the BASELINE.md methodology for the profiler
tools (bench.py carries its own copy by design — the driver contract file
must stay self-contained): dependency-chain the body inside one jit via
optimization barriers, difference two trip counts of the same program,
keep the best positive delta.  Returns None when every repeat differenced
non-positive (tunnel noise) — callers must record an error, not divide.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def chained_loop(body):
    """jit(data, iters) running ``body`` iters times, dependency-chained;
    the FULL output tree passes through an optimization barrier, so no
    part of the body is dead-code-eliminated."""
    @jax.jit
    def run(data, iters):
        def step(_, carry):
            acc, d = carry
            din = lax.optimization_barrier((d, acc))[0]
            out = body(din)
            out = lax.optimization_barrier(out)
            leaves = [l for l in jax.tree_util.tree_leaves(out) if l.size]
            probe = (lax.convert_element_type(jnp.ravel(leaves[0])[0],
                                              jnp.int32)
                     if leaves else jnp.int32(0))
            return (acc + probe) % jnp.int32(65521), d
        acc, _ = lax.fori_loop(0, iters, step, (jnp.int32(0), data))
        return acc
    return run


def time_diff(body, data, lo: int = 2, hi: int = 8,
              repeats: int = 2) -> float | None:
    """Steady-state seconds/iteration, or None if timing was unusable."""
    run = chained_loop(body)
    np.asarray(run(data, lo))            # compile + warm
    best = None
    good = 0
    for _ in range(repeats + 3):
        t0 = time.perf_counter()
        np.asarray(run(data, lo))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run(data, hi))
        t_hi = time.perf_counter() - t0
        per = (t_hi - t_lo) / (hi - lo)
        if per <= 0:
            continue
        good += 1
        best = per if best is None else min(best, per)
        if good >= repeats:
            break
    return best
