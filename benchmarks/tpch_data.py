"""Mini TPC-H lineitem generator for the Q1 pricing-summary query.

Decimal measures are written as parquet DECIMAL (FLBA) so the framework's
decimal decode path feeds the query; flags are low-cardinality strings like
the spec's returnflag/linestatus.
"""

from __future__ import annotations

import decimal
import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


def generate(n: int = 50_000, seed: int = 21) -> tuple[bytes, dict]:
    rng = np.random.default_rng(seed)
    epoch98 = 10561    # days 1970 → 1998-12-01
    qty = rng.integers(1, 51, n).astype(np.int64)
    price_c = rng.integers(90_000, 10_000_000, n)        # cents
    disc_c = rng.integers(0, 11, n)                      # 0.00-0.10
    tax_c = rng.integers(0, 9, n)                        # 0.00-0.08
    ship = rng.integers(epoch98 - 2500, epoch98 + 100, n).astype(np.int32)
    flags = np.where(rng.random(n) < 0.5, "N",
                     np.where(rng.random(n) < 0.5, "A", "R"))
    status = np.where(flags == "N", "O", "F")

    table = pa.table({
        "l_returnflag": pa.array(flags.tolist()),
        "l_linestatus": pa.array(status.tolist()),
        "l_quantity": pa.array(qty),
        "l_extendedprice": pa.array(
            [decimal.Decimal(int(c)) / 100 for c in price_c],
            pa.decimal128(12, 2)),
        "l_discount": pa.array(
            [decimal.Decimal(int(c)) / 100 for c in disc_c],
            pa.decimal128(4, 2)),
        "l_tax": pa.array(
            [decimal.Decimal(int(c)) / 100 for c in tax_c],
            pa.decimal128(4, 2)),
        "l_shipdate": pa.array(ship, pa.date32()),
    })
    buf = io.BytesIO()
    pq.write_table(table, buf, compression="SNAPPY")
    raw = {"flags": flags, "status": status, "qty": qty,
           "price_c": price_c, "disc_c": disc_c, "tax_c": tax_c,
           "ship": ship}
    return buf.getvalue(), raw
