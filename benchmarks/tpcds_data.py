"""Mini TPC-DS-shaped data generator (BASELINE config #3 subset).

Generates the slice of the TPC-DS schema the query subset needs —
``store_sales`` fact plus ``item`` / ``date_dim`` / ``store`` dimensions —
as Snappy Parquet bytes via pyarrow (the independent writer/oracle, as in
the decode tests).  Shapes follow the spec's spirit: surrogate-key joins,
low-cardinality string dimensions (brand/category/state), decimal-valued
measures carried as scaled int64 cents (the framework's decimal64
representation, ``RowConversion.java:114-118``).
"""

from __future__ import annotations

import functools
import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

CATEGORIES = ["Books", "Home", "Electronics", "Jewelry", "Music",
              "Shoes", "Sports", "Women", "Men", "Children"]
STATES = ["TN", "CA", "TX", "WA", "NY", "GA", "OH", "IL"]


def _parquet(table: pa.Table, row_group_size: int | None = None) -> bytes:
    buf = io.BytesIO()
    kw = {} if row_group_size is None else {"row_group_size": row_group_size}
    pq.write_table(table, buf, compression="SNAPPY", use_dictionary=False,
                   **kw)
    return buf.getvalue()


@functools.lru_cache(maxsize=8)
def generate(n_sales: int = 100_000, n_items: int = 2000,
             n_dates: int = 366 * 3, n_stores: int = 12,
             seed: int = 42,
             row_group_size: int | None = None) -> dict[str, bytes]:
    # memoized: generation is pure in its arguments, and several test
    # modules ask for identical datasets — returning the SAME byte blobs
    # lets the decode layer's identity memo skip re-scanning them.
    # Callers must treat the returned dict as read-only.
    rng = np.random.default_rng(seed)

    import decimal as _dec
    item = pa.table({
        "i_item_sk": pa.array(np.arange(1, n_items + 1, dtype=np.int32)),
        "i_item_id": pa.array(
            [f"AAAA{sk:012d}" for sk in range(1, n_items + 1)]),
        "i_current_price": pa.array(
            [_dec.Decimal(int(c)) / 100
             for c in rng.integers(50, 500_00, n_items)],
            pa.decimal128(7, 2)),     # FLBA decimal → decimal32(-2) decode
        "i_brand_id": pa.array(
            rng.integers(1000, 1100, n_items).astype(np.int32)),
        "i_brand": pa.array(
            [f"brand#{b}" for b in rng.integers(1, 60, n_items)]),
        "i_category_id": pa.array(
            rng.integers(1, len(CATEGORIES) + 1, n_items).astype(np.int32)),
        "i_category": pa.array(
            [CATEGORIES[c] for c in rng.integers(0, len(CATEGORIES),
                                                 n_items)]),
        "i_manufact_id": pa.array(
            rng.integers(1, 1000, n_items).astype(np.int32)),
        "i_manager_id": pa.array(
            rng.integers(1, 100, n_items).astype(np.int32)),
    })

    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(1, n_dates + 1, dtype=np.int32)),
        "d_year": pa.array(
            (1999 + (np.arange(n_dates) // 366)).astype(np.int32)),
        "d_moy": pa.array(
            (1 + (np.arange(n_dates) // 30) % 12).astype(np.int32)),
    })

    store = pa.table({
        "s_store_sk": pa.array(np.arange(1, n_stores + 1, dtype=np.int32)),
        "s_state": pa.array(
            [STATES[s] for s in rng.integers(0, len(STATES), n_stores)]),
    })

    # decimal(7,2) measures as int64 cents (decimal64 scale -2)
    price_cents = rng.integers(100, 300_00, n_sales).astype(np.int64)
    list_cents = price_cents + rng.integers(0, 50_00, n_sales)
    qty = rng.integers(1, 100, n_sales).astype(np.int32)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(1, n_dates + 1, n_sales).astype(np.int32)),
        "ss_item_sk": pa.array(
            rng.integers(1, n_items + 1, n_sales).astype(np.int32)),
        # stores 1..n_stores-1 only: the LAST store never sells, so the
        # left-join query family has a genuinely unmatched dimension row
        "ss_store_sk": pa.array(
            rng.integers(1, max(n_stores, 2), n_sales).astype(np.int32)),
        "ss_quantity": pa.array(qty),
        "ss_sales_price_cents": pa.array(price_cents),
        "ss_list_price_cents": pa.array(list_cents),
        "ss_ext_sales_price": pa.array(
            (price_cents * qty).astype(np.float64) / 100.0),
    })

    # second fact table (the multi-fact union family: Q71/Q76 shape)
    n_web = max(n_sales // 3, 1)
    w_price = rng.integers(100, 300_00, n_web).astype(np.int64)
    w_qty = rng.integers(1, 100, n_web).astype(np.int32)
    # ~3% null prices: COUNT(*) vs COUNT(col) and null-skipping SUM must
    # actually diverge somewhere in the dataset (q_null_share family)
    w_ext = (w_price * w_qty).astype(np.float64) / 100.0
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(
            rng.integers(1, n_dates + 1, n_web).astype(np.int32)),
        "ws_item_sk": pa.array(
            rng.integers(1, n_items + 1, n_web).astype(np.int32)),
        "ws_quantity": pa.array(w_qty),
        "ws_ext_sales_price": pa.array(
            w_ext, mask=rng.random(n_web) < 0.03),
    })

    rgs = row_group_size
    return {"store_sales": _parquet(store_sales, rgs),
            "item": _parquet(item, rgs), "date_dim": _parquet(date_dim, rgs),
            "store": _parquet(store, rgs), "web_sales": _parquet(web_sales,
                                                                 rgs)}


def _store_sales_batch(rng: np.random.Generator, n_rows: int, n_items: int,
                       n_dates: int, n_stores: int) -> pa.Table:
    """One batch of store_sales rows, same schema and distributions as
    ``generate`` (incl. the never-selling last store)."""
    price_cents = rng.integers(100, 300_00, n_rows).astype(np.int64)
    list_cents = price_cents + rng.integers(0, 50_00, n_rows)
    qty = rng.integers(1, 100, n_rows).astype(np.int32)
    return pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(1, n_dates + 1, n_rows).astype(np.int32)),
        "ss_item_sk": pa.array(
            rng.integers(1, n_items + 1, n_rows).astype(np.int32)),
        "ss_store_sk": pa.array(
            rng.integers(1, max(n_stores, 2), n_rows).astype(np.int32)),
        "ss_quantity": pa.array(qty),
        "ss_sales_price_cents": pa.array(price_cents),
        "ss_list_price_cents": pa.array(list_cents),
        "ss_ext_sales_price": pa.array(
            (price_cents * qty).astype(np.float64) / 100.0),
    })


def append_rows(n_rows: int, seed: int, *, n_items: int = 2000,
                n_dates: int = 366 * 3, n_stores: int = 12,
                row_group_size: int | None = None,
                base: bytes | None = None) -> bytes:
    """Deterministic batch of appended ``store_sales`` rows (the streaming
    ingest unit): schema/distributions match ``generate``, keyed off its
    own seed stream so epochs are reproducible and disjoint from the base
    dataset.

    Without ``base``, returns a standalone parquet blob (one or more row
    groups at ``row_group_size``) for ``stream.DeltaTable.append_file``.
    With ``base``, returns the base file rewritten with the new rows
    appended — when the base's row count is a multiple of
    ``row_group_size`` the existing row-group layout is preserved as a
    prefix, the contract ``stream.DeltaTable.extend_file`` validates."""
    rng = np.random.default_rng(seed)
    batch = _store_sales_batch(rng, n_rows, n_items, n_dates, n_stores)
    if base is not None:
        old = pq.read_table(io.BytesIO(base))
        batch = pa.concat_tables([old, batch])
    return _parquet(batch, row_group_size)
