"""Mini Mortgage-ETL-shaped raw data generator (BASELINE config #5).

The RAPIDS Mortgage demo ingests Fannie Mae performance + acquisition files
whose columns arrive as raw TEXT (dates "%m/%d/%Y", decimal rates/balances,
coded delinquency statuses) and casts them on the accelerator; this
generator reproduces that shape as parquet STRING columns so the framework's
``ops.strings`` parse kernels (to_int64/to_decimal/to_date) carry the same
load the reference's libcudf string-cast kernels do.
"""

from __future__ import annotations

import io

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

SELLERS = ["BANK OF AMERICA", "WELLS FARGO", "QUICKEN", "OTHER",
           "JPMORGAN CHASE", "CITIMORTGAGE"]
STATES = ["CA", "TX", "NY", "FL", "IL", "WA", "OH", "GA"]


def _parquet(table: pa.Table) -> bytes:
    buf = io.BytesIO()
    pq.write_table(table, buf, compression="SNAPPY")
    return buf.getvalue()


def generate(n_loans: int = 2000, periods_per_loan: int = 12,
             seed: int = 11) -> dict[str, bytes]:
    rng = np.random.default_rng(seed)

    loan_ids = np.arange(10**11, 10**11 + n_loans, dtype=np.int64)

    acq = pa.table({
        "loan_id": pa.array(loan_ids),
        "orig_interest_rate": pa.array(
            [f"{r:.4f}" for r in rng.uniform(2.5, 8.0, n_loans)]),
        "orig_upb": pa.array(
            [str(u) for u in rng.integers(50_000, 800_000, n_loans)]),
        "orig_date": pa.array(
            [f"{rng.integers(2000, 2020)}-{rng.integers(1, 13):02d}-01"
             for _ in range(n_loans)]),
        "state": pa.array(
            [STATES[s] for s in rng.integers(0, len(STATES), n_loans)]),
        "seller_name": pa.array(
            [None if rng.random() < 0.05 else
             SELLERS[s] for s in rng.integers(0, len(SELLERS), n_loans)]),
    })

    n_perf = n_loans * periods_per_loan
    perf_loan = np.repeat(loan_ids, periods_per_loan)
    month = np.tile(np.arange(periods_per_loan), n_loans)
    years = 2019 + month // 12
    moys = 1 + month % 12
    # ~3% of statuses are the unparseable "X" code; ~2% of UPBs are blank —
    # the raw-data warts the ETL must absorb
    status_pool = rng.integers(0, 4, n_perf)
    statuses = np.where(rng.random(n_perf) < 0.03, -1, status_pool)
    upb = rng.uniform(10_000, 900_000, n_perf)
    perf = pa.table({
        "loan_id": pa.array(perf_loan),
        "monthly_reporting_period": pa.array(
            [f"{m:02d}/01/{y}" for m, y in zip(moys, years)]),
        "current_actual_upb": pa.array(
            ["" if rng.random() < 0.02 else f"{u:.2f}" for u in upb]),
        "current_loan_delinquency_status": pa.array(
            ["X" if s < 0 else str(s) for s in statuses]),
        "servicer_name": pa.array(
            [None if rng.random() < 0.3 else
             SELLERS[s] for s in rng.integers(0, len(SELLERS), n_perf)]),
    })

    return {"perf": _parquet(perf), "acq": _parquet(acq)}
