/*
 * A host-resident column handle backed by native memory.
 *
 * The TPU framework's stand-in for the cudf Java ColumnVector the reference
 * API trades in (RowConversion.java:101-110): fixed-width payload bytes or
 * string chars + int32 Arrow offsets, with an optional byte-per-row
 * validity vector.  Native ownership follows the reference's handle
 * protocol — the creator owns the handle until close().
 */
package com.tpu.rapids.jni;

public final class HostColumn implements AutoCloseable {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  private long handle;
  private final int typeId;
  private final int scale;

  private HostColumn(long handle, int typeId, int scale) {
    this.handle = handle;
    this.typeId = typeId;
    this.scale = scale;
  }

  /**
   * Builds a fixed-width column by copying {@code rowCount * typeSize}
   * little-endian bytes from {@code dataAddress}.  {@code validAddress} is
   * a byte-per-row validity vector, or 0 for all-valid.
   */
  public static HostColumn fromFixedWidth(int typeId, int scale, long rowCount,
      long dataAddress, long validAddress) {
    long h = makeFixed(typeId, scale, rowCount, dataAddress, validAddress);
    return new HostColumn(h, typeId, scale);
  }

  /** Builds a string column from Arrow offsets ({@code rowCount+1} int32s)
   *  and a chars buffer. */
  public static HostColumn fromStrings(long rowCount, long offsetsAddress,
      long charsAddress, long validAddress) {
    long h = makeString(rowCount, offsetsAddress, charsAddress, validAddress);
    return new HostColumn(h, /*STRING=*/24, 0);
  }

  static HostColumn wrap(long handle, int typeId, int scale) {
    return new HostColumn(handle, typeId, scale);
  }

  public long getNativeHandle() {
    if (handle == 0) {
      throw new IllegalStateException("column closed");
    }
    return handle;
  }

  public int getTypeId() {
    return typeId;
  }

  public int getScale() {
    return scale;
  }

  // Readback surface — the reference verifies conversions through cudf's
  // copy-to-host accessors (RowConversionTest.java:29-59); these expose
  // the native buffers for the same purpose.

  public long getRowCount() {
    return rows(getNativeHandle());
  }

  /** Payload byte length (fixed-width bytes, or string chars). */
  public long getDataSize() {
    return dataSize(getNativeHandle());
  }

  /** Address of the payload bytes (valid until close()). */
  public long getDataAddress() {
    return dataAddress(getNativeHandle());
  }

  /** Address of the int32 Arrow offsets, or 0 for fixed-width columns. */
  public long getOffsetsAddress() {
    return offsetsAddress(getNativeHandle());
  }

  /** Address of the byte-per-row validity vector, or 0 when all-valid. */
  public long getValidityAddress() {
    return validAddress(getNativeHandle());
  }

  @Override
  public void close() {
    if (handle != 0) {
      close(handle);
      handle = 0;
    }
  }

  private static native long makeFixed(int typeId, int scale, long rowCount,
      long dataAddress, long validAddress);

  private static native long makeString(long rowCount, long offsetsAddress,
      long charsAddress, long validAddress);

  private static native void close(long handle);

  private static native long rows(long handle);

  private static native long dataSize(long handle);

  private static native long dataAddress(long handle);

  private static native long offsetsAddress(long handle);

  private static native long validAddress(long handle);
}
