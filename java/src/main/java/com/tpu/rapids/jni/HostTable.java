/*
 * An ordered set of equal-length HostColumns (the cudf Table analog).
 *
 * Columns are shared, not owned: closing the table releases the table's
 * references while column handles stay valid until their own close() — the
 * same refcount discipline the reference inherits from cudf Java.
 */
package com.tpu.rapids.jni;

public final class HostTable implements AutoCloseable {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  private long handle;

  private HostTable(long handle) {
    this.handle = handle;
  }

  public static HostTable fromColumns(HostColumn... columns) {
    long[] handles = new long[columns.length];
    for (int i = 0; i < columns.length; i++) {
      handles[i] = columns[i].getNativeHandle();
    }
    return new HostTable(makeTable(handles));
  }

  static HostTable wrap(long handle) {
    return new HostTable(handle);
  }

  public long getNativeHandle() {
    if (handle == 0) {
      throw new IllegalStateException("table closed");
    }
    return handle;
  }

  public long getRowCount() {
    return rowCount(getNativeHandle());
  }

  /**
   * Releases each column as an independently-owned handle — the
   * convert_table_for_return protocol (RowConversionJni.cpp:33-38).
   * Caller closes each returned handle.
   */
  public long[] releaseColumns() {
    return columns(getNativeHandle());
  }

  @Override
  public void close() {
    if (handle != 0) {
      close(handle);
      handle = 0;
    }
  }

  private static native long makeTable(long[] columnHandles);

  private static native long rowCount(long handle);

  private static native long[] columns(long handle);

  private static native void close(long handle);
}
