/*
 * Handle to a native-parsed, pruned Parquet footer.
 *
 * Capability parity with the reference's ParquetFooter (ParquetFooter.java
 * :27-235): a schema DSL describing the columns Spark expects, a
 * depth-first flattening into parallel names/numChildren/tags arrays for
 * cheap JNI transfer, readAndFilter (thrift parse + column prune + row
 * group selection by split midpoint), and PAR1-framed re-serialization.
 */
package com.tpu.rapids.jni;

import java.util.ArrayList;
import java.util.List;

public final class ParquetFooter implements AutoCloseable {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  // tags match the native engine (footer_engine.cpp; reference
  // NativeParquetJni.cpp Tag{VALUE,STRUCT,LIST,MAP})
  private static final int TAG_VALUE = 0;
  private static final int TAG_STRUCT = 1;
  private static final int TAG_LIST = 2;
  private static final int TAG_MAP = 3;

  /** Base of the expected-schema DSL (ParquetFooter.java:35-93 analog). */
  public abstract static class SchemaElement {
    final String name;
    final int tag;
    final List<SchemaElement> children = new ArrayList<>();

    SchemaElement(String name, int tag) {
      this.name = name;
      this.tag = tag;
    }
  }

  public static final class ValueElement extends SchemaElement {
    public ValueElement(String name) {
      super(name, TAG_VALUE);
    }
  }

  public static final class StructElement extends SchemaElement {
    public StructElement(String name, SchemaElement... kids) {
      super(name, TAG_STRUCT);
      for (SchemaElement k : kids) {
        children.add(k);
      }
    }
  }

  public static final class ListElement extends SchemaElement {
    public ListElement(String name, SchemaElement element) {
      super(name, TAG_LIST);
      children.add(element);
    }
  }

  public static final class MapElement extends SchemaElement {
    public MapElement(String name, SchemaElement key, SchemaElement value) {
      super(name, TAG_MAP);
      children.add(key);
      children.add(value);
    }
  }

  private long handle;

  private ParquetFooter(long handle) {
    this.handle = handle;
  }

  /**
   * Parse + prune the raw footer bytes at {@code bufferAddress}: keep only
   * columns present in {@code schema} (case-folded when
   * {@code ignoreCase}), and only row groups whose byte midpoint lies in
   * [partOffset, partOffset+partLength).
   */
  public static ParquetFooter readAndFilter(long bufferAddress,
      long bufferLength, long partOffset, long partLength,
      StructElement schema, boolean ignoreCase) {
    List<String> names = new ArrayList<>();
    List<Integer> numChildren = new ArrayList<>();
    List<Integer> tags = new ArrayList<>();
    depthFirst(schema, names, numChildren, tags);
    int n = names.size();
    int[] nc = new int[n];
    int[] tg = new int[n];
    String[] nm = new String[n];
    for (int i = 0; i < n; i++) {
      nm[i] = ignoreCase ? names.get(i).toLowerCase(java.util.Locale.ROOT) : names.get(i);
      nc[i] = numChildren.get(i);
      tg[i] = tags.get(i);
    }
    long h = readAndFilter(bufferAddress, bufferLength, partOffset,
        partLength, nm, nc, tg, schema.children.size(), ignoreCase);
    return new ParquetFooter(h);
  }

  /** Depth-first flattening, root excluded (ParquetFooter.java:136-185). */
  private static void depthFirst(SchemaElement node, List<String> names,
      List<Integer> numChildren, List<Integer> tags) {
    for (SchemaElement c : node.children) {
      names.add(c.name);
      numChildren.add(c.children.size());
      tags.add(c.tag);
      depthFirst(c, names, numChildren, tags);
    }
  }

  public long getNumRows() {
    return getNumRows(getNativeHandle());
  }

  public long getNumColumns() {
    return getNumColumns(getNativeHandle());
  }

  /**
   * Re-serialize as a standalone thrift "file": PAR1 + compact-protocol
   * footer + length + PAR1 (NativeParquetJni.cpp:666-699 framing).
   * Returns bytes written into the caller's buffer.
   */
  public long serializeThriftFile(long outAddress, long outCapacity) {
    return serializeThriftFile(getNativeHandle(), outAddress, outCapacity);
  }

  private long getNativeHandle() {
    if (handle == 0) {
      throw new IllegalStateException("footer closed");
    }
    return handle;
  }

  @Override
  public void close() {
    if (handle != 0) {
      close(handle);
      handle = 0;
    }
  }

  private static native long readAndFilter(long bufferAddress,
      long bufferLength, long partOffset, long partLength, String[] names,
      int[] numChildren, int[] tags, int parentNumChildren,
      boolean ignoreCase);

  private static native long getNumRows(long handle);

  private static native long getNumColumns(long handle);

  private static native long serializeThriftFile(long handle, long outAddress,
      long outCapacity);

  private static native void close(long handle);
}
