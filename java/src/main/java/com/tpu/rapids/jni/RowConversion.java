/*
 * Columnar <-> JCUDF row transcode, the framework's flagship API.
 *
 * Capability parity with the reference's RowConversion (RowConversion.java
 * :101-125): convertToRows produces row batches in the JCUDF format,
 * convertFromRows rebuilds columns from one batch plus a (typeId, scale)
 * schema.  The engine underneath is TPU-native (XLA/Pallas on device,
 * host_table.cpp on host) instead of CUDA.
 *
 * JCUDF row format (bit-identical to the reference's spec,
 * RowConversion.java:40-99):
 *   - rows are C-struct-like; each fixed-width column slot is aligned to
 *     its own byte size, string columns hold an 8-byte (offset,length)
 *     pair aligned to 4;
 *   - one validity bit per column, bit i of validity byte b = column
 *     b*8+i, bytes appended after the last data slot;
 *   - string chars follow the validity bytes; every row is padded to an
 *     8-byte boundary;
 *   - a row may not exceed 1KB, and each output batch stays under 2GB
 *     (int32 offsets), split at 32-row multiples.
 */
package com.tpu.rapids.jni;

public final class RowConversion {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  private RowConversion() {}

  /** One or more ≤2GB JCUDF row batches (LIST&lt;INT8&gt; analog). */
  public static final class RowBatches implements AutoCloseable {
    private long handle;

    RowBatches(long handle) {
      this.handle = handle;
    }

    public long getNativeHandle() {
      if (handle == 0) {
        throw new IllegalStateException("row batches closed");
      }
      return handle;
    }

    @Override
    public void close() {
      if (handle != 0) {
        freeRows(handle);
        handle = 0;
      }
    }
  }

  /** Columnar table -> JCUDF row batches. */
  public static RowBatches convertToRows(HostTable table) {
    return new RowBatches(convertToRows(table.getNativeHandle()));
  }

  /**
   * One JCUDF row batch -> columnar table.  {@code typeIds}/{@code scales}
   * mirror the reference's schema marshalling (RowConversion.java:110-120).
   */
  public static HostTable convertFromRows(RowBatches rows, int batch,
      int[] typeIds, int[] scales) {
    return HostTable.wrap(
        convertFromRows(rows.getNativeHandle(), batch, typeIds, scales));
  }

  /** Wraps caller-owned row bytes (e.g. shuffle-received) as a batch. */
  public static RowBatches importRows(long dataAddress, long dataSize,
      long offsetsAddress, long rowCount) {
    return new RowBatches(
        importRows(dataAddress, dataSize, offsetsAddress, rowCount));
  }

  private static native long convertToRows(long tableHandle);

  private static native long convertFromRows(long rowsHandle, int batch,
      int[] typeIds, int[] scales);

  private static native long importRows(long dataAddress, long dataSize,
      long offsetsAddress, long rowCount);

  private static native void freeRows(long rowsHandle);
}
