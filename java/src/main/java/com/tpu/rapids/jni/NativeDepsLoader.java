/*
 * Loader for the single native artifact libsrjt.so.
 *
 * Capability parity with the reference's NativeDepsLoader.loadNativeDeps()
 * class-init protocol (RowConversion.java:23-25 in spark-rapids-jni): every
 * API class triggers this loader before first native call.  The library is
 * located via -Dsrjt.native.path, java.library.path, or a resource embedded
 * under /<os.arch>/<os.name>/ in the jar (pom.xml:450-471 analog).
 */
package com.tpu.rapids.jni;

import java.io.File;
import java.io.IOException;
import java.io.InputStream;
import java.nio.file.Files;
import java.nio.file.Path;
import java.nio.file.StandardCopyOption;

public final class NativeDepsLoader {
  private static boolean loaded = false;

  private NativeDepsLoader() {}

  public static synchronized void loadNativeDeps() {
    if (loaded) {
      return;
    }
    String explicit = System.getProperty("srjt.native.path");
    if (explicit != null) {
      System.load(new File(explicit).getAbsolutePath());
      loaded = true;
      return;
    }
    try {
      System.loadLibrary("srjt");
      loaded = true;
      return;
    } catch (UnsatisfiedLinkError ignored) {
      // fall through to the embedded resource
    }
    String resource = "/" + System.getProperty("os.arch") + "/"
        + System.getProperty("os.name") + "/libsrjt.so";
    try (InputStream in = NativeDepsLoader.class.getResourceAsStream(resource)) {
      if (in == null) {
        throw new UnsatisfiedLinkError("libsrjt.so not found: " + resource);
      }
      Path tmp = Files.createTempFile("libsrjt", ".so");
      Files.copy(in, tmp, StandardCopyOption.REPLACE_EXISTING);
      tmp.toFile().deleteOnExit();
      System.load(tmp.toAbsolutePath().toString());
      loaded = true;
    } catch (IOException e) {
      throw new UnsatisfiedLinkError("failed to extract libsrjt.so: " + e);
    }
  }
}
