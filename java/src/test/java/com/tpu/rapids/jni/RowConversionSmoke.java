/*
 * JVM smoke test — the reference's RowConversionTest.java:29-59 shape with
 * no test-framework dependency (runs with a bare `java`): load libsrjt.so
 * through NativeDepsLoader, build an 8-column host table (7 fixed-width
 * types + 1 string column, with nulls), round-trip it through
 * RowConversion.convertToRows/convertFromRows, and assert byte equality of
 * every payload, offset, and validity buffer.
 *
 * Run (after ci/premerge.sh compiled the classes):
 *   java -cp spark_rapids_jni_tpu/java_classes \
 *        com.tpu.rapids.jni.RowConversionSmoke
 */
package com.tpu.rapids.jni;

import java.lang.reflect.Field;
import java.util.Random;

public final class RowConversionSmoke {
  private static final sun.misc.Unsafe U = unsafe();

  private static sun.misc.Unsafe unsafe() {
    try {
      Field f = sun.misc.Unsafe.class.getDeclaredField("theUnsafe");
      f.setAccessible(true);
      return (sun.misc.Unsafe) f.get(null);
    } catch (ReflectiveOperationException e) {
      throw new RuntimeException("sun.misc.Unsafe unavailable", e);
    }
  }

  private static long put(byte[] bytes) {
    long addr = U.allocateMemory(Math.max(bytes.length, 1));
    for (int i = 0; i < bytes.length; i++) {
      U.putByte(addr + i, bytes[i]);
    }
    return addr;
  }

  private static void check(boolean ok, String what) {
    if (!ok) {
      throw new AssertionError("FAILED: " + what);
    }
  }

  private static void checkBytes(long addr, byte[] expect, String what) {
    for (int i = 0; i < expect.length; i++) {
      check(U.getByte(addr + i) == expect[i], what + " byte " + i);
    }
  }

  public static void main(String[] args) {
    final int n = 1000;
    Random rng = new Random(7);

    // type ids follow the framework's TypeId enum (types.py): INT8=1,
    // INT16=2, INT32=3, INT64=4, FLOAT32=9, FLOAT64=10, BOOL8=11,
    // STRING=24 — the same marshalling RowConversion.convertFromRows takes.
    int[] typeIds = {1, 2, 3, 4, 9, 10, 11, 24};
    int[] scales = new int[typeIds.length];
    int[] sizes = {1, 2, 4, 8, 4, 8, 1, 0};

    byte[][] payloads = new byte[typeIds.length][];
    byte[][] valids = new byte[typeIds.length][];
    byte[] offsetsBytes = null;
    HostColumn[] cols = new HostColumn[typeIds.length];
    for (int c = 0; c < typeIds.length; c++) {
      valids[c] = new byte[n];
      for (int r = 0; r < n; r++) {
        valids[c][r] = (byte) (rng.nextInt(10) == 0 ? 0 : 1);
      }
      if (typeIds[c] == 24) {
        StringBuilder chars = new StringBuilder();
        byte[] offs = new byte[(n + 1) * 4];
        int total = 0;
        for (int r = 0; r <= n; r++) {
          if (r > 0 && valids[c][r - 1] != 0) {
            String s = "s" + (r % 37);
            chars.append(s);
            total += s.length();
          }
          offs[4 * r] = (byte) total;
          offs[4 * r + 1] = (byte) (total >> 8);
          offs[4 * r + 2] = (byte) (total >> 16);
          offs[4 * r + 3] = (byte) (total >> 24);
        }
        payloads[c] = chars.toString().getBytes();
        offsetsBytes = offs;
        cols[c] = HostColumn.fromStrings(
            n, put(offs), put(payloads[c]), put(valids[c]));
      } else {
        payloads[c] = new byte[n * sizes[c]];
        rng.nextBytes(payloads[c]);
        if (typeIds[c] == 11) {                 // BOOL8: 0/1 payloads
          for (int i = 0; i < n; i++) {
            payloads[c][i] = (byte) (payloads[c][i] & 1);
          }
        }
        cols[c] = HostColumn.fromFixedWidth(
            typeIds[c], 0, n, put(payloads[c]), put(valids[c]));
      }
    }

    try (HostTable table = HostTable.fromColumns(cols);
         RowConversion.RowBatches rows = RowConversion.convertToRows(table);
         HostTable back =
             RowConversion.convertFromRows(rows, 0, typeIds, scales)) {
      check(back.getRowCount() == n, "row count");
      long[] handles = back.releaseColumns();
      for (int c = 0; c < typeIds.length; c++) {
        HostColumn col = HostColumn.wrap(handles[c], typeIds[c], scales[c]);
        check(col.getRowCount() == n, "col " + c + " rows");
        check(col.getDataSize() == payloads[c].length, "col " + c + " size");
        checkBytes(col.getDataAddress(), payloads[c], "col " + c + " data");
        if (typeIds[c] == 24) {
          checkBytes(col.getOffsetsAddress(), offsetsBytes,
              "col " + c + " offsets");
        }
        long va = col.getValidityAddress();
        check(va != 0, "col " + c + " validity present");
        checkBytes(va, valids[c], "col " + c + " validity");
        col.close();
      }
    }
    System.out.println("RowConversionSmoke OK: 8-column x " + n
        + "-row JCUDF round trip byte-exact through libsrjt.so");
  }
}
