#!/usr/bin/env python
"""Adaptive query execution benchmark → AQE_BENCH.json.

Two workloads, each timed static vs adaptive with results asserted
BIT-IDENTICAL before any timing is recorded (AQE must never change
bytes, only speed):

* **skewed_join** — the repartition (shuffled) join with 90% of fact
  rows on one hot key, over the 8-device CPU mesh.  Static routes by
  plain hash (``salt=1``: the hot destination's bucket capacity — and
  the padded probe work of every chip — scales with the hot-key mass);
  adaptive (``SRJT_AQE=1``) detects the measured bucket-need skew and
  re-routes through salted sub-joins (``plan.aqe.skew_split``).  The
  wasted-work proxy recorded next to wall time is the mesh-wide padded
  bucket slot count (``shuffle.padded_slots.*``).

* **mispredicted_order** — a star join whose plan tree bakes in the
  WRONG join order (the big non-selective dimension first — what a
  stale/adversarial cardinality prior would make the static optimizer
  emit).  Static executes the tree as written; adaptive re-orders the
  not-yet-executed joins on observed dimension cardinalities
  (``plan.aqe.replan``), probing the selective dimension first
  (its inner join keeps ~1% of fact rows).  Wasted-work proxy: rows
  flowing through the join probes (``join.match_rows`` totals).

Floors (skipped with ``--quick``): skewed_join ≥ 2.0×,
mispredicted_order ≥ 1.3×.

Usage: python tools/aqe_bench.py [--quick] [out.json]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

N_DEV = 8
RESULTS = {"benches": {}}


def _wall(fn, warm=1, iters=5):
    for _ in range(warm):
        fn()
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _aqe(on: bool):
    os.environ["SRJT_AQE"] = "1" if on else "0"


def bench_skewed_join():
    import spark_rapids_jni_tpu as sr
    from spark_rapids_jni_tpu.parallel import make_mesh
    from spark_rapids_jni_tpu.parallel import repartition_join as rj
    from spark_rapids_jni_tpu.utils import metrics

    mesh = make_mesh(N_DEV, "data")
    rng = np.random.default_rng(7)
    n, nb, groups = N_DEV * 262144, 4096, 32
    fk = rng.integers(0, nb, n).astype(np.int64)
    fk[rng.random(n) < 0.9] = 11                     # hot key: 90% of rows
    fv = rng.integers(-100, 100, n).astype(np.int64)
    bk = np.arange(nb, dtype=np.int64)
    bg = rng.integers(0, groups, nb).astype(np.int32)
    fd = (jnp.asarray(fk), jnp.asarray(fv))
    bd = (jnp.asarray(bk), jnp.asarray(bg))
    fvld = jnp.ones((n, 2), bool)
    bvld = jnp.ones((nb, 2), bool)

    def run(**kw):
        s, c, d = rj.repartition_join_agg_auto(
            mesh, (sr.int64, sr.int64), (sr.int64, sr.int32),
            0, 0, 1, 1, groups, fd, fvld, bd, bvld, **kw)
        jax.block_until_ready((s, c))
        return np.asarray(s), np.asarray(c), int(np.asarray(d))

    def padded(**kw):
        metrics.set_enabled(True)
        metrics.reset()
        run(**kw)
        slots = (metrics.counter_value("shuffle.padded_slots.fact")
                 + metrics.counter_value("shuffle.padded_slots.build"))
        fired = metrics.counter_value("plan.aqe.skew_split.fired")
        metrics.set_enabled(False)
        return int(slots), int(fired)

    _aqe(False)
    s1, c1, d1 = run(salt=1)
    _aqe(True)
    s2, c2, d2 = run()
    assert d1 == 0 and d2 == 0, "bucket overflow on the auto path"
    assert (s1 == s2).all() and (c1 == c2).all(), \
        "salted sub-join result differs from static"
    slots_static, _ = padded(salt=1)
    _aqe(True)
    slots_aqe, fired = padded()
    assert fired >= 1, "skew split did not fire on the skewed workload"
    _aqe(False)
    t_static = _wall(lambda: run(salt=1))
    _aqe(True)
    t_aqe = _wall(run)
    _aqe(False)
    return {"rows": n, "hot_fraction": 0.9,
            "static_wall_s": round(t_static, 4),
            "adaptive_wall_s": round(t_aqe, 4),
            "speedup": round(t_static / t_aqe, 2),
            "padded_slots_static": slots_static,
            "padded_slots_adaptive": slots_aqe,
            "bit_identical": True}


def bench_mispredicted_order():
    from spark_rapids_jni_tpu.column import Column, Table, force_column
    from spark_rapids_jni_tpu.plan import adaptive, ir, lower

    rng = np.random.default_rng(13)
    n, n_big, n_small_space, n_small = 1_500_000, 300_000, 6400, 64
    fact = Table([
        Column.from_numpy(
            rng.integers(0, n_big, n).astype(np.int64)),       # f_big_sk
        Column.from_numpy(
            rng.integers(0, n_small_space, n).astype(np.int64)),  # f_small_sk
        Column.from_numpy(rng.integers(1, 50, n).astype(np.int64)),  # f_qty
    ])
    dim_big = Table([
        Column.from_numpy(np.arange(n_big, dtype=np.int64)),   # big_sk
        Column.from_numpy((np.arange(n_big) % 23).astype(np.int32)),  # b_tag
    ])
    dim_small = Table([                       # selective: ~1% of fact rows
        Column.from_numpy(np.arange(n_small, dtype=np.int64)),  # small_sk
        Column.from_numpy((np.arange(n_small) % 5).astype(np.int32)),  # s_tag
    ])
    tables = {"fact": fact, "dim_big": dim_big, "dim_small": dim_small}
    schemas = {"fact": ["f_big_sk", "f_small_sk", "f_qty"],
               "dim_big": ["big_sk", "b_tag"],
               "dim_small": ["small_sk", "s_tag"]}

    # ADVERSARIAL plan: the big non-selective dim joins first — the shape
    # a stale prior claiming dim_big is tiny would make the optimizer emit
    tree = ir.FusedJoinAggregate(
        ir.Join(ir.Scan("fact"), ir.Scan("dim_big"),
                ("f_big_sk",), ("big_sk",)),
        ir.Scan("dim_small"), ("f_small_sk",), ("small_sk",),
        ("b_tag",), (("f_qty", "sum", "total"), ("f_qty", "count", "cnt")))

    def rows(t):
        cols = [force_column(c).to_numpy() for c in t]
        return [c.tolist() for c in cols]

    def run_static():
        cat = lower.TableCatalog(tables, schemas)
        t, _ = lower._execute(tree, cat, record_stats=False)
        if t.num_rows:
            np.asarray(force_column(t[0]).data[:1])
        return t

    def run_adaptive():
        cat = lower.TableCatalog(tables, schemas)
        t = adaptive.execute_adaptive(tree, cat, record_stats=False)
        if t.num_rows:
            np.asarray(force_column(t[0]).data[:1])
        return t

    from spark_rapids_jni_tpu.utils import metrics

    def pairs(fn):
        # rows flowing through the join probes — the FJA path never
        # materializes expanded pairs, so match_rows is the wasted-work
        # proxy: the mispredicted order pushes ALL fact rows through the
        # big join; the reordered plan only the selective 2%
        metrics.set_enabled(True)
        metrics.reset()
        fn()
        h = metrics.snapshot()["histograms"].get("join.match_rows")
        replans = metrics.counter_value("plan.aqe.replan.fired")
        metrics.set_enabled(False)
        return int(h["total"]) if h else 0, int(replans)

    _aqe(False)
    t_s = run_static()
    _aqe(True)
    t_a = run_adaptive()
    assert rows(t_s) == rows(t_a), "adaptive reorder changed result bytes"
    pairs_static, _ = pairs(run_static)
    _aqe(True)
    pairs_aqe, replans = pairs(run_adaptive)
    assert replans >= 1, "replan did not fire on the adversarial order"
    _aqe(False)
    wall_static = _wall(run_static, warm=1, iters=5)
    _aqe(True)
    wall_aqe = _wall(run_adaptive, warm=1, iters=5)
    _aqe(False)
    return {"fact_rows": n, "dim_big_rows": n_big, "dim_small_rows": n_small,
            "static_wall_s": round(wall_static, 4),
            "adaptive_wall_s": round(wall_aqe, 4),
            "speedup": round(wall_static / wall_aqe, 2),
            "join_match_rows_static": pairs_static,
            "join_match_rows_adaptive": pairs_aqe,
            "bit_identical": True}


def main():
    quick = "--quick" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    out_path = args[0] if args else "AQE_BENCH.json"

    RESULTS["benches"]["skewed_join"] = bench_skewed_join()
    print("skewed_join:", json.dumps(RESULTS["benches"]["skewed_join"]))
    RESULTS["benches"]["mispredicted_order"] = bench_mispredicted_order()
    print("mispredicted_order:",
          json.dumps(RESULTS["benches"]["mispredicted_order"]))

    if not quick:
        sk = RESULTS["benches"]["skewed_join"]["speedup"]
        mo = RESULTS["benches"]["mispredicted_order"]["speedup"]
        assert sk >= 2.0, f"skewed_join speedup {sk} < 2.0x floor"
        assert mo >= 1.3, f"mispredicted_order speedup {mo} < 1.3x floor"
    with open(out_path, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
