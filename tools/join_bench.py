#!/usr/bin/env python
"""Join engine v2 microbenchmark → JOIN_BENCH.json.

Isolates the q19-shape regression from the query harness: times
``ops.join.join_indices`` (the engine, not the gather tail) across the
planner's decision matrix —

  * dense vs sparse build keys   (direct lookup vs sort-probe fallback)
  * 1:1 vs 1:N build sides       (unique no-expansion path vs CSR chains)
  * cached vs cold build index   (memo hit skips the build phase)

Two bases are reported:

* **eager full-join** — ``join_indices`` end to end, including the
  planner's host syncs and the expansion tail both engines share.  The
  tail (output materialization) dominates at 10M rows and is engine-
  independent, so it compresses the ratio.
* **in-jit engine steady** (the acceptance basis) — build + probe under
  one ``jax.jit``, the way production queries actually run the engine
  (``models/compiled.py`` replays the whole query as one dispatch with
  planner scalars baked in from the capture tape).  The kernels call the
  real ``join_plan._key_sorted_order`` / ``probe_counts``; their counts
  are asserted identical to the eager engine's before timing.

Cold-build runs rotate through pre-copied key buffers so each iteration
misses the identity-keyed index memo; cached runs reuse one buffer so
every iteration hits it.

Acceptance (ISSUE 1): dense ≥ 10× sort-probe on the 10M-probe / 1M-build
dense-key inner join (warm, in-jit engine basis); cached build ≥ 5× cold
on a build-dominant shape.

Usage: python tools/join_bench.py [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import Column
from spark_rapids_jni_tpu.ops import join_plan
from spark_rapids_jni_tpu.ops.join import join_indices

ITERS = 5
RESULTS = {"backend": None, "cases": {}, "acceptance": {}}


def _col(data, copies=1):
    """A Column per copy — distinct device buffers, equal contents, so each
    use is a build-index memo MISS (cold) when copies rotate."""
    return [Column.from_numpy(data) for _ in range(copies)]


def _block(res):
    if isinstance(res, tuple):
        for r in res:
            r.block_until_ready()
    else:
        res.block_until_ready()


def _time_join(left_cols, right_cols, engine, iters=ITERS):
    """Median seconds/join.  Buffers rotate per iteration (cold build when
    right_cols holds distinct copies; cached when it holds one)."""
    with join_plan.force_engine(engine):
        _block(join_indices(left_cols[0], right_cols[0], "inner"))  # warm
        times = []
        for i in range(iters):
            lc = left_cols[i % len(left_cols)]
            rc = right_cols[i % len(right_cols)]
            t0 = time.perf_counter()
            _block(join_indices(lc, rc, "inner"))
            times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_case(name, note, lk, rk, engines=("sorted", "dense")):
    entry = {"note": note, "n_probe": int(lk.shape[0]),
             "n_build": int(rk.shape[0])}
    # fresh build buffer each iteration → the build phase is IN the timing
    lcols = _col(lk)
    rcols = _col(rk, copies=ITERS + 1)
    for eng in engines:
        entry[f"{eng}_cold_s"] = _time_join(lcols, rcols, eng)
    if len(engines) == 2:
        entry["dense_speedup_vs_sorted"] = round(
            entry["sorted_cold_s"] / entry["dense_cold_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(
        f"{k}={v}" for k, v in entry.items() if k != "note"), flush=True)
    return entry


def bench_engine_steady(name, lk, rk, iters=3):
    """Build + probe under one jit — the compiled-query execution basis.

    The planner's scalars (kmin/span/n_valid) are captured eagerly first,
    exactly as models/compiled.py bakes them from the tape; the jitted
    replay then re-derives the index from the raw key buffers and probes
    it through the real ``join_plan.probe_counts``.
    """
    pk, bk = jnp.asarray(lk), jnp.asarray(rk)
    with join_plan.force_engine("dense"):
        ix = join_plan._build_index(bk, None, True, False)
    kmin, span, nv = ix.kmin, ix.span, ix.n_valid

    @jax.jit
    def dense_engine(p, b):
        # replay of _build_index's dense branch with the captured plan
        slot = jnp.clip(b.astype(jnp.int64) - kmin, 0, span - 1)
        slot = slot.astype(jnp.int32)
        lut_cnt = jnp.zeros(span, jnp.int32).at[slot].add(1)
        lut_lo = (jnp.cumsum(lut_cnt) - lut_cnt).astype(jnp.int32)
        jix = join_plan.BuildIndex("dense", nv, None, None, kmin, span,
                                   lut_lo, lut_cnt, True)
        return join_plan.probe_counts(jix, p, None)

    @jax.jit
    def sorted_engine(p, b):
        order, skeys = join_plan._key_sorted_order(b, None, nv)
        jix = join_plan.BuildIndex("sorted", nv, order, skeys, 0, 0,
                                   None, None, False)
        return join_plan.probe_counts(jix, p, None)

    _, dc = dense_engine(pk, bk)
    _, sc = sorted_engine(pk, bk)
    assert bool(jnp.all(dc == sc)), "engine count mismatch"

    entry = {"n_probe": int(pk.shape[0]), "n_build": int(bk.shape[0]),
             "basis": "in-jit build+probe, steady over %d iters" % iters}
    for tag, fn in (("sorted", sorted_engine), ("dense", dense_engine)):
        _block(fn(pk, bk))
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(pk, bk)
        _block(r)
        entry[f"{tag}_steady_s"] = (time.perf_counter() - t0) / iters
    entry["dense_speedup_vs_sorted"] = round(
        entry["sorted_steady_s"] / entry["dense_steady_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(f"{k}={v}" for k, v in entry.items()),
          flush=True)
    return entry


def bench_cached(name, lk, rk):
    """Build-dominant shape: small probe, 1M-row build.  Cold rotates
    buffers (memo miss, index rebuilt per join); cached reuses one buffer
    (memo hit, build phase skipped)."""
    lcols = _col(lk)
    entry = {"n_probe": int(lk.shape[0]), "n_build": int(rk.shape[0])}
    entry["cold_s"] = _time_join(lcols, _col(rk, copies=ITERS + 1), "dense")
    entry["cached_s"] = _time_join(lcols, _col(rk), "dense")
    entry["cached_speedup_vs_cold"] = round(
        entry["cold_s"] / entry["cached_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(f"{k}={v}" for k, v in entry.items()),
          flush=True)
    return entry


def main():
    RESULTS["backend"] = jax.default_backend()
    rng = np.random.default_rng(0)
    n_probe, n_build = 10_000_000, 1_000_000

    # the acceptance shape: TPC-DS star join — dense unique surrogate PK
    build_1to1 = rng.permutation(np.arange(n_build, dtype=np.int64))
    probe = build_1to1[rng.integers(0, n_build, n_probe)]
    print("dense 1:1 (10M probe / 1M build):", flush=True)
    bench_case(
        "dense_1to1_10M", "unique dense PK — the q19/q65 star shape",
        probe, build_1to1)
    print("engine steady (in-jit, 10M probe / 1M build):", flush=True)
    acc = bench_engine_steady("engine_steady_1to1_10M", probe, build_1to1)

    # 1:N — CSR duplicate chains, ~4 build rows per key, smaller probe so
    # the ~8M-pair expansion stays CPU-benchable
    n_keys = 250_000
    build_1toN = rng.integers(0, n_keys, n_build).astype(np.int64)
    probe_1toN = rng.integers(0, n_keys, 2_000_000).astype(np.int64)
    print("dense 1:N (2M probe / 1M build, ~4 dups/key):", flush=True)
    bench_case("dense_1toN_2M", "CSR duplicate chains, pair expansion",
               probe_1toN, build_1toN)

    # sparse keys: planner must fall back — both engines take sort-probe
    sparse_build = rng.integers(0, 2**60, n_build, dtype=np.int64)
    sparse_probe = sparse_build[rng.integers(0, n_build, 2_000_000)]
    print("sparse fallback (2M probe / 1M build):", flush=True)
    e = bench_case("sparse_fallback_2M",
                   "span ≫ c·n — heuristic rejects dense; parity check",
                   sparse_probe, sparse_build, engines=("sorted",))
    with join_plan.force_engine(None):
        ix = join_plan.build_index(jnp.asarray(sparse_build), None, True)
        e["planner_kind"] = ix.kind

    # cached vs cold: build-dominant (65K probe vs 1M build)
    small_probe = build_1to1[rng.integers(0, n_build, 65_536)]
    print("cached vs cold build index (64K probe / 1M build):", flush=True)
    cache = bench_cached("cached_build_64K_probe", small_probe, build_1to1)

    RESULTS["acceptance"] = {
        "dense_speedup_vs_sorted_10M": acc["dense_speedup_vs_sorted"],
        "dense_ge_10x": acc["dense_speedup_vs_sorted"] >= 10.0,
        "cached_speedup_vs_cold": cache["cached_speedup_vs_cold"],
        "cached_ge_5x": cache["cached_speedup_vs_cold"] >= 5.0,
    }
    out = sys.argv[1] if len(sys.argv) > 1 else "JOIN_BENCH.json"
    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print(json.dumps(RESULTS["acceptance"]), flush=True)


if __name__ == "__main__":
    main()
