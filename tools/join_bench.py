#!/usr/bin/env python
"""Join engine v2 microbenchmark → JOIN_BENCH.json.

Isolates the q19-shape regression from the query harness: times
``ops.join.join_indices`` (the engine, not the gather tail) across the
planner's decision matrix —

  * dense vs sparse build keys   (direct lookup vs sort-probe fallback)
  * 1:1 vs 1:N build sides       (unique no-expansion path vs CSR chains)
  * cached vs cold build index   (memo hit skips the build phase)

Two bases are reported:

* **eager full-join** — ``join_indices`` end to end, including the
  planner's host syncs and the expansion tail both engines share.  The
  tail (output materialization) dominates at 10M rows and is engine-
  independent, so it compresses the ratio.
* **in-jit engine steady** (the acceptance basis) — build + probe under
  one ``jax.jit``, the way production queries actually run the engine
  (``models/compiled.py`` replays the whole query as one dispatch with
  planner scalars baked in from the capture tape).  The kernels call the
  real ``join_plan._key_sorted_order`` / ``probe_counts``; their counts
  are asserted identical to the eager engine's before timing.

Cold-build runs rotate through pre-copied key buffers so each iteration
misses the identity-keyed index memo; cached runs reuse one buffer so
every iteration hits it.

Multi-key cases (ISSUE 4) ride the same harness: 2-key / 3-key int
tuples and string+int pack onto one int64 composite
(``join_plan.plan_keys``) and run both engines; the wide-window case
overflows 63 bits and takes the fingerprint-and-verify path.  A pandas
``merge`` on the same host data anchors the largest composite case, and a
repeated probe records that the pack-plan and build-index cache-hit
counters fire.

Acceptance (ISSUE 1): dense ≥ 10× sort-probe on the 10M-probe / 1M-build
dense-key inner join (warm, in-jit engine basis); cached build ≥ 5× cold
on a build-dominant shape.  (ISSUE 4): 2-key dense-composite ≥ 2× the
sort-probe baseline at the largest 2-key size; cache-hit counters fire on
repeated multi-key probes.

Usage: python tools/join_bench.py [out.json]
"""

import json
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import Column
from spark_rapids_jni_tpu.ops import join_plan
from spark_rapids_jni_tpu.ops.join import join_indices
from spark_rapids_jni_tpu.utils import metrics

ITERS = 5
RESULTS = {"backend": None, "cases": {}, "acceptance": {}}


def _col(data, copies=1):
    """A Column per copy — distinct device buffers, equal contents, so each
    use is a build-index memo MISS (cold) when copies rotate."""
    return [Column.from_numpy(data) for _ in range(copies)]


def _block(res):
    if isinstance(res, tuple):
        for r in res:
            r.block_until_ready()
    else:
        res.block_until_ready()


def _time_join(left_cols, right_cols, engine, iters=ITERS):
    """Median seconds/join.  Buffers rotate per iteration (cold build when
    right_cols holds distinct copies; cached when it holds one)."""
    with join_plan.force_engine(engine):
        _block(join_indices(left_cols[0], right_cols[0], "inner"))  # warm
        times = []
        for i in range(iters):
            lc = left_cols[i % len(left_cols)]
            rc = right_cols[i % len(right_cols)]
            t0 = time.perf_counter()
            _block(join_indices(lc, rc, "inner"))
            times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_case(name, note, lk, rk, engines=("sorted", "dense")):
    entry = {"note": note, "n_probe": int(lk.shape[0]),
             "n_build": int(rk.shape[0])}
    # fresh build buffer each iteration → the build phase is IN the timing
    lcols = _col(lk)
    rcols = _col(rk, copies=ITERS + 1)
    for eng in engines:
        entry[f"{eng}_cold_s"] = _time_join(lcols, rcols, eng)
    if len(engines) == 2:
        entry["dense_speedup_vs_sorted"] = round(
            entry["sorted_cold_s"] / entry["dense_cold_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(
        f"{k}={v}" for k, v in entry.items() if k != "note"), flush=True)
    return entry


def bench_engine_steady(name, lk, rk, iters=3):
    """Build + probe under one jit — the compiled-query execution basis.

    The planner's scalars (kmin/span/n_valid) are captured eagerly first,
    exactly as models/compiled.py bakes them from the tape; the jitted
    replay then re-derives the index from the raw key buffers and probes
    it through the real ``join_plan.probe_counts``.
    """
    pk, bk = jnp.asarray(lk), jnp.asarray(rk)
    with join_plan.force_engine("dense"):
        ix = join_plan._build_index(bk, None, True, False)
    kmin, span, nv = ix.kmin, ix.span, ix.n_valid

    @jax.jit
    def dense_engine(p, b):
        # replay of _build_index's dense branch with the captured plan
        slot = jnp.clip(b.astype(jnp.int64) - kmin, 0, span - 1)
        slot = slot.astype(jnp.int32)
        lut_cnt = jnp.zeros(span, jnp.int32).at[slot].add(1)
        lut_lo = (jnp.cumsum(lut_cnt) - lut_cnt).astype(jnp.int32)
        jix = join_plan.BuildIndex("dense", nv, None, None, kmin, span,
                                   lut_lo, lut_cnt, True)
        return join_plan.probe_counts(jix, p, None)

    @jax.jit
    def sorted_engine(p, b):
        order, skeys = join_plan._key_sorted_order(b, None, nv)
        jix = join_plan.BuildIndex("sorted", nv, order, skeys, 0, 0,
                                   None, None, False)
        return join_plan.probe_counts(jix, p, None)

    _, dc = dense_engine(pk, bk)
    _, sc = sorted_engine(pk, bk)
    assert bool(jnp.all(dc == sc)), "engine count mismatch"

    entry = {"n_probe": int(pk.shape[0]), "n_build": int(bk.shape[0]),
             "basis": "in-jit build+probe, steady over %d iters" % iters}
    for tag, fn in (("sorted", sorted_engine), ("dense", dense_engine)):
        _block(fn(pk, bk))
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(pk, bk)
        _block(r)
        entry[f"{tag}_steady_s"] = (time.perf_counter() - t0) / iters
    entry["dense_speedup_vs_sorted"] = round(
        entry["sorted_steady_s"] / entry["dense_steady_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(f"{k}={v}" for k, v in entry.items()),
          flush=True)
    return entry


def bench_cached(name, lk, rk):
    """Build-dominant shape: small probe, 1M-row build.  Cold rotates
    buffers (memo miss, index rebuilt per join); cached reuses one buffer
    (memo hit, build phase skipped)."""
    lcols = _col(lk)
    entry = {"n_probe": int(lk.shape[0]), "n_build": int(rk.shape[0])}
    entry["cold_s"] = _time_join(lcols, _col(rk, copies=ITERS + 1), "dense")
    entry["cached_s"] = _time_join(lcols, _col(rk), "dense")
    entry["cached_speedup_vs_cold"] = round(
        entry["cold_s"] / entry["cached_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(f"{k}={v}" for k, v in entry.items()),
          flush=True)
    return entry


def _mcol(datas, copies=1):
    """One multi-key column list per copy — distinct buffers per copy so
    rotating copies misses both the pack-plan memo and the index memo."""
    return [[Column.from_numpy(d) for d in datas] for _ in range(copies)]


def bench_multikey(name, note, lks, rks, engines=("sorted", "dense")):
    """Time ``join_indices`` on a key-column LIST (composite/fingerprint
    path) — same cold-build rotation discipline as :func:`bench_case`."""
    plan = join_plan.plan_keys([Column.from_numpy(d) for d in lks],
                               [Column.from_numpy(d) for d in rks])
    entry = {"note": note, "n_probe": int(lks[0].shape[0]),
             "n_build": int(rks[0].shape[0]), "n_keys": len(lks),
             "pack_mode": plan.mode}
    lcols = _mcol(lks)
    rcols = _mcol(rks, copies=ITERS + 1)
    for eng in engines:
        entry[f"{eng}_cold_s"] = _time_join(lcols, rcols, eng)
    if len(engines) == 2:
        entry["dense_speedup_vs_sorted"] = round(
            entry["sorted_cold_s"] / entry["dense_cold_s"], 2)
    RESULTS["cases"][name] = entry
    print(f"  {name}: " + ", ".join(
        f"{k}={v}" for k, v in entry.items() if k != "note"), flush=True)
    return entry


def _pair_keys(rng, n_probe, n_build, spans, match_frac=0.85):
    """Unique build tuples over mixed-radix ``spans``; probe tuples hit a
    build tuple with ``match_frac`` probability (misses stay inside the
    windows, so they exercise the probe, not the validity fold)."""
    idx = np.arange(n_build, dtype=np.int64)
    rks = []
    for s in reversed(spans):
        rks.append(idx % s)
        idx = idx // s
    rks = rks[::-1]
    sel = rng.integers(0, n_build, n_probe)
    lks = [rk[sel].copy() for rk in rks]
    miss = rng.random(n_probe) >= match_frac
    lks[-1] = np.where(miss, (lks[-1] + 1) % spans[-1], lks[-1])
    return lks, rks


def bench_multikey_cases(rng):
    # 2-key int: the acceptance sweep — largest size is the basis
    acc = None
    for n_probe, n_build in ((1_000_000, 100_000), (4_000_000, 400_000)):
        print(f"2-key composite ({n_probe // 1_000_000}M probe / "
              f"{n_build // 1_000} K build):", flush=True)
        lks, rks = _pair_keys(rng, n_probe, n_build,
                              ((n_build + 255) // 256, 256))
        acc = bench_multikey(
            f"composite_2key_{n_probe // 1_000_000}M",
            "unique (a, b) build tuples packed onto the dense LUT",
            lks, rks)
    # pandas anchor on the largest 2-key shape (full merge — it also
    # materializes the output, so treat as a reference point, not a race)
    ldf = pd.DataFrame({"a": lks[0], "b": lks[1]})
    rdf = pd.DataFrame({"a": rks[0], "b": rks[1], "r": np.arange(len(rks[0]))})
    t0 = time.perf_counter()
    ldf.merge(rdf, on=["a", "b"])
    acc["pandas_merge_s"] = time.perf_counter() - t0
    print(f"  pandas merge (largest 2-key): {acc['pandas_merge_s']:.3f}s",
          flush=True)

    # 3-key int
    print("3-key composite (2M probe / 300K build):", flush=True)
    lks, rks = _pair_keys(rng, 2_000_000, 300_000, (19, 64, 256))
    bench_multikey("composite_3key_2M",
                   "three-radix pack, still one int64 composite lane",
                   lks, rks)

    # string + int: dictionary codes from the shared encode pack like ints;
    # both engines pay the encode, the LUT-vs-searchsorted gap remains
    print("string+int composite (500K probe / 100K build):", flush=True)
    cats = np.asarray([f"cat_{i:04d}" for i in range(16)])
    idx = np.arange(100_000, dtype=np.int64)
    rs = cats[(idx // 8192).astype(np.int64)]     # unique (cat, i) tuples
    ri = idx % 8192                               # code·int window < cap
    sel = rng.integers(0, 100_000, 500_000)
    miss = rng.random(500_000) >= 0.85
    ls = rs[sel]
    li = np.where(miss, (ri[sel] + 1) % 8192, ri[sel])

    def _sv(vals):
        return Column.strings_from_list([str(v) for v in vals])

    plan = join_plan.plan_keys([_sv(ls), Column.from_numpy(li)],
                               [_sv(rs), Column.from_numpy(ri)])
    entry = {"note": "shared-dict codes + int payload", "n_probe": 500_000,
             "n_build": 100_000, "n_keys": 2, "pack_mode": plan.mode}
    lcols = [[_sv(ls), Column.from_numpy(li)]]
    rcols = [[_sv(rs), Column.from_numpy(ri)] for _ in range(ITERS + 1)]
    for eng in ("sorted", "dense"):
        entry[f"{eng}_cold_s"] = _time_join(lcols, rcols, eng)
    entry["dense_speedup_vs_sorted"] = round(
        entry["sorted_cold_s"] / entry["dense_cold_s"], 2)
    RESULTS["cases"]["composite_string_int_500K"] = entry
    print("  composite_string_int_500K: " + ", ".join(
        f"{k}={v}" for k, v in entry.items() if k != "note"), flush=True)

    # overflow → fingerprint-and-verify (no dense window exists)
    print("fingerprint overflow (1M probe / 200K build):", flush=True)
    wide = rng.integers(-2**61, 2**61, 200_000, dtype=np.int64)
    sel = rng.integers(0, 200_000, 1_000_000)
    bench_multikey("fingerprint_2key_1M",
                   "63-bit window overflow — murmur3 probe + verify",
                   [wide[sel], wide[::-1][sel]], [wide, wide[::-1]],
                   engines=("sorted",))

    # repeated probe: pack-plan + build-index cache hits must fire
    metrics.set_enabled(True)
    metrics.reset()
    lt = [Column.from_numpy(d) for d in lks]
    rt = [Column.from_numpy(d) for d in rks]
    _block(join_indices(lt, rt, "inner"))
    t0 = time.perf_counter()
    _block(join_indices(lt, rt, "inner"))
    counters = metrics.snapshot()["counters"]
    hits = {k: int(v) for k, v in counters.items()
            if k in ("join.pack.cache_hit", "join.build_index.cache_hit")}
    RESULTS["cases"]["multikey_repeat_probe"] = {
        "note": "second probe of the same key buffers",
        "repeat_s": time.perf_counter() - t0, "cache_hit_counters": hits}
    metrics.reset()
    metrics.set_enabled(None)
    print(f"  multikey_repeat_probe: cache_hit_counters={hits}", flush=True)
    return acc, hits


def main():
    RESULTS["backend"] = jax.default_backend()
    rng = np.random.default_rng(0)
    n_probe, n_build = 10_000_000, 1_000_000

    # the acceptance shape: TPC-DS star join — dense unique surrogate PK
    build_1to1 = rng.permutation(np.arange(n_build, dtype=np.int64))
    probe = build_1to1[rng.integers(0, n_build, n_probe)]
    print("dense 1:1 (10M probe / 1M build):", flush=True)
    bench_case(
        "dense_1to1_10M", "unique dense PK — the q19/q65 star shape",
        probe, build_1to1)
    print("engine steady (in-jit, 10M probe / 1M build):", flush=True)
    acc = bench_engine_steady("engine_steady_1to1_10M", probe, build_1to1)

    # 1:N — CSR duplicate chains, ~4 build rows per key, smaller probe so
    # the ~8M-pair expansion stays CPU-benchable
    n_keys = 250_000
    build_1toN = rng.integers(0, n_keys, n_build).astype(np.int64)
    probe_1toN = rng.integers(0, n_keys, 2_000_000).astype(np.int64)
    print("dense 1:N (2M probe / 1M build, ~4 dups/key):", flush=True)
    bench_case("dense_1toN_2M", "CSR duplicate chains, pair expansion",
               probe_1toN, build_1toN)

    # sparse keys: planner must fall back — both engines take sort-probe
    sparse_build = rng.integers(0, 2**60, n_build, dtype=np.int64)
    sparse_probe = sparse_build[rng.integers(0, n_build, 2_000_000)]
    print("sparse fallback (2M probe / 1M build):", flush=True)
    e = bench_case("sparse_fallback_2M",
                   "span ≫ c·n — heuristic rejects dense; parity check",
                   sparse_probe, sparse_build, engines=("sorted",))
    with join_plan.force_engine(None):
        ix = join_plan.build_index(jnp.asarray(sparse_build), None, True)
        e["planner_kind"] = ix.kind

    # cached vs cold: build-dominant (65K probe vs 1M build)
    small_probe = build_1to1[rng.integers(0, n_build, 65_536)]
    print("cached vs cold build index (64K probe / 1M build):", flush=True)
    cache = bench_cached("cached_build_64K_probe", small_probe, build_1to1)

    mk, hits = bench_multikey_cases(rng)

    RESULTS["acceptance"] = {
        "dense_speedup_vs_sorted_10M": acc["dense_speedup_vs_sorted"],
        "dense_ge_10x": acc["dense_speedup_vs_sorted"] >= 10.0,
        "cached_speedup_vs_cold": cache["cached_speedup_vs_cold"],
        "cached_ge_5x": cache["cached_speedup_vs_cold"] >= 5.0,
        "composite_2key_speedup_vs_sorted_largest":
            mk["dense_speedup_vs_sorted"],
        "composite_2key_ge_2x": mk["dense_speedup_vs_sorted"] >= 2.0,
        "multikey_cache_hits_fire": all(
            hits.get(k, 0) >= 1 for k in
            ("join.pack.cache_hit", "join.build_index.cache_hit")),
    }
    out = sys.argv[1] if len(sys.argv) > 1 else "JOIN_BENCH.json"
    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print(json.dumps(RESULTS["acceptance"]), flush=True)


if __name__ == "__main__":
    main()
