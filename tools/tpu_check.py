#!/usr/bin/env python
"""On-TPU validation sweep → PALLAS_TPU_CHECK.json.

Interpret-mode tests (the CPU pytest suite) cannot catch Mosaic compile or
miscompile issues, so once per round this script byte-compares, on the real
chip:

1. the ragged DMA engine (pack / unpack / segmented_copy) vs NumPy;
2. the full string JCUDF transcode (DMA path) vs the scalar NumPy oracle
   (``rowconv/reference.py``) across schema shapes;
3. the fixed-width u32-words transcode (round-3 permute/transpose
   formulations) vs the oracle across the schema matrix, including FLOAT64
   bit-pair columns and decimal128 — byte movement must be exact on chip;
4. the arithmetic f64 bits<->values path (``utils.f64bits``) round-trips
   normals/inf/nan exactly on the emulated-f64 backend.

Usage: python tools/tpu_check.py [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Table, Column, convert_to_rows, convert_from_rows
from spark_rapids_jni_tpu.rowconv import ragged, reference
from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout
from spark_rapids_jni_tpu.utils import f64bits

RESULTS = {"backend": None, "checks": [], "ok": True}


def record(name, ok, note=""):
    RESULTS["checks"].append({"name": name, "ok": bool(ok), "note": note})
    RESULTS["ok"] = RESULTS["ok"] and bool(ok)
    print(f"  {'PASS' if ok else 'FAIL'} {name} {note}", flush=True)


def check_ragged():
    from benchmarks.ragged_data import random_ragged
    rng = np.random.default_rng(0)
    for n, M, aligned in [(301, 64, False), (1000, 256, False),
                          (777, 33, False), (4097, 300, True)]:
        dense, offs, flat = random_ragged(rng, n, M, aligned)
        got = np.asarray(ragged.pack_rows(jnp.asarray(dense), offs))
        record(f"ragged.pack n={n} M={M}", np.array_equal(got, flat))
        got2 = np.asarray(ragged.unpack_rows(jnp.asarray(flat), offs, M))
        record(f"ragged.unpack n={n} M={M}", np.array_equal(got2, dense))

    # gappy segmented copy
    S, n = 500000, 400
    src = rng.integers(1, 256, S).astype(np.uint8)
    sizes = rng.integers(0, 256, n)
    gaps = rng.integers(0, 700, n)
    src_offs = np.cumsum(sizes + gaps) - (sizes + gaps)
    dst_offs = np.concatenate([[0], np.cumsum(sizes)])[:-1]
    total = int(sizes.sum())
    expect = np.zeros(total, np.uint8)
    for k in range(n):
        expect[dst_offs[k]:dst_offs[k] + sizes[k]] = \
            src[src_offs[k]:src_offs[k] + sizes[k]]
    got = np.asarray(ragged.segmented_copy(jnp.asarray(src), src_offs,
                                           dst_offs, sizes, total))
    record("ragged.segmented_copy gappy", np.array_equal(got, expect))


def check_strings_transcode():
    rng = np.random.default_rng(1)
    words = ["", "a", "spark", "tpu-native kernels", "xy",
             "longer string payload!", "ab\x00cd"]
    for n, nulls in [(1000, None), (503, 7)]:
        strs = [words[i] for i in rng.integers(0, len(words), n)]
        if nulls:
            strs = [None if i % nulls == 0 else s
                    for i, s in enumerate(strs)]
        t = Table([
            Column.from_numpy(rng.integers(-100, 100, n).astype(np.int32)),
            Column.strings_from_list(strs),
            Column.from_numpy(rng.integers(0, 2**40, n).astype(np.int64)),
            Column.strings_from_list(
                [words[i] for i in rng.integers(0, len(words), n)]),
        ])
        b = convert_to_rows(t)
        ob, _ = reference.to_rows_np(t)
        record(f"strings to_rows oracle n={n} nulls={nulls}",
               np.array_equal(b[0].host_bytes(), ob))
        back = convert_from_rows(b[0], t.schema)
        ok = (back[1].to_pylist() == t[1].to_pylist()
              and back[3].to_pylist() == t[3].to_pylist()
              and np.array_equal(back[0].to_numpy(), t[0].to_numpy()))
        record(f"strings roundtrip n={n} nulls={nulls}", ok)


SCHEMAS = {
    "int32_only": [sr.int32] * 3,
    "mixed_words": [sr.int32, sr.int16, sr.int8],
    "wide_mixed": [sr.int64, sr.int32, sr.int16, sr.int8, sr.float32,
                   sr.bool8, sr.float64] * 2,
    "bytes_only": [sr.int8] * 5,
    "timestamps_decimals": [sr.timestamp_ms, sr.decimal32(-2),
                            sr.decimal64(-4), sr.bool8, sr.types.decimal128(-4)],
    # wide enough to route through the 2-D-transpose interleave (W > 40)
    "wide_176col": [sr.int64, sr.int32, sr.float64, sr.int16] * 44,
}


def _random_table(rng, schema, n):
    cols = []
    for i, dt in enumerate(schema):
        v = (rng.random(n) < 0.8) if i % 2 == 0 else None
        if dt.id == sr.TypeId.DECIMAL128:
            lanes = rng.integers(-2**62, 2**62, (n, 2), dtype=np.int64)
            cols.append(Column(dt, jnp.asarray(lanes),
                               validity=None if v is None else jnp.asarray(v)))
        elif dt == sr.bool8:
            cols.append(Column.from_numpy(
                rng.integers(0, 2, n).astype(np.uint8), dt, v))
        elif dt.storage.kind == "f":
            cols.append(Column.from_numpy(
                rng.standard_normal(n).astype(dt.storage), dt, v))
        else:
            info = np.iinfo(dt.storage)
            cols.append(Column.from_numpy(
                rng.integers(info.min // 2, info.max // 2, n,
                             dtype=dt.storage), dt, v))
    return Table(cols)


def check_fixed_words():
    rng = np.random.default_rng(2)
    for name, schema in SCHEMAS.items():
        n = 4097
        t = _random_table(rng, schema, n)
        b = convert_to_rows(t)
        want, _ = reference.to_rows_np(t)
        record(f"fixed words to_rows {name}",
               np.array_equal(b[0].host_bytes(), want))
        back = convert_from_rows(b[0], t.schema)
        ok = True
        for ca, cb in zip(back.columns, t.columns):
            va = np.asarray(ca.validity_or_true())
            ok = ok and np.array_equal(va, np.asarray(cb.validity_or_true()))
            da, db = np.asarray(ca.data), np.asarray(cb.data)
            ok = ok and np.array_equal(da[va], db[va])
        record(f"fixed words roundtrip {name}", ok)


def check_f64bits():
    rng = np.random.default_rng(3)
    vals = np.concatenate([
        rng.standard_normal(4000),
        rng.standard_normal(4000) * 10.0 ** rng.integers(-300, 300, 4000),
        np.array([0.0, -0.0, 1.0, -1.0, np.inf, -np.inf, np.nan,
                  2.0 ** -1022, 2.0 ** 1023, 1.7976931348623157e308]),
    ]).astype(np.float64)
    bits = vals.view(np.uint32).reshape(-1, 2)
    dec = np.asarray(jax.jit(f64bits.from_bits)(jnp.asarray(bits)))
    record("f64bits.from_bits exact",
           np.array_equal(dec.view(np.uint64), vals.view(np.uint64)))
    enc = np.asarray(jax.jit(f64bits.to_bits)(jnp.asarray(vals)))
    # NaN canonicalizes on the arithmetic path — compare through a decode
    nan = np.isnan(vals)
    ok = (np.array_equal(enc[~nan], bits[~nan])
          and np.isnan(enc[nan].view(np.float64)).all())
    record("f64bits.to_bits exact (NaN canonical)", ok)


def main():
    t0 = time.time()
    RESULTS["backend"] = jax.default_backend()
    if RESULTS["backend"] != "tpu":
        RESULTS["ok"] = False
        RESULTS["error"] = "not running on a TPU backend"
    else:
        print("ragged engine:", flush=True)
        check_ragged()
        print("strings transcode:", flush=True)
        check_strings_transcode()
        print("fixed-width u32-words transcode:", flush=True)
        check_fixed_words()
        print("f64 bits<->values:", flush=True)
        check_f64bits()
    RESULTS["seconds"] = round(time.time() - t0, 1)
    out = sys.argv[1] if len(sys.argv) > 1 else "PALLAS_TPU_CHECK.json"
    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print(json.dumps({"ok": RESULTS["ok"], "checks": len(RESULTS["checks"]),
                      "seconds": RESULTS["seconds"]}), flush=True)


if __name__ == "__main__":
    main()
