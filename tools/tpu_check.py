#!/usr/bin/env python
"""On-TPU validation sweep → PALLAS_TPU_CHECK.json.

Interpret-mode tests (the CPU pytest suite) cannot catch Mosaic compile or
miscompile issues, so once per round this script byte-compares, on the real
chip:

1. the ragged DMA engine (pack / unpack / segmented_copy) vs NumPy;
2. the full string JCUDF transcode (DMA path) vs the scalar NumPy oracle
   (``rowconv/reference.py``) across schema shapes;
3. the fixed-width u32-words transcode (round-3 permute/transpose
   formulations) vs the oracle across the schema matrix, including FLOAT64
   bit-pair columns and decimal128 — byte movement must be exact on chip;
4. the arithmetic f64 bits<->values path (``utils.f64bits``) round-trips
   normals/inf/nan exactly on the emulated-f64 backend.

Usage: python tools/tpu_check.py [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu import Table, Column, convert_to_rows, convert_from_rows
from spark_rapids_jni_tpu.rowconv import ragged, reference
from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout
from spark_rapids_jni_tpu.utils import f64bits

RESULTS = {"backend": None, "checks": [], "ok": True}


def record(name, ok, note=""):
    RESULTS["checks"].append({"name": name, "ok": bool(ok), "note": note})
    RESULTS["ok"] = RESULTS["ok"] and bool(ok)
    print(f"  {'PASS' if ok else 'FAIL'} {name} {note}", flush=True)


def check_ragged():
    from benchmarks.ragged_data import random_ragged
    rng = np.random.default_rng(0)
    for n, M, aligned in [(301, 64, False), (1000, 256, False),
                          (777, 33, False), (4097, 300, True)]:
        dense, offs, flat = random_ragged(rng, n, M, aligned)
        got = np.asarray(ragged.pack_rows(jnp.asarray(dense), offs))
        record(f"ragged.pack n={n} M={M}", np.array_equal(got, flat))
        got2 = np.asarray(ragged.unpack_rows(jnp.asarray(flat), offs, M))
        record(f"ragged.unpack n={n} M={M}", np.array_equal(got2, dense))

    # gappy segmented copy
    S, n = 500000, 400
    src = rng.integers(1, 256, S).astype(np.uint8)
    sizes = rng.integers(0, 256, n)
    gaps = rng.integers(0, 700, n)
    src_offs = np.cumsum(sizes + gaps) - (sizes + gaps)
    dst_offs = np.concatenate([[0], np.cumsum(sizes)])[:-1]
    total = int(sizes.sum())
    expect = np.zeros(total, np.uint8)
    for k in range(n):
        expect[dst_offs[k]:dst_offs[k] + sizes[k]] = \
            src[src_offs[k]:src_offs[k] + sizes[k]]
    got = np.asarray(ragged.segmented_copy(jnp.asarray(src), src_offs,
                                           dst_offs, sizes, total))
    record("ragged.segmented_copy gappy", np.array_equal(got, expect))


def check_strings_transcode():
    rng = np.random.default_rng(1)
    words = ["", "a", "spark", "tpu-native kernels", "xy",
             "longer string payload!", "ab\x00cd"]
    for n, nulls in [(1000, None), (503, 7)]:
        strs = [words[i] for i in rng.integers(0, len(words), n)]
        if nulls:
            strs = [None if i % nulls == 0 else s
                    for i, s in enumerate(strs)]
        t = Table([
            Column.from_numpy(rng.integers(-100, 100, n).astype(np.int32)),
            Column.strings_from_list(strs),
            Column.from_numpy(rng.integers(0, 2**40, n).astype(np.int64)),
            Column.strings_from_list(
                [words[i] for i in rng.integers(0, len(words), n)]),
        ])
        b = convert_to_rows(t)
        ob, _ = reference.to_rows_np(t)
        record(f"strings to_rows oracle n={n} nulls={nulls}",
               np.array_equal(b[0].host_bytes(), ob))
        back = convert_from_rows(b[0], t.schema)
        ok = (back[1].to_pylist() == t[1].to_pylist()
              and back[3].to_pylist() == t[3].to_pylist()
              and np.array_equal(back[0].to_numpy(), t[0].to_numpy()))
        record(f"strings roundtrip n={n} nulls={nulls}", ok)


SCHEMAS = {
    "int32_only": [sr.int32] * 3,
    "mixed_words": [sr.int32, sr.int16, sr.int8],
    "wide_mixed": [sr.int64, sr.int32, sr.int16, sr.int8, sr.float32,
                   sr.bool8, sr.float64] * 2,
    "bytes_only": [sr.int8] * 5,
    "timestamps_decimals": [sr.timestamp_ms, sr.decimal32(-2),
                            sr.decimal64(-4), sr.bool8, sr.types.decimal128(-4)],
    # wide enough to route through the 2-D-transpose interleave (W > 40)
    # while staying under the 1KB JCUDF row limit (~920B rows, W=230)
    "wide_135col": [sr.int32, sr.float64, sr.float32] * 45,
}


def _random_table(rng, schema, n):
    cols = []
    for i, dt in enumerate(schema):
        v = (rng.random(n) < 0.8) if i % 2 == 0 else None
        if dt.id == sr.TypeId.DECIMAL128:
            lanes = rng.integers(-2**62, 2**62, (n, 2), dtype=np.int64)
            cols.append(Column(dt, jnp.asarray(lanes),
                               validity=None if v is None else jnp.asarray(v)))
        elif dt == sr.bool8:
            cols.append(Column.from_numpy(
                rng.integers(0, 2, n).astype(np.uint8), dt, v))
        elif dt.storage.kind == "f":
            cols.append(Column.from_numpy(
                rng.standard_normal(n).astype(dt.storage), dt, v))
        else:
            info = np.iinfo(dt.storage)
            cols.append(Column.from_numpy(
                rng.integers(info.min // 2, info.max // 2, n,
                             dtype=dt.storage), dt, v))
    return Table(cols)


def check_strings_large_n():
    """from_rows' large-n branch (device-side slots, no host metadata) must
    agree byte-for-byte with the small-n slots+segmented-copy branch."""
    from spark_rapids_jni_tpu.rowconv import convert as cv
    rng = np.random.default_rng(5)
    n = 70000   # > _DMA_FROM_ROWS_MAX_N (65536)
    words = ["", "a", "tpu", "larger payload string", "x" * 30]
    t = Table([
        Column.from_numpy(rng.integers(-1000, 1000, n).astype(np.int32)),
        Column.strings_from_list(
            [words[i] for i in rng.integers(0, len(words), n)]),
        Column.strings_from_list(
            [words[i] for i in rng.integers(0, len(words), n)]),
    ])
    b = convert_to_rows(t)[0]
    big = convert_from_rows(b, t.schema)          # large-n branch
    old = cv._DMA_FROM_ROWS_MAX_N
    cv._DMA_FROM_ROWS_MAX_N = 1 << 40
    try:
        small = convert_from_rows(b, t.schema)    # slots + segmented copy
    finally:
        cv._DMA_FROM_ROWS_MAX_N = old
    ok = True
    for ca, cb in zip(big.columns, small.columns):
        ok = ok and np.array_equal(np.asarray(ca.data), np.asarray(cb.data))
        if ca.offsets is not None:
            ok = ok and np.array_equal(np.asarray(ca.offsets),
                                       np.asarray(cb.offsets))
        ok = ok and np.array_equal(np.asarray(ca.validity_or_true()),
                                   np.asarray(cb.validity_or_true()))
    record("strings from_rows large-n == small-n", ok)


def check_xpack_engines():
    """Round-5 engines on the real chip: the fused to_rows/from_rows xpack
    programs (prove they ENGAGE, then byte-compare vs the non-xpack path),
    segmented_gather, and cap-boundary geometries incl. empty strings and
    an Lw outlier."""
    import os
    from spark_rapids_jni_tpu.rowconv import xpack
    rng = np.random.default_rng(7)
    cases = [
        ("bench_shape", 4000, lambda i: ["", "tpu", "spark-rapids",
                                         "columnar row transcode",
                                         "x" * 24, "payload"][i % 6]),
        ("empty_heavy", 2000, lambda i: "" if i % 3 else "ab"),
        ("outlier", 1500, lambda i: "z" * 300 if i == 700 else "s" * (i % 9)),
    ]
    for name, n, gen in cases:
        strs = [gen(i) for i in range(n)]
        t = Table([
            Column.from_numpy(rng.integers(-99, 99, n).astype(np.int64),
                              sr.int64, rng.random(n) < 0.9),
            Column.strings_from_list(strs),
            Column.strings_from_list([s[::-1] for s in strs]),
        ])
        layout = compute_row_layout(t.schema)
        b = convert_to_rows(t)[0]
        res = xpack.from_rows_var_x(layout, b)
        record(f"xpack from_rows engages [{name}]", res is not None)
        got = convert_from_rows(b, t.schema)
        # save/restore around the A/B write below, not a config read
        saved = os.environ.get("SRJT_XPACK")  # srjt-lint: disable=knob-env
        os.environ["SRJT_XPACK"] = "0"
        try:
            want_b = convert_to_rows(t)[0]
            want = convert_from_rows(want_b, t.schema)
        finally:
            if saved is None:
                del os.environ["SRJT_XPACK"]
            else:
                os.environ["SRJT_XPACK"] = saved
        record(f"xpack to_rows bytes [{name}]",
               np.array_equal(b.host_bytes(), want_b.host_bytes()))
        ok = True
        for ca, cb in zip(got.columns, want.columns):
            ok = ok and np.array_equal(np.asarray(ca.data),
                                       np.asarray(cb.data))
            if ca.offsets is not None:
                ok = ok and np.array_equal(np.asarray(ca.offsets),
                                           np.asarray(cb.offsets))
        record(f"xpack from_rows columns [{name}]", ok)

    # segmented_gather: ordered segments with gaps, vs numpy
    S = 200_000
    src_b = rng.integers(0, 256, S).astype(np.uint8)
    nseg = 3000
    lens = rng.integers(0, 90, nseg).astype(np.int32)
    gaps = rng.integers(0, 8, nseg)
    starts = np.zeros(nseg, np.int64)
    p = 0
    for i in range(nseg):
        starts[i] = p
        p += lens[i] + gaps[i]
    dst = np.zeros(nseg + 1, np.int64)
    np.cumsum(lens, out=dst[1:])
    geom = xpack.plan_segmented_gather(starts, lens, dst)
    record("segmented_gather plans", geom is not None)
    if geom is not None:
        got = np.asarray(xpack.segmented_gather(
            geom, jnp.asarray(src_b), jnp.asarray(starts.astype(np.int32)),
            jnp.asarray(lens), jnp.asarray(dst.astype(np.int32))))
        want = np.concatenate(
            [src_b[s:s + l] for s, l in zip(starts, lens)])             if lens.sum() else np.zeros(0, np.uint8)
        record("segmented_gather bytes", np.array_equal(got, want))


def check_dict_strings():
    """Dictionary-string device decode (round 5) byte-exact on chip vs the
    host decoder, nulls included."""
    import io
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.parquet import decode, device_scan
    rng = np.random.default_rng(9)
    n = 30_000
    words = ["", "tpu", "dictionary-entry-payload", "x" * 60, "ünïcodé"]
    vals = [None if rng.random() < 0.1 else words[i]
            for i in rng.integers(0, len(words), n)]
    t = pa.table({"s": pa.array(vals, pa.string())})
    buf = io.BytesIO()
    pq.write_table(t, buf, compression="SNAPPY", use_dictionary=True,
                   row_group_size=12_000)
    raw = buf.getvalue()
    dev = device_scan.scan_table(raw).columns[0]
    host = decode.read_table(raw).columns[0]
    ok = (np.array_equal(np.asarray(dev.data), np.asarray(host.data))
          and np.array_equal(np.asarray(dev.offsets),
                             np.asarray(host.offsets))
          and np.array_equal(np.asarray(dev.validity_or_true()),
                             np.asarray(host.validity_or_true())))
    record("dict strings device decode", ok)


def check_dict_fast_path():
    """Dictionary fast path on chip: the scan keeps codes (no byte
    materialization), dictionary-aware predicates (evaluate once per
    entry, gather the boolean by code) match a per-row byte-matrix
    oracle, and code gathers match reference row selection."""
    import io
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.column import DictColumn, Table
    from spark_rapids_jni_tpu.ops import filter as F
    from spark_rapids_jni_tpu.ops import strings as S
    from spark_rapids_jni_tpu.parquet import device_scan
    rng = np.random.default_rng(11)
    n = 20_000
    words = ["alpha", "alpaca", "beta", "betamax", "", "gamma-ray",
             "alphabet"]
    picks = rng.integers(0, len(words), n)
    vals = [None if rng.random() < 0.1 else words[i] for i in picks]
    t = pa.table({"s": pa.array(vals, pa.string())})
    buf = io.BytesIO()
    pq.write_table(t, buf, use_dictionary=True, row_group_size=8_000)
    col = device_scan.scan_table(buf.getvalue()).columns[0]
    record("dict fast path scan produces codes", isinstance(col, DictColumn))
    if not isinstance(col, DictColumn):
        return

    # dictionary-aware predicate vs byte-matrix oracle (per-row evaluate)
    def oracle(pred):
        return np.array([bool(v is not None and pred(v)) for v in vals])

    checks = [
        ("equal", S.equal_to_scalar(col, "alpha"), oracle(lambda v: v == "alpha")),
        ("starts_with", S.starts_with(col, "alp"), oracle(lambda v: v.startswith("alp"))),
        ("like", S.like(col, "%eta%"), oracle(lambda v: "eta" in v)),
    ]
    for name, got, want in checks:
        bits = np.asarray(got.data) != 0
        if got.validity is not None:
            bits = bits & np.asarray(got.validity)
        record(f"dict predicate {name} vs oracle", np.array_equal(bits, want))
    m = F.isin(col, ["beta", "gamma-ray", "absent"])
    record("dict isin vs oracle",
           np.array_equal(np.asarray(m), oracle(lambda v: v in ("beta", "gamma-ray"))))

    # code gather: row selection without touching string bytes
    idx = jnp.asarray(rng.integers(0, n, 4_000).astype(np.int32))
    g = F.gather(Table([col]), idx).columns[0]
    record("dict gather stays codes", isinstance(g, DictColumn))
    want = [vals[i] for i in np.asarray(idx)]
    record("dict gather rows", g.to_pylist() == want)


def check_fixed_words():
    rng = np.random.default_rng(2)
    for name, schema in SCHEMAS.items():
        n = 4097
        t = _random_table(rng, schema, n)
        b = convert_to_rows(t)
        want, _ = reference.to_rows_np(t)
        record(f"fixed words to_rows {name}",
               np.array_equal(b[0].host_bytes(), want))
        back = convert_from_rows(b[0], t.schema)
        ok = True
        for ca, cb in zip(back.columns, t.columns):
            va = np.asarray(ca.validity_or_true())
            ok = ok and np.array_equal(va, np.asarray(cb.validity_or_true()))
            da, db = np.asarray(ca.data), np.asarray(cb.data)
            ok = ok and np.array_equal(da[va], db[va])
        record(f"fixed words roundtrip {name}", ok)


def check_f64bits():
    """The arithmetic bits<->values path, within the backend's contract:
    the TPU's emulated f64 carries only ~47-49 effective mantissa bits, so
    the promise is ulp-bounded closeness for normals, exactness for specials
    (powers of two, zeros, infinities), and self-consistent round-trips —
    bit-exactness exists only on native-bitcast backends (CPU suite)."""
    rng = np.random.default_rng(3)
    # Full ~48-bit precision exists only in the middle of the emulation's
    # f32-like exponent window: near its bottom the value's LOW f32
    # component denormal-flushes (precision shrinks gradually, like
    # denormals do), so the ulp assertion samples |x| in ~[2^-60, 2^60].
    vals = np.concatenate([
        rng.standard_normal(4000),
        rng.standard_normal(4000) * 10.0 ** rng.integers(-18, 18, 4000),
        np.array([0.0, -0.0, 1.0, -1.0, np.inf, -np.inf, np.nan,
                  2.0 ** -60, 2.0 ** 60, 0.5, 2.0 ** 100]),
    ]).astype(np.float64)
    bits = vals.view(np.uint32).reshape(-1, 2)
    dec = np.asarray(jax.jit(f64bits.from_bits)(jnp.asarray(bits)))
    finite = np.isfinite(vals)
    # ulp distance via ordered-int mapping of the bit patterns
    a = vals.view(np.int64).copy()
    b = dec.view(np.int64).copy()
    a = np.where(a < 0, np.int64(-2**63) - a, a)
    b = np.where(b < 0, np.int64(-2**63) - b, b)
    ulps = np.abs(a - b)[finite].max() if finite.any() else 0
    record("f64bits.from_bits ulp-bounded", ulps <= 64, f"max ulps={ulps}")
    specials = np.isin(vals, [0.0, 1.0, -1.0, 0.5, 2.0 ** 100]) | ~np.isfinite(vals)
    nan = np.isnan(vals)
    ok_special = np.array_equal(
        dec[specials & ~nan].view(np.uint64),
        vals[specials & ~nan].view(np.uint64)) and np.isnan(dec[nan]).all()
    record("f64bits.from_bits exact on specials", ok_special)
    # encode(decode(bits)) must be self-consistent: decoding again on the
    # same backend reproduces the same emulated value
    enc = np.asarray(jax.jit(
        lambda x: f64bits.to_bits(f64bits.from_bits(x)))(jnp.asarray(bits)))
    dec2 = np.asarray(jax.jit(f64bits.from_bits)(jnp.asarray(enc)))
    ok_rt = np.array_equal(dec2[finite], dec[finite]) and np.isnan(dec2[nan]).all()
    record("f64bits encode(decode) self-consistent", ok_rt)
    # outside the window, decode degrades monotonically to 0 / +-inf
    big = np.array([1e300, -1e300, 1e-300, -1e-300], np.float64)
    dbig = np.asarray(jax.jit(f64bits.from_bits)(
        jnp.asarray(big.view(np.uint32).reshape(-1, 2))))
    record("f64bits out-of-window degrades to 0/inf",
           dbig[0] == np.inf and dbig[1] == -np.inf
           and dbig[2] == 0.0 and abs(dbig[3]) == 0.0,
           f"decoded={dbig.tolist()}")


def check_query_ops():
    """Minimal on-chip repros for the op family behind the 13 TPU-crashing
    queries (VERDICT weak #1: rollup/grouping-sets/cube, rank/window,
    string-compare) — each probe is one op over ~1-2k rows, differentially
    checked against a host oracle, so a worker crash here pinpoints the
    culprit op without running the query suite."""
    from spark_rapids_jni_tpu import ops
    from spark_rapids_jni_tpu.ops import strings as S
    from spark_rapids_jni_tpu.ops import window as W

    rng = np.random.default_rng(13)
    n = 1500
    a = rng.integers(0, 7, n).astype(np.int64)
    b = rng.integers(0, 5, n).astype(np.int64)
    v = rng.integers(-100, 100, n).astype(np.int64)
    av = rng.random(n) < 0.9       # null keys ride along (Spark groups them)
    t = Table([Column.from_numpy(a, validity=av), Column.from_numpy(b),
               Column.from_numpy(v)])

    def host_sets(sets):
        # oracle: one dict pass per grouping set, Spark grouping_id bits
        # (MSB = first key, set when the key is aggregated away)
        rows = set()
        for s in sets:
            gid = sum(1 << (1 - k) for k in range(2) if k not in s)
            acc = {}
            for i in range(n):
                ka = (int(a[i]) if av[i] else None) if 0 in s else None
                kb = int(b[i]) if 1 in s else None
                acc[(ka, kb)] = acc.get((ka, kb), 0) + int(v[i])
            rows |= {(ka, kb, sv, gid) for (ka, kb), sv in acc.items()}
        return rows

    def got_rows(out):
        return set(zip(out[0].to_pylist(), out[1].to_pylist(),
                       out[2].to_pylist(), out[3].to_pylist()))

    out = ops.groupby_rollup(t, [0, 1], [(2, "sum")])
    record("query-ops rollup(sum)",
           got_rows(out) == host_sets([[0, 1], [0], []]))
    out = ops.groupby_cube(t, [0, 1], [(2, "sum")])
    record("query-ops cube(sum)",
           got_rows(out) == host_sets([[0, 1], [0], [1], []]))
    out = ops.groupby_grouping_sets(t, [0, 1], [[0], [1]], [(2, "sum")])
    record("query-ops grouping-sets(sum)",
           got_rows(out) == host_sets([[0], [1]]))

    # rank / dense_rank / row_number / lag vs a host scan
    part = rng.integers(0, 40, n).astype(np.int64)
    key = rng.integers(0, 25, n).astype(np.int64)
    wt = Table([Column.from_numpy(part), Column.from_numpy(key),
                Column.from_numpy(v)])
    spec = W.WindowSpec(wt, partition_by=[0], order_by_keys=[1])
    order = sorted(range(n), key=lambda i: (part[i], key[i], i))
    exp_rn = np.zeros(n, np.int64)
    exp_rk = np.zeros(n, np.int64)
    exp_dr = np.zeros(n, np.int64)
    exp_lag = [None] * n
    pos = rk = dr = 0
    for j, i in enumerate(order):
        prev = order[j - 1] if j else None
        if prev is None or part[prev] != part[i]:
            pos, rk, dr = 1, 1, 1
        else:
            pos += 1
            if key[prev] != key[i]:
                rk, dr = pos, dr + 1
            exp_lag[i] = int(v[prev])
        exp_rn[i], exp_rk[i], exp_dr[i] = pos, rk, dr
    record("query-ops row_number",
           np.array_equal(np.asarray(W.row_number(spec).to_numpy()), exp_rn))
    record("query-ops rank",
           np.array_equal(np.asarray(W.rank(spec, [1]).to_numpy()), exp_rk))
    record("query-ops dense_rank",
           np.array_equal(np.asarray(W.dense_rank(spec, [1]).to_numpy()),
                          exp_dr))
    record("query-ops lag", W.lag(spec, 2, 1).to_pylist() == exp_lag)

    # string compares (contains / starts_with / equal_to_scalar)
    words = ["", "brand#1", "BRAND#12", "spark", "s", "importers #1",
             "xx#1yy", None]
    strs = [words[i] for i in rng.integers(0, len(words), n)]
    sc = Column.strings_from_list(strs)
    want = [None if s is None else ("#1" in s) for s in strs]
    record("query-ops strings.contains",
           S.contains(sc, "#1").to_pylist() == want)
    want = [None if s is None else s.startswith("s") for s in strs]
    record("query-ops strings.starts_with",
           S.starts_with(sc, "s").to_pylist() == want)
    want = [None if s is None else (s == "spark") for s in strs]
    record("query-ops strings.equal_to_scalar",
           S.equal_to_scalar(sc, "spark").to_pylist() == want)


def check_composite_pack():
    """Composite-key pack/unpack lowering (join engine v2 multi-key): the
    mixed-radix int64 mul/add pack chain and its floordiv/mod inverse,
    jitted on chip, vs a NumPy oracle — then one end-to-end 2-key join
    planned through ``join_plan.plan_keys`` whose pairs must reproduce the
    host tuple join.  A miscompile in the int64 chains shows up here as a
    single failing probe, not a wrong TPC-DS aggregate."""
    from spark_rapids_jni_tpu.ops import join_plan
    from spark_rapids_jni_tpu.ops.join import join_indices

    rng = np.random.default_rng(17)
    n = 4096
    for name, spans, kmins in [
        ("3key_small", (19, 64, 256), (-7, 0, 1000)),
        ("2key_wide", (1 << 20, 1 << 21), (123_456, -998_877)),
        ("4key_mixed", (11, 13, 17, 1 << 30), (0, -5, 2, -(1 << 29))),
    ]:
        lanes = [rng.integers(k, k + s, n, dtype=np.int64)
                 for s, k in zip(spans, kmins)]
        comp = np.zeros(n, np.int64)
        stride = 1
        for s, k, l in zip(spans[::-1], kmins[::-1], lanes[::-1]):
            comp += (l - k) * stride
            stride *= s

        @jax.jit
        def pack(ls, spans=spans, kmins=kmins):
            c = jnp.zeros(n, jnp.int64)
            st = 1
            for s, k, l in zip(spans[::-1], kmins[::-1], ls[::-1]):
                d = l.astype(jnp.int64) - k
                c = c + jnp.clip(d, 0, s - 1) * st
                st *= s
            return c

        got = np.asarray(pack([jnp.asarray(l) for l in lanes]))
        record(f"composite pack {name}", np.array_equal(got, comp))

        @jax.jit
        def unpack(c, spans=spans, kmins=kmins):
            outs = []
            for s, k in zip(spans[::-1], kmins[::-1]):
                outs.append(c % s + k)
                c = c // s
            return outs[::-1]

        back = [np.asarray(x) for x in unpack(jnp.asarray(comp))]
        record(f"composite unpack {name}",
               all(np.array_equal(b, l) for b, l in zip(back, lanes)))

    # end-to-end: planner packs, engines probe, pairs match host tuples
    import collections
    nb, npr = 3000, 8000
    ra = rng.integers(-50, 50, nb, dtype=np.int64)
    rb = rng.integers(0, 9, nb, dtype=np.int64)
    sel = rng.integers(0, nb, npr)
    la = ra[sel]
    lb = np.where(rng.random(npr) < 0.8, rb[sel], rb[sel] + 10)
    lt = [Column.from_numpy(la), Column.from_numpy(lb)]
    rt = [Column.from_numpy(ra), Column.from_numpy(rb)]
    plan = join_plan.plan_keys(lt, rt)
    record("composite plan_keys mode", plan.mode == "composite", plan.mode)
    li, ri = join_indices(lt, rt, "inner")
    li, ri = np.asarray(li), np.asarray(ri)
    keys_eq = (np.array_equal(la[li], ra[ri])
               and np.array_equal(lb[li], rb[ri]))
    cnt = collections.Counter(zip(ra.tolist(), rb.tolist()))
    want = sum(cnt[(x, y)] for x, y in zip(la.tolist(), lb.tolist()))
    record("composite 2-key join pairs", keys_eq and li.shape[0] == want,
           f"pairs={li.shape[0]}")


def main():
    t0 = time.time()
    RESULTS["backend"] = jax.default_backend()
    if RESULTS["backend"] != "tpu":
        RESULTS["ok"] = False
        RESULTS["error"] = "not running on a TPU backend"
    else:
        print("ragged engine:", flush=True)
        check_ragged()
        print("strings transcode:", flush=True)
        check_strings_transcode()
        print("strings large-n branch:", flush=True)
        check_strings_large_n()
        print("xpack engines (round 5):", flush=True)
        check_xpack_engines()
        print("dict strings:", flush=True)
        check_dict_strings()
        print("dict fast path (codes + predicates):", flush=True)
        check_dict_fast_path()
        print("fixed-width u32-words transcode:", flush=True)
        check_fixed_words()
        print("f64 bits<->values:", flush=True)
        check_f64bits()
        print("chip-killer query ops (rollup/window/string-compare):",
              flush=True)
        check_query_ops()
        print("composite-key pack/unpack lowering:", flush=True)
        check_composite_pack()
    RESULTS["seconds"] = round(time.time() - t0, 1)
    out = sys.argv[1] if len(sys.argv) > 1 else "PALLAS_TPU_CHECK.json"
    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print(json.dumps({"ok": RESULTS["ok"], "checks": len(RESULTS["checks"]),
                      "seconds": RESULTS["seconds"]}), flush=True)


if __name__ == "__main__":
    main()
