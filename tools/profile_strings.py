#!/usr/bin/env python
"""String-transcode formulation shootout on the real chip → PROFILE_strings.json.

VERDICT r3 next-step #1: the var-width path is ~2000× off the fixed path
(0.013-0.042 GB/s wall vs 27.9+).  The round-3 design moved bytes with the
ragged DMA engine, whose per-segment cost is O(staged window) — at the bench
geometry (11-byte strings, 125-byte rows) that is ~50× write amplification —
and whose host-side geometry prep uploads MBs of metadata through a
~25 MB/s tunnel per call.  The round-4 redesign is a single-jit gather/roll
formulation; this script measures every candidate primitive so the chosen
formulation is evidence-based (same methodology as profile_transcode.py:
dependency-chained fori_loop, trip-count differenced).

Stages measured:
  1. per-element 1D gather, u8 and u32, sorted and random indices
  2. row-gather of [*, 128] u32 blocks (512B granularity)
  3. vmap'd dynamic_slice window gather (8/32-word windows per row)
  4. take_along_axis in-row gather [n, 32]
  5. within-row variable roll via log-shift select tree [n, 32]
  6. marker-cumsum segment_of at pack scale
  7. ragged engine at TINY segments (the bench geometry) for comparison
  8. candidate fused pack: out32[q] = dense_flat[q + delta[row_of[q]]]

Usage: python tools/profile_strings.py [out.json]
"""

import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax import lax

RESULTS = {"backend": None, "stages": []}
N_LO, N_HI = 3, 13
OUT_PATH = "PROFILE_strings.json"


def _flush():
    with open(OUT_PATH, "w") as f:
        json.dump(RESULTS, f, indent=1)


def _loop(body):
    @jax.jit
    def run(data, iters):
        def step(_, carry):
            acc, data_ = carry
            d = lax.optimization_barrier((data_, acc))[0]
            out = body(d)
            out = lax.optimization_barrier(out)
            leaf = jax.tree_util.tree_leaves(out)[0]
            probe = lax.convert_element_type(jnp.ravel(leaf)[0], jnp.int32)
            return (acc + probe) % jnp.int32(65521), data_
        acc, _ = lax.fori_loop(0, iters, step, (jnp.int32(0), data))
        return acc
    return run


def measure(name, body, data, nbytes, note="", n_elems=None):
    run = _loop(body)
    try:
        np.asarray(run(data, N_LO))          # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(data, N_LO))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run(data, N_HI))
        t_hi = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001
        RESULTS["stages"].append({"name": name, "error": repr(e)[:300]})
        _flush()
        print(f"  FAIL {name}: {e!r}"[:200], flush=True)
        return None
    per_iter = (t_hi - t_lo) / (N_HI - N_LO)
    if per_iter <= 0:
        RESULTS["stages"].append({"name": name, "error": "nonpositive delta",
                                  "t_lo_s": t_lo, "t_hi_s": t_hi})
        _flush()
        print(f"  NOISY {name}: t_lo={t_lo:.3f} t_hi={t_hi:.3f}", flush=True)
        return None
    gbps = nbytes / per_iter / 1e9
    rec = {"name": name, "per_iter_ms": round(per_iter * 1e3, 3),
           "gbps": round(gbps, 2), "nbytes": nbytes, "note": note}
    if n_elems:
        rec["gelems_per_s"] = round(n_elems / per_iter / 1e9, 4)
    RESULTS["stages"].append(rec)
    _flush()
    extra = f"  {rec.get('gelems_per_s','')} Gelem/s" if n_elems else ""
    print(f"  {name}: {per_iter*1e3:.3f} ms/iter  {gbps:.2f} GB/s{extra}  "
          f"{note}", flush=True)
    return per_iter


def main():
    global OUT_PATH
    if len(sys.argv) > 1:
        OUT_PATH = sys.argv[1]   # incremental flushes must hit the same file
    RESULTS["backend"] = jax.default_backend()
    print(f"backend: {RESULTS['backend']}", flush=True)
    rng = np.random.default_rng(0)

    # --- 1. per-element 1D gather -----------------------------------------
    NSRC = 1 << 25                       # 32M
    src32 = jnp.asarray(rng.integers(0, 2**32, NSRC, dtype=np.uint32))
    src8 = jnp.asarray(rng.integers(0, 256, NSRC, dtype=np.uint8))
    NIDX = 1 << 23                       # 8M indices
    idx_sorted = jnp.asarray(np.sort(rng.integers(0, NSRC, NIDX)).astype(np.int32))
    idx_rand = jnp.asarray(rng.integers(0, NSRC, NIDX).astype(np.int32))
    # near-affine sorted indices (the pack pattern: idx = q + small delta)
    q = np.arange(NIDX, dtype=np.int64)
    idx_affine = jnp.asarray((q + np.minimum(q // 37, NSRC - NIDX - 1))
                             .astype(np.int32))

    for nm, idx in [("sorted", idx_sorted), ("rand", idx_rand),
                    ("affine", idx_affine)]:
        measure(f"gather_u32_{nm}", lambda i, s=src32: s[i], idx,
                NIDX * 4 * 2, n_elems=NIDX)
    measure("gather_u8_sorted", lambda i, s=src8: s[i], idx_sorted,
            NIDX * 2, n_elems=NIDX)

    # --- 2. row-gather of [*, 128] blocks ---------------------------------
    src2d = src32.reshape(-1, 128)        # [256K, 128]
    ridx = jnp.asarray(np.sort(rng.integers(0, src2d.shape[0], 1 << 17))
                       .astype(np.int32))
    measure("rowgather_512B", lambda i, s=src2d: s[i], ridx,
            (1 << 17) * 512 * 2, n_elems=1 << 17)
    # [*, 8] rows (32B granularity)
    src2d8 = src32.reshape(-1, 8)
    ridx8 = jnp.asarray(np.sort(rng.integers(0, src2d8.shape[0], 1 << 21))
                        .astype(np.int32))
    measure("rowgather_32B", lambda i, s=src2d8: s[i], ridx8,
            (1 << 21) * 32 * 2, n_elems=1 << 21)

    # --- 3. vmap'd dynamic_slice window gather ----------------------------
    NROW = 1 << 20
    starts = jnp.asarray(np.sort(rng.integers(0, NSRC - 64, NROW))
                         .astype(np.int32))

    def win_gather(W):
        def f(st, s=src32):
            return jax.vmap(
                lambda o: lax.dynamic_slice(s, (o,), (W,)))(st)
        return f
    measure("winslice_8w", win_gather(8), starts, NROW * 32 * 2,
            n_elems=NROW * 8)
    measure("winslice_32w", win_gather(32), starts, NROW * 128 * 2,
            n_elems=NROW * 32)

    # --- 4./5. in-row gather and log-shift roll ---------------------------
    M = 32
    x_nm = jnp.asarray(rng.integers(0, 2**32, (NROW, M), dtype=np.uint32))
    shift = jnp.asarray(rng.integers(0, M, NROW).astype(np.int32))
    ridx_in = jnp.asarray(rng.integers(0, M, (NROW, M)).astype(np.int32))

    def tala(i, x=x_nm):
        return jnp.take_along_axis(x, i, axis=1)
    measure("take_along_axis_32", tala, ridx_in, NROW * M * 4 * 2,
            n_elems=NROW * M)

    def logshift(s, x=x_nm):
        # right-shift each row by s[r] words: out[r, k] = x[r, k - s[r]]
        out = x
        for b in range(5):                     # log2(32)
            sh = 1 << b
            shifted = jnp.pad(out, ((0, 0), (sh, 0)))[:, :M]
            bit = ((s >> b) & 1).astype(bool)[:, None]
            out = jnp.where(bit, shifted, out)
        return out
    measure("logshift_roll_32", logshift, shift, NROW * M * 4 * 2,
            "5 select passes")

    # --- 6. marker-cumsum segment_of --------------------------------------
    TOT = 1 << 25
    seg_starts = np.sort(rng.integers(0, TOT, 1 << 20)).astype(np.int32)
    seg_starts = jnp.asarray(np.concatenate(
        [[0], seg_starts, [TOT]]).astype(np.int32))

    def segof(st):
        markers = jnp.zeros((TOT,), jnp.int32).at[st[1:-1]].add(1)
        return jnp.cumsum(markers)
    measure("segment_of_32M", segof, seg_starts, TOT * 4 * 2,
            "marker scatter + cumsum")

    # --- 7. ragged engine at bench-tiny segments --------------------------
    from spark_rapids_jni_tpu.rowconv import ragged
    if ragged.dma_supported():
        n_seg = 1 << 20
        lens = rng.integers(0, 25, n_seg)     # 0..24B strings (bench mix)
        offs = np.zeros(n_seg + 1, dtype=np.int64)
        np.cumsum(lens, out=offs[1:])
        total = int(offs[-1])
        chars = jnp.asarray(rng.integers(0, 256, total, dtype=np.uint8))
        t0 = time.perf_counter()
        r = ragged.unpack(chars, offs, 32)
        np.asarray(r[:1, :1])
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        r = ragged.unpack(chars, offs, 32)
        np.asarray(r[:1, :1])
        t2 = time.perf_counter() - t0
        RESULTS["stages"].append({
            "name": "ragged_unpack_tiny_wall", "cold_s": round(t1, 3),
            "warm_s": round(t2, 3),
            "gbps_warm": round(total / t2 / 1e9, 3),
            "note": f"{n_seg} segs avg {total/n_seg:.1f}B — wall incl host prep"})
        print(f"  ragged_unpack_tiny: cold {t1:.2f}s warm {t2:.2f}s "
              f"({total/t2/1e9:.3f} GB/s)", flush=True)

        dense = jnp.asarray(rng.integers(0, 256, (n_seg, 32), dtype=np.uint8))
        ro = np.zeros(n_seg + 1, dtype=np.int64)
        np.cumsum(rng.integers(8, 33, n_seg), out=ro[1:])
        t0 = time.perf_counter()
        p = ragged.pack(dense, ro)
        np.asarray(p[:1])
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        p = ragged.pack(dense, ro)
        np.asarray(p[:1])
        t2 = time.perf_counter() - t0
        RESULTS["stages"].append({
            "name": "ragged_pack_tiny_wall", "cold_s": round(t1, 3),
            "warm_s": round(t2, 3),
            "gbps_warm": round(int(ro[-1]) / t2 / 1e9, 3),
            "note": "1M rows avg 20B packed — wall incl host prep"})
        print(f"  ragged_pack_tiny: cold {t1:.2f}s warm {t2:.2f}s", flush=True)

    # --- 8. candidate fused pack ------------------------------------------
    # rows of Mw=32 words packed to ~20 words each: out[q] = flat[q + d[row_of[q]]]
    n_rows = 1 << 20
    Mw = 32
    lens_w = rng.integers(8, 33, n_rows)
    offw = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(lens_w, out=offw[1:])
    TOTW = int(offw[-1])
    delta_np = (np.arange(n_rows, dtype=np.int64) * Mw - offw[:-1]).astype(np.int32)
    dense_flat = jnp.asarray(rng.integers(0, 2**32, n_rows * Mw,
                                          dtype=np.uint32))
    offw_dev = jnp.asarray(offw.astype(np.int32))
    delta_dev = jnp.asarray(delta_np)

    def fused_pack(args):
        flat, offs, delta = args
        markers = jnp.zeros((TOTW,), jnp.int32).at[offs[1:-1]].add(1)
        row_of = jnp.cumsum(markers)
        qq = jnp.arange(TOTW, dtype=jnp.int32)
        return flat[qq + delta[row_of]]
    measure("fused_pack_gather", fused_pack,
            (dense_flat, offw_dev, delta_dev), TOTW * 4 * 2,
            f"{n_rows} rows, segment_of + affine gather", n_elems=TOTW)

    _flush()
    print(f"wrote {OUT_PATH}", flush=True)


if __name__ == "__main__":
    main()
