#!/usr/bin/env python
"""SF1-class TPC-DS query timing + sync accounting → QUERY_BENCH.json.

BASELINE config #3 at scale: a 10M-row store_sales fact (20K items, 50
stores, 3 years of dates) generated as snappy parquet, decoded through the
scan path, then a representative query slice measured three ways:

  cold     — eager capture run: jit compiles + the plan's size-resolution
             syncs (``models/compiled.py`` records the tape here)
  warm     — the compiled ONE-PROGRAM form: wall time of a single dispatch
             + result materialization through the tunnel (syncs counted;
             steady state is 0 plan syncs — only the result pull remains)
  steady   — trip-count-differenced in-jit time of the compiled program
             (same methodology as bench.py): pure device time per query,
             the number comparable against local pandas wall time, since
             the ~65-110 ms tunnel RTT is a deployment artifact, not a
             property of the engine

The JAX persistent compilation cache is enabled so a second process's cold
run reuses every compiled program (VERDICT r3 next-step #3).

Per-query observability (utils/metrics.py): the cold capture run — the
eager, fully-instrumented execution — records a span tree plus engine/
cache counters; each query entry carries a ``stages`` breakdown and a
``metrics`` counter snapshot, and ``SRJT_QB_TRACE_DIR=<dir>`` additionally
exports one Chrome-trace JSON per query (inspect with
``tools/trace_report.py`` or Perfetto).  Metrics are disabled again before
the warm/steady timings so the measured numbers stay instrumentation-free
(``SRJT_QB_METRICS=0`` turns the whole thing off).

Usage: python tools/query_bench.py [n_sales] [out.json] [q1,q2,...]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax

# persistent compile cache: cold runs in a fresh process reuse executables
jax.config.update("jax_compilation_cache_dir", ".jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp
from jax import lax

RESULTS = {"queries": {}}

# long-runner steady coverage (ROADMAP): these queries exceed the default
# warm cap, so the differencing loop runs with reduced trip counts instead
# of skipping — fewer iterations bound the on-chip work that crashed the
# worker in the first full sweep
STEADY_LONG = {"q19", "q65", "q_having"}

# counter prefixes worth surfacing per query entry (the full registry goes
# to the per-query trace file when SRJT_QB_TRACE_DIR is set)
_METRIC_PREFIXES = ("join.engine.", "join.build_index.", "join.expand.",
                    "compiled.", "parquet.device_cols",
                    "parquet.host_fallback_cols", "shuffle.", "arena.")


def _metrics_pick(counters: dict) -> dict:
    return {k: v for k, v in sorted(counters.items())
            if k.startswith(_METRIC_PREFIXES)}


def steady_per_iter(prog, tables, lo=2, hi=6):
    """Differenced steady-state seconds per query execution."""
    @jax.jit
    def run(tbls, iters):
        def step(_, carry):
            acc, t = carry
            tin = lax.optimization_barrier((t, acc))[0]
            out = prog(tin)
            out = lax.optimization_barrier(out)
            # probe the first NON-EMPTY leaf (a 0-row result table has
            # size-0 columns; indexing them would fail at trace time)
            leaves = [l for l in jax.tree_util.tree_leaves(out) if l.size]
            probe = (lax.convert_element_type(jnp.ravel(leaves[0])[0],
                                              jnp.int32)
                     if leaves else jnp.int32(0))
            return (acc + probe) % jnp.int32(65521), t
        acc, _ = lax.fori_loop(0, iters, step, (jnp.int32(0), tbls))
        return acc

    np.asarray(run(tables, lo))          # compile + warm
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(run(tables, lo))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run(tables, hi))
        t_hi = time.perf_counter() - t0
        per = (t_hi - t_lo) / (hi - lo)
        if per > 0:
            best = per if best is None else min(best, per)
    return best


def main():
    if "--profile" in sys.argv:
        # survives the crash-handler os.execv via the env knob
        sys.argv.remove("--profile")
        os.environ["SRJT_QB_PROFILE"] = "1"
    if "--sql" in sys.argv:
        # serve the SQL ports of the corpus (models/tpcds_sql.py) through
        # the front-end instead of the hand-fused queries — same tables,
        # same measurement; survives re-exec via the env knob
        sys.argv.remove("--sql")
        os.environ["SRJT_QB_SQL"] = "1"
    n_sales = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else "QUERY_BENCH.json"
    print(f"backend: {jax.default_backend()}  n_sales: {n_sales}", flush=True)

    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.models.compiled import compile_query
    from spark_rapids_jni_tpu.utils import knobs, metrics, syncs

    use_sql = knobs.get("SRJT_QB_SQL")
    use_metrics = knobs.get("SRJT_QB_METRICS")
    trace_dir = knobs.get("SRJT_QB_TRACE_DIR")
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)

    t0 = time.perf_counter()
    files = tpcds_data.generate(n_sales=n_sales, n_items=20_000,
                                n_stores=50, seed=5)
    gen_s = time.perf_counter() - t0
    print(f"generated {sum(len(v) for v in files.values())/1e6:.0f} MB "
          f"parquet in {gen_s:.1f}s", flush=True)

    t0 = time.perf_counter()
    tables = tpcds.load_tables(files)
    # force materialization of the fact columns (uploads are lazy)
    for c in tables["store_sales"].columns:
        np.asarray(c.data[:1])
    load_s = time.perf_counter() - t0
    RESULTS["n_sales"] = n_sales
    RESULTS["load_s"] = round(load_s, 1)
    print(f"decode+upload: {load_s:.1f}s", flush=True)

    if use_sql:
        from spark_rapids_jni_tpu import sql as sql_fe
        from spark_rapids_jni_tpu.models import tpcds_sql
        RESULTS["mode"] = "sql"
    catalog = tpcds_sql.SQL if use_sql else tpcds.QUERIES
    chosen = (sorted(catalog)
              if len(sys.argv) <= 3 or sys.argv[3] == "all"
              else sys.argv[3].split(","))

    # resume support: a TPU-worker crash poisons the whole process (every
    # later dispatch fails UNAVAILABLE), so the crash handler re-execs a
    # fresh process that reloads tables and SKIPS completed queries.
    # Queries that crashed twice are abandoned (a deterministic
    # chip-killer must not re-exec forever).
    if knobs.get("SRJT_QB_RESUME") == "1" and os.path.exists(out_path):
        with open(out_path) as f:
            prior = json.load(f)
        RESULTS["queries"].update(prior.get("queries", {}))
        RESULTS.setdefault("resumes", prior.get("resumes", 0))
        RESULTS["resumes"] += 1

    def _crashed(exc_repr: str) -> bool:
        return "UNAVAILABLE" in exc_repr or "crashed" in exc_repr

    def _transient(exc_repr: str) -> bool:
        return "HTTP 5" in exc_repr

    def _reexec() -> bool:
        """Re-exec for a fresh backend; False = budget exhausted (the
        caller must STOP — the poisoned backend fails every dispatch)."""
        with open(out_path, "w") as f:
            json.dump(RESULTS, f, indent=1)
        tries = knobs.get("SRJT_QB_TRIES")
        if tries >= 6:
            print("re-exec budget exhausted; stopping", flush=True)
            RESULTS["budget_exhausted"] = True
            return False
        os.environ["SRJT_QB_RESUME"] = "1"
        os.environ["SRJT_QB_TRIES"] = str(tries + 1)
        print("TPU worker crashed — re-exec for a fresh backend",
              flush=True)
        os.execv(sys.executable, [sys.executable] + sys.argv)

    # fewest-attempts-first: fresh queries run before retry-prone ones, so
    # one process lifetime completes every healthy query even when a
    # hang-prone query would otherwise eat the watchdog budget first
    chosen = sorted(chosen, key=lambda q: (
        RESULTS["queries"].get(q, {}).get("attempts", 0), q))
    for name in chosen:
        prev = RESULTS["queries"].get(name)
        if prev is not None:
            steady_on = knobs.get("SRJT_QB_STEADY")
            done = ("steady_ms" in prev
                    or ("steady_skipped" in prev
                        and not (steady_on
                                 and "disabled" in prev["steady_skipped"])))
            struck_out = (prev.get("crashes", 0) >= 2
                          or prev.get("attempts", 0) >= 4)
            gave_up = ("gave_up" in prev or struck_out
                       or ("error" in prev and not _crashed(prev["error"])
                           and not _transient(prev["error"])))
            if struck_out and "gave_up" not in prev:
                RESULTS["queries"][name] = {
                    **prev, "gave_up": "attempt budget (hang/crash?)"}
            if done or gave_up:
                continue
        if use_sql:
            fn = sql_fe.compile_sql(tpcds_sql.SQL[name],
                                    tpcds_sql.TABLE_SCHEMAS,
                                    tpcds_sql.PARAMS.get(name, {}))
        else:
            fn = tpcds.QUERIES[name]
        # attempt accounting is written to disk BEFORE the query runs: a
        # hung remote compile leaves no exception, so the only evidence a
        # watchdog-killed attempt happened is this counter.  3 strikes →
        # the query is abandoned on the next resume.
        attempts = (prev or {}).get("attempts", 0) + 1
        RESULTS["queries"][name] = {**(prev or {}), "attempts": attempts}
        with open(out_path, "w") as f:
            json.dump(RESULTS, f, indent=1)
        entry = {"crashes": (prev or {}).get("crashes", 0),
                 "attempts": attempts}
        # transient remote-compile failures (HTTP 5xx) retry in-process;
        # an entry whose only error is transient is also retried on resume
        if prev and "error" in prev and _transient(prev["error"]):
            entry = {k: v for k, v in prev.items() if k != "error"}
            entry["attempts"] = attempts   # keep the pre-run increment
        try:
            # cold: eager capture (compiles + size syncs, tape recorded).
            # The capture run is the INSTRUMENTED one — metrics are on for
            # it alone, so the warm/steady numbers below stay
            # instrumentation-free.
            if use_metrics:
                metrics.set_enabled(True)
                metrics.reset()
            syncs.reset_sync_count()
            t0 = time.perf_counter()
            with metrics.query_span(name, n_sales=n_sales):
                cq = compile_query(fn, tables)
                jax.block_until_ready(
                    [c.data for c in cq.expected.columns])
                if cq.expected.num_rows:
                    np.asarray(cq.expected[0].data[:1])
            entry["cold_wall_s"] = round(time.perf_counter() - t0, 2)
            entry["cold_syncs"] = syncs.reset_sync_count()
            entry["tape_len"] = len(cq.tape)
            if knobs.get("SRJT_QB_EXPLAIN"):
                # planner EXPLAIN for queries that have a plan-tree port
                try:
                    from spark_rapids_jni_tpu.models import tpcds_plans
                    from spark_rapids_jni_tpu.plan import rules as prules
                    if name in tpcds_plans.PLANS:
                        entry["plan"] = prules.explain(
                            tpcds_plans.PLANS[name](),
                            tpcds_plans.TABLE_SCHEMAS)
                        if knobs.get("SRJT_AQE"):
                            # adaptive EXPLAIN: re-executes the optimized
                            # tree stage-by-stage and annotates each stage
                            # with the AQE rules that fired
                            from spark_rapids_jni_tpu.plan import adaptive
                            entry["plan_adaptive"] = \
                                adaptive.explain_adaptive(
                                    tpcds_plans.PLANS[name](),
                                    tpcds_plans.TABLE_SCHEMAS, tables)
                except Exception as e:          # noqa: BLE001
                    entry["plan"] = f"explain failed: {e!r}"
            if knobs.get("SRJT_QB_PROFILE"):
                # per-plan-node runtime profile (queries with a plan-tree
                # port): one profiled execution of the optimized tree,
                # attached as the node-profile dict
                try:
                    from spark_rapids_jni_tpu.models import tpcds_plans
                    from spark_rapids_jni_tpu.plan import lower as plower
                    from spark_rapids_jni_tpu.plan import \
                        profile as pprofile
                    from spark_rapids_jni_tpu.plan import rules as prules
                    if name in tpcds_plans.PLANS:
                        ptree = prules.optimize(
                            tpcds_plans.PLANS[name](),
                            tpcds_plans.TABLE_SCHEMAS).tree
                        was_on = pprofile.enabled()
                        pprofile.set_enabled(True)
                        try:
                            with pprofile.query(name) as prof:
                                plower.execute(
                                    ptree, plower.TableCatalog(
                                        tables,
                                        tpcds_plans.TABLE_SCHEMAS))
                        finally:
                            pprofile.set_enabled(was_on)
                        entry["profile"] = prof.as_dict()
                except Exception as e:          # noqa: BLE001
                    entry["profile"] = f"profile failed: {e!r}"
            if use_metrics:
                snap = metrics.snapshot()
                entry["stages"] = metrics.stage_breakdown()
                entry["metrics"] = _metrics_pick(snap["counters"])
                hbm_peak = snap["gauges"].get("hbm.live_bytes.peak")
                if hbm_peak is not None:
                    entry["hbm_peak_bytes"] = int(hbm_peak)
                # HBM-arena accounting (present when SRJT_HBM_ARENA /
                # SRJT_HBM_BUDGET enabled the subsystem for the run)
                arena_peak = snap["gauges"].get("arena.peak_bytes")
                if arena_peak is not None:
                    entry["peak_arena_bytes"] = int(arena_peak)
                spills = snap["counters"].get("arena.spill.events")
                if spills:
                    entry["spills"] = int(spills)
                    entry["spill_bytes"] = int(
                        snap["counters"].get("arena.spill.bytes", 0))
                if trace_dir:
                    metrics.export_chrome_trace(
                        os.path.join(trace_dir, f"{name}.json"))
                metrics.set_enabled(False)

            # warm: the one-program form, wall incl. result pull.
            # run() is the production API (validates the tape against the
            # data with one stacked sync — models/compiled.py staleness
            # guard); run_unchecked is the steady loop over verified data.
            out = cq.run(tables)          # compile the fused + size programs
            jax.block_until_ready([c.data for c in out.columns])
            if out.num_rows:
                np.asarray(out[0].data[:1])
            syncs.reset_sync_count()
            t0 = time.perf_counter()
            out = cq.run(tables)
            jax.block_until_ready([c.data for c in out.columns])
            if out.num_rows:
                np.asarray(out[0].data[:1])
            entry["warm_wall_s"] = round(time.perf_counter() - t0, 3)
            entry["warm_syncs"] = syncs.reset_sync_count()
            t0 = time.perf_counter()
            out = cq.run_unchecked(tables)
            jax.block_until_ready([c.data for c in out.columns])
            if out.num_rows:
                np.asarray(out[0].data[:1])
            entry["warm_unchecked_s"] = round(time.perf_counter() - t0, 3)
            entry["rows_out"] = out.num_rows

            # steady: differenced in-jit device time per execution.
            # Heavy queries skip it: the differencing loop multiplies the
            # on-chip work and a long-running loop is what crashed the
            # worker in the first full-sweep attempt (q19, 34 s warm).
            # STEADY_LONG members run anyway with reduced trip counts
            # (1 vs 3 iterations) so the ROADMAP coverage gap closes
            # without the unbounded loop.
            steady_cap = knobs.get("SRJT_QB_STEADY_CAP")
            if not knobs.get("SRJT_QB_STEADY"):
                entry["steady_skipped"] = "disabled (SRJT_QB_STEADY=0)"
            elif entry["warm_unchecked_s"] <= steady_cap:
                per = steady_per_iter(cq._prog, tables)
                entry["steady_ms"] = (round(per * 1e3, 1)
                                      if per is not None else None)
            elif name in STEADY_LONG:
                per = steady_per_iter(cq._prog, tables, lo=1, hi=3)
                entry["steady_ms"] = (round(per * 1e3, 1)
                                      if per is not None else None)
                entry["steady_trips"] = "1/3"
            else:
                entry["steady_skipped"] = f"warm > {steady_cap:g}s"
        except Exception as e:  # noqa: BLE001 — record, keep going
            if use_metrics:
                metrics.set_enabled(False)
            entry["error"] = repr(e)[:300]
            # keep any measurements a previous attempt already paid for
            entry = {**(prev or {}), **entry}
            if _crashed(entry["error"]):
                entry["crashes"] = entry.get("crashes", 0) + 1
                RESULTS["queries"][name] = entry
                if not _reexec():
                    break          # poisoned backend: stop the loop
        RESULTS["queries"][name] = entry
        print(f"{name}: {entry}", flush=True)
        # flush after every query: a worker crash on a later (heavier)
        # query must not lose the measurements already taken
        with open(out_path, "w") as f:
            json.dump(RESULTS, f, indent=1)

    print("wrote", out_path, flush=True)


if __name__ == "__main__":
    main()
