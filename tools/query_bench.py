#!/usr/bin/env python
"""SF1-class TPC-DS query timing + sync accounting → QUERY_BENCH.json.

BASELINE config #3 at scale: a 10M-row store_sales fact (20K items, 50
stores, 3 years of dates) generated as snappy parquet, decoded through the
scan path, then a representative query slice timed twice:

  run 1 (cold): jit compiles + one-time dictionary/width syncs
  run 2 (warm): steady state — compiled programs, memoized dictionary
                encodes and string widths (``utils/syncs.py``)

For each run the wall time AND the number of intentional host scalar syncs
(the ``syncs.scalar`` funnel: group counts, filter counts, string widths,
dictionary sizes) are recorded — the VERDICT r2 "sync-count-per-query"
figure.  On the tunneled chip each sync costs ~65-110 ms, so warm counts
approximate the dispatch-bound floor of a plan.

Usage: python tools/query_bench.py [n_sales] [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax

RESULTS = {"queries": {}}


def main():
    n_sales = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else "QUERY_BENCH.json"
    print(f"backend: {jax.default_backend()}  n_sales: {n_sales}", flush=True)

    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.utils import syncs

    t0 = time.perf_counter()
    files = tpcds_data.generate(n_sales=n_sales, n_items=20_000,
                                n_stores=50, seed=5)
    gen_s = time.perf_counter() - t0
    print(f"generated {sum(len(v) for v in files.values())/1e6:.0f} MB "
          f"parquet in {gen_s:.1f}s", flush=True)

    t0 = time.perf_counter()
    tables = tpcds.load_tables(files)
    # force materialization of the fact columns (uploads are lazy)
    for c in tables["store_sales"].columns:
        np.asarray(c.data[:1])
    load_s = time.perf_counter() - t0
    RESULTS["n_sales"] = n_sales
    RESULTS["load_s"] = round(load_s, 1)
    print(f"decode+upload: {load_s:.1f}s", flush=True)

    chosen = (sys.argv[3].split(",") if len(sys.argv) > 3
              else ["q3", "q55", "q62", "q_state_rollup", "q_having"])
    for name in chosen:
        fn = tpcds.QUERIES[name]
        entry = {}
        for run in ("cold", "warm"):
            syncs.reset_sync_count()
            t0 = time.perf_counter()
            out = fn(tables)
            # materialize EVERY result column before stopping the clock
            jax.block_until_ready([c.data for c in out.columns])
            if out.num_rows:          # tiny real readback: block_until_ready
                np.asarray(out[0].data[:1])   # is a no-op on the tunnel
            wall = time.perf_counter() - t0
            entry[f"{run}_wall_s"] = round(wall, 2)
            entry[f"{run}_syncs"] = syncs.reset_sync_count()
        entry["rows_out"] = out.num_rows
        RESULTS["queries"][name] = entry
        print(f"{name}: cold {entry['cold_wall_s']}s "
              f"({entry['cold_syncs']} syncs) -> warm "
              f"{entry['warm_wall_s']}s ({entry['warm_syncs']} syncs), "
              f"{out.num_rows} rows", flush=True)
        # flush after every query: a worker crash on a later (heavier)
        # query must not lose the measurements already taken
        with open(out_path, "w") as f:
            json.dump(RESULTS, f, indent=1)

    print("wrote", out_path, flush=True)


if __name__ == "__main__":
    main()
