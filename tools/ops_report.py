#!/usr/bin/env python
"""One-shot ops report for the serving runtime.

Renders, in one terminal page, the state an on-call operator asks for
first: live request state (queue depth, in-flight bytes, workers,
quarantine), SLO watchdog status per query class, the latency-attribution
breakdown (where a request's end-to-end time went, stage by stage), and
the flight recorder's recent ring.  Three sources, same report:

* **in-process** — ``report(sched)`` on a live ``QueryScheduler``
  (importable; what a serving harness calls on SIGUSR1 or a debug
  endpoint).
* **Prometheus scrape** — ``--url http://host:PORT/metrics`` against a
  runtime started with ``SRJT_METRICS_PORT``: renders the counter /
  gauge / histogram families (no live queue state — the scrape surface
  is the registry, not the scheduler).
* **incident snapshot** — ``ops_report.py incident-<kind>-*.json``:
  renders a flight-recorder dump cold, lifecycle events of the breaching
  request first.

Usage:
  python tools/ops_report.py <incident.json>           # post-mortem
  python tools/ops_report.py --url http://host:9f/metrics   # live scrape
"""

from __future__ import annotations

import json
import sys

STAGES = ("queue", "coalesce", "admission", "dispatch", "ready")


def _fmt_bytes(n) -> str:
    if n is None:
        return "unlimited"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f} {unit}" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024
    return f"{n:.1f} GiB"


def _attribution_lines(hists: dict) -> list[str]:
    """The stage-attribution table from ``exec.stage.*_ms`` histograms
    (metrics-snapshot dict shape: count/total/min/max)."""
    rows = []
    for st in STAGES:
        h = hists.get(f"exec.stage.{st}_ms")
        if h and h.get("count"):
            rows.append((st, h["count"], h["total"] / h["count"], h["max"]))
    if not rows:
        return ["  (no exec.stage.* observations)"]
    total_mean = sum(r[2] for r in rows)
    out = [f"  {'stage':<10} {'count':>7} {'mean ms':>10} "
           f"{'max ms':>10} {'share':>7}"]
    for st, cnt, mean, mx in rows:
        share = mean / total_mean * 100 if total_mean else 0.0
        out.append(f"  {st:<10} {cnt:>7} {mean:>10.3f} {mx:>10.3f} "
                   f"{share:>6.1f}%")
    return out


# byte-path ingest counters (round 6): where scan bytes went before any
# query ran — slab staging, fused row filter, pallas kernel dispatch
_INGEST = (
    ("parquet.stage.slab_bytes", "slab bytes shipped", _fmt_bytes),
    ("parquet.stage.transfers", "slab transfers", None),
    ("parquet.stage.overlap_ms", "walk/stage overlap ms", None),
    ("parquet.scan.donated_bytes", "decode-donated bytes", _fmt_bytes),
    ("parquet.rowfilter.fused_scans", "fused-filter scans", None),
    ("parquet.rowfilter.rows_kept", "fused-filter rows kept", None),
    ("rowconv.pallas.hits", "pallas kernel hits", None),
    ("rowconv.pallas.fallbacks", "pallas lax fallbacks", None),
)


def _ingest_lines(counters: dict, events: list) -> list[str]:
    """Ingest attribution: the staging-tier counters, plus the per-load
    deltas the prefetcher stamped on its ``exec.prefetch.ingest`` events
    (how much of each prefetch load was byte-path work)."""
    out = []
    for name, label, fmt in _INGEST:
        v = counters.get(name)
        if v:
            out.append(f"  {label:<26} {fmt(v) if fmt else f'{v:.0f}'}")
    for ev in [e for e in events
               if e.get("kind") == "exec.prefetch.ingest"][-5:]:
        out.append(
            f"  prefetch[{ev.get('key')}]: "
            f"{_fmt_bytes(ev.get('slab_bytes', 0))} staged in "
            f"{ev.get('transfers', 0):.0f} transfers, "
            f"overlap {ev.get('overlap_ms', 0):.0f} ms")
    return out or ["  (no byte-path ingest activity recorded)"]


def _ledger_lines(ledger: dict) -> list[str]:
    """The compile-cost ledger table: per plan fingerprint, where the
    compile budget went (capture/trace ms, recompiles, cache hits), with
    cold/warm attribution — cold = the one-time capture + trace +
    first-dispatch cost this process paid, rehydrates = plans adopted
    from the AOT artifact store (``exec/artifacts.py``) whose capture
    cost was paid by an EARLIER process instead."""
    if not ledger:
        return ["  (no compiled plans this process)"]
    out = []
    for plan in sorted(ledger):
        e = ledger[plan]
        traces = e.get("traces", 0)
        cold_ms = (e.get("capture_ms", 0) + e.get("trace_ms", 0)
                   + e.get("first_dispatch_ms", 0))
        out.append(
            f"  {plan}")
        out.append(
            f"    captures {e.get('captures', 0):.0f} "
            f"({e.get('capture_ms', 0):.1f} ms)  "
            f"traces {traces:.0f} ({e.get('trace_ms', 0):.1f} ms, "
            f"{max(traces - 1, 0):.0f} recompile)  "
            f"first-dispatch {e.get('first_dispatch_ms', 0):.1f} ms")
        out.append(
            f"    cold {cold_ms:.1f} ms  "
            f"rehydrates {e.get('rehydrates', 0):.0f} (AOT, zero-capture)  "
            f"warm runs {e.get('runs', 0):.0f}")
        out.append(
            f"    cache hit/size/miss "
            f"{e.get('cache_hits', 0):.0f}/"
            f"{e.get('cache_size_hits', 0):.0f}/"
            f"{e.get('cache_misses', 0):.0f}")
    return out


def _profile_lines(last: int = 3) -> list[str]:
    """Recent query profiles (``plan/profile.py`` retention ring): the
    top self-time nodes of each, one line per node."""
    from spark_rapids_jni_tpu.plan import profile
    profs = profile.completed(last=last)
    if not profs:
        return ["  (no completed query profiles — run with SRJT_PROFILE=1)"]
    out = []
    for p in profs:
        mis = len(p.mispredictions())
        out.append(f"  {p.name}: wall {p.wall_ms:.1f} ms, "
                   f"{sum(1 for _ in p.nodes())} nodes, "
                   f"{mis} mispredicted")
        top = sorted(p.nodes(), key=lambda n: -n.self_ms())[:4]
        for n in top:
            flag = "  MISPREDICT" if n.mispredicted() else ""
            out.append(f"    {n.self_ms():>8.2f} ms  rows={n.out_rows}  "
                       f"{n.line}{flag}")
    return out


def _probe_profile_lines(v) -> list[str]:
    """Render the ``plan.active_profile`` flight probe: per stuck thread,
    the open node stack (innermost last) of the in-flight query."""
    out = []
    for tid, prof in sorted((v or {}).items()):
        out.append(f"    thread {tid}: {prof.get('name')} "
                   f"({len(prof.get('nodes') or [])} nodes closed)")
        for line in prof.get("open") or []:
            out.append(f"      open: {line}")
    return out


def _slo_lines(slo: dict) -> list[str]:
    th = slo.get("thresholds") or {}
    if not th:
        return ["  (no SLO objectives configured — set SRJT_SLO_* )"]
    out = [f"  objectives: {th}  window: {slo.get('window_s')}s"]
    for cls, st in sorted((slo.get("classes") or {}).items()):
        if st is None:
            out.append(f"  {cls:<12} (below min window population)")
            continue
        mark = "BREACHED" if st.get("breached") else "ok"
        out.append(
            f"  {cls:<12} n={st['n']:<5} p50={st['p50_ms']:.1f}ms "
            f"p95={st['p95_ms']:.1f}ms p99={st['p99_ms']:.1f}ms "
            f"err={st['error_rate']:.3f} degr={st['degrade_rate']:.3f} "
            f"[{mark}]")
        for obj, v in (st.get("objectives") or {}).items():
            if v.get("breached"):
                out.append(f"      !! {obj}: observed {v['observed']} "
                           f"> limit {v['limit']}")
    return out


def report(sched) -> str:
    """The live report for an in-process ``QueryScheduler``."""
    from spark_rapids_jni_tpu.utils import flight, metrics
    st = sched.ops_state()
    snap = metrics.snapshot()
    lines = ["== serving state =="]
    lines.append(
        f"  queue depth {st['queue_depth']}  workers {st['workers']}  "
        f"in-flight {_fmt_bytes(st['inflight_bytes'])} / "
        f"{_fmt_bytes(st['inflight_cap'])}  "
        f"quarantined {st['quarantined']}")
    pc = st["plan_cache"]
    lines.append(
        f"  plan cache: {pc['entries']}/{pc['cap']} entries, "
        f"hit {pc['hit']:.0f} miss {pc['miss']:.0f} "
        f"size_hit {pc['size_hit']:.0f} stale {pc['stale']:.0f}")
    lines.append("== SLO watchdog ==")
    lines.extend(_slo_lines(st["slo"]))
    lines.append("== latency attribution ==")
    lines.extend(_attribution_lines(snap["histograms"]))
    lines.append("== ingest attribution ==")
    lines.extend(_ingest_lines(snap.get("counters") or {}, flight.events()))
    lines.append("== compile ledger ==")
    lines.extend(_ledger_lines(snap.get("ledger") or {}))
    lines.append("== query profiles ==")
    lines.extend(_profile_lines())
    lines.append("== flight ring (newest last) ==")
    for ev in flight.events(last=15):
        extra = {k: v for k, v in ev.items()
                 if k not in ("ts", "tid", "kind")}
        lines.append(f"  {ev['kind']:<24} {extra}")
    return "\n".join(lines)


def report_incident(path: str) -> str:
    """Render an incident snapshot file: the breaching request's own
    lifecycle first, then the serving state the snapshot froze."""
    with open(path) as f:
        snap = json.load(f)
    rid = snap.get("request_id")
    batch = snap.get("batch") or []
    lines = [f"== incident: {snap.get('kind')} ==",
             f"  request {rid}  batch {batch or '-'}",
             f"  fields: {snap.get('fields')}"]
    evs = snap.get("events") or []
    mine = [e for e in evs
            if rid and (e.get("rid") == rid or rid in (e.get("batch") or ()))]
    lines.append(f"== lifecycle of {rid} "
                 f"({len(mine)} of {len(evs)} ring events) ==")
    for ev in mine or evs[-15:]:
        extra = {k: v for k, v in ev.items()
                 if k not in ("ts", "tid", "kind")}
        lines.append(f"  {ev['kind']:<24} {extra}")
    probes = snap.get("probes") or {}
    if probes:
        lines.append("== probes at incident time ==")
        for k, v in sorted(probes.items()):
            if k == "plan.active_profile" and isinstance(v, dict):
                lines.append(f"  {k}: (in-flight node profiles)")
                lines.extend(_probe_profile_lines(v))
            else:
                lines.append(f"  {k}: {v}")
    hists = (snap.get("metrics") or {}).get("histograms") or {}
    lines.append("== latency attribution ==")
    lines.extend(_attribution_lines(hists))
    lines.append("== ingest attribution ==")
    lines.extend(_ingest_lines(
        (snap.get("metrics") or {}).get("counters") or {}, evs))
    ledger = (snap.get("metrics") or {}).get("ledger")
    if ledger:
        lines.append("== compile ledger ==")
        lines.extend(_ledger_lines(ledger))
    return "\n".join(lines)


def report_scrape(url: str) -> str:
    """Render a ``/metrics`` scrape: the srjt counter/gauge/histogram
    families grouped, histogram mean from ``_sum``/``_count``."""
    from urllib.request import urlopen
    text = urlopen(url, timeout=5).read().decode()
    counters, gauges, hists = {}, {}, {}
    ledger: dict = {}
    types = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            types[name] = kind
            continue
        if not line or line.startswith("#"):
            continue
        name, _, val = line.partition(" ")
        base = name.split("{")[0]
        if base == "srjt_compile_ledger":
            # labeled family: srjt_compile_ledger{plan="...",kind="..."}
            import re
            m = re.search(r'plan="([^"]*)".*kind="([^"]*)"', name)
            if m:
                ledger.setdefault(m.group(1), {})[m.group(2)] = float(val)
            continue
        if base.endswith("_sum") and types.get(base[:-4]) == "histogram":
            hists.setdefault(base[:-4], {})["sum"] = float(val)
        elif base.endswith("_count") and types.get(base[:-6]) == "histogram":
            hists.setdefault(base[:-6], {})["count"] = float(val)
        elif types.get(base) == "gauge":
            gauges[base] = float(val)
        elif types.get(base) == "counter":
            counters[base] = float(val)
    lines = [f"== scrape {url} ==", "== counters =="]
    for k, v in sorted(counters.items()):
        lines.append(f"  {k:<44} {v:.0f}")
    lines.append("== gauges ==")
    for k, v in sorted(gauges.items()):
        lines.append(f"  {k:<44} {v:.0f}")
    lines.append("== histograms (mean ms where applicable) ==")
    for k, h in sorted(hists.items()):
        if h.get("count"):
            lines.append(f"  {k:<44} n={h['count']:.0f} "
                         f"mean={h['sum'] / h['count']:.3f}")
    if ledger:
        lines.append("== compile ledger ==")
        lines.extend(_ledger_lines(ledger))
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    if len(argv) == 2 and argv[0] == "--url":
        print(report_scrape(argv[1]))
        return 0
    if len(argv) == 1 and not argv[0].startswith("-"):
        print(report_incident(argv[0]))
        return 0
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
