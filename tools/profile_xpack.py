#!/usr/bin/env python
"""Per-stage profile of the fused var-width engines → PROFILE_XPACK.json.

Times each stage of the to_rows xpack program (and the from_rows inverse)
in isolation at the bench geometry, with the same chained-fori-loop
differencing as bench.py, so the cost center is measurable instead of
guessed (VERDICT r4: the 12-col to_rows axis sits at ~0.64 GB/s against a
1 GB/s bar — which stage eats the 191 ms?).

Stages (to_rows):
  fixed_region   — _var_fixed_region + u8→u32 (dense fixed matrix)
  extract        — per-column extract_group_windows (char windows)
  place          — per-column funnel + _place_words + mask + OR into dense
  pack           — pack_windows (output window combine)
  full           — the whole _to_rows_x_jit (sanity: ≈ sum of stages)

Usage: python tools/profile_xpack.py [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

RESULTS = {"stages": []}


from benchmarks.measure import time_diff as _time_diff


def _chained(body, data, lo=2, hi=8, reps=2):
    return _time_diff(body, data, lo, hi, reps)


def record(name, per_s, nbytes, note=""):
    if per_s is None:
        RESULTS["stages"].append({"name": name, "error": "timing unusable"})
        print(f"  {name}: timing unusable", flush=True)
        return
    e = {"name": name, "per_iter_ms": round(per_s * 1e3, 2),
         "gbps": round(nbytes / per_s / 1e9, 3), "note": note}
    RESULTS["stages"].append(e)
    print(f"  {name}: {e['per_iter_ms']} ms  {e['gbps']} GB/s  {note}",
          flush=True)


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "PROFILE_XPACK.json"
    import bench
    from spark_rapids_jni_tpu.rowconv import xpack
    from spark_rapids_jni_tpu.rowconv.convert import _var_fixed_region
    from spark_rapids_jni_tpu.rowconv.layout import (
        compute_row_layout, row_sizes_with_strings, build_batches,
        MAX_BATCH_BYTES)
    from spark_rapids_jni_tpu.utils import hostcache

    RESULTS["backend"] = jax.default_backend()
    print("backend:", RESULTS["backend"], flush=True)

    table = bench.build_table(1_000_000, 12, string_every=3)
    layout = compute_row_layout(table.schema)
    n = table.num_rows
    var_idx = layout.variable_column_indices
    col_offs = [hostcache.host_i64(table[ci].offsets) for ci in var_idx]
    total_lens = np.zeros(n, dtype=np.int64)
    for o in col_offs:
        total_lens += o[1:] - o[:-1]
    batches = build_batches(row_sizes_with_strings(layout, total_lens),
                            MAX_BATCH_BYTES)
    offs_np = batches.row_offsets_within_batch[0]
    geom = xpack._plan_geometry(layout, n, offs_np, col_offs)
    assert geom is not None
    n_, Mw, P, nwin, total_w, g, colgeo = geom
    total_b = total_w * 4
    RESULTS["geom"] = {"n": n, "Mw": Mw, "P": P, "nwin": nwin,
                       "total_mb": round(total_b / 1e6, 1), "g": g,
                       "colgeo": [list(c) for c in colgeo]}
    print("geom:", RESULTS["geom"], flush=True)

    datas = tuple(c.data for c in table.columns)
    str_offsets = tuple(table[ci].offsets.astype(jnp.int32)
                        for ci in var_idx)
    valid = tuple(c.validity for c in table.columns)
    fpv = layout.fixed_plus_validity
    fpvw = -(-fpv // 4)

    # --- stage: fixed region ---------------------------------------------
    def fixed_stage(a):
        ds, so, va = a
        vmat = jnp.stack([jnp.ones((n,), jnp.bool_) if v is None else v
                          for v in va], axis=1)
        f2 = _var_fixed_region(layout, ds, so, vmat)
        return xpack._u8_to_u32_rows(
            jnp.pad(f2, ((0, 0), (0, fpvw * 4 - fpv))))
    per = _chained(fixed_stage, (datas, str_offsets, valid))
    record("fixed_region", per, n * fpv)

    # --- stage: char window extraction (all var cols) ---------------------
    def extract_stage(a):
        ds, so = a
        outs = []
        for vi in range(len(var_idx)):
            B, Lw = colgeo[vi]
            if Lw == 0:
                continue
            outs.append(xpack.extract_group_windows(
                ds[var_idx[vi]].reshape(-1), so[vi], n, g, B, Lw))
        return tuple(outs)
    per = _chained(extract_stage, (datas, str_offsets))
    chars_total = int(sum(col_offs[vi][-1] for vi in range(len(var_idx))))
    record("extract_windows", per, chars_total)

    # --- stage: per-column place into dense -------------------------------
    def place_stage(a):
        ds, so = a
        lens = jnp.stack([so[vi][1:] - so[vi][:-1]
                          for vi in range(len(var_idx))],
                         axis=1).astype(jnp.int32)
        prefix = jnp.cumsum(lens, axis=1) - lens
        dense = jnp.zeros((n, Mw), jnp.uint32)
        for vi in range(len(var_idx)):
            B, Lw = colgeo[vi]
            if Lw == 0:
                continue
            win = xpack.extract_group_windows(
                ds[var_idx[vi]].reshape(-1), so[vi], n, g, B, Lw)
            start_b = fpv + prefix[:, vi]
            a2 = jnp.pad(win, ((0, 0), (0, 1)))
            prev = jnp.pad(win, ((0, 0), (1, 0)))
            rb = (start_b % 4).astype(jnp.uint32)[:, None]
            fun = a2
            for k in (1, 2, 3):
                v = ((a2 << jnp.uint32(8 * k))
                     | (prev >> jnp.uint32(32 - 8 * k)))
                fun = jnp.where(rb == k, v, fun)
            placed = xpack._place_words(fun, start_b // 4, Mw)
            mask = xpack._byte_mask(Mw, start_b, start_b + lens[:, vi])
            dense = dense | (placed & mask)
        return dense
    per_place = _chained(place_stage, (datas, str_offsets))
    record("extract+place", per_place, chars_total,
           "includes extract (subtract extract_windows for place alone)")

    # --- stage: pack_windows ----------------------------------------------
    lens_np = np.stack([o[1:] - o[:-1] for o in col_offs], axis=1)
    row_b_np = fpv + lens_np.sum(axis=1)
    rs_w = ((row_b_np + 7) // 8 * 8) // 4
    dst_w_np = np.concatenate([[0], np.cumsum(rs_w)]).astype(np.int32)
    dense0 = jnp.asarray(
        np.random.default_rng(0).integers(0, 2**32, (n, Mw),
                                          dtype=np.uint32))
    dst_w = jnp.asarray(dst_w_np)

    def pack_stage(a):
        d, dw = a
        return xpack.pack_windows(d, dw, total_w, P, nwin)
    per_pack = _chained(pack_stage, (dense0, dst_w))
    record("pack_windows", per_pack, total_b)

    # --- pallas kernel entries (round 6) -----------------------------------
    # Each knobbed Mosaic kernel at the SAME geometry as its lax stage
    # above, so before/after is a same-row comparison.  Off-knob and
    # geometry-fallback cases record a skip marker instead of a number —
    # the JSON documents the fallback ladder, never fakes a kernel time.
    from spark_rapids_jni_tpu.rowconv import xpallas

    def pallas_entry(name, knob, fn, nbytes):
        m = xpallas.mode(knob)
        if m == "off":
            RESULTS["stages"].append({"name": name,
                                      "skipped": f"{knob} off"})
            print(f"  {name}: skipped ({knob} off)", flush=True)
            return
        out = fn()                                   # warm / envelope check
        if out is None:
            RESULTS["stages"].append({"name": name,
                                      "skipped": "geometry fallback"})
            print(f"  {name}: geometry outside kernel envelope", flush=True)
            return
        jax.block_until_ready(out)
        reps = 2 if m == "interpret" else 10
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(fn())
        per = (time.perf_counter() - t0) / reps
        record(name, per, nbytes, f"mode={m}")

    pallas_entry("pallas.pack_windows", "SRJT_PALLAS_PACKWIN",
                 lambda: xpallas.try_pack_windows(dense0, dst_w, total_w,
                                                  P, nwin), total_b)
    off0 = col_offs[0]
    _B0, Lw0 = colgeo[0]
    if Lw0:
        pallas_entry("pallas.extract_rows", "SRJT_PALLAS_EXTRACT",
                     lambda: xpallas.try_extract_rows(
                         datas[var_idx[0]].reshape(-1), off0, Lw0 * 4),
                     int(off0[-1]))
    rng0 = np.random.default_rng(7)
    u8len = -(n * fpv) // 2048 * -2048
    flat_u8 = jnp.asarray(rng0.integers(0, 256, u8len, dtype=np.int64)
                          .astype(np.uint8))
    pallas_entry("pallas.u8_to_u32", "SRJT_PALLAS_TRANSPOSE",
                 lambda: xpallas.try_u8_to_u32(flat_u8), u8len)
    Dn, Wd = 4096, 32
    mat0 = jnp.asarray(rng0.integers(0, 2**32, (Dn, Wd), dtype=np.int64)
                       .astype(np.uint32))
    idx0 = jnp.asarray(rng0.integers(0, Dn, 200_000).astype(np.int32))
    pallas_entry("pallas.gather_rows", "SRJT_PALLAS_DICT_GATHER",
                 lambda: xpallas.try_gather_rows(mat0, idx0),
                 200_000 * Wd * 4)

    # --- full program ------------------------------------------------------
    def full(a):
        ds, so, va = a
        return xpack._to_rows_x_jit(layout, geom, ds, so, va)
    per_full = _chained(full, (datas, str_offsets, valid))
    record("full_to_rows", per_full, total_b)

    # --- from_rows inverse -------------------------------------------------
    from spark_rapids_jni_tpu import convert_to_rows
    b = convert_to_rows(table)[0]
    words = xpack.batch_words(b)
    fgeom = xpack.plan_from_rows(layout, b, words)
    if fgeom is not None:
        fn_, fMw, fg, fBw, fcolgeo = fgeom
        RESULTS["from_geom"] = {"Mw": fMw, "g": fg, "Bw": fBw,
                                "colgeo": [list(c) for c in fcolgeo]}

        def extract_rows_stage(a):
            w, o = a
            return xpack._extract_row_windows(w, o, fn_, fg, fBw, fMw)
        per = _chained(extract_rows_stage, (words, b.offsets))
        record("from.extract_rows", per, total_b)

        def from_full(a):
            w, o = a
            return xpack._from_rows_x_jit(layout, fgeom, w, o)
        per = _chained(from_full, (words, b.offsets))
        record("from.full", per, total_b)

    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
