#!/usr/bin/env python
"""Parquet → gradient-step throughput for the ml/ handoff → ML_BENCH.json.

Two pipelines over the same synthetic parquet file, same model, same batch
schedule:

  device — ``parquet/`` device scan → ``FeatureSpec.pack`` (JCUDF row
           stream reinterpretation, dict-string categoricals stay codes)
           → ``BatchPipeline`` device shuffle → fused-``lax.scan`` epochs
           (ONE dispatch per epoch, zero steady-state host syncs);
  host   — pyarrow decode → pandas/numpy feature pack (the differential
           oracle) → python minibatch loop over numpy SGD steps (the
           classic "pull the query result to the host and train there").

The features must be BIT-IDENTICAL across the two pipelines (the oracle is
the same contract ``tests/test_ml.py`` pins); throughput is end-to-end
rows/s from parquet bytes to the last gradient step.  The premerge gate
expects ``speedup_vs_host ≥ 3`` on CPU CI.

Usage: python tools/ml_bench.py [n_rows] [out.json]
"""

import io
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax

jax.config.update("jax_compilation_cache_dir", ".jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

EPOCHS = 12
BATCH = 32
N_CATS = 8                       # dict-encoded string features (the usual
MOMENTUM = 0.9                   # fraud/ads feature-table shape)
SEED = 17


def gen_parquet(n: int, seed: int = SEED) -> bytes:
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    cols = {}
    for i in range(6):
        cols[f"num{i}"] = rng.normal(size=n)
    for i in range(4):
        cols[f"int{i}"] = rng.integers(-500, 500, n)
    nullable = rng.integers(0, 100, n)
    mask = rng.random(n) < 0.15
    cols["amount"] = pa.array(np.where(mask, 0, nullable),
                              mask=mask, type=pa.int64())
    for i in range(N_CATS):
        vocab = [f"c{i}_{v:03d}" for v in range(16 + 8 * i)]
        cols[f"cat{i}"] = pa.array([vocab[j] for j in rng.integers(
            0, len(vocab), n)]).dictionary_encode()
    z = cols["num0"] - 0.5 * cols["num1"] + 0.01 * cols["int0"]
    cols["label"] = (z + rng.normal(size=n) * 0.3 > 0).astype(np.int64)
    buf = io.BytesIO()
    pq.write_table(pa.table(cols), buf, compression="SNAPPY")
    return buf.getvalue()


NUMERIC = [f"num{i}" for i in range(6)] + [f"int{i}" for i in range(4)]
CATEGORICAL = [f"cat{i}" for i in range(N_CATS)]
FEATURES = NUMERIC + ["amount"] + CATEGORICAL


def host_features(blob: bytes):
    """The numpy oracle: same lane contract as FeatureSpec.pack."""
    import pyarrow.parquet as pq
    tab = pq.read_table(io.BytesIO(blob))
    lanes = []
    for name in NUMERIC:
        lanes.append(np.asarray(tab[name]).astype(np.float32))
    amt = tab["amount"].to_pandas()
    vals = amt.to_numpy(dtype=np.float64, na_value=np.nan)
    valid = ~np.isnan(vals)
    mean = np.float32(vals[valid].sum() / valid.sum())
    lanes.append(np.where(valid, vals.astype(np.float32), mean))
    for name in CATEGORICAL:
        strs = [str(v) for v in tab[name].to_pylist()]
        rank = {v: i for i, v in enumerate(sorted(set(strs)))}
        lanes.append(np.array([rank[v] for v in strs], np.float32))
    X = np.stack(lanes, axis=1)
    y = np.asarray(tab["label"]).astype(np.float32)
    return X, y


def host_train(X, y, epochs: int, batch: int, lr=1e-4, momentum=MOMENTUM):
    """The host-loop baseline: per-epoch numpy shuffle + momentum-SGD
    minibatches — the same math the device trainer runs."""
    rng = np.random.default_rng(SEED)
    n, k = X.shape
    nb = n // batch
    w = np.zeros(k, np.float32)
    b = np.float32(0.0)
    vw = np.zeros(k, np.float32)
    vb = np.float32(0.0)
    lr, mu = np.float32(lr), np.float32(momentum)
    for _ in range(epochs):
        perm = rng.permutation(n)[:nb * batch]
        Xs = X[perm].reshape(nb, batch, k)
        ys = y[perm].reshape(nb, batch)
        for i in range(nb):
            xb, yb = Xs[i], ys[i]
            z = xb @ w + b
            with np.errstate(over="ignore"):        # exp(-z) → inf ⇒ p = 0
                p = np.float32(1.0) / (np.float32(1.0) + np.exp(-z))
            g = (p - yb) / np.float32(batch)
            vw = mu * vw + xb.T @ g
            vb = mu * vb + g.sum(dtype=np.float32)
            w = w - lr * vw
            b = b - lr * vb
    return w, b


def main():
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 80000
    out_path = sys.argv[2] if len(sys.argv) > 2 else "ML_BENCH.json"
    print(f"backend: {jax.default_backend()}  n_rows: {n_rows}", flush=True)

    from spark_rapids_jni_tpu import ml
    from spark_rapids_jni_tpu.ml import features as F
    from spark_rapids_jni_tpu.parquet import device_scan as decode
    from spark_rapids_jni_tpu.utils import syncs

    blob = gen_parquet(n_rows)
    res = {"n_rows": n_rows, "epochs": EPOCHS, "batch": BATCH,
           "parquet_bytes": len(blob)}

    spec = F.FeatureSpec.of(
        [F.Feature(c) for c in NUMERIC]
        + [F.Feature("amount", impute="mean")]
        + [F.Feature(c) for c in CATEGORICAL],
        label="label", label_transform=("gt", 0.0))
    names = FEATURES + ["label"]

    # --- device pipeline: parquet → pack → fused epochs --------------------
    # cold pass: parquet decode + pack + warm epoch all compile here (the
    # persistent .jax_cache amortizes this across runs, mirroring how the
    # mortgage bench reports cold vs steady)
    t0 = time.perf_counter()
    tbl = decode.read_table(blob, columns=names)
    fb = spec.pack(tbl, names)
    fb.X.block_until_ready()
    res["decode_pack_cold_s"] = round(time.perf_counter() - t0, 3)
    pipe = ml.BatchPipeline(fb, batch_size=BATCH, seed=SEED)
    tr = ml.Trainer(ml.logistic_regression(),
                    ml.sgd(lr=1e-4, momentum=MOMENTUM))
    params, ostate = tr.init(pipe.k)
    t0 = time.perf_counter()
    Xb, yb = pipe.epoch_arrays(0)               # warm epoch: compiles
    params, ostate, loss = tr.run_epoch(params, ostate, Xb, yb)
    loss.block_until_ready()
    res["train_cold_s"] = round(time.perf_counter() - t0, 3)

    # steady end-to-end pass: fresh decode → pack → EPOCHS fused epochs,
    # exactly the recurring-training-job path
    t0 = time.perf_counter()
    tbl = decode.read_table(blob, columns=names)
    fb = spec.pack(tbl, names)
    fb.X.block_until_ready()
    decode_pack_s = time.perf_counter() - t0
    res["decode_pack_s"] = round(decode_pack_s, 3)
    pipe = ml.BatchPipeline(fb, batch_size=BATCH, seed=SEED)
    # warm the fresh pipeline's shuffle program (identical shape → persistent
    # cache hit); the recurring job reuses compiled programs, so compile time
    # belongs in the cold numbers, not the steady pass
    wp, wo = tr.init(pipe.k)
    Xb, yb = pipe.epoch_arrays(0)
    jax.block_until_ready(tr.run_epoch(wp, wo, Xb, yb))
    params, ostate = tr.init(pipe.k)
    syncs.reset_sync_count()
    t0 = time.perf_counter()
    for e in range(EPOCHS):
        Xb, yb = pipe.epoch_arrays(e)
        params, ostate, loss = tr.run_epoch(params, ostate, Xb, yb)
    steady_syncs = syncs.sync_count()
    loss.block_until_ready()
    steady_s = time.perf_counter() - t0
    res["steady_syncs"] = steady_syncs
    res["train_steady_s"] = round(steady_s, 3)
    res["final_loss"] = round(float(loss), 5)
    dev_e2e = decode_pack_s + steady_s
    res["device_rows_per_s"] = round(pipe.rows_per_epoch * EPOCHS / dev_e2e)
    print(f"device: decode+pack {res['decode_pack_s']}s (cold "
          f"{res['decode_pack_cold_s']}s)  steady {res['train_steady_s']}s  "
          f"syncs={steady_syncs}  {res['device_rows_per_s']} rows/s",
          flush=True)

    # --- host baseline ------------------------------------------------------
    t0 = time.perf_counter()
    hX, hy = host_features(blob)
    res["host_decode_pack_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    host_train(hX, hy, EPOCHS, BATCH)
    host_train_s = time.perf_counter() - t0
    res["host_train_s"] = round(host_train_s, 3)
    host_e2e = res["host_decode_pack_s"] + host_train_s
    res["host_rows_per_s"] = round(
        (hX.shape[0] // BATCH) * BATCH * EPOCHS / host_e2e)
    res["speedup_vs_host"] = round(
        res["device_rows_per_s"] / res["host_rows_per_s"], 2)
    print(f"host: decode+pack {res['host_decode_pack_s']}s  train "
          f"{res['host_train_s']}s  {res['host_rows_per_s']} rows/s  "
          f"speedup {res['speedup_vs_host']}x", flush=True)

    # --- bit-identity gate --------------------------------------------------
    res["features_bit_identical"] = bool(
        np.array_equal(np.asarray(fb.X), hX)
        and np.array_equal(np.asarray(fb.y),
                           (hy > 0).astype(np.float32)))
    print(f"features bit-identical: {res['features_bit_identical']}",
          flush=True)

    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
    print("wrote", out_path, flush=True)
    if not res["features_bit_identical"]:
        sys.exit(1)
    if res["steady_syncs"] != 0:
        sys.exit(1)


if __name__ == "__main__":
    main()
