#!/usr/bin/env python
"""SF-scale TPC-H q6 scan benchmark → SCAN_BENCH.json (BASELINE config #2).

Generates an SF1-class lineitem (6M rows, the four q6 columns) as a Snappy
parquet file, then measures each stage of the scan separately:

  stage 1 (host): footer parse + page walk + native-snappy decompression +
                  payload concatenation (wall-clock)
  stage 2 (H2D):  raw payload upload through the tunnel (wall-clock)
  stage 3 (chip): jitted decode (PLAIN bitcast + f64 bit pairs) + the fused
                  q6 predicate/aggregate — steady-state device time via
                  trip-count differencing (the BASELINE "GB/s columnar scan
                  per chip" metric)

Correctness is asserted against numpy computing q6 on the raw generator
arrays before any timing is recorded.

Usage: python tools/scan_bench.py [n_rows] [out.json]
"""

import io
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

RESULTS = {}


def make_lineitem_sf(n: int, seed: int = 3):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    epoch94 = 8766
    qty = rng.integers(1, 51, n).astype(np.int64)
    price = (rng.random(n) * 100000).round(2)
    disc = rng.integers(0, 11, n).astype(np.float64) / 100.0
    ship = rng.integers(epoch94 - 400, epoch94 + 800, n).astype(np.int32)
    t = pa.table({
        "l_quantity": pa.array(qty),
        "l_extendedprice": pa.array(price),
        "l_discount": pa.array(disc),
        "l_shipdate": pa.array(ship, pa.int32()),
    })
    buf = io.BytesIO()
    pq.write_table(t, buf, compression="SNAPPY", use_dictionary=False,
                   row_group_size=1 << 20)
    return buf.getvalue(), (qty, price, disc, ship)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 6_000_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else "SCAN_BENCH.json"
    print(f"backend: {jax.default_backend()}  rows: {n}", flush=True)
    RESULTS["backend"] = jax.default_backend()

    t0 = time.perf_counter()
    raw, (qty, price, disc, ship) = make_lineitem_sf(n)
    print(f"generated {len(raw)/1e6:.1f} MB parquet in "
          f"{time.perf_counter()-t0:.1f}s", flush=True)
    col_bytes = n * (8 + 8 + 8 + 4)
    RESULTS.update(rows=n, parquet_mb=round(len(raw) / 1e6, 1),
                   column_bytes=col_bytes)

    from spark_rapids_jni_tpu.parquet import decode as D
    from spark_rapids_jni_tpu.parquet import device_scan as DS
    from spark_rapids_jni_tpu.models.q6 import COLUMNS

    # stage 1: host staging — raw payload walk only (no decode, no upload)
    meta = DS.parse_struct(DS.extract_footer_bytes(raw))
    leaves = D._leaf_schema_elements(meta)
    names = [l.name for l in leaves]
    want = [names.index(c) for c in COLUMNS]
    groups = meta.get(D.FMD.ROW_GROUPS)
    chunk_lists = {i: [] for i in want}
    for rg in groups.values:
        chunks = rg.get(D.RG.COLUMNS).values
        for i in want:
            chunk_lists[i].append(chunks[i])
    t0 = time.perf_counter()
    parts = {}
    for i in want:
        ps = [DS._walk_chunk_raw(raw, c, leaves[i].max_def,
                                 leaves[i].max_rep)
              for c in chunk_lists[i]]
        assert all(p is not None and p[0] == "plain" for p in ps), \
            "expected the PLAIN fast path"
        parts[i] = b"".join(p[3] for p in ps)
    host_s = time.perf_counter() - t0
    staged_mb = sum(len(v) for v in parts.values()) / 1e6
    RESULTS["host_staging_s"] = round(host_s, 3)
    RESULTS["host_staging_gbps"] = round(staged_mb / 1e3 / host_s, 3)
    print(f"host staging (footer+snappy+concat): {host_s:.2f}s "
          f"({staged_mb/1e3/host_s:.2f} GB/s)", flush=True)

    # stage 2: upload (as u32 words — the free host view, round 5)
    t0 = time.perf_counter()
    raws = {i: jnp.asarray(np.frombuffer(parts[i], np.uint32))
            for i in want}
    for v in raws.values():
        v.block_until_ready()
    # force materialization with a tiny readback (block_until_ready is a
    # no-op on the tunneled backend)
    _ = [np.asarray(v[:1]) for v in raws.values()]
    h2d_s = time.perf_counter() - t0
    RESULTS["h2d_s"] = round(h2d_s, 3)
    RESULTS["h2d_gbps"] = round(staged_mb / 1e3 / h2d_s, 3)
    print(f"H2D upload: {h2d_s:.2f}s ({staged_mb/1e3/h2d_s:.2f} GB/s)",
          flush=True)

    # stage 2b: coalesced slab staging (round 6, SRJT_STAGE_SLABS) —
    # same payloads, but queued into per-dtype slabs and shipped with ONE
    # device_put per slab instead of one transfer per column.  The
    # before/after pair (h2d_gbps vs h2d_staged_gbps) is the tentpole's
    # upload metric.
    from spark_rapids_jni_tpu.parquet import staging
    t0 = time.perf_counter()
    stager = staging.SlabStager()
    handles = {i: staging.asarray(np.frombuffer(parts[i], np.uint32),
                                  stager) for i in want}
    stager.flush()
    staged_vals = {i: h.get() for i, h in handles.items()}
    _ = [np.asarray(v[:1]) for v in staged_vals.values()]
    slab_s = time.perf_counter() - t0
    RESULTS["h2d_staged_s"] = round(slab_s, 3)
    RESULTS["h2d_staged_gbps"] = round(staged_mb / 1e3 / slab_s, 3)
    print(f"H2D staged (slab-coalesced): {slab_s:.2f}s "
          f"({staged_mb/1e3/slab_s:.2f} GB/s)", flush=True)
    for v in staged_vals.values():
        v.delete()

    # stage 3: on-chip decode + q6, trip-count differenced
    from spark_rapids_jni_tpu.utils import f64bits
    phys_of = {i: D.PT_INT64 if leaves[i].name == "l_quantity"
               else D.PT_INT32 if leaves[i].name == "l_shipdate"
               else D.PT_DOUBLE for i in want}
    lo, hi = 8766, 8766 + 365

    def body(bufs):
        # the production decode path (_device_plain): wide-block strided
        # u8→u32 — the narrow-minor [k,w] bitcast this replaced relayouts
        # at ~3 GB/s on TPU and was the round-3/4 scan bottleneck
        qraw, praw, draw, sraw = bufs
        q = DS._device_plain_w(D.PT_INT64, qraw, None)
        pbits = DS._device_plain_w(D.PT_DOUBLE, praw, None)  # u32 [n, 2]
        dbits = DS._device_plain_w(D.PT_DOUBLE, draw, None)
        s = DS._device_plain_w(D.PT_INT32, sraw, None)
        ep = f64bits.from_bits(pbits)
        disc_v = f64bits.from_bits(dbits)
        mask = ((s >= lo) & (s < hi)
                & (disc_v >= 0.05 - 1e-9) & (disc_v <= 0.07 + 1e-9)
                & (q < 24))
        rev = jnp.where(mask, ep * disc_v, 0.0)
        return jnp.sum(rev, dtype=jnp.float64), jnp.sum(mask,
                                                        dtype=jnp.int64)

    bufs = tuple(raws[i] for i in want)

    # correctness first
    rev, matched = jax.jit(body)(bufs)
    m = ((ship >= lo) & (ship < hi) & (disc >= 0.05 - 1e-9)
         & (disc <= 0.07 + 1e-9) & (qty < 24))
    expect = float((price[m] * disc[m]).sum())
    ok = (int(matched) == int(m.sum())
          and abs(float(rev) - expect) <= 1e-6 * max(abs(expect), 1))
    RESULTS["q6_correct"] = bool(ok)
    print(f"q6 on-chip correct: {ok} (matched {int(matched)})", flush=True)

    @jax.jit
    def loop(bufs, iters):
        def step(_, carry):
            acc, bs = carry
            bs2 = jax.lax.optimization_barrier((bs, acc))[0]
            rev, cnt = body(bs2)
            probe = jax.lax.convert_element_type(cnt, jnp.int32)
            return (acc + probe) % jnp.int32(65521), bs
        acc, _ = jax.lax.fori_loop(0, iters, step, (jnp.int32(0), bufs))
        return acc

    np.asarray(loop(bufs, 2))
    times = {}
    for it in (2, 12):
        t0 = time.perf_counter()
        np.asarray(loop(bufs, it))
        times[it] = time.perf_counter() - t0
    per = max((times[12] - times[2]) / 10, 1e-9)
    gbps = col_bytes / per / 1e9
    RESULTS["device_scan_ms"] = round(per * 1e3, 2)
    RESULTS["device_scan_gbps"] = round(gbps, 2)
    print(f"on-chip decode+q6: {per*1e3:.2f} ms/scan -> {gbps:.2f} GB/s",
          flush=True)

    # decode stage alone — the BASELINE "GB/s columnar scan per chip"
    # figure (the reference's analog is libcudf page decode, not decode
    # fused with a query)
    from benchmarks.measure import time_diff as _td

    def decode_only(bufs):
        return tuple(DS._device_plain_w(phys_of[i], b, None)
                     for i, b in zip(want, bufs))
    per_d = _td(decode_only, bufs, 2, 12)
    if per_d is not None:
        RESULTS["device_decode_ms"] = round(per_d * 1e3, 2)
        RESULTS["device_decode_gbps"] = round(col_bytes / per_d / 1e9, 2)
        print(f"on-chip decode stage: {per_d*1e3:.2f} ms -> "
              f"{col_bytes/per_d/1e9:.2f} GB/s "
              "(BASELINE 'columnar scan per chip')", flush=True)

    # dictionary-string column decode (round 5): the most common real-
    # world string encoding, decoded fully on device (_scan_dict_str)
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq
        rng = np.random.default_rng(11)
        nd = 2_000_000
        words = [f"category-{i:04d}" for i in range(4000)]
        svals = [words[j] for j in rng.integers(0, len(words), nd)]
        tb = pa.table({"s": pa.array(svals, pa.string())})
        bio = io.BytesIO()
        pq.write_table(tb, bio, compression="SNAPPY", use_dictionary=True)
        draw_pq = bio.getvalue()
        col = DS.scan_table(draw_pq).columns[0]      # warm/compile
        np.asarray(col.data[:1])
        t0 = time.perf_counter()
        col = DS.scan_table(draw_pq).columns[0]
        np.asarray(col.data[:1])
        dwall = time.perf_counter() - t0
        total_chars = int(np.asarray(col.offsets[-1]))
        ok3 = col.to_pylist()[:2] == svals[:2]
        RESULTS["dict_str_rows"] = nd
        RESULTS["dict_str_wall_s"] = round(dwall, 3)
        RESULTS["dict_str_mbps"] = round(total_chars / dwall / 1e6, 1)
        RESULTS["dict_str_correct"] = bool(ok3)
        print(f"dict-string device decode: {dwall:.2f}s wall for "
              f"{total_chars/1e6:.0f} MB chars ({nd} rows), correct: {ok3}",
              flush=True)
    except Exception as e:  # noqa: BLE001 — stage is best-effort
        RESULTS["dict_str_error"] = repr(e)[:200]

    # pipelined full scan (round 6): producer thread walks column i+1
    # while the consumer stages column i.  pipeline_occupancy = fraction
    # of the scan wall during which walk and stage genuinely overlapped
    # (pairwise span intersection, from the parquet.stage.overlap probe).
    try:
        from spark_rapids_jni_tpu.utils import flight
        was = flight.enabled()
        flight.set_enabled(True)
        flight.reset()
        t0 = time.perf_counter()
        tbl = DS.scan_table(raw)
        _ = [np.asarray(c.data[:1]) for c in tbl.columns]
        pwall = time.perf_counter() - t0
        ev = [e for e in flight.events()
              if e.get("kind") == "parquet.stage.overlap"]
        fl = [e for e in flight.events()
              if e.get("kind") == "parquet.stage.flush"]
        overlap_ms = float(ev[-1]["overlap_ms"]) if ev else 0.0
        flight.set_enabled(was)
        RESULTS["pipelined_scan_wall_s"] = round(pwall, 3)
        RESULTS["pipeline_overlap_ms"] = round(overlap_ms, 1)
        RESULTS["pipeline_occupancy"] = round(
            min(overlap_ms / 1e3 / pwall, 1.0), 3) if pwall else 0.0
        RESULTS["stage_flush_transfers"] = int(
            sum(e.get("slabs", 0) for e in fl))
        print(f"pipelined scan_table: {pwall:.2f}s wall, overlap "
              f"{overlap_ms:.0f} ms (occupancy "
              f"{RESULTS['pipeline_occupancy']:.1%}), "
              f"{RESULTS['stage_flush_transfers']} slab transfers",
              flush=True)
    except Exception as e:  # noqa: BLE001 — stage is best-effort
        RESULTS["pipeline_error"] = repr(e)[:200]

    if "--skip-e2e" not in sys.argv:
        # end-to-end wall via the public API (cold staging; first run also
        # pays ~8 min of fresh 6M-row jit compiles through the remote helper)
        from spark_rapids_jni_tpu.models import q6 as q6m
        t0 = time.perf_counter()
        rev2, m2 = q6m.run(raw, lo, hi)
        e2e = time.perf_counter() - t0
        RESULTS["end_to_end_wall_s"] = round(e2e, 2)
        RESULTS["end_to_end_mbps"] = round(col_bytes / e2e / 1e6, 2)
        ok2 = m2 == int(m.sum())
        RESULTS["q6_api_correct"] = bool(ok2)
        print(f"end-to-end q6.run: {e2e:.2f}s wall "
              f"({col_bytes/e2e/1e9:.3f} GB/s incl. host staging + upload), "
              f"correct: {ok2}", flush=True)

    with open(out_path, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print("wrote", out_path, flush=True)


if __name__ == "__main__":
    main()
