#!/usr/bin/env python
"""Probe the two primitives behind the round-4 string redesign:

(A) slab gather: gathering [n/g, g*W] slabs should cost ~24ns per GATHERED
    row (flat), i.e. ~24/g ns per logical row;
(B) per-row log-shift byte roll on [n, W] u32 should fuse to a handful of
    memory passes.

Usage: python tools/probe_slab.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax import lax


def timeit(name, fn, *args, iters=(3, 13)):
    run = jax.jit(lambda a: fn(*a))

    @jax.jit
    def loop(a, it):
        def step(_, carry):
            acc, aa = carry
            d = lax.optimization_barrier((aa, acc))[0]
            out = fn(*d)
            out = lax.optimization_barrier(out)
            probe = lax.convert_element_type(jnp.ravel(out)[0], jnp.int32)
            return (acc + probe) % jnp.int32(65521), aa
        acc, _ = lax.fori_loop(0, it, step, (jnp.int32(0), a))
        return acc
    np.asarray(loop(args, iters[0]))
    t0 = time.perf_counter(); np.asarray(loop(args, iters[0]))
    t_lo = time.perf_counter() - t0
    t0 = time.perf_counter(); np.asarray(loop(args, iters[1]))
    t_hi = time.perf_counter() - t0
    per = (t_hi - t_lo) / (iters[1] - iters[0])
    print(f"  {name}: {per*1e3:.3f} ms/iter", flush=True)
    return per


def main():
    print(f"backend: {jax.default_backend()}", flush=True)
    rng = np.random.default_rng(0)

    # (A) slab gathers at several widths, 128K gathered rows
    src = jnp.asarray(rng.integers(0, 2**32, 1 << 25, dtype=np.uint32))
    for W in (16, 64, 160, 384):
        s2 = src.reshape(-1, W)
        m = 1 << 17
        idx = jnp.asarray(np.sort(rng.integers(0, s2.shape[0] - 1, m))
                          .astype(np.int32))
        per = timeit(f"slabgather_{W}w_128K", lambda i, s=s2: s[i], idx)
        print(f"    -> {per/m*1e9:.1f} ns/gathered-row, "
              f"{m*W*4*2/per/1e9:.1f} GB/s", flush=True)

    # (B) log-shift byte roll on [1M, 40] u32 (per-row dynamic shift)
    n, W = 1 << 20, 40
    x = jnp.asarray(rng.integers(0, 2**32, (n, W), dtype=np.uint32))
    sh = jnp.asarray(rng.integers(0, W * 4, n).astype(np.int32))

    def byte_roll(x, sh):
        w = sh // 4
        out = x
        for b in range(6):                       # log2(64) word passes
            s = 1 << b
            shifted = jnp.pad(out, ((0, 0), (s, 0)))[:, :W]
            bit = ((w >> b) & 1).astype(bool)[:, None]
            out = jnp.where(bit, shifted, out)
        prev = jnp.pad(out, ((0, 0), (1, 0)))[:, :W]
        rb = (sh % 4).astype(jnp.uint32)[:, None]
        res = out
        for k in (1, 2, 3):
            v = (out << jnp.uint32(8 * k)) | (prev >> jnp.uint32(32 - 8 * k))
            res = jnp.where(rb == k, v, res)
        return res
    per = timeit("byteroll_1Mx40w", byte_roll, x, sh)
    print(f"    -> {n*W*4*2/per/1e9:.1f} GB/s effective", flush=True)

    # (B2) OR-combine of 5 placed rolls (the pack frame combine)
    nwin, F = 1 << 18, 168
    slab = jnp.asarray(rng.integers(0, 2**32, (nwin, 200), dtype=np.uint32))
    offs = jnp.asarray(rng.integers(0, 128, (nwin, 5)).astype(np.int32))

    def frame_combine(slab, offs):
        acc = jnp.zeros((nwin, F), jnp.uint32)
        for p in range(5):
            piece = jnp.pad(slab[:, p * 40:(p + 1) * 40],
                            ((0, 0), (0, F - 40)))
            w = offs[:, p]
            out = piece
            for b in range(8):
                s = 1 << b
                shifted = jnp.pad(out, ((0, 0), (s, 0)))[:, :F]
                bit = ((w >> b) & 1).astype(bool)[:, None]
                out = jnp.where(bit, shifted, out)
            acc = acc | out
        return acc
    per = timeit("framecombine_256Kx168w_P5", frame_combine, slab, offs)
    print(f"    -> {nwin*F*4/per/1e9:.1f} GB/s of output", flush=True)


if __name__ == "__main__":
    main()
