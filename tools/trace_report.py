#!/usr/bin/env python
"""Summarize a Chrome-trace JSON produced by utils/metrics.py.

Aggregates the complete ("ph": "X") span events by name into a top-N table
(call count, total/max/mean ms, sorted by total time) and prints the
``srjtCounters`` registry the exporter rides along — the terminal-side
answer to "where did this query spend its time" without opening Perfetto.

Works on any Chrome-trace file (object format with ``traceEvents`` or a
bare event array), so it also digests traces from other tools.

Usage: python tools/trace_report.py <trace.json> [top_n]
"""

from __future__ import annotations

import json
import sys


def load_events(path: str) -> tuple[list[dict], dict]:
    """→ (trace events, extras dict with srjtCounters/Gauges/Histograms)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):                 # bare event array
        return doc, {}
    events = doc.get("traceEvents", [])
    extras = {k: doc[k] for k in ("srjtCounters", "srjtGauges",
                                  "srjtHistograms") if k in doc}
    return events, extras


def summarize(events: list[dict]) -> dict[str, dict]:
    """Aggregate "X" (complete) events by name: count, total/max ms."""
    agg: dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        e = agg.setdefault(ev.get("name", "?"),
                           {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        e["count"] += 1
        e["total_ms"] += dur_ms
        e["max_ms"] = max(e["max_ms"], dur_ms)
    return agg


def render(agg: dict[str, dict], top_n: int = 20) -> str:
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["total_ms"])[:top_n]
    if not rows:
        return "(no span events)"
    w = max((len(name) for name, _ in rows), default=4)
    lines = [f"{'span':<{w}}  {'count':>6}  {'total_ms':>10}  "
             f"{'mean_ms':>9}  {'max_ms':>9}"]
    for name, e in rows:
        mean = e["total_ms"] / e["count"] if e["count"] else 0.0
        lines.append(f"{name:<{w}}  {e['count']:>6}  "
                     f"{e['total_ms']:>10.3f}  {mean:>9.3f}  "
                     f"{e['max_ms']:>9.3f}")
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    if len(argv) < 2:
        print(__doc__.strip().splitlines()[-1], file=sys.stderr)
        return 2
    path = argv[1]
    top_n = int(argv[2]) if len(argv) > 2 else 20
    events, extras = load_events(path)
    agg = summarize(events)
    print(f"{path}: {len(events)} events, {len(agg)} distinct spans")
    print(render(agg, top_n))
    counters = extras.get("srjtCounters")
    if counters:
        print("\ncounters:")
        w = max(len(k) for k in counters)
        for k in sorted(counters):
            v = counters[k]
            v = int(v) if float(v).is_integer() else v
            print(f"  {k:<{w}}  {v}")
    gauges = extras.get("srjtGauges")
    if gauges:
        print("\ngauges:")
        w = max(len(k) for k in gauges)
        for k in sorted(gauges):
            print(f"  {k:<{w}}  {gauges[k]}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
