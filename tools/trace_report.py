#!/usr/bin/env python
"""Summarize a Chrome-trace JSON produced by utils/metrics.py.

Aggregates the complete ("ph": "X") span events by name into a top-N table
(call count, total/self/max ms, sorted by SELF time) and prints the
``srjtCounters`` registry the exporter rides along — the terminal-side
answer to "where did this query spend its time" without opening Perfetto.

Spans nest (``compiled.run`` contains ``compiled.dispatch`` contains
``plan.node:*``), so the table reports both inclusive ``total_ms`` and
exclusive ``self_ms`` — self-time is computed with a per-(pid,tid) stack
sweep over the interval tree, so a join span appearing under two stages
is never double-counted against its parents.

``--by-node`` groups the per-plan-node spans (``plan.node:<Op>`` with a
``node_id`` arg, emitted while ``SRJT_PROFILE=1``) by node identity
instead of name — one row per plan node, not per op class.

Works on any Chrome-trace file (object format with ``traceEvents`` or a
bare event array), so it also digests traces from other tools.

Usage: python tools/trace_report.py <trace.json> [top_n] [--by-node]
"""

from __future__ import annotations

import json
import sys


def load_events(path: str) -> tuple[list[dict], dict]:
    """→ (trace events, extras dict with srjtCounters/Gauges/...)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):                 # bare event array
        return doc, {}
    events = doc.get("traceEvents", [])
    extras = {k: doc[k] for k in ("srjtCounters", "srjtGauges",
                                  "srjtHistograms", "srjtLedger")
              if k in doc}
    return events, extras


def self_times(events: list[dict]) -> list[float]:
    """Exclusive duration (µs) for each event, aligned by index.

    Per (pid, tid) lane: sort by (start asc, dur desc) — a parent sorts
    before the children it contains — and run an enclosing-interval
    stack.  Each event's duration is subtracted from the innermost
    enclosing event's self-time, so nested spans never double-count."""
    lanes: dict[tuple, list[int]] = {}
    for i, ev in enumerate(events):
        if ev.get("ph") != "X":
            continue
        lanes.setdefault((ev.get("pid"), ev.get("tid")), []).append(i)
    selfs = [0.0] * len(events)
    for idxs in lanes.values():
        idxs.sort(key=lambda i: (float(events[i].get("ts", 0.0)),
                                 -float(events[i].get("dur", 0.0))))
        stack: list[int] = []              # indices of open ancestors
        for i in idxs:
            ts = float(events[i].get("ts", 0.0))
            dur = float(events[i].get("dur", 0.0))
            while stack:
                p = stack[-1]
                p_end = (float(events[p].get("ts", 0.0))
                         + float(events[p].get("dur", 0.0)))
                if ts >= p_end:            # sibling, not ancestor
                    stack.pop()
                    continue
                break
            selfs[i] = dur
            if stack:
                selfs[stack[-1]] -= dur
            stack.append(i)
    return selfs


def summarize(events: list[dict], by_node: bool = False) -> dict[str, dict]:
    """Aggregate "X" (complete) events: count, total(inclusive)/self/max
    ms.  ``by_node`` keys plan-node spans by their ``node_id`` arg."""
    selfs = self_times(events)
    agg: dict[str, dict] = {}
    for i, ev in enumerate(events):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        if by_node:
            args = ev.get("args") or {}
            if not str(name).startswith("plan.node:"):
                continue
            nid = args.get("node_id")
            name = (args.get("line") or name) if nid is None else \
                f"{name} [{str(nid)[-12:]}]"
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        e = agg.setdefault(name, {"count": 0, "total_ms": 0.0,
                                  "self_ms": 0.0, "max_ms": 0.0})
        e["count"] += 1
        e["total_ms"] += dur_ms
        e["self_ms"] += max(selfs[i], 0.0) / 1e3
        e["max_ms"] = max(e["max_ms"], dur_ms)
    return agg


def render(agg: dict[str, dict], top_n: int = 20) -> str:
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["self_ms"])[:top_n]
    if not rows:
        return "(no span events)"
    w = max((len(name) for name, _ in rows), default=4)
    lines = [f"{'span':<{w}}  {'count':>6}  {'total_ms':>10}  "
             f"{'self_ms':>10}  {'mean_ms':>9}  {'max_ms':>9}"]
    for name, e in rows:
        mean = e["total_ms"] / e["count"] if e["count"] else 0.0
        lines.append(f"{name:<{w}}  {e['count']:>6}  "
                     f"{e['total_ms']:>10.3f}  {e['self_ms']:>10.3f}  "
                     f"{mean:>9.3f}  {e['max_ms']:>9.3f}")
    return "\n".join(lines)


def main(argv: list[str]) -> int:
    args = [a for a in argv[1:] if a != "--by-node"]
    by_node = "--by-node" in argv[1:]
    if not args:
        print(__doc__.strip().splitlines()[-1], file=sys.stderr)
        return 2
    path = args[0]
    top_n = int(args[1]) if len(args) > 1 else 20
    events, extras = load_events(path)
    agg = summarize(events, by_node=by_node)
    print(f"{path}: {len(events)} events, {len(agg)} distinct "
          f"{'nodes' if by_node else 'spans'}")
    print(render(agg, top_n))
    counters = extras.get("srjtCounters")
    if counters:
        print("\ncounters:")
        w = max(len(k) for k in counters)
        for k in sorted(counters):
            v = counters[k]
            v = int(v) if float(v).is_integer() else v
            print(f"  {k:<{w}}  {v}")
    gauges = extras.get("srjtGauges")
    if gauges:
        print("\ngauges:")
        w = max(len(k) for k in gauges)
        for k in sorted(gauges):
            print(f"  {k:<{w}}  {gauges[k]}")
    ledger = extras.get("srjtLedger")
    if ledger:
        print("\ncompile ledger:")
        for plan in sorted(ledger):
            ent = ledger[plan]
            body = "  ".join(f"{k}={ent[k]:g}" for k in sorted(ent))
            print(f"  {plan}: {body}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
