#!/usr/bin/env python
"""Dictionary-string fast path benchmark → DICT_BENCH.json.

Three axes, each timed on the SAME dictionary-encoded parquet bytes with
the fast path on (``DictColumn`` codes flow through the ops) vs off
(``SRJT_DICT_STRINGS=0`` — the scan materializes bytes, today's baseline
path), results asserted bit-identical before any timing is recorded:

* **queries** — ``q_like_brands`` (LIKE/substring over a wide item
  dimension) and ``q_isin_states`` (IN-list over stores): dictionary-aware
  predicates evaluate once per dictionary entry instead of once per row;
* **string groupby** — 1M-row low-cardinality string key: keys group by
  code rank, never touching bytes;
* **rowconv** — the BENCH_r05 ``strings_mixed12_1M`` to_rows shape with
  its string columns dictionary-encoded: codes ride the fixed-width
  one-program transcode (``dict_encode_for_rows``), dictionaries travel
  out of band.  Effective GB/s is computed over the PLAIN string-layout
  JCUDF row bytes — the same logical workload the 0.645 GB/s r05 number
  measured — divided by the dict-path wall time.

Usage: python tools/dict_bench.py [n_items] [out.json]
"""

import io
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax

jax.config.update("jax_compilation_cache_dir", ".jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp  # noqa: E402

R05_STRINGS_TO_ROWS_GBPS = 0.645   # BENCH_r05.json strings_mixed12_1M_to_rows

RESULTS = {"benches": {}}


def _redict(raw: bytes) -> bytes:
    """Rewrite a parquet blob with dictionary encoding ON (the TPC-DS
    generator writes plain pages)."""
    import pyarrow.parquet as pq
    t = pq.read_table(io.BytesIO(raw))
    buf = io.BytesIO()
    pq.write_table(t, buf, compression="SNAPPY", use_dictionary=True)
    return buf.getvalue()


def _wall(fn, warm=1, iters=5):
    for _ in range(warm):
        fn()
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _tables_equal(a, b):
    assert a.num_columns == b.num_columns and a.num_rows == b.num_rows
    for ca, cb in zip(a.columns, b.columns):
        if ca.dtype.id.name == "STRING":
            assert ca.to_pylist() == cb.to_pylist()
        else:
            np.testing.assert_array_equal(np.asarray(ca.data),
                                          np.asarray(cb.data))


def _scan(raw, columns, dict_on: bool):
    from spark_rapids_jni_tpu.parquet import device_scan
    # save/restore around the A/B write below, not a config read
    old = os.environ.get("SRJT_DICT_STRINGS")  # srjt-lint: disable=knob-env
    os.environ["SRJT_DICT_STRINGS"] = "1" if dict_on else "0"
    try:
        return device_scan.scan_table(raw, columns=columns)
    finally:
        if old is None:
            os.environ.pop("SRJT_DICT_STRINGS", None)
        else:
            os.environ["SRJT_DICT_STRINGS"] = old


def bench_queries(n_items: int):
    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu.column import as_dict_column
    from spark_rapids_jni_tpu.models import tpcds

    # a wide item dimension makes the string predicate the dominant stage
    # (the join fact stays moderate) — the shape the fast path targets
    files = tpcds_data.generate(n_sales=150_000, n_items=n_items,
                                n_stores=48, seed=5)
    item_raw = _redict(files["item"])
    store_raw = _redict(files["store"])

    base = tpcds.load_tables(files)

    def tbls(dict_on):
        t = dict(base)
        t["item"] = _scan(item_raw, tpcds.ITEM_COLS, dict_on)
        t["store"] = _scan(store_raw, tpcds.STORE_COLS, dict_on)
        return t

    td, tm = tbls(True), tbls(False)
    assert as_dict_column(td["item"][tpcds.ITEM_COLS.index("i_brand")]) \
        is not None, "item scan did not keep dict codes"
    assert as_dict_column(tm["item"][tpcds.ITEM_COLS.index("i_brand")]) \
        is None

    for qname in ("q_like_brands", "q_isin_states"):
        qfn = tpcds.QUERIES[qname]
        _tables_equal(qfn(td), qfn(tm))    # bit-identity gate
        dict_s = _wall(lambda: qfn(td))
        mat_s = _wall(lambda: qfn(tm))
        entry = {"dict_ms": round(dict_s * 1e3, 1),
                 "materialized_ms": round(mat_s * 1e3, 1),
                 "speedup": round(mat_s / dict_s, 2),
                 "n_items": n_items}
        RESULTS["benches"][qname] = entry
        print(f"{qname}: {entry}", flush=True)


def bench_string_groupby():
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_jni_tpu.ops import groupby as G

    n, card = 1_000_000, 200
    rng = np.random.default_rng(3)
    vals = np.array([f"group-key-{i:04d}" for i in range(card)])
    t = pa.table({"s": pa.array(vals[rng.integers(0, card, n)]),
                  "x": rng.integers(-1000, 1000, n).astype(np.int64)})
    buf = io.BytesIO()
    pq.write_table(t, buf, compression="SNAPPY", use_dictionary=True)
    raw = buf.getvalue()

    td, tm = _scan(raw, None, True), _scan(raw, None, False)
    _tables_equal(G.groupby_aggregate(td, [0], [(1, "sum")]),
                  G.groupby_aggregate(tm, [0], [(1, "sum")]))
    dict_s = _wall(lambda: G.groupby_aggregate(td, [0], [(1, "sum")]))
    mat_s = _wall(lambda: G.groupby_aggregate(tm, [0], [(1, "sum")]))
    entry = {"dict_ms": round(dict_s * 1e3, 1),
             "materialized_ms": round(mat_s * 1e3, 1),
             "speedup": round(mat_s / dict_s, 2),
             "rows": n, "cardinality": card}
    RESULTS["benches"]["string_groupby"] = entry
    print(f"string_groupby: {entry}", flush=True)


def bench_rowconv():
    import bench as drvbench
    from spark_rapids_jni_tpu.column import Column, DictColumn, Table
    from spark_rapids_jni_tpu.ops import strings as S
    from spark_rapids_jni_tpu.rowconv import convert as RC

    table = drvbench.build_table(1_000_000, 12, string_every=3)

    # dictionary-encode the string columns (what the scan produces for
    # dictionary-encoded pages)
    cols = []
    for c in table.columns:
        if c.dtype.id.name == "STRING":
            codes, uniq = S.dictionary_encode(c)
            cols.append(DictColumn(codes.data.astype(jnp.int32), uniq,
                                   c.validity, sorted_dict=True))
        else:
            cols.append(c)
    dict_table = Table(cols)

    # plain path: today's number (r05 measured 0.645 GB/s on TPU, in-jit
    # chained-fori_loop steady state — the methodology we mirror below)
    batches = RC.convert_to_rows(table)
    plain_bytes = sum(b.num_bytes for b in batches)

    def plain():
        b = RC.convert_to_rows(table)[0]
        np.asarray(b.data[:8])

    def dict_rows():
        enc, _dicts = RC.dict_encode_for_rows(dict_table)
        b = RC.convert_to_rows(enc)[0]
        np.asarray(b.data[:8])

    # round-trip parity gate: codes through rows + restore == plain table
    enc, dicts = RC.dict_encode_for_rows(dict_table)
    eb = RC.convert_to_rows(enc)
    back = RC.convert_from_rows(eb[0], [c.dtype for c in enc.columns])
    restored = RC.restore_dict_columns(back, dicts)
    for i, c in enumerate(table.columns):
        if c.dtype.id.name == "STRING":
            assert restored[i].to_pylist() == c.to_pylist()

    plain_s = _wall(plain, warm=1, iters=3)
    dict_s = _wall(dict_rows, warm=1, iters=3)

    # in-jit steady state: the dict-encoded table is fully fixed-width, so
    # the fixed-path trip-count-differencing methodology (the one behind
    # every BENCH_r05 number, bench.py time_diff) applies directly
    def to_body(tbl):
        return RC.convert_to_rows(tbl)[0].data
    steady_s = drvbench.time_diff(to_body, enc, 2, 8)
    steady_gbps = plain_bytes / steady_s / 1e9

    entry = {
        "plain_wall_ms": round(plain_s * 1e3, 1),
        "dict_wall_ms": round(dict_s * 1e3, 1),
        "dict_steady_ms": round(steady_s * 1e3, 2),
        "plain_wall_gbps": round(plain_bytes / plain_s / 1e9, 3),
        "dict_wall_gbps": round(plain_bytes / dict_s / 1e9, 3),
        "dict_steady_gbps": round(steady_gbps, 2),
        "speedup_vs_local_plain_wall": round(plain_s / dict_s, 2),
        "speedup_vs_r05_steady": round(
            steady_gbps / R05_STRINGS_TO_ROWS_GBPS, 2),
        "r05_baseline_gbps": R05_STRINGS_TO_ROWS_GBPS,
        "note": "effective GB/s = plain string-layout JCUDF row bytes / "
                "dict-path time (codes ride the fixed-width program, "
                "dictionaries travel out of band); steady = in-jit "
                "chained-fori_loop trip-count differencing, the same "
                "methodology as the r05 baseline number",
    }
    RESULTS["benches"]["rowconv_strings_mixed12_1M_to_rows"] = entry
    print(f"rowconv: {entry}", flush=True)


def main():
    n_items = int(sys.argv[1]) if len(sys.argv) > 1 else 1_200_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else "DICT_BENCH.json"
    RESULTS["backend"] = jax.default_backend()
    t0 = time.perf_counter()
    bench_queries(n_items)
    bench_string_groupby()
    bench_rowconv()
    RESULTS["seconds"] = round(time.perf_counter() - t0, 1)
    with open(out_path, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print("wrote", out_path, flush=True)


if __name__ == "__main__":
    main()
