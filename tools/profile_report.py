#!/usr/bin/env python
"""Summarize query-profile artifacts (``plan/profile.py`` JSON exports).

Reads one or more profile files — or a directory of them, e.g. the
``SRJT_PROFILE_DIR`` a profiled run exported into — flattens the node
trees, and prints the top-N plan nodes by SELF time (exclusive of
profiled children) with rows, bytes, est-vs-observed cardinality, engine
and AQE decisions.  Mispredicted nodes (>2× off the optimizer's prior)
are flagged: they are the rows worth re-running with fresh stats.

``--regress BASELINE`` compares against an earlier artifact (file or
directory; node identity = the structural ``node_id`` fingerprint) and
reports nodes whose self time regressed by more than ``--factor``
(default 1.5×) — the per-node answer to "which stage got slower".

Usage:
  python tools/profile_report.py <profile.json|dir> [top_n]
  python tools/profile_report.py <new> --regress <old> [--factor 1.5]

Exit code: 0, or 3 when --regress found regressions (CI-gateable).
"""

from __future__ import annotations

import glob
import json
import os
import sys


def _paths(arg: str) -> list[str]:
    if os.path.isdir(arg):
        return sorted(glob.glob(os.path.join(arg, "profile-*.json")))
    return [arg]


def load_profiles(arg: str) -> list[dict]:
    out = []
    for p in _paths(arg):
        with open(p) as f:
            out.append(json.load(f))
    return out


def flatten(profiles: list[dict]) -> dict[str, dict]:
    """node_id → aggregated {count, wall_ms, self_ms, rows, ...}."""
    agg: dict[str, dict] = {}

    def visit(n: dict) -> None:
        e = agg.setdefault(n["node_id"], {
            "line": n.get("line", n.get("op", "?")), "count": 0,
            "wall_ms": 0.0, "self_ms": 0.0, "fence_ms": 0.0,
            "out_rows": 0, "out_bytes": 0, "est_rows": None,
            "mispredict": False, "engine": None, "decisions": []})
        e["count"] += 1
        e["wall_ms"] += float(n.get("wall_ms", 0.0))
        e["self_ms"] += float(n.get("self_ms", 0.0))
        e["fence_ms"] += float(n.get("fence_ms", 0.0) or 0.0)
        e["out_rows"] += int(n.get("out_rows") or 0)
        e["out_bytes"] += int(n.get("out_bytes") or 0)
        if n.get("est_rows") is not None:
            e["est_rows"] = n["est_rows"]
        e["mispredict"] = e["mispredict"] or bool(n.get("mispredict"))
        if n.get("engine"):
            e["engine"] = n["engine"]
        for d in n.get("decisions", ()):
            if d not in e["decisions"]:
                e["decisions"].append(d)
        for c in n.get("children", ()):
            visit(c)

    for prof in profiles:
        for root in prof.get("nodes", ()):
            visit(root)
    return agg


def render(agg: dict[str, dict], top_n: int = 20) -> str:
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["self_ms"])[:top_n]
    if not rows:
        return "(no profiled nodes)"
    lines = [f"{'self_ms':>9}  {'wall_ms':>9}  {'count':>5}  "
             f"{'rows':>9}  {'bytes':>11}  node"]
    for nid, e in rows:
        flags = []
        if e["mispredict"]:
            est = e["est_rows"]
            flags.append("MISPREDICT"
                         + (f"(est={est:g})" if est is not None else ""))
        if e["engine"]:
            flags.append(f"engine={e['engine']}")
        suffix = ("   [" + " ".join(flags) + "]") if flags else ""
        lines.append(f"{e['self_ms']:>9.3f}  {e['wall_ms']:>9.3f}  "
                     f"{e['count']:>5}  {e['out_rows']:>9}  "
                     f"{e['out_bytes']:>11}  {e['line']}{suffix}")
        for d in e["decisions"]:
            lines.append(" " * 11 + f"fired {d}")
    return "\n".join(lines)


def regressions(new: dict[str, dict], old: dict[str, dict],
                factor: float) -> list[tuple[str, float, float]]:
    """Nodes present in both whose mean self time grew > factor×."""
    out = []
    for nid, e in new.items():
        o = old.get(nid)
        if o is None or not o["count"] or not e["count"]:
            continue
        n_mean = e["self_ms"] / e["count"]
        o_mean = o["self_ms"] / o["count"]
        if o_mean > 0 and n_mean > factor * o_mean:
            out.append((e["line"], o_mean, n_mean))
    return sorted(out, key=lambda r: -(r[2] - r[1]))


def main(argv: list[str]) -> int:
    args = list(argv[1:])
    factor = 1.5
    baseline = None
    if "--factor" in args:
        i = args.index("--factor")
        factor = float(args[i + 1])
        del args[i:i + 2]
    if "--regress" in args:
        i = args.index("--regress")
        baseline = args[i + 1]
        del args[i:i + 2]
    if not args:
        print("usage: profile_report.py <profile.json|dir> [top_n] "
              "[--regress BASELINE] [--factor F]", file=sys.stderr)
        return 2
    profiles = load_profiles(args[0])
    top_n = int(args[1]) if len(args) > 1 else 20
    agg = flatten(profiles)
    total = sum(p.get("wall_ms", 0.0) for p in profiles)
    mis = sum(1 for e in agg.values() if e["mispredict"])
    print(f"{args[0]}: {len(profiles)} profile(s), {len(agg)} distinct "
          f"node(s), wall {total:.2f} ms, {mis} mispredicted")
    print(render(agg, top_n))
    for prof in profiles:
        led = prof.get("compile_ledger")
        if led:
            body = "  ".join(f"{k}={led[k]:g}" for k in sorted(led))
            print(f"\ncompile ledger [{prof.get('fingerprint')}]: {body}")
    if baseline is not None:
        old = flatten(load_profiles(baseline))
        regs = regressions(agg, old, factor)
        print(f"\nregression check vs {baseline} (> {factor:g}x): "
              f"{len(regs)} node(s)")
        for line, o_mean, n_mean in regs:
            print(f"  {o_mean:.3f} ms → {n_mean:.3f} ms  {line}")
        if regs:
            return 3
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
