#!/usr/bin/env python
"""Chaos bench: scripted fault schedules against the multi-device serving
runtime → CHAOS_BENCH.json.

Where ``tools/serve_bench.py`` measures the fault-free serving ceiling,
this bench measures the ROBUSTNESS deliverables: what a device fault
costs and what the runtime guarantees while absorbing it.  Three
scripted scenarios over a TPC-DS mix, each asserting the chaos
contract (zero lost requests, every response bit-identical to serial):

  kill_replica — a one-shot fatal fault downs one replica mid-run.
                 Reports the failover latency (e2e of relocated
                 requests vs the fault-free median), the recovery time
                 (quarantine → probe re-admission), and the
                 post-recovery QPS ratio vs the pre-chaos baseline.
  oom_storm    — a burst of injected allocation failures.  Transient
                 faults retry IN PLACE with jittered backoff: the
                 report asserts zero quarantines and counts retries.
  flap         — repeated kill/recover rounds against the same pool.
                 Every round must fail over and re-admit; the report
                 carries per-round recovery times and the final pool
                 state (all replicas healthy, none ejected).

Fault schedules are armed programmatically via
``faultinj.injector.load_dict`` (the chaos harness entry point) using
the ``maxHits`` one-shot cap, so a "killed" device is genuinely healthy
again when the recovery probe's canary reaches it.

Usage: python tools/chaos_bench.py [n_sales] [out.json] [devices] [requests]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax


def canon(result):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]


def identical(a, b) -> bool:
    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y) for x, y in zip(a, b))


def wait_all_healthy(sched, timeout=30.0):
    """Block until every non-ejected replica is healthy; returns the
    wait (the recovery time when entered right after a fault)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        snaps = sched.ops_state()["replicas"]
        if all(s["state"] == "healthy" for s in snaps
               if s["state"] != "ejected"):
            return time.monotonic() - t0
        time.sleep(0.02)
    raise AssertionError(
        f"pool never recovered: {sched.ops_state()['replicas']}")


def run_mix(sched, mix, queries, tables, oracle, timeout=600):
    """Submit the mix, block, assert zero lost / bit-identical.
    Returns (wall_s, tickets)."""
    t0 = time.perf_counter()
    tickets = [sched.submit(q, queries[q], tables) for q in mix]
    outs = [tk.result(timeout=timeout) for tk in tickets]
    wall = time.perf_counter() - t0
    bad = sum(not identical(canon(out), oracle[q])
              for out, q in zip(outs, mix))
    assert bad == 0, f"{bad} responses diverged under chaos"
    return wall, tickets


def main():
    n_sales = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else "CHAOS_BENCH.json"
    n_devices = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    n_requests = int(sys.argv[4]) if len(sys.argv) > 4 else 24

    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu import exec as xc
    from spark_rapids_jni_tpu.faultinj import injector as finj
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.utils import flight, metrics

    metrics.set_enabled(True)
    avail = jax.local_device_count()
    n_devices = min(n_devices, avail)
    assert n_devices >= 2, \
        f"chaos bench needs ≥2 devices (have {avail}; set XLA_FLAGS=" \
        "--xla_force_host_platform_device_count=8)"

    qnames = ["q3", "q42"]
    print(f"backend: {jax.default_backend()}  devices: {n_devices}  "
          f"n_sales: {n_sales}  mix: {qnames}  requests: {n_requests}",
          flush=True)
    files = tpcds_data.generate(n_sales=n_sales, n_items=2000,
                                n_stores=12, seed=5)
    tables = tpcds.load_tables(files)
    mix = [qnames[i % len(qnames)] for i in range(n_requests)]
    oracle = {q: canon(tpcds.QUERIES[q](tables)) for q in qnames}
    inj = finj.get_injector()
    results = {"n_sales": n_sales, "devices": n_devices,
               "requests": n_requests, "queries": qnames}

    # coalesce_ms=0: each request dispatches (and rolls the fault dice)
    # individually — a coalesced batch is ONE interception for the whole
    # group, which starves percent-based storm schedules.  max_retries
    # covers the oom_storm's worst case (maxHits consecutive OOMs on one
    # request): the storm must drain through retries, not failures.
    sched_kw = dict(workers=n_devices, devices=n_devices,
                    queue_depth=max(64, n_requests), coalesce_ms=0,
                    max_retries=8, probe_base_s=0.05, probe_max_s=0.5)

    def warm_variants(sched):
        """Compile AND verify every (replica, query) plan variant out of
        band — which replica serves a given request is wakeup order, so
        warming through submit() cannot cover them all deterministically.
        Two runs per variant: capture-compile, then the checked first
        replay that validates the tape (the same double-run
        ``tools/serve_bench.py`` uses)."""
        for rep in sched.replicas:
            for q in qnames:
                with rep.scope():
                    placed = rep.place(tables)
                    for _ in range(2):
                        jax.block_until_ready(sched.plans.run(
                            q, tpcds.QUERIES[q], placed,
                            variant=f"d{rep.index}"))

    # ---- scenario 1: kill one replica mid-run ------------------------------
    with xc.QueryScheduler(**sched_kw) as sched:
        warm_variants(sched)    # the baseline measures serving, not compiles
        base_wall, base_tks = run_mix(sched, mix, tpcds.QUERIES, tables,
                                      oracle)
        base_e2e = sorted(tk.timings["e2e_s"] for tk in base_tks)
        base_p50 = base_e2e[len(base_e2e) // 2]
        metrics.reset()
        flight.reset()
        inj.load_dict({"seed": 7, "sites": {
            "exec.dispatch": {"percent": 100,
                              "injectionType": "device_error",
                              "maxHits": 1}}})
        inj.enable()
        chaos_wall, chaos_tks = run_mix(sched, mix, tpcds.QUERIES,
                                        tables, oracle)
        wait_all_healthy(sched)
        inj.disable()
        # recovery time from the black box: first quarantine incident →
        # first recovery incident (wall-clock the probe lifecycle took)
        evs = flight.events()
        t_q = next(e["ts"] for e in evs
                   if e["kind"] == "incident:quarantine")
        t_r = next(e["ts"] for e in evs
                   if e["kind"] == "incident:recovery" and e["ts"] >= t_q)
        recovery_s = t_r - t_q
        counters = dict(metrics.snapshot()["counters"])
        relocated = [tk for tk in chaos_tks if tk.relocations > 0]
        assert relocated, "fault never relocated a request"
        assert counters.get("exec.failover.recovered", 0) >= 1, \
            "victim never recovered"
        reloc_e2e = sorted(tk.timings["e2e_s"] for tk in relocated)
        # post-recovery: the healed pool serves at its pre-chaos rate
        metrics.reset()
        post_wall, _ = run_mix(sched, mix, tpcds.QUERIES, tables, oracle)
    results["kill_replica"] = {
        "baseline_qps": round(n_requests / base_wall, 2),
        "chaos_qps": round(n_requests / chaos_wall, 2),
        "post_recovery_qps": round(n_requests / post_wall, 2),
        "post_recovery_ratio": round(base_wall / post_wall, 2),
        "relocated_requests": len(relocated),
        "failover_latency_p50_ms": round(
            reloc_e2e[len(reloc_e2e) // 2] * 1e3, 2),
        "baseline_e2e_p50_ms": round(base_p50 * 1e3, 2),
        "recovery_s": round(recovery_s, 3),
        "counters": {k: int(v) for k, v in sorted(counters.items())
                     if k.startswith("exec.failover.")
                     or k in ("exec.quarantined", "exec.completed")},
        "lost_requests": 0, "responses_identical": True}
    print(f"kill_replica: {len(relocated)} relocated, recovery "
          f"{results['kill_replica']['recovery_s']}s, post-recovery "
          f"{results['kill_replica']['post_recovery_ratio']}x baseline",
          flush=True)

    # ---- scenario 2: OOM storm (transient; retries, no quarantine) ---------
    metrics.reset()
    with xc.QueryScheduler(**sched_kw) as sched:
        warm_variants(sched)
        inj.load_dict({"seed": 11, "sites": {
            "exec.dispatch": {"percent": 40, "injectionType": "oom",
                              "maxHits": 8}}})
        inj.enable()
        storm_wall, _ = run_mix(sched, mix, tpcds.QUERIES, tables, oracle)
        injected_ooms = int(inj.injected_count)   # disable() zeroes it
        inj.disable()
        counters = dict(metrics.snapshot()["counters"])
        snaps = sched.ops_state()["replicas"]
    assert all(s["state"] == "healthy" for s in snaps), snaps
    assert counters.get("exec.quarantined", 0) == 0, \
        "transient OOM must not quarantine"
    assert injected_ooms >= 1, "storm never fired"
    results["oom_storm"] = {
        "qps": round(n_requests / storm_wall, 2),
        "retries": int(counters.get("exec.retries", 0)),
        "injected_ooms": injected_ooms,
        "quarantines": 0, "lost_requests": 0,
        "responses_identical": True}
    print(f"oom_storm: {results['oom_storm']['retries']} retries, "
          "0 quarantines, all identical", flush=True)

    # ---- scenario 3: flapping device (kill / recover / kill again) ---------
    metrics.reset()
    rounds = 3
    round_recovery = []
    with xc.QueryScheduler(**sched_kw) as sched:
        warm_variants(sched)
        for r in range(rounds):
            inj.load_dict({"seed": 100 + r, "sites": {
                "exec.dispatch": {"percent": 100,
                                  "injectionType": "device_error",
                                  "maxHits": 1}}})
            inj.enable()
            run_mix(sched, mix, tpcds.QUERIES, tables, oracle)
            round_recovery.append(round(wait_all_healthy(sched), 3))
            inj.disable()
        counters = dict(metrics.snapshot()["counters"])
        snaps = sched.ops_state()["replicas"]
    assert all(s["state"] == "healthy" for s in snaps), snaps
    assert counters.get("exec.failover.recovered", 0) >= rounds, counters
    results["flap"] = {
        "rounds": rounds,
        "recovery_s_per_round": round_recovery,
        "recoveries": int(counters.get("exec.failover.recovered", 0)),
        "ejected": int(counters.get("exec.failover.ejected", 0)),
        "lost_requests": 0, "responses_identical": True}
    print(f"flap: {rounds} rounds, recoveries "
          f"{results['flap']['recoveries']}, 0 ejections, 0 lost",
          flush=True)

    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {out_path}", flush=True)


if __name__ == "__main__":
    main()
