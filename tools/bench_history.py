#!/usr/bin/env python
"""Collate every ``*_BENCH.json`` artifact into ``BENCH_TRAJECTORY.json``.

Each smoke/bench script (``ci/*_smoke.sh``, ``tools/query_bench.py``, …)
leaves a JSON artifact at the repo root; nothing has collated them, so
the perf trajectory across PRs is invisible.  This tool flattens every
numeric scalar in each artifact to a dot-path metric and stamps it with
the artifact's last-touching commit (``git log -1 -- <file>``), producing
one machine-readable ledger:

    {"generated_from": [...],
     "metrics": [{"artifact": "JOIN_BENCH.json",
                  "metric": "benches.fact_dim.speedup",
                  "value": 3.1,
                  "commit": "f9fb599",
                  "subject": "PR 15: ...'"}, ...]}

Downstream, ``tools/profile_report.py --regress`` answers per-node
questions; this answers the per-PR one ("what did each change buy").

Usage: python tools/bench_history.py [--root DIR] [--out FILE]
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys


def _flatten(doc, prefix: str = "") -> list[tuple[str, float]]:
    """Numeric scalars as (dot.path, value); bools/strings skipped."""
    out: list[tuple[str, float]] = []
    if isinstance(doc, dict):
        for k in sorted(doc):
            out.extend(_flatten(doc[k], f"{prefix}{k}."))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.extend(_flatten(v, f"{prefix}{i}."))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out.append((prefix[:-1], float(doc)))
    return out


def _provenance(root: str, path: str) -> tuple[str, str]:
    """(short commit, subject) of the commit that last touched ``path``."""
    try:
        line = subprocess.run(
            ["git", "log", "-1", "--format=%h%x09%s", "--",
             os.path.basename(path)],
            cwd=root, capture_output=True, text=True, timeout=30,
        ).stdout.strip()
        if line:
            h, _, subj = line.partition("\t")
            return h, subj
    except Exception:
        pass
    return "", ""


def collect(root: str) -> dict:
    arts = sorted(glob.glob(os.path.join(root, "*_BENCH.json")))
    metrics = []
    for path in arts:
        try:
            with open(path) as f:
                doc = json.load(f)
        except Exception as exc:
            print(f"warning: skipping {path}: {exc}", file=sys.stderr)
            continue
        commit, subject = _provenance(root, path)
        name = os.path.basename(path)
        for metric, value in _flatten(doc):
            metrics.append({"artifact": name, "metric": metric,
                            "value": value, "commit": commit,
                            "subject": subject})
    return {"generated_from": [os.path.basename(a) for a in arts],
            "metrics": metrics}


def main(argv: list[str]) -> int:
    root = "."
    out = None
    args = list(argv[1:])
    if "--root" in args:
        i = args.index("--root")
        root = args[i + 1]
        del args[i:i + 2]
    if "--out" in args:
        i = args.index("--out")
        out = args[i + 1]
        del args[i:i + 2]
    if out is None:
        out = os.path.join(root, "BENCH_TRAJECTORY.json")
    doc = collect(root)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, out)
    by_art: dict[str, int] = {}
    for m in doc["metrics"]:
        by_art[m["artifact"]] = by_art.get(m["artifact"], 0) + 1
    print(f"{out}: {len(doc['metrics'])} metrics from "
          f"{len(doc['generated_from'])} artifacts")
    for art in sorted(by_art):
        print(f"  {art}: {by_art[art]}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
