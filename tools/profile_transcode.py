#!/usr/bin/env python
"""Per-stage transcode profiling on the real chip → PROFILE_transcode.json.

VERDICT r2 weak #1: 3.77 GB/s driver round-trip vs a 70-110 GB/s elementwise
ceiling, with no per-stage breakdown.  This script answers "where does the
time go" with honest device timing:

* every measurement is a dependency-chained ``fori_loop`` inside ONE jit with
  one tiny D2H at the end (tunnel rules — see BASELINE.md methodology note);
* the fixed dispatch+sync overhead (~12 ms + ~65-110 ms through the tunnel)
  is removed exactly by differencing two trip counts of the SAME jitted
  loop: t(N_HI) - t(N_LO) over (N_HI - N_LO) iterations.

Measured stages:
  1. sync/dispatch floor (empty body)
  2. elementwise u32 ceiling, XLA and Pallas HBM copy
  3. interleave variants  (u32 [W, n] -> flat [n*W], JCUDF word order)
  4. deinterleave variants (flat -> [W, n])
  5. u8<->u32 lane conversion
  6. current full to_rows / from_rows / round trip at the bench schema

Usage: python tools/profile_transcode.py [out.json]
"""

import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax import lax

RESULTS = {"backend": None, "stages": []}
N_LO, N_HI = 5, 45


def _loop(body):
    """jit(data, iters) running ``body(data)`` chained ``iters`` times."""
    @jax.jit
    def run(data, iters):
        def step(_, carry):
            acc, data_ = carry
            d = lax.optimization_barrier((data_, acc))[0]
            out = body(d)
            out = lax.optimization_barrier(out)
            leaf = jax.tree_util.tree_leaves(out)[0]
            probe = lax.convert_element_type(jnp.ravel(leaf)[0], jnp.int32)
            return (acc + probe) % jnp.int32(65521), data_
        acc, _ = lax.fori_loop(0, iters, step, (jnp.int32(0), data))
        return acc
    return run


def measure(name, body, data, nbytes, note=""):
    """Record per-iteration device seconds and GB/s for ``body``."""
    run = _loop(body)
    try:
        np.asarray(run(data, N_LO))          # compile + warm
        t0 = time.perf_counter()
        np.asarray(run(data, N_LO))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run(data, N_HI))
        t_hi = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001 — record and continue the sweep
        RESULTS["stages"].append({"name": name, "error": repr(e)[:300]})
        print(f"  FAIL {name}: {e!r}"[:200], flush=True)
        return None
    per_iter = max((t_hi - t_lo) / (N_HI - N_LO), 1e-9)
    gbps = nbytes / per_iter / 1e9
    RESULTS["stages"].append({
        "name": name, "per_iter_ms": round(per_iter * 1e3, 3),
        "gbps": round(gbps, 2), "nbytes": nbytes,
        "t_lo_s": round(t_lo, 4), "t_hi_s": round(t_hi, 4), "note": note,
    })
    print(f"  {name}: {per_iter*1e3:.3f} ms/iter  {gbps:.2f} GB/s  {note}",
          flush=True)
    return per_iter


# ---------------------------------------------------------------------------
# interleave / deinterleave variants.  Contract: x is u32 [W, n] (words
# stacked, n multiple of 128); output is the flat JCUDF word stream
# out[r*W + w] = x[w, r], shape [n*W] (or a wide-minor 2-D view of it).
# ---------------------------------------------------------------------------

def il_strided(x):
    W, n = x.shape
    out = jnp.zeros((n // 128, 128 * W), jnp.uint32)
    for w in range(W):
        out = out.at[:, w::W].set(x[w].reshape(n // 128, 128))
    return out


def il_transpose(x):
    return x.T.reshape(-1)


def il_perm3(x):
    W, n = x.shape
    return x.reshape(W, n // 128, 128).transpose(1, 2, 0).reshape(
        n // 128, 128 * W)


def _mk_il_pallas(kind, tr):
    from jax.experimental import pallas as pl

    def f(x):
        W, n = x.shape

        def kernel(x_ref, o_ref):
            xb = x_ref[...]                       # [W, tr]
            if kind == "transpose":
                o_ref[...] = xb.T.reshape(tr // 128, 128 * W)
            else:                                 # strided lane writes
                o = jnp.zeros((tr // 128, 128 * W), jnp.uint32)
                for w in range(W):
                    o = o.at[:, w::W].set(xb[w].reshape(tr // 128, 128))
                o_ref[...] = o

        return pl.pallas_call(
            kernel,
            grid=(n // tr,),
            in_specs=[pl.BlockSpec((W, tr), lambda i: (0, i))],
            out_specs=pl.BlockSpec((tr // 128, 128 * W), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n // 128, 128 * W), jnp.uint32),
        )(x)
    return f


def dl_strided(flat_w):
    def f(x2):
        n128, lanes = x2.shape
        W = lanes // 128
        return jnp.stack([x2[:, w::W].reshape(-1) for w in range(W)])
    return f(flat_w)


def dl_transpose_fn(W):
    def f(flat):
        return flat.reshape(-1, W).T
    return f


def dl_perm3_fn(W):
    def f(x2):
        n128 = x2.shape[0]
        return x2.reshape(n128, 128, W).transpose(2, 0, 1).reshape(W, -1)
    return f


def _mk_dl_pallas(tr, W):
    from jax.experimental import pallas as pl

    def f(x2):
        n128 = x2.shape[0]
        n = n128 * 128

        def kernel(x_ref, o_ref):
            xb = x_ref[...]                       # [tr//128, 128W]
            o_ref[...] = xb.reshape(tr, W).T      # [W, tr]

        return pl.pallas_call(
            kernel,
            grid=(n // tr,),
            in_specs=[pl.BlockSpec((tr // 128, 128 * W), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((W, tr), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((W, n), jnp.uint32),
        )(x2)
    return f


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "PROFILE_transcode.json"
    RESULTS["backend"] = jax.default_backend()
    print(f"backend: {RESULTS['backend']}", flush=True)
    rng = np.random.default_rng(0)

    # 1. floor
    measure("floor_empty", lambda d: d, jnp.zeros((8, 128), jnp.uint32), 0)

    # 2. ceilings
    n_ew = 1 << 24                                # 64 MiB u32
    big = jnp.asarray(rng.integers(0, 2**32, n_ew, dtype=np.uint32))
    measure("xla_elementwise_u32", lambda x: x * jnp.uint32(3) + jnp.uint32(1),
            big, 2 * 4 * n_ew, "read+write counted")

    from jax.experimental import pallas as pl

    def pallas_copy(x):
        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]
        blk = 1 << 16
        return pl.pallas_call(
            kernel, grid=(x.shape[0] // blk,),
            in_specs=[pl.BlockSpec((blk,), lambda i: (i,))],
            out_specs=pl.BlockSpec((blk,), lambda i: (i,)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)
    measure("pallas_copy_u32", pallas_copy, big, 2 * 4 * n_ew)

    # 3./4. interleave / deinterleave, bench-like W and wide W
    n = 1 << 20
    for W in (11, 53):
        x = jnp.asarray(rng.integers(0, 2**32, (W, n), dtype=np.uint32))
        flat2 = jnp.asarray(
            rng.integers(0, 2**32, (n // 128, 128 * W), dtype=np.uint32))
        nbytes = 2 * 4 * n * W
        measure(f"il_strided_W{W}", il_strided, x, nbytes)
        measure(f"il_transpose_W{W}", il_transpose, x, nbytes)
        measure(f"il_perm3_W{W}", il_perm3, x, nbytes)
        for tr in (2048, 8192):
            measure(f"il_pallas_T_W{W}_tr{tr}",
                    _mk_il_pallas("transpose", tr), x, nbytes)
        measure(f"il_pallas_S_W{W}_tr2048", _mk_il_pallas("strided", 2048),
                x, nbytes)
        measure(f"dl_strided_W{W}", dl_strided, flat2, nbytes)
        measure(f"dl_transpose_W{W}", dl_transpose_fn(W),
                flat2.reshape(-1), nbytes)
        measure(f"dl_perm3_W{W}", dl_perm3_fn(W), flat2, nbytes)
        measure(f"dl_pallas_W{W}_tr2048", _mk_dl_pallas(2048, W), flat2,
                nbytes)

    # 5. u8<->u32
    from spark_rapids_jni_tpu.rowconv import ragged
    nb8 = 1 << 26
    b8 = jnp.asarray(rng.integers(0, 256, nb8, dtype=np.uint8))
    w32 = jnp.asarray(rng.integers(0, 2**32, nb8 // 4, dtype=np.uint32))
    measure("u8_to_u32", ragged.u8_to_u32, b8, 2 * nb8)
    measure("u32_to_u8", ragged.u32_to_u8, w32, 2 * nb8)

    # 5b. fixed-path compose breakdown (VERDICT r5 task: which stage of
    # _to_rows_fixed_words eats the gap between the 343 GB/s interleave
    # ceiling and the ~30 GB/s public path?)
    import bench as bench_mod_
    from spark_rapids_jni_tpu.rowconv import convert as cv
    from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout
    tbl_c = bench_mod_.build_table(1_000_000, 12)
    lay_c = compute_row_layout(tbl_c.schema)
    Wc = lay_c.fixed_row_size // 4
    nrows = tbl_c.num_rows
    datas_c = tuple(c.data for c in tbl_c.columns)
    valid_c = jnp.stack([c.validity_or_true() for c in tbl_c.columns],
                        axis=1)
    row_bytes_c = nrows * lay_c.fixed_row_size

    def stage_only(a):
        ds = a
        return tuple(cv._stage_column_dt(d, dt)
                     for d, dt in zip(ds, lay_c.schema))
    measure("fx_stage_columns", stage_only, datas_c, row_bytes_c,
            f"per-column bitcast staging, W={Wc}")

    def vbytes_only(a):
        v = a
        outs = []
        for k in range(lay_c.validity_bytes):
            acc = jnp.zeros((nrows,), jnp.uint32)
            for i in range(min(8, lay_c.num_columns - k * 8)):
                acc = acc | (v[:, k * 8 + i].astype(jnp.uint32)
                             << jnp.uint32(i))
            outs.append(acc)
        return tuple(outs)
    measure("fx_validity_bytes", vbytes_only, valid_c, nrows * 2)

    staged_pre = tuple(cv._stage_column_dt(d, dt)
                       for d, dt in zip(datas_c, lay_c.schema))

    def compose_only(a):
        st = a
        plan = cv._word_plan(lay_c)
        words = []
        for w in range(Wc):
            acc = None
            for ii, kind, arg in plan[w]:
                if kind == "vbyte":
                    continue
                x = st[ii]
                v = (x if kind == "full"
                     else x[:, arg] if kind == "pair"
                     else x << jnp.uint32(arg * 8))
                acc = v if acc is None else acc | v
            words.append(acc if acc is not None
                         else jnp.zeros((nrows,), jnp.uint32))
        return tuple(words)
    measure("fx_compose_words", compose_only, staged_pre, row_bytes_c,
            "from pre-staged arrays (no bitcasts)")

    def whole_words(a):
        ds, v = a
        return cv._to_rows_fixed_words(lay_c, ds, v)
    measure("fx_to_rows_words_full", whole_words, (datas_c, valid_c),
            row_bytes_c, "stage+compose+interleave")

    from spark_rapids_jni_tpu import convert_to_rows as _ctr
    b0_c = _ctr(tbl_c)[0]

    def decode_words(a):
        return cv._from_rows_fixed_words(lay_c, a)
    measure("fx_from_rows_words_full", decode_words, b0_c.data,
            row_bytes_c, "deinterleave+decode")

    # 6. current public path at the bench schema (reuse section 5b's table)
    table = tbl_c
    from spark_rapids_jni_tpu import convert_to_rows, convert_from_rows
    from spark_rapids_jni_tpu.column import Column, Table as _Table

    batches0 = convert_to_rows(table)
    row_bytes = sum(b.num_bytes for b in batches0)
    schema = table.schema

    def to_rows_body(tbl):
        return convert_to_rows(tbl)[0].data
    measure("current_to_rows_1M", to_rows_body, table, row_bytes,
            "row bytes counted once")

    def from_rows_body(batch):
        t = convert_from_rows(batch, schema)
        return t.columns[0].data
    measure("current_from_rows_1M", from_rows_body, batches0[0], row_bytes,
            "row bytes counted once")

    def rt_body(tbl):
        b = convert_to_rows(tbl)[0]
        t = convert_from_rows(b, schema)
        return t.columns[0].data
    measure("current_roundtrip_1M", rt_body, table, 2 * row_bytes,
            "row bytes counted per direction (bench metric)")

    with open(out_path, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print(f"wrote {out_path}", flush=True)


if __name__ == "__main__":
    main()
