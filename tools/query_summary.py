#!/usr/bin/env python
"""Summarize QUERY_BENCH vs HOST_QUERY_BASELINE → the SF1 subset totals
the north-star metric tracks.  Prints one JSON object and updates
QUERY_BENCH.json's "summary" key in place."""

import json
import sys

sys.path.insert(0, ".")


def main():
    qb_path = sys.argv[1] if len(sys.argv) > 1 else "QUERY_BENCH.json"
    hb_path = sys.argv[2] if len(sys.argv) > 2 else "HOST_QUERY_BASELINE.json"
    qb = json.load(open(qb_path))
    hb = json.load(open(hb_path))
    chip = qb["queries"]
    host = hb["queries"]
    names = sorted(set(chip) & set(host))
    rows = []
    for n in names:
        c, h = chip[n], host[n]
        if "warm_unchecked_s" not in c or "wall_s" not in h:
            continue
        rows.append({
            "query": n,
            "chip_warm_s": c["warm_wall_s"],
            "chip_unchecked_s": c["warm_unchecked_s"],
            "chip_steady_ms": c.get("steady_ms"),
            "pandas_s": h["wall_s"],
            "chip_wins_warm": c["warm_wall_s"] <= h["wall_s"],
            "chip_wins_unchecked": c["warm_unchecked_s"] <= h["wall_s"],
        })
    steady_rows = [r for r in rows if r["chip_steady_ms"] is not None]
    summary = {
        "queries_compared": len(rows),
        "steady_measured": len(steady_rows),
        "wins_steady": sum(r["chip_steady_ms"] / 1e3 <= r["pandas_s"]
                           for r in steady_rows),
        "steady_total_ms": round(sum(r["chip_steady_ms"]
                                     for r in steady_rows), 1),
        "pandas_total_for_steady_set_s": round(
            sum(r["pandas_s"] for r in steady_rows), 3),
        "chip_warm_total_s": round(sum(r["chip_warm_s"] for r in rows), 2),
        "chip_unchecked_total_s": round(
            sum(r["chip_unchecked_s"] for r in rows), 2),
        "pandas_total_s": round(sum(r["pandas_s"] for r in rows), 2),
        "wins_warm": sum(r["chip_wins_warm"] for r in rows),
        "wins_unchecked": sum(r["chip_wins_unchecked"] for r in rows),
        "measured_chip": sum(1 for e in chip.values()
                             if "warm_unchecked_s" in e),
        "with_steady": sum(1 for e in chip.values()
                           if e.get("steady_ms") is not None),
    }
    qb["summary"] = summary
    with open(qb_path, "w") as f:
        json.dump(qb, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
