#!/usr/bin/env python
"""Pandas host baseline for the SF1-class query slice → HOST_QUERY_BASELINE.json.

Times the same plans ``tools/query_bench.py`` runs on chip, executed by
pandas over the identical parquet bytes (pyarrow reader) — the CPU
single-node context figure for BASELINE config #3 (the north star compares
against CPU Spark; single-process pandas is the in-image stand-in).

Usage: python tools/query_host_baseline.py [n_sales] [out.json]
"""

import io
import json
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, ".")

RESULTS = {"queries": {}}


def main():
    n_sales = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    out = sys.argv[2] if len(sys.argv) > 2 else "HOST_QUERY_BASELINE.json"
    from benchmarks import tpcds_data
    files = tpcds_data.generate(n_sales=n_sales, n_items=20_000,
                                n_stores=50, seed=5)
    t0 = time.perf_counter()
    dfs = {k: pd.read_parquet(io.BytesIO(v)) for k, v in files.items()}
    RESULTS["n_sales"] = n_sales
    RESULTS["load_s"] = round(time.perf_counter() - t0, 1)
    ss, item, dd, store = (dfs["store_sales"], dfs["item"],
                           dfs["date_dim"], dfs["store"])

    def q3():
        mid = 436   # the framework query's default parameter
        j = (ss.merge(item[item.i_manufact_id == mid], left_on="ss_item_sk",
                      right_on="i_item_sk")
             .merge(dd[dd.d_moy == 11], left_on="ss_sold_date_sk",
                    right_on="d_date_sk"))
        return (j.groupby(["d_year", "i_brand_id", "i_brand"],
                          as_index=False)["ss_ext_sales_price"].sum())

    def q55():
        mid = 28
        j = ss.merge(item[item.i_manager_id == mid], left_on="ss_item_sk",
                     right_on="i_item_sk")
        return (j.groupby(["i_brand_id", "i_brand"], as_index=False)
                ["ss_ext_sales_price"].sum())

    def q62():
        ssf = ss[(ss.ss_quantity >= 10) & (ss.ss_quantity <= 60)]
        j = ssf.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                      right_on="d_date_sk")
        return j.groupby("d_moy", as_index=False)["ss_quantity"].count()

    def q_state_rollup():
        sf = store[store.s_state == "TN"]
        j = ss.merge(sf, left_on="ss_store_sk", right_on="s_store_sk")
        return (j.groupby("s_state", as_index=False)
                .agg(s=("ss_sales_price_cents", "sum"),
                     m=("ss_quantity", "mean"),
                     c=("ss_quantity", "count")))

    def q_having():
        j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
        rev = (j.groupby("i_brand_id", as_index=False)
               ["ss_ext_sales_price"].sum())
        return rev[rev.ss_ext_sales_price > 1000.0]

    for name, fn in [("q3", q3), ("q55", q55), ("q62", q62),
                     ("q_state_rollup", q_state_rollup),
                     ("q_having", q_having)]:
        fn()      # warm (page cache, dtypes)
        t0 = time.perf_counter()
        res = fn()
        wall = time.perf_counter() - t0
        RESULTS["queries"][name] = {"wall_s": round(wall, 2),
                                    "rows_out": int(len(res))}
        print(f"{name}: {wall:.2f}s, {len(res)} rows", flush=True)

    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
