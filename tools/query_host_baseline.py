#!/usr/bin/env python
"""Pandas host baseline for the FULL query subset → HOST_QUERY_BASELINE.json.

Times every plan in ``benchmarks/pandas_queries.py`` (the pandas twins of
``models/tpcds.QUERIES``, cardinality-checked against the framework in
``tests/test_pandas_queries.py``) over the identical parquet bytes —
the CPU single-node context figure for BASELINE config #3 (the north
star compares against CPU Spark; single-process pandas is the in-image
stand-in).

Usage: python tools/query_host_baseline.py [n_sales] [out.json]
"""

import io
import json
import sys
import time

import pandas as pd

sys.path.insert(0, ".")

RESULTS = {"queries": {}}


def main():
    n_sales = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    out = sys.argv[2] if len(sys.argv) > 2 else "HOST_QUERY_BASELINE.json"
    from benchmarks import pandas_queries as PQ
    from benchmarks import tpcds_data
    files = tpcds_data.generate(n_sales=n_sales, n_items=20_000,
                                n_stores=50, seed=5)
    t0 = time.perf_counter()
    dfs = {k: pd.read_parquet(io.BytesIO(v)) for k, v in files.items()}
    RESULTS["n_sales"] = n_sales
    RESULTS["load_s"] = round(time.perf_counter() - t0, 1)
    print(f"pandas load: {RESULTS['load_s']}s", flush=True)

    total = 0.0
    for name in sorted(PQ.QUERIES):
        fn = PQ.QUERIES[name]
        try:
            fn(dfs)      # warm (page cache, dtypes)
            t0 = time.perf_counter()
            res = fn(dfs)
            wall = time.perf_counter() - t0
            RESULTS["queries"][name] = {"wall_s": round(wall, 3),
                                        "rows_out": int(len(res))}
            total += wall
            print(f"{name}: {wall:.3f}s, {len(res)} rows", flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep going
            RESULTS["queries"][name] = {"error": repr(e)[:200]}
            print(f"{name}: ERROR {e!r}", flush=True)
        with open(out, "w") as f:
            json.dump(RESULTS, f, indent=1)

    n_ok = sum(1 for e in RESULTS["queries"].values() if "wall_s" in e)
    RESULTS["subset_total_s"] = round(total, 2)
    RESULTS["subset_queries_ok"] = n_ok
    print(f"pandas subset total ({n_ok}/{len(PQ.QUERIES)} queries): "
          f"{total:.2f}s", flush=True)
    with open(out, "w") as f:
        json.dump(RESULTS, f, indent=1)
    print("wrote", out, flush=True)


if __name__ == "__main__":
    main()
