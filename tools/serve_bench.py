#!/usr/bin/env python
"""Serving-runtime throughput bench → SERVE_BENCH.json.

Measures the question the ``exec/`` subsystem exists to answer: how many
QUERIES PER SECOND does this engine serve over a TPC-DS query mix, and
what does a request wait on?  Three configurations over the same request
stream (round-robin over the chosen queries):

  serial_eager    — one request at a time, eager execution: the engine
                    WITHOUT the serving runtime (no plan reuse, ~30
                    dispatches + size syncs per request).
  serial_compiled — one at a time through a warm plan cache: isolates
                    the plan-cache contribution from concurrency.
  concurrent      — the full runtime: ``QueryScheduler`` with N workers
                    (``SRJT_SERVE_WORKERS``, default 4), warm plan
                    cache, admission on.  XLA executions release the
                    GIL, so worker overlap is real compute overlap.

A fourth phase sweeps OFFERED load: paced open-loop arrivals at 1x/2x/4x
the serial-compiled ceiling with cross-request coalescing on (the
``batched`` section).  Past 1x a serial server saturates; coalescing
collapses the same-plan backlog into shared launches, so throughput
tracks the offered rate while queue wait stays flat.

Every response in every configuration is checked BIT-IDENTICAL to the
serial eager oracle — concurrency and caching must never change results.
A final degraded phase re-runs the mix under a deliberately tiny
``SRJT_EXEC_INFLIGHT_BYTES`` cap: every request over-caps, admission
degrades them to the sorted join engine (exclusive admission), and the
bench asserts completion with correct results — the "pressure never
fails a servable request" contract, measured.

Latency detail comes from the runtime's own histograms
(``exec.queue_wait_ms`` / ``exec.e2e_ms`` p50/p95/p99 via
``metrics.percentile``) plus the per-stage attribution family
(``exec.stage.{queue,coalesce,admission,dispatch,ready}_ms``) — where a
request's time actually went, the numbers a capacity plan needs.

A final ``flight_overhead`` phase re-runs the 1x paced load with the
always-on flight recorder OFF and then ON and records the steady-state
cost (the <2% budget the recorder's always-on discipline promises).

With ``--devices N`` a multi-device phase re-runs the concurrent mix
over N replicas (``QueryScheduler(devices=N)``): requests route to
per-device replicas with replicated inputs, and the report carries
per-device QPS (from the ``exec.device.*.completed`` counters) plus
failover/quarantine counts.  On hosts where the N devices are forced
host-platform slices of one physical core, per-device QPS measures
placement overhead honestly — not a speedup.

``--cold-start`` switches to the zero-compile cold-start bench
(``exec/artifacts.py``): FRESH subprocesses measure first-request latency
per query three ways — empty-store baseline (every plan pays
capture→trace→compile), a populate pass, then warm trials against the
populated ``SRJT_AOT_DIR`` (plans rehydrate from persisted tapes, XLA
executables deserialize from the shared disk cache).  The mode asserts
the cold-start contract: warm processes perform ZERO capture runs
(``compiled.capture`` in the ledger snapshot) with results bit-identical
to the baseline, and records first-request p50/p99 before/after into a
``cold_start`` entry merged into SERVE_BENCH.json.

Usage: python tools/serve_bench.py [n_sales] [out.json] [q1,q2,...] [requests]
                                   [--devices N]
       python tools/serve_bench.py --cold-start [n_sales] [out.json]
                                   [q1,q2,...] [trials]
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")

import jax


def canon(result):
    """A result pytree as host arrays (forces lazy columns)."""
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]


def identical(a, b) -> bool:
    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y) for x, y in zip(a, b))


def hist_pcts(metrics, name):
    """p50/p95/p99 of one latency histogram (None when unobserved)."""
    return {"p50": metrics.percentile(name, 50),
            "p95": metrics.percentile(name, 95),
            "p99": metrics.percentile(name, 99)}


def stage_attribution(metrics):
    """Per-stage latency breakdown from ``exec.stage.*_ms``: where a
    request's end-to-end time went, stage by stage."""
    hists = metrics.snapshot()["histograms"]
    out = {}
    for st in ("queue", "coalesce", "admission", "dispatch", "ready"):
        h = hists.get(f"exec.stage.{st}_ms")
        if h and h["count"]:
            out[st] = {"count": h["count"],
                       "mean_ms": round(h["total"] / h["count"], 3),
                       "p95_ms": metrics.percentile(
                           f"exec.stage.{st}_ms", 95)}
    return out


# --- zero-compile cold start (exec/artifacts.py) ----------------------------


def _result_hash(result) -> str:
    h = hashlib.sha256()
    for leaf in canon(result):
        a = np.ascontiguousarray(leaf)
        h.update(a.dtype.str.encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def cold_child(n_sales: int, qnames: list, out_path: str) -> None:
    """One fresh serving process: load the mix's tables, serve each query
    ONCE through a real QueryScheduler, and report first-request wall
    times, result hashes, and the compile-ledger counters.  The parent
    decides what the numbers mean (baseline vs populate vs warm)."""
    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu import exec as xc
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.utils import metrics

    metrics.set_enabled(True)
    files = tpcds_data.generate(n_sales=n_sales, n_items=2000,
                                n_stores=12, seed=5)
    tables = tpcds.load_tables(files)
    for c in tables["store_sales"].columns:
        np.asarray(c.data[:1])          # force fact upload out of band
    first_ms, hashes = {}, {}
    with xc.QueryScheduler(workers=2) as sched:
        if sched._warmup_thread is not None:
            # measure steady warm-up, not a race with it
            sched._warmup_thread.join(timeout=60)
        for q in qnames:
            t0 = time.perf_counter()
            out = sched.run(q, tpcds.QUERIES[q], tables)
            jax.block_until_ready(jax.tree_util.tree_leaves(out))
            first_ms[q] = (time.perf_counter() - t0) * 1e3
            hashes[q] = _result_hash(out)
            # second (untimed) request: on a live capture the FIRST
            # response is the capture run's own eager result — the
            # replay program only compiles here.  Running it makes a
            # populate pass persist the XLA executables the warm
            # processes deserialize (the real serving steady state).
            sched.run(q, tpcds.QUERIES[q], tables)
    snap = metrics.snapshot()["counters"]
    with open(out_path, "w") as f:
        json.dump({"first_request_ms": first_ms, "hashes": hashes,
                   "capture": int(snap.get("compiled.capture", 0)),
                   "rehydrate": int(snap.get("compiled.rehydrate", 0)),
                   "aot_reject": int(snap.get("aot.reject", 0)),
                   "ledger": metrics.ledger_snapshot()}, f)


def cold_start_main(argv: list) -> None:
    n_sales = int(argv[0]) if len(argv) > 0 else 100_000
    out_path = argv[1] if len(argv) > 1 else "SERVE_BENCH.json"
    qnames = (argv[2].split(",") if len(argv) > 2
              else ["q3", "q42", "q52", "q55"])
    trials = int(argv[3]) if len(argv) > 3 else 3

    def run_child(aot_dir):
        env = os.environ.copy()
        env.pop("SRJT_AOT_DIR", None)
        if aot_dir:
            env["SRJT_AOT_DIR"] = aot_dir
        fd, res = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--cold-child",
                 str(n_sales), ",".join(qnames), res],
                env=env, check=True)
            with open(res) as f:
                return json.load(f)
        finally:
            os.unlink(res)

    print(f"cold-start bench: n_sales={n_sales} mix={qnames} "
          f"trials={trials}", flush=True)
    # decode once so every child rides the memoized dataset files
    from benchmarks import tpcds_data
    tpcds_data.generate(n_sales=n_sales, n_items=2000, n_stores=12, seed=5)

    with tempfile.TemporaryDirectory(prefix="srjt_aot_") as root:
        # baseline: every trial a FRESH empty store — each process pays
        # the full capture→trace→compile tax (plus store writes, honestly
        # counted against the baseline)
        baseline = []
        for i in range(trials):
            r = run_child(os.path.join(root, f"empty{i}"))
            assert r["capture"] > 0, "baseline must capture live"
            baseline.append(r)
            print(f"  baseline[{i}]: capture={r['capture']} "
                  f"first-request {sorted(r['first_request_ms'].values())}",
                  flush=True)
        store = os.path.join(root, "store")
        populate = run_child(store)
        assert populate["capture"] > 0
        print(f"  populate: capture={populate['capture']} → {store}",
              flush=True)
        warm = []
        for i in range(trials):
            r = run_child(store)
            assert r["capture"] == 0, (
                f"warm trial {i} performed {r['capture']} capture runs — "
                "the zero-compile contract is broken")
            assert r["rehydrate"] >= len(qnames)
            assert r["hashes"] == baseline[0]["hashes"], (
                "rehydrated results diverged from live-capture results")
            warm.append(r)
            print(f"  warm[{i}]: capture=0 rehydrate={r['rehydrate']} "
                  f"first-request {sorted(r['first_request_ms'].values())}",
                  flush=True)

    def pool(rs):
        lat = [v for r in rs for v in r["first_request_ms"].values()]
        return {"p50_ms": round(float(np.percentile(lat, 50)), 1),
                "p99_ms": round(float(np.percentile(lat, 99)), 1),
                "mean_ms": round(float(np.mean(lat)), 1)}

    base_p, warm_p = pool(baseline), pool(warm)
    speedup = round(base_p["p99_ms"] / max(warm_p["p99_ms"], 1e-9), 2)
    entry = {
        "n_sales": n_sales, "queries": qnames, "trials": trials,
        "baseline_empty_store": base_p,
        "warm_populated_store": warm_p,
        "p99_speedup": speedup,
        "warm_capture_runs": 0,
        "warm_rehydrates": int(sum(r["rehydrate"] for r in warm)),
        "responses_identical": True,
        "per_query_first_request_ms": {
            q: {"baseline_ms": round(float(np.mean(
                    [r["first_request_ms"][q] for r in baseline])), 1),
                "warm_ms": round(float(np.mean(
                    [r["first_request_ms"][q] for r in warm])), 1)}
            for q in qnames}}
    print(f"cold start: baseline p99 {base_p['p99_ms']:.0f} ms → warm p99 "
          f"{warm_p['p99_ms']:.0f} ms ({speedup:.1f}x)", flush=True)
    results = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            results = json.load(f)
    results["cold_start"] = entry
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {out_path} (cold_start entry)", flush=True)


def main():
    argv = list(sys.argv[1:])
    if argv and argv[0] == "--cold-child":
        cold_child(int(argv[1]), argv[2].split(","), argv[3])
        return
    if argv and argv[0] == "--cold-start":
        cold_start_main(argv[1:])
        return
    n_devices = 1
    if "--devices" in argv:
        i = argv.index("--devices")
        n_devices = int(argv[i + 1])
        del argv[i:i + 2]
    n_sales = int(argv[0]) if len(argv) > 0 else 200_000
    out_path = argv[1] if len(argv) > 1 else "SERVE_BENCH.json"
    qnames = (argv[2].split(",") if len(argv) > 2
              else ["q3", "q42", "q52", "q55"])
    n_requests = int(argv[3]) if len(argv) > 3 else 32
    from spark_rapids_jni_tpu.utils import knobs
    workers = knobs.get("SRJT_SERVE_WORKERS")

    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu import exec as xc
    from spark_rapids_jni_tpu.models import tpcds
    from spark_rapids_jni_tpu.utils import metrics

    metrics.set_enabled(True)   # the wait histograms ARE the deliverable

    print(f"backend: {jax.default_backend()}  n_sales: {n_sales}  "
          f"mix: {qnames}  requests: {n_requests}  workers: {workers}",
          flush=True)
    files = tpcds_data.generate(n_sales=n_sales, n_items=2000,
                                n_stores=12, seed=5)
    tables = tpcds.load_tables(files)
    for c in tables["store_sales"].columns:
        np.asarray(c.data[:1])          # force fact upload out of band

    mix = [(f"req{i}", qnames[i % len(qnames)]) for i in range(n_requests)]
    results = {"n_sales": n_sales, "queries": qnames,
               "requests": n_requests, "workers": workers}

    # oracle + serial eager timing in one pass
    oracle = {}
    t0 = time.perf_counter()
    for _, q in mix:
        out = canon(tpcds.QUERIES[q](tables))
        oracle.setdefault(q, out)
    serial_s = time.perf_counter() - t0
    results["serial_eager"] = {
        "wall_s": round(serial_s, 3),
        "qps": round(n_requests / serial_s, 2)}
    print(f"serial eager:    {n_requests / serial_s:7.2f} qps", flush=True)

    plans = xc.PlanCache()
    for q in qnames:                    # warm the cache out of band
        jax.block_until_ready(plans.run(q, tpcds.QUERIES[q], tables))
        jax.block_until_ready(plans.run(q, tpcds.QUERIES[q], tables))

    t0 = time.perf_counter()
    serial_out = [canon(plans.run(q, tpcds.QUERIES[q], tables))
                  for _, q in mix]
    sc_s = time.perf_counter() - t0
    assert all(identical(out, oracle[q]) for out, (_, q) in
               zip(serial_out, mix)), "serial compiled diverged"
    results["serial_compiled"] = {
        "wall_s": round(sc_s, 3), "qps": round(n_requests / sc_s, 2)}
    print(f"serial compiled: {n_requests / sc_s:7.2f} qps", flush=True)

    # coalesce_ms=0: this phase measures pure interleaving (the pre-
    # batching runtime) so the batched sweep below has a clean baseline
    with xc.QueryScheduler(workers=workers, plan_cache=plans,
                           coalesce_ms=0) as sched:
        t0 = time.perf_counter()
        tickets = [sched.submit(q, tpcds.QUERIES[q], tables)
                   for _, q in mix]
        outs = [tk.result(timeout=600) for tk in tickets]
        conc_s = time.perf_counter() - t0
    bad = sum(not identical(canon(out), oracle[q])
              for out, (_, q) in zip(outs, mix))
    assert bad == 0, f"{bad} concurrent responses diverged from serial"
    results["concurrent"] = {
        "wall_s": round(conc_s, 3),
        "qps": round(n_requests / conc_s, 2),
        "speedup_vs_serial": round(serial_s / conc_s, 2),
        "speedup_vs_serial_compiled": round(sc_s / conc_s, 2),
        "queue_wait_ms": hist_pcts(metrics, "exec.queue_wait_ms"),
        "e2e_ms": hist_pcts(metrics, "exec.e2e_ms"),
        "stage_attribution": stage_attribution(metrics),
        "responses_identical": True}
    print(f"concurrent:      {n_requests / conc_s:7.2f} qps "
          f"({serial_s / conc_s:.1f}x serial eager, "
          f"{sc_s / conc_s:.1f}x serial compiled)", flush=True)

    # multi-device phase (--devices N): the same mix over N per-device
    # replicas.  Per-device QPS comes from the runtime's own counters;
    # failover counters should be zero in a fault-free run.
    if n_devices > 1:
        avail = jax.local_device_count()
        n_dev = min(n_devices, avail)
        if n_dev < n_devices:
            print(f"multi-device: only {avail} local devices, "
                  f"running {n_dev} replicas", flush=True)
        metrics.reset()
        # own plan cache: n_dev per-device variants of every query would
        # evict the single-device entries the later phases replay warm
        mplans = xc.PlanCache(cap=max(32, 2 * n_dev * len(qnames)))
        with xc.QueryScheduler(workers=max(workers, n_dev), devices=n_dev,
                               plan_cache=mplans, coalesce_ms=0,
                               queue_depth=max(64, n_requests)) as msched:
            # warm every (replica, query) plan variant out of band —
            # which replica serves a submit() is wakeup order, so warming
            # through the queue cannot cover them all deterministically.
            # Two runs per variant: capture-compile, then the checked
            # first replay that validates the tape.
            for rep in msched.replicas:
                for q in qnames:
                    with rep.scope():
                        placed = rep.place(tables)
                        for _ in range(2):
                            jax.block_until_ready(msched.plans.run(
                                q, tpcds.QUERIES[q], placed,
                                variant=f"d{rep.index}"))
            # settle: the n_dev * len(qnames) compiles above leave a
            # transient (allocator/page churn) that depresses the next
            # few seconds of dispatch on a shared-core host — absorb it
            # out of band so the measured run sees steady state
            for tk in [msched.submit(q, tpcds.QUERIES[q], tables)
                       for _, q in mix]:
                tk.result(timeout=600)
            metrics.reset()
            t0 = time.perf_counter()
            tickets = [msched.submit(q, tpcds.QUERIES[q], tables)
                       for _, q in mix]
            outs = [tk.result(timeout=600) for tk in tickets]
            md_s = time.perf_counter() - t0
            rep_names = [r.name for r in msched.replicas]
        bad = sum(not identical(canon(out), oracle[q])
                  for out, (_, q) in zip(outs, mix))
        assert bad == 0, f"{bad} multi-device responses diverged"
        snap = metrics.snapshot()["counters"]
        per_dev = {name: int(snap.get(
            "exec.device." + name.replace(":", "") + ".completed", 0))
            for name in rep_names}
        results["multi_device"] = {
            "devices": n_dev,
            "wall_s": round(md_s, 3),
            "qps": round(n_requests / md_s, 2),
            "qps_vs_single_device": round(conc_s / md_s, 2),
            "per_device_completed": per_dev,
            "per_device_qps": {name: round(c / md_s, 2)
                               for name, c in per_dev.items()},
            "devices_used": sum(1 for c in per_dev.values() if c),
            "failover": {k: int(v) for k, v in sorted(snap.items())
                         if k.startswith("exec.failover.")
                         or k == "exec.quarantined"},
            "queue_wait_ms": hist_pcts(metrics, "exec.queue_wait_ms"),
            "e2e_ms": hist_pcts(metrics, "exec.e2e_ms"),
            "responses_identical": True}
        print(f"multi-device ({n_dev}): {n_requests / md_s:7.2f} qps "
              f"({conc_s / md_s:.2f}x single-device concurrent, "
              f"{results['multi_device']['devices_used']} devices used)",
              flush=True)
        # release the phase's replicated tables + variant executables and
        # re-settle the single-device path before the paced phases below
        del msched, mplans
        import gc
        gc.collect()
        for _, q in mix:
            jax.block_until_ready(plans.run(q, tpcds.QUERIES[q], tables))
        metrics.reset()

    # batched offered-load sweep: paced open-loop arrivals at 1x/2x/4x
    # the serial-compiled ceiling.  Above 1x a serial server saturates
    # and queue wait grows without bound; coalescing collapses the
    # backlog of same-plan requests into shared launches, so measured
    # throughput tracks the OFFERED rate while queue wait stays flat —
    # the cross-request batching deliverable, measured.
    counter_acc = dict(metrics.snapshot()["counters"])
    sc_qps = n_requests / sc_s
    from spark_rapids_jni_tpu.utils import knobs as _knobs
    results["batched"] = {"coalesce_window_ms": float(
        _knobs.get("SRJT_EXEC_COALESCE_MS")), "loads": {}}
    for mult in (1, 2, 4):
        metrics.reset()
        rate = sc_qps * mult
        n_load = n_requests * mult
        lmix = [(f"req{i}", qnames[i % len(qnames)]) for i in range(n_load)]
        with xc.QueryScheduler(workers=workers, plan_cache=plans,
                               queue_depth=max(64, n_load)) as bsched:
            t0 = time.perf_counter()
            tickets = []
            for i, (_, q) in enumerate(lmix):
                lag = t0 + i / rate - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                tickets.append(bsched.submit(q, tpcds.QUERIES[q], tables))
            outs = [tk.result(timeout=600) for tk in tickets]
            bat_s = time.perf_counter() - t0
        bad = sum(not identical(canon(out), oracle[q])
                  for out, (_, q) in zip(outs, lmix))
        assert bad == 0, f"{bad} batched responses diverged at {mult}x"
        snap = metrics.snapshot()
        bh = snap["histograms"].get("exec.batch.size")
        results["batched"]["loads"][f"{mult}x"] = {
            "offered_qps": round(rate, 2),
            "requests": n_load,
            "wall_s": round(bat_s, 3),
            "qps": round(n_load / bat_s, 2),
            "qps_vs_serial_compiled": round((n_load / bat_s) / sc_qps, 2),
            "queue_wait_ms": hist_pcts(metrics, "exec.queue_wait_ms"),
            "e2e_ms": hist_pcts(metrics, "exec.e2e_ms"),
            "stage_attribution": stage_attribution(metrics),
            "batch_sizes": None if bh is None else {
                "launches": bh["count"], "max": bh["max"],
                "mean": round(bh["total"] / bh["count"], 2)},
            "responses_identical": True}
        for k, v in snap["counters"].items():
            counter_acc[k] = counter_acc.get(k, 0) + v
        print(f"batched {mult}x load: {n_load / bat_s:7.2f} qps "
              f"({(n_load / bat_s) / sc_qps:.2f}x serial compiled, "
              f"batch max {0 if bh is None else bh['max']:.0f})",
              flush=True)
    metrics.reset()

    # flight-recorder overhead: the same 1x paced load with the always-on
    # ring OFF, then ON.  The recorder's contract is that it is cheap
    # enough to never turn off; this measures that claim on the serving
    # hot path (a handful of dict builds + deque appends per request).
    from spark_rapids_jni_tpu.utils import flight

    def paced_1x():
        with xc.QueryScheduler(workers=workers, plan_cache=plans,
                               queue_depth=max(64, n_requests)) as fsched:
            t0 = time.perf_counter()
            tickets = []
            for i, (_, q) in enumerate(mix):
                lag = t0 + i / sc_qps - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                tickets.append(fsched.submit(q, tpcds.QUERIES[q], tables))
            for tk in tickets:
                tk.result(timeout=600)
            return time.perf_counter() - t0

    paced_1x()                          # warm both paths out of band
    flight.set_enabled(False)
    off_s = min(paced_1x() for _ in range(2))
    flight.set_enabled(True)
    on_s = min(paced_1x() for _ in range(2))
    flight.set_enabled(None)            # back to the env knob
    overhead_pct = (on_s - off_s) / off_s * 100
    results["flight_overhead"] = {
        "off_wall_s": round(off_s, 3), "on_wall_s": round(on_s, 3),
        "overhead_pct": round(overhead_pct, 2), "budget_pct": 2.0}
    print(f"flight recorder: off {n_requests / off_s:7.2f} qps, "
          f"on {n_requests / on_s:7.2f} qps "
          f"({overhead_pct:+.2f}% wall)", flush=True)
    metrics.reset()

    # degraded phase: every request over-caps the in-flight ledger →
    # exclusive admission on the sorted engine; must complete, bit-exact
    with xc.QueryScheduler(workers=workers, inflight_bytes=4096) as dsched:
        t0 = time.perf_counter()
        tickets = [dsched.submit(q, tpcds.QUERIES[q], tables)
                   for _, q in mix]
        outs = [tk.result(timeout=600) for tk in tickets]
        deg_s = time.perf_counter() - t0
        degraded = sum(tk.degraded for tk in tickets)
    bad = sum(not identical(canon(out), oracle[q])
              for out, (_, q) in zip(outs, mix))
    assert bad == 0, f"{bad} degraded responses diverged from serial"
    assert degraded > 0, "tight cap should have degraded requests"
    results["degraded"] = {
        "wall_s": round(deg_s, 3),
        "qps": round(n_requests / deg_s, 2),
        "degraded_requests": int(degraded),
        "responses_identical": True}
    print(f"degraded (4 KiB cap): {n_requests / deg_s:6.2f} qps, "
          f"{degraded}/{n_requests} degraded, all identical", flush=True)

    for k, v in metrics.snapshot()["counters"].items():
        counter_acc[k] = counter_acc.get(k, 0) + v
    results["counters"] = {k: v for k, v in sorted(counter_acc.items())
                           if k.startswith(("exec.", "compiled.",
                                            "join.engine."))}
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {out_path}", flush=True)


if __name__ == "__main__":
    main()
