#!/usr/bin/env python
"""Mortgage ETL timing (BASELINE config #5) → MORTGAGE_BENCH.json.

Round-3 state: the eager pipeline spent ~300 s producing a (300, 9)
feature matrix — per-loan string-parse syncs and eager dispatches through
the tunnel.  Round 4 compiles the whole decode-free plan
(``models.mortgage.etl_tables``) into ONE program via the capture/replay
machinery (``models/compiled.py``), so the steady state is a single
dispatch.  Reported:

  decode_s   — parquet → device tables (host staging + upload)
  cold_s     — eager capture run (records the sync tape) + fused compile
  warm_s     — one-dispatch re-execution, wall incl. result pull
  steady_ms  — trip-count-differenced in-jit time per execution

The tail extends the demo end-to-end into a trained model (the ``ml/``
handoff): the ETL output packs into an on-device feature matrix
(``models.mortgage.feature_spec``), a logistic "ever delinquent" model
trains through the fused-epoch harness (``train_rows_per_s``), and the
final loss is checked against a sklearn logistic-regression reference on
the identical standardized features (``sklearn_parity``).

Usage: python tools/mortgage_bench.py [n_loans] [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax

jax.config.update("jax_compilation_cache_dir", ".jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def main():
    n_loans = int(sys.argv[1]) if len(sys.argv) > 1 else 300
    out_path = sys.argv[2] if len(sys.argv) > 2 else "MORTGAGE_BENCH.json"
    print(f"backend: {jax.default_backend()}  n_loans: {n_loans}",
          flush=True)

    from benchmarks import mortgage_data
    from spark_rapids_jni_tpu.models import mortgage
    from spark_rapids_jni_tpu.models.compiled import compile_query
    from spark_rapids_jni_tpu.utils import syncs
    from tools.query_bench import steady_per_iter

    files = mortgage_data.generate(n_loans=n_loans, seed=11)
    res = {"n_loans": n_loans}

    t0 = time.perf_counter()
    tables = mortgage.load_tables(files)
    for t in tables.values():
        for c in t.columns:
            np.asarray(c.data[:1])
    res["decode_s"] = round(time.perf_counter() - t0, 2)
    print(f"decode: {res['decode_s']}s", flush=True)

    syncs.reset_sync_count()
    t0 = time.perf_counter()
    cq = compile_query(mortgage.etl_tables, tables)
    jax.block_until_ready([c.data for c in cq.expected.columns])
    np.asarray(cq.expected[0].data[:1])
    res["cold_s"] = round(time.perf_counter() - t0, 2)
    res["cold_syncs"] = syncs.reset_sync_count()
    print(f"cold: {res['cold_s']}s  syncs={res['cold_syncs']}", flush=True)

    out = cq.run(tables)           # compile the fused + size programs
    np.asarray(out[0].data[:1])
    syncs.reset_sync_count()
    t0 = time.perf_counter()
    out = cq.run(tables)           # checked: staleness guard sync included
    jax.block_until_ready([c.data for c in out.columns])
    np.asarray(out[0].data[:1])
    res["warm_s"] = round(time.perf_counter() - t0, 3)
    res["warm_syncs"] = syncs.reset_sync_count()
    t0 = time.perf_counter()
    out = cq.run_unchecked(tables)  # the one-dispatch steady form
    jax.block_until_ready([c.data for c in out.columns])
    np.asarray(out[0].data[:1])
    res["warm_unchecked_s"] = round(time.perf_counter() - t0, 3)
    res["rows_out"] = out.num_rows
    print(f"warm: {res['warm_s']}s  syncs={res['warm_syncs']}  "
          f"rows={res['rows_out']}", flush=True)

    per = steady_per_iter(cq._prog, tables)
    res["steady_ms"] = round(per * 1e3, 1) if per is not None else None
    print(f"steady: {res['steady_ms']} ms", flush=True)

    # --- ETL → trained model: the ml/ handoff on the ETL output ------------
    import jax.numpy as jnp
    from spark_rapids_jni_tpu import ml

    spec = mortgage.feature_spec()
    t0 = time.perf_counter()
    fb = spec.pack(out, mortgage.FEATURE_COLS)
    fb.X.block_until_ready()
    res["pack_s"] = round(time.perf_counter() - t0, 3)

    # standardize on-device (dollar/day-scale lanes would swamp the logits);
    # sklearn sees the identical standardized matrix
    mean = jnp.mean(fb.X, axis=0)
    std = jnp.maximum(jnp.std(fb.X, axis=0), jnp.float32(1e-6))
    fb = ml.FeatureBatch((fb.X - mean) / std, fb.y, fb.feature_names)

    epochs = 300
    pipe = ml.BatchPipeline(fb, batch_size=32, seed=11)
    tr = ml.Trainer(ml.logistic_regression(), ml.sgd(lr=0.5, momentum=0.9))
    fit = tr.fit(pipe, 2)          # warm the shuffle + fused-epoch programs
    syncs.reset_sync_count()
    t0 = time.perf_counter()
    fit = tr.fit(pipe, epochs)
    train_s = time.perf_counter() - t0
    res["train_s"] = round(train_s, 3)
    res["train_epochs"] = epochs
    res["train_syncs"] = syncs.reset_sync_count()
    res["train_rows_per_s"] = round(pipe.rows_per_epoch * epochs / train_s)
    res["final_loss"] = round(fit.final_loss, 5)
    print(f"train: {res['train_s']}s  {res['train_rows_per_s']} rows/s  "
          f"loss={res['final_loss']}  syncs={res['train_syncs']}", flush=True)

    try:
        from sklearn.linear_model import LogisticRegression
        from sklearn.metrics import log_loss
        hX, hy = np.asarray(fb.X), np.asarray(fb.y)
        ref = LogisticRegression(penalty=None, max_iter=2000).fit(hX, hy)
        res["sklearn_loss"] = round(
            float(log_loss(hy, ref.predict_proba(hX))), 5)
        res["sklearn_parity"] = bool(
            res["final_loss"] <= res["sklearn_loss"] * 1.1 + 0.02)
        print(f"sklearn: loss={res['sklearn_loss']}  "
              f"parity={res['sklearn_parity']}", flush=True)
    except ImportError:            # sklearn is optional on minimal images
        res["sklearn_loss"] = None
        res["sklearn_parity"] = None

    with open(out_path, "w") as f:
        json.dump(res, f, indent=1)
    print("wrote", out_path, flush=True)


if __name__ == "__main__":
    main()
