#!/usr/bin/env python
"""SQL front-end overhead benchmark → SQL_BENCH.json.

Measures what the SQL surface ADDS on top of pre-built plan trees, per
corpus query (``models/tpcds_sql.py``):

  parse_us     — tokenizer + recursive-descent parse alone
  bind_us      — parse + name resolution into the raw IR tree
  cold_us      — parse + bind + rule optimization (memo bypassed):
                 the full cost of the first-ever submission of a text
  hand_us      — building + optimizing the equivalent hand tree: the
                 pre-built-tree baseline the overhead is measured against
  warm_us      — a repeat ``sql_to_plan`` under ``SRJT_SQL_CACHE``: one
                 dict probe, which is why a warm SQL submission is
                 amortized-FREE against pre-built trees (and the plan
                 cache dedupes the compile via the shared fingerprint)

Pure host-side work — no device, no tables, no decode.  Run anywhere:

    python tools/sql_bench.py [repeats] [out.json]
"""

import json
import statistics
import sys
import time

sys.path.insert(0, ".")


def _best_us(fn, repeats: int) -> float:
    """Median-of-repeats wall time in microseconds."""
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        samples.append((time.perf_counter() - t0) * 1e6)
    return round(statistics.median(samples), 1)


def main():
    repeats = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    out_path = sys.argv[2] if len(sys.argv) > 2 else "SQL_BENCH.json"

    from spark_rapids_jni_tpu import sql as sql_fe
    from spark_rapids_jni_tpu.models import tpcds_sql as TS
    from spark_rapids_jni_tpu.plan import ir, rules
    from spark_rapids_jni_tpu.sql import binder, parser

    results = {"repeats": repeats, "queries": {}}
    for name in TS.QUERY_NAMES:
        text = TS.SQL[name]
        params = TS.PARAMS.get(name, {})
        schemas = TS.TABLE_SCHEMAS

        parse_us = _best_us(lambda: parser.parse(text), repeats)
        bind_us = _best_us(
            lambda: binder.bind(parser.parse(text), schemas, params, text),
            repeats)
        cold_us = _best_us(
            lambda: sql_fe.sql_to_plan(text, schemas, params,
                                       stats=None, optimize=True)
            if sql_fe.clear_cache() is None else None, repeats)
        hand_us = _best_us(
            lambda: rules.optimize(TS.hand_tree(name), schemas), repeats)
        sql_fe.clear_cache()
        sql_fe.sql_to_plan(text, schemas, params)          # prime the memo
        warm_us = _best_us(
            lambda: sql_fe.sql_to_plan(text, schemas, params), repeats)

        # the differential invariant the whole design rests on
        fp_sql = ir.fingerprint(sql_fe.sql_to_plan(text, schemas, params))
        fp_hand = ir.fingerprint(rules.optimize(TS.hand_tree(name),
                                                schemas).tree)
        results["queries"][name] = {
            "parse_us": parse_us, "bind_us": bind_us, "cold_us": cold_us,
            "hand_us": hand_us, "warm_us": warm_us,
            "overhead_cold_us": round(cold_us - hand_us, 1),
            "fingerprint_shared": fp_sql == fp_hand,
        }
        print(f"{name:>20}: parse {parse_us:7.1f}us  cold {cold_us:7.1f}us"
              f"  hand {hand_us:7.1f}us  warm {warm_us:6.1f}us  "
              f"fp_shared={fp_sql == fp_hand}", flush=True)

    q = results["queries"]
    results["summary"] = {
        "n_queries": len(q),
        "all_fingerprints_shared": all(e["fingerprint_shared"]
                                       for e in q.values()),
        "median_cold_overhead_us": round(statistics.median(
            e["overhead_cold_us"] for e in q.values()), 1),
        "median_warm_us": round(statistics.median(
            e["warm_us"] for e in q.values()), 1),
    }
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"\n{results['summary']}")
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
