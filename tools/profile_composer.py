#!/usr/bin/env python
"""Composer-kernel throughput at the bench string geometry → stdout.

Measures the streaming ragged-composer (rowconv/composer.py) end-to-end and
kernel-only at the strings_mixed12_1M bench geometry (1M rows, 4 string
columns, ~125B rows), before wiring it into convert_to_rows.  Also times
the ragged.unpack direction (fixed-region extraction for from_rows).

Usage: python tools/profile_composer.py [n_rows]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.rowconv import composer, ragged


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    rng = np.random.default_rng(0)
    print(f"backend: {jax.default_backend()}  n={n}", flush=True)

    # bench-like geometry: fixed+validity region 83B, 4 string cols 0..24B
    fpv = 83
    nvar = 4
    lens = [rng.integers(0, 25, n).astype(np.int64) for _ in range(nvar)]
    src_offs = []
    srcs = []
    fixed_offs = np.arange(n + 1, dtype=np.int64) * fpv
    src_offs.append(fixed_offs)
    srcs.append(jnp.asarray(
        rng.integers(0, 256, n * fpv, dtype=np.uint8)))
    for v in range(nvar):
        o = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens[v], out=o[1:])
        src_offs.append(o)
        srcs.append(jnp.asarray(
            rng.integers(0, 256, max(int(o[-1]), 1), dtype=np.uint8)))

    row_sizes = fpv + sum(lens)
    row_sizes = -(-row_sizes // 8) * 8          # 8B-aligned JCUDF rows
    dst_offs = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_sizes, out=dst_offs[1:])
    total = int(dst_offs[-1])
    print(f"total {total/1e6:.1f} MB, avg row {total/n:.1f} B", flush=True)

    all_lens = [fixed_offs[1:] - fixed_offs[:-1]] + lens

    t0 = time.perf_counter()
    plan = composer.plan_compose(src_offs, dst_offs,
                                 [int(s.shape[0]) for s in srcs])
    t_plan = time.perf_counter() - t0
    print(f"plan: {t_plan*1e3:.1f} ms  RB={plan.RB} nblocks={plan.nblocks} "
          f"rsb={plan.rsb} win_rows={plan.win_rows} cap={plan.cap_rows}",
          flush=True)

    t0 = time.perf_counter()
    wb_np = composer.plan_prefetch(plan, src_offs)
    wb = [jnp.asarray(w) for w in wb_np]
    mb_np = (np.minimum(np.arange(plan.nblocks, dtype=np.int64) * plan.RB, n)
             * plan.S // composer.LANE).astype(np.int32)
    mb = jnp.asarray(mb_np)
    meta = composer.build_meta(
        plan, [jnp.asarray(o) for o in src_offs],
        [jnp.asarray(l) for l in all_lens], jnp.asarray(dst_offs))
    meta.block_until_ready()
    t_meta = time.perf_counter() - t0
    print(f"prefetch+meta: {t_meta*1e3:.1f} ms", flush=True)

    t0 = time.perf_counter()
    out = composer.compose(plan, wb, mb, meta, srcs)
    np.asarray(out[:2])
    t_cold = time.perf_counter() - t0
    print(f"compose cold: {t_cold:.2f} s", flush=True)

    for _ in range(3):
        t0 = time.perf_counter()
        out = composer.compose(plan, wb, mb, meta, srcs)
        np.asarray(out[:2])
        t_warm = time.perf_counter() - t0
        print(f"compose warm: {t_warm*1e3:.1f} ms  "
              f"{total/t_warm/1e9:.2f} GB/s", flush=True)

    # correctness spot check vs the XLA oracle on a small prefix
    ns = 4096
    sub_offs = [o[:ns + 1] for o in src_offs]
    sub_lens = [l[:ns] for l in all_lens]
    sub_dst = dst_offs[:ns + 1]
    ref = composer.compose_xla(sub_offs, sub_lens, sub_dst, srcs,
                               int(sub_dst[-1]))
    pad = (-out.shape[0]) % 128
    got = np.asarray(ragged.u32_to_u8(
        jnp.pad(out, (0, pad))))[:int(sub_dst[-1])]
    ok = bool((np.asarray(ref) == got).all())
    print(f"prefix byte-exact vs XLA oracle: {ok}", flush=True)

    # unpack direction (fixed-region extraction at from_rows geometry)
    flat_u8 = ragged.u32_to_u8(jnp.pad(out, (0, (-out.shape[0]) % 128)))
    t0 = time.perf_counter()
    dense = ragged.unpack(flat_u8, dst_offs, fpv)
    np.asarray(dense[:1, :1])
    t_cold = time.perf_counter() - t0
    print(f"unpack(fpv={fpv}) cold: {t_cold:.2f} s", flush=True)
    for _ in range(2):
        t0 = time.perf_counter()
        dense = ragged.unpack(flat_u8, dst_offs, fpv)
        np.asarray(dense[:1, :1])
        t_warm = time.perf_counter() - t0
        print(f"unpack warm: {t_warm*1e3:.1f} ms  "
              f"{n*fpv/t_warm/1e9:.2f} GB/s (dense bytes)", flush=True)


if __name__ == "__main__":
    main()
