#!/usr/bin/env python
"""Streaming incremental-maintenance bench → STREAM_BENCH.json.

Measures the claim the ``stream/`` subsystem makes: refreshing a
materialized view after an append touches O(delta) work, not O(table).
Three TPC-DS-shaped views (all on the merge-EXACT tier — int64 cents
sums, counts, min/max, integer means — so refreshed results must be
bit-identical, not just close) are registered over the ``store_sales``
fact, then N epochs each append 1/64 of the base table
(``benchmarks/tpcds_data.append_rows``) and measure, per view per epoch:

  refresh_s   — ``ViewRegistry.refresh``: delta row groups decoded,
                partial states merged into the running state, post tail
                re-applied.
  full_s      — from-scratch recompute of the same optimized plan over a
                full ``DeltaTable.scan()`` (min of two runs, so the
                number is warm-compile: the honest steady-state cost of
                NOT maintaining the view).

plus the decoded-work assertion: the ``stream.delta.rowgroups`` counter
must advance by EXACTLY the appended file's row-group count (full
recomputes land on ``stream.scan.rowgroups``, so the two cannot blur),
and every epoch's refresh result must be bit-identical to the full
recompute.

Pass gates (recorded in the JSON): per-view median warm speedup >= 10x,
delta row-group accounting exact everywhere, all epochs bit-identical.

Usage: python tools/stream_bench.py [n_sales] [epochs] [out.json]
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax

jax.config.update("jax_compilation_cache_dir", ".jax_cache")


def canon(table):
    from spark_rapids_jni_tpu.column import force_column
    out = []
    for c in table.columns:
        c = force_column(c)
        out.append(np.asarray(c.data))
        if c.offsets is not None:
            out.append(np.asarray(c.offsets))
        if c.validity is not None:
            out.append(np.asarray(c.validity))
    return out


def identical(a, b) -> bool:
    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y) for x, y in zip(a, b))


def view_plans():
    """Three maintainable TPC-DS-shaped views, exact tier throughout."""
    from spark_rapids_jni_tpu.plan import ir

    def q3_cents():
        # q3's join-filter-aggregate shape with the decimal measure kept
        # as int64 cents (the merge-exact spelling of its revenue sum)
        j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                            ("ss_item_sk",), ("i_item_sk",)),
                    ir.Scan("date_dim"), ("ss_sold_date_sk",), ("d_date_sk",))
        f = ir.Filter(j, ir.And((
            ir.Cmp("==", ir.Col("i_manufact_id"), ir.Lit(436)),
            ir.Cmp("==", ir.Col("d_moy"), ir.Lit(11)))))
        keys = ("d_year", "i_brand_id", "i_brand")
        return ir.Sort(ir.Aggregate(f, keys, (
            ("ss_sales_price_cents", "sum", "sum_cents"),
            ("ss_quantity", "count", "n"))), keys)

    def store_daily():
        # wide-key rollup feed: per store per day revenue + volume
        f = ir.Filter(ir.Scan("store_sales"),
                      ir.Cmp("<=", ir.Col("ss_store_sk"), ir.Lit(8)))
        keys = ("ss_store_sk", "ss_sold_date_sk")
        return ir.Aggregate(f, keys, (
            ("ss_sales_price_cents", "sum", "rev_cents"),
            ("ss_list_price_cents", "sum", "list_cents"),
            ("ss_quantity", "sum", "units"),
            ("ss_quantity", "count", "n")))

    def price_profile():
        # selection + integer-mean family over a small key domain
        keys = ("ss_store_sk",)
        return ir.Sort(ir.Aggregate(ir.Scan("store_sales"), keys, (
            ("ss_sales_price_cents", "min", "min_cents"),
            ("ss_sales_price_cents", "max", "max_cents"),
            ("ss_quantity", "mean", "avg_qty"),
            ("ss_quantity", "count", "n"))), keys)

    return {"q3_cents": q3_cents(), "store_daily": store_daily(),
            "price_profile": price_profile()}


def main():
    n_sales = int(sys.argv[1]) if len(sys.argv) > 1 else 1_600_000
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    out_path = sys.argv[3] if len(sys.argv) > 3 else "STREAM_BENCH.json"
    # one year of dates: every (store, day) cell of the widest view is
    # populated by the base load, so appends extend existing groups and
    # the running state keeps a STABLE shape across epochs — the steady
    # state a streaming view lives in (a changing group count retraces,
    # which is an honest first-sighting cost but not the regime measured)
    n_items, n_dates, rgs = 2000, 366, 4096
    n_append = max(n_sales // 64, 1)

    from benchmarks import tpcds_data
    from spark_rapids_jni_tpu.models import tpcds, tpcds_plans
    from spark_rapids_jni_tpu.plan import lower
    from spark_rapids_jni_tpu.stream import DeltaTable, ViewRegistry
    from spark_rapids_jni_tpu.stream.delta import _file_meta
    from spark_rapids_jni_tpu.utils import metrics

    metrics.set_enabled(True)   # the row-group counters ARE the assertion

    print(f"backend: {jax.default_backend()}  n_sales: {n_sales}  "
          f"append: {n_append} rows x {epochs} epochs  "
          f"row_group_size: {rgs}", flush=True)
    files = tpcds_data.generate(n_sales=n_sales, n_items=n_items,
                                n_dates=n_dates, seed=5, row_group_size=rgs)
    tables = tpcds.load_tables(files)
    statics = {k: tables[k] for k in ("item", "date_dim", "store")}
    schemas = {k: tpcds_plans.TABLE_SCHEMAS[k] for k in statics}

    blobs = [tpcds_data.append_rows(n_append, seed=9000 + e,
                                    n_items=n_items, n_dates=n_dates,
                                    row_group_size=rgs)
             for e in range(1, epochs + 1)]

    # warm pass: run the IDENTICAL append/refresh sequence through a
    # shadow registry first.  Filter and join outputs have data-dependent
    # row counts, so each epoch's delta relation is a shape the jit cache
    # has never seen — the warm pass pays that one-time compile for every
    # (epoch, view) so the measured pass times steady-state refresh work,
    # the same out-of-band warming discipline serve_bench applies to its
    # plan cache.
    wdelta = DeltaTable("store_sales", files=[files["store_sales"]])
    wreg = ViewRegistry(wdelta, statics, schemas)
    wviews = [wreg.register_view(p, name=f"warm:{n}")
              for n, p in view_plans().items()]
    print("warming shape variants (shadow pass)...", flush=True)
    for blob in blobs:
        wdelta.append_file(blob)
        for v in wviews:
            wreg.refresh(v)
    wreg.close()

    delta = DeltaTable("store_sales", files=[files["store_sales"]])
    reg = ViewRegistry(delta, statics, schemas)
    views = {}
    for name, plan in view_plans().items():
        v = reg.register_view(plan, name=name)
        assert v.kind == "incremental", (name, v.reason)
        assert v.exact, name
        views[name] = v

    def full(v):
        cat = lower.TableCatalog(
            {**statics, "store_sales": delta.scan()}, reg.schemas)
        return lower.execute(v.tree, cat, record_stats=False)

    results = {"n_sales": n_sales, "epochs": epochs,
               "append_rows": n_append, "row_group_size": rgs,
               "views": {n: {"kind": v.kind, "exact": v.exact,
                             "epochs": []}
                         for n, v in views.items()}}

    for e in range(1, epochs + 1):
        blob = blobs[e - 1]
        ngroups, _ = _file_meta(blob)
        delta.append_file(blob)
        for name, v in views.items():
            c0 = metrics.counter_value("stream.delta.rowgroups")
            t0 = time.perf_counter()
            got = canon(reg.refresh(v))
            refresh_s = time.perf_counter() - t0
            dgroups = metrics.counter_value("stream.delta.rowgroups") - c0

            t0 = time.perf_counter()
            expect = canon(full(v))
            full1 = time.perf_counter() - t0
            t0 = time.perf_counter()
            expect2 = canon(full(v))
            full_s = min(full1, time.perf_counter() - t0)

            ok = identical(got, expect) and identical(expect, expect2)
            rg_ok = dgroups == len(ngroups)
            results["views"][name]["epochs"].append({
                "epoch": e, "refresh_s": round(refresh_s, 5),
                "full_s": round(full_s, 5),
                "speedup": round(full_s / refresh_s, 2),
                "delta_rowgroups": int(dgroups),
                "appended_rowgroups": len(ngroups),
                "rowgroups_exact": rg_ok, "identical": ok})
            assert ok, f"{name} epoch {e}: refresh diverged from recompute"
            assert rg_ok, (f"{name} epoch {e}: decoded {dgroups} delta row "
                           f"groups, appended {len(ngroups)}")
            print(f"epoch {e} {name:14s}: refresh {refresh_s * 1e3:8.2f} ms"
                  f"  full {full_s * 1e3:8.2f} ms"
                  f"  ({full_s / refresh_s:6.1f}x)  "
                  f"groups {int(dgroups)}/{len(ngroups)}  bit-identical",
                  flush=True)

    all_pass = True
    for name, rec in results["views"].items():
        sp = sorted(ep["speedup"] for ep in rec["epochs"])
        med = sp[len(sp) // 2]
        rec["median_speedup"] = med
        rec["pass_10x"] = med >= 10.0
        rec["rowgroups_exact"] = all(ep["rowgroups_exact"]
                                     for ep in rec["epochs"])
        rec["all_identical"] = all(ep["identical"] for ep in rec["epochs"])
        all_pass &= (rec["pass_10x"] and rec["rowgroups_exact"]
                     and rec["all_identical"])
        print(f"{name:14s}: median {med:6.1f}x  "
              f"{'PASS' if rec['pass_10x'] else 'FAIL'}", flush=True)
    results["counters"] = {
        k: v for k, v in sorted(metrics.snapshot()["counters"].items())
        if k.startswith("stream.")}
    results["pass"] = all_pass
    reg.close()
    with open(out_path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {out_path}  overall: {'PASS' if all_pass else 'FAIL'}",
          flush=True)
    if not all_pass:
        sys.exit(1)


if __name__ == "__main__":
    main()
