#!/usr/bin/env bash
# Query-planner smoke — the plan-IR analog of ci/join_smoke.sh: optimize
# ONE TPC-DS plan tree (q3) with metrics on, assert at least one pushdown
# rule and the join→aggregate fusion actually fired, execute the optimized
# tree against parquet bytes written with small row groups so the
# statistics pruner has something to drop (rowgroups_pruned > 0 in the
# exported counters), and assert the lowered result is bit-identical to
# the hand-fused kernel over the fully decoded tables.
# Artifacts land in target/plan_smoke/ for workflow upload.
#
# Usage: ci/plan_smoke.sh [n_sales] [query]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-50000}"
QUERY="${2:-q3}"
OUT=target/plan_smoke
mkdir -p "$OUT"

echo "== plan smoke: $QUERY over $N_SALES rows =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERY" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
qname = os.environ["SRJT_SMOKE_Q"]

import numpy as np

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import plan as P
from spark_rapids_jni_tpu.column import force_column
from spark_rapids_jni_tpu.models import tpcds, tpcds_plans
from spark_rapids_jni_tpu.plan import ir
from spark_rapids_jni_tpu.utils import metrics

# small row groups: the footer-statistics pruner needs >1 group per
# DIMENSION file — q3's pushed-down predicates land on item/date_dim, so
# those tables (1–2k rows) must split into groups with distinct stats
files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, seed=5,
                            row_group_size=256)
tables = tpcds.load_tables(files)

metrics.reset()
res = tpcds_plans.optimized(qname)
fired = {ev.rule for ev in res.events}
print(f"{qname}: {res.passes} optimizer pass(es), rules fired: "
      f"{sorted(fired)}")
assert fired & {"projection_pushdown", "filter_pushdown"}, fired
assert "fuse_join_aggregate" in fired, fired
assert any(isinstance(n, ir.FusedJoinAggregate) for n in ir.walk(res.tree))
# fusion is DETECTED, never hand-wired into the plan definition
assert not any(isinstance(n, ir.FusedJoinAggregate)
               for n in ir.walk(tpcds_plans.PLANS[qname]()))

with metrics.span(f"plan:{qname}", n_sales=n_sales):
    got = P.execute(res.tree, P.FileCatalog(dict(files)),
                    record_stats=False)
print(f"{qname}: {got.num_rows} rows (optimized plan, pruned scan)")

trace_path = metrics.export_chrome_trace(os.path.join(out, "trace.json"))
with open(os.path.join(out, "explain.txt"), "w") as f:
    f.write(P.explain(tpcds_plans.PLANS[qname](),
                      tpcds_plans.TABLE_SCHEMAS))

with open(trace_path) as f:
    doc = json.load(f)
counters = doc["srjtCounters"]
assert counters.get("plan.scan.columns_pruned", 0) >= 1, counters
assert counters.get("plan.scan.rowgroups_pruned", 0) >= 1, counters
print("columns pruned:", counters["plan.scan.columns_pruned"],
      "| row groups pruned:", counters["plan.scan.rowgroups_pruned"],
      "| trace well-formed:", trace_path)

# differential: the pruned plan execution must be bit-identical to the
# hand-fused kernel over the fully decoded tables
expect = getattr(tpcds, qname)(tables)
assert got.num_rows == expect.num_rows, (got.num_rows, expect.num_rows)
for i in range(len(expect.columns)):
    a, b = force_column(expect[i]), force_column(got[i])
    assert a.dtype.id == b.dtype.id, f"col {i} dtype"
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data),
                                  err_msg=f"col {i}")
    if a.offsets is not None:
        np.testing.assert_array_equal(np.asarray(a.offsets),
                                      np.asarray(b.offsets))
print("optimized plan result identical to hand-fused kernel")
PYEOF

echo "plan smoke OK"
