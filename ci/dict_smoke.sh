#!/usr/bin/env bash
# Dictionary-string fast path smoke — run a TPC-DS string query over
# dictionary-encoded parquet with the dict scan path ON (DictColumn codes
# flow scan→predicate→join→groupby) and OFF (SRJT_DICT_STRINGS=0, the
# materializing baseline), assert the results bit-identical, and assert
# the fast path actually engaged (plan.scan.dict_cols fired) without
# touching string bytes before the output boundary
# (strings.dict.materialize stays 0 through query execution).
#
# Usage: ci/dict_smoke.sh [n_sales] [query]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-50000}"
QUERY="${2:-q_like_brands}"

echo "== dict smoke: $QUERY over $N_SALES rows =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERY" \
python - <<'PYEOF'
import io
import os
import sys

sys.path.insert(0, ".")

n_sales = int(os.environ["SRJT_SMOKE_N"])
qname = os.environ["SRJT_SMOKE_Q"]

import numpy as np
import pyarrow.parquet as pq

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.column import as_dict_column
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.parquet import device_scan
from spark_rapids_jni_tpu.utils import metrics


def redict(raw):
    # the generator writes plain pages; the fast path needs dict pages
    t = pq.read_table(io.BytesIO(raw))
    buf = io.BytesIO()
    pq.write_table(t, buf, compression="SNAPPY", use_dictionary=True)
    return buf.getvalue()


files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, seed=5)
item_raw, store_raw = redict(files["item"]), redict(files["store"])
base = tpcds.load_tables(files)


def load(dict_on):
    os.environ["SRJT_DICT_STRINGS"] = "1" if dict_on else "0"
    try:
        t = dict(base)
        t["item"] = device_scan.scan_table(item_raw,
                                           columns=tpcds.ITEM_COLS)
        t["store"] = device_scan.scan_table(store_raw,
                                            columns=tpcds.STORE_COLS)
        return t
    finally:
        os.environ.pop("SRJT_DICT_STRINGS", None)


metrics.set_enabled(True)
metrics.reset()
td = load(True)
counters = metrics.snapshot()["counters"]
assert counters.get("plan.scan.dict_cols", 0) >= 1, counters
brand = td["item"][tpcds.ITEM_COLS.index("i_brand")]
assert as_dict_column(brand) is not None, "scan did not keep dict codes"
print("dict scan engaged: plan.scan.dict_cols =",
      counters["plan.scan.dict_cols"])

metrics.reset()
got = tpcds.QUERIES[qname](td)
counters = metrics.snapshot()["counters"]
metrics.set_enabled(False)
assert counters.get("strings.dict.predicate", 0) >= 1, counters
assert counters.get("strings.dict.materialize", 0) == 0, counters
print("query ran on codes: strings.dict.predicate =",
      counters["strings.dict.predicate"],
      "| strings.dict.materialize = 0")

tm = load(False)
assert as_dict_column(tm["item"][tpcds.ITEM_COLS.index("i_brand")]) is None
want = tpcds.QUERIES[qname](tm)
assert got.num_rows == want.num_rows, (got.num_rows, want.num_rows)
for i in range(got.num_columns):
    a, b = got[i], want[i]
    assert a.dtype.id == b.dtype.id, f"col {i} dtype"
    if a.dtype.id.name == "STRING":
        assert a.to_pylist() == b.to_pylist(), f"col {i}"
    else:
        np.testing.assert_array_equal(np.asarray(a.data),
                                      np.asarray(b.data),
                                      err_msg=f"col {i}")
print(f"{qname}: {got.num_rows} rows — dict path bit-identical to "
      "materialized path")
PYEOF

echo "dict smoke OK"
