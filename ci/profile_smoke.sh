#!/usr/bin/env bash
# Query-profiling smoke — the EXPLAIN ANALYZE gate: run two TPC-DS plan
# queries (q3, q65) with SRJT_PROFILE=1 and assert (1) the profiled
# result is bit-identical to the unprofiled execution, (2) every node's
# observed rows landed in the profile and mispredictions are computed,
# (3) the exported Chrome trace (with plan.node:* spans nested under the
# query span) parses as JSON and trace_report --by-node renders it,
# (4) the profile JSON artifact lands in SRJT_PROFILE_DIR and
# profile_report.py renders/regression-checks it, and (5) the compile
# ledger shows up in metrics.to_prometheus() and the exposition still
# passes the text-format lint.
# Artifacts land in target/profile_smoke/ for workflow upload.
#
# Usage: ci/profile_smoke.sh [n_sales]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-50000}"
OUT=target/profile_smoke
rm -rf "$OUT"
mkdir -p "$OUT"

echo "== profile smoke: q3+q65 over $N_SALES rows =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_PROFILE_DIR="$OUT/profiles" \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])

import numpy as np

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import plan as P
from spark_rapids_jni_tpu.column import force_column
from spark_rapids_jni_tpu.models import tpcds, tpcds_plans
from spark_rapids_jni_tpu.models.compiled import compile_query
from spark_rapids_jni_tpu.plan import lower, profile
from spark_rapids_jni_tpu.utils import metrics

files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, seed=5)
tables = tpcds.load_tables(files)

metrics.reset()
profile.reset()

def rows(t):
    out = []
    for c in t.columns:
        fc = force_column(c)
        out.append(np.asarray(fc.data))
    return out

for qname in ("q3", "q65"):
    tree = tpcds_plans.optimized(qname).tree
    cat = lower.TableCatalog(tables, tpcds_plans.TABLE_SCHEMAS)
    plain = lower.execute(tree, cat, record_stats=False)

    # profiled execution: explain_analyze force-enables SRJT_PROFILE
    text = profile.explain_analyze(
        tree, tpcds_plans.TABLE_SCHEMAS, tables)
    assert "rows est=" in text and "obs=" in text, text[:400]
    prof = profile.completed(last=1)[0]

    profile.set_enabled(True)
    try:
        with profile.query(qname, P.fingerprint(tree)) as pr:
            got = lower.execute(
                tree, lower.TableCatalog(
                    tables, tpcds_plans.TABLE_SCHEMAS),
                record_stats=False)
    finally:
        profile.set_enabled(None)

    # (1) bit-identical under profiling
    a, b = rows(plain), rows(got)
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(x, y, err_msg=f"{qname} col {i}")
    # (2) every closed node carries observed rows
    nodes = list(pr.nodes())
    assert nodes, "no profiled nodes"
    assert all(n.out_rows is not None for n in nodes), nodes
    root = pr.roots[0]
    assert root.out_rows == plain.num_rows, (root.out_rows,
                                             plain.num_rows)
    with open(os.path.join(out, f"{qname}_explain_analyze.txt"),
              "w") as f:
        f.write(text)
    print(f"{qname}: profiled bit-identical, "
          f"{len(nodes)} nodes, root rows {root.out_rows}")

# (3) Chrome trace with plan.node spans, valid JSON
trace_path = metrics.export_chrome_trace(os.path.join(out, "trace.json"))
with open(trace_path) as f:
    doc = json.load(f)
names = {ev.get("name") for ev in doc["traceEvents"]}
assert any(str(n).startswith("plan.node:") for n in names), sorted(names)
assert "srjtLedger" in doc, list(doc)
print(f"chrome trace OK: {len(doc['traceEvents'])} events "
      f"({sum(1 for n in names if str(n).startswith('plan.node:'))} "
      f"node span names)")

# (4) profile artifacts landed; reports render
pdir = os.environ["SRJT_PROFILE_DIR"]
arts = sorted(os.listdir(pdir))
assert arts, f"no profile artifacts in {pdir}"
for a in arts:
    with open(os.path.join(pdir, a)) as f:
        json.load(f)
print(f"profile artifacts OK: {arts}")

# (5) compile ledger present in the Prometheus exposition + lint.
# compile_query exercises capture + trace + first dispatch.
cq = compile_query(tpcds.q3, tables)
cq.run(tables)
led = metrics.ledger_snapshot()
assert any(v.get("captures") for v in led.values()), led
assert any(v.get("traces") for v in led.values()), led
import re
prom = metrics.to_prometheus()
assert "srjt_compile_ledger" in prom, prom[-800:]
line_re = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"[^\"]*\")*\})? (-?[0-9.e+-]+|\+Inf|-Inf|NaN)$")
type_re = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                     r"(counter|gauge|histogram)$")
for ln in prom.splitlines():
    assert (type_re.match(ln) if ln.startswith("#")
            else line_re.match(ln)), f"prometheus lint: bad line {ln!r}"
with open(os.path.join(out, "metrics.prom"), "w") as f:
    f.write(prom)
print(f"prometheus lint OK: {len(prom.splitlines())} lines, "
      f"ledger plans: {len(led)}")
PYEOF

echo "== trace_report --by-node =="
python tools/trace_report.py "$OUT/trace.json" 12 --by-node

echo "== profile_report =="
python tools/profile_report.py "$OUT/profiles" 12
# self-comparison must report zero regressions (exit 0)
python tools/profile_report.py "$OUT/profiles" 5 --regress "$OUT/profiles"

echo "profile smoke OK"
