#!/usr/bin/env bash
# SQL front-end smoke (sql/ + exec.submit_sql) — the serve-arbitrary-SQL
# runbook, asserted end to end: a mixed TPC-DS slice (joins, rollup,
# semi/anti, UNION ALL, windows) is served twice — once from hand-built
# plan trees, once from SQL text through QueryScheduler.submit_sql — and
# the results must be bit-identical; the SQL submission must land a
# plan-cache HIT on the entry the hand tree compiled (shared structural
# fingerprint, zero extra compiles); a malformed query must raise a
# caret-positioned SqlError AND count a sql_parse_error flight incident;
# and tools/sql_bench.py must report every corpus fingerprint shared.
# Artifacts land in target/sql_smoke/.
#
# Usage: ci/sql_smoke.sh [n_sales] [queries]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-20000}"
QUERIES="${2:-q3,q55,q36_rollup,q16_anti,q_union_channels,q67_rank}"
OUT=target/sql_smoke
rm -rf "$OUT"
mkdir -p "$OUT"

echo "== sql smoke: $QUERIES over $N_SALES rows =="
SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERIES" SRJT_SMOKE_OUT="$OUT" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
python - <<'PYEOF'
"""Serve the mix twice — hand trees vs submit_sql — and assert the SQL
path is a bit-identical, compile-free alias of the hand path."""
import hashlib
import json
import os
import sys

sys.path.insert(0, ".")

import numpy as np

import jax

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu import sql as sql_fe
from spark_rapids_jni_tpu.models import tpcds, tpcds_sql as TS
from spark_rapids_jni_tpu.plan import ir, lower, rules
from spark_rapids_jni_tpu.sql import SqlError
from spark_rapids_jni_tpu.utils import flight, metrics

metrics.set_enabled(True)
flight.set_enabled(True)
qnames = os.environ["SRJT_SMOKE_Q"].split(",")
out_dir = os.environ["SRJT_SMOKE_OUT"]

files = tpcds_data.generate(n_sales=int(os.environ["SRJT_SMOKE_N"]),
                            n_items=300, seed=11)
tables = tpcds.load_tables(files)
SCHEMAS = TS.TABLE_SCHEMAS


def result_hash(result):
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(result):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(a.dtype.str.encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


doc = {"queries": {}}
with xc.QueryScheduler(workers=2) as sched:
    for q in qnames:
        params = TS.PARAMS.get(q, {})
        hand = rules.optimize(TS.hand_tree(q), SCHEMAS).tree
        h_hand = result_hash(
            sched.run(ir.fingerprint(hand), lower.compile_plan(hand,
                                                               SCHEMAS),
                      tables))
        hit0 = metrics.counter_value("exec.plan_cache.hit")
        h_sql = result_hash(
            sched.submit_sql(TS.SQL[q], tables, schemas=SCHEMAS,
                             params=params).result())
        hit1 = metrics.counter_value("exec.plan_cache.hit")
        assert h_sql == h_hand, f"{q}: SQL result diverged from hand tree"
        assert hit1 == hit0 + 1, \
            f"{q}: SQL submission missed the hand tree's plan-cache " \
            f"entry (hit {hit0} -> {hit1}) — fingerprints diverged"
        doc["queries"][q] = {"hash": h_sql, "cache_hit": True}
        print(f"[sql] {q}: bit-identical, plan-cache HIT")

    # a malformed query: caret-positioned error + flight incident
    inc0 = metrics.counter_value("flight.incident.sql_parse_error")
    try:
        sched.submit_sql("SELECT FROM store_sales", tables,
                         schemas=SCHEMAS)
    except SqlError as e:
        assert e.line == 1 and e.col == 8, (e.line, e.col)
        assert "^" in str(e), "caret missing from rendered error"
    else:
        raise AssertionError("malformed SQL did not raise SqlError")
    assert metrics.counter_value(
        "flight.incident.sql_parse_error") == inc0 + 1, \
        "sql_parse_error incident not counted"
    print("[sql] malformed query: caret at 1:8, incident counted")

doc["sql_cache"] = sql_fe.cache_stats()
with open(os.path.join(out_dir, "smoke.json"), "w") as f:
    json.dump(doc, f, indent=1)
PYEOF

echo "== sql bench (parse/bind/optimize overhead, shared fingerprints) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
python tools/sql_bench.py 3 "$OUT/SQL_BENCH.json" > "$OUT/bench.log"
python - "$OUT/SQL_BENCH.json" <<'PYEOF'
import json, sys
s = json.load(open(sys.argv[1]))["summary"]
assert s["all_fingerprints_shared"], \
    "a corpus query's SQL fingerprint diverged from its hand tree"
assert s["median_warm_us"] < s["median_cold_overhead_us"], s
print(f"bench OK: {s['n_queries']} queries, fingerprints shared, "
      f"cold overhead {s['median_cold_overhead_us']}us vs warm "
      f"{s['median_warm_us']}us")
PYEOF

echo "sql smoke OK"
