#!/usr/bin/env bash
# Byte-path staging smoke — scan a multi-row-group, multi-dtype parquet
# through the round-6 raw path (slab-coalesced uploads + pipelined
# walk/stage + forced decode donation) and through the eager path, assert
# the tables bit-identical, and assert the pipeline actually engaged:
# the flight ring must hold >=1 parquet.stage.flush, >=1
# parquet.stage.overlap and >=1 parquet.scan.donate event.
#
# Usage: ci/bytes_smoke.sh [n_rows]
set -euo pipefail
cd "$(dirname "$0")/.."

N_ROWS="${1:-200000}"

echo "== bytes smoke: staged+pipelined+donated scan over $N_ROWS rows =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" SRJT_SMOKE_N="$N_ROWS" \
python - <<'PYEOF'
import io
import os
import sys

sys.path.insert(0, ".")

n = int(os.environ["SRJT_SMOKE_N"])

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_jni_tpu.parquet import device_scan
from spark_rapids_jni_tpu.utils import flight

rng = np.random.default_rng(5)
t = pa.table({
    "qty": pa.array(rng.integers(1, 51, n).astype(np.int64)),
    "price": pa.array((rng.random(n) * 100000).round(2)),
    "ship": pa.array(rng.integers(8000, 9500, n).astype(np.int32)),
    "tag": pa.array([f"tag{v}" for v in rng.integers(0, 40, n)]),
})
buf = io.BytesIO()
pq.write_table(t, buf, compression="SNAPPY", row_group_size=n // 4)
raw = buf.getvalue()


def scan(env):
    for k, v in env.items():
        os.environ[k] = v
    try:
        return device_scan.scan_table(raw)
    finally:
        for k in env:
            del os.environ[k]


eager = scan({"SRJT_STAGE_SLABS": "0", "SRJT_SCAN_DONATE": "0"})

flight.set_enabled(True)
flight.reset()
staged = scan({"SRJT_STAGE_SLABS": "1", "SRJT_STAGE_PIPELINE": "1",
               "SRJT_SCAN_DONATE": "1"})
evs = flight.events()

for a, b in zip(eager.columns, staged.columns):
    np.testing.assert_array_equal(np.asarray(a.data), np.asarray(b.data))
    np.testing.assert_array_equal(np.asarray(a.validity_or_true()),
                                  np.asarray(b.validity_or_true()))
print(f"staged scan bit-identical over {staged.num_rows} rows "
      f"x {staged.num_columns} cols")

kinds = [e["kind"] for e in evs]
flushes = [e for e in evs if e["kind"] == "parquet.stage.flush"]
overlaps = [e for e in evs if e["kind"] == "parquet.stage.overlap"]
donates = [e for e in evs if e["kind"] == "parquet.scan.donate"]
assert flushes, f"no slab flush event in trace: {kinds}"
assert overlaps, f"no walk/stage overlap event in trace: {kinds}"
assert donates, f"no donation event in trace: {kinds}"
slabs = sum(e["slabs"] for e in flushes)
print(f"trace: {slabs} slab transfers, overlap "
      f"{overlaps[-1]['overlap_ms']} ms over {overlaps[-1]['columns']} "
      f"cols, donated {donates[-1]['bytes']} bytes "
      f"({donates[-1]['buffers']} buffers)")
print("bytes smoke OK")
PYEOF
