#!/usr/bin/env bash
# Chaos smoke — the fault-tolerance analog of ci/exec_smoke.sh: serve a
# TPC-DS mix on a 4-replica (forced-host-device) pool, kill one replica
# mid-run with a one-shot injected fatal fault, and assert the chaos
# contract end to end: (1) ZERO failed requests — every response resolves
# bit-identical to the serial oracle, (2) the victim quarantines and its
# requests fail over (``incident:quarantine`` + ``incident:failover`` in
# the flight ring, ``exec.failover.relocated`` counted), (3) the recovery
# probe's canary re-admits the victim (``incident:recovery``, replica
# healthy) within a bounded wait, and (4) device-targeted injection rules
# (``device:`` + ``maxHits``) discriminate by replica scope.  Artifacts
# land in target/chaos_smoke/.
#
# Usage: ci/chaos_smoke.sh [n_sales]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-20000}"
OUT=target/chaos_smoke
mkdir -p "$OUT"

echo "== chaos smoke: one-shot device kill over $N_SALES rows =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=4}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 SRJT_EXEC=1 \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" \
python - <<'PYEOF'
import json
import os
import sys
import time

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])

import numpy as np

import jax

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu.faultinj import injector as finj
from spark_rapids_jni_tpu.faultinj.injector import InjectedDeviceError
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.utils import flight, metrics

metrics.set_enabled(True)
n_dev = min(4, jax.local_device_count())
assert n_dev >= 2, "chaos smoke needs >=2 local devices"

qnames = ["q3", "q42"]
files = tpcds_data.generate(n_sales=n_sales, n_items=2000, n_stores=10,
                            seed=5)
tables = tpcds.load_tables(files)

def canon(result):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]

oracle = {q: canon(tpcds.QUERIES[q](tables)) for q in qnames}
mix = [qnames[i % len(qnames)] for i in range(12)]
inj = finj.get_injector()
flight.reset()

with xc.QueryScheduler(workers=n_dev, devices=n_dev, coalesce_ms=0,
                       probe_base_s=0.05, probe_max_s=0.5) as sched:
    # warm pass (also proves the fault-free multi-device path)
    for q, tk in [(q, sched.submit(q, tpcds.QUERIES[q], tables))
                  for q in mix]:
        got = canon(tk.result(timeout=300))
        assert all(np.array_equal(a, b)
                   for a, b in zip(got, oracle[q])), "warm diverged"

    # one-shot fatal fault: whichever replica serves next dies once
    inj.load_dict({"seed": 3, "sites": {
        "exec.dispatch": {"percent": 100,
                          "injectionType": "device_error",
                          "maxHits": 1}}})
    inj.enable()
    tickets = [(q, sched.submit(q, tpcds.QUERIES[q], tables))
               for q in mix]
    failed = 0
    for q, tk in tickets:
        got = canon(tk.result(timeout=300))
        ok = len(got) == len(oracle[q]) and all(
            np.array_equal(a, b) for a, b in zip(got, oracle[q]))
        failed += not ok
    assert failed == 0, f"{failed} requests failed under chaos"
    assert inj.injected_count == 1, "fault did not fire exactly once"
    relocated = sum(tk.relocations > 0 for _, tk in tickets)
    assert relocated >= 1, "no request failed over"

    # recovery: the probe's canary re-admits the victim
    vi = next(i for i, r in enumerate(sched.replicas)
              if r.resilient.fatal_count >= 1)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        snap = sched.ops_state()["replicas"][vi]
        if snap["state"] == "healthy" and snap["recoveries"] >= 1:
            break
        time.sleep(0.05)
    assert snap["state"] == "healthy" and snap["recoveries"] >= 1, snap
    victim = snap["device"]
    replicas = sched.ops_state()["replicas"]

kinds = {e["kind"] for e in flight.events()
         if e["kind"].startswith("incident:")}
for want in ("incident:quarantine", "incident:failover",
             "incident:recovery"):
    assert want in kinds, f"missing {want} (have {sorted(kinds)})"
counters = metrics.snapshot()["counters"]
assert counters.get("exec.failover.relocated", 0) >= 1, counters
assert counters.get("exec.failover.recovered", 0) >= 1, counters
print(f"chaos OK: victim {victim}, {relocated} relocated, 0 failed, "
      "quarantine+failover+recovery incidents present")

# device-targeted rules discriminate by replica scope (maxHits one-shot)
inj.load_dict({"seed": 1, "sites": {
    "exec.dispatch": {"percent": 100, "injectionType": "device_error",
                      "device": "cpu:1", "maxHits": 1}}})
with finj.device_scope("cpu:0"):
    assert inj.check("exec.dispatch") is None
fired = False
try:
    with finj.device_scope("cpu:1"):
        inj.check("exec.dispatch")
except InjectedDeviceError:
    fired = True
assert fired, "device-targeted rule never fired in its scope"
with finj.device_scope("cpu:1"):
    assert inj.check("exec.dispatch") is None   # maxHits spent
inj.disable()
print("device targeting OK: fires only in scope, one-shot cap honored")

summary = {
    "devices": n_dev,
    "requests": len(mix),
    "failed_requests": 0,
    "relocated": int(relocated),
    "victim": victim,
    "replicas": replicas,
    "failover_counters": {k: int(v) for k, v in sorted(counters.items())
                          if k.startswith("exec.failover.")
                          or k == "exec.quarantined"},
}
with open(os.path.join(out, "summary.json"), "w") as f:
    json.dump(summary, f, indent=1)
print("wrote", os.path.join(out, "summary.json"))
PYEOF

echo "== chaos contract under SRJT_SANITIZE=strict =="
# Runtime sanitizers armed in strict mode: a lock-order inversion taken
# anywhere in the failover/recovery machinery, or an unexpected plan
# recompile, raises at the violation site and fails this smoke.
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=4}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SRJT_SANITIZE=strict \
python -m pytest tests/test_chaos.py -q

echo "chaos smoke OK"
