#!/usr/bin/env bash
# Serving-runtime smoke — the exec/ analog of ci/arena_smoke.sh: serve a
# small TPC-DS mix through the concurrent QueryScheduler and assert the
# serving contract end to end: (1) concurrent responses bit-identical to
# serial eager execution, (2) typed backpressure (ExecQueueFull) and
# deadline errors surface instead of stalls, (3) a tight
# SRJT_EXEC_INFLIGHT_BYTES cap completes the whole mix via degraded
# admission (sorted join engine) with ≥1 exec.admission.degraded counted
# and zero wrong results, (4) a same-plan burst coalesces into batched
# launches (≥1 exec.batch.size sample ≥2) with responses still
# bit-identical, (5) a forced deadline breach (tiny SRJT_EXEC_DEADLINE)
# dumps a flight-recorder incident snapshot that parses and carries the
# breaching request id, and (6) metrics.to_prometheus() passes a
# text-exposition-format lint.  Artifacts land in target/exec_smoke/.
#
# Usage: ci/exec_smoke.sh [n_sales] [queries]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-50000}"
QUERIES="${2:-q3,q42,q55}"
OUT=target/exec_smoke
mkdir -p "$OUT"

echo "== exec smoke: $QUERIES over $N_SALES rows =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 SRJT_EXEC=1 \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERIES" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
qnames = os.environ["SRJT_SMOKE_Q"].split(",")

import numpy as np

import jax

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.utils import metrics

assert xc.enabled(), "SRJT_EXEC gate did not enable"

files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, n_stores=10,
                            seed=5)
tables = tpcds.load_tables(files)

def canon(result):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(result)]

oracle = {q: canon(tpcds.QUERIES[q](tables)) for q in qnames}

# 1) concurrent == serial, through the full runtime (4 workers, mix x4)
mix = [q for q in qnames for _ in range(4)]
with xc.QueryScheduler(workers=4) as sched:
    tickets = [sched.submit(q, tpcds.QUERIES[q], tables) for q in mix]
    for q, tk in zip(mix, tickets):
        got = canon(tk.result(timeout=300))
        assert len(got) == len(oracle[q]) and all(
            np.array_equal(a, b) for a, b in zip(got, oracle[q])), \
            f"{q}: concurrent response differs from serial"
print(f"concurrent identical: {len(mix)} responses over {len(qnames)} "
      "queries")

# 2) typed backpressure + deadline (no stalls, no silent drops)
import time
def slow(tbls, _q=qnames[0]):
    time.sleep(0.05)
    return tpcds.QUERIES[_q](tbls)
full = deadline = 0
with xc.QueryScheduler(workers=1, queue_depth=2) as tiny:
    held = []
    for _ in range(10):
        try:
            held.append(tiny.submit("slow", slow, tables, compiled=False))
        except xc.ExecQueueFull:
            full += 1
    tk = None
    while tk is None:
        try:
            tk = tiny.submit("dl", slow, tables, compiled=False,
                             timeout_s=0.001)
        except xc.ExecQueueFull:
            time.sleep(0.02)
    try:
        tk.result(timeout=60)
    except xc.ExecDeadlineExceeded:
        deadline = 1
    for h in held:
        h.result(timeout=120)
assert full >= 1, "bounded queue never rejected"
assert deadline == 1, "deadline did not surface"
print(f"backpressure OK: {full} queue-full rejections, typed deadline")

# 3) degraded admission under a pressure cap: completes, bit-exact
metrics.reset()
with xc.QueryScheduler(workers=4, inflight_bytes=4096) as dsched:
    tickets = [dsched.submit(q, tpcds.QUERIES[q], tables) for q in mix]
    wrong = 0
    for q, tk in zip(mix, tickets):
        got = canon(tk.result(timeout=300))
        wrong += not (len(got) == len(oracle[q]) and all(
            np.array_equal(a, b) for a, b in zip(got, oracle[q])))
snap = metrics.snapshot()["counters"]
assert wrong == 0, f"{wrong} degraded responses wrong"
assert snap.get("exec.admission.degraded", 0) >= 1, snap
print(f"degraded OK: {int(snap['exec.admission.degraded'])} degraded "
      f"admissions, 0 wrong results")

# 4) cross-request coalescing: a same-plan burst behind a slow blocker
# batches into shared launches, responses bit-identical to serial
metrics.reset()
q0 = qnames[0]
with xc.QueryScheduler(workers=2, coalesce_ms=100) as bsched:
    blocker = [bsched.submit("blocker", slow, tables, compiled=False)
               for _ in range(2)]          # occupy both workers
    tickets = [bsched.submit(q0, tpcds.QUERIES[q0], tables)
               for _ in range(8)]
    for b in blocker:
        b.result(timeout=300)
    wrong = sum(
        not all(np.array_equal(a, b) for a, b in
                zip(canon(tk.result(timeout=300)), oracle[q0]))
        for tk in tickets)
snap = metrics.snapshot()
assert wrong == 0, f"{wrong} batched responses wrong"
bh = snap["histograms"].get("exec.batch.size")
assert bh is not None and bh["max"] >= 2, \
    f"burst did not coalesce: {bh}"
print(f"batched OK: {int(bh['count'])} batched launches, "
      f"max batch {int(bh['max'])}, 0 wrong results")

# 5) forced incident: a deadline breach under the env default deadline
# must dump a snapshot whose ring covers the breaching request's
# lifecycle (submit → resolve) — the black-box contract, end to end
from spark_rapids_jni_tpu.utils import flight
inc_dir = os.path.join(out, "incidents")
os.environ["SRJT_INCIDENT_DIR"] = inc_dir
os.environ["SRJT_EXEC_DEADLINE"] = "0.001"
flight.reset()
with xc.QueryScheduler(workers=1, queue_depth=4) as isched:
    blocker = isched.submit("blocker", slow, tables, compiled=False,
                            timeout_s=600)
    doomed = isched.submit("doomed", slow, tables, compiled=False)
    try:
        doomed.result(timeout=60)
        raise AssertionError("env deadline did not fire")
    except xc.ExecDeadlineExceeded:
        pass
    blocker.result(timeout=300)
del os.environ["SRJT_EXEC_DEADLINE"]
snaps = [p for p in os.listdir(inc_dir)
         if p.startswith("incident-deadline-")]
assert snaps, "deadline breach wrote no incident snapshot"
with open(os.path.join(inc_dir, snaps[0])) as f:
    inc = json.load(f)                    # parses — never torn
assert inc["kind"] == "deadline" and inc["request_id"] == doomed.rid, inc
rid_kinds = {e["kind"] for e in inc["events"]
             if e.get("rid") == doomed.rid}
assert {"exec.submit", "exec.resolve"} <= rid_kinds, rid_kinds
assert "scheduler.queue_depth" in inc["probes"], inc["probes"]
print(f"incident OK: {snaps[0]} carries {doomed.rid} lifecycle "
      f"({sorted(rid_kinds)})")

# 6) Prometheus export lint: every line must match the text exposition
# grammar (TYPE comments; metric lines name{labels} value)
import re
prom = metrics.to_prometheus()
assert prom.strip(), "empty prometheus export after a served mix"
line_re = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"[^\"]*\")*\})? (-?[0-9.e+-]+|\+Inf|-Inf|NaN)$")
type_re = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                     r"(counter|gauge|histogram)$")
for ln in prom.splitlines():
    assert (type_re.match(ln) if ln.startswith("#")
            else line_re.match(ln)), f"prometheus lint: bad line {ln!r}"
with open(os.path.join(out, "metrics.prom"), "w") as f:
    f.write(prom)
print(f"prometheus lint OK: {len(prom.splitlines())} lines")

with open(os.path.join(out, "summary.json"), "w") as f:
    json.dump(metrics.summary(), f, indent=1)
print("wrote", os.path.join(out, "summary.json"))
PYEOF

echo "== serving contract under SRJT_SANITIZE=strict =="
# Runtime sanitizers armed in strict mode: a lock-order inversion in the
# scheduler/admission/coalesce path, or an unexpected recompile of a
# warm plan (the silent jax.default_device regression class), raises at
# the violation site and fails this smoke.
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SRJT_SANITIZE=strict \
python -m pytest tests/test_exec_runtime.py -q

echo "exec smoke OK"
