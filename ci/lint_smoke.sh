#!/usr/bin/env bash
# Static-analysis gate — runs tools/srjt_lint.py (concurrency, retrace/
# host-sync, knob-registry passes) against the checked-in baseline and
# fails on any non-baselined finding.  The linter is stdlib-only (no jax
# import) and prints a per-rule summary; budget is <30 s so it can sit at
# the FRONT of premerge, before the native build.
#
# Usage: ci/lint_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== srjt_lint: static analysis vs ci/lint_baseline.json =="
start=$(date +%s)
python tools/srjt_lint.py --baseline ci/lint_baseline.json
elapsed=$(( $(date +%s) - start ))
if (( elapsed >= 30 )); then
    echo "lint smoke FAILED: runtime ${elapsed}s exceeds the 30s budget" >&2
    exit 1
fi

echo "lint smoke OK (${elapsed}s)"
