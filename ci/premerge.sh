#!/usr/bin/env bash
# Premerge gate — the analog of the reference's ci/premerge-build.sh:26-29
# (`mvn verify -DBUILD_TESTS=ON` on a device runner): build the native
# artifact, stamp build provenance, run the full test suite, and — when a
# JDK is present — compile the Java tier.
#
# Usage: ci/premerge.sh [--skip-tests]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== static analysis (srjt_lint) =="
ci/lint_smoke.sh

echo "== native build =="
make -C spark_rapids_jni_tpu/native -s clean
make -C spark_rapids_jni_tpu/native -s -j"$(nproc)"

echo "== build provenance =="
python ci/build_info.py

if command -v javac >/dev/null 2>&1; then
    echo "== java tier (compiled BEFORE the wheel so classes embed) =="
    CLASSDIR=spark_rapids_jni_tpu/java_classes
    rm -rf "$CLASSDIR"                   # no orphaned .class files
    mkdir -p "$CLASSDIR"
    [[ -f "$CLASSDIR/__init__.py" ]] || cat > "$CLASSDIR/__init__.py" <<'PYEOF'
"""Compiled Java tier (present only when the wheel was built with a JDK —
the reference jar's .class payload analog, pom.xml:450-471)."""
PYEOF
    javac -d "$CLASSDIR" $(find java -name '*.java')
    if command -v java >/dev/null 2>&1; then
        echo "== java tier: JVM smoke (RowConversionSmoke) =="
        java -Dsrjt.native.path="$(pwd)/spark_rapids_jni_tpu/native/libsrjt.so" \
            -cp "$CLASSDIR" com.tpu.rapids.jni.RowConversionSmoke \
            | tee target/java_smoke.log
    fi
else
    echo "== java tier: no javac in environment, skipped =="
fi

echo "== wheel packaging (jar-with-embedded-.so analog) =="
python -m pip wheel . --no-deps --no-build-isolation -q -w target/dist
python - <<'PYEOF'
import glob, zipfile
w = sorted(glob.glob("target/dist/*.whl"))[-1]
names = zipfile.ZipFile(w).namelist()
for so in ("native/libsrjt.so", "native/libsrjt_parquet.so"):
    assert any(n.endswith(so) for n in names), f"{so} missing from wheel"
print(f"wheel OK: {w}")
PYEOF

if [[ "${1:-}" != "--skip-tests" ]]; then
    echo "== tests =="
    python -m pytest tests/ -q
    echo "== exec smoke (serving runtime) =="
    ci/exec_smoke.sh
    echo "== chaos smoke (fault-tolerant serving) =="
    ci/chaos_smoke.sh
    echo "== plan smoke (query planner) =="
    ci/plan_smoke.sh
    echo "== aqe smoke (adaptive query execution) =="
    ci/aqe_smoke.sh
    echo "== stream smoke (incremental maintenance) =="
    ci/stream_smoke.sh
    echo "== dict smoke (dictionary-string fast path) =="
    ci/dict_smoke.sh
    echo "== bytes smoke (staged/pipelined/donated scan) =="
    ci/bytes_smoke.sh
    echo "== profile smoke (EXPLAIN ANALYZE / per-node profiles) =="
    ci/profile_smoke.sh
    echo "== ml smoke (ETL→ML handoff) =="
    ci/ml_smoke.sh
    echo "== coldstart smoke (AOT plan-artifact store) =="
    ci/coldstart_smoke.sh
    echo "== sql smoke (SQL front-end / submit_sql) =="
    ci/sql_smoke.sh
fi

echo "premerge OK"
