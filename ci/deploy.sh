#!/usr/bin/env bash
# Deploy — the analog of the reference's ci/deploy.sh (mvn deploy of the
# versioned jar to the maven repo).  Publishes the built wheel to the
# package index configured via SRJT_DEPLOY_URL; without one (local runs,
# forks) it verifies the artifact and stops — a dry run, never a failure.
set -euo pipefail
cd "$(dirname "$0")/.."
ARTIFACT_DIR=${1:-target/nightly}

WHEEL=$(ls "$ARTIFACT_DIR"/*.whl 2>/dev/null | head -1 || true)
if [[ -z "$WHEEL" ]]; then
    WHEEL=$(ls dist/*.whl 2>/dev/null | head -1 || true)
fi
[[ -n "$WHEEL" ]] || { echo "deploy: no wheel found" >&2; exit 1; }

echo "== verify artifact =="
python -m zipfile -l "$WHEEL" | grep -q "libsrjt.so" \
    || { echo "deploy: wheel is missing the native artifact" >&2; exit 1; }

if [[ -z "${SRJT_DEPLOY_URL:-}" ]]; then
    echo "deploy: SRJT_DEPLOY_URL not set — dry run, artifact verified:"
    ls -la "$WHEEL"
    exit 0
fi

echo "== upload to $SRJT_DEPLOY_URL =="
python -m pip install -q twine
python -m twine upload --repository-url "$SRJT_DEPLOY_URL" "$WHEEL"
