#!/usr/bin/env bash
# Metrics/trace subsystem smoke — the observability analog of the java
# RowConversionSmoke step: run ONE compiled TPC-DS query end to end with
# metrics + JSON structured logging enabled, export the Chrome trace, and
# assert the trace is well-formed (span tree rooted at the query, nonzero
# join-engine counters, trace_report.py digests it).  The artifacts land in
# target/metrics_smoke/ for workflow upload.
#
# Usage: ci/metrics_smoke.sh [n_sales] [query]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-200000}"
QUERY="${2:-q3}"
OUT=target/metrics_smoke
mkdir -p "$OUT"

echo "== metrics smoke: $QUERY over $N_SALES rows =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SPARK_RAPIDS_TPU_LOG=json \
SPARK_RAPIDS_TPU_LOG_FILE="$OUT/events.jsonl" \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERY" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
qname = os.environ["SRJT_SMOKE_Q"]

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.models.compiled import compile_query
from spark_rapids_jni_tpu.utils import metrics

files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, n_stores=10,
                            seed=5)
tables = tpcds.load_tables(files)

metrics.reset()
with metrics.query_span(qname, n_sales=n_sales):
    cq = compile_query(tpcds.QUERIES[qname], tables)
res = cq.run(tables)
print(f"{qname}: {res.num_rows} rows, tape_len={len(cq.tape)}")

trace_path = metrics.export_chrome_trace(os.path.join(out, "trace.json"))
with open(os.path.join(out, "summary.json"), "w") as f:
    json.dump(metrics.summary(), f, indent=1)

# --- assertions: the acceptance-criterion shape -----------------------------
with open(trace_path) as f:
    doc = json.load(f)
events = doc["traceEvents"]
xs = [e for e in events if e.get("ph") == "X"]
assert xs, "no span events in trace"
names = {e["name"] for e in xs}
assert f"query:{qname}" in names, f"missing query root span: {sorted(names)}"
assert any(n.startswith("join.") for n in names), names
assert any(n.startswith("groupby.") or n.startswith("sort.")
           for n in names), names
counters = doc["srjtCounters"]
assert sum(v for k, v in counters.items()
           if k.startswith("join.engine.")) > 0, counters
assert sum(v for k, v in counters.items()
           if k.startswith("join.build_index.")) > 0, counters
assert counters.get("compiled.capture", 0) >= 1, counters

roots = metrics.span_roots()
root = next(s for s in roots if s["name"] == f"query:{qname}")
assert root.get("children"), "query root span has no stage children"

log_path = os.path.join(out, "events.jsonl")
assert os.path.exists(log_path), "structured log missing"
with open(log_path) as f:
    for line in f:
        rec = json.loads(line)          # every line is well-formed JSON
        assert "event" in rec and "ts" in rec
print("trace + structured log well-formed:", trace_path)
PYEOF

echo "== trace_report =="
python tools/trace_report.py "$OUT/trace.json" 15

echo "metrics smoke OK"
