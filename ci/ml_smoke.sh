#!/usr/bin/env bash
# ML-handoff smoke — the zero-copy ETL→ML gate: one mini end-to-end pass
# under the strict sanitizer: synthetic parquet (numerics + a dict-string
# categorical + a nullable column) → device decode → FeatureSpec pack
# (bit-identical to the numpy oracle) → fused-epoch training with ZERO
# steady-loop host syncs → servable registration → predict through the
# exec/ scheduler bit-identical to direct evaluation, plus an online
# FeatureView refresh over a delta append.  EXPLAIN ANALYZE must show the
# ml.pack/ml.predict stages.
#
# Usage: ci/ml_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ml smoke: parquet → features → train → serve =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_SANITIZE=strict \
python - <<'PYEOF'
import io
import sys

sys.path.insert(0, ".")

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu import ml
from spark_rapids_jni_tpu.plan import ir
from spark_rapids_jni_tpu.ml import features as F
from spark_rapids_jni_tpu.parquet import device_scan as decode
from spark_rapids_jni_tpu.utils import syncs

n = 600
rng = np.random.default_rng(3)
cats = ["alpha", "beta", "gamma", "delta"]
mask = rng.random(n) < 0.2
buf = io.BytesIO()
pq.write_table(pa.table({
    "a": rng.normal(size=n),
    "b": rng.integers(-50, 50, n),
    "c": pa.array(np.where(mask, 0, rng.integers(0, 9, n)),
                  mask=mask, type=pa.int64()),
    "cat": pa.array([cats[i] for i in rng.integers(0, 4, n)]
                    ).dictionary_encode(),
    "label": rng.integers(0, 2, n),
}), buf)
blob = buf.getvalue()

names = ["a", "b", "c", "cat", "label"]
tbl = decode.read_table(blob, columns=names)
spec = F.FeatureSpec.of(
    [F.Feature("a"), F.Feature("b"), F.Feature("c", impute="mean"),
     F.Feature("cat")],
    label="label", label_transform=("gt", 0.0))
fb = spec.pack(tbl, names)

# numpy oracle: bit-identical features
host = pq.read_table(io.BytesIO(blob))
cvals = host["c"].to_pandas().to_numpy(dtype=np.float64, na_value=np.nan)
cvalid = ~np.isnan(cvals)
cmean = np.float32(cvals[cvalid].sum() / cvalid.sum())
strs = [str(v) for v in host["cat"].to_pylist()]
rank = {v: i for i, v in enumerate(sorted(set(strs)))}
oracle = np.stack([
    np.asarray(host["a"]).astype(np.float32),
    np.asarray(host["b"]).astype(np.float32),
    np.where(cvalid, cvals.astype(np.float32), cmean),
    np.array([rank[v] for v in strs], np.float32),
], axis=1)
assert np.array_equal(np.asarray(fb.X), oracle), "feature pack != oracle"
print(f"pack: {fb.num_rows}x{fb.num_features} bit-identical to oracle")

# fused training: zero steady-loop syncs
pipe = ml.BatchPipeline(fb, batch_size=64, seed=7)
tr = ml.Trainer(ml.logistic_regression(), ml.sgd(lr=0.05, momentum=0.9))
params, ostate = tr.init(pipe.k)
Xb, yb = pipe.epoch_arrays(0)
params, ostate, loss = tr.run_epoch(params, ostate, Xb, yb)
loss.block_until_ready()
base = syncs.sync_count()
for e in range(1, 6):
    Xb, yb = pipe.epoch_arrays(e)
    params, ostate, loss = tr.run_epoch(params, ostate, Xb, yb)
assert syncs.sync_count() - base == 0, "steady loop synced the host"
assert np.isfinite(float(loss)), "training diverged"
print(f"train: 5 steady epochs, 0 syncs, loss={float(loss):.4f}")

# serve through the scheduler == direct evaluation
from spark_rapids_jni_tpu import exec as xc

tree = ir.Scan("t")
sv = ml.ServableModel.from_plan(
    "smoke", tree, {"t": names},
    F.FeatureSpec.of([F.Feature("a"), F.Feature("b"),
                      F.Feature("c", impute="mean"), F.Feature("cat")]),
    ml.logistic_regression(), params)
ml.register_servable(sv)
tables = {"t": tbl}
direct = sv.predict_table(tables)
with xc.QueryScheduler(workers=2, devices=2) as sched:
    served = sched.submit_predict("smoke", tables).result(timeout=120)
assert np.array_equal(np.asarray(served[0].data),
                      np.asarray(direct[0].data)), "scheduler != direct"
print("serve: scheduler prediction bit-identical to direct")

# online feature store: refresh after a delta append re-packs
from spark_rapids_jni_tpu.stream.delta import DeltaTable
from spark_rapids_jni_tpu.stream.view import ViewRegistry

dt = DeltaTable("events", files=[blob])
reg = ViewRegistry(dt, {}, {})
fv = ml.FeatureView(reg, ir.Scan("events"), spec, name="fv_smoke")
n0 = fv.current().num_rows
dt.append_file(blob)
n1 = fv.refresh().num_rows
assert n1 == 2 * n0, f"feature view missed the append: {n0} -> {n1}"
fv.close()
print(f"feature view: {n0} -> {n1} rows after delta append")

print("ml smoke OK")
PYEOF
