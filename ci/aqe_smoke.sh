#!/usr/bin/env bash
# Adaptive-query-execution smoke — the AQE analog of ci/plan_smoke.sh,
# run with the STRICT runtime sanitizer on: (1) with SRJT_AQE=0 the
# lowered execution is byte-for-byte the static path; (2) with SRJT_AQE=1
# an adversarially-ordered star join replans from observed cardinalities
# and an out-of-range dense prior flips the join engine, both
# bit-identical to the static plan; (3) the skew-salted repartition
# sub-join over the 8-device mesh fires and merges exactly; (4) the
# cardinality-stats sidecar round-trips through its JSON file.
# Artifacts land in target/aqe_smoke/ for workflow upload.
#
# Usage: ci/aqe_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=target/aqe_smoke
mkdir -p "$OUT"

echo "== aqe smoke (strict sanitizer) =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SRJT_SANITIZE=strict \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_SMOKE_OUT="$OUT" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]

import numpy as np
import jax.numpy as jnp

import spark_rapids_jni_tpu as sr
from spark_rapids_jni_tpu.column import Column, Table, force_column
from spark_rapids_jni_tpu.plan import adaptive, ir, lower, rules
from spark_rapids_jni_tpu.plan import stats as plan_stats
from spark_rapids_jni_tpu.utils import metrics

rng = np.random.default_rng(5)
n = 20_000


def _col(a):
    return Column.from_numpy(np.asarray(a))


tables = {
    "fact": Table([_col(rng.integers(0, 5000, n).astype(np.int64)),
                   _col(rng.integers(0, 400, n).astype(np.int64)),
                   _col(rng.integers(1, 9, n).astype(np.int64))]),
    "dim_big": Table([_col(np.arange(5000, dtype=np.int64)),
                      _col((np.arange(5000) % 11).astype(np.int32))]),
    "dim_small": Table([_col(np.arange(24, dtype=np.int64)),
                        _col((np.arange(24) % 3).astype(np.int32))]),
}
schemas = {"fact": ["f_big_sk", "f_small_sk", "f_qty"],
           "dim_big": ["big_sk", "b_tag"],
           "dim_small": ["small_sk", "s_tag"]}

# adversarial order: big dim first
tree = ir.FusedJoinAggregate(
    ir.Join(ir.Scan("fact"), ir.Scan("dim_big"), ("f_big_sk",), ("big_sk",)),
    ir.Scan("dim_small"), ("f_small_sk",), ("small_sk",),
    ("b_tag",), (("f_qty", "sum", "total"), ("f_qty", "count", "cnt")))


def rows(t):
    return [force_column(c).to_numpy().tolist() for c in t]


# (1) AQE off → byte-for-byte the static path
os.environ["SRJT_AQE"] = "0"
cat = lower.TableCatalog(tables, schemas)
static = lower.execute(tree, cat, record_stats=False)
off = lower.execute(tree, lower.TableCatalog(tables, schemas),
                    record_stats=False)
assert rows(static) == rows(off)
print("AQE off: static path byte-identical")

# (2) AQE on → replan fires, result bit-identical
os.environ["SRJT_AQE"] = "1"
metrics.set_enabled(True)
metrics.reset()
report = adaptive.AdaptiveReport()
got = adaptive.execute_adaptive(tree, lower.TableCatalog(tables, schemas),
                                record_stats=False, report=report)
assert rows(got) == rows(static), "adaptive result differs from static"
assert metrics.counter_value("plan.aqe.replan.fired") >= 1
kinds = {d.kind for d in report.decisions()}
assert "replan" in kinds, kinds
print("AQE on: replan fired, bit-identical —",
      sorted(kinds))

# engine flip: sparse build keys under a dense-looking span
sp_tables = {
    "fact": Table([_col(rng.integers(0, 15_000, n).astype(np.int64)),
                   _col(rng.integers(1, 9, n).astype(np.int64))]),
    "dim": Table([_col(rng.permutation(15_000)[:600].astype(np.int64)),
                  _col((np.arange(600) % 7).astype(np.int32))]),
}
sp_schemas = {"fact": ["f_sk", "f_qty"], "dim": ["d_sk", "d_tag"]}
sp_tree = ir.FusedJoinAggregate(
    ir.Scan("fact"), ir.Scan("dim"), ("f_sk",), ("d_sk",),
    ("d_tag",), (("f_qty", "sum", "total"),))
os.environ["SRJT_AQE"] = "0"
sp_static = lower.execute(sp_tree, lower.TableCatalog(sp_tables, sp_schemas),
                          record_stats=False)
os.environ["SRJT_AQE"] = "1"
sp_got = adaptive.execute_adaptive(
    sp_tree, lower.TableCatalog(sp_tables, sp_schemas), record_stats=False)
assert rows(sp_got) == rows(sp_static)
flips = metrics.counter_value("plan.aqe.engine_flip.fired")
assert flips >= 1, "engine flip did not fire"
print("engine flip fired:", int(flips), "— bit-identical")

# (3) skew-salted repartition sub-join over the mesh
from spark_rapids_jni_tpu.parallel import make_mesh
from spark_rapids_jni_tpu.parallel import repartition_join as rj

mesh = make_mesh(8, "data")
ns, nb, G = 16_384, 256, 8
fk = rng.integers(0, nb, ns).astype(np.int64)
fk[rng.random(ns) < 0.7] = 3
fv = rng.integers(-20, 20, ns).astype(np.int64)
bk = np.arange(nb, dtype=np.int64)
bg = (bk % G).astype(np.int32)
args = (mesh, (sr.int64, sr.int64), (sr.int64, sr.int32), 0, 0, 1, 1, G,
        (jnp.asarray(fk), jnp.asarray(fv)), jnp.ones((ns, 2), bool),
        (jnp.asarray(bk), jnp.asarray(bg)), jnp.ones((nb, 2), bool))
s1, c1, d1 = rj.repartition_join_agg_auto(*args, salt=1)
sA, cA, dA = rj.repartition_join_agg_auto(*args)
assert int(np.asarray(d1)) == 0 and int(np.asarray(dA)) == 0
assert (np.asarray(s1) == np.asarray(sA)).all()
assert (np.asarray(c1) == np.asarray(cA)).all()
fired = metrics.counter_value("plan.aqe.skew_split.fired")
assert fired >= 1, "skew split did not fire"
print("skew split fired, salted merge exact")

# (4) cardinality-stats sidecar roundtrip
side = os.path.join(out, "stats_sidecar.json")
st = plan_stats.CardinalityStats(max_entries=16)
st.observe("plan:aaaa", 123)
st.observe("plan:bbbb", 456)
assert st.save_sidecar(side)
st2 = plan_stats.CardinalityStats(max_entries=16)
assert st2.load_sidecar(side) == 2
assert dict(st2._rows) == {"plan:aaaa": 123, "plan:bbbb": 456}
print("stats sidecar roundtrip OK")

with open(os.path.join(out, "explain.txt"), "w") as f:
    f.write(rules.explain(tree, schemas, adaptive_report=report))
with open(os.path.join(out, "counters.json"), "w") as f:
    snap = metrics.snapshot()
    json.dump({k: v for k, v in snap["counters"].items()
               if k.startswith(("plan.aqe", "shuffle."))}, f, indent=1)
os.environ["SRJT_AQE"] = "0"
print("artifacts:", out)
PYEOF

echo "aqe smoke OK"
