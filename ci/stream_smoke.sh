#!/usr/bin/env bash
# Streaming-maintenance smoke — the stream/ analog of ci/plan_smoke.sh:
# register ONE q3-shaped view (int64 cents sum: the merge-EXACT spelling)
# over a small store_sales fact, append three epochs of rows, and assert
# the two contracts the subsystem exists for:
#
#   1. O(delta) work — each refresh decodes EXACTLY the appended file's
#      row groups (stream.delta.rowgroups in the exported counters; full
#      recomputes land on stream.scan.rowgroups, so the two can't blur),
#   2. exactness — every epoch's refreshed result is bit-identical to a
#      from-scratch recompute of the same plan, including one epoch
#      routed through the concurrent scheduler (submit_refresh).
#
# Artifacts land in target/stream_smoke/ for workflow upload.
#
# Usage: ci/stream_smoke.sh [n_sales] [epochs]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-40000}"
EPOCHS="${2:-3}"
OUT=target/stream_smoke
mkdir -p "$OUT"

echo "== stream smoke: $EPOCHS epochs of $((N_SALES / 64))-row appends =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_E="$EPOCHS" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
epochs = int(os.environ["SRJT_SMOKE_E"])
n_append, rgs = max(n_sales // 64, 1), 2048

import numpy as np

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu.column import force_column
from spark_rapids_jni_tpu.models import tpcds, tpcds_plans
from spark_rapids_jni_tpu.plan import ir, lower
from spark_rapids_jni_tpu.stream import DeltaTable, ViewRegistry
from spark_rapids_jni_tpu.stream.delta import _file_meta
from spark_rapids_jni_tpu.utils import metrics

files = tpcds_data.generate(n_sales=n_sales, n_items=500, seed=7,
                            row_group_size=rgs)
tables = tpcds.load_tables(files)
statics = {k: tables[k] for k in ("item", "date_dim", "store")}
schemas = {k: tpcds_plans.TABLE_SCHEMAS[k] for k in statics}
delta = DeltaTable("store_sales", files=[files["store_sales"]])
reg = ViewRegistry(delta, statics, schemas)

j = ir.Join(ir.Join(ir.Scan("store_sales"), ir.Scan("item"),
                    ("ss_item_sk",), ("i_item_sk",)),
            ir.Scan("date_dim"), ("ss_sold_date_sk",), ("d_date_sk",))
f = ir.Filter(j, ir.And((
    ir.Cmp("==", ir.Col("i_manufact_id"), ir.Lit(436)),
    ir.Cmp("==", ir.Col("d_moy"), ir.Lit(11)))))
keys = ("d_year", "i_brand_id", "i_brand")
plan = ir.Sort(ir.Aggregate(f, keys, (
    ("ss_sales_price_cents", "sum", "sum_cents"),
    ("ss_quantity", "count", "n"))), keys)

metrics.reset()
v = reg.register_view(plan, name="q3_cents")
assert v.kind == "incremental", v.reason
assert v.exact
print(f"view registered: kind={v.kind} exact={v.exact}")


def bitcmp(a, b, tag):
    assert a.num_rows == b.num_rows, (tag, a.num_rows, b.num_rows)
    for i in range(len(a.columns)):
        x, y = force_column(a[i]), force_column(b[i])
        np.testing.assert_array_equal(np.asarray(x.data),
                                      np.asarray(y.data),
                                      err_msg=f"{tag} col {i}")
        if x.offsets is not None:
            np.testing.assert_array_equal(np.asarray(x.offsets),
                                          np.asarray(y.offsets))


def oracle():
    cat = lower.TableCatalog({**statics, "store_sales": delta.scan()},
                             reg.schemas)
    return lower.execute(v.tree, cat, record_stats=False)


bitcmp(reg.refresh(v), oracle(), "epoch0")
with xc.QueryScheduler(workers=2) as sched:
    for e in range(1, epochs + 1):
        blob = tpcds_data.append_rows(n_append, seed=1000 + e, n_items=500,
                                      row_group_size=rgs)
        ngroups = len(_file_meta(blob)[0])
        delta.append_file(blob)
        c0 = metrics.counter_value("stream.delta.rowgroups")
        if e == epochs:     # last epoch runs through the serving runtime
            got = sched.submit_refresh(reg, v).result(timeout=300)
        else:
            got = reg.refresh(v)
        dgroups = int(metrics.counter_value("stream.delta.rowgroups") - c0)
        assert dgroups == ngroups, (dgroups, ngroups)
        bitcmp(got, oracle(), f"epoch{e}")
        print(f"epoch {e}: decoded {dgroups}/{ngroups} appended row "
              f"groups, result bit-identical to full recompute")

trace_path = metrics.export_chrome_trace(os.path.join(out, "trace.json"))
with open(trace_path) as fh:
    doc = json.load(fh)
counters = doc["srjtCounters"]
assert counters.get("stream.refresh.incremental", 0) >= epochs, counters
assert counters.get("stream.refresh.submitted", 0) == 1, counters
assert counters.get("stream.view.fallback", 0) == 0, counters
with open(os.path.join(out, "stats.json"), "w") as fh:
    json.dump(reg.stats(), fh, indent=1)
print("incremental refreshes:", counters["stream.refresh.incremental"],
      "| delta row groups:", counters["stream.delta.rowgroups"],
      "| trace well-formed:", trace_path)
reg.close()
PYEOF

echo "stream smoke OK"
