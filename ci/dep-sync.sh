#!/usr/bin/env bash
# Dependency refresh — the analog of the reference's ci/submodule-sync.sh
# (bump thirdparty/cudf to branch HEAD, rebuild, push if green).  The
# moving dependency here is JAX: install the latest release, run the
# build + suite against it, and leave a green-marker + version for the
# workflow to branch on.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=target/dep-sync
mkdir -p "$OUT"
rm -f "$OUT/green"

python -m pip install -U jax numpy pytest pandas pyarrow
python - <<'PYEOF' > "$OUT/version"
import jax
print(jax.__version__, end="")
PYEOF
# the tracked pin the bot branch actually bumps (reference analog: the
# cudf submodule SHA); CI installs whatever this records
cp "$OUT/version" ci/jax-pin.txt
echo "testing against jax $(cat "$OUT/version")"

bash ci/premerge.sh --skip-tests
if XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q | tee "$OUT/pytest.log"; then
    touch "$OUT/green"
    echo "dep-sync: GREEN against jax $(cat "$OUT/version")"
else
    echo "dep-sync: suite FAILED against jax $(cat "$OUT/version")" >&2
    exit 1
fi
