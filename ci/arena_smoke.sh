#!/usr/bin/env bash
# HBM-arena smoke — the memory-subsystem analog of ci/metrics_smoke.sh:
# run ONE TPC-DS join query twice (unbudgeted reference, then EAGERLY under
# a deliberately tiny SRJT_HBM_BUDGET — the index cache is bypassed under
# capture/replay, so only eager runs register spillable residents), assert
# the budgeted run recorded at least one spill event in the exported Chrome
# trace AND produced bit-identical results.  Artifacts land in
# target/arena_smoke/ for workflow upload.
#
# Usage: ci/arena_smoke.sh [n_sales] [query] [budget]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-200000}"
QUERY="${2:-q3}"
BUDGET="${3:-2k}"     # tiny on purpose: must undercut the dim-table
#                       index residents so the second join forces a spill
OUT=target/arena_smoke
mkdir -p "$OUT"

echo "== arena smoke: $QUERY over $N_SALES rows, budget $BUDGET =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERY" \
SRJT_SMOKE_BUDGET="$BUDGET" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
qname = os.environ["SRJT_SMOKE_Q"]
budget_s = os.environ["SRJT_SMOKE_BUDGET"]

import numpy as np

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.memory import arena, budget, spill
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.ops import join_plan
from spark_rapids_jni_tpu.utils import metrics

files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, n_stores=10,
                            seed=5)
tables = tpcds.load_tables(files)

# reference: arena off, eager
budget.set_enabled(False)
expect = tpcds.QUERIES[qname](tables)

# budgeted run: cold caches, tiny budget, eager (capture would bypass the
# index cache and leave nothing to spill)
join_plan._INDEX_CACHE.clear()
spill.reset()
arena.reset()
budget.reset()
os.environ["SRJT_HBM_BUDGET"] = budget_s
budget.set_enabled(None)
assert budget.active(), "arena did not enable"
metrics.reset()
with budget.query_budget(qname, n_sales=n_sales):
    got = tpcds.QUERIES[qname](tables)
print(f"{qname}: {got.num_rows} rows under budget {budget_s}")

trace_path = metrics.export_chrome_trace(os.path.join(out, "trace.json"))
with open(os.path.join(out, "summary.json"), "w") as f:
    json.dump(metrics.summary(), f, indent=1)

# --- assertions: the acceptance-criterion shape -----------------------------
assert got.num_rows == expect.num_rows, (got.num_rows, expect.num_rows)
for i in range(len(expect.columns)):
    a, b = expect[i], got[i]
    if a.dtype.id.name == "STRING":
        assert a.to_pylist() == b.to_pylist(), f"col {i} differs"
    else:
        np.testing.assert_array_equal(a.to_numpy(), b.to_numpy(),
                                      err_msg=f"col {i}")
print("budgeted result identical to unbudgeted")

with open(trace_path) as f:
    doc = json.load(f)
counters = doc["srjtCounters"]
assert counters.get("arena.spill.events", 0) >= 1, counters
assert counters.get("arena.spill.bytes", 0) >= 0, counters
names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
assert f"query:{qname}" in names, sorted(names)
assert "arena.spill" in names, sorted(names)
gauges = doc.get("srjtGauges", {})
print("spill events:", counters["arena.spill.events"],
      "spill bytes:", counters.get("arena.spill.bytes"),
      "arena peak:", gauges.get("arena.peak_bytes"))
print("trace well-formed:", trace_path)
PYEOF

echo "arena smoke OK"
