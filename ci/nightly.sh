#!/usr/bin/env bash
# Nightly pipeline — the analog of the reference's ci/nightly-build.sh:25-30
# (mvn deploy of the cuda-classified jar after a full build): build, full
# test suite, driver-contract checks, benchmarks, and on-TPU validation,
# with every artifact dropped under target/nightly/ for archival.
#
# Usage: ci/nightly.sh [--no-tpu]   (--no-tpu skips chip-bound stages)
set -euo pipefail
cd "$(dirname "$0")/.."
OUT=target/nightly
mkdir -p "$OUT"

echo "== build + wheel + provenance =="
bash ci/premerge.sh --skip-tests

echo "== full CPU suite (8-device virtual mesh) =="
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q | tee "$OUT/pytest.log"

echo "== multichip dryrun (driver contract) =="
env XLA_FLAGS= JAX_PLATFORMS= python __graft_entry__.py dryrun 8 \
    | tee "$OUT/dryrun.log"

if [[ "${1:-}" != "--no-tpu" ]]; then
    echo "== headline benchmark (real chip) =="
    python bench.py > "$OUT/bench.json" || true
    tail -1 "$OUT/bench.json"

    echo "== on-TPU validation sweep =="
    python tools/tpu_check.py "$OUT/tpu_check.json" || true

    echo "== SF1 scan benchmark =="
    python tools/scan_bench.py 6000000 "$OUT/scan_bench.json" || true

    echo "== SF1 query benchmark (persistent compile cache in .jax_cache) =="
    # query_bench.py enables jax_compilation_cache_dir=.jax_cache, so this
    # nightly's compiles seed the cache and the next process's cold run
    # reuses every executable (VERDICT r3 next-step #3)
    python tools/query_bench.py 10000000 "$OUT/query_bench.json" || true
fi

cp -f target/dist/*.whl "$OUT"/ 2>/dev/null || true
cp -f target/version-info.properties "$OUT"/ 2>/dev/null || true
echo "nightly artifacts in $OUT/:"
ls -la "$OUT"
