#!/usr/bin/env bash
# Composite-key join smoke — the join-engine analog of ci/arena_smoke.sh:
# run ONE multi-key TPC-DS query (q_channel_day: channels join on the
# (item_sk, sold_date_sk) tuple) with metrics on, assert the exported
# Chrome trace recorded at least one `join.pack.composite` count (the
# tuple actually took the packed dense path), then re-run the same query
# with SRJT_JOIN_ENGINE=sorted and assert bit-identical results.
# Artifacts land in target/join_smoke/ for workflow upload.
#
# Usage: ci/join_smoke.sh [n_sales] [query]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-200000}"
QUERY="${2:-q_channel_day}"
OUT=target/join_smoke
mkdir -p "$OUT"

echo "== join smoke: $QUERY over $N_SALES rows =="
XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
SPARK_RAPIDS_TPU_METRICS=1 \
SRJT_SMOKE_OUT="$OUT" SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERY" \
python - <<'PYEOF'
import json
import os
import sys

sys.path.insert(0, ".")

out = os.environ["SRJT_SMOKE_OUT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
qname = os.environ["SRJT_SMOKE_Q"]

import numpy as np

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.ops import join_plan
from spark_rapids_jni_tpu.utils import metrics

files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, n_stores=10,
                            seed=5)
tables = tpcds.load_tables(files)

# planner-chosen run: the multi-key tuple must pack onto the composite path
metrics.reset()
with metrics.span(f"query:{qname}", n_sales=n_sales):
    got = tpcds.QUERIES[qname](tables)
print(f"{qname}: {got.num_rows} rows (planner engines)")

trace_path = metrics.export_chrome_trace(os.path.join(out, "trace.json"))
with open(os.path.join(out, "summary.json"), "w") as f:
    json.dump(metrics.summary(), f, indent=1)

with open(trace_path) as f:
    doc = json.load(f)
counters = doc["srjtCounters"]
assert counters.get("join.pack.composite", 0) >= 1, counters
names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
assert "join.pack" in names, sorted(names)
print("composite packs:", counters["join.pack.composite"],
      "| trace well-formed:", trace_path)

# pinned sort-probe run over FRESH tables: every engine decision forced to
# the sorted fallback — results must be bit-identical to the packed run
join_plan._INDEX_CACHE.clear()
join_plan._PLAN_CACHE.clear()
os.environ["SRJT_JOIN_ENGINE"] = "sorted"
expect = tpcds.QUERIES[qname](tables)
assert got.num_rows == expect.num_rows, (got.num_rows, expect.num_rows)
for i in range(len(expect.columns)):
    a, b = expect[i], got[i]
    if a.dtype.id.name == "STRING":
        assert a.to_pylist() == b.to_pylist(), f"col {i} differs"
    else:
        np.testing.assert_array_equal(a.to_numpy(), b.to_numpy(),
                                      err_msg=f"col {i}")
print("composite result identical to forced-sorted run")
PYEOF

echo "join smoke OK"
