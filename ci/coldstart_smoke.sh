#!/usr/bin/env bash
# Zero-compile cold-start smoke (exec/artifacts.py) — the two-process
# runbook, asserted end to end: process A serves a TPC-DS mix against an
# empty SRJT_AOT_DIR and populates the plan-artifact store (capture tapes
# + warm-up manifest + the XLA executable cache); process B — a FRESH
# interpreter — serves the SAME mix and must perform ZERO capture runs
# (compiled.capture == 0 in the ledger snapshot, every plan rehydrated
# from its persisted tape) with results bit-identical to A's; process C
# re-serves after an artifact file is deliberately corrupted and must
# DEGRADE to live capture (aot.reject counted, results still identical) —
# never fail.  Artifacts land in target/coldstart_smoke/.
#
# Usage: ci/coldstart_smoke.sh [n_sales] [queries]
set -euo pipefail
cd "$(dirname "$0")/.."

N_SALES="${1:-50000}"
QUERIES="${2:-q3,q42,q55}"
OUT=target/coldstart_smoke
AOT="$OUT/aot"
rm -rf "$OUT"
mkdir -p "$OUT"

cat > "$OUT/serve_once.py" <<'PYEOF'
"""One fresh serving process over the smoke mix: serve each query through
the full QueryScheduler, dump result hashes + compile-ledger counters."""
import hashlib
import json
import os
import sys

sys.path.insert(0, ".")

import numpy as np

import jax

from benchmarks import tpcds_data
from spark_rapids_jni_tpu import exec as xc
from spark_rapids_jni_tpu.models import tpcds
from spark_rapids_jni_tpu.utils import metrics

metrics.set_enabled(True)
mode = os.environ["SRJT_SMOKE_MODE"]
out_path = os.environ["SRJT_SMOKE_RESULT"]
n_sales = int(os.environ["SRJT_SMOKE_N"])
qnames = os.environ["SRJT_SMOKE_Q"].split(",")

files = tpcds_data.generate(n_sales=n_sales, n_items=2_000, n_stores=10,
                            seed=5)
tables = tpcds.load_tables(files)

def result_hash(result):
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(result):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(a.dtype.str.encode())
        h.update(repr(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()

hashes = {}
with xc.QueryScheduler(workers=2) as sched:
    if sched._warmup_thread is not None:
        sched._warmup_thread.join(timeout=60)
    for q in qnames:
        hashes[q] = result_hash(sched.run(q, tpcds.QUERIES[q], tables))
        # second request: a live capture answers the first request with
        # the capture run's own eager result — only this one compiles
        # the replay program, persisting its XLA executable for the
        # warm process to deserialize
        sched.run(q, tpcds.QUERIES[q], tables)
snap = metrics.snapshot()["counters"]
doc = {"mode": mode, "hashes": hashes,
       "capture": int(snap.get("compiled.capture", 0)),
       "rehydrate": int(snap.get("compiled.rehydrate", 0)),
       "aot_write": int(snap.get("aot.write", 0)),
       "aot_hit": int(snap.get("aot.hit", 0)),
       "aot_reject": int(snap.get("aot.reject", 0)),
       "warmed": int(snap.get("exec.aot.warmed", 0)),
       "ledger": {k: {m: round(float(x), 3) for m, x in v.items()}
                  for k, v in metrics.ledger_snapshot().items()}}
with open(out_path, "w") as f:
    json.dump(doc, f, indent=1)
print(f"[{mode}] capture={doc['capture']} rehydrate={doc['rehydrate']} "
      f"aot_write={doc['aot_write']} aot_reject={doc['aot_reject']}")
PYEOF

run_once() {  # $1 = mode, $2 = result file
    XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}" \
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    SRJT_AOT_DIR="$AOT" \
    SRJT_SMOKE_MODE="$1" SRJT_SMOKE_RESULT="$2" \
    SRJT_SMOKE_N="$N_SALES" SRJT_SMOKE_Q="$QUERIES" \
    python "$OUT/serve_once.py"
}

echo "== cold-start smoke: $QUERIES over $N_SALES rows =="
echo "== process A: populate $AOT =="
run_once populate "$OUT/populate.json"

echo "== process B: warm serve (must be ZERO capture runs) =="
run_once warm "$OUT/warm.json"

echo "== process C: forced corruption (must degrade to capture) =="
python - "$AOT" <<'PYEOF'
import json, os, sys
plans = os.path.join(sys.argv[1], "plans")
victim = sorted(os.listdir(plans))[0]
with open(os.path.join(plans, victim), "w") as f:
    f.write('{"version": 1, "tape": [7, 13')     # torn write
print(f"corrupted {victim}")
PYEOF
run_once corrupted "$OUT/corrupted.json"

python - "$OUT" <<'PYEOF'
import json, os, sys
out = sys.argv[1]
docs = {m: json.load(open(os.path.join(out, f"{m}.json")))
        for m in ("populate", "warm", "corrupted")}
a, b, c = docs["populate"], docs["warm"], docs["corrupted"]
nq = len(a["hashes"])
assert a["capture"] >= nq, a          # cold process captures every plan
assert a["aot_write"] >= nq, a        # ...and persists every artifact
assert b["capture"] == 0, \
    f"warm process performed {b['capture']} capture runs — " \
    "the zero-compile contract is broken"
assert b["rehydrate"] >= nq and b["aot_hit"] >= nq, b
assert b["hashes"] == a["hashes"], "rehydrated results diverged"
led = b["ledger"]
assert all(v.get("captures", 0) == 0 for v in led.values()), led
assert c["aot_reject"] >= 1, c        # the corrupt artifact was rejected
assert c["capture"] >= 1, c           # ...and degraded to live capture
assert c["hashes"] == a["hashes"], "post-corruption results diverged"
print(f"cold start OK: {nq} plans — populate capture={a['capture']}, "
      f"warm capture=0 rehydrate={b['rehydrate']}, corruption degraded "
      f"to {c['capture']} capture(s), all results bit-identical")
PYEOF

echo "coldstart smoke OK"
