#!/usr/bin/env python
"""Driver benchmark: JCUDF row-conversion on TPU across the reference axes.

Mirrors the reference's nvbench axes (``benchmarks/row_conversion.cpp``):

* "Fixed Width Only"  — cycled fixed-width schema (i8,i16,i32,i64,f32,f64,
  bool — f64 included since the bit-pair Column storage landed) at 12 and
  212 columns, {1M, 4M} rows, {to_rows, from_rows, roundtrip};
* "Fixed or Variable Width" — strings included: a 4-string-column mixed
  schema at 1M rows (the DMA segmented-copy path) and a 155-column mixed
  schema with strings at 256K rows (the XLA gather path; the reference
  likewise skips its string case above 1M rows,
  ``row_conversion.cpp:145-149``).

Timing methodology (see BASELINE.md): on the axon-tunneled chip a dispatch
costs ~12 ms and a sync ~65-110 ms, and ``block_until_ready`` is a no-op.
Fixed-width measurements therefore run dependency-chained ``fori_loop``
iterations inside ONE jit and remove the fixed dispatch+sync overhead
EXACTLY by differencing two trip counts of the same jitted loop:
``(t(HI) - t(LO)) / (HI - LO)``.  This is steady-state device time per
conversion — the same quantity nvbench's hot loop reports — and is immune
to tunnel congestion (round 2's 3.77 GB/s driver number was ~90% tunnel
sync, measured in tools/profile_transcode.py).  The string path has host
orchestration between kernels (offset syncs, like the reference's
``row_conversion.cu:2215``), so it reports wall-clock over eager calls —
honest end-to-end numbers for this backend.

Output contract (driver): the driver parses the LAST stdout line.  The
headline is emitted EARLY (right after it is measured, so a driver-side
timeout still records it) and again LAST with every per-axis result
embedded under "axes".  Per-axis progress lines go to stderr:
  {"metric": "jcudf_row_conversion_roundtrip_1M", "value": N,
   "unit": "GB/s", "vs_baseline": N, "axes": [...]}
vs_baseline = device GB/s / vectorized-NumPy host GB/s on the same workload.
"""

import json
import os
import sys
import time

import numpy as np


def _emit(payload: dict) -> None:
    """The ONE stdout JSON line (driver contract)."""
    print(json.dumps(payload))
    sys.stdout.flush()


def _progress(payload: dict) -> None:
    """Per-axis progress — stderr only, never stdout."""
    print(json.dumps(payload), file=sys.stderr)
    sys.stderr.flush()


def _fail(msg: str) -> None:
    _emit({
        "metric": "jcudf_row_conversion_roundtrip_1M",
        "value": 0.0, "unit": "GB/s", "vs_baseline": 0.0, "error": msg,
    })
    sys.exit(0)


def _probe_backend(max_tries: int = 3):
    """Initialize the JAX backend, re-execing to retry transient failures
    (backend-init failure is cached process-wide by JAX, so retries need a
    fresh process).  After the budget: emit an error JSON and exit 0 so the
    driver always records a parseable result."""
    import jax
    try:
        return jax.devices()
    except Exception as e:  # noqa: BLE001 — any init failure handled the same
        from spark_rapids_jni_tpu.utils import knobs
        tries = knobs.get("SRJT_BENCH_TRIES")
        if tries < max_tries:
            os.environ["SRJT_BENCH_TRIES"] = str(tries + 1)
            time.sleep(5)  # short: a driver timeout must not outrun the JSON
            os.execv(sys.executable, [sys.executable] + sys.argv)
        _fail(f"backend init failed after {max_tries} retries: {e!r}")


import jax                                                    # noqa: E402

_DEVICES = _probe_backend()

try:
    import jax.numpy as jnp
    import spark_rapids_jni_tpu as sr
    from spark_rapids_jni_tpu import (Column, Table, convert_to_rows,
                                      convert_from_rows)
    from spark_rapids_jni_tpu.rowconv import host as host_engine
except Exception as e:  # noqa: BLE001 — import failure must still yield JSON
    _fail(f"package import failed: {e!r}")

# Reference type cycle (row_conversion.cpp:30-38), f64 included.
CYCLE = [sr.int8, sr.int16, sr.int32, sr.int64, sr.float32, sr.float64,
         sr.bool8]


def build_table(n_rows: int, n_cols: int, string_every: int = 0,
                seed: int = 7, cycle=None) -> Table:
    rng = np.random.default_rng(seed)
    cycle = cycle or CYCLE
    words = ["", "tpu", "spark-rapids", "columnar row transcode",
             "x" * 24, "payload"]
    cols = []
    for i in range(n_cols):
        if string_every and i % string_every == string_every - 1:
            strs = [words[j] for j in rng.integers(0, len(words), n_rows)]
            cols.append(Column.strings_from_list(strs))
            continue
        dt = cycle[i % len(cycle)]
        if dt == sr.bool8:
            arr = rng.integers(0, 2, n_rows).astype(np.uint8)
        elif dt.storage.kind == "f":
            arr = rng.standard_normal(n_rows).astype(dt.storage)
        else:
            info = np.iinfo(dt.storage)
            arr = rng.integers(info.min // 2, info.max // 2, n_rows,
                               dtype=dt.storage)
        validity = rng.random(n_rows) < 0.9 if i % 3 == 0 else None
        cols.append(Column.from_numpy(arr, dt, validity))
    return Table(cols)


def _chained_loop(body, data):
    """jit(data, iters): run ``body`` iters times, dependency-chained."""
    @jax.jit
    def run(data, iters):
        def step(_, carry):
            acc, d = carry
            din = jax.lax.optimization_barrier((d, acc))[0]
            out = body(din)
            out = jax.lax.optimization_barrier(out)
            leaf = jax.tree_util.tree_leaves(out)[0]
            probe = jax.lax.convert_element_type(jnp.ravel(leaf)[0],
                                                 jnp.int32)
            return (acc + probe) % jnp.int32(65521), d
        acc, _ = jax.lax.fori_loop(0, iters, step, (jnp.int32(0), data))
        return acc
    return run


def time_diff(body, data, lo: int, hi: int, repeats: int = 2) -> float:
    """Steady-state seconds/iteration by trip-count differencing.

    A repeat whose delta is non-positive (t_hi <= t_lo: pure timing noise,
    e.g. a tunnel stall during the lo run) is discarded and retried rather
    than clamped — clamping to 1e-9 s would report an absurd ~1e9× GB/s."""
    run = _chained_loop(body, data)
    np.asarray(run(data, lo))            # compile + warm
    best = None
    good = 0
    for _ in range(repeats + 3):         # up to 3 extra retries for noise
        t0 = time.perf_counter()
        np.asarray(run(data, lo))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(run(data, hi))
        t_hi = time.perf_counter() - t0
        per = (t_hi - t_lo) / (hi - lo)
        if per <= 0:
            continue
        good += 1
        best = per if best is None else min(best, per)
        if good >= repeats:
            break
    if best is None:
        raise RuntimeError(
            f"time_diff: every repeat non-positive (last: t_lo={t_lo:.3f}s "
            f"t_hi={t_hi:.3f}s) — timing unusable, not clamping")
    return best


def bench_fixed(name: str, table: Table, lo: int, hi: int, results: list):
    schema = table.schema
    batch0 = convert_to_rows(table)[0]
    row_bytes = batch0.num_bytes

    def to_body(tbl):
        return convert_to_rows(tbl)[0].data

    def from_body(b):
        return convert_from_rows(b, schema).columns[0].data

    def rt_body(tbl):
        b = convert_to_rows(tbl)[0]
        # Materialize the row stream between directions: without the
        # barrier XLA cancels from∘to (the deinterleave is the inverse
        # permute of the interleave) and "measures" an identity.
        from spark_rapids_jni_tpu.rowconv.convert import RowBatch
        b = RowBatch(jax.lax.optimization_barrier(b.data), b.offsets)
        return convert_from_rows(b, schema).columns[0].data

    out = {}
    for direction, body, data, nbytes in [
            ("to_rows", to_body, table, row_bytes),
            ("from_rows", from_body, batch0, row_bytes),
            ("roundtrip", rt_body, table, 2 * row_bytes)]:
        per = time_diff(body, data, lo, hi)
        gbps = nbytes / per / 1e9
        out[direction] = round(gbps, 2)
        results.append({"metric": f"{name}_{direction}",
                        "value": round(gbps, 3), "unit": "GB/s",
                        "ms_per_iter": round(per * 1e3, 3)})
        _progress(results[-1])
    return out


def _strings_steady_to_rows(table: Table):
    """In-jit steady-state seconds/to_rows for the xpack var engine.

    The round-4 var-width engine runs the WHOLE batch as one jitted
    program with zero internal host syncs (rowconv/xpack.py), so the same
    trip-count-differencing methodology as the fixed path applies — this
    is the nvbench-hot-loop quantity.  Returns None when the xpack path
    does not cover the geometry (caller falls back to wall timing only).
    """
    from spark_rapids_jni_tpu.rowconv import xpack
    from spark_rapids_jni_tpu.rowconv.layout import (
        compute_row_layout, row_sizes_with_strings, build_batches,
        MAX_BATCH_BYTES)
    from spark_rapids_jni_tpu.utils import hostcache
    layout = compute_row_layout(table.schema)
    n = table.num_rows
    var_idx = layout.variable_column_indices
    col_offs = [hostcache.host_i64(table[ci].offsets) for ci in var_idx]
    total_lens = np.zeros(n, dtype=np.int64)
    for o in col_offs:
        total_lens += o[1:] - o[:-1]
    batches = build_batches(row_sizes_with_strings(layout, total_lens),
                            MAX_BATCH_BYTES)
    if len(batches.row_boundaries) != 2:
        return None                      # multi-batch: wall timing only
    offs_np = batches.row_offsets_within_batch[0]
    geom = xpack._plan_geometry(layout, n, offs_np, col_offs)
    if geom is None:
        return None
    data = (tuple(c.data for c in table.columns),
            tuple(table[ci].offsets for ci in var_idx),
            tuple(c.validity for c in table.columns))

    def body(a):
        return xpack._to_rows_x_jit(layout, geom, a[0], a[1], a[2])
    per = time_diff(body, data, 2, 8)
    return per, int(offs_np[-1])


def _strings_steady_from_rows(table: Table, batch):
    """In-jit steady-state seconds/from_rows for the inverse xpack engine
    (round 5): the whole batch as ONE jitted program, same trip-count
    differencing as the fixed path.  None when the engine does not cover
    the geometry."""
    from spark_rapids_jni_tpu.rowconv import xpack
    from spark_rapids_jni_tpu.rowconv.layout import compute_row_layout
    layout = compute_row_layout(table.schema)
    words = xpack.batch_words(batch)
    geom = xpack.plan_from_rows(layout, batch, words)
    if geom is None:
        return None

    def body(a):
        # return the FULL output tree: returning one leaf would let
        # jaxpr-level DCE prune the rest of the program's outputs and
        # time a fraction of the conversion
        return xpack._from_rows_x_jit(layout, geom, a[0], a[1])
    per = time_diff(body, (words, batch.offsets), 2, 8)
    return per, batch.num_bytes


def _try_steady(fn, tag: str, tries: int = 2):
    """Best-effort steady probe with a retry (the remote helper can 500
    transiently — round 4 lost the 155-col label to a single failure)."""
    for attempt in range(tries):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — steady number is best-effort
            _progress({"metric": tag, "attempt": attempt,
                       "error": repr(e)[:200]})
    return None


def bench_strings(name: str, table: Table, iters: int, results: list):
    """Strings axis: in-jit steady state for BOTH directions (one-program
    xpack engines) + honest wall-clock."""
    schema = table.schema
    batches = convert_to_rows(table)          # warm/compile
    all_bytes = sum(b.num_bytes for b in batches)
    batch0_bytes = batches[0].num_bytes       # from_rows times batch 0 only
    np.asarray(batches[0].data[:8])

    t0 = time.perf_counter()
    for _ in range(iters):
        b = convert_to_rows(table)[0]
        np.asarray(b.data[:8])
    to_s = (time.perf_counter() - t0) / iters

    steady = _try_steady(lambda: _strings_steady_to_rows(table),
                         f"{name}_to_rows_steady_error")

    back = convert_from_rows(batches[0], schema)   # warm
    np.asarray(back.columns[0].data[:8])
    t0 = time.perf_counter()
    for _ in range(iters):
        t = convert_from_rows(batches[0], schema)
        np.asarray(t.columns[0].data[:8])
    from_s = (time.perf_counter() - t0) / iters

    steady_from = _try_steady(
        lambda: _strings_steady_from_rows(table, batches[0]),
        f"{name}_from_rows_steady_error")

    for direction, steady_res, wall_s, wall_bytes in [
            ("to_rows", steady, to_s, all_bytes),
            ("from_rows", steady_from, from_s, batch0_bytes)]:
        if steady_res is not None:
            per, nbytes = steady_res
            results.append({
                "metric": f"{name}_{direction}",
                "value": round(nbytes / per / 1e9, 3),
                "unit": "GB/s", "ms_per_iter": round(per * 1e3, 1),
                "timing": "in-jit chained fori_loop (one-program xpack "
                          "engine)",
                "wall_ms": round(wall_s * 1e3, 1),
                "wall_gbps": round(wall_bytes / wall_s / 1e9, 3)})
        else:
            results.append({
                "metric": f"{name}_{direction}",
                "value": round(wall_bytes / wall_s / 1e9, 3),
                "unit": "GB/s", "ms_per_iter": round(wall_s * 1e3, 1),
                "timing": "wall-clock (host-orchestrated path)"})
        _progress(results[-1])


def time_host(table: Table) -> float:
    def roundtrip():
        rows = host_engine.to_rows_fixed_np(table)
        host_engine.from_rows_fixed_np(rows, table.schema)

    roundtrip()
    t0 = time.perf_counter()
    for _ in range(2):
        roundtrip()
    return (time.perf_counter() - t0) / 2


def main():
    quick = "--quick" in sys.argv
    # wall budget for the OPTIONAL axes: the headline must never be starved
    # by a driver-side timeout, so it is emitted the moment it exists and
    # the axes only run while budget remains (each new axis needs several
    # cold jit compiles through the remote helper)
    from spark_rapids_jni_tpu.utils import knobs
    try:
        budget_s = knobs.get("SRJT_BENCH_BUDGET_S")
    except ValueError:
        budget_s = 1200.0   # malformed env must not cost the headline
    t_start = time.perf_counter()
    results: list = []

    # headline config: 12-col cycled fixed schema @ 1M rows
    t12_1m = build_table(1_000_000, 12)
    head = bench_fixed("fixed12_1M", t12_1m, 5, 45, results)

    host_s = time_host(t12_1m)
    row_bytes = convert_to_rows(t12_1m)[0].num_bytes
    host_gbps = 2 * row_bytes / host_s / 1e9

    def headline(axes):
        from spark_rapids_jni_tpu.rowconv import xpack
        return {
            "metric": "jcudf_row_conversion_roundtrip_1M",
            "value": head["roundtrip"],
            "unit": "GB/s",
            "vs_baseline": round(head["roundtrip"] / host_gbps, 3),
            "backend": _DEVICES[0].platform,
            "to_rows": head["to_rows"],
            "from_rows": head["from_rows"],
            "host_gbps": round(host_gbps, 3),
            "timing": "in-jit chained fori_loop, trip-count differencing",
            "xpack_fallbacks": dict(xpack.fallback_counts),
            "axes": axes,
        }

    # emit NOW: if anything below dies or the driver's clock runs out, the
    # last stdout line is already a complete, parseable headline
    _emit(headline(results + [{"metric": "axes_pending"}] if not quick
                   else results))

    if not quick:
        axes = [
            ("fixed12_4M", lambda name: bench_fixed(
                name, build_table(4_000_000, 12), 3, 13, results)),
            ("fixed212_1M", lambda name: bench_fixed(
                name, build_table(1_000_000, 212), 3, 13, results)),
            ("strings_mixed12_1M", lambda name: bench_strings(
                name, build_table(1_000_000, 12, string_every=3), 3,
                results)),
            # 155-col wide schema with strings (reference axis,
            # row_conversion.cpp:69-138): narrow type cycle keeps the row
            # under the 1KB JCUDF limit (~500B rows, 15 string columns)
            ("strings_mixed155_256K", lambda name: bench_strings(
                name, build_table(256_000, 155, string_every=10,
                                  cycle=[sr.int32, sr.int16, sr.int8,
                                         sr.float32, sr.bool8]), 2,
                results)),
        ]
        for name, run_axis in axes:
            if time.perf_counter() - t_start > budget_s:
                results.append({"metric": "axes_skipped_budget",
                                "skipped_from": name})
                _progress(results[-1])
                break
            try:
                run_axis(name)
            except Exception as e:  # noqa: BLE001 — axes are best-effort
                results.append({"metric": "axis_error", "axis": name,
                                "error": repr(e)[:300]})
                _progress(results[-1])

    _emit(headline(results))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a JSON line, always
        _fail(repr(e))
