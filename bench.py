#!/usr/bin/env python
"""Headline benchmark: JCUDF row-conversion round trip on TPU vs CPU baseline.

BASELINE.md staged config #1: "row_conversion round-trip micro-op (1M-row
int64 batch, CPU ref)".  Mirrors the reference's nvbench axes in spirit
(``benchmarks/row_conversion.cpp:27-67``: N-row cycled fixed-width schema ×
{to row, from row}, reporting memory throughput).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

value        = bytes transcoded per second through the device path, counting
               the JCUDF row bytes once per direction (to_rows + from_rows).
vs_baseline  = device GB/s / vectorized-NumPy-host GB/s on the same workload.
"""

import json
import os
import sys
import time

import numpy as np

import jax


def _emit(payload: dict) -> None:
    print(json.dumps(payload))
    sys.stdout.flush()


def _probe_backend(max_tries: int = 3) -> list:
    """Initialize the JAX backend, re-execing to retry transient failures.

    Round-1 postmortem: a one-shot ``Unable to initialize backend`` traceback
    produced rc=1 and no JSON at all (BENCH_r01.json parsed:null).  Backend
    init failure is cached process-wide by JAX, so retries must come from a
    fresh process: re-exec with a counter.  After the budget is spent, emit a
    JSON line with an "error" key and exit 0 so the driver always records a
    parseable result.
    """
    try:
        return jax.devices()
    except Exception as e:  # noqa: BLE001 — any init failure handled the same
        tries = int(os.environ.get("SRJT_BENCH_TRIES", "0"))
        if tries < max_tries:
            os.environ["SRJT_BENCH_TRIES"] = str(tries + 1)
            time.sleep(5)  # short: a driver timeout must not outrun the JSON
            os.execv(sys.executable, [sys.executable] + sys.argv)
        _emit({
            "metric": "jcudf_row_conversion_roundtrip_1M",
            "value": 0.0,
            "unit": "GB/s",
            "vs_baseline": 0.0,
            "error": f"backend init failed after {max_tries} retries: {e!r}",
        })
        sys.exit(0)


_DEVICES = _probe_backend()

try:
    import spark_rapids_jni_tpu as sr
    from spark_rapids_jni_tpu import (Column, Table, convert_to_rows,
                                      convert_from_rows)
    from spark_rapids_jni_tpu.rowconv import host as host_engine
except Exception as e:  # noqa: BLE001 — import failure must still yield JSON
    _emit({
        "metric": "jcudf_row_conversion_roundtrip_1M",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
        "error": f"package import failed: {e!r}",
    })
    sys.exit(0)

N_ROWS = 1_000_000
# 12-column cycled fixed-width schema (int64-heavy per BASELINE config #1;
# f64 excluded: its payload legitimately stages via host on TPU and would
# turn this into a transfer benchmark).
SCHEMA_CYCLE = [sr.int64, sr.int32, sr.int16, sr.int8, sr.float32, sr.bool8]
N_COLS = 12
WARMUP, ITERS = 2, 5


def build_table(n_rows: int) -> Table:
    rng = np.random.default_rng(7)
    cols = []
    for i in range(N_COLS):
        dt = SCHEMA_CYCLE[i % len(SCHEMA_CYCLE)]
        if dt.storage.kind == "f":
            arr = rng.standard_normal(n_rows).astype(dt.storage)
        elif dt == sr.bool8:
            arr = rng.integers(0, 2, n_rows).astype(np.uint8)
        else:
            info = np.iinfo(dt.storage)
            arr = rng.integers(info.min // 2, info.max // 2, n_rows,
                               dtype=dt.storage)
        validity = rng.random(n_rows) < 0.9 if i % 3 == 0 else None
        cols.append(Column.from_numpy(arr, dt, validity))
    return Table(cols)


def time_device(table: Table) -> tuple[float, int]:
    """In-jit chained-loop timing with one forced materialization.

    Two facts about the axon-tunneled v5e dictate the shape of this timer
    (round-1's 106-208 GB/s figure predates both and was a dispatch-rate
    artifact, not throughput):

    * ``jax.block_until_ready`` is NOT a sync — execution defers until bytes
      are requested, so the timed window must end with a real (tiny) D2H;
    * every dispatch costs ~12 ms and every sync ~65-110 ms through the
      tunnel, so the ITERS round trips run inside ONE jitted ``fori_loop``
      (the public conversion API is jit-traceable for fixed-width schemas),
      dependency-chained so the device cannot elide iterations.
    """
    import jax.numpy as jnp
    from spark_rapids_jni_tpu.column import Column, Table as _Table

    batches0 = convert_to_rows(table)
    total_bytes = sum(b.num_bytes for b in batches0)

    @jax.jit
    def loop(table):
        def body(_, carry):
            cols = list(table.columns)
            c0 = cols[0]
            cols[0] = Column(c0.dtype,
                             jax.lax.optimization_barrier(
                                 (c0.data, carry))[0],
                             c0.offsets, c0.validity)
            acc = jnp.zeros((), jnp.int32)
            for batch in convert_to_rows(_Table(cols)):
                back = convert_from_rows(batch, table.schema)
                for c in back.columns:
                    acc = acc + jax.lax.convert_element_type(
                        jnp.ravel(c.data)[0], jnp.int32)
            return acc % jnp.int32(251)
        return jax.lax.fori_loop(0, ITERS, body, jnp.int32(0))

    np.asarray(loop(table))   # compile + warm
    t0 = time.perf_counter()
    np.asarray(loop(table))   # one dispatch, one real sync
    dt = (time.perf_counter() - t0) / ITERS
    return dt, total_bytes


def time_host(table: Table) -> float:
    def roundtrip():
        rows = host_engine.to_rows_fixed_np(table)
        host_engine.from_rows_fixed_np(rows, table.schema)
        return rows

    roundtrip()
    t0 = time.perf_counter()
    for _ in range(max(1, ITERS // 2)):
        roundtrip()
    return (time.perf_counter() - t0) / max(1, ITERS // 2)


def main():
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else N_ROWS
    table = build_table(n_rows)

    dev_s, row_bytes = time_device(table)
    host_s = time_host(table)

    transcoded = 2 * row_bytes  # row bytes once per direction
    dev_gbps = transcoded / dev_s / 1e9
    host_gbps = transcoded / host_s / 1e9

    _emit({
        "metric": "jcudf_row_conversion_roundtrip_1M",
        "value": round(dev_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(dev_gbps / host_gbps, 3),
        "backend": _DEVICES[0].platform,
    })


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a JSON line, always
        _emit({
            "metric": "jcudf_row_conversion_roundtrip_1M",
            "value": 0.0,
            "unit": "GB/s",
            "vs_baseline": 0.0,
            "error": repr(e),
        })
        sys.exit(0)
