"""Shuffle tests on the virtual 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from spark_rapids_jni_tpu.ops.hashing import murmur3_32, hash_partition
from spark_rapids_jni_tpu.parallel import (make_mesh, bucketize_rows,
                                           all_to_all_shuffle)
from spark_rapids_jni_tpu.parallel.shuffle import received_mask

try:                                    # jax ≥ 0.5 top-level name
    _shard_map = jax.shard_map
except AttributeError:                  # 0.4.x keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def test_bucketize_groups_and_counts():
    rows = jnp.asarray(np.arange(20, dtype=np.uint8).reshape(10, 2))
    part = jnp.asarray(np.asarray([0, 1, 0, 2, 1, 0, 2, 2, 2, 1],
                                  dtype=np.int32))
    b = bucketize_rows(rows, part, num_partitions=3, capacity=4)
    np.testing.assert_array_equal(np.asarray(b.counts), [3, 3, 4])
    assert int(b.dropped) == 0
    # bucket 0 holds rows 0, 2, 5 in arrival order
    np.testing.assert_array_equal(np.asarray(b.rows)[0, :3],
                                  np.asarray(rows)[[0, 2, 5]])


def test_bucketize_capacity_overflow_counted():
    rows = jnp.zeros((10, 2), dtype=jnp.uint8)
    part = jnp.zeros((10,), dtype=jnp.int32)
    b = bucketize_rows(rows, part, num_partitions=2, capacity=4)
    np.testing.assert_array_equal(np.asarray(b.counts), [4, 0])
    assert int(b.dropped) == 6


def test_all_to_all_shuffle_delivers_every_row_once():
    n_dev, per_dev, cap = 8, 32, 24
    mesh = make_mesh(n_dev)
    keys_np = np.arange(n_dev * per_dev, dtype=np.int64)
    rows_np = np.repeat(keys_np[:, None], 4, axis=1).astype(np.uint8)

    def step(keys, rows):
        part = hash_partition(murmur3_32(keys), n_dev)
        sent = bucketize_rows(rows, part, n_dev, cap)
        recv = all_to_all_shuffle(sent, "data")
        mask = received_mask(recv)
        # every received row must now hash-partition to *this* device
        my = jax.lax.axis_index("data")
        flat = recv.rows.reshape(-1, rows.shape[1])
        rec_keys = flat[:, 0].astype(jnp.int64)  # low byte of key
        ok = jnp.all(jnp.where(
            mask.reshape(-1),
            hash_partition(murmur3_32(rec_keys), n_dev) == my, True))
        return (jax.lax.psum(recv.counts.sum(), "data"),
                jax.lax.psum(recv.dropped, "data"),
                jax.lax.psum(ok.astype(jnp.int32), "data"))

    fn = jax.jit(_shard_map(step, mesh=mesh,
                            in_specs=(P("data"), P("data")),
                            out_specs=(P(), P(), P())))
    # keys < 256 so the uint8 row payload round-trips the key exactly
    total, dropped, ok = fn(jnp.asarray(keys_np), jnp.asarray(rows_np))
    assert int(np.asarray(total)[0] if np.asarray(total).ndim else total) == n_dev * per_dev
    assert int(np.asarray(dropped).reshape(-1)[0]) == 0
    assert int(np.asarray(ok).reshape(-1)[0]) == n_dev


def test_graft_entry_contract():
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out[2].shape == ()
    g.dryrun_multichip(8)
    g.dryrun_multichip(2)


def test_bucketize_out_of_range_part_ids_dropped_not_misrouted():
    rows = jnp.asarray(np.arange(12, dtype=np.uint8).reshape(6, 2))
    part = jnp.asarray(np.asarray([0, -1, 1, 3, 2, 0], dtype=np.int32))
    b = bucketize_rows(rows, part, num_partitions=3, capacity=4)
    np.testing.assert_array_equal(np.asarray(b.counts), [2, 1, 1])
    assert int(b.dropped) == 2  # the -1 and the 3
    # partition 2 must hold only its own row, not the wrapped -1
    np.testing.assert_array_equal(np.asarray(b.rows)[2, 0], [8, 9])
