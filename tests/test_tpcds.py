"""TPC-DS subset differential tests (BASELINE config #3): every query's
result is compared against pandas executing the same plan over the same
Snappy parquet bytes — join + groupby + string keys + decimals end-to-end
through decode → ops → output."""

import io

import numpy as np
import pandas as pd
import pytest

from benchmarks import tpcds_data
from spark_rapids_jni_tpu.models import tpcds


@pytest.fixture(scope="module")
def files():
    return tpcds_data.generate(n_sales=40_000, n_items=500, seed=7)


@pytest.fixture(scope="module")
def dfs(files):
    return {name: pd.read_parquet(io.BytesIO(raw))
            for name, raw in files.items()}


@pytest.fixture(scope="module")
def tables(files):
    return tpcds.load_tables(files)


def _assert_result(out, expect_df, key_cols, val_specs):
    """out: framework Table (keys..., aggs...); expect_df: pandas frame with
    the same columns, unsorted."""
    expect = expect_df.sort_values(key_cols).reset_index(drop=True)
    assert out.num_rows == len(expect), (out.num_rows, len(expect))
    for i, k in enumerate(key_cols):
        got = (out[i].to_pylist() if out[i].dtype.id.name == "STRING"
               else out[i].to_numpy().tolist())
        assert got == expect[k].tolist(), k
    for j, (name, kind) in enumerate(val_specs):
        got = np.asarray(out[len(key_cols) + j].to_numpy(), dtype=np.float64)
        if kind == "decimal2":
            got = got / 100.0
        np.testing.assert_allclose(got, expect[name].to_numpy(), rtol=1e-9)


def test_q3(tables, dfs):
    mid = int(dfs["item"].i_manufact_id.mode()[0])   # guaranteed present
    out = tpcds.q3(tables, manufact_id=mid, moy=11)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item[item.i_manufact_id == mid], left_on="ss_item_sk",
                  right_on="i_item_sk")
         .merge(dd[dd.d_moy == 11], left_on="ss_sold_date_sk",
                right_on="d_date_sk"))
    exp = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           ["ss_ext_sales_price"].sum())
    _assert_result(out, exp, ["d_year", "i_brand_id", "i_brand"],
                   [("ss_ext_sales_price", "float")])


def test_q42(tables, dfs):
    mid = int(dfs["item"].i_manager_id.mode()[0])
    out = tpcds.q42(tables, manager_id=mid, year=2000, moy=11)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item[item.i_manager_id == mid], left_on="ss_item_sk",
                  right_on="i_item_sk")
         .merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)],
                left_on="ss_sold_date_sk", right_on="d_date_sk"))
    exp = (j.groupby(["d_year", "i_category_id", "i_category"],
                     as_index=False)["ss_ext_sales_price"].sum())
    _assert_result(out, exp, ["d_year", "i_category_id", "i_category"],
                   [("ss_ext_sales_price", "float")])


def test_q52(tables, dfs):
    out = tpcds.q52(tables, moy=12, year=2001)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 2001)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(item, left_on="ss_item_sk", right_on="i_item_sk"))
    exp = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           ["ss_ext_sales_price"].sum())
    _assert_result(out, exp, ["d_year", "i_brand_id", "i_brand"],
                   [("ss_ext_sales_price", "float")])


def test_q55(tables, dfs):
    mid = int(dfs["item"].i_manager_id.mode()[0])
    out = tpcds.q55(tables, manager_id=mid)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item[item.i_manager_id == mid], left_on="ss_item_sk",
                 right_on="i_item_sk")
    exp = (j.groupby(["i_brand_id", "i_brand"], as_index=False)
           ["ss_ext_sales_price"].sum())
    _assert_result(out, exp, ["i_brand_id", "i_brand"],
                   [("ss_ext_sales_price", "float")])


def test_q_state_rollup(tables, dfs):
    out = tpcds.q_state_rollup(tables, state="TN")
    ss, store = dfs["store_sales"], dfs["store"]
    j = ss.merge(store[store.s_state == "TN"], left_on="ss_store_sk",
                 right_on="s_store_sk")
    exp = (j.groupby(["s_state"], as_index=False)
           .agg(price=("ss_sales_price_cents", "sum"),
                qmean=("ss_quantity", "mean"),
                qcount=("ss_quantity", "count")))
    exp["price"] = exp["price"] / 100.0   # decimal(…,2) dollars
    _assert_result(out, exp, ["s_state"],
                   [("price", "decimal2"), ("qmean", "float"),
                    ("qcount", "float")])


def test_q7(tables, dfs):
    out = tpcds.q7(tables, year=2000)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
         .merge(item, left_on="ss_item_sk", right_on="i_item_sk"))
    exp = (j.groupby(["i_item_id"], as_index=False)
           .agg(q=("ss_quantity", "mean"),
                lp=("ss_list_price_cents", "mean"),
                sp=("ss_sales_price_cents", "mean")))
    _assert_result(out, exp, ["i_item_id"],
                   [("q", "float"), ("lp", "float"), ("sp", "float")])


def test_q19(tables, dfs):
    out = tpcds.q19(tables, year=1999, moy=11, manager_lo=1, manager_hi=50)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(item[(item.i_manager_id >= 1) & (item.i_manager_id <= 50)],
                  left_on="ss_item_sk", right_on="i_item_sk")
         .merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)],
                left_on="ss_sold_date_sk", right_on="d_date_sk"))
    exp = (j.groupby(["i_brand_id", "i_brand", "i_manufact_id"],
                     as_index=False)["ss_ext_sales_price"].sum())
    _assert_result(out, exp, ["i_brand_id", "i_brand", "i_manufact_id"],
                   [("ss_ext_sales_price", "float")])


def test_q62(tables, dfs):
    out = tpcds.q62(tables, year=2000, qty_lo=10, qty_hi=60)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = (ss[(ss.ss_quantity >= 10) & (ss.ss_quantity <= 60)]
         .merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                right_on="d_date_sk"))
    exp = (j.groupby(["d_moy"], as_index=False)
           .agg(cnt=("ss_quantity", "count")))
    _assert_result(out, exp, ["d_moy"], [("cnt", "float")])


def test_q52_topn(tables, dfs):
    out = tpcds.q52_topn(tables, moy=12, year=2001, n=5)
    ss, item, dd = dfs["store_sales"], dfs["item"], dfs["date_dim"]
    j = (ss.merge(dd[(dd.d_moy == 12) & (dd.d_year == 2001)],
                  left_on="ss_sold_date_sk", right_on="d_date_sk")
         .merge(item, left_on="ss_item_sk", right_on="i_item_sk"))
    exp = (j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .sort_values(["ss_ext_sales_price", "i_brand_id"],
                        ascending=[False, True]).head(5)
           .reset_index(drop=True))
    assert out.num_rows == len(exp)
    assert out[1].to_numpy().tolist() == exp["i_brand_id"].tolist()
    np.testing.assert_allclose(np.asarray(out[3].to_numpy()),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)


def test_q65(tables, dfs):
    out = tpcds.q65(tables, frac=0.9)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    rev = (j.groupby(["i_brand_id"], as_index=False)
           ["ss_ext_sales_price"].sum())
    thr = rev["ss_ext_sales_price"].mean() * 0.9
    exp = (rev[rev.ss_ext_sales_price < thr]
           .sort_values("i_brand_id").reset_index(drop=True))
    _assert_result(out, exp, ["i_brand_id"],
                   [("ss_ext_sales_price", "float")])


def test_q_store_counts(tables, dfs):
    out = tpcds.q_store_counts(tables)
    ss, store = dfs["store_sales"], dfs["store"]
    j = store.merge(ss, left_on="s_store_sk", right_on="ss_store_sk",
                    how="left")
    exp = (j.groupby(["s_store_sk", "s_state"], as_index=False)
           .agg(cnt=("ss_item_sk", "count"))
           .sort_values("s_store_sk").reset_index(drop=True))
    assert out.num_rows == len(exp)
    assert out[0].to_numpy().tolist() == exp["s_store_sk"].tolist()
    assert out[2].to_numpy().tolist() == exp["cnt"].tolist()
    # the never-selling store must appear with count 0
    assert 0 in out[2].to_numpy().tolist()


@pytest.mark.slow
def test_run_all_smoke(files):
    # spec-default parameters may select nothing at this mini scale — an
    # empty result is a valid result (Spark returns empty, not an error)
    results = tpcds.run_all(files)
    assert set(results) == set(tpcds.QUERIES)
    for name, t in results.items():
        # set-operation queries (INTERSECT/EXCEPT) legitimately return a
        # single key column; everything else carries keys + measures
        min_cols = 1 if name in ("q8_intersect", "q87_except") else 2
        assert t.num_columns >= min_cols, name
        assert t.num_rows >= 0, name


# ---- round-3 additions: window / LIKE / union / distinct-count family ----

def test_q67_rank(tables, dfs):
    out = tpcds.q67_rank(tables, top_n=3)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    rev = (j.groupby(["i_category", "i_brand_id"], as_index=False)
           ["ss_ext_sales_price"].sum())
    rev["rk"] = (rev.sort_values(["ss_ext_sales_price", "i_brand_id"],
                                 ascending=[False, True])
                 .groupby("i_category").cumcount() + 1)
    # pandas rank with our tie semantics: RANK over (sum desc, brand asc)
    # has no ties because brand_id is unique within the sort
    exp = (rev[rev.rk <= 3]
           .sort_values(["i_category", "rk", "i_brand_id"])
           .reset_index(drop=True))
    assert out.num_rows == len(exp)
    assert out[0].to_pylist() == exp["i_category"].tolist()
    assert out[1].to_numpy().tolist() == exp["i_brand_id"].tolist()
    np.testing.assert_allclose(out[2].to_numpy(),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)
    assert out[3].to_numpy().tolist() == exp["rk"].tolist()


def test_q_like_brands(tables, dfs):
    out = tpcds.q_like_brands(tables, pat="#1", cat_prefix="S")
    ss, item = dfs["store_sales"], dfs["item"]
    item_f = item[item.i_brand.str.contains("#1", regex=False)
                  & item.i_category.str.startswith("S")]
    j = ss.merge(item_f, left_on="ss_item_sk", right_on="i_item_sk")
    exp = (j.groupby(["i_category"], as_index=False)
           ["ss_ext_sales_price"].sum())
    _assert_result(out, exp, ["i_category"],
                   [("ss_ext_sales_price", "float")])


def test_q_union_channels(tables, dfs):
    out = tpcds.q_union_channels(tables)
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    both = pd.concat([
        ss[["ss_item_sk", "ss_ext_sales_price"]]
        .rename(columns={"ss_item_sk": "sk", "ss_ext_sales_price": "price"}),
        ws[["ws_item_sk", "ws_ext_sales_price"]]
        .rename(columns={"ws_item_sk": "sk", "ws_ext_sales_price": "price"}),
    ])
    j = both.merge(item, left_on="sk", right_on="i_item_sk")
    exp = j.groupby(["i_category"], as_index=False)["price"].sum()
    _assert_result(out, exp, ["i_category"], [("price", "float")])


def test_q_lag_growth(tables, dfs):
    out = tpcds.q_lag_growth(tables)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    rev = (j.groupby(["ss_store_sk", "d_year", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .sort_values(["ss_store_sk", "d_year", "d_moy"])
           .reset_index(drop=True))
    prev = rev.groupby("ss_store_sk")["ss_ext_sales_price"].shift(1)
    delta = rev["ss_ext_sales_price"] - prev.fillna(0.0)
    assert out.num_rows == len(rev)
    np.testing.assert_array_equal(out[0].to_numpy(),
                                  rev["ss_store_sk"].to_numpy())
    got_delta = np.asarray(
        [v if v is not None else np.nan for v in out[4].to_pylist()])
    want = np.where(prev.isna().to_numpy(), np.nan, delta.to_numpy())
    np.testing.assert_allclose(got_delta, want, rtol=1e-9)


def test_q_running_share(tables, dfs):
    out = tpcds.q_running_share(tables, year=2000)
    ss, dd = dfs["store_sales"], dfs["date_dim"]
    j = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    rev = (j.groupby(["ss_store_sk", "d_moy"], as_index=False)
           ["ss_ext_sales_price"].sum()
           .sort_values(["ss_store_sk", "d_moy"]).reset_index(drop=True))
    rev["cum"] = rev.groupby("ss_store_sk")["ss_ext_sales_price"].cumsum()
    assert out.num_rows == len(rev)
    np.testing.assert_allclose(out[3].to_numpy(), rev["cum"].to_numpy(),
                               rtol=1e-9)


def test_q_nunique_items(tables, dfs):
    out = tpcds.q_nunique_items(tables)
    ss = dfs["store_sales"]
    exp = (ss.groupby("ss_store_sk")["ss_item_sk"].nunique()
           .reset_index().sort_values("ss_store_sk"))
    assert out[0].to_numpy().tolist() == exp["ss_store_sk"].tolist()
    assert out[1].to_numpy().tolist() == exp["ss_item_sk"].tolist()


def test_q_having(tables, dfs):
    out = tpcds.q_having(tables, min_total=1000.0)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    rev = (j.groupby("i_brand_id", as_index=False)
           ["ss_ext_sales_price"].sum())
    exp = rev[rev.ss_ext_sales_price > 1000.0].sort_values("i_brand_id")
    assert out[0].to_numpy().tolist() == exp["i_brand_id"].tolist()
    np.testing.assert_allclose(out[1].to_numpy(),
                               exp["ss_ext_sales_price"].to_numpy(),
                               rtol=1e-9)


def test_q_case_when(tables, dfs):
    out = tpcds.q_case_when(tables, qty_cut=50)
    ss, item = dfs["store_sales"], dfs["item"]
    j = ss.merge(item, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.assign(bulk=np.where(j.ss_quantity > 50, j.ss_ext_sales_price, 0.0),
                 retail=np.where(j.ss_quantity > 50, 0.0,
                                 j.ss_ext_sales_price))
    exp = j.groupby("i_category", as_index=False)[["bulk", "retail"]].sum()
    _assert_result(out, exp, ["i_category"],
                   [("bulk", "float"), ("retail", "float")])


def test_q_distinct_pairs(tables, dfs):
    out = tpcds.q_distinct_pairs(tables)
    item = dfs["item"]
    exp = (item[["i_brand_id", "i_category_id"]].drop_duplicates()
           .sort_values(["i_brand_id", "i_category_id"]))
    assert out.num_rows == len(exp)
    assert out[0].to_numpy().tolist() == exp["i_brand_id"].tolist()
    assert out[1].to_numpy().tolist() == exp["i_category_id"].tolist()


def test_q_isin_states(tables, dfs):
    out = tpcds.q_isin_states(tables, states=("TN", "CA"))
    ss, store = dfs["store_sales"], dfs["store"]
    store_f = store[store.s_state.isin(["TN", "CA"])]
    j = ss.merge(store_f, left_on="ss_store_sk", right_on="s_store_sk")
    exp = j.groupby(["s_state"], as_index=False)["ss_ext_sales_price"].sum()
    _assert_result(out, exp, ["s_state"], [("ss_ext_sales_price", "float")])


@pytest.mark.slow      # whole-corpus sweep; every query has its own test
def test_run_all_executes_every_query(files):
    outs = tpcds.run_all(files)
    assert len(outs) == len(tpcds.QUERIES) >= 21
    for name, t in outs.items():
        assert t.num_rows >= 0, name


# ---- round-6 additions: composite multi-key joins + left-outer fusion ----

def test_q_channel_day(tables, dfs):
    out = tpcds.q_channel_day(tables)
    ss, ws, item = dfs["store_sales"], dfs["web_sales"], dfs["item"]
    s_rev = (ss.groupby(["ss_item_sk", "ss_sold_date_sk"], as_index=False)
             ["ss_ext_sales_price"].sum())
    w_rev = (ws.groupby(["ws_item_sk", "ws_sold_date_sk"], as_index=False)
             ["ws_ext_sales_price"].sum())
    j = (s_rev.merge(w_rev, left_on=["ss_item_sk", "ss_sold_date_sk"],
                     right_on=["ws_item_sk", "ws_sold_date_sk"])
         .merge(item, left_on="ss_item_sk", right_on="i_item_sk"))
    exp = (j.groupby("i_category", as_index=False)
           .agg(s=("ss_ext_sales_price", "sum"),
                w=("ws_ext_sales_price", "sum")))
    _assert_result(out, exp, ["i_category"], [("s", "float"), ("w", "float")])


def test_q_web_also_qty(tables, dfs):
    out = tpcds.q_web_also_qty(tables)
    ss, ws = dfs["store_sales"], dfs["web_sales"]
    pairs = ws[["ws_item_sk", "ws_sold_date_sk"]].drop_duplicates()
    j = ss.merge(pairs, left_on=["ss_item_sk", "ss_sold_date_sk"],
                 right_on=["ws_item_sk", "ws_sold_date_sk"])
    exp = (j.groupby("ss_store_sk", as_index=False)["ss_quantity"].sum())
    _assert_result(out, exp, ["ss_store_sk"], [("ss_quantity", "float")])


def test_q_brand_rev_left(tables, dfs):
    out = tpcds.q_brand_rev_left(tables, manager_id=28)
    ss, item = dfs["store_sales"], dfs["item"]
    item_f = item[item.i_manager_id == 28]
    j = ss.merge(item_f, left_on="ss_item_sk", right_on="i_item_sk",
                 how="left")
    exp = (j.groupby("i_brand_id", dropna=False, as_index=False)
           .agg(s=("ss_ext_sales_price", "sum"), c=("ss_item_sk", "count"))
           .sort_values("i_brand_id", na_position="last",
                        ignore_index=True))
    assert out.num_rows == len(exp)
    # brand ids incl. the null group for every non-selected item's sales
    got_b = out[0].to_pylist()
    exp_b = [None if pd.isna(b) else int(b) for b in exp["i_brand_id"]]
    # our sort may place the null key first or last — align on key value
    if got_b[0] is None:
        got_b = got_b[1:] + [None]
        perm = list(range(1, len(exp))) + [0]
    else:
        perm = list(range(len(exp)))
    assert got_b == exp_b
    got_s = np.asarray(out[1].to_numpy(), dtype=np.float64)[perm]
    got_c = np.asarray(out[2].to_numpy())[perm]
    np.testing.assert_allclose(got_s, exp["s"].to_numpy(), rtol=1e-9)
    assert got_c.tolist() == exp["c"].tolist()


# --- plan-tree differential sweep --------------------------------------------
# Every ported query runs three ways over the same data: plan-tree
# (optimized + lowered), hand-fused (the oracle-checked kernels above),
# and — transitively through the tests above — the pandas oracle.  The
# plan path must be BIT-identical to the hand-fused path: same dtypes,
# same device buffers, same offsets, same validity.


from spark_rapids_jni_tpu import plan as P                    # noqa: E402
from spark_rapids_jni_tpu.column import force_column          # noqa: E402
from spark_rapids_jni_tpu.models import tpcds_plans           # noqa: E402
from spark_rapids_jni_tpu.plan import ir as pir               # noqa: E402

PLAN_QUERIES = sorted(tpcds_plans.PLANS)


def _plan_params(name, dfs):
    """Pick filter values guaranteed to select rows in this dataset."""
    if name == "q3":
        return {"manufact_id": int(dfs["item"].i_manufact_id.mode()[0])}
    if name in ("q42", "q55"):
        return {"manager_id": int(dfs["item"].i_manager_id.mode()[0])}
    return {}


def _assert_bitwise(got, exp):
    assert got.num_rows == exp.num_rows
    assert got.num_columns == exp.num_columns
    for i in range(got.num_columns):
        a, b = force_column(got[i]), force_column(exp[i])
        assert a.dtype.id == b.dtype.id, f"col {i} dtype"
        np.testing.assert_array_equal(np.asarray(a.data),
                                      np.asarray(b.data), err_msg=f"col {i}")
        assert (a.offsets is None) == (b.offsets is None), f"col {i} offsets"
        if a.offsets is not None:
            np.testing.assert_array_equal(np.asarray(a.offsets),
                                          np.asarray(b.offsets))
        assert (a.validity is None) == (b.validity is None), \
            f"col {i} validity"
        if a.validity is not None:
            np.testing.assert_array_equal(np.asarray(a.validity),
                                          np.asarray(b.validity))


@pytest.mark.parametrize("name", PLAN_QUERIES)
def test_plan_tree_matches_hand_fused(tables, dfs, name):
    params = _plan_params(name, dfs)
    qfn, tree = tpcds_plans.plan_fn(name, **params)
    got = qfn(tables)
    exp = getattr(tpcds, name)(tables, **params)
    assert got.num_rows > 0            # params chosen so rows survive
    _assert_bitwise(got, exp)
    # and again straight from the UN-optimized tree: the rewrites are
    # result-invariant, not just "usually equivalent"
    cat = P.TableCatalog(tables, tpcds_plans.TABLE_SCHEMAS)
    _assert_bitwise(P.execute(tree, cat, record_stats=False), exp)


@pytest.mark.parametrize("name", PLAN_QUERIES)
def test_plan_fusion_is_rule_detected(name):
    # raw plan definitions contain NO hand-wired fused node ...
    raw = tpcds_plans.PLANS[name]()
    assert not any(isinstance(n, pir.FusedJoinAggregate)
                   for n in pir.walk(raw))
    # ... the optimizer introduces it
    res = tpcds_plans.optimized(name)
    assert any(ev.rule == "fuse_join_aggregate" for ev in res.events)
    assert any(isinstance(n, pir.FusedJoinAggregate)
               for n in pir.walk(res.tree))
    # and every query gets at least one pushdown rewrite too
    assert any(ev.rule in ("projection_pushdown", "filter_pushdown")
               for ev in res.events)


def test_plan_file_catalog_matches_hand_fused(files, tables, dfs):
    """Lowered Scan nodes read parquet bytes directly (pruned decode);
    results must still be bit-identical to hand kernels over the fully
    decoded tables."""
    params = _plan_params("q3", dfs)
    res = tpcds_plans.optimized("q3", **params)
    out = P.execute(res.tree, P.FileCatalog(dict(files)),
                    record_stats=False)
    _assert_bitwise(out, tpcds.q3(tables, **params))


def test_plan_capture_replay_matches_hand_fused(tables, dfs):
    from spark_rapids_jni_tpu.models import compiled
    params = _plan_params("q42", dfs)
    qfn, _ = tpcds_plans.plan_fn("q42", **params)
    cq = compiled.compile_query(qfn, tables)
    exp = tpcds.q42(tables, **params)
    _assert_bitwise(cq.run(tables), exp)
    assert qfn.plan_fingerprint.startswith("plan:")
