"""Static-analysis + runtime-sanitizer tests.

Two layers, matching ``spark_rapids_jni_tpu/analysis/``:

* the AST passes — seeded fixture trees prove each rule fires with the
  right (file, line, rule id), and a self-clean check proves the REAL
  tree lints to zero findings modulo ``ci/lint_baseline.json`` (the
  premerge gate ``ci/lint_smoke.sh`` enforces the same invariant).
* the runtime sanitizers — the lock-order watchdog detects a real
  inversion taken by two call sites (incident mode records it, strict
  mode raises), and the retrace tripwire fires on a second trace of the
  same plan key unless wrapped in ``allow_retrace``.

Plus the regressions for the genuine findings this linter surfaced:
the ``utils.syncs`` global counter and the ``exec.placement.Replica``
counters are hammered from threads and must not lose updates.
"""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "spark_rapids_jni_tpu"

from spark_rapids_jni_tpu.analysis import (  # noqa: E402
    concurrency, core, knobpass, sanitize, tracepass)


# --------------------------------------------------------------------------
# fixture helpers: build a tiny package tree and lint it
# --------------------------------------------------------------------------

def _write(tmp_path, rel, body):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return rel


def _lint(tmp_path):
    sources = core.collect_sources(str(tmp_path), subdirs=(PKG,))
    return sources, (concurrency.run(sources) + tracepass.run(sources))


def _findings(findings, rule):
    return [f for f in findings if f.rule == rule]


# --------------------------------------------------------------------------
# concurrency pass
# --------------------------------------------------------------------------

def test_lock_order_inversion_detected(tmp_path):
    rel = _write(tmp_path, f"{PKG}/memory/fix.py", """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "conc-lock-order")
    assert len(hits) == 1, findings
    f = hits[0]
    assert f.path == rel
    # anchored at the lexically first edge in the cycle: the inner
    # `with B:` of ab() on line 8
    assert f.line == 8
    assert "memory.fix.A" in f.message and "memory.fix.B" in f.message


def test_lock_order_inversion_through_calls(tmp_path):
    # the inversion only exists inter-procedurally: f holds A and calls
    # g (which takes B); h nests B->A directly
    _write(tmp_path, f"{PKG}/exec/fix2.py", """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def g():
            with B:
                pass

        def f():
            with A:
                g()

        def h():
            with B:
                with A:
                    pass
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "conc-lock-order")
    assert len(hits) == 1, findings
    assert "exec.fix2.A" in hits[0].message


def test_lock_order_clean_tree_has_no_cycle(tmp_path):
    _write(tmp_path, f"{PKG}/memory/ok.py", """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def one():
            with A:
                with B:
                    pass

        def two():
            with A:
                with B:
                    pass
    """)
    _, findings = _lint(tmp_path)
    assert not _findings(findings, "conc-lock-order"), findings


def test_mixed_guard_detected(tmp_path):
    rel = _write(tmp_path, f"{PKG}/exec/fix3.py", """\
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self.n = 0

            def bump(self):
                with self._mu:
                    self.n += 1

            def racy_reset(self):
                self.n = 0
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "conc-mixed-guard")
    assert len(hits) == 1, findings
    f = hits[0]
    assert f.path == rel and f.line == 13
    assert "self.n" in f.message and "racy_reset" in f.message


def test_global_augassign_detected(tmp_path):
    rel = _write(tmp_path, f"{PKG}/utils/fix4.py", """\
        import threading

        _count = 0
        _mu = threading.Lock()

        def bump_racy():
            global _count
            _count += 1

        def bump_ok():
            global _count
            with _mu:
                _count += 1
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "conc-global-augassign")
    assert len(hits) == 1, findings
    assert hits[0].path == rel and hits[0].line == 8
    assert "_count" in hits[0].message


# --------------------------------------------------------------------------
# retrace/host-sync pass
# --------------------------------------------------------------------------

def test_item_in_traced_scope_detected(tmp_path):
    rel = _write(tmp_path, f"{PKG}/ops/fix5.py", """\
        import jax.numpy as jnp

        def total_width(col):
            return col.item()
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "trace-host-sync")
    assert len(hits) == 1, findings
    assert hits[0].path == rel and hits[0].line == 4
    assert ".item()" in hits[0].message


def test_int_over_device_expr_detected_and_scalar_sanctioned(tmp_path):
    _write(tmp_path, f"{PKG}/rowconv/fix6.py", """\
        import jax.numpy as jnp
        from ..utils import syncs

        def bad(col):
            return int(jnp.max(col))

        def good(col):
            return syncs.scalar(jnp.max(col))

        def host_ok(offs_np):
            return int(offs_np.max(initial=0))
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "trace-host-sync")
    assert len(hits) == 1, findings          # only `bad` fires
    assert hits[0].line == 5


def test_branch_on_device_expr_detected(tmp_path):
    _write(tmp_path, f"{PKG}/ops/fix7.py", """\
        import jax.numpy as jnp

        def clamp(col):
            if jnp.any(col < 0):
                return jnp.abs(col)
            return col
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "trace-branch")
    assert len(hits) == 1 and hits[0].line == 4, findings


def test_set_iteration_in_fingerprint_detected(tmp_path):
    _write(tmp_path, f"{PKG}/plan/fix8.py", """\
        def plan_fingerprint(cols):
            parts = []
            for name in {c.name for c in cols}:
                parts.append(name)
            return tuple(parts)

        def not_a_key_fn(cols):
            for name in {c.name for c in cols}:
                pass
    """)
    _, findings = _lint(tmp_path)
    hits = _findings(findings, "trace-iter")
    assert len(hits) == 1 and hits[0].line == 3, findings
    assert "plan_fingerprint" in hits[0].message


def test_inline_suppression_silences_finding(tmp_path):
    _write(tmp_path, f"{PKG}/ops/fix9.py", """\
        def pull(x):
            return x.item()  # srjt-lint: disable=trace-host-sync
    """)
    sources, findings = _lint(tmp_path)
    by_rel = {s.rel: s for s in sources}
    kept = core.filter_findings(findings, by_rel, baseline=None)
    assert not _findings(kept, "trace-host-sync"), kept


# --------------------------------------------------------------------------
# knob pass + registry
# --------------------------------------------------------------------------

def test_raw_environ_read_detected(tmp_path):
    rel = _write(tmp_path, f"{PKG}/exec/fix10.py", """\
        import os

        def enabled():
            return os.environ.get("SRJT_FIXTURE_KNOB", "0") == "1"
    """)
    sources = core.collect_sources(str(tmp_path), subdirs=(PKG,))
    findings = knobpass.run(sources, registered=set())
    hits = _findings(findings, "knob-env")
    assert len(hits) == 1, findings
    assert hits[0].path == rel and hits[0].line == 4
    assert "SRJT_FIXTURE_KNOB" in hits[0].message


def test_unregistered_knob_detected(tmp_path):
    rel = _write(tmp_path, f"{PKG}/exec/fix11.py", """\
        from ..utils import knobs

        def depth():
            return knobs.get("SRJT_NOT_A_REAL_KNOB")
    """)
    sources = core.collect_sources(str(tmp_path), subdirs=(PKG,))
    registered = set(knobpass.load_registry(REPO))
    findings = knobpass.run(sources, registered)
    hits = _findings(findings, "knob-unregistered")
    assert len(hits) == 1, findings
    assert hits[0].path == rel and hits[0].line == 4
    assert "SRJT_NOT_A_REAL_KNOB" in hits[0].message


def test_undocumented_knob_detected():
    sources = []
    findings = knobpass.run(sources, registered={"SRJT_GHOST_KNOB"},
                            readme_text="no table here")
    hits = _findings(findings, "knob-undoc")
    assert len(hits) == 1 and hits[0].path == "README.md", findings


def test_registry_semantics(monkeypatch):
    from spark_rapids_jni_tpu.utils import knobs
    monkeypatch.delenv("SRJT_EXEC_PREFETCH_DEPTH", raising=False)
    assert knobs.get("SRJT_EXEC_PREFETCH_DEPTH") == 2   # default
    monkeypatch.setenv("SRJT_EXEC_PREFETCH_DEPTH", "5")
    assert knobs.get("SRJT_EXEC_PREFETCH_DEPTH") == 5   # re-read per call
    # on-unless-off boolean family
    monkeypatch.delenv("SRJT_FLIGHT", raising=False)
    assert knobs.get("SRJT_FLIGHT") is True
    monkeypatch.setenv("SRJT_FLIGHT", "off")
    assert knobs.get("SRJT_FLIGHT") is False
    # optional float: unset -> None
    monkeypatch.delenv("SRJT_EXEC_DEADLINE", raising=False)
    assert knobs.get("SRJT_EXEC_DEADLINE") is None
    monkeypatch.setenv("SRJT_EXEC_DEADLINE", "1.5")
    assert knobs.get("SRJT_EXEC_DEADLINE") == 1.5
    with pytest.raises(KeyError):
        knobs.get("SRJT_NEVER_REGISTERED")
    assert knobs.is_registered("SRJT_EXEC")
    assert not knobs.is_registered("SRJT_NEVER_REGISTERED")


def test_every_registered_knob_documented():
    from spark_rapids_jni_tpu.utils import knobs
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    missing = [k for k in knobs.REGISTRY if k not in readme]
    assert not missing, f"knobs missing from README: {missing}"


# --------------------------------------------------------------------------
# self-clean: the real tree lints to zero modulo the checked-in baseline
# --------------------------------------------------------------------------

def test_real_tree_is_clean_modulo_baseline():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "srjt_lint.py"),
         "--root", REPO,
         "--baseline", os.path.join(REPO, "ci", "lint_baseline.json")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, \
        f"non-baselined findings:\n{proc.stdout}\n{proc.stderr}"


# --------------------------------------------------------------------------
# runtime sanitizer: lock-order watchdog
# --------------------------------------------------------------------------

@pytest.fixture
def sanitizer(monkeypatch):
    monkeypatch.setenv("SRJT_SANITIZE", "1")
    sanitize.reset()
    yield sanitize
    sanitize.reset()


def test_watchdog_records_inversion(sanitizer):
    a = sanitize.tracked_lock("test.wd.a")
    b = sanitize.tracked_lock("test.wd.b")
    with a:
        with b:
            pass
    with b:
        with a:                      # inversion: established order is a->b
            pass
    vio = sanitize.violations()
    assert len(vio) == 1, vio
    assert vio[0]["acquiring"] == "test.wd.a"
    assert vio[0]["while_holding"] == "test.wd.b"
    assert "test.wd" in vio[0]["prior_stack"] or vio[0]["prior_stack"]


def test_watchdog_strict_raises(monkeypatch):
    monkeypatch.setenv("SRJT_SANITIZE", "strict")
    sanitize.reset()
    try:
        a = sanitize.tracked_lock("test.strict.a")
        b = sanitize.tracked_lock("test.strict.b")
        with a:
            with b:
                pass
        with pytest.raises(sanitize.LockOrderError):
            with b:
                with a:
                    pass
        # the failed acquisition must not leak into the held stack
        with a:
            with b:
                pass
    finally:
        sanitize.reset()


def test_watchdog_reentrant_and_consistent_order_ok(sanitizer):
    r = sanitize.tracked_rlock("test.wd.r")
    inner = sanitize.tracked_lock("test.wd.inner")
    for _ in range(3):
        with r:
            with r:                  # reentrant: no edge
                with inner:
                    pass
    assert not sanitize.violations()


def test_watchdog_off_returns_plain_primitives(monkeypatch):
    monkeypatch.setenv("SRJT_SANITIZE", "0")
    lk = sanitize.tracked_lock("test.off")
    assert type(lk) is threading.Lock().__class__


def test_watchdog_cross_thread_edges(sanitizer):
    # thread 1 establishes a->b; thread 2 takes b->a: classic deadlock
    # candidate that never actually deadlocks in the test
    a = sanitize.tracked_lock("test.xt.a")
    b = sanitize.tracked_lock("test.xt.b")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with b:
        with a:
            pass
    assert len(sanitize.violations()) == 1


# --------------------------------------------------------------------------
# runtime sanitizer: retrace tripwire
# --------------------------------------------------------------------------

def test_retrace_tripwire(sanitizer):
    sanitize.note_trace("plan#t1")              # warmup
    assert not sanitize.retrace_events()
    sanitize.note_trace("plan#t1")              # unexpected retrace
    events = sanitize.retrace_events()
    assert len(events) == 1 and events[0]["key"] == "plan#t1"
    assert events[0]["count"] == 2


def test_retrace_allowed_inside_scope(sanitizer):
    sanitize.note_trace("plan#t2")
    with sanitize.allow_retrace():
        sanitize.note_trace("plan#t2")          # vmap-build style: fine
    assert not sanitize.retrace_events()
    sanitize.note_trace("plan#t2")              # outside the scope: trips
    assert len(sanitize.retrace_events()) == 1


def test_retrace_strict_raises(monkeypatch):
    monkeypatch.setenv("SRJT_SANITIZE", "strict")
    sanitize.reset()
    try:
        sanitize.note_trace("plan#t3")
        with pytest.raises(sanitize.RetraceError):
            sanitize.note_trace("plan#t3")
    finally:
        sanitize.reset()


def test_compiled_query_warm_replay_does_not_trip(monkeypatch):
    monkeypatch.setenv("SRJT_SANITIZE", "strict")
    sanitize.reset()
    try:
        import jax.numpy as jnp
        from spark_rapids_jni_tpu.models import compiled as C

        def q(tbls):
            return jnp.sum(tbls["x"] * 2)

        tables = {"x": jnp.arange(8, dtype=jnp.int32)}
        cq = C.compile_query(q, tables)
        first = cq.run(tables)                  # warmup trace
        for _ in range(3):                      # steady loop: no retrace
            assert int(cq.run_unchecked(tables)) == int(first)
    finally:
        sanitize.reset()


# --------------------------------------------------------------------------
# regressions for the genuine findings fixed alongside the linter
# --------------------------------------------------------------------------

def test_sync_count_thread_safe():
    from spark_rapids_jni_tpu.utils import syncs
    syncs.reset_sync_count()
    n_threads, n_iter = 8, 500
    barrier = threading.Barrier(n_threads)

    def work():
        barrier.wait()
        for _ in range(n_iter):
            syncs.scalar(7)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert syncs.reset_sync_count() == n_threads * n_iter


def test_replica_counters_thread_safe():
    from spark_rapids_jni_tpu.exec.placement import Replica

    class FakeDevice:
        platform, id = "cpu", 0

    rep = Replica(0, FakeDevice())
    n_threads, n_iter = 8, 400
    barrier = threading.Barrier(n_threads)

    def work():
        barrier.wait()
        for _ in range(n_iter):
            rep.note_active()
            rep.note_completed()
            rep.note_active(-1)
            rep.note_probe_failed()
            rep.note_probe_ok()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rep.active == 0
    assert rep.completed == n_threads * n_iter
    assert rep.fail_streak == 0
