"""Mortgage ETL differential tests (BASELINE config #5): the framework
pipeline vs pandas running the same parse/join/aggregate plan over the same
raw parquet bytes."""

import io

import numpy as np
import pandas as pd
import pytest

from benchmarks import mortgage_data
from spark_rapids_jni_tpu.models import mortgage
from spark_rapids_jni_tpu.column import Column
from spark_rapids_jni_tpu.ops import strings as S


@pytest.fixture(scope="module")
def files():
    return mortgage_data.generate(n_loans=500, periods_per_loan=8, seed=3)


@pytest.fixture(scope="module")
def dfs(files):
    return {k: pd.read_parquet(io.BytesIO(v)) for k, v in files.items()}


def _expected_features(dfs):
    perf, acq = dfs["perf"].copy(), dfs["acq"].copy()
    perf["period"] = (pd.to_datetime(perf.monthly_reporting_period,
                                     format="%m/%d/%Y")
                      - pd.Timestamp("1970-01-01")).dt.days
    perf["upb_cents"] = (pd.to_numeric(perf.current_actual_upb,
                                       errors="coerce") * 100).round()
    perf["delinq"] = pd.to_numeric(perf.current_loan_delinquency_status,
                                   errors="coerce").fillna(-1)
    agg = (perf.groupby("loan_id")
           .agg(max_delinq=("delinq", "max"),
                mean_upb=("upb_cents", "mean"),
                cnt=("loan_id", "count"),
                first_period=("period", "min")).reset_index())
    agg["mean_upb"] = agg["mean_upb"] / 100.0
    acq["rate_e4"] = (pd.to_numeric(acq.orig_interest_rate) * 10**4).round()
    acq["upb_i"] = pd.to_numeric(acq.orig_upb)
    acq["odate"] = (pd.to_datetime(acq.orig_date)
                    - pd.Timestamp("1970-01-01")).dt.days
    out = acq.merge(agg, on="loan_id").sort_values("loan_id")
    return out.reset_index(drop=True)


@pytest.mark.slow
def test_etl_matches_pandas(files, dfs):
    out = mortgage.etl(files)
    exp = _expected_features(dfs)
    assert out.num_rows == len(exp)
    cols = {name: out[i] for i, name in enumerate(mortgage.FEATURE_COLS)}
    np.testing.assert_array_equal(np.asarray(cols["loan_id"].data),
                                  exp.loan_id.to_numpy())
    np.testing.assert_array_equal(np.asarray(cols["orig_rate_e4"].data),
                                  exp.rate_e4.to_numpy().astype(np.int64))
    np.testing.assert_array_equal(np.asarray(cols["orig_upb"].data),
                                  exp.upb_i.to_numpy().astype(np.int64))
    np.testing.assert_array_equal(np.asarray(cols["orig_date_days"].data),
                                  exp.odate.to_numpy().astype(np.int32))
    np.testing.assert_array_equal(np.asarray(cols["max_delinquency"].data),
                                  exp.max_delinq.to_numpy().astype(np.int64))
    # mean UPB skips blank (null) rows — pandas mean(skipna) is the oracle
    np.testing.assert_allclose(cols["mean_upb"].to_numpy(),
                               exp.mean_upb.to_numpy(), rtol=1e-9)
    np.testing.assert_array_equal(np.asarray(cols["num_records"].data),
                                  exp.cnt.to_numpy().astype(np.int64))
    np.testing.assert_array_equal(np.asarray(cols["first_period_days"].data),
                                  exp.first_period.to_numpy().astype(np.int32))


@pytest.mark.slow      # full second ETL run just to re-check code maps
def test_categorical_codes_consistent(files, dfs):
    out = mortgage.etl(files)
    exp = _expected_features(dfs)
    state_codes = np.asarray(
        out[mortgage.FEATURE_COLS.index("state_code")].data)
    # dictionary codes are order-preserving ranks: equal states ⇔ equal codes
    df = pd.DataFrame({"state": exp.state.to_numpy(), "code": state_codes})
    assert (df.groupby("state").code.nunique() == 1).all()
    assert (df.groupby("code").state.nunique() == 1).all()
    # null sellers land in the -1 bucket
    seller_codes = np.asarray(
        out[mortgage.FEATURE_COLS.index("seller_code")].data)
    null_mask = exp.seller_name.isna().to_numpy()
    assert (seller_codes[null_mask] == -1).all()
    assert (seller_codes[~null_mask] >= 0).all()


def test_feature_matrix_shape(files):
    ids, mat = mortgage.feature_matrix(files)
    assert mat.shape == (500, len(mortgage.FEATURE_COLS) - 1)
    assert ids.shape[0] == 500
    assert not np.isnan(np.asarray(mat)).any()


class TestParseKernels:
    def test_to_int64_matches_python(self):
        vals = ["0", "-1", "123456789012345678", "+42", "", "9x", "--1",
                None, "007"]
        out = S.to_int64(Column.strings_from_list(vals))
        want = [0, -1, 123456789012345678, 42, None, None, None, None, 7]
        assert out.to_pylist() == want

    def test_to_decimal_matches_python(self):
        vals = ["3.14159", "-2.5", "100", "0.005", "1.", ".25", "1.2.3",
                None, "abc"]
        out = S.to_decimal(Column.strings_from_list(vals), -3)
        want = [3142, -2500, 100000, 5, 1000, 250, None, None, None]
        assert out.to_pylist() == want

    def test_to_date_roundtrip_numpy(self):
        rng = np.random.default_rng(0)
        days = rng.integers(-20000, 40000, 500)
        dates = (np.datetime64("1970-01-01") + days).astype("datetime64[D]")
        iso = [str(d) for d in dates]
        out = S.to_date(Column.strings_from_list(iso))
        np.testing.assert_array_equal(np.asarray(out.data), days)
        mdy = [f"{d.astype(object).month:02d}/{d.astype(object).day:02d}/"
               f"{d.astype(object).year:04d}" for d in dates]
        out2 = S.to_date(Column.strings_from_list(mdy), "%m/%d/%Y")
        np.testing.assert_array_equal(np.asarray(out2.data), days)


class TestParseStrictness:
    def test_to_int64_overflow_is_null(self):
        vals = ["99999999999999999999", "9223372036854775808",
                "000000000000000000005", "123456789012345678"]
        out = S.to_int64(Column.strings_from_list(vals))
        # >18 significant digits → null (conservative Spark CAST);
        # leading zeros don't count as significant
        assert out.to_pylist() == [None, None, 5, 123456789012345678]

    def test_to_decimal_overflow_is_null(self):
        out = S.to_decimal(Column.strings_from_list(
            ["99999999999999999999.5", "1.5"]), -3)
        assert out.to_pylist() == [None, 1500]

    def test_to_date_rejects_impossible_dates(self):
        vals = ["2021-02-31", "2020-02-29", "2019-02-29", "2021-04-31",
                "2020/01/02", "2020-1x-02", "2020-01-02"]
        out = S.to_date(Column.strings_from_list(vals))
        assert out.to_pylist() == [None, 18321, None, None, None, None,
                                   18263]

    def test_to_date_mdy_separators(self):
        out = S.to_date(Column.strings_from_list(
            ["02/29/2020", "02-29-2020", "13/01/2020"]), "%m/%d/%Y")
        assert out.to_pylist() == [18321, None, None]

    def test_fill_null_decimal128_rejected(self):
        from spark_rapids_jni_tpu.ops import decimal128 as d128
        from spark_rapids_jni_tpu.ops import fill_null
        with pytest.raises(TypeError):
            fill_null(d128.from_pyints([1, None]), 0)

    def test_whitespace_trimmed_like_spark(self):
        out = S.to_int64(Column.strings_from_list([" 42", "42 ", "  -7  ",
                                                   " ", "1 2"]))
        assert out.to_pylist() == [42, 42, -7, None, None]

    def test_to_decimal_positive_scale_rounds(self):
        out = S.to_decimal(Column.strings_from_list(["255", "244", "-255"]), 1)
        assert out.to_pylist() == [26, 24, -26]

    def test_all_ascii_whitespace_trimmed(self):
        out = S.to_int64(Column.strings_from_list(
            ["42\n", "\r42", "\t42\x0b", "4\n2"]))
        assert out.to_pylist() == [42, 42, 42, None]
