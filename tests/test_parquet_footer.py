"""Parquet footer engine tests against real pyarrow-written files.

Validation strategy: pyarrow is an independent, widely-trusted parquet
implementation — footers we prune are re-parsed with
``pyarrow.parquet.read_metadata`` to prove the serialized result is a valid
footer with exactly the expected surviving schema.
"""

import io
import struct

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_jni_tpu.parquet import (
    ParquetFooter, StructElement, ValueElement, ListElement, MapElement,
    read_and_filter,
)
from spark_rapids_jni_tpu.parquet.footer import extract_footer_bytes
from spark_rapids_jni_tpu.parquet import thrift as T


def write_parquet(table: pa.Table, **kw) -> bytes:
    buf = io.BytesIO()
    pq.write_table(table, buf, **kw)
    return buf.getvalue()


def simple_file(n=100, row_group_size=None) -> bytes:
    t = pa.table({
        "a": pa.array(np.arange(n, dtype=np.int64)),
        "B": pa.array(np.arange(n, dtype=np.int32)),
        "c": pa.array([f"s{i}" for i in range(n)]),
        "d": pa.array(np.arange(n, dtype=np.float64)),
    })
    return write_parquet(t, row_group_size=row_group_size or n)


def nested_file(n=10) -> bytes:
    t = pa.table({
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "s": pa.array([{"x": i, "y": float(i)} for i in range(n)],
                      type=pa.struct([("x", pa.int32()), ("y", pa.float64())])),
        "l": pa.array([[i, i + 1] for i in range(n)],
                      type=pa.list_(pa.int32())),
        "m": pa.array([[(str(i), i)] for i in range(n)],
                      type=pa.map_(pa.string(), pa.int64())),
    })
    return write_parquet(t)


def reparse(footer: ParquetFooter) -> pq.FileMetaData:
    return pq.read_metadata(io.BytesIO(footer.serialize_thrift_file()))


def test_thrift_roundtrip_is_byte_identical():
    raw = extract_footer_bytes(simple_file())
    s = T.parse_struct(raw)
    assert T.serialize_struct(s) == raw


def test_prune_to_subset_of_columns():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("a"), ValueElement("c"))
    f = read_and_filter(raw, 0, -1, schema)
    assert f.num_columns == 2
    assert f.num_rows == 100
    md = reparse(f)
    assert md.schema.names == ["a", "c"]
    assert md.num_columns == 2
    assert md.row_group(0).num_columns == 2
    # surviving chunk metadata is the original ones
    assert md.row_group(0).column(0).path_in_schema == "a"
    assert md.row_group(0).column(1).path_in_schema == "c"


def test_prune_case_insensitive():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("b"))
    # case-sensitive: no match → column silently pruned away (skip path)
    f = read_and_filter(raw, 0, -1, schema, ignore_case=False)
    assert f.num_columns == 0
    f = read_and_filter(raw, 0, -1, schema, ignore_case=True)
    assert f.num_columns == 1
    assert reparse(f).schema.names == ["B"]  # original name preserved


def test_prune_missing_column_is_skipped():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("a"), ValueElement("zz"))
    f = read_and_filter(raw, 0, -1, schema)
    assert f.num_columns == 1
    assert reparse(f).schema.names == ["a"]


def test_prune_nested_struct_child():
    raw = extract_footer_bytes(nested_file())
    schema = StructElement("root",
                           StructElement("s", ValueElement("x")),
                           ValueElement("id"))
    # note: pruner matches file order; s comes after id in the file, so
    # request order does not matter — matching walks the file schema
    f = read_and_filter(raw, 0, -1, schema)
    md = reparse(f)
    assert f.num_columns == 2
    names = [md.row_group(0).column(i).path_in_schema
             for i in range(md.row_group(0).num_columns)]
    assert names == ["id", "s.x"]


def test_prune_list_and_map():
    raw = extract_footer_bytes(nested_file())
    schema = StructElement(
        "root",
        ListElement("l", ValueElement("element")),
        MapElement("m", ValueElement("key"), ValueElement("value")))
    f = read_and_filter(raw, 0, -1, schema)
    md = reparse(f)
    rg = md.row_group(0)
    paths = [rg.column(i).path_in_schema for i in range(rg.num_columns)]
    assert paths == ["l.list.element", "m.key_value.key", "m.key_value.value"]


def test_row_group_split_filtering():
    raw_file = simple_file(n=10000, row_group_size=1000)
    raw = extract_footer_bytes(raw_file)
    md_full = pq.read_metadata(io.BytesIO(raw_file))
    assert md_full.num_row_groups == 10
    schema = StructElement("root", ValueElement("a"))

    # whole file → all rows
    f = read_and_filter(raw, 0, len(raw_file), schema)
    assert f.num_rows == 10000

    # split covering only the first row group's midpoint
    rg0 = md_full.row_group(0)
    first_off = min(rg0.column(0).data_page_offset,
                    rg0.column(0).dictionary_page_offset or 2**62)
    mid0 = first_off + rg0.total_byte_size // 2
    f = read_and_filter(raw, 0, mid0 + 1, schema)
    assert 0 < f.num_rows < 10000

    # empty split → nothing
    f = read_and_filter(raw, len(raw_file) + 100, 50, schema)
    assert f.num_rows == 0
    assert reparse(f).num_row_groups == 0


def test_split_partition_is_exact():
    """Every row group lands in exactly one split."""
    raw_file = simple_file(n=5000, row_group_size=500)
    raw = extract_footer_bytes(raw_file)
    schema = StructElement("root", ValueElement("a"), ValueElement("B"),
                           ValueElement("c"), ValueElement("d"))
    half = len(raw_file) // 2
    f1 = read_and_filter(raw, 0, half, schema)
    f2 = read_and_filter(raw, half, len(raw_file) - half, schema)
    assert f1.num_rows + f2.num_rows == 5000
    assert f1.num_rows > 0 and f2.num_rows > 0


def test_full_schema_preserves_everything():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("a"), ValueElement("B"),
                           ValueElement("c"), ValueElement("d"))
    f = read_and_filter(raw, 0, -1, schema)
    md = reparse(f)
    assert md.schema.names == ["a", "B", "c", "d"]
    assert md.num_rows == 100
    # created_by and version survive the generic round trip
    orig = pq.read_metadata(io.BytesIO(simple_file()))
    assert md.created_by == orig.created_by
    assert md.format_version == orig.format_version


def test_serialized_framing():
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("a"))
    blob = read_and_filter(raw, 0, -1, schema).serialize_thrift_file()
    assert blob[:4] == b"PAR1" and blob[-4:] == b"PAR1"
    (length,) = struct.unpack("<I", blob[-8:-4])
    assert length == len(blob) - 12


def test_uppercase_expected_names_fold_both_sides():
    # the expected-schema side must fold too (reference folds both sides)
    raw = extract_footer_bytes(simple_file())
    schema = StructElement("root", ValueElement("A"), ValueElement("D"))
    f = read_and_filter(raw, 0, -1, schema, ignore_case=True)
    assert f.num_columns == 2
    assert reparse(f).schema.names == ["a", "d"]


def test_bool_list_roundtrip_in_generic_tree():
    # compact encoding: struct { 1: list<bool> [T,F,T]; 2: i32 5 }
    blob = bytes([0x19, 0x31, 0x01, 0x02, 0x01, 0x15, 0x0A, 0x00])
    s = T.parse_struct(blob)
    lv = s.get(1)
    assert list(lv.values) == [True, False, True]
    assert s.get(2) == 5
    assert T.serialize_struct(s) == blob


def test_malformed_footer_clean_errors():
    from spark_rapids_jni_tpu.parquet.thrift import (Struct, Field, ListValue,
                                                     TType, serialize_struct)
    # struct with no schema field at all
    import pytest as _pytest
    with _pytest.raises(ValueError, match="no schema"):
        read_and_filter(serialize_struct(Struct([])), 0, -1,
                        StructElement("root", ValueElement("a")))
    # schema present but no row_groups: prunes fine, zero rows
    root = Struct([Field(4, TType.BINARY, b"root"),
                   Field(5, TType.I32, 1)])
    leaf = Struct([Field(1, TType.I32, 1),    # type = INT32 (leaf)
                   Field(4, TType.BINARY, b"a")])
    meta = Struct([Field(2, TType.LIST, ListValue(TType.STRUCT, [root, leaf]))])
    f = read_and_filter(serialize_struct(meta), 0, 10, 
                        StructElement("root", ValueElement("a")))
    assert f.num_rows == 0 and f.num_columns == 1
